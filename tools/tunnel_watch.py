"""Tunnel liveness watcher: probe the TPU backend in a killable subprocess
on a cadence, appending one status line per attempt to .tunnel_probe.log,
and exit 0 the moment a probe succeeds.

Run under tmux/nohup during long build sessions; the log's last line tells
whether the device is reachable without risking an in-process backend-init
hang (the axon tunnel can block `jax.devices()` for ~45 min — see
bench.py probe_device for the same pattern).
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from foundationdb_tpu.utils.procutil import (  # noqa: E402
    device_probe_argv,
    run_killable,
)

LOG = os.path.join(REPO, ".tunnel_probe.log")
PROBE_TIMEOUT = int(os.environ.get("TUNNEL_PROBE_TIMEOUT", "240"))
INTERVAL = int(os.environ.get("TUNNEL_PROBE_INTERVAL", "360"))


def log(line):
    stamp = time.strftime("%H:%M:%S")
    with open(LOG, "a") as f:
        f.write(f"{stamp} {line}\n")
    print(f"{stamp} {line}", flush=True)


def main():
    attempt = 0
    while True:
        attempt += 1
        t0 = time.perf_counter()
        try:
            rc, out, err = run_killable(device_probe_argv(REPO), PROBE_TIMEOUT)
            if rc == 0:
                log(f"UP attempt={attempt} {out.strip()}")
                return 0
            log(f"DOWN attempt={attempt} rc={rc} {err.strip()[-200:]}")
        except Exception as e:
            log(f"DOWN attempt={attempt} {type(e).__name__}: {e}")
        spent = time.perf_counter() - t0
        time.sleep(max(0, INTERVAL - spent))


if __name__ == "__main__":
    sys.exit(main())
