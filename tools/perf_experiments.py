"""A/B harness for conflict-engine perf experiments on the real TPU.

Runs the driver-config device bench (24 x 64k txns, window=50) under each
experiment flag combination in a fresh subprocess (flags are read at
import), printing one JSON line per variant.  Variants are
decision-identical to the baseline — verified by the differential suites
under the same flags — so the only question hardware answers is speed.

Variants (the one shared table, bench.VARIANTS):
  baseline        the shipping configuration
  tiered4         FDB_TPU_HISTORY=tiered + EVICT_EVERY=4 — two-tier
                  history: per-batch sorts at delta size, a major
                  compaction (the two full-H sorts, amortized) every 4th
                  batch behind a traced lax.cond (ISSUE 4)
  tiered4_2level  tiered + the coarse-then-fine search
  search2level    FDB_TPU_SEARCH=2level — coarse-then-fine history search
  evict4          FDB_TPU_EVICT_EVERY=4 — eviction compaction every 4th
                  batch (h_cap gets headroom for the unevicted batches)
  both*           2level/evict combinations
  pipeline1/2/3   FDB_TPU_PIPELINE_DEPTH sweep (ISSUE 11) — the FULL
                  resolve loop (encode + dispatch + readback + mirror
                  apply) at each depth; pipeline1 is the synchronous
                  before-arm
  kernels         FDB_TPU_KERNELS=1 (ISSUE 14) — Pallas fused
                  merge/evict + phase-1 search kernels, flat history
  tiered4_kernels kernels + the tiered history (the expected shipping
                  combination: delta-bounded batches AND one-pass
                  compactions)

Run: python tools/perf_experiments.py   (on the TPU host)
     python tools/perf_experiments.py --kernels   (CPU kernel A/B:
     interpret-mode bit-identity + in-step nokernel attribution)
     python tools/perf_experiments.py --pipeline   (CPU overlap sweep,
     any host)
     python tools/perf_experiments.py --timeline  (short pipelined run
     -> TIMELINE.json Perfetto artifact + phase attribution, any host)
     python tools/perf_experiments.py --contention  (witness-guided vs
     blind retry Zipf A/B -> CONTENTION_AB.json, any host)
     python tools/perf_experiments.py --hostpath  (serialized host-path
     phase decomposition + coalesce A/B -> BENCH_r08.json, any host)
     python tools/perf_experiments.py --hostbudget  (perfcheck's host
     budgets live: host_syncs/host_allocs per pipelined batch + the
     per-key vs bulk encode split, any host)
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RUNNER = r"""
import json, os, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np
import bench

rng = np.random.default_rng(2024)
depth = os.environ.get("FDB_TPU_PIPELINE_DEPTH")
mc = os.environ.get("BENCH_MULTICHIP")
hp = os.environ.get("BENCH_HOSTPATH")
if hp:
    # Serialized host-path decomposition (ISSUE 19): per-phase wall costs
    # at the round-11 stream shape — not a throughput contender.
    phases = bench._pipeline_phase_costs(rng, 30, 2500, %(h_cap)d)
    total_ms = (phases["encode_ms_per_batch"]
                + phases["device_step_ms_per_batch"]
                + phases["mirror_apply_ms_per_batch"])
    print("RESULT " + json.dumps({
        "txns_per_sec": round(2500 * 1e3 / max(1e-9, total_ms), 1),
        "hostpath": phases,
    }))
elif mc:
    # Mesh-sharded variant (ISSUE 15): the full shard-granular resolve
    # loop (per-shard clipping + mirrors + host min-combine).
    rate, info = bench.bench_multichip(rng, int(mc), h_cap=%(h_cap)d)
    print("RESULT " + json.dumps({"txns_per_sec": round(rate, 1),
                                  "multichip": info}))
elif depth:
    # Pipeline variants (ISSUE 11) price the FULL resolve loop: encode +
    # dispatch + readback + mirror apply at the given depth; the span
    # layer's overlap-efficiency metric rides along (ISSUE 12).
    rate, overlap = bench.bench_pipeline(rng, int(depth), h_cap=%(h_cap)d)
    print("RESULT " + json.dumps({"txns_per_sec": round(rate, 1),
                                  "overlap_efficiency_wall": overlap["wall"]}))
else:
    rate = bench.bench_jax(rng, h_cap=%(h_cap)d)
    print("RESULT " + json.dumps({"txns_per_sec": round(rate, 1)}))
"""

sys.path.insert(0, REPO)
import bench  # noqa: E402  (the variant table is shared with the driver bench)

# One shared table: every name the A/B can crown in TUNED.json must be
# attemptable by the driver-time bench (bench.variant_plan sorts by name).
VARIANTS = list(bench.VARIANTS)


def main():
    if "--programs" in sys.argv:
        # Device program cost accounting (ISSUE 10): compile every
        # DEVICE_ENTRY_POINTS entry at its canonical trace shapes and
        # dump the cost table — carried-buffer bytes, temp/output
        # allocation, FLOPs per batch, compile wall — the baseline
        # dataset Pallas-kernel PRs (ROADMAP item 1) are judged against.
        # Runs anywhere (CPU analysis; no device needed).
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
        from foundationdb_tpu.conflict.engine_jax import program_cost_table

        try:
            # Optional: registers the sharded_step entry too.
            import foundationdb_tpu.parallel.sharded_resolver  # noqa: F401
        except Exception as e:  # noqa: BLE001 - optional entry; table notes the absence
            print(json.dumps({"sharded_step_import": str(e)}),
                  file=sys.stderr)
        print(json.dumps(program_cost_table(include_wall=True), indent=2,
                         sort_keys=True))
        return
    if "--timeline" in sys.argv:
        # Timeline artifact (ISSUE 12): a short pipelined run with span
        # recording + in-step phase attribution, exported as a Perfetto
        # JSON (TIMELINE.json) — so the next device window ships a
        # timeline alongside its BENCH numbers.  Runs anywhere (the CPU
        # backend's async dispatch provides the overlap).
        print(json.dumps(bench.bench_timeline(), indent=2))
        return
    if "--pipeline" in sys.argv:
        # CPU-phase pipeline overlap microbench (ISSUE 11): the resolve
        # loop at the skipListTest stream shape under depths 1/2/3, plus
        # the serialized phase decomposition (encode / device step /
        # mirror apply) showing what the overlap hides.  No device
        # needed — JAX's async CPU dispatch provides the compute thread
        # the host phases overlap with, so the win prices on any host.
        print(json.dumps(bench.bench_pipeline_cpu(), indent=2))
        return
    if "--kernels" in sys.argv:
        # Pallas kernel A/B on the CPU (ISSUE 14 satellite): interpret-
        # mode Pallas vs the XLA fallback — cross-seed bit-identity
        # evidence + the deterministic in-step nokernel FLOP attribution.
        # Runs anywhere; the honest device walls come from the `kernels`
        # / `tiered4_kernels` variants on a live tunnel.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        print(json.dumps(bench.bench_kernels_cpu(), indent=2))
        return
    if "--multichip" in sys.argv:
        # Shard-granular multichip A/B (ISSUE 15): the sharded resolve
        # loop on a VIRTUAL 8-device CPU mesh — always runnable, no
        # tunnel needed — across shard counts, emitted as the
        # MULTICHIP_r06-style artifact.  The honest device rates come
        # from the `multichip` entry in the shared VARIANTS table, which
        # the driver runs behind the same probe cap as every device arm.
        env = dict(os.environ)
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        code = (
            "import json, sys; sys.path.insert(0, %r)\n"
            "import bench\n"
            "print('RESULT ' + json.dumps(bench.bench_multichip_cpu()))\n"
        ) % REPO
        res = subprocess.run(
            [sys.executable, "-c", code],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=1800,
        )
        line = next(
            (l for l in res.stdout.splitlines() if l.startswith("RESULT ")),
            None,
        )
        artifact = {
            "rc": res.returncode,
            "ok": res.returncode == 0 and line is not None,
            "skipped": False,
            "arm": "cpu_virtual_mesh",
        }
        if line is not None:
            artifact.update(json.loads(line[len("RESULT "):]))
        else:
            artifact["tail"] = (res.stdout + res.stderr)[-800:]
        out_path = os.path.join(REPO, "MULTICHIP_r06.json")
        bench.atomic_write_json(out_path, artifact, indent=2,
                                sort_keys=True)
        print(json.dumps(artifact, indent=2, sort_keys=True))
        print(f"wrote {out_path}", file=sys.stderr)
        return
    if "--contention" in sys.argv:
        # Witness-guided vs blind retry A/B (ISSUE 17): the
        # high-contention Zipf soak arm twice under identical seeds —
        # once with FDB_TPU_WITNESS_RETRY seeding the retry read version
        # from the abort witness, once blind (fresh GRV + backoff) —
        # scored on goodput, retry count, and commit p99.  Runs anywhere
        # (simulated cluster, virtual time); a fresh subprocess keeps the
        # process-global span hub / flight recorder out of the score.
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = REPO
        code = (
            "import json, sys; sys.path.insert(0, %r)\n"
            "from foundationdb_tpu.workloads.soak import run_contention_ab\n"
            "ab = run_contention_ab(minutes=0.1, peak_tps=100.0, seed=3)\n"
            "ab.pop('reports', None)  # scores only; soak owns the blobs\n"
            "print('RESULT ' + json.dumps(ab))\n"
        ) % REPO
        res = subprocess.run(
            [sys.executable, "-c", code],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=1800,
        )
        line = next(
            (l for l in res.stdout.splitlines() if l.startswith("RESULT ")),
            None,
        )
        artifact = {
            "rc": res.returncode,
            "ok": res.returncode == 0 and line is not None,
            "arm": "contention_zipf_ab",
        }
        if line is not None:
            artifact.update(json.loads(line[len("RESULT "):]))
        else:
            artifact["tail"] = (res.stdout + res.stderr)[-800:]
        out_path = os.path.join(REPO, "CONTENTION_AB.json")
        bench.atomic_write_json(out_path, artifact, indent=2,
                                sort_keys=True)
        print(json.dumps(artifact, indent=2, sort_keys=True))
        print(f"wrote {out_path}", file=sys.stderr)
        return
    if "--hostbudget" in sys.argv:
        # Host-budget counters live (ISSUE 20): the numbers the perfcheck
        # pass family polices, measured on a depth-2 pipelined run —
        # sanctioned host_syncs per batch (gate: <= 3), staging-ring
        # allocations at steady state (gate: 0), and the per-key vs bulk
        # encode split (gate: zero per-key Python on the resolve path).
        # Runs anywhere (CPU backend); the pins live in
        # tests/test_perf_smoke.py, this arm prints them at bench shape.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import numpy as np

        from foundationdb_tpu.conflict.api import ConflictSet
        from foundationdb_tpu.conflict.keys import ENCODE_OPS

        rng = np.random.default_rng(2024)
        depth, warm, measured, per_batch = 2, 4, 12, 2500
        os.environ["FDB_TPU_PIPELINE_DEPTH"] = str(depth)
        cs = ConflictSet(backend="jax", key_words=bench.KEY_WORDS,
                         h_cap=1 << 19)
        streams = [
            bench.txns_from_packed(
                bench.gen_packed(rng, per_batch, i, bench.KEY_WORDS),
                per_batch)
            for i in range(warm + measured)
        ]

        def run_one(i):
            cs.pipeline_submit(streams[i], i + bench.WINDOW, i)
            while cs.pipeline_inflight > depth - 1:
                cs.pipeline_complete_oldest()

        for i in range(warm):
            run_one(i)
        cs.pipeline_drain()
        c0 = dict(cs.device_metrics()["counters"])
        e0 = dict(ENCODE_OPS)
        for j in range(measured):
            run_one(warm + j)
        cs.pipeline_drain()
        c1 = cs.device_metrics()["counters"]
        e1 = dict(ENCODE_OPS)
        print(json.dumps({
            "batches": measured,
            "host_syncs_per_batch":
                (c1["host_syncs"] - c0["host_syncs"]) / measured,
            "host_allocs_per_batch":
                (c1["host_allocs"] - c0["host_allocs"]) / measured,
            "encode_perkey_delta": e1["perkey"] - e0["perkey"],
            "encode_bulk_batches_delta":
                e1["bulk_batches"] - e0["bulk_batches"],
            "gates": {"host_syncs_per_batch": "<= 3 (sanctioned scopes)",
                      "host_allocs_per_batch": "== 0 (staging ring)",
                      "encode_perkey_delta": "== 0 (bulk encode path)"},
        }, indent=2, sort_keys=True))
        return
    if "--hostpath" in sys.argv:
        # Serialized host-path decomposition (ISSUE 19): per-phase wall
        # costs (encode / device step / mirror apply) at the round-11
        # stream shape, with and without coalesced mirror folds, plus the
        # depth-1/2 full resolve loop — the before/after evidence for the
        # columnar mirror + vectorized encode work.  Runs anywhere (CPU
        # backend); the fresh subprocess keeps env flags and the process-
        # global span hub out of the score.
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = REPO
        code = (
            "import json, os, sys; sys.path.insert(0, %r)\n"
            "import numpy as np\n"
            "import bench\n"
            "out = bench.bench_pipeline_cpu(depths=(1, 2))\n"
            "os.environ['FDB_TPU_MIRROR_COALESCE'] = '2'\n"
            "out['phases_serialized_coalesce2'] = "
            "bench._pipeline_phase_costs(\n"
            "    np.random.default_rng(2024), 30, 2500, 1 << 19)\n"
            "print('RESULT ' + json.dumps(out))\n"
        ) % REPO
        res = subprocess.run(
            [sys.executable, "-c", code],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=3600,
        )
        line = next(
            (l for l in res.stdout.splitlines() if l.startswith("RESULT ")),
            None,
        )
        artifact = {
            "rc": res.returncode,
            "ok": res.returncode == 0 and line is not None,
            "arm": "hostpath_serialized",
        }
        if line is not None:
            artifact.update(json.loads(line[len("RESULT "):]))
        else:
            artifact["tail"] = (res.stdout + res.stderr)[-800:]
        out_path = os.path.join(REPO, "BENCH_r08.json")
        bench.atomic_write_json(out_path, artifact, indent=2,
                                sort_keys=True)
        print(json.dumps(artifact, indent=2, sort_keys=True))
        print(f"wrote {out_path}", file=sys.stderr)
        return
    if "--mirror" in sys.argv:
        # Host-side mirror A/B (ISSUE 9; bench.MIRROR_VARIANTS): no
        # device needed, runs anywhere — flat vs batched-snapshot mirror
        # apply/detect/rehydrate cost at the skipListTest stream shape.
        import numpy as np

        print(json.dumps(bench.bench_mirror(np.random.default_rng(2024)),
                         indent=2))
        return
    out = {}
    for name, flags, h_cap in VARIANTS:
        env = dict(os.environ)
        env.update(flags)
        env["PYTHONPATH"] = REPO
        code = RUNNER % {"repo": REPO, "h_cap": h_cap}
        print(f"[ab] running {name} (flags={flags})...", file=sys.stderr,
              flush=True)
        try:
            res = subprocess.run(
                [sys.executable, "-c", code],
                env=env, cwd=REPO, capture_output=True, text=True,
                timeout=1800,
            )
            line = next(
                (l for l in res.stdout.splitlines() if l.startswith("RESULT ")),
                None,
            )
            if line is None:
                out[name] = {"error": (res.stdout + res.stderr)[-400:]}
            else:
                out[name] = json.loads(line[len("RESULT "):])
        except subprocess.TimeoutExpired:
            out[name] = {"error": "timeout"}
        print(json.dumps({name: out[name]}), flush=True)
        # Tunnel-resilient per-arm artifact (ISSUE 18 satellite): every
        # finished arm lands atomically before the next one starts, so a
        # mid-campaign tunnel death leaves a partial AB_ARMS.json instead
        # of a lost session.
        bench.atomic_write_json(
            os.path.join(REPO, "AB_ARMS.json"),
            {"arms": out, "complete": False},
            indent=2, sort_keys=True,
        )
    bench.atomic_write_json(
        os.path.join(REPO, "AB_ARMS.json"),
        {"arms": out, "complete": True},
        indent=2, sort_keys=True,
    )
    print(json.dumps({"all": out}), flush=True)
    # Persist the winner so the driver-time bench tries it FIRST (and its
    # compile is already in the shared persistent .jax_cache).
    scored = [
        (v["txns_per_sec"], k) for k, v in out.items() if "txns_per_sec" in v
    ]
    if scored:
        rate, name = max(scored)
        bench.atomic_write_json(
            os.path.join(REPO, "TUNED.json"),
            {
                "variant": name,
                "txns_per_sec": rate,
                "source": "tools/perf_experiments.py in-session A/B",
            },
        )
        print(json.dumps({"tuned": name, "txns_per_sec": rate}), flush=True)


if __name__ == "__main__":
    main()
