"""Per-phase device timing of the conflict engine at the bench config.

Times each jitted sub-piece of detect_core in isolation (own compile, own
block_until_ready bracket) at the BENCH shapes: 64k txns, rr=wr=64k ranges,
h_cap=3.4M, steady-state hcount=2.87M.  Numbers guide which phase gets the
next kernel (PERF_NOTES "next lever").

Run on the TPU:  python tools/profile_engine.py
"""
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from foundationdb_tpu.ops.rangequery import (
    build_max_table, build_min_table, range_max, range_min,
    searchsorted_words, searchsorted_1d,
)
from foundationdb_tpu.ops.stabbing import stabbing_min

KW1 = 3  # bench config: key_words=2 + length word
H = 3407872
HCOUNT = 2874612
RR = WR = 65536
TXN = 65536
P = 2 * RR + 2 * WR
REPS = 10


def timeit(name, fn, *args):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = jax.block_until_ready(fn(*args))
    dt = (time.perf_counter() - t0) / REPS
    print(f"{name:42s} {dt*1e3:8.2f} ms")
    return out


def main():
    rng = np.random.default_rng(7)
    hkeys_np = np.sort(
        rng.integers(0, 2**32, size=(H,), dtype=np.uint32)
    ).astype(np.uint32)
    hkeys = jnp.asarray(
        np.stack([hkeys_np] + [rng.integers(0, 2**32, size=(H,), dtype=np.uint32)
                               for _ in range(KW1 - 1)])
    )
    hvers = jnp.asarray(rng.integers(0, 1 << 20, size=(H,), dtype=np.int32))
    q = jnp.asarray(rng.integers(0, 2**32, size=(KW1, RR), dtype=np.uint32))
    q2 = jnp.asarray(rng.integers(0, 2**32, size=(KW1, 2 * WR), dtype=np.uint32))

    print(f"config: H={H} hcount={HCOUNT} RR=WR={RR} P={P} reps={REPS}")

    f = jax.jit(lambda k, x: searchsorted_words(k, x, "left"))
    timeit("search 64k into H (x1; phase1 does x2)", f, hkeys, q)
    f2 = jax.jit(lambda k, x: searchsorted_words(k, x, "left"))
    timeit("search 128k into H (x1; phase5 does x2)", f2, hkeys, q2)

    timeit("build_max_table over H", jax.jit(build_max_table), hvers)

    i = jnp.asarray(rng.integers(0, H - 1, size=(RR,), dtype=np.int32))
    j = jnp.clip(i + 1000, 0, H - 1)
    tab = jax.jit(build_max_table)(hvers)
    timeit("range_max 64k queries", jax.jit(range_max), tab, i, j)

    # fixpoint pieces at full width P
    p_log2 = max(1, math.ceil(math.log2(P)))
    wb = jnp.asarray(np.sort(rng.integers(0, P, size=(WR,), dtype=np.int32)))
    we = jnp.clip(wb + 4, 0, P - 1)
    wt = jnp.asarray(rng.integers(0, TXN, size=(WR,), dtype=np.int32))
    act = jnp.ones((WR,), bool)
    f3 = jax.jit(lambda b, e, t, a: stabbing_min(b, e, t, a, p_log2))
    stab = timeit("stabbing_min full width P", f3, wb, we, wt, act)
    timeit("build_min_table over P", jax.jit(build_min_table), stab)

    # phase4-6 streaming: cumsums over H
    delta = jnp.asarray(rng.integers(-1, 2, size=(H,), dtype=np.int32))
    timeit("one cumsum over H", jax.jit(lambda d: jnp.cumsum(d)), delta)

    # new-keys sort: 128k x (kw1+1)
    nk = jnp.asarray(rng.integers(0, 2**32, size=(KW1, 2 * WR), dtype=np.uint32))
    iota = jnp.arange(2 * WR, dtype=jnp.int32)
    f4 = jax.jit(
        lambda k, io: jax.lax.sort(
            tuple(k[w] for w in range(KW1)) + (io,), num_keys=KW1, is_stable=True
        )
    )
    timeit("sort 128k new keys (kw1 keys + iota)", f4, nk, iota)

    # compact_to analog: single-key sort of H rows carrying kw1+1 payloads
    pos = jnp.asarray(rng.permutation(H).astype(np.int32))
    f5 = jax.jit(
        lambda p, k, v: jax.lax.sort(
            (p,) + tuple(k[w] for w in range(KW1)) + (v,),
            num_keys=1, is_stable=True,
        )
    )
    timeit("compact_to sort H rows (x2 in ph5/6)", f5, pos, hkeys, hvers)

    # merged concat form (phase 5 sorts H + 128k rows)
    bigpos = jnp.asarray(rng.permutation(H + 2 * WR).astype(np.int32))
    bigk = jnp.concatenate([hkeys, nk], axis=1)
    bigv = jnp.concatenate([hvers, jnp.zeros((2 * WR,), jnp.int32)])
    timeit("compact_to sort H+128k rows", f5, bigpos, bigk, bigv)

    # --- tiered history (ISSUE 4): steady-state delta work vs the ---
    # --- amortized major compaction                                ---
    DCAP = 5 * 2 * WR  # the tiered4 variant's FDB_TPU_DELTA_CAP
    print(f"tiered pieces: d_cap={DCAP}")
    dkeys_np = np.sort(
        rng.integers(0, 2**32, size=(DCAP,), dtype=np.uint32)
    ).astype(np.uint32)
    dkeys = jnp.asarray(
        np.stack([dkeys_np] + [
            rng.integers(0, 2**32, size=(DCAP,), dtype=np.uint32)
            for _ in range(KW1 - 1)
        ])
    )
    dvers = jnp.asarray(rng.integers(0, 1 << 20, size=(DCAP,), dtype=np.int32))
    timeit("tiered: search 64k into delta (x2/batch)", f, dkeys, q)
    timeit("tiered: build_max_table over delta (1/batch)",
           jax.jit(build_max_table), dvers)
    dpos = jnp.asarray(rng.permutation(DCAP + 2 * WR).astype(np.int32))
    dbigk = jnp.concatenate([dkeys, nk], axis=1)
    dbigv = jnp.concatenate([dvers, jnp.zeros((2 * WR,), jnp.int32)])
    f6 = jax.jit(
        lambda p, k, v: jax.lax.sort(
            (p,) + tuple(k[w] for w in range(KW1)) + (v,),
            num_keys=1, is_stable=True,
        )
    )
    timeit("tiered: compact_to sort delta+128k (x2/batch)", f6, dpos,
           dbigk, dbigv)
    # _major_compact searches the FULL delta into the base twice (left +
    # right) — measure at the real D width so cadence/d_cap tuning isn't
    # made against a ~10x-understated number.
    timeit("tiered: search full delta into H (x2/compaction)", f, hkeys,
           dkeys)
    # The compaction itself is ~2x the full-delta search above + ~2x
    # "compact_to sort H+128k rows" + one build_max_table over H — read
    # those rows; divide by the cadence for the amortized per-batch cost.


if __name__ == "__main__":
    main()
