#!/bin/bash
# One-command TPU window exploitation: run when the axon tunnel answers.
#   1. A/B every decision-identical engine variant at the driver bench
#      config (writes TUNED.json so the driver-time bench tries the
#      winner first, with its compile already in .jax_cache).  The list
#      is the ONE shared table bench.VARIANTS — baseline, the two-tier
#      history arms (tiered4 / tiered4_2level, ISSUE 4), search2level,
#      and the evict-batching arms.
#   2. phase-level profiler at the real shapes (attributes ms/batch),
#      including the tiered per-batch vs major-compaction pieces
# Outputs land in perf_runs/<timestamp>/ and survive the session.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT="$REPO/perf_runs/$(date +%Y%m%dT%H%M%S)"
mkdir -p "$OUT"
cd "$REPO"

echo "[window] probing device..." | tee "$OUT/log.txt"
timeout 240 python -c "
import jax
ds = jax.devices()
assert any(d.platform == 'tpu' for d in ds), ds
print('TPU:', ds)
" 2>&1 | tee -a "$OUT/log.txt" || { echo "[window] tunnel dead"; exit 1; }

echo "[window] A/B variants (perf_experiments)..." | tee -a "$OUT/log.txt"
timeout 5400 python tools/perf_experiments.py \
    > "$OUT/ab.jsonl" 2> >(tee -a "$OUT/log.txt" >&2)
tail -2 "$OUT/ab.jsonl" | tee -a "$OUT/log.txt"

echo "[window] phase profiler..." | tee -a "$OUT/log.txt"
timeout 1800 python tools/profile_engine.py \
    > "$OUT/profile.json" 2> >(tee -a "$OUT/log.txt" >&2)
cat "$OUT/profile.json" | tee -a "$OUT/log.txt"

echo "[window] done; TUNED.json:" | tee -a "$OUT/log.txt"
cat TUNED.json 2>/dev/null | tee -a "$OUT/log.txt"
