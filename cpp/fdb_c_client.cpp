// Native C client for foundationdb_tpu: the C ABI from fdb_tpu_c.h over
// the versioned tagged wire protocol.
//
// Ref: bindings/c/fdb_c.cpp (the ABI shape) + fdbrpc/FlowTransport.actor.cpp
// (framing: 4-byte big-endian length + versioned frame; hello =
// "<PROTOCOL_VERSION> <address>"; requests are _Envelope(request, reply_to)
// sent to (token, payload); replies are (is_err, value) tuples delivered to
// the reply endpoint's token over the SAME connection).  Struct ids and
// field positions come from wire_schema.h, generated from the live Python
// registry so both implementations stay in lockstep.
//
// Build:  python tools/gen_wire_schema.py > cpp/wire_schema.h
//         g++ -std=c++17 -O2 -fPIC -shared cpp/fdb_c_client.cpp -o libfdb_tpu_c.so

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

#include "fdb_tpu_c.h"
#include "wire_schema.h"

namespace {

// ---------------------------------------------------------------------------
// Wire value model (mirrors rpc/wire.py's vocabulary)
// ---------------------------------------------------------------------------

enum Tag : uint8_t {
  T_NONE = 0, T_TRUE = 1, T_FALSE = 2, T_INT = 3, T_FLOAT = 4,
  T_BYTES = 5, T_STR = 6, T_LIST = 7, T_TUPLE = 8, T_DICT = 9,
  T_STRUCT = 10, T_ENUM = 11,
};

struct Value;
using ValuePtr = std::shared_ptr<Value>;

struct Value {
  Tag tag = T_NONE;
  int64_t i = 0;            // T_INT / T_ENUM value
  double f = 0;             // T_FLOAT
  std::string bytes;        // T_BYTES / T_STR payload
  std::vector<ValuePtr> items;            // list/tuple/struct fields
  std::vector<std::pair<ValuePtr, ValuePtr>> pairs;  // dict
  uint16_t class_id = 0;    // T_STRUCT / T_ENUM

  static ValuePtr none() { auto v = std::make_shared<Value>(); return v; }
  static ValuePtr boolean(bool b) {
    auto v = std::make_shared<Value>(); v->tag = b ? T_TRUE : T_FALSE; return v;
  }
  static ValuePtr integer(int64_t n) {
    auto v = std::make_shared<Value>(); v->tag = T_INT; v->i = n; return v;
  }
  static ValuePtr blob(const std::string& b) {
    auto v = std::make_shared<Value>(); v->tag = T_BYTES; v->bytes = b; return v;
  }
  static ValuePtr str(const std::string& s) {
    auto v = std::make_shared<Value>(); v->tag = T_STR; v->bytes = s; return v;
  }
  static ValuePtr list() { auto v = std::make_shared<Value>(); v->tag = T_LIST; return v; }
  static ValuePtr tup() { auto v = std::make_shared<Value>(); v->tag = T_TUPLE; return v; }
  static ValuePtr strct(uint16_t cid) {
    auto v = std::make_shared<Value>(); v->tag = T_STRUCT; v->class_id = cid; return v;
  }
  static ValuePtr enm(uint16_t cid, int64_t n) {
    auto v = std::make_shared<Value>(); v->tag = T_ENUM; v->class_id = cid; v->i = n; return v;
  }
};

struct WireError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

// ---------------------------------------------------------------------------
// Codec
// ---------------------------------------------------------------------------

void put_varint(std::string& out, uint64_t n) {
  while (true) {
    uint8_t b = n & 0x7F;
    n >>= 7;
    if (n) out.push_back(char(b | 0x80));
    else { out.push_back(char(b)); return; }
  }
}

uint64_t zigzag(int64_t n) {
  return n >= 0 ? (uint64_t(n) << 1) : ((uint64_t(-n) << 1) - 1);
}

int64_t unzigzag(uint64_t n) {
  return (n & 1) ? -int64_t((n + 1) >> 1) : int64_t(n >> 1);
}

void put_u16(std::string& out, uint16_t v) {
  out.push_back(char(v >> 8));
  out.push_back(char(v & 0xFF));
}

void encode(std::string& out, const ValuePtr& v, int depth = 0) {
  if (depth > 64) throw WireError("nesting too deep");
  switch (v->tag) {
    case T_NONE: case T_TRUE: case T_FALSE:
      out.push_back(char(v->tag));
      break;
    case T_INT:
      out.push_back(char(T_INT));
      put_varint(out, zigzag(v->i));
      break;
    case T_FLOAT: {
      out.push_back(char(T_FLOAT));
      uint64_t bits;
      std::memcpy(&bits, &v->f, 8);
      for (int s = 56; s >= 0; s -= 8) out.push_back(char((bits >> s) & 0xFF));
      break;
    }
    case T_BYTES: case T_STR:
      out.push_back(char(v->tag));
      put_varint(out, v->bytes.size());
      out += v->bytes;
      break;
    case T_LIST: case T_TUPLE:
      out.push_back(char(v->tag));
      put_varint(out, v->items.size());
      for (auto& it : v->items) encode(out, it, depth + 1);
      break;
    case T_DICT:
      out.push_back(char(T_DICT));
      put_varint(out, v->pairs.size());
      for (auto& kv : v->pairs) {
        encode(out, kv.first, depth + 1);
        encode(out, kv.second, depth + 1);
      }
      break;
    case T_STRUCT:
      out.push_back(char(T_STRUCT));
      put_u16(out, v->class_id);
      put_varint(out, v->items.size());
      for (auto& it : v->items) encode(out, it, depth + 1);
      break;
    case T_ENUM:
      out.push_back(char(T_ENUM));
      put_u16(out, v->class_id);
      put_varint(out, zigzag(v->i));
      break;
  }
}

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  uint8_t byte() {
    if (p >= end) throw WireError("truncated frame");
    return *p++;
  }
  const uint8_t* take(size_t n) {
    if (size_t(end - p) < n) throw WireError("truncated frame");
    const uint8_t* q = p;
    p += n;
    return q;
  }
  uint64_t varint() {
    uint64_t out = 0;
    int shift = 0;
    for (int i = 0; i < 16; i++) {
      uint8_t b = byte();
      out |= uint64_t(b & 0x7F) << shift;
      if (!(b & 0x80)) return out;
      shift += 7;
    }
    throw WireError("varint too long");
  }
  uint16_t u16() {
    const uint8_t* q = take(2);
    return uint16_t(q[0]) << 8 | q[1];
  }
};

ValuePtr decode(Reader& r, int depth = 0) {
  if (depth > 64) throw WireError("nesting too deep");
  uint8_t tag = r.byte();
  switch (tag) {
    case T_NONE: return Value::none();
    case T_TRUE: return Value::boolean(true);
    case T_FALSE: return Value::boolean(false);
    case T_INT: return Value::integer(unzigzag(r.varint()));
    case T_FLOAT: {
      const uint8_t* q = r.take(8);
      uint64_t bits = 0;
      for (int i = 0; i < 8; i++) bits = (bits << 8) | q[i];
      auto v = std::make_shared<Value>();
      v->tag = T_FLOAT;
      std::memcpy(&v->f, &bits, 8);
      return v;
    }
    case T_BYTES: case T_STR: {
      uint64_t n = r.varint();
      const uint8_t* q = r.take(n);
      auto v = std::make_shared<Value>();
      v->tag = Tag(tag);
      v->bytes.assign(reinterpret_cast<const char*>(q), n);
      return v;
    }
    case T_LIST: case T_TUPLE: {
      uint64_t n = r.varint();
      auto v = std::make_shared<Value>();
      v->tag = Tag(tag);
      for (uint64_t i = 0; i < n; i++) v->items.push_back(decode(r, depth + 1));
      return v;
    }
    case T_DICT: {
      uint64_t n = r.varint();
      auto v = std::make_shared<Value>();
      v->tag = T_DICT;
      for (uint64_t i = 0; i < n; i++) {
        auto k = decode(r, depth + 1);
        auto val = decode(r, depth + 1);
        v->pairs.emplace_back(k, val);
      }
      return v;
    }
    case T_STRUCT: {
      uint16_t cid = r.u16();
      uint64_t n = r.varint();
      auto v = Value::strct(cid);
      for (uint64_t i = 0; i < n; i++) v->items.push_back(decode(r, depth + 1));
      return v;
    }
    case T_ENUM: {
      uint16_t cid = r.u16();
      return Value::enm(cid, unzigzag(r.varint()));
    }
    default:
      throw WireError("unknown tag");
  }
}

std::string encode_frame(const ValuePtr& v) {
  std::string out;
  out.push_back(char(FDBTPU_WIRE_VERSION));
  encode(out, v);
  return out;
}

ValuePtr decode_frame(const uint8_t* buf, size_t len) {
  Reader r{buf, buf + len};
  if (r.byte() != FDBTPU_WIRE_VERSION) throw WireError("wire version");
  auto v = decode(r);
  if (r.p != r.end) throw WireError("trailing bytes");
  return v;
}

// ---------------------------------------------------------------------------
// Error table (subset of flow/error.py; unknowns map to internal_error)
// ---------------------------------------------------------------------------

const std::map<std::string, int>& error_table() {
  // Generated from flow/error.py (wire_schema.h) — never hand-copied.
  static const std::map<std::string, int> t = {
#define X(name, code) {name, code},
      FDBTPU_ERROR_TABLE(X)
#undef X
  };
  return t;
}

int error_code_for(const std::string& name) {
  auto it = error_table().find(name);
  return it != error_table().end() ? it->second : 4100;
}

// ---------------------------------------------------------------------------
// Transport: one blocking connection; hello; request/reply matching
// ---------------------------------------------------------------------------

struct Connection {
  int fd = -1;
  std::string my_address;
  uint64_t next_token = 1;
  std::string inbuf;

  explicit Connection(const std::string& hostport) {
    auto colon = hostport.rfind(':');
    if (colon == std::string::npos) throw WireError("address needs host:port");
    std::string host = hostport.substr(0, colon);
    std::string port = hostport.substr(colon + 1);
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0 || !res)
      throw WireError("resolve failed");
    fd = socket(res->ai_family, res->ai_socktype, 0);
    if (fd < 0 || connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
      freeaddrinfo(res);
      if (fd >= 0) close(fd);
      fd = -1;
      throw WireError("connect failed");
    }
    freeaddrinfo(res);
    my_address = "cclient-" + std::to_string(uint64_t(getpid())) + "-" +
                 std::to_string(uintptr_t(this) & 0xFFFF) + ":0";
    std::string hello = std::string(FDBTPU_PROTOCOL_VERSION) + " " + my_address;
    send_raw(hello);
  }

  ~Connection() {
    if (fd >= 0) close(fd);
  }

  void send_raw(const std::string& frame) {
    std::string msg;
    uint32_t n = frame.size();
    msg.push_back(char((n >> 24) & 0xFF));
    msg.push_back(char((n >> 16) & 0xFF));
    msg.push_back(char((n >> 8) & 0xFF));
    msg.push_back(char(n & 0xFF));
    msg += frame;
    size_t off = 0;
    while (off < msg.size()) {
      ssize_t w = ::send(fd, msg.data() + off, msg.size() - off, 0);
      if (w <= 0) throw WireError("send failed");
      off += size_t(w);
    }
  }

  // Read one complete frame body.
  std::string read_frame() {
    while (true) {
      if (inbuf.size() >= 4) {
        uint32_t n = (uint8_t(inbuf[0]) << 24) | (uint8_t(inbuf[1]) << 16) |
                     (uint8_t(inbuf[2]) << 8) | uint8_t(inbuf[3]);
        if (n > (64u << 20)) throw WireError("frame too large");
        if (inbuf.size() >= 4 + size_t(n)) {
          std::string frame = inbuf.substr(4, n);
          inbuf.erase(0, 4 + size_t(n));
          return frame;
        }
      }
      char buf[65536];
      ssize_t r = recv(fd, buf, sizeof buf, 0);
      if (r <= 0) throw WireError("connection closed");
      inbuf.append(buf, size_t(r));
    }
  }

  // Send _Envelope(request, reply_to=(my_address, token)) to a stream
  // endpoint and block for the (is_err, value) reply on that token.
  ValuePtr call(const std::string& dst_addr, int64_t dst_token,
                const ValuePtr& request, std::string* err_name) {
    (void)dst_addr;  // single-connection client: everything rides this conn
    uint64_t reply_token = next_token++;
    auto reply_ep = Value::strct(SID_ENDPOINT);
    reply_ep->items = {Value::str(my_address), Value::integer(int64_t(reply_token))};
    auto env = Value::strct(SID_ENVELOPE);
    env->items = {request, reply_ep};
    auto msg = Value::tup();
    msg->items = {Value::integer(dst_token), env};
    send_raw(encode_frame(msg));
    while (true) {
      std::string frame = read_frame();
      auto v = decode_frame(reinterpret_cast<const uint8_t*>(frame.data()),
                            frame.size());
      if (v->tag != T_TUPLE || v->items.size() != 2) throw WireError("bad frame");
      if (v->items[0]->tag != T_INT) throw WireError("bad token");
      if (uint64_t(v->items[0]->i) != reply_token) continue;  // stale reply
      auto reply = v->items[1];
      if (reply->tag != T_TUPLE || reply->items.size() != 2)
        throw WireError("bad reply");
      bool is_err = reply->items[0]->tag == T_TRUE;
      if (is_err) {
        // Bare errors are (True, name); a structured cause widens to
        // (True, (name, detail)).  The C ABI surfaces numeric codes
        // only, so take the name and drop the detail.
        auto ev = reply->items[1];
        if (ev->tag == T_TUPLE && !ev->items.empty()) ev = ev->items[0];
        *err_name = ev->bytes;  // error name string
        return nullptr;
      }
      err_name->clear();
      return reply->items[1];
    }
  }
};

// crc32 for well-known tokens: token = (1<<40) | crc32(name).
uint32_t crc32_of(const std::string& s) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (unsigned char ch : s) c = table[(c ^ ch) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

int64_t well_known_token(const std::string& name) {
  return (int64_t(1) << 40) | crc32_of(name);
}

// Positional field access with the wire protocol's short-struct
// tolerance: a peer may legally send FEWER fields than we know (old peer,
// new local field — wire.py fills the tail from defaults); out-of-range
// reads here return None instead of indexing past the vector.
ValuePtr fget(const ValuePtr& v, size_t i) {
  if (!v || v->tag != T_STRUCT || i >= v->items.size()) return Value::none();
  return v->items[i];
}

// Extract (address, token) from a RequestStreamRef struct value.
struct StreamRef {
  std::string address;
  int64_t token = 0;
  bool ok = false;
};

StreamRef ref_of(const ValuePtr& v) {
  StreamRef out;
  if (!v || v->tag != T_STRUCT || v->class_id != SID_REQUESTSTREAMREF) return out;
  auto ep = fget(v, F_REQUESTSTREAMREF_ENDPOINT);
  if (!ep || ep->tag != T_STRUCT || ep->class_id != SID_ENDPOINT) return out;
  auto addr = fget(ep, F_ENDPOINT_ADDRESS);
  auto tok = fget(ep, F_ENDPOINT_TOKEN);
  if (addr->tag != T_STR || tok->tag != T_INT) return out;
  out.address = addr->bytes;
  out.token = tok->i;
  out.ok = true;
  return out;
}

}  // namespace

// ---------------------------------------------------------------------------
// ABI objects
// ---------------------------------------------------------------------------

struct FDBDatabase {
  std::unique_ptr<Connection> conn;
  StreamRef grv, commit, get_value, get_key_values;
};

struct Range {
  std::string begin, end;
};

struct FDBTransaction {
  FDBDatabase* db = nullptr;
  bool has_read_version = false;
  int64_t read_version = 0;
  std::vector<ValuePtr> mutations;  // Mutation structs
  std::vector<Range> read_ranges, write_ranges;
  std::map<std::string, std::pair<bool, std::string>> overlay;  // RYW: key -> (present, value)
};

struct FDBFuture {
  int err = 0;
  std::string err_name;
  bool has_value = false;
  bool present = false;
  std::string value;
  bool has_version = false;
  int64_t version = 0;
  // range results
  bool has_kvs = false;
  std::vector<std::pair<std::string, std::string>> kvs;
  bool more = false;
  std::vector<FDBKeyValue> kv_view;  // pointers into kvs
};

static std::string key_after(const std::string& k) { return k + '\0'; }

static FDBFuture* make_err(const std::string& name) {
  auto* f = new FDBFuture();
  f->err_name = name;
  f->err = error_code_for(name);
  return f;
}

extern "C" {

const char* fdb_get_error(fdb_error_t code) {
  for (auto& kv : error_table())
    if (kv.second == code) {
      return kv.first.c_str();
    }
  return code == 0 ? "success" : "unknown_error_code";
}

fdb_error_t fdb_select_api_version(int) { return 0; }

fdb_error_t fdb_create_database(const char* cluster_address, FDBDatabase** out_db) {
  try {
    auto db = std::make_unique<FDBDatabase>();
    db->conn = std::make_unique<Connection>(cluster_address);
    // Bootstrap: discover the proxy + storage interfaces (real_node.py's
    // well-known bootstrap stream).
    std::string err;
    auto ifaces = db->conn->call(cluster_address, well_known_token("bootstrap"),
                                 Value::none(), &err);
    if (!ifaces || ifaces->tag != T_DICT) return FDB_E_NETWORK_FAILED;
    for (auto& kv : ifaces->pairs) {
      const std::string& name = kv.first->bytes;
      if (name == "proxy" && kv.second->tag == T_STRUCT) {
        db->grv = ref_of(fget(kv.second, F_PROXYINTERFACE_GET_CONSISTENT_READ_VERSION));
        db->commit = ref_of(fget(kv.second, F_PROXYINTERFACE_COMMIT));
      } else if (name == "storage" && kv.second->tag == T_STRUCT) {
        db->get_value = ref_of(fget(kv.second, F_STORAGEINTERFACE_GET_VALUE));
        db->get_key_values = ref_of(fget(kv.second, F_STORAGEINTERFACE_GET_KEY_VALUES));
      }
    }
    if (!db->grv.ok || !db->commit.ok || !db->get_value.ok)
      return FDB_E_NETWORK_FAILED;
    *out_db = db.release();
    return 0;
  } catch (const std::exception&) {
    return FDB_E_NETWORK_FAILED;
  }
}

void fdb_database_destroy(FDBDatabase* db) { delete db; }

fdb_error_t fdb_database_create_transaction(FDBDatabase* db, FDBTransaction** out_tr) {
  auto* tr = new FDBTransaction();
  tr->db = db;
  *out_tr = tr;
  return 0;
}

void fdb_transaction_destroy(FDBTransaction* tr) { delete tr; }

void fdb_transaction_reset(FDBTransaction* tr) {
  tr->has_read_version = false;
  tr->mutations.clear();
  tr->read_ranges.clear();
  tr->write_ranges.clear();
  tr->overlay.clear();
}

static ValuePtr make_mutation(int type, const std::string& p1, const std::string& p2) {
  auto m = Value::strct(SID_MUTATION);
  m->items = {Value::enm(EID_MUTATIONTYPE, type), Value::blob(p1), Value::blob(p2)};
  return m;
}

void fdb_transaction_set(FDBTransaction* tr, const uint8_t* key, int key_len,
                         const uint8_t* value, int value_len) {
  std::string k(reinterpret_cast<const char*>(key), size_t(key_len));
  std::string v(reinterpret_cast<const char*>(value), size_t(value_len));
  tr->mutations.push_back(make_mutation(MT_SET_VALUE, k, v));
  tr->write_ranges.push_back({k, key_after(k)});
  tr->overlay[k] = {true, v};
}

void fdb_transaction_clear(FDBTransaction* tr, const uint8_t* key, int key_len) {
  std::string k(reinterpret_cast<const char*>(key), size_t(key_len));
  tr->mutations.push_back(make_mutation(MT_CLEAR_RANGE, k, key_after(k)));
  tr->write_ranges.push_back({k, key_after(k)});
  tr->overlay[k] = {false, ""};
}

void fdb_transaction_atomic_op(FDBTransaction* tr, const uint8_t* key,
                               int key_len, const uint8_t* param,
                               int param_len, int mutation_type) {
  std::string k(reinterpret_cast<const char*>(key), size_t(key_len));
  std::string p(reinterpret_cast<const char*>(param), size_t(param_len));
  tr->mutations.push_back(make_mutation(mutation_type, k, p));
  tr->write_ranges.push_back({k, key_after(k)});
  // The overlay cannot model server-side atomic application: drop any
  // cached view so a later get re-reads through the server... it cannot
  // (the op is pending).  Parity note: reads of a key with a pending
  // atomic in THIS simplified client return the pre-op value; use the
  // Python client for full RYW-over-atomics semantics.
  tr->overlay.erase(k);
}

fdb_error_t fdb_transaction_on_error(FDBTransaction* tr, fdb_error_t err) {
  switch (err) {
    case 1020:  /* not_committed */
    case 1021:  /* commit_unknown_result */
    case 1007:  /* transaction_too_old */
    case 1009:  /* future_version */
    case 1037:  /* process_behind */
    case 1038:  /* database_locked */
      fdb_transaction_reset(tr);
      return 0;
    default:
      return err;
  }
}

void fdb_transaction_clear_range(FDBTransaction* tr, const uint8_t* begin,
                                 int begin_len, const uint8_t* end, int end_len) {
  std::string b(reinterpret_cast<const char*>(begin), size_t(begin_len));
  std::string e(reinterpret_cast<const char*>(end), size_t(end_len));
  tr->mutations.push_back(make_mutation(MT_CLEAR_RANGE, b, e));
  tr->write_ranges.push_back({b, e});
  // RYW overlay for range clears is coarse: later gets inside [b,e) miss.
  for (auto it = tr->overlay.lower_bound(b);
       it != tr->overlay.end() && it->first < e;)
    it = tr->overlay.erase(it);
}

static int ensure_read_version(FDBTransaction* tr, std::string* err_name) {
  if (tr->has_read_version) return 0;
  auto req = Value::strct(SID_GETREADVERSIONREQUEST);
  req->items = {Value::integer(1), Value::integer(0), Value::none()};
  auto v = tr->db->conn->call(tr->db->grv.address, tr->db->grv.token, req, err_name);
  if (!v) return error_code_for(*err_name);
  tr->read_version = v->i;
  tr->has_read_version = true;
  return 0;
}

FDBFuture* fdb_transaction_get_read_version(FDBTransaction* tr) {
  std::string err;
  try {
    int rc = ensure_read_version(tr, &err);
    if (rc) return make_err(err);
  } catch (const std::exception&) {
    return make_err("broken_promise");
  }
  auto* f = new FDBFuture();
  f->has_version = true;
  f->version = tr->read_version;
  return f;
}

FDBFuture* fdb_transaction_get(FDBTransaction* tr, const uint8_t* key, int key_len) {
  std::string k(reinterpret_cast<const char*>(key), size_t(key_len));
  // Read-your-writes: pending mutations win over the store.
  auto ov = tr->overlay.find(k);
  if (ov != tr->overlay.end()) {
    auto* f = new FDBFuture();
    f->has_value = true;
    f->present = ov->second.first;
    f->value = ov->second.second;
    return f;
  }
  std::string err;
  try {
    int rc = ensure_read_version(tr, &err);
    if (rc) return make_err(err);
    auto req = Value::strct(SID_GETVALUEREQUEST);
    req->items = {Value::blob(k), Value::integer(tr->read_version)};
    auto v = tr->db->conn->call(tr->db->get_value.address,
                                tr->db->get_value.token, req, &err);
    if (!v) return make_err(err);
    // GetValueReply(value, version)
    auto* f = new FDBFuture();
    f->has_value = true;
    auto val = fget(v, F_GETVALUEREPLY_VALUE);
    f->present = val->tag == T_BYTES;
    if (f->present) f->value = val->bytes;
    tr->read_ranges.push_back({k, key_after(k)});
    return f;
  } catch (const std::exception&) {
    return make_err("broken_promise");
  }
}

FDBFuture* fdb_transaction_get_range(FDBTransaction* tr, const uint8_t* begin,
                                     int begin_len, const uint8_t* end,
                                     int end_len, int limit) {
  std::string b(reinterpret_cast<const char*>(begin), size_t(begin_len));
  std::string e(reinterpret_cast<const char*>(end), size_t(end_len));
  std::string err;
  try {
    int rc = ensure_read_version(tr, &err);
    if (rc) return make_err(err);
    auto req = Value::strct(SID_GETKEYVALUESREQUEST);
    req->items = {Value::blob(b), Value::blob(e),
                  Value::integer(tr->read_version),
                  Value::integer(limit > 0 ? limit : (1 << 30)),
                  Value::boolean(false)};
    auto v = tr->db->conn->call(tr->db->get_key_values.address,
                                tr->db->get_key_values.token, req, &err);
    if (!v) return make_err(err);
    auto* f = new FDBFuture();
    f->has_kvs = true;
    // Merge the RYW overlay over the server rows (pending sets win,
    // pending point-clears mask) so get() and get_range() agree inside
    // one transaction.  Range-clear coarseness is documented in
    // fdb_tpu_c.h.
    std::map<std::string, std::string> merged;
    auto data = fget(v, F_GETKEYVALUESREPLY_DATA);
    for (auto& row : data->items) {
      if (row->items.size() >= 2)
        merged[row->items[0]->bytes] = row->items[1]->bytes;
    }
    for (auto it = tr->overlay.lower_bound(b);
         it != tr->overlay.end() && it->first < e; ++it) {
      if (it->second.first) merged[it->first] = it->second.second;
      else merged.erase(it->first);
    }
    for (auto& kv : merged) {
      f->kvs.emplace_back(kv.first, kv.second);
      if (limit > 0 && int(f->kvs.size()) >= limit) break;
    }
    f->more = fget(v, F_GETKEYVALUESREPLY_MORE)->tag == T_TRUE;
    tr->read_ranges.push_back({b, e});
    return f;
  } catch (const std::exception&) {
    return make_err("broken_promise");
  }
}

FDBFuture* fdb_transaction_commit(FDBTransaction* tr) {
  std::string err;
  try {
    if (tr->mutations.empty() && tr->write_ranges.empty()) {
      auto* f = new FDBFuture();  // read-only: nothing to do
      f->has_version = true;
      f->version = tr->read_version;
      return f;
    }
    // Reads need a snapshot to resolve against (a blind write commits
    // with read_snapshot 0 and no read set, like causal_write_risky).
    if (!tr->read_ranges.empty()) {
      int rc = ensure_read_version(tr, &err);
      if (rc) return make_err(err);
    }
    auto ctref = Value::strct(SID_COMMITTRANSACTIONREF);
    auto rrs = Value::list();
    for (auto& r : tr->read_ranges) {
      auto t = Value::tup();
      t->items = {Value::blob(r.begin), Value::blob(r.end)};
      rrs->items.push_back(t);
    }
    auto wrs = Value::list();
    for (auto& r : tr->write_ranges) {
      auto t = Value::tup();
      t->items = {Value::blob(r.begin), Value::blob(r.end)};
      wrs->items.push_back(t);
    }
    auto muts = Value::list();
    muts->items = tr->mutations;
    ctref->items = {
        Value::integer(tr->read_ranges.empty() ? 0 : tr->read_version),
        rrs, wrs, muts};
    auto req = Value::strct(SID_COMMITTRANSACTIONREQUEST);
    req->items = {ctref, Value::integer(0), Value::none()};
    auto v = tr->db->conn->call(tr->db->commit.address, tr->db->commit.token,
                                req, &err);
    if (!v) return make_err(err);
    auto* f = new FDBFuture();
    f->has_version = true;
    f->version = v->i;
    return f;
  } catch (const std::exception&) {
    return make_err("commit_unknown_result");
  }
}

fdb_error_t fdb_future_block_until_ready(FDBFuture*) { return 0; }

fdb_error_t fdb_future_get_error(FDBFuture* f) { return f->err; }

fdb_error_t fdb_future_get_value(FDBFuture* f, fdb_bool_t* out_present,
                                 const uint8_t** out_value, int* out_value_len) {
  if (f->err) return f->err;
  if (!f->has_value) return 4100;
  *out_present = f->present ? 1 : 0;
  *out_value = reinterpret_cast<const uint8_t*>(f->value.data());
  *out_value_len = int(f->value.size());
  return 0;
}

fdb_error_t fdb_future_get_version(FDBFuture* f, int64_t* out_version) {
  if (f->err) return f->err;
  if (!f->has_version) return 4100;
  *out_version = f->version;
  return 0;
}

fdb_error_t fdb_future_get_keyvalue_array(FDBFuture* f, const FDBKeyValue** out_kv,
                                          int* out_count, fdb_bool_t* out_more) {
  if (f->err) return f->err;
  if (!f->has_kvs) return 4100;
  f->kv_view.clear();
  for (auto& kv : f->kvs)
    f->kv_view.push_back(FDBKeyValue{
        reinterpret_cast<const uint8_t*>(kv.first.data()), int(kv.first.size()),
        reinterpret_cast<const uint8_t*>(kv.second.data()), int(kv.second.size())});
  *out_kv = f->kv_view.data();
  *out_count = int(f->kv_view.size());
  *out_more = f->more ? 1 : 0;
  return 0;
}

void fdb_future_destroy(FDBFuture* f) { delete f; }

}  // extern "C"
