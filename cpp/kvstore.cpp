// Native key-value engine: the reference's "memory" storage engine in C++.
//
// Ref: fdbserver/KeyValueStoreMemory.actor.cpp — the full key space lives in
// RAM (here an ordered std::map); durability comes from a write-ahead log
// with CRC-framed records fsynced at commit, periodically compacted into a
// snapshot file (the reference snapshots through its disk queue; same
// recovery contract: load snapshot, replay WAL, truncate torn tail).
//
// Exposed as a C ABI for ctypes (pybind11 is not available in this image).
// Single-threaded by design, like every flow storage engine: the Python
// event loop serializes access.
//
// File layout in <dir>:
//   snapshot-<gen>      length-prefixed (k, v) pairs + trailer CRC
//   wal-<gen>           CRC-framed records: 1-byte op, k, v
//   CURRENT             "gen\n" — which generation is authoritative
// Recovery: read CURRENT, load snapshot-<gen>, replay wal-<gen> until the
// first bad frame (torn tail), ignore everything else.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

uint32_t crc32(const uint8_t* data, size_t len, uint32_t seed = 0) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = ~seed;
  for (size_t i = 0; i < len; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return ~c;
}

void put32(std::string& out, uint32_t v) {
  out.append(reinterpret_cast<const char*>(&v), 4);
}

struct Store {
  std::string dir;
  std::map<std::string, std::string> kv;
  int wal_fd = -1;
  uint64_t gen = 0;
  uint64_t wal_bytes = 0;
  uint64_t compact_threshold = 64ull << 20;
  std::string pending;  // buffered, unsynced WAL frames
  std::string last_error;

  std::string path(const char* kind, uint64_t g) const {
    char buf[64];
    snprintf(buf, sizeof buf, "/%s-%llu", kind, (unsigned long long)g);
    return dir + buf;
  }

  bool write_all(int fd, const std::string& data) {
    size_t off = 0;
    while (off < data.size()) {
      ssize_t n = ::write(fd, data.data() + off, data.size() - off);
      if (n < 0) {
        if (errno == EINTR) continue;
        last_error = "write failed";
        return false;
      }
      off += (size_t)n;
    }
    return true;
  }

  // -- WAL framing: [len u32][crc u32][op u8][klen u32][k][vlen u32][v] --
  void frame(char op, const std::string& k, const std::string& v) {
    std::string body;
    body.push_back(op);
    put32(body, (uint32_t)k.size());
    body += k;
    put32(body, (uint32_t)v.size());
    body += v;
    std::string rec;
    put32(rec, (uint32_t)body.size());
    put32(rec, crc32((const uint8_t*)body.data(), body.size()));
    rec += body;
    pending += rec;
  }

  bool commit() {
    if (!pending.empty()) {
      if (!write_all(wal_fd, pending)) return false;
      wal_bytes += pending.size();
      pending.clear();
      if (::fdatasync(wal_fd) != 0) {
        last_error = "fdatasync failed";
        return false;
      }
    }
    if (wal_bytes > compact_threshold) return compact();
    return true;
  }

  bool compact() {
    uint64_t next = gen + 1;
    std::string snap = path("snapshot", next);
    int fd = ::open(snap.c_str(), O_CREAT | O_TRUNC | O_WRONLY, 0644);
    if (fd < 0) {
      last_error = "snapshot open failed";
      return false;
    }
    std::string buf;
    uint32_t running = 0;
    for (auto& [k, v] : kv) {
      put32(buf, (uint32_t)k.size());
      buf += k;
      put32(buf, (uint32_t)v.size());
      buf += v;
      if (buf.size() > (1u << 20)) {
        running = crc32((const uint8_t*)buf.data(), buf.size(), running);
        if (!write_all(fd, buf)) { ::close(fd); return false; }
        buf.clear();
      }
    }
    running = crc32((const uint8_t*)buf.data(), buf.size(), running);
    if (!write_all(fd, buf)) { ::close(fd); return false; }
    std::string trailer = "SNAPEND!";
    put32(trailer, running);
    if (!write_all(fd, trailer) || ::fdatasync(fd) != 0) {
      ::close(fd);
      last_error = "snapshot write failed";
      return false;
    }
    ::close(fd);
    // Fresh empty WAL for the new generation, then flip CURRENT (the
    // commit point of the compaction), then drop the old generation.
    std::string wal = path("wal", next);
    int wfd = ::open(wal.c_str(), O_CREAT | O_TRUNC | O_WRONLY, 0644);
    if (wfd < 0) { last_error = "wal open failed"; return false; }
    std::string cur = dir + "/CURRENT.tmp";
    int cfd = ::open(cur.c_str(), O_CREAT | O_TRUNC | O_WRONLY, 0644);
    if (cfd < 0) { ::close(wfd); last_error = "CURRENT open failed"; return false; }
    char num[32];
    snprintf(num, sizeof num, "%llu\n", (unsigned long long)next);
    if (!write_all(cfd, num) || ::fdatasync(cfd) != 0) { ::close(cfd); ::close(wfd); return false; }
    ::close(cfd);
    if (::rename(cur.c_str(), (dir + "/CURRENT").c_str()) != 0) {
      ::close(wfd);
      last_error = "CURRENT rename failed";
      return false;
    }
    ::unlink(path("snapshot", gen).c_str());
    ::unlink(path("wal", gen).c_str());
    if (wal_fd >= 0) ::close(wal_fd);
    wal_fd = wfd;
    wal_bytes = 0;
    gen = next;
    return true;
  }

  bool load_snapshot(const std::string& p) {
    int fd = ::open(p.c_str(), O_RDONLY);
    if (fd < 0) return true;  // absent = empty (gen 0 bootstrap)
    struct stat st;
    if (fstat(fd, &st) != 0) { ::close(fd); return false; }
    std::string img((size_t)st.st_size, '\0');
    size_t off = 0;
    while (off < img.size()) {
      ssize_t n = ::read(fd, &img[off], img.size() - off);
      if (n <= 0) break;
      off += (size_t)n;
    }
    ::close(fd);
    if (img.size() < 12) return img.empty();
    size_t body = img.size() - 12;
    if (memcmp(img.data() + body, "SNAPEND!", 8) != 0) {
      last_error = "snapshot trailer missing";
      return false;
    }
    uint32_t want;
    memcpy(&want, img.data() + body + 8, 4);
    if (crc32((const uint8_t*)img.data(), body) != want) {
      last_error = "snapshot crc mismatch";
      return false;
    }
    size_t i = 0;
    while (i + 8 <= body) {
      uint32_t kl, vl;
      memcpy(&kl, img.data() + i, 4);
      if (i + 4 + kl + 4 > body) break;
      memcpy(&vl, img.data() + i + 4 + kl, 4);
      if (i + 8 + kl + vl > body) break;
      kv.emplace(img.substr(i + 4, kl), img.substr(i + 8 + kl, vl));
      i += 8 + kl + vl;
    }
    return true;
  }

  void apply(char op, const std::string& a, const std::string& b) {
    if (op == 'S') {
      kv[a] = b;
    } else {  // 'C': clear range [a, b); empty b = clear to end
      auto lo = kv.lower_bound(a);
      auto hi = b.empty() ? kv.end() : kv.lower_bound(b);
      kv.erase(lo, hi);
    }
  }

  bool replay_wal(const std::string& p) {
    int fd = ::open(p.c_str(), O_RDONLY);
    if (fd < 0) return true;  // absent = nothing to replay
    struct stat st;
    fstat(fd, &st);
    std::string img((size_t)st.st_size, '\0');
    size_t off = 0;
    while (off < img.size()) {
      ssize_t n = ::read(fd, &img[off], img.size() - off);
      if (n <= 0) break;
      off += (size_t)n;
    }
    ::close(fd);
    size_t i = 0;
    while (i + 8 <= img.size()) {
      uint32_t len, want;
      memcpy(&len, img.data() + i, 4);
      memcpy(&want, img.data() + i + 4, 4);
      if (i + 8 + len > img.size()) break;  // torn tail
      const uint8_t* b = (const uint8_t*)img.data() + i + 8;
      if (crc32(b, len) != want) break;  // torn/corrupt: durable prefix ends
      if (len < 9) break;
      char op = (char)b[0];
      uint32_t kl, vl;
      memcpy(&kl, b + 1, 4);
      if (5 + kl + 4 > len) break;
      memcpy(&vl, b + 5 + kl, 4);
      if (9 + kl + vl > len) break;
      apply(op, std::string((const char*)b + 5, kl),
            std::string((const char*)b + 9 + kl, vl));
      i += 8 + len;
    }
    wal_bytes = i;
    return true;
  }

  bool open_store(const char* d) {
    dir = d;
    ::mkdir(d, 0755);
    // CURRENT names the authoritative generation.
    FILE* f = fopen((dir + "/CURRENT").c_str(), "r");
    if (f) {
      unsigned long long g = 0;
      if (fscanf(f, "%llu", &g) == 1) gen = g;
      fclose(f);
    }
    if (!load_snapshot(path("snapshot", gen))) return false;
    if (!replay_wal(path("wal", gen))) return false;
    wal_fd = ::open(path("wal", gen).c_str(), O_CREAT | O_WRONLY | O_APPEND, 0644);
    if (wal_fd < 0) {
      last_error = "wal open failed";
      return false;
    }
    return true;
  }
};

struct Iter {
  std::vector<std::pair<std::string, std::string>> rows;
  size_t i = 0;
};

}  // namespace

extern "C" {

void* kv_open(const char* dir) {
  Store* s = new Store();
  if (!s->open_store(dir)) {
    delete s;
    return nullptr;
  }
  return s;
}

void kv_close(void* h) {
  Store* s = (Store*)h;
  if (!s) return;
  if (s->wal_fd >= 0) ::close(s->wal_fd);
  delete s;
}

void kv_set(void* h, const char* k, uint32_t kl, const char* v, uint32_t vl) {
  Store* s = (Store*)h;
  std::string key(k, kl), val(v, vl);
  s->frame('S', key, val);
  s->apply('S', key, val);
}

void kv_clear_range(void* h, const char* b, uint32_t bl, const char* e, uint32_t el) {
  Store* s = (Store*)h;
  std::string begin(b, bl), end(e, el);
  s->frame('C', begin, end);
  s->apply('C', begin, end);
}

int kv_commit(void* h) { return ((Store*)h)->commit() ? 0 : -1; }

int kv_compact(void* h) { return ((Store*)h)->compact() ? 0 : -1; }

// get: returns 1 + fills out/out_len (valid until the next call), 0 if absent
int kv_get(void* h, const char* k, uint32_t kl, const char** out, uint32_t* out_len) {
  Store* s = (Store*)h;
  auto it = s->kv.find(std::string(k, kl));
  if (it == s->kv.end()) return 0;
  *out = it->second.data();
  *out_len = (uint32_t)it->second.size();
  return 1;
}

void* kv_range_open(void* h, const char* b, uint32_t bl, const char* e,
                    uint32_t el, uint32_t limit, int reverse) {
  Store* s = (Store*)h;
  std::string begin(b, bl), end(e, el);
  Iter* it = new Iter();
  auto lo = s->kv.lower_bound(begin);
  auto hi = end.empty() ? s->kv.end() : s->kv.lower_bound(end);
  if (!reverse) {
    for (auto p = lo; p != hi && it->rows.size() < limit; ++p)
      it->rows.emplace_back(p->first, p->second);
  } else {
    for (auto p = hi; p != lo && it->rows.size() < limit;) {
      --p;
      it->rows.emplace_back(p->first, p->second);
    }
  }
  return it;
}

int kv_range_next(void* h, const char** k, uint32_t* kl, const char** v, uint32_t* vl) {
  Iter* it = (Iter*)h;
  if (it->i >= it->rows.size()) return 0;
  auto& row = it->rows[it->i++];
  *k = row.first.data();
  *kl = (uint32_t)row.first.size();
  *v = row.second.data();
  *vl = (uint32_t)row.second.size();
  return 1;
}

void kv_range_close(void* h) { delete (Iter*)h; }

uint64_t kv_count(void* h) { return ((Store*)h)->kv.size(); }

const char* kv_last_error(void* h) { return ((Store*)h)->last_error.c_str(); }

void kv_set_compact_threshold(void* h, uint64_t bytes) {
  ((Store*)h)->compact_threshold = bytes;
}

}  // extern "C"
