/* C ABI for the foundationdb_tpu framework.
 *
 * Ref: bindings/c/foundationdb/fdb_c.h:190 — the same surface shape
 * (database / transaction / future handles, byte-string keys/values,
 * integer error codes) so a caller of the reference's C API finds the
 * familiar contract.  This client is NATIVE: it speaks the versioned
 * tagged wire protocol (rpc/wire.py, generated schema in wire_schema.h)
 * over TCP to a real-mode cluster — no embedded interpreter.
 *
 * Simplifications vs the reference ABI (documented, not hidden):
 *   - Futures resolve synchronously (the call blocks); fdb_future_block_
 *     until_ready is therefore a no-op kept for source compatibility.
 *   - One outstanding request per transaction (single connection,
 *     blocking reads).
 *   - Read-your-writes covers point sets/clears (get and get_range both
 *     see them); a clear_range's masking of SERVER rows inside the same
 *     transaction is not modeled — commit ordering is still exact.
 */
#ifndef FDB_TPU_C_H
#define FDB_TPU_C_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef int fdb_error_t;
typedef int fdb_bool_t;

typedef struct FDBDatabase FDBDatabase;
typedef struct FDBTransaction FDBTransaction;
typedef struct FDBFuture FDBFuture;

/* Error codes mirror flow/error.py (the reference's error_definitions). */
#define FDB_E_SUCCESS 0
#define FDB_E_NOT_COMMITTED 1020
#define FDB_E_COMMIT_UNKNOWN_RESULT 1021
#define FDB_E_TRANSACTION_TOO_OLD 1007
#define FDB_E_BROKEN_PROMISE 1100
#define FDB_E_DATABASE_LOCKED 1038
#define FDB_E_NETWORK_FAILED 1026

const char* fdb_get_error(fdb_error_t code);
fdb_error_t fdb_select_api_version(int version);

/* cluster_address: "host:port" of a real-mode node serving the
 * well-known bootstrap stream (tools/real_node.py). */
fdb_error_t fdb_create_database(const char* cluster_address,
                                FDBDatabase** out_db);
void fdb_database_destroy(FDBDatabase* db);

fdb_error_t fdb_database_create_transaction(FDBDatabase* db,
                                            FDBTransaction** out_tr);
void fdb_transaction_destroy(FDBTransaction* tr);
void fdb_transaction_reset(FDBTransaction* tr);

void fdb_transaction_set(FDBTransaction* tr,
                         const uint8_t* key, int key_len,
                         const uint8_t* value, int value_len);
void fdb_transaction_clear(FDBTransaction* tr,
                           const uint8_t* key, int key_len);
void fdb_transaction_clear_range(FDBTransaction* tr,
                                 const uint8_t* begin, int begin_len,
                                 const uint8_t* end, int end_len);

FDBFuture* fdb_transaction_get(FDBTransaction* tr,
                               const uint8_t* key, int key_len);
FDBFuture* fdb_transaction_get_range(FDBTransaction* tr,
                                     const uint8_t* begin, int begin_len,
                                     const uint8_t* end, int end_len,
                                     int limit);
/* mutation_type: the MutationType enum value (wire_schema.h MT_*; the
 * full set matches client/types.py MutationType). */
void fdb_transaction_atomic_op(FDBTransaction* tr,
                               const uint8_t* key, int key_len,
                               const uint8_t* param, int param_len,
                               int mutation_type);
/* Reset-and-classify like the reference's fdb_transaction_on_error:
 * returns 0 when the error is retryable (the transaction has been reset
 * and may be retried), else echoes the error. */
fdb_error_t fdb_transaction_on_error(FDBTransaction* tr, fdb_error_t err);
FDBFuture* fdb_transaction_get_read_version(FDBTransaction* tr);
FDBFuture* fdb_transaction_commit(FDBTransaction* tr);

/* Futures (synchronously resolved; see header comment). */
fdb_error_t fdb_future_block_until_ready(FDBFuture* f);
fdb_error_t fdb_future_get_error(FDBFuture* f);
fdb_error_t fdb_future_get_value(FDBFuture* f, fdb_bool_t* out_present,
                                 const uint8_t** out_value,
                                 int* out_value_len);
fdb_error_t fdb_future_get_version(FDBFuture* f, int64_t* out_version);
typedef struct {
    const uint8_t* key;
    int key_len;
    const uint8_t* value;
    int value_len;
} FDBKeyValue;
fdb_error_t fdb_future_get_keyvalue_array(FDBFuture* f,
                                          const FDBKeyValue** out_kv,
                                          int* out_count,
                                          fdb_bool_t* out_more);
void fdb_future_destroy(FDBFuture* f);

#ifdef __cplusplus
}
#endif
#endif /* FDB_TPU_C_H */
