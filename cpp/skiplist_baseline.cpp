// C++ CPU baseline for the resolver conflict-detection benchmark.
//
// The reference measures its ConflictSet with `fdbserver -r skiplisttest`
// (fdbserver/SkipList.cpp:1412-1502): 500 batches x 2500 transactions, each
// with 1 read + 1 write conflict range, integer keys uniform in [0, 2e7),
// range width 1 + U[0,10), read_snapshot = batch index, detect at
// now = i + 50, window evicted below i.  Building the reference's binary
// needs its Mono-era actor-compiler toolchain, so this is an independent,
// from-scratch C++ implementation of the same *semantics* (the in-repo
// authority is foundationdb_tpu/conflict/engine_cpu.py; differentially
// tested against it via --selftest) at competitive native performance:
// a versioned skiplist whose links carry span version-maxima.
//
// Data model (same as engine_cpu.py): a step function key -> version of the
// last committed write covering [key, next_key).  A read [b, e) at snapshot
// s conflicts iff max version over the covering entries > s.  Committed
// writes overwrite [b, e) at the batch version; eviction drops a boundary
// iff it and its predecessor are both below the window.
//
// Invariant note: maxv spans may transiently OVER-approximate by versions
// already below the eviction window (deletions fold the dead node's span
// max into the predecessor instead of an exact walk).  Safe: every live
// read snapshot is >= the window floor, so a dead below-window version can
// never flip a `max > snapshot` comparison.
//
// Usage:
//   skiplist_baseline                  run the microbench, print one JSON line
//   skiplist_baseline --batches N --per-batch M [--window W]
//   skiplist_baseline --selftest       read batches on stdin, print decisions
//
// Selftest stdin format (ints):
//   B <now> <new_oldest> <ntxn>
//   <snap> <nr> <nw> then nr+nw lines "r b e" / "w b e"
// Output: one line per batch: space-separated statuses
// (0=conflict, 1=too_old, 2=committed — conflict/types.py codes).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <chrono>
#include <map>
#include <vector>

namespace {

constexpr int kMaxH = 16;
constexpr int64_t kFloorVersion = INT64_MIN / 4;
constexpr int kConflict = 0, kTooOld = 1, kCommitted = 2;

struct Node {
  uint64_t key;
  int64_t vers;  // version of [key, next->key)
  int h;
  Node* nxt[kMaxH];
  int64_t maxv[kMaxH];  // max vers over nodes in (this, nxt[l]]
};

class Pool {
 public:
  Node* alloc() {
    if (free_) {
      Node* n = free_;
      free_ = n->nxt[0];
      return n;
    }
    if (block_used_ == kBlock) {
      blocks_.push_back(new Node[kBlock]);
      block_used_ = 0;
    }
    return &blocks_.back()[block_used_++];
  }
  void release(Node* n) {
    n->nxt[0] = free_;
    free_ = n;
  }
  ~Pool() {
    for (Node* b : blocks_) delete[] b;
  }

 private:
  static constexpr size_t kBlock = 1 << 14;
  std::vector<Node*> blocks_;
  size_t block_used_ = kBlock;
  Node* free_ = nullptr;
};

class VersionedSkipList {
 public:
  VersionedSkipList() {
    head_ = pool_.alloc();
    head_->key = 0;
    head_->vers = kFloorVersion;
    head_->h = kMaxH;
    for (int l = 0; l < kMaxH; l++) {
      head_->nxt[l] = nullptr;
      head_->maxv[l] = kFloorVersion;
    }
    n_nodes_ = 1;
  }

  // Max version over step entries covering [b, e); requires b < e.
  int64_t RangeMax(uint64_t b, uint64_t e) const {
    const Node* x = head_;
    for (int l = kMaxH - 1; l >= 0; l--)
      while (x->nxt[l] && x->nxt[l]->key <= b) x = x->nxt[l];
    // x = last node with key <= b; accumulate span maxima over nodes < e.
    int64_t acc = x->vers;
    int l = x->h - 1;
    while (l >= 0) {
      const Node* nx = x->nxt[l];
      if (nx && nx->key < e) {
        if (x->maxv[l] > acc) acc = x->maxv[l];
        x = nx;
        l = x->h - 1;  // climb as high as the new node allows
      } else {
        l--;
      }
    }
    return acc;
  }

  // Set the step function to `v` on [b, e); b < e.
  void Overwrite(uint64_t b, uint64_t e, int64_t v) {
    Node* pred[kMaxH];
    Node* x = head_;
    for (int l = kMaxH - 1; l >= 0; l--) {
      while (x->nxt[l] && x->nxt[l]->key < b) x = x->nxt[l];
      pred[l] = x;
    }
    // Scan the doomed region [b, e) once at level 0, collecting nodes and
    // the version that resumes at e.
    Node* y = pred[0]->nxt[0];
    Node* doomed = nullptr;
    int n_doomed = 0;
    int64_t val_before_e = pred[0]->vers;
    while (y && y->key < e) {
      val_before_e = y->vers;
      Node* nx = y->nxt[0];
      y->nxt[0] = doomed;  // reuse nxt[0] as the doomed-chain link
      doomed = y;
      n_doomed++;
      y = nx;
    }
    bool node_at_e = y && y->key == e;

    // Unlink the doomed span at levels >= 1, then level 0 via pred.
    for (int l = 1; l < kMaxH; l++) {
      Node* z = pred[l]->nxt[l];
      while (z && z->key < e) z = z->nxt[l];
      pred[l]->nxt[l] = z;
    }
    pred[0]->nxt[0] = y;
    n_nodes_ -= n_doomed;
    while (doomed) {
      Node* nx = doomed->nxt[0];
      pool_.release(doomed);
      doomed = nx;
    }

    // Boundary at b, and an end boundary at e resuming the old value.
    InsertAfterPreds(pred, b, v);
    if (!node_at_e) {
      Node* pred2[kMaxH];
      for (int l = 0; l < kMaxH; l++) {
        Node* p = pred[l];
        while (p->nxt[l] && p->nxt[l]->key < e) p = p->nxt[l];
        pred2[l] = p;
      }
      InsertAfterPreds(pred2, e, val_before_e);
    }
    RecomputePath(pred, e);
  }

  // Evict boundaries wholly below `oldest`, sweeping at most `budget`
  // level-0 nodes from a cursor (ref: the amortized removal sweep in
  // setOldestVersion).  The head boundary is never removed.
  void EvictBelow(int64_t oldest, int budget) {
    Node* pred[kMaxH];
    Node* x = head_;
    for (int l = kMaxH - 1; l >= 0; l--) {
      while (x->nxt[l] && x->nxt[l]->key < sweep_key_) x = x->nxt[l];
      pred[l] = x;
    }
    Node* prev = pred[0];
    Node* cur = prev->nxt[0];
    while (cur && budget > 0) {
      budget--;
      if (cur->vers < oldest && prev->vers < oldest) {
        for (int l = 0; l < cur->h; l++) {
          // pred[l] is the last level-l node before cur; absorb cur's span
          // max (over-approx by a below-window amount; see header note).
          if (pred[l]->nxt[l] == cur) {
            pred[l]->nxt[l] = cur->nxt[l];
            if (cur->maxv[l] > pred[l]->maxv[l])
              pred[l]->maxv[l] = cur->maxv[l];
          }
        }
        Node* nx = cur->nxt[0];
        pool_.release(cur);
        n_nodes_--;
        cur = nx;
      } else {
        for (int l = 0; l < cur->h; l++) pred[l] = cur;
        prev = cur;
        cur = cur->nxt[0];
      }
    }
    sweep_key_ = cur ? cur->key : 0;  // wrap at the end
  }

  size_t node_count() const { return n_nodes_; }

 private:
  void InsertAfterPreds(Node* pred[kMaxH], uint64_t key, int64_t v) {
    int h = 1;
    uint64_t r = NextRand();
    while (h < kMaxH && (r & 3) == 3) {  // p = 1/4 per extra level
      r >>= 2;
      h++;
    }
    Node* n = pool_.alloc();
    n->key = key;
    n->vers = v;
    n->h = h;
    for (int l = 0; l < kMaxH; l++) {
      n->maxv[l] = kFloorVersion;
      n->nxt[l] = nullptr;
    }
    for (int l = 0; l < h; l++) {
      n->nxt[l] = pred[l]->nxt[l];
      pred[l]->nxt[l] = n;
    }
    n_nodes_++;
  }

  // Recompute span maxima, bottom-up, for every node on the predecessor
  // path plus the nodes inserted in (pred, last_key] — the only spans a
  // bounded overwrite can change.
  void RecomputePath(Node* pred[kMaxH], uint64_t last_key) {
    for (int l = 0; l < kMaxH; l++) {
      for (Node* x = pred[l]; x && x->key <= last_key; x = x->nxt[l]) {
        x->maxv[l] = Recompute(x, l);
        if (x == pred[l] && x->key > last_key) break;
      }
      // pred[l] itself always recomputed (the loop starts there; its key
      // is < b <= last_key except for head wraps, which still enter once).
    }
  }

  int64_t Recompute(const Node* x, int l) const {
    if (l == 0) return x->nxt[0] ? x->nxt[0]->vers : kFloorVersion;
    int64_t m = kFloorVersion;
    const Node* end = x->nxt[l];
    for (const Node* y = x; y != end; y = y->nxt[l - 1]) {
      if (y->maxv[l - 1] > m) m = y->maxv[l - 1];
      if (!y->nxt[l - 1]) break;
    }
    return m;
  }

  uint64_t NextRand() {
    rng_ ^= rng_ << 13;
    rng_ ^= rng_ >> 7;
    rng_ ^= rng_ << 17;
    return rng_;
  }

  Pool pool_;
  Node* head_;
  uint64_t rng_ = 0x9e3779b97f4a7c15ull;
  uint64_t sweep_key_ = 0;
  size_t n_nodes_;
};

// Merged half-open interval set: the intra-batch committed-write
// accumulator (engine_cpu._IntervalSet semantics).
class IntervalSet {
 public:
  void clear() { m_.clear(); }
  bool Intersects(uint64_t b, uint64_t e) const {
    if (b >= e) return false;
    auto it = m_.upper_bound(b);
    if (it != m_.begin()) {
      auto p = std::prev(it);
      if (p->second > b) return true;
    }
    return it != m_.end() && it->first < e;
  }
  void Add(uint64_t b, uint64_t e) {
    if (b >= e) return;
    auto it = m_.upper_bound(b);
    if (it != m_.begin()) {
      auto p = std::prev(it);
      if (p->second >= b) {
        b = p->first;
        if (p->second > e) e = p->second;
        it = m_.erase(p);
      }
    }
    while (it != m_.end() && it->first <= e) {
      if (it->second > e) e = it->second;
      it = m_.erase(it);
    }
    m_.emplace(b, e);
  }
  template <typename F>
  void ForEach(F f) const {
    for (auto& kv : m_) f(kv.first, kv.second);
  }

 private:
  std::map<uint64_t, uint64_t> m_;
};

struct Txn {
  int64_t snap;
  std::vector<std::pair<uint64_t, uint64_t>> reads, writes;
};

class ConflictSet {
 public:
  std::vector<int> Detect(const std::vector<Txn>& txns, int64_t now,
                          int64_t new_oldest) {
    std::vector<int> st(txns.size(), kCommitted);
    // Phase 1: too-old + history (ref checkReadConflictRanges).
    for (size_t t = 0; t < txns.size(); t++) {
      const Txn& tr = txns[t];
      if (tr.snap < oldest_ && !tr.reads.empty()) {
        st[t] = kTooOld;
        continue;
      }
      for (auto& r : tr.reads) {
        if (r.first < r.second &&
            list_.RangeMax(r.first, r.second) > tr.snap) {
          st[t] = kConflict;
          break;
        }
      }
    }
    // Phase 2: intra-batch, in order (ref checkIntraBatchConflicts).
    active_.clear();
    for (size_t t = 0; t < txns.size(); t++) {
      if (st[t] != kCommitted) continue;
      bool hit = false;
      for (auto& r : txns[t].reads)
        if (active_.Intersects(r.first, r.second)) {
          hit = true;
          break;
        }
      if (hit) {
        st[t] = kConflict;
        continue;
      }
      for (auto& w : txns[t].writes) active_.Add(w.first, w.second);
    }
    // Phase 3: merge committed writes at `now` (ref mergeWriteConflictRanges).
    active_.ForEach(
        [&](uint64_t b, uint64_t e) { list_.Overwrite(b, e, now); });
    // Phase 4: window eviction (amortized cursor sweep, ref removeBefore).
    if (new_oldest > oldest_) {
      oldest_ = new_oldest;
      list_.EvictBelow(oldest_, 40000);
    }
    return st;
  }

  size_t node_count() const { return list_.node_count(); }

 private:
  VersionedSkipList list_;
  IntervalSet active_;
  int64_t oldest_ = 0;
};

uint64_t g_rand = 88172645463325252ull;
uint64_t Rand() {
  g_rand ^= g_rand << 13;
  g_rand ^= g_rand >> 7;
  g_rand ^= g_rand << 17;
  return g_rand;
}

int RunBench(int n_batches, int per_batch, int window) {
  constexpr uint64_t kKeyspace = 20000000;
  ConflictSet cs;
  // Pre-generate all batches (generation excluded from the timed region,
  // as in bench.py's gen_packed pre-pass).
  std::vector<std::vector<Txn>> batches(n_batches);
  for (int i = 0; i < n_batches; i++) {
    batches[i].resize(per_batch);
    for (int t = 0; t < per_batch; t++) {
      Txn& tr = batches[i][t];
      tr.snap = i;
      uint64_t rb = Rand() % kKeyspace;
      tr.reads.push_back({rb, rb + 1 + Rand() % 10});
      uint64_t wb = Rand() % kKeyspace;
      tr.writes.push_back({wb, wb + 1 + Rand() % 10});
    }
  }
  int64_t n_committed = 0;
  auto t0 = std::chrono::steady_clock::now();
  for (int i = 0; i < n_batches; i++) {
    auto st = cs.Detect(batches[i], i + window, i);
    for (int s : st) n_committed += (s == kCommitted);
  }
  auto t1 = std::chrono::steady_clock::now();
  double dt = std::chrono::duration<double>(t1 - t0).count();
  double rate = (double)n_batches * per_batch / dt;
  printf(
      "{\"metric\": \"cpp_skiplist_txns_per_sec\", \"value\": %.1f, "
      "\"unit\": \"txn/s\", \"batches\": %d, \"per_batch\": %d, "
      "\"window\": %d, \"committed\": %lld, \"boundaries\": %zu, "
      "\"seconds\": %.3f}\n",
      rate, n_batches, per_batch, window, (long long)n_committed,
      cs.node_count(), dt);
  return 0;
}

int RunSelftest() {
  ConflictSet cs;
  char tag[8];
  long long now, old_;
  int ntxn;
  while (scanf("%7s %lld %lld %d", tag, &now, &old_, &ntxn) == 4) {
    std::vector<Txn> txns(ntxn);
    for (int t = 0; t < ntxn; t++) {
      long long snap;
      int nr, nw;
      if (scanf("%lld %d %d", &snap, &nr, &nw) != 3) return 1;
      txns[t].snap = snap;
      for (int k = 0; k < nr + nw; k++) {
        char rw[4];
        unsigned long long b, e;
        if (scanf("%3s %llu %llu", rw, &b, &e) != 3) return 1;
        if (rw[0] == 'r')
          txns[t].reads.push_back({b, e});
        else
          txns[t].writes.push_back({b, e});
      }
    }
    auto st = cs.Detect(txns, now, old_);
    for (size_t i = 0; i < st.size(); i++)
      printf("%d%c", st[i], i + 1 == st.size() ? '\n' : ' ');
    if (st.empty()) printf("\n");
    fflush(stdout);
  }
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  int n_batches = 500, per_batch = 2500, window = 50;
  bool selftest = false;
  for (int i = 1; i < argc; i++) {
    if (!strcmp(argv[i], "--selftest")) {
      selftest = true;
    } else if (!strcmp(argv[i], "--batches") && i + 1 < argc) {
      n_batches = atoi(argv[++i]);
    } else if (!strcmp(argv[i], "--per-batch") && i + 1 < argc) {
      per_batch = atoi(argv[++i]);
    } else if (!strcmp(argv[i], "--window") && i + 1 < argc) {
      window = atoi(argv[++i]);
    }
  }
  if (selftest) return RunSelftest();
  return RunBench(n_batches, per_batch, window);
}
