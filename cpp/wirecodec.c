/* C accelerator for the versioned tagged wire codec (rpc/wire.py).
 *
 * Byte-identical to the Python reference implementation: same tags,
 * varint/zigzag forms, depth cap, bounds checks, and error taxonomy
 * (WireEncodeError / WireDecodeError, supplied by Python at configure()).
 * Values outside the C fast path's range (ints beyond 64 bits) raise the
 * supplied Fallback exception; the Python wrapper retries the whole frame
 * with the pure-Python codec, so behavior is unchanged — only speed.
 *
 * The registry (struct/enum vocabularies) is handed over as dicts at
 * configure(); decode constructs data only — struct instantiation is a
 * positional dataclass call, enum construction a class call, exactly as
 * the Python decoder does.
 *
 * Built on demand into cpp/_fdb_wirecodec.so (see rpc/wire_native.py);
 * CPython limited-to-this-interpreter API (not abi3) for speed.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

enum {
    T_NONE = 0, T_TRUE = 1, T_FALSE = 2, T_INT = 3, T_FLOAT = 4,
    T_BYTES = 5, T_STR = 6, T_LIST = 7, T_TUPLE = 8, T_DICT = 9,
    T_STRUCT = 10, T_ENUM = 11,
};
#define WIRE_VERSION 1
#define MAX_DEPTH 64
#define MAX_VARINT_BYTES 16

/* configure()-supplied state */
static PyObject *g_struct_by_id;   /* cid(int) -> (cls, (names...), min_req) */
static PyObject *g_enum_by_id;     /* cid(int) -> cls */
static PyObject *g_struct_ids;     /* cls -> (cid, (names...)) */
static PyObject *g_enum_ids;       /* cls -> cid */
static PyObject *g_enc_err;        /* WireEncodeError */
static PyObject *g_dec_err;        /* WireDecodeError */
static PyObject *g_fallback;       /* _CFallback */
static PyObject *g_intenum;        /* enum.IntEnum */
static PyObject *g_is_dataclass;   /* dataclasses.is_dataclass */

/* ---------------- growable output buffer ---------------- */

typedef struct {
    char *data;
    Py_ssize_t len, cap;
} Buf;

static int buf_init(Buf *b, Py_ssize_t cap) {
    b->data = PyMem_Malloc(cap);
    if (!b->data) { PyErr_NoMemory(); return -1; }
    b->len = 0; b->cap = cap;
    return 0;
}

static int buf_reserve(Buf *b, Py_ssize_t extra) {
    if (b->len + extra <= b->cap) return 0;
    Py_ssize_t ncap = b->cap * 2;
    while (ncap < b->len + extra) ncap *= 2;
    char *nd = PyMem_Realloc(b->data, ncap);
    if (!nd) { PyErr_NoMemory(); return -1; }
    b->data = nd; b->cap = ncap;
    return 0;
}

static inline int buf_byte(Buf *b, unsigned char c) {
    if (buf_reserve(b, 1) < 0) return -1;
    b->data[b->len++] = (char)c;
    return 0;
}

static inline int buf_write(Buf *b, const char *p, Py_ssize_t n) {
    if (buf_reserve(b, n) < 0) return -1;
    memcpy(b->data + b->len, p, n);
    b->len += n;
    return 0;
}

static int buf_varint(Buf *b, uint64_t n) {
    do {
        unsigned char c = n & 0x7F;
        n >>= 7;
        if (n) c |= 0x80;
        if (buf_byte(b, c) < 0) return -1;
    } while (n);
    return 0;
}

static inline uint64_t zigzag64(int64_t n) {
    return ((uint64_t)n << 1) ^ (uint64_t)(n >> 63);
}

static inline int64_t unzigzag64(uint64_t z) {
    return (int64_t)(z >> 1) ^ -(int64_t)(z & 1);
}

/* ---------------- encode ---------------- */

static int enc_value(Buf *b, PyObject *v, int depth);

static int enc_buffer_like(Buf *b, PyObject *v) {
    Py_buffer view;
    if (PyObject_GetBuffer(v, &view, PyBUF_SIMPLE) < 0) {
        /* e.g. a non-contiguous memoryview: the Python reference copies
         * it via bytes(v); stay behavior-identical through fallback. */
        PyErr_Clear();
        PyErr_SetString(g_fallback, "non-simple buffer");
        return -1;
    }
    int rc = -1;
    if (buf_byte(b, T_BYTES) == 0 &&
        buf_varint(b, (uint64_t)view.len) == 0 &&
        buf_write(b, view.buf, view.len) == 0)
        rc = 0;
    PyBuffer_Release(&view);
    return rc;
}

static int enc_long(Buf *b, PyObject *v) {
    int overflow = 0;
    long long n = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (overflow) {
        PyErr_SetString(g_fallback, "int beyond 64 bits");
        return -1;
    }
    if (n == -1 && PyErr_Occurred()) return -1;
    if (buf_byte(b, T_INT) < 0) return -1;
    return buf_varint(b, zigzag64((int64_t)n));
}

static int enc_value(Buf *b, PyObject *v, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(g_enc_err, "nesting too deep");
        return -1;
    }
    if (v == Py_None) return buf_byte(b, T_NONE);
    if (v == Py_True) return buf_byte(b, T_TRUE);
    if (v == Py_False) return buf_byte(b, T_FALSE);

    PyTypeObject *tp = Py_TYPE(v);
    if (PyLong_Check(v)) {
        if (!PyLong_CheckExact(v)) {
            /* Registered IntEnum member, or an unregistered one (error) */
            PyObject *cid = PyDict_GetItem(g_enum_ids, (PyObject *)tp);
            if (cid != NULL) {
                long c = PyLong_AsLong(cid);
                int overflow = 0;
                long long n = PyLong_AsLongLongAndOverflow(v, &overflow);
                if (overflow || (n == -1 && PyErr_Occurred())) {
                    PyErr_SetString(g_fallback, "enum beyond 64 bits");
                    return -1;
                }
                unsigned char hdr[3] = {
                    T_ENUM, (unsigned char)((c >> 8) & 0xFF),
                    (unsigned char)(c & 0xFF),
                };
                if (buf_write(b, (char *)hdr, 3) < 0) return -1;
                return buf_varint(b, zigzag64((int64_t)n));
            }
            int is_enum = PyObject_IsInstance(v, g_intenum);
            if (is_enum < 0) return -1;
            if (is_enum) {
                PyErr_Format(g_enc_err, "unregistered enum %s",
                             tp->tp_name);
                return -1;
            }
            /* plain int subclass (incl. bool handled above) */
        }
        return enc_long(b, v);
    }
    if (PyFloat_Check(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        uint64_t u;
        memcpy(&u, &d, 8);
        unsigned char be[9];
        be[0] = T_FLOAT;
        for (int i = 0; i < 8; i++)
            be[1 + i] = (unsigned char)((u >> (8 * (7 - i))) & 0xFF);
        return buf_write(b, (char *)be, 9);
    }
    if (PyBytes_Check(v)) {
        if (buf_byte(b, T_BYTES) < 0) return -1;
        Py_ssize_t n = PyBytes_GET_SIZE(v);
        if (buf_varint(b, (uint64_t)n) < 0) return -1;
        return buf_write(b, PyBytes_AS_STRING(v), n);
    }
    if (PyByteArray_Check(v) || PyMemoryView_Check(v))
        return enc_buffer_like(b, v);
    if (PyUnicode_Check(v)) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(v, &n);
        if (!s) return -1;
        if (buf_byte(b, T_STR) < 0) return -1;
        if (buf_varint(b, (uint64_t)n) < 0) return -1;
        return buf_write(b, s, n);
    }
    if (PyList_Check(v)) {
        Py_ssize_t n = PyList_GET_SIZE(v);
        if (buf_byte(b, T_LIST) < 0 || buf_varint(b, (uint64_t)n) < 0)
            return -1;
        for (Py_ssize_t i = 0; i < n; i++)
            if (enc_value(b, PyList_GET_ITEM(v, i), depth + 1) < 0)
                return -1;
        return 0;
    }
    if (PyTuple_Check(v)) {
        Py_ssize_t n = PyTuple_GET_SIZE(v);
        if (buf_byte(b, T_TUPLE) < 0 || buf_varint(b, (uint64_t)n) < 0)
            return -1;
        for (Py_ssize_t i = 0; i < n; i++)
            if (enc_value(b, PyTuple_GET_ITEM(v, i), depth + 1) < 0)
                return -1;
        return 0;
    }
    if (PyDict_Check(v)) {
        if (buf_byte(b, T_DICT) < 0 ||
            buf_varint(b, (uint64_t)PyDict_GET_SIZE(v)) < 0)
            return -1;
        PyObject *k, *val;
        Py_ssize_t pos = 0;
        while (PyDict_Next(v, &pos, &k, &val)) {
            if (enc_value(b, k, depth + 1) < 0) return -1;
            if (enc_value(b, val, depth + 1) < 0) return -1;
        }
        return 0;
    }
    /* registered struct? */
    {
        PyObject *entry = PyDict_GetItem(g_struct_ids, (PyObject *)tp);
        if (entry != NULL) {
            long cid = PyLong_AsLong(PyTuple_GET_ITEM(entry, 0));
            PyObject *names = PyTuple_GET_ITEM(entry, 1);
            Py_ssize_t n = PyTuple_GET_SIZE(names);
            unsigned char hdr[3] = {
                T_STRUCT, (unsigned char)((cid >> 8) & 0xFF),
                (unsigned char)(cid & 0xFF),
            };
            if (buf_write(b, (char *)hdr, 3) < 0) return -1;
            if (buf_varint(b, (uint64_t)n) < 0) return -1;
            for (Py_ssize_t i = 0; i < n; i++) {
                PyObject *fv =
                    PyObject_GetAttr(v, PyTuple_GET_ITEM(names, i));
                if (!fv) return -1;
                int rc = enc_value(b, fv, depth + 1);
                Py_DECREF(fv);
                if (rc < 0) return -1;
            }
            return 0;
        }
    }
    {
        PyObject *isdc =
            PyObject_CallFunctionObjArgs(g_is_dataclass, v, NULL);
        if (!isdc) return -1;
        int truthy = PyObject_IsTrue(isdc);
        Py_DECREF(isdc);
        if (truthy < 0) return -1;
        if (truthy) {
            PyErr_Format(g_enc_err, "unregistered struct %s", tp->tp_name);
            return -1;
        }
    }
    PyErr_Format(g_enc_err, "type %s is not in the wire vocabulary",
                 tp->tp_name);
    return -1;
}

static PyObject *py_encode(PyObject *self, PyObject *arg) {
    Buf b;
    if (buf_init(&b, 256) < 0) return NULL;
    if (buf_byte(&b, WIRE_VERSION) < 0 || enc_value(&b, arg, 0) < 0) {
        PyMem_Free(b.data);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize(b.data, b.len);
    PyMem_Free(b.data);
    return out;
}

/* ---------------- decode ---------------- */

typedef struct {
    const unsigned char *buf;
    Py_ssize_t pos, end;
} Rd;

static int rd_byte(Rd *r, unsigned char *out) {
    if (r->pos >= r->end) {
        PyErr_SetString(g_dec_err, "truncated frame");
        return -1;
    }
    *out = r->buf[r->pos++];
    return 0;
}

static int rd_take(Rd *r, Py_ssize_t n, const unsigned char **out) {
    if (n < 0 || r->end - r->pos < n) {
        PyErr_SetString(g_dec_err, "truncated frame");
        return -1;
    }
    *out = r->buf + r->pos;
    r->pos += n;
    return 0;
}

/* Python accepts varints up to 112 bits (arbitrary-precision result);
 * the C fast path covers 64 bits and signals fallback beyond. */
static int rd_varint(Rd *r, uint64_t *out) {
    uint64_t n = 0;
    int shift = 0;
    for (int i = 0; i < MAX_VARINT_BYTES; i++) {
        unsigned char c;
        if (rd_byte(r, &c) < 0) return -1;
        if (shift >= 64 && (c & 0x7F)) {
            PyErr_SetString(g_fallback, "varint beyond 64 bits");
            return -1;
        }
        if (shift < 64) {
            if (shift > 0 && (c & 0x7F) &&
                ((uint64_t)(c & 0x7F) << shift) >> shift !=
                    (uint64_t)(c & 0x7F)) {
                PyErr_SetString(g_fallback, "varint beyond 64 bits");
                return -1;
            }
            n |= (uint64_t)(c & 0x7F) << shift;
        }
        if (!(c & 0x80)) {
            *out = n;
            return 0;
        }
        shift += 7;
    }
    PyErr_SetString(g_dec_err, "varint too long");
    return -1;
}

static PyObject *dec_value(Rd *r, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(g_dec_err, "nesting too deep");
        return NULL;
    }
    unsigned char tag;
    if (rd_byte(r, &tag) < 0) return NULL;
    switch (tag) {
    case T_NONE: Py_RETURN_NONE;
    case T_TRUE: Py_RETURN_TRUE;
    case T_FALSE: Py_RETURN_FALSE;
    case T_INT: {
        uint64_t z;
        if (rd_varint(r, &z) < 0) return NULL;
        return PyLong_FromLongLong(unzigzag64(z));
    }
    case T_FLOAT: {
        const unsigned char *p;
        if (rd_take(r, 8, &p) < 0) return NULL;
        uint64_t u = 0;
        for (int i = 0; i < 8; i++) u = (u << 8) | p[i];
        double d;
        memcpy(&d, &u, 8);
        return PyFloat_FromDouble(d);
    }
    case T_BYTES: {
        uint64_t n;
        const unsigned char *p;
        if (rd_varint(r, &n) < 0) return NULL;
        if (n > (uint64_t)PY_SSIZE_T_MAX ||
            rd_take(r, (Py_ssize_t)n, &p) < 0)
            return n > (uint64_t)PY_SSIZE_T_MAX
                       ? (PyErr_SetString(g_dec_err, "truncated frame"),
                          NULL)
                       : NULL;
        return PyBytes_FromStringAndSize((const char *)p, (Py_ssize_t)n);
    }
    case T_STR: {
        uint64_t n;
        const unsigned char *p;
        if (rd_varint(r, &n) < 0) return NULL;
        if (n > (uint64_t)PY_SSIZE_T_MAX) {
            PyErr_SetString(g_dec_err, "truncated frame");
            return NULL;
        }
        if (rd_take(r, (Py_ssize_t)n, &p) < 0) return NULL;
        PyObject *s =
            PyUnicode_DecodeUTF8((const char *)p, (Py_ssize_t)n, NULL);
        if (!s) {
            PyErr_Clear();
            PyErr_SetString(g_dec_err, "bad utf-8");
            return NULL;
        }
        return s;
    }
    case T_LIST:
    case T_TUPLE: {
        uint64_t n;
        if (rd_varint(r, &n) < 0) return NULL;
        if (n > (uint64_t)(r->end - r->pos)) {
            PyErr_SetString(g_dec_err, "length exceeds frame");
            return NULL;
        }
        PyObject *out = (tag == T_LIST) ? PyList_New((Py_ssize_t)n)
                                        : PyTuple_New((Py_ssize_t)n);
        if (!out) return NULL;
        for (Py_ssize_t i = 0; i < (Py_ssize_t)n; i++) {
            PyObject *item = dec_value(r, depth + 1);
            if (!item) { Py_DECREF(out); return NULL; }
            if (tag == T_LIST) PyList_SET_ITEM(out, i, item);
            else PyTuple_SET_ITEM(out, i, item);
        }
        return out;
    }
    case T_DICT: {
        uint64_t n;
        if (rd_varint(r, &n) < 0) return NULL;
        if (n > (uint64_t)(r->end - r->pos) / 2 + 1 &&
            n * 2 > (uint64_t)(r->end - r->pos)) {
            PyErr_SetString(g_dec_err, "length exceeds frame");
            return NULL;
        }
        PyObject *out = PyDict_New();
        if (!out) return NULL;
        for (uint64_t i = 0; i < n; i++) {
            PyObject *k = dec_value(r, depth + 1);
            if (!k) { Py_DECREF(out); return NULL; }
            PyObject *val = dec_value(r, depth + 1);
            if (!val) { Py_DECREF(k); Py_DECREF(out); return NULL; }
            int rc = PyDict_SetItem(out, k, val);
            Py_DECREF(k);
            Py_DECREF(val);
            if (rc < 0) {
                if (PyErr_ExceptionMatches(PyExc_TypeError)) {
                    PyErr_Clear();
                    PyErr_SetString(g_dec_err, "bad dict key");
                }
                Py_DECREF(out);
                return NULL;
            }
        }
        return out;
    }
    case T_ENUM: {
        const unsigned char *p;
        uint64_t z;
        if (rd_take(r, 2, &p) < 0) return NULL;
        long cid = ((long)p[0] << 8) | p[1];
        if (rd_varint(r, &z) < 0) return NULL;
        PyObject *key = PyLong_FromLong(cid);
        PyObject *cls = PyDict_GetItem(g_enum_by_id, key); /* borrowed */
        Py_DECREF(key);
        if (cls == NULL) {
            PyErr_Format(g_dec_err, "unknown enum id 0x%x", (unsigned int)cid);
            return NULL;
        }
        PyObject *out =
            PyObject_CallFunction(cls, "L", (long long)unzigzag64(z));
        if (!out) {
            if (PyErr_ExceptionMatches(PyExc_ValueError)) {
                PyErr_Clear();
                PyErr_SetString(g_dec_err, "invalid enum value");
            }
            return NULL;
        }
        return out;
    }
    case T_STRUCT: {
        const unsigned char *p;
        uint64_t n;
        if (rd_take(r, 2, &p) < 0) return NULL;
        long cid = ((long)p[0] << 8) | p[1];
        PyObject *key = PyLong_FromLong(cid);
        PyObject *entry = PyDict_GetItem(g_struct_by_id, key);
        Py_DECREF(key);
        if (entry == NULL) {
            PyErr_Format(g_dec_err, "unknown struct id 0x%x", (unsigned int)cid);
            return NULL;
        }
        PyObject *cls = PyTuple_GET_ITEM(entry, 0);
        PyObject *names = PyTuple_GET_ITEM(entry, 1);
        long min_req = PyLong_AsLong(PyTuple_GET_ITEM(entry, 2));
        Py_ssize_t known = PyTuple_GET_SIZE(names);
        if (rd_varint(r, &n) < 0) return NULL;
        if ((Py_ssize_t)n > known) {
            PyErr_Format(g_dec_err,
                         "%s: peer sent %zd fields, we know %zd",
                         ((PyTypeObject *)cls)->tp_name, (Py_ssize_t)n,
                         known);
            return NULL;
        }
        if ((long)n < min_req) {
            PyErr_Format(g_dec_err, "%s: missing field with no default",
                         ((PyTypeObject *)cls)->tp_name);
            return NULL;
        }
        PyObject *args = PyTuple_New((Py_ssize_t)n);
        if (!args) return NULL;
        for (Py_ssize_t i = 0; i < (Py_ssize_t)n; i++) {
            PyObject *fv = dec_value(r, depth + 1);
            if (!fv) { Py_DECREF(args); return NULL; }
            PyTuple_SET_ITEM(args, i, fv);
        }
        PyObject *out = PyObject_CallObject(cls, args);
        Py_DECREF(args);
        if (!out) {
            if (PyErr_ExceptionMatches(PyExc_TypeError) ||
                PyErr_ExceptionMatches(PyExc_ValueError)) {
                PyErr_Clear();
                PyErr_Format(g_dec_err, "%s: construction failed",
                             ((PyTypeObject *)cls)->tp_name);
            }
            return NULL;
        }
        return out;
    }
    default:
        PyErr_Format(g_dec_err, "unknown tag %d", (int)tag);
        return NULL;
    }
}

static PyObject *py_decode(PyObject *self, PyObject *arg) {
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    Rd r = {(const unsigned char *)view.buf, 0, view.len};
    unsigned char ver;
    PyObject *out = NULL;
    if (rd_byte(&r, &ver) < 0) goto done;
    if (ver != WIRE_VERSION) {
        PyErr_Format(g_dec_err, "wire version %d != %d", (int)ver,
                     WIRE_VERSION);
        goto done;
    }
    out = dec_value(&r, 0);
    if (out && r.pos != r.end) {
        Py_CLEAR(out);
        PyErr_Format(g_dec_err, "%zd trailing bytes", r.end - r.pos);
    }
done:
    PyBuffer_Release(&view);
    return out;
}

/* ---------------- configure ---------------- */

static PyObject *py_configure(PyObject *self, PyObject *args) {
    PyObject *sbi, *ebi, *sid, *eid, *ee, *de, *fb, *ie, *isdc;
    if (!PyArg_ParseTuple(args, "OOOOOOOOO", &sbi, &ebi, &sid, &eid, &ee,
                          &de, &fb, &ie, &isdc))
        return NULL;
    Py_XDECREF(g_struct_by_id); Py_INCREF(sbi); g_struct_by_id = sbi;
    Py_XDECREF(g_enum_by_id); Py_INCREF(ebi); g_enum_by_id = ebi;
    Py_XDECREF(g_struct_ids); Py_INCREF(sid); g_struct_ids = sid;
    Py_XDECREF(g_enum_ids); Py_INCREF(eid); g_enum_ids = eid;
    Py_XDECREF(g_enc_err); Py_INCREF(ee); g_enc_err = ee;
    Py_XDECREF(g_dec_err); Py_INCREF(de); g_dec_err = de;
    Py_XDECREF(g_fallback); Py_INCREF(fb); g_fallback = fb;
    Py_XDECREF(g_intenum); Py_INCREF(ie); g_intenum = ie;
    Py_XDECREF(g_is_dataclass); Py_INCREF(isdc); g_is_dataclass = isdc;
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"configure", py_configure, METH_VARARGS,
     "configure(struct_by_id, enum_by_id, struct_ids, enum_ids, "
     "WireEncodeError, WireDecodeError, Fallback, IntEnum, is_dataclass)"},
    {"encode", py_encode, METH_O, "encode(value) -> frame bytes"},
    {"decode", py_decode, METH_O, "decode(frame bytes) -> value"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_fdb_wirecodec",
    "C fast path for the fdb-tpu wire codec", -1, methods,
};

PyMODINIT_FUNC PyInit__fdb_wirecodec(void) {
    return PyModule_Create(&module);
}
