"""Resolver conflict-detection benchmark (the north-star metric).

Mirrors the reference's skipListTest microbench (fdbserver/SkipList.cpp:
1412-1502): batches of transactions with 1 read + 1 write conflict range
each, int keys uniform in [0, 2e7), range width 1 + U[0,10), read_snapshot =
batch index, detect at now = i+50 with window new_oldest = i.

Measured:
  - CPU baseline: CpuConflictSet (the host fallback engine) at the
    reference's 2500-txn batches.  (The reference's own C++ SkipList number
    must be produced by `fdbserver -r skiplisttest`; until a native baseline
    lands in-repo, the host engine is the stand-in baseline.)
  - Device: JaxConflictSet at 64k-txn batches (the BASELINE.json target
    configuration), including host packing + transfer + device->host
    verdict readback.

Prints ONE JSON line: value = device txns/sec at 64k batches,
vs_baseline = device / CPU-baseline throughput ratio.
"""

import json
import os
import sys
import time

import numpy as np

CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")


def _log(msg):
    print(f"[bench +{time.perf_counter() - _T0:.1f}s] {msg}", file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def setup_jax(tries=None, backoff=20):
    if tries is None:
        # Device init runs inside a killable subprocess now (the parent
        # enforces a hard wall-clock timeout), so one in-process try is
        # enough; override with BENCH_INIT_TRIES.
        tries = int(os.environ.get("BENCH_INIT_TRIES", "1"))
    """Import jax, enable the persistent compilation cache, and initialize
    the device backend with retries (the axon TPU tunnel on this host is
    slow to come up and has failed transiently before — BENCH_r01).

    Returns the platform name of the default device.  Raises on final
    failure; callers must still emit the JSON line.
    """
    os.makedirs(CACHE_DIR, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # knob name varies across jax versions; cache still works
    last = None
    for i in range(tries):
        try:
            devs = jax.devices()
            _log(f"jax backend up: {[str(d) for d in devs]}")
            return devs[0].platform
        except Exception as e:  # backend init failure (e.g. axon UNAVAILABLE)
            last = e
            _log(f"backend init failed (try {i + 1}/{tries}): {e}")
            try:
                jax.extend.backend.clear_backends()
            except Exception:
                pass
            if i + 1 < tries:
                time.sleep(backoff * (i + 1))
    raise last


def warm_compile_probe():
    """Compile+run a small-shape program first: proves the device works in
    seconds, before committing to the multi-minute 64k/h_cap=1M compile."""
    from foundationdb_tpu.conflict.engine_jax import JaxConflictSet

    rng = np.random.default_rng(7)
    cs = JaxConflictSet(key_words=KEY_WORDS, h_cap=1 << 12)
    pb = gen_packed(rng, 1024, 0, KEY_WORDS)
    t0 = time.perf_counter()
    cs.detect_packed(pb, now=4, new_oldest_version=0)
    _log(f"warm probe (1k txns) compiled+ran in {time.perf_counter() - t0:.1f}s")

KEYSPACE = 20_000_000
KEY_BYTES = 4  # 20M keys fit in 4 big-endian bytes, like the ref's setK ints
KEY_WORDS = 2
WINDOW = 50  # detect at now=i+50, evict below i => 50-batch live window


def _base_h_cap() -> int:
    """Device-bench history capacity: the FDB_TPU_H_CAP g_env knob, else
    the dropped default (ISSUE 14 satellite / PERF_NOTES lever 2) —
    3145728 = 2.87M live boundaries at window 50 + ~10% headroom (was
    3407872 / +19%; every H-proportional pass scales with it, and the
    engine's must-fit guard grows rather than truncates if a workload
    outruns it — tests/test_kernels.py pins the guard).  Knob values
    arrive rounded up to a 256-row multiple (api.env_h_cap) so the
    Pallas kernels keep their full tile."""
    from foundationdb_tpu.conflict.api import env_h_cap

    env = env_h_cap()
    return env if env > 0 else 3145728


BASE_H_CAP = _base_h_cap()


def gen_packed(rng, n_txn, batch_index, key_words):
    """Vectorized PackedBatch generation (1 read + 1 write range per txn)."""
    from foundationdb_tpu.conflict.engine_jax import PackedBatch, _next_pow2
    from foundationdb_tpu.conflict import keys as keylib

    cap = _next_pow2(n_txn, 8)
    pb = PackedBatch(cap, cap, cap, key_words)
    for begin, end, txn in (
        (pb.r_begin, pb.r_end, pb.r_txn),
        (pb.w_begin, pb.w_end, pb.w_txn),
    ):
        a = rng.integers(0, KEYSPACE, n_txn, dtype=np.int64)
        b = a + 1 + rng.integers(0, 10, n_txn, dtype=np.int64)
        begin[:n_txn] = keylib.encode_int_keys(a, key_words, KEY_BYTES)
        end[:n_txn] = keylib.encode_int_keys(b, key_words, KEY_BYTES)
        txn[:n_txn] = np.arange(n_txn, dtype=np.int32)
    pb.r_snap[:n_txn] = batch_index
    pb.t_snap[:n_txn] = batch_index
    pb.t_has_reads[:n_txn] = True
    pb.t_valid[:n_txn] = True
    pb.n_txn = pb.n_r = pb.n_w = n_txn
    return pb


def txns_from_packed(pb, n_txn):
    """Unpack to TransactionConflictInfo list for the CPU engine."""
    from foundationdb_tpu.conflict.engine_jax import _unpack_transactions

    assert pb.n_txn == n_txn
    return _unpack_transactions(pb)


def bench_cpp(rng=None):
    """The honest vs_baseline denominator: the native C++ skiplist at the
    reference's own skipListTest config (500 x 2500; SkipList.cpp:1412),
    built from cpp/skiplist_baseline.cpp on demand (differentially tested
    against engine_cpu in tests/test_cpp_baseline.py)."""
    import json
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(repo, "cpp", "skiplist_baseline.cpp")
    binp = os.path.join(repo, "cpp", "skiplist_baseline")
    if not os.path.exists(binp) or os.path.getmtime(binp) < os.path.getmtime(src):
        subprocess.run(["g++", "-O3", "-o", binp, src], check=True)
    out = subprocess.run(
        [binp], capture_output=True, text=True, check=True, timeout=300
    ).stdout
    return json.loads(out)["value"]


def bench_cpu(rng, n_batches=20, per_batch=2500):
    from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet

    cs = CpuConflictSet()
    batches = [
        txns_from_packed(gen_packed(rng, per_batch, i, KEY_WORDS), per_batch)
        for i in range(n_batches)
    ]
    t0 = time.perf_counter()
    for i, txns in enumerate(batches):
        cs.detect(txns, now=i + WINDOW, new_oldest_version=i)
    dt = time.perf_counter() - t0
    return n_batches * per_batch / dt


# Mirror A/B arms (ISSUE 9) — the CPU-side companion to VARIANTS below:
# the always-on mirror's maintenance cost (amortized apply_batch) and the
# breaker-probe rehydration host cost, flat array vs batched-snapshot
# engine.  FDB_TPU_MIRROR_ENGINE is the production selector; bench_mirror
# runs both arms in-process (no device needed, so this phase always
# produces numbers even when the tunnel is down).  Shared by bench.main
# and `tools/perf_experiments.py --mirror`.
MIRROR_VARIANTS = [
    # engine_cpu.CpuConflictSet (the default) — columnar chunks since
    # ISSUE 19 (searchsorted sweeps over encoded-key columns).
    ("mirror_columnar", {}),
    ("mirror_flat", {"FDB_TPU_MIRROR_ENGINE": "flat"}),
]


def bench_mirror(rng, n_batches=30, per_batch=2500, degraded_batches=4):
    """Flat vs chunked mirror A/B at the skipListTest stream shape:

      apply_txns_per_sec      mirror maintenance — adopting device-decided
                              batches (apply_batch), the always-on cost
      detect_txns_per_sec     degraded-mode serving — what the ratekeeper's
                              measured-cpu-tps clamp sees (cpu_mirror_tps
                              honesty for ratekeeper_use_measured_cpu_tps)
      rehydrate_host_s        the probe's host-side key-encode cost after a
                              `degraded_batches`-batch mirror-only window
                              (chunked: only chunks changed since the last
                              device sync re-encode; flat: the full O(H)
                              legacy path)
    """
    from foundationdb_tpu.conflict import keys as keylib
    from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
    from foundationdb_tpu.conflict.engine_cpu_flat import FlatCpuConflictSet

    from foundationdb_tpu.conflict.types import TransactionConflictInfo

    batches = [
        txns_from_packed(gen_packed(rng, per_batch, i, KEY_WORDS), per_batch)
        for i in range(n_batches)
    ]
    # Verdicts decided ONCE so both arms adopt identical inputs.
    dec = FlatCpuConflictSet()
    decided = [
        list(dec.detect(txns, now=i + WINDOW, new_oldest_version=i))
        for i, txns in enumerate(batches)
    ]
    # Degraded-window stream: throttled (ratekeeper_degraded_tps_fraction
    # of peak) and drawn from a 1/64 keyspace band — one identical copy
    # consumed by both arms.
    band = KEYSPACE // 64
    base = int(rng.integers(0, KEYSPACE - band))
    degraded_stream = []
    for j in range(degraded_batches):
        i = n_batches + j
        a = rng.integers(0, band, per_batch // 8, dtype=np.int64) + base
        txns = [
            TransactionConflictInfo(
                read_snapshot=i,
                write_ranges=[
                    (int(x).to_bytes(KEY_BYTES, "big"),
                     int(x + 1).to_bytes(KEY_BYTES, "big"))
                ],
            )
            for x in a
        ]
        degraded_stream.append((txns, i + WINDOW, i))
    out = {}
    for name, flags in MIRROR_VARIANTS:
        # The flags dict IS the selector, exactly as it would be in the
        # process environment (FDB_TPU_MIRROR_ENGINE semantics).
        eng_cls = (
            FlatCpuConflictSet
            if flags.get("FDB_TPU_MIRROR_ENGINE") == "flat"
            else CpuConflictSet
        )
        # Arm 1: apply_batch (mirror maintenance under device authority).
        # The columnar engine gets the bench key width so its chunks'
        # primary ek encoding IS the device encoding (chunk_encoding
        # then re-encodes nothing, exactly as in production where the
        # api passes the device key_words through).
        eng = (
            eng_cls()
            if eng_cls is FlatCpuConflictSet
            else eng_cls(key_words=KEY_WORDS)
        )
        t0 = time.perf_counter()
        for i in range(n_batches):
            eng.apply_batch(batches[i], decided[i], now=i + WINDOW,
                            new_oldest_version=i)
        apply_dt = time.perf_counter() - t0
        # Sync point: the device has applied everything so far.  Chunked:
        # warm the per-chunk encode cache exactly as note_synced would.
        chunked = hasattr(eng, "snapshot")
        if chunked:
            from foundationdb_tpu.conflict.engine_cpu import chunk_encoding

            for ch in eng.snapshot().chunks:
                chunk_encoding(ch, KEY_WORDS)
        # Degraded window: the mirror alone serves a few batches.  The
        # window is REALISTIC, i.e. throttled and localized — the PR-7
        # ratekeeper contracts admission to the degraded fraction the
        # moment the breaker opens, so a degraded window sees a fraction
        # of peak load, not full-rate uniform sprays (which would touch
        # every chunk and flatten the proportionality lever on purpose).
        for txns, now_, nov in degraded_stream:
            eng.detect(txns, now=now_, new_oldest_version=nov)
        # Probe rehydration, host-side: the per-key encode work load_from
        # pays (the device-transfer memcpy is the same for both arms).
        t0 = time.perf_counter()
        if chunked:
            from foundationdb_tpu.conflict.engine_cpu import chunk_encoding

            ents, enc_keys = [], 0
            for ch in eng.snapshot().chunks:
                ent, n = chunk_encoding(ch, KEY_WORDS)
                ents.append(ent[0])
                enc_keys += n
            np.concatenate(ents, axis=0)
        else:
            enc_keys = len(eng.keys)
            keylib.encode_keys(eng.keys, KEY_WORDS)
        rehydrate_dt = time.perf_counter() - t0
        # Arm 2: degraded-mode detect throughput (fresh engine, same
        # stream) — the measured-mirror-tps the ratekeeper clamps to.
        eng2 = eng_cls()
        t0 = time.perf_counter()
        for i in range(n_batches):
            eng2.detect(batches[i], now=i + WINDOW, new_oldest_version=i)
        detect_dt = time.perf_counter() - t0
        out[name] = {
            "apply_txns_per_sec": round(n_batches * per_batch / apply_dt, 1),
            "detect_txns_per_sec": round(n_batches * per_batch / detect_dt, 1),
            "rehydrate_host_s": round(rehydrate_dt, 6),
            "rehydrate_keys_encoded": enc_keys,
            "boundaries": eng.boundary_count,
        }
    return out


def bench_jax(rng, n_batches=24, per_batch=65536, h_cap=None, window=WINDOW):
    """Steady-state device throughput at the BASELINE.json 64k-batch config,
    with the reference's full 50-batch live window (skipListTest detects at
    now=i+50, evicts below i — SkipList.cpp:1473-1475).

    Dispatch is pipelined (dispatch_packed): host packing + the single-blob
    transfer of batch N+1 overlap device compute of batch N, exactly as the
    production resolver pipelines batches on the prevVersion chain.  h_cap
    is pre-sized for the steady-state boundary count (2.87M live
    boundaries + ~19% headroom; every H-proportional pass scales with it)
    so no growth (= jit reshape + recompile) happens inside the timed
    region.
    """
    import jax

    from foundationdb_tpu.conflict.engine_jax import JaxConflictSet

    if h_cap is None:
        h_cap = BASE_H_CAP
    verbose = bool(os.environ.get("BENCH_VERBOSE"))
    cs = JaxConflictSet(key_words=KEY_WORDS, h_cap=h_cap)
    warm = window + 2
    batches = [
        gen_packed(rng, per_batch, i, KEY_WORDS) for i in range(n_batches + warm)
    ]
    h_cap0 = cs.h_cap
    d_cap0 = getattr(cs, "d_cap", 0)
    # Warm-up: compile AND fill the MVCC window to steady state.
    for i in range(warm):
        cs.detect_packed(batches[i], now=i + window, new_oldest_version=i)
    if verbose:
        # boundary_count_bound, not boundary_count: the exact tiered count
        # folds the delta host-side (O(rows) Python) — minutes at bench
        # h_cap, unaffordable inside a tunnel window.
        _log(f"steady-state boundaries: <= {cs.boundary_count_bound}")
    t0 = time.perf_counter()
    pending = []
    for j in range(warm, warm + n_batches):
        pending.append(
            cs.dispatch_packed(batches[j], now=j + window, new_oldest_version=j)
        )
    jax.block_until_ready(pending[-1][0])
    dt = time.perf_counter() - t0
    for _statuses, undecided in pending:
        assert int(undecided) == 0, "fixpoint diverged mid-bench"
    assert cs.h_cap == h_cap0, "history grew mid-bench; raise h_cap"
    assert getattr(cs, "d_cap", 0) == d_cap0, (
        "delta tier grew mid-bench; raise FDB_TPU_DELTA_CAP"
    )
    if verbose:
        _log(
            f"{n_batches} batches in {dt:.2f}s "
            f"({dt / n_batches * 1e3:.0f} ms/batch), "
            f"boundaries<={cs.boundary_count_bound}"
        )
    return n_batches * per_batch / dt


def bench_pipeline(rng, depth, n_batches=24, per_batch=65536,
                   h_cap=None, window=WINDOW):
    """Full resolve-loop throughput at pipeline depth `depth` (ISSUE 11):
    per batch, host pack/encode + device dispatch + verdict readback +
    authoritative-mirror apply_batch, through the production ConflictSet
    pipeline (depth 1 == the synchronous resolve path — the before arm).
    Unlike bench_jax (dispatch-only, unbounded pipelining, no mirror),
    this prices the host phases the resolver actually pays per batch, so
    the depth-2-vs-1 ratio is meaningful on ANY host: with JAX's async
    dispatch the mirror apply of batch N-1 and the pack/encode of batch
    N+1 run under device (or XLA-CPU) compute of batch N.

    Returns (txns_per_sec, overlap) where overlap is the span-layer
    pipeline overlap-efficiency metric (ISSUE 12: overlapped device
    time / total device time over the measured batches' device
    in-flight spans) on both the wall axis (the real number) and the
    deterministic event-sequence axis."""
    from foundationdb_tpu.conflict.api import ConflictSet
    from foundationdb_tpu.flow.spans import (
        SpanHub,
        global_span_hub,
        overlap_efficiency,
        set_global_span_hub,
    )

    prev = os.environ.get("FDB_TPU_PIPELINE_DEPTH")
    os.environ["FDB_TPU_PIPELINE_DEPTH"] = str(depth)
    try:
        cs = ConflictSet(backend="jax", key_words=KEY_WORDS, h_cap=h_cap)
    finally:
        if prev is None:
            os.environ.pop("FDB_TPU_PIPELINE_DEPTH", None)
        else:
            os.environ["FDB_TPU_PIPELINE_DEPTH"] = prev
    warm = window + 2
    streams = [
        txns_from_packed(gen_packed(rng, per_batch, i, KEY_WORDS), per_batch)
        for i in range(n_batches + warm)
    ]
    h_cap0 = cs._jax.h_cap

    def run_one(i):
        e = cs.pipeline_submit(streams[i], i + window, i)
        while cs.pipeline_inflight > depth - 1:
            cs.pipeline_complete_oldest()
        return e

    for i in range(warm):
        run_one(i)
    cs.pipeline_drain()
    # Fresh span hub for the MEASURED region only: the overlap metric
    # must price these n_batches, not the warmup's compile-skewed spans.
    old_hub = global_span_hub()
    set_global_span_hub(SpanHub())
    try:
        t0 = time.perf_counter()
        entries = [run_one(warm + j) for j in range(n_batches)]
        cs.pipeline_drain()
        dt = time.perf_counter() - t0
        dev_spans = global_span_hub().spans(name="device")
        overlap = {
            "wall": round(overlap_efficiency(dev_spans, axis="wall"), 4),
            "seq": round(overlap_efficiency(dev_spans, axis="seq"), 4),
            "device_spans": len(dev_spans),
        }
    finally:
        set_global_span_hub(old_hub)
    assert all(e.done and not e.degraded for e in entries)
    assert cs._jax.h_cap == h_cap0, "history grew mid-bench; raise h_cap"
    return n_batches * per_batch / dt, overlap


def _pipeline_phase_costs(rng, n_batches, per_batch, h_cap, window=WINDOW):
    """Serialized per-phase wall costs at the same stream shape: the two
    host phases the pipeline can hide (pack/encode, mirror apply) vs the
    device step it cannot.  The decomposition that makes the depth-sweep
    ratio auditable."""
    from foundationdb_tpu.conflict.api import env_coalesce_window
    from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
    from foundationdb_tpu.conflict.engine_jax import (
        JaxConflictSet,
        PackedBatch,
    )

    cs = JaxConflictSet(key_words=KEY_WORDS, h_cap=h_cap)
    mirror = CpuConflictSet(key_words=KEY_WORDS)
    # FDB_TPU_MIRROR_COALESCE rides the variant flags: a coalescing arm
    # amortizes the fold across K batches (the per-batch average is the
    # honest number; folds are lumpy by design).
    mirror.coalesce_window = env_coalesce_window()
    warm = window + 2
    streams = [
        txns_from_packed(gen_packed(rng, per_batch, i, KEY_WORDS), per_batch)
        for i in range(n_batches + warm)
    ]
    encode_s = step_s = apply_s = 0.0
    for i, txns in enumerate(streams):
        t0 = time.perf_counter()
        pb = PackedBatch.from_transactions(txns, KEY_WORDS)
        t1 = time.perf_counter()
        statuses = cs.detect_packed(pb, now=i + window, new_oldest_version=i)
        t2 = time.perf_counter()
        mirror.apply_batch(
            txns, [int(s) for s in statuses[: len(txns)]],
            now=i + window, new_oldest_version=i,
        )
        t3 = time.perf_counter()
        if i >= warm:
            encode_s += t1 - t0
            step_s += t2 - t1
            apply_s += t3 - t2
    # Settle any queued coalesced batches INSIDE the accounted apply cost
    # so a coalescing arm cannot hide its final partial fold.
    t0 = time.perf_counter()
    _ = mirror.boundary_count
    apply_s += time.perf_counter() - t0
    host_fraction = round(
        (encode_s + apply_s) / max(1e-9, encode_s + step_s + apply_s), 3
    )
    return {
        "encode_ms_per_batch": round(1e3 * encode_s / n_batches, 2),
        "device_step_ms_per_batch": round(1e3 * step_s / n_batches, 2),
        "mirror_apply_ms_per_batch": round(1e3 * apply_s / n_batches, 2),
        # Same ratio, two lenses: what depth-2 can hide under device
        # compute, and the host share of the serialized loop (the
        # ISSUE-19 gate reads host_fraction <= 0.10).
        "overlappable_fraction": host_fraction,
        "host_fraction": host_fraction,
    }


def bench_pipeline_cpu(depths=(1, 2, 3), n_batches=30, per_batch=2500,
                       h_cap=1 << 19):
    """CPU-phase pipeline sweep (ISSUE 11 satellite; prices on any host,
    tunnel or no tunnel): the resolve loop at the skipListTest stream
    shape (2500-txn batches, 20M keyspace, 50-batch window) under each
    depth, plus the serialized phase decomposition.  The acceptance
    gate reads ratio_2v1."""
    # Persistent compile cache (same dir as the device bench): the sweep
    # compiles one shape per history mode; repeat runs are cache-warm.
    os.makedirs(CACHE_DIR, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    out = {"shape": {"per_batch": per_batch, "n_batches": n_batches,
                     "h_cap": h_cap, "window": WINDOW}}
    out["phases_serialized"] = _pipeline_phase_costs(
        np.random.default_rng(2024), n_batches, per_batch, h_cap
    )
    for d in depths:
        rate, overlap = bench_pipeline(
            np.random.default_rng(2024), d,
            n_batches=n_batches, per_batch=per_batch, h_cap=h_cap,
        )
        out[f"pipeline{d}"] = {
            "txns_per_sec": round(rate, 1),
            # ISSUE 12: overlapped device time / total device time off
            # the span layer — the structural explanation of the ratio
            # below (depth 1 is 0 by construction).
            "overlap_efficiency_wall": overlap["wall"],
            "overlap_efficiency_seq": overlap["seq"],
        }
    if "pipeline1" in out and "pipeline2" in out:
        out["ratio_2v1"] = round(
            out["pipeline2"]["txns_per_sec"]
            / out["pipeline1"]["txns_per_sec"], 3,
        )
    return out


def bench_multichip(rng, n_shards, n_batches=16, per_batch=65536,
                    h_cap=None, window=WINDOW):
    """Mesh-sharded resolve loop (ISSUE 15): ShardedJaxConflictSet's FULL
    production serve path — batch replicated to the mesh, clipped per
    shard in-core, per-shard LOCAL verdicts min-combined host-side, and
    every shard's authoritative mirror maintained per batch (the thing
    the resolver actually pays, not a dispatch-only microbench).  Runs on
    a virtual CPU mesh anywhere (tests/driver set
    xla_force_host_platform_device_count) and on a real mesh behind the
    driver's probe cap.  Returns (txns_per_sec, info)."""
    import jax

    from foundationdb_tpu.parallel.sharded_resolver import (
        ShardedJaxConflictSet,
        uniform_int_split_keys,
    )

    devs = jax.devices()
    assert len(devs) >= n_shards, (
        f"multichip arm needs >= {n_shards} devices, got {len(devs)}"
    )
    if h_cap is None:
        h_cap = BASE_H_CAP
    from foundationdb_tpu.conflict.engine_jax import _next_pow2

    # Per-shard capacity: an even slice of the global steady state plus
    # whole-batch headroom (one shard can receive every write of a batch
    # that hugs its range) — the engine's must-fit guard grows rather
    # than truncates if a workload outruns it, and the stability assert
    # below keeps the timed region honest.
    cap_s = _next_pow2(h_cap // n_shards + 4 * per_batch, 4096)
    split = uniform_int_split_keys(n_shards, KEYSPACE, KEY_BYTES)
    cs = ShardedJaxConflictSet(
        split, key_words=KEY_WORDS, h_cap=cap_s,
        devices=devs[:n_shards], bucket_mins=(8, 8, 8),
    )
    warm = window + 2
    batches = [
        gen_packed(rng, per_batch, i, KEY_WORDS)
        for i in range(n_batches + warm)
    ]
    for i in range(warm):
        cs.detect_packed(batches[i], now=i + window, new_oldest_version=i)
    h_cap0 = cs.h_cap
    t0 = time.perf_counter()
    for j in range(warm, warm + n_batches):
        cs.detect_packed(batches[j], now=j + window, new_oldest_version=j)
    dt = time.perf_counter() - t0
    assert cs.h_cap == h_cap0, "shard history grew mid-bench; raise h_cap"
    sig = cs.backend_signal()
    assert sig["shards_degraded"] == 0, "a shard degraded mid-bench"
    info = {
        "n_shards": n_shards,
        "per_shard_h_cap": cap_s,
        "per_batch": per_batch,
        "n_batches": n_batches,
        "window": window,
    }
    return n_batches * per_batch / dt, info


def bench_multichip_cpu(n_shards=(1, 4, 8), n_batches=12, per_batch=2500,
                        h_cap=1 << 19):
    """CPU virtual-mesh multichip A/B (ISSUE 15 satellite; always
    runnable — no tunnel needed): the sharded resolve loop at the
    skipListTest stream shape across shard counts, with the 1-shard arm
    as the scaling baseline.  Wall numbers are virtual-mesh relative
    (all shards share one host CPU); the honest device rates come from
    the `multichip` variant behind the probe cap."""
    import jax

    out = {"shape": {"per_batch": per_batch, "n_batches": n_batches,
                     "h_cap": h_cap, "window": WINDOW},
           "n_devices": len(jax.devices())}
    for n in n_shards:
        if n > len(jax.devices()):
            out[f"sharded{n}"] = {"skipped": f"only {len(jax.devices())} devices"}
            continue
        rate, info = bench_multichip(
            np.random.default_rng(2024), n,
            n_batches=n_batches, per_batch=per_batch, h_cap=h_cap,
        )
        out[f"sharded{n}"] = {"txns_per_sec": round(rate, 1), **info}
    if "sharded1" in out and "txns_per_sec" in out.get("sharded8", {}):
        out["ratio_8v1"] = round(
            out["sharded8"]["txns_per_sec"]
            / out["sharded1"]["txns_per_sec"], 3,
        )
    return out


def bench_kernels_cpu(n_batches=16, per_batch=512, h_cap=1 << 12,
                      seeds=(2024, 2025, 2026)):
    """CPU-phase kernel A/B (ISSUE 14 satellite; prices on any host):
    the Pallas arms run in INTERPRET mode here, so the wall numbers are
    the emulator's, not Mosaic's — the artifact's job is (a) the
    cross-seed verdict+state identity evidence at a realistic stream
    shape, and (b) the deterministic in-step FLOP attribution
    (phase_attribution's `nokernel` A/B), which IS the structural story
    the device run will price.  Emits a BENCH-style dict
    (`tools/perf_experiments.py --kernels`)."""
    import jax

    from foundationdb_tpu.conflict.engine_jax import JaxConflictSet

    os.makedirs(CACHE_DIR, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    out = {
        "metric": "kernels_cpu_ab",
        "mode": "interpret",  # honest: Pallas emulation off-TPU
        "shape": {"per_batch": per_batch, "n_batches": n_batches,
                  "h_cap": h_cap, "window": WINDOW, "seeds": list(seeds)},
    }

    def run_arm(kflag, history, seed):
        env_keys = ("FDB_TPU_KERNELS", "FDB_TPU_HISTORY",
                    "FDB_TPU_DELTA_CAP", "FDB_TPU_EVICT_EVERY")
        saved = {k: os.environ.get(k) for k in env_keys}
        os.environ["FDB_TPU_KERNELS"] = kflag
        if history:
            os.environ["FDB_TPU_HISTORY"] = history
            os.environ["FDB_TPU_DELTA_CAP"] = str(h_cap // 8)
            os.environ["FDB_TPU_EVICT_EVERY"] = "4"
        else:
            for k in env_keys[1:]:
                os.environ.pop(k, None)
        try:
            rng = np.random.default_rng(seed)
            cs = JaxConflictSet(key_words=KEY_WORDS, h_cap=h_cap)
            batches = [gen_packed(rng, per_batch, i, KEY_WORDS)
                       for i in range(n_batches)]
            cs.detect_packed(batches[0], now=WINDOW, new_oldest_version=0)
            t0 = time.perf_counter()
            verdicts = [
                tuple(cs.detect_packed(b, now=i + 1 + WINDOW,
                                       new_oldest_version=i + 1).tolist())
                for i, b in enumerate(batches[1:])
            ]
            dt = time.perf_counter() - t0
            from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet

            cpu = CpuConflictSet()
            cs.store_to(cpu)
            exported = (tuple(cpu.keys), tuple(cpu.vers))
            return verdicts, exported, dt
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    for history in ("", "tiered"):
        label = "tiered" if history else "flat"
        identical = True
        walls = {"kernels": 0.0, "xla": 0.0}
        for seed in seeds:
            kv, ks, kdt = run_arm("1", history, seed)
            xv, xs, xdt = run_arm("0", history, seed)
            identical &= (kv == xv and ks == xs)
            walls["kernels"] += kdt
            walls["xla"] += xdt
        out[label] = {
            "bit_identical": identical,
            "wall_seconds_interpret": {k: round(v, 3)
                                       for k, v in walls.items()},
        }
        assert identical, f"kernel arm diverged from XLA arm ({label})"
    # Deterministic in-step attribution with the nokernel A/B block.
    from foundationdb_tpu.conflict.phase_attribution import attribute_phases

    saved = os.environ.get("FDB_TPU_KERNELS")
    os.environ["FDB_TPU_KERNELS"] = "1"
    try:
        cs = JaxConflictSet(key_words=KEY_WORDS, h_cap=h_cap)
        rep = attribute_phases(cs, record=False)
        out["attribution"] = {
            "phases": rep["phases"],
            "kernel_ab": rep["kernel_ab"],
        }
    finally:
        if saved is None:
            os.environ.pop("FDB_TPU_KERNELS", None)
        else:
            os.environ["FDB_TPU_KERNELS"] = saved
    return out


def bench_timeline(out_path="TIMELINE.json", depth=2, n_batches=16,
                   per_batch=2500, h_cap=1 << 19):
    """Timeline artifact for the next device window (ISSUE 12 satellite):
    a short pipelined resolve run with span recording, the
    phase-attribution harness hung off the last dispatch span, and the
    whole thing exported as a Perfetto / Chrome trace-event JSON file —
    so BENCH numbers ship WITH the timeline that explains them."""
    from foundationdb_tpu.conflict.api import ConflictSet
    from foundationdb_tpu.conflict.phase_attribution import attribute_phases
    from foundationdb_tpu.flow.spans import (
        SpanHub,
        global_span_hub,
        overlap_efficiency,
        set_global_span_hub,
    )
    from foundationdb_tpu.flow.trace_export import (
        perfetto_trace,
        validate_perfetto,
    )

    rng = np.random.default_rng(2024)
    prev = os.environ.get("FDB_TPU_PIPELINE_DEPTH")
    os.environ["FDB_TPU_PIPELINE_DEPTH"] = str(depth)
    try:
        cs = ConflictSet(backend="jax", key_words=KEY_WORDS, h_cap=h_cap)
    finally:
        if prev is None:
            os.environ.pop("FDB_TPU_PIPELINE_DEPTH", None)
        else:
            os.environ["FDB_TPU_PIPELINE_DEPTH"] = prev
    streams = [
        txns_from_packed(gen_packed(rng, per_batch, i, KEY_WORDS), per_batch)
        for i in range(n_batches)
    ]
    old_hub = global_span_hub()
    set_global_span_hub(SpanHub())
    try:
        for i, txns in enumerate(streams):
            cs.pipeline_submit(txns, i + WINDOW, i)
            while cs.pipeline_inflight > depth - 1:
                cs.pipeline_complete_oldest()
        cs.pipeline_drain()
        attribution = attribute_phases(
            cs._jax, streams[-1], measure=True, repeats=2
        )
        hub = global_span_hub()
        dev_spans = hub.spans(name="device")
        doc = perfetto_trace(hub, include_wall=True)
        errors = validate_perfetto(doc)
        atomic_write_json(
            out_path, doc, sort_keys=True, separators=(",", ":")
        )
        return {
            "metric": "pipeline_overlap_efficiency",
            "value": round(overlap_efficiency(dev_spans, axis="wall"), 4),
            "unit": "overlapped/total device time (wall)",
            "depth": depth,
            "n_batches": n_batches,
            "per_batch": per_batch,
            "spans": sum(len(r) for r in hub.rings.values()),
            "timeline_path": out_path,
            "schema_errors": errors,
            "phase_attribution": {
                "phases": attribution["phases"],
                "measured": attribution.get("measured"),
            },
        }
    finally:
        set_global_span_hub(old_hub)


def _persist_arms(out):
    """Tunnel-resilient per-arm artifact (ISSUE 18 satellite): after each
    device arm completes (or fails), the variants-so-far land atomically
    in BENCH_ARMS.json — a mid-campaign tunnel death leaves every
    finished arm on disk instead of a lost session."""
    atomic_write_json(
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_ARMS.json"
        ),
        {
            "variants": out.get("variants", {}),
            "best_variant": out.get("variant"),
            "best_txns_per_sec": out.get("value"),
            "run_attempts": out.get("run_attempts", 0),
        },
        indent=2,
        sort_keys=True,
    )


def atomic_write_json(path, doc, **dump_kwargs):
    """Write a JSON artifact via tmp + os.replace (ISSUE 18 satellite):
    a mid-campaign tunnel death leaves either the previous artifact or
    the complete new one on disk, never a torn half-write — so partial
    bench sessions keep every arm that finished."""
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, **dump_kwargs)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def emit(out, errors):
    """Print the full best-so-far result as one JSON line and flush, so a
    mid-run kill still leaves the best partial result on stdout (the driver
    takes the last line).  Records 1-min load average and warns when > 1.5
    (orphaned processes depressed round 3's baselines by ~2.6x)."""
    load1 = os.getloadavg()[0]
    out["loadavg_1m"] = round(load1, 2)
    if load1 > 1.5:
        out["load_warning"] = (
            f"1-min load {load1:.2f} > 1.5 on a 1-core host; numbers below "
            "are likely understated (check for orphaned processes)"
        )
    else:
        out.pop("load_warning", None)
    if errors:
        out["error"] = "; ".join(errors)
    print(json.dumps(out), flush=True)


def device_phase_main():
    """Runs inside a subprocess (see main): device init + the device bench.
    The parent enforces a hard wall-clock timeout and kills us on hang, so a
    broken axon tunnel (25-min init hangs, observed r2/r3) cannot eat the
    driver's budget.  Prints one JSON line with the device results.

    The engine-variant flags (FDB_TPU_SEARCH / FDB_TPU_EVICT_EVERY /
    FDB_TPU_SEARCH_STRIDE) are read from the environment by the engine at
    trace time — the parent sets them per variant attempt; h_cap rides
    BENCH_H_CAP (evict-batching variants need headroom)."""
    from foundationdb_tpu.utils.procutil import reap_group_on_term

    # If bench.py dies, the kernel TERMs us (PDEATHSIG) and this handler
    # SIGKILLs our whole session — including tunnel helper grandchildren
    # that PDEATHSIG alone would orphan.
    reap_group_on_term()
    res = {}
    platform = setup_jax()
    res["platform"] = platform
    warm_compile_probe()
    h_cap = int(os.environ.get("BENCH_H_CAP", str(BASE_H_CAP)))
    _log(f"device bench: 24 batches x 65536 txns, window=50, h_cap={h_cap} "
         "(first compile may take minutes on this 1-core host)...")
    rng = np.random.default_rng(2024)
    depth_flag = os.environ.get("FDB_TPU_PIPELINE_DEPTH")
    mc_flag = os.environ.get("BENCH_MULTICHIP")
    hp_flag = os.environ.get("BENCH_HOSTPATH")
    if hp_flag:
        # Serialized host-path decomposition (ISSUE 19) at the round-11
        # stream shape: 30 x 2500-txn batches against h_cap history.
        phases = _pipeline_phase_costs(rng, 30, 2500, h_cap)
        res["hostpath"] = phases
        total_ms = (
            phases["encode_ms_per_batch"]
            + phases["device_step_ms_per_batch"]
            + phases["mirror_apply_ms_per_batch"]
        )
        res["jax_txns_per_sec"] = round(2500 * 1e3 / max(1e-9, total_ms), 1)
    elif mc_flag:
        # Mesh-sharded variant (ISSUE 15): the full shard-granular
        # resolve loop over the visible devices.
        rate, info = bench_multichip(rng, int(mc_flag), h_cap=h_cap)
        res["jax_txns_per_sec"] = round(rate, 1)
        res["multichip"] = info
    elif depth_flag:
        # Pipeline variants price the full resolve loop (ISSUE 11).
        rate, overlap = bench_pipeline(rng, int(depth_flag), h_cap=h_cap)
        res["jax_txns_per_sec"] = round(rate, 1)
        res["overlap_efficiency_wall"] = overlap["wall"]
    else:
        res["jax_txns_per_sec"] = round(bench_jax(rng, h_cap=h_cap), 1)
    _log(f"device: {res['jax_txns_per_sec']:,.0f} txn/s")
    print(json.dumps(res), flush=True)


def run_device_subprocess(timeout):
    """Run the device phase in a killable child; return its parsed JSON dict.
    Raises on timeout / crash / unparseable output."""
    from foundationdb_tpu.utils.procutil import run_killable

    t0 = time.perf_counter()
    rc, stdout, _ = run_killable(
        [sys.executable, os.path.abspath(__file__), "--device-phase"],
        timeout,
        stderr=sys.stderr,
    )
    _log(f"device subprocess exited rc={rc} "
         f"after {time.perf_counter() - t0:.0f}s")
    if rc != 0:
        raise RuntimeError(f"device phase rc={rc}")
    for line in reversed(stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    raise RuntimeError("device phase printed no JSON")


def probe_device(timeout):
    """Cheap killable liveness check: `jax.devices()` in a child with a hard
    timeout.  A dead tunnel costs `timeout` seconds here instead of the full
    device-phase budget.  The child installs the group-reaping TERM handler
    so tunnel helper grandchildren die with it."""
    from foundationdb_tpu.utils.procutil import device_probe_argv, run_killable

    repo = os.path.dirname(os.path.abspath(__file__))
    rc, stdout, stderr = run_killable(device_probe_argv(repo), timeout)
    if rc != 0:
        raise RuntimeError(f"device probe failed: {stderr.strip()[-500:]}")
    _log(f"device probe ok: {stdout.strip()}")


def wait_for_device(out, errors, deadline, probe_state=None):
    """Retry the killable liveness probe until it succeeds, `deadline`
    (time.perf_counter() units) passes, or — when `probe_state` is given —
    the TOTAL probe spend cap is hit.  The axon tunnel is known to be down
    for stretches and come back (BENCH_r01/r03/r04 all lost the lottery
    with a single-shot probe), but an all-session-dead tunnel must not
    ride the whole run to a driver kill either (BENCH_SESSION_NOTE shows
    7 probe attempts eating the budget): `probe_state` caps probing at
    `max_consecutive_fails` failures in a row AND `budget_s` cumulative
    UNPRODUCTIVE probe seconds (failed attempts + inter-attempt sleeps;
    a success resets both counters).  Tradeoff made explicit: at the default caps (2 fails / 25%)
    a tunnel that is dead at the START of the run forfeits the device
    phase after ~2 probe cycles — the rc=124 failure mode costs bounded
    time now, at the price of the old wait-out-the-flap behavior.  A
    mid-run flap after a SUCCESSFUL probe still retries (success resets
    the consecutive count); operators who want the old patience raise
    BENCH_PROBE_MAX_FAILS / BENCH_PROBE_BUDGET_FRAC.  Emits a heartbeat
    JSON line per attempt so the driver's last-line read always shows
    progress (probe_attempts / probe_elapsed_s) alongside the
    best-so-far result.

    Returns True when a probe succeeded, False when a budget/cap ran out
    (probe_state["skipped"] then says which)."""
    # 240s per attempt: a healthy-but-slow tunnel can need minutes to answer
    # (r2's successful init took ~2 min); a dead tunnel hangs and gets
    # killed at the timeout, so the attempt cadence self-adjusts.
    probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))
    interval = int(os.environ.get("BENCH_PROBE_INTERVAL", "90"))
    ps = probe_state if probe_state is not None else {
        "spent_s": 0.0, "consecutive_fails": 0,
        "budget_s": float("inf"), "max_consecutive_fails": 1 << 30,
    }
    while True:
        if ps.get("skipped"):
            return False
        remaining = deadline - time.perf_counter()
        if remaining <= 5:
            out.setdefault("probe_last_error", "no attempt fit in budget")
            return False
        if ps["spent_s"] >= ps["budget_s"]:
            ps["skipped"] = (
                f"probe spend cap: {ps['spent_s']:.0f}s of "
                f"{ps['budget_s']:.0f}s probe budget used"
            )
            return False
        if ps["consecutive_fails"] >= ps["max_consecutive_fails"]:
            ps["skipped"] = (
                f"{ps['consecutive_fails']} consecutive probe failures "
                f"(cap {ps['max_consecutive_fails']})"
            )
            return False
        out["probe_attempts"] = out.get("probe_attempts", 0) + 1
        t_attempt = time.perf_counter()
        try:
            probe_device(min(probe_timeout, max(10, int(remaining))))
            # A successful probe resets BOTH caps: the budget bounds
            # consecutive UNPRODUCTIVE probing (the dead-tunnel mode),
            # not the healthy-but-slow tunnel whose ~2-min successful
            # probes across many variant attempts would otherwise eat it.
            ps["consecutive_fails"] = 0
            ps["spent_s"] = 0.0
            out.pop("probe_last_error", None)
            out["probe_elapsed_s"] = round(time.perf_counter() - t_attempt, 1)
            return True
        except Exception as e:
            ps["spent_s"] += time.perf_counter() - t_attempt
            ps["consecutive_fails"] += 1
            msg = f"{type(e).__name__}: {str(e)[-300:]}"
            out["probe_last_error"] = msg
            out["probe_elapsed_s"] = round(ps["spent_s"], 1)
            _log(
                f"device probe attempt {out['probe_attempts']} failed ({msg}); "
                f"{deadline - time.perf_counter():.0f}s of budget left"
            )
            emit(out, errors)  # heartbeat: best-so-far + probe progress
            # Cadence-based sleep: attempts START every `interval` seconds;
            # an attempt that burned its timeout re-probes immediately.
            # The sleep counts toward the probe-spend cap too — probing
            # wall time is probing wall time, whether the tunnel hangs
            # (240s timeouts) or fails fast (sleep-dominated).
            attempt_dur = time.perf_counter() - t_attempt
            sleep_s = min(
                max(0, interval - attempt_dur),
                max(0, deadline - time.perf_counter() - 10),
            )
            if sleep_s > 0:
                time.sleep(sleep_s)
                ps["spent_s"] += sleep_s


def main():
    """Prints a full JSON result line after EVERY completed phase (C++
    baseline, Python CPU, device) — the driver's last-line read always sees
    the best result achieved so far, even if a later phase is killed."""
    out = {
        "metric": "resolver_conflict_txns_per_sec_64k_batch",
        "value": 0.0,
        "unit": "txn/s",
        "vs_baseline": 0.0,
    }
    errors = []
    cpu_rate = None
    cpp_rate = None
    try:
        _log("C++ baseline: 500 batches x 2500 txns (skiplist_baseline)...")
        cpp_rate = bench_cpp()
        _log(f"C++ baseline: {cpp_rate:,.0f} txn/s")
        out["cpp_txns_per_sec"] = round(cpp_rate, 1)
    except Exception as e:
        errors.append(f"cpp: {type(e).__name__}: {e}")
    emit(out, errors)
    try:
        rng = np.random.default_rng(2024)
        _log("Python engine: 20 batches x 2500 txns (CpuConflictSet)...")
        cpu_rate = bench_cpu(rng)
        _log(f"Python engine: {cpu_rate:,.0f} txn/s")
        out["cpu_txns_per_sec"] = round(cpu_rate, 1)
        out["value"] = round(cpu_rate, 1)
        out["vs_baseline"] = round(cpu_rate / cpp_rate, 3) if cpp_rate else 1.0
    except Exception as e:
        errors.append(f"cpu: {type(e).__name__}: {e}")
    emit(out, errors)
    try:
        _log("mirror A/B: flat vs chunked apply/rehydrate (ISSUE 9)...")
        out["mirror"] = bench_mirror(np.random.default_rng(2024))
        _log(f"mirror: {json.dumps(out['mirror'])}")
    except Exception as e:
        errors.append(f"mirror: {type(e).__name__}: {e}")
    emit(out, errors)
    try:
        device_phase(out, errors, cpp_rate, cpu_rate)
    except Exception as e:
        errors.append(f"device: {type(e).__name__}: {e}")
    emit(out, errors)


# Engine variants, all DECISION-IDENTICAL to the default compile (verified
# by the differential suites run under each flag set — tests/
# test_engine_experiments.py); the only question hardware answers is
# speed, so the driver-time device phase may honestly report the fastest.
VARIANTS = [
    ("baseline", {}, BASE_H_CAP),
    # Two-tier history (ISSUE 4): per-batch phase-5/6 sorts run at delta
    # size; a major compaction every 4 batches (FDB_TPU_EVICT_EVERY is the
    # cadence alias in tiered mode) pays the full-H sorts amortized.  The
    # base keeps sub-window rows between compactions, so h_cap gets the
    # same headroom as the evict-batching variants; the delta is sized for
    # 4 batches of 2*64k rows.
    (
        "tiered4",
        {
            "FDB_TPU_HISTORY": "tiered",
            "FDB_TPU_EVICT_EVERY": "4",
            "FDB_TPU_DELTA_CAP": str(5 * 2 * 65536),
        },
        BASE_H_CAP + 3 * 2 * 65536,
    ),
    (
        "tiered4_2level",
        {
            "FDB_TPU_HISTORY": "tiered",
            "FDB_TPU_EVICT_EVERY": "4",
            "FDB_TPU_DELTA_CAP": str(5 * 2 * 65536),
            "FDB_TPU_SEARCH": "2level",
        },
        BASE_H_CAP + 3 * 2 * 65536,
    ),
    (
        "both_evict8_stride1k",
        {
            "FDB_TPU_SEARCH": "2level",
            "FDB_TPU_SEARCH_STRIDE": "1024",
            "FDB_TPU_EVICT_EVERY": "8",
        },
        BASE_H_CAP + 7 * 2 * 65536,
    ),
    (
        "both",
        {"FDB_TPU_SEARCH": "2level", "FDB_TPU_EVICT_EVERY": "4"},
        BASE_H_CAP + 3 * 2 * 65536,
    ),
    ("search2level", {"FDB_TPU_SEARCH": "2level"}, BASE_H_CAP),
    ("evict4", {"FDB_TPU_EVICT_EVERY": "4"}, BASE_H_CAP + 3 * 2 * 65536),
    # Pipeline depth sweep (ISSUE 11): the FULL resolve loop (encode +
    # dispatch + readback + mirror apply) via bench_pipeline — a
    # depth-flagged variant runs that loop instead of the dispatch-only
    # bench_jax, so the arm prices exactly what the resolver pays.
    # pipeline1 is the synchronous before-arm; deeper arms overlap the
    # host phases under device compute.
    ("pipeline1", {"FDB_TPU_PIPELINE_DEPTH": "1"}, BASE_H_CAP),
    ("pipeline2", {"FDB_TPU_PIPELINE_DEPTH": "2"}, BASE_H_CAP),
    ("pipeline3", {"FDB_TPU_PIPELINE_DEPTH": "3"}, BASE_H_CAP),
    # Serialized host-path decomposition (ISSUE 19): per-phase wall costs
    # (encode / device step / mirror apply) at the round-11 stream shape
    # — the arm that records the host_fraction the columnar mirror and
    # coalesced apply drive down.  Not a throughput contender: its
    # jax_txns_per_sec is the serialized loop, reported for context.
    ("hostpath", {"BENCH_HOSTPATH": "1"}, 1 << 19),
    # Pallas fused kernels (ISSUE 14): merge/evict as ONE streaming pass +
    # the phase-1 searches over VMEM-resident tiles.  On the TPU backend
    # '1' compiles real Mosaic kernels; decision-identical to the XLA
    # arms by the tests/test_kernels.py differential gate.
    ("kernels", {"FDB_TPU_KERNELS": "1"}, BASE_H_CAP),
    (
        "tiered4_kernels",
        {
            "FDB_TPU_KERNELS": "1",
            "FDB_TPU_HISTORY": "tiered",
            "FDB_TPU_EVICT_EVERY": "4",
            "FDB_TPU_DELTA_CAP": str(5 * 2 * 65536),
        },
        BASE_H_CAP + 3 * 2 * 65536,
    ),
    # Mesh-sharded resolve loop (ISSUE 15): the shard-granular production
    # path over 8 chips — per-shard clipped serving, host min-combine,
    # per-shard mirror maintenance.  On the CPU virtual mesh this arm is
    # relative-only (bench_multichip_cpu is the always-runnable A/B); on
    # a real mesh it rides the same probe cap as every device arm.
    ("multichip", {"BENCH_MULTICHIP": "8"}, BASE_H_CAP),
]

_VARIANT_FLAG_KEYS = (
    "FDB_TPU_SEARCH",
    "FDB_TPU_SEARCH_STRIDE",
    "FDB_TPU_EVICT_EVERY",
    "FDB_TPU_HISTORY",
    "FDB_TPU_DELTA_CAP",
    "FDB_TPU_PIPELINE_DEPTH",
    "FDB_TPU_KERNELS",
    "BENCH_MULTICHIP",
    "BENCH_HOSTPATH",
    "FDB_TPU_MIRROR_COALESCE",
    "BENCH_H_CAP",
)


def variant_plan():
    """Attempt order: the TUNED.json winner first (written by
    tools/perf_experiments.py after an on-device A/B), else the shipping
    default; the remaining variants follow as budget allows."""
    plan = list(VARIANTS)
    tuned_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "TUNED.json"
    )
    try:
        with open(tuned_path) as f:
            tuned = json.load(f).get("variant")
        plan.sort(key=lambda v: v[0] != tuned)
    except (OSError, ValueError):
        pass
    return plan


def device_phase(out, errors, cpp_rate, cpu_rate):
    """Whole-budget device phase: BENCH_DEVICE_TIMEOUT is the TOTAL
    wall-clock budget for probe attempts AND bench runs, consumed by a
    probe→run→(on failure) re-probe loop, so a tunnel that flaps after a
    successful probe (the r2/r3 init-hang mode) re-enters probing with the
    remaining budget instead of abandoning it.  A successful probe grants
    the run at least BENCH_RUN_MIN — a probe succeeding at minute 50 still
    gets a full run (the persistent .jax_cache makes the compile fast), at
    worst overrunning into the driver's kill, which is safe because every
    phase already emitted its best-so-far line.

    Once ONE variant has produced a number, remaining budget goes to the
    other decision-identical variants and the best rate wins (all compiles
    hit the persistent cache when the in-session A/B already ran them)."""
    # Context for a tunnel-dead round: the number measured IN-SESSION on
    # the real chip (clearly labeled — it is NOT this run's result; the
    # driver's own device phase below remains the verified number).
    note_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_SESSION_NOTE.json"
    )
    if os.path.exists(note_path):
        try:
            with open(note_path) as f:
                out["in_session_device_note"] = json.load(f)
        except (OSError, json.JSONDecodeError):
            pass
    budget = int(os.environ.get("BENCH_DEVICE_TIMEOUT", "3600"))
    # Per-run cap, NOT the whole remaining budget: a device subprocess that
    # hangs in backend init (the r2/r3 mode) is killed after run_min so the
    # loop actually gets to re-probe with what's left.  run_min is sized for
    # a worst-case cold compile on this 1-core host.
    run_min = int(os.environ.get("BENCH_RUN_MIN", "1500"))
    max_runs = int(os.environ.get("BENCH_RUN_ATTEMPTS", "6"))
    # Total device-probe spend cap (ISSUE 4 satellite): a dead tunnel gets
    # at most BENCH_PROBE_MAX_FAILS consecutive failures or 25% of the
    # device budget in probe wall time, whichever trips first — then the
    # device phase is SKIPPED explicitly (device_skipped in the JSON)
    # instead of riding the whole run into the driver's kill.
    probe_state = {
        "spent_s": 0.0,
        "consecutive_fails": 0,
        "budget_s": float(os.environ.get("BENCH_PROBE_BUDGET_FRAC", "0.25"))
        * budget,
        "max_consecutive_fails": int(
            os.environ.get("BENCH_PROBE_MAX_FAILS", "2")
        ),
    }
    out["device_skipped"] = False
    # After a first number is on the board, a further variant attempt is
    # worth starting only with this much budget left (cache-warm runs take
    # minutes; a cold-compile attempt that gets killed loses nothing —
    # the best-so-far line is already emitted).
    extra_reserve = int(os.environ.get("BENCH_VARIANT_RESERVE", "420"))
    deadline = time.perf_counter() + budget
    run_attempts = 0
    last_err = None
    best = None  # (rate, variant name)
    queue = variant_plan()
    vi = 0
    fails_here = 0  # consecutive failures of the CURRENT variant
    out["variants"] = {}
    while time.perf_counter() < deadline - 5 and run_attempts < max_runs:
        if best is not None and (
            vi >= len(queue)
            or deadline - time.perf_counter() < extra_reserve
        ):
            break
        if vi >= len(queue):
            # No number yet and the whole plan failed once through:
            # keep cycling within the budget (tunnel flaps are transient).
            vi = 0
        if not wait_for_device(out, errors, deadline, probe_state):
            if probe_state.get("skipped"):
                out["device_skipped"] = probe_state["skipped"]
                emit(out, errors)
            break
        name, flags, h_cap = queue[vi]
        for k in _VARIANT_FLAG_KEYS:
            os.environ.pop(k, None)
        os.environ.update(flags)
        os.environ["BENCH_H_CAP"] = str(h_cap)
        run_attempts += 1
        out["run_attempts"] = run_attempts
        cap = max(300, min(run_min, int(deadline - time.perf_counter())))
        _log(f"device run {run_attempts}: variant={name} cap={cap}s")
        try:
            res = run_device_subprocess(cap)
        except Exception as e:
            last_err = (
                f"run attempt {run_attempts} ({name}): "
                f"{type(e).__name__}: {e}"
            )
            _log(f"device {last_err}; "
                 f"{deadline - time.perf_counter():.0f}s of budget left")
            out["variants"][name] = {"error": str(e)[-200:]}
            emit(out, errors)
            _persist_arms(out)
            fails_here += 1
            if best is not None or fails_here >= 2:
                # With a number on the board a failing EXTRA variant is
                # skipped outright; with none, two consecutive failures of
                # the SAME variant advance the plan — a deterministically
                # broken first variant (stale TUNED.json) must not starve
                # the baseline of its run attempts.
                vi += 1
                fails_here = 0
            # A fast deterministic crash must not spin probe->run: pause
            # before re-probing (the probe itself sleeps only on failure).
            time.sleep(min(30, max(0, deadline - time.perf_counter() - 10)))
            continue
        out["platform"] = res.get("platform")
        jax_rate = res["jax_txns_per_sec"]
        out["variants"][name] = {"txns_per_sec": jax_rate}
        if "hostpath" in res:
            out["variants"][name]["hostpath"] = res["hostpath"]
        # vs_baseline is the north-star ratio: device throughput over the
        # NATIVE C++ skiplist on this host (BASELINE.md:30-35).  Best
        # variant wins — all variants compute identical verdicts.
        if best is None or jax_rate > best[0]:
            best = (jax_rate, name)
            out["value"] = jax_rate
            out["variant"] = name
            if cpp_rate:
                out["vs_baseline"] = round(jax_rate / cpp_rate, 3)
            elif cpu_rate:
                out["vs_baseline"] = round(jax_rate / cpu_rate, 3)
        emit(out, errors)
        _persist_arms(out)
        vi += 1
        fails_here = 0
    if best is None:
        raise RuntimeError(
            f"no device number"
            + (
                f" (skipped: {out['device_skipped']})"
                if out.get("device_skipped")
                else ""
            )
            + f": {out.get('probe_attempts', 0)} probe "
            f"attempts, {run_attempts} run attempts over {budget}s; "
            f"last: {last_err or out.get('probe_last_error')}"
        )


if __name__ == "__main__":
    if "--device-phase" in sys.argv:
        device_phase_main()
    else:
        main()
