"""MultiVersion client: protocol-generation selection against a live
cluster.

Ref: fdbclient/MultiVersionTransaction.h:402 / MultiVersionApi — several
client libraries probe; the one whose protocol the cluster speaks serves.
A fake future generation stands in for "another installed client
library", exactly how the reference tests its dummy libs.
"""

import signal
import subprocess

import pytest

from conftest import spawn_real_node

from foundationdb_tpu.client.multi_version import (
    ClientGeneration,
    MultiVersionClient,
    _bootstrap_current,
    current_generation,
)
from foundationdb_tpu.flow.error import FdbError
from foundationdb_tpu.flow.eventloop import EventLoop, set_event_loop


@pytest.fixture(scope="module")
def server():
    proc = spawn_real_node("server")
    ready = proc.stdout.readline().strip()
    assert ready.startswith("READY "), ready
    yield ready.split()[1]
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        proc.kill()


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def _fake_future_gen():
    return ClientGeneration(
        b"FDBTPU-0xFFFFFFFFFFFFFFFFFUTURE",
        _bootstrap_current,
        "fake future client",
    )


def test_selects_compatible_generation_after_rejection(server):
    """Newest-first probing: the fake future generation is rejected at the
    hello, the shipped one connects, and the database works through it."""
    loop = EventLoop(seed=11)
    set_event_loop(loop)
    mv = MultiVersionClient([_fake_future_gen(), current_generation()])
    net, proc, db = mv.connect(server, loop, timeout_s=20.0)
    assert mv.selected is not None and mv.selected.description == "current tree"
    assert mv.attempts[0] == (
        "fake future client", "incompatible_protocol_version"
    )
    assert mv.attempts[1][1] == "selected"

    async def roundtrip():
        tr = db.create_transaction()
        tr.set(b"mv_key", b"mv_val")
        await tr.commit()
        tr2 = db.create_transaction()
        return await tr2.get(b"mv_key")

    task = proc.spawn(roundtrip(), "mv_roundtrip")
    assert net.run_realtime(until=task, timeout_s=30.0) == b"mv_val"
    net.close()


def test_no_compatible_generation_raises(server):
    loop = EventLoop(seed=12)
    set_event_loop(loop)
    mv = MultiVersionClient([_fake_future_gen()])
    with pytest.raises(FdbError, match="incompatible_protocol_version"):
        mv.connect(server, loop, timeout_s=15.0)
    assert mv.attempts == [
        ("fake future client", "incompatible_protocol_version")
    ]


def test_down_cluster_reports_connection_failed_not_version_skew():
    """An unreachable cluster must NOT be misdiagnosed as a protocol
    mismatch (the hello was never rejected — it was never delivered)."""
    import socket

    # A port nothing listens on.
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_addr = "127.0.0.1:%d" % s.getsockname()[1]
    s.close()

    loop = EventLoop(seed=13)
    set_event_loop(loop)
    mv = MultiVersionClient([current_generation()])
    with pytest.raises(FdbError, match="connection_failed|timed_out"):
        mv.connect(dead_addr, loop, timeout_s=4.0)
    assert mv.attempts[0][1] in ("connection_failed", "timed_out")
