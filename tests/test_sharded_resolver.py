"""Differential tests: mesh-sharded conflict engine vs per-shard CPU oracle.

The oracle reproduces the reference's multi-resolver semantics in plain
Python: split each transaction's ranges per resolver key range
(ref: ResolutionRequestBuilder, MasterProxyServer.actor.cpp:280-303), run an
independent CpuConflictSet per resolver (each commits writes on its local
verdict, Resolver.actor.cpp:140-153), min-combine the verdicts
(MasterProxyServer.actor.cpp:492-499), and report TooOld only from resolvers
that actually received read ranges.
"""

import numpy as np
import pytest

from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.types import COMMITTED, TransactionConflictInfo
from foundationdb_tpu.parallel.sharded_resolver import (
    ShardedJaxConflictSet,
    uniform_int_split_keys,
)

N_SHARDS = 4
KEY_BYTES = 8


def make_key(i: int) -> bytes:
    return int(i).to_bytes(KEY_BYTES, "big")


class MultiResolverCpuOracle:
    def __init__(self, split_keys, oldest_version=0):
        self.bounds = []
        lows = [b""] + list(split_keys)
        highs = list(split_keys) + [None]
        self.bounds = list(zip(lows, highs))
        self.engines = [CpuConflictSet(oldest_version) for _ in self.bounds]

    @staticmethod
    def _clip(rng, lo, hi):
        b, e = rng
        cb = max(b, lo)
        ce = e if hi is None else min(e, hi)
        return (cb, ce) if cb < ce else None

    def detect(self, txns, now, new_oldest):
        verdicts = []
        for (lo, hi), eng in zip(self.bounds, self.engines):
            local = []
            for tr in txns:
                rr = [
                    c
                    for r in tr.read_ranges
                    if (c := self._clip(r, lo, hi)) is not None
                ]
                wr = [
                    c
                    for r in tr.write_ranges
                    if (c := self._clip(r, lo, hi)) is not None
                ]
                local.append(
                    TransactionConflictInfo(
                        read_snapshot=tr.read_snapshot,
                        read_ranges=rr,
                        write_ranges=wr,
                    )
                )
            verdicts.append(eng.detect(local, now, new_oldest))
        return [min(v) for v in zip(*verdicts)]


def random_txn(rng, now, *, n_keys=2000, max_ranges=3, snap_back=50):
    def rrange():
        a = rng.integers(0, n_keys)
        b = a + rng.integers(1, 20)
        return (make_key(a), make_key(b))

    return TransactionConflictInfo(
        read_snapshot=now - int(rng.integers(0, snap_back)),
        read_ranges=[rrange() for _ in range(rng.integers(0, max_ranges + 1))],
        write_ranges=[rrange() for _ in range(rng.integers(0, max_ranges + 1))],
    )


@pytest.fixture(scope="module")
def sharded():
    import jax

    split = uniform_int_split_keys(N_SHARDS, 2000, KEY_BYTES)
    return ShardedJaxConflictSet(
        split,
        key_words=3,
        h_cap=1 << 12,
        devices=jax.devices()[:N_SHARDS],
        bucket_mins=(64, 128, 128),  # one compiled bucket for all batches
    )


def test_differential_vs_multiresolver_oracle(sharded):
    rng = np.random.default_rng(7)
    split = uniform_int_split_keys(N_SHARDS, 2000, KEY_BYTES)
    oracle = MultiResolverCpuOracle(split)
    now = 100
    for batch_i in range(12):
        n = int(rng.integers(1, 40))
        txns = [random_txn(rng, now) for _ in range(n)]
        now += int(rng.integers(1, 30))
        new_oldest = max(0, now - 120)
        got = sharded.detect(txns, now, new_oldest)
        want = oracle.detect(txns, now, new_oldest)
        assert got == want, f"batch {batch_i}: {got} != {want}"


def test_single_shard_matches_unsharded():
    import jax

    from foundationdb_tpu.conflict.engine_jax import JaxConflictSet

    rng = np.random.default_rng(3)
    one = ShardedJaxConflictSet(
        [],
        key_words=3,
        h_cap=1 << 12,
        devices=jax.devices()[:1],
        bucket_mins=(32, 64, 64),
    )
    ref = JaxConflictSet(key_words=3, h_cap=1 << 12, bucket_mins=(32, 64, 64))
    now = 50
    for _ in range(6):
        txns = [random_txn(rng, now) for _ in range(int(rng.integers(1, 20)))]
        now += 10
        got = one.detect(txns, now, now - 100)
        want = ref.detect(txns, now, now - 100)
        assert got == want


def test_cross_shard_write_read_conflict(sharded):
    """A write spanning a shard boundary must conflict a later read on the
    far side of the boundary (history really is partitioned, not duplicated)."""
    sharded.clear(0)
    boundary = 2000 // N_SHARDS  # first split point
    w = TransactionConflictInfo(
        read_snapshot=10,
        write_ranges=[(make_key(boundary - 5), make_key(boundary + 5))],
    )
    assert sharded.detect([w], now=20, new_oldest_version=0) == [COMMITTED]
    # stale read entirely inside the second shard, overlapping the write
    r = TransactionConflictInfo(
        read_snapshot=15,
        read_ranges=[(make_key(boundary + 1), make_key(boundary + 3))],
    )
    from foundationdb_tpu.conflict.types import CONFLICT

    assert sharded.detect([r], now=30, new_oldest_version=0) == [CONFLICT]
    # fresh read sees no conflict
    r2 = TransactionConflictInfo(
        read_snapshot=25,
        read_ranges=[(make_key(boundary - 2), make_key(boundary + 3))],
    )
    assert sharded.detect([r2], now=40, new_oldest_version=0) == [COMMITTED]


def test_sharded_divergence_falls_back_to_cpu(sharded, monkeypatch):
    """If any shard's fixpoint diverges, the whole batch re-runs on per-shard
    CPU engines with identical multi-resolver semantics, and the device state
    round-trips exactly (decisions keep matching the oracle afterward)."""
    import jax.numpy as jnp

    sharded.clear(0)
    rng = np.random.default_rng(23)
    split = uniform_int_split_keys(N_SHARDS, 2000, KEY_BYTES)
    oracle = MultiResolverCpuOracle(split)
    real_step_for = type(sharded)._step_for

    def diverged_step_for(self, pb):
        def step(lo, hi, active, hkeys, hvers, hcount, oldest, *rest):
            return (
                hkeys,
                hvers,
                hcount,
                oldest,
                jnp.zeros((hcount.shape[0], pb.txn_cap), jnp.int32),
                jnp.asarray(1, jnp.int32),
                jnp.asarray(0, jnp.int32),
            )

        return step

    now = 100
    for batch_i in range(9):
        patched = 3 <= batch_i < 6
        monkeypatch.setattr(
            type(sharded),
            "_step_for",
            diverged_step_for if patched else real_step_for,
        )
        txns = [random_txn(rng, now) for _ in range(int(rng.integers(1, 30)))]
        now += int(rng.integers(1, 30))
        new_oldest = max(0, now - 120)
        got = sharded.detect(txns, now, new_oldest)
        want = oracle.detect(txns, now, new_oldest)
        assert got == want, f"batch {batch_i} (patched={patched})"
    monkeypatch.setattr(type(sharded), "_step_for", real_step_for)


def test_sharded_global_state_roundtrip(sharded):
    """store_to flattens per-shard step functions into one global CPU engine
    and load_from scatters it back; a round trip must be exact (this is the
    resharding primitive): decisions keep matching the oracle afterward."""
    from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet

    sharded.clear(0)
    rng = np.random.default_rng(31)
    split = uniform_int_split_keys(N_SHARDS, 2000, KEY_BYTES)
    oracle = MultiResolverCpuOracle(split)
    now = 50
    for batch_i in range(6):
        txns = [random_txn(rng, now) for _ in range(int(rng.integers(1, 30)))]
        now += int(rng.integers(1, 20))
        new_oldest = max(0, now - 120)
        got = sharded.detect(txns, now, new_oldest)
        assert got == oracle.detect(txns, now, new_oldest), f"batch {batch_i}"
        if batch_i in (2, 4):
            flat = CpuConflictSet()
            sharded.store_to(flat)
            sharded.load_from(flat)


@pytest.mark.slow  # tier-1 headroom (ISSUE 4): cluster integration (self-described non-differential)
def test_sharded_set_serves_a_real_cluster():
    """END-TO-END: the mesh-sharded device conflict set as the CLUSTER's
    resolver engine — workloads commit through it, long keys (system
    keyspace, idempotence markers) ride the per-shard CPU fallback
    against the SAME sharded state, and the consistency gate passes.
    This is the multichip data plane inside the actual database, not a
    standalone differential (ref: the resolver's ConflictSet swap point,
    Resolver.actor.cpp:140-153)."""
    import jax

    from foundationdb_tpu.flow import set_event_loop, testprobe
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.workloads import (
        ConsistencyChecker,
        CycleWorkload,
        IncrementWorkload,
        run_workloads,
    )

    long_key_before = testprobe.hit_sites.get("sharded_long_key_fallback", 0)
    split = [b"d", b"j", b"q"]  # 4 shards over the byte keyspace
    cs = ShardedJaxConflictSet(
        split,
        key_words=8,  # effective device width = min(32, the
        # conflict_max_device_key_bytes knob = 16): covers this test's
        # user keys and the \xff/SC/ self-conflict keys (13 bytes);
        # anything longer rides the CPU pin by design
        h_cap=1 << 12,
        devices=jax.devices()[:4],
        bucket_mins=(64, 128, 128),
    )
    calls = {"n": 0}
    orig_packed = cs.detect_packed

    def counting_packed(pb, now, new_oldest):
        calls["n"] += 1
        return orig_packed(pb, now, new_oldest)

    cs.detect_packed = counting_packed

    c = SimCluster(seed=777, n_proxies=2, n_storages=2, conflict_set=cs)
    # Phase 1: short keys only — the device path must carry the cluster.
    run_workloads(
        c,
        [CycleWorkload(nodes=5, ops=10, actors=2)],
        timeout_vt=60000.0,
    )
    assert calls["n"] > 0, "device path never dispatched"
    # Phase 2: a write whose key exceeds the digitization width pins
    # authority to the per-shard CPU engines mid-flight; correctness
    # must hold across the handoff (device history flattened into the
    # CPU engines, later batches resolved there).
    db = c.database("longkey")

    async def long_write(tr):
        tr.set(b"longkey/" + b"x" * 40, b"v")

    c.run_until(db.process.spawn(db.run(long_write), "lw"), timeout_vt=600.0)
    run_workloads(
        c,
        [
            IncrementWorkload(counters=3, actors=2, ops=8),
            ConsistencyChecker(),
        ],
        timeout_vt=60000.0,
        quiet=True,
    )
    # …and long keys (e.g. \xff system ranges) took the exact-semantics
    # CPU fallback instead of crashing the resolver.
    assert (
        testprobe.hit_sites.get("sharded_long_key_fallback", 0)
        > long_key_before
    )
    set_event_loop(None)


def test_long_key_pin_abi_consistency():
    """The long-key CPU-authority pin must hold across the WHOLE ABI:
    detect_packed resolves on the pinned engines (not stale device
    state), store_to exports the pinned history, load_from with long
    keys re-pins instead of raising, and clear() drops the pin."""
    import jax

    from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
    from foundationdb_tpu.conflict.engine_jax import PackedBatch
    from foundationdb_tpu.conflict.types import CONFLICT, COMMITTED

    split = [make_key(1000)]
    cs = ShardedJaxConflictSet(
        split, key_words=2, h_cap=1 << 10,
        devices=jax.devices()[:2], bucket_mins=(16, 16, 16),
    )
    LONG = b"L" * 20  # > 8 bytes: beyond kw=2 digitization
    now = 100

    def txn(reads, writes, snap):
        return TransactionConflictInfo(
            read_snapshot=snap, read_ranges=reads, write_ranges=writes
        )

    # Long-key write commits -> pin engages.
    [st] = cs.detect([txn([], [(LONG, LONG + b"\x00")], now)], now, 0)
    assert st == COMMITTED and cs._cpu_engines is not None

    # detect_packed (the bench/dispatch ABI) while pinned must see the
    # pinned history: a short-key write committed NOW through the packed
    # path must conflict a later stale reader.
    pb = PackedBatch.from_transactions(
        [txn([], [(make_key(5), make_key(6))], now + 1)], 2,
        min_txn=16, min_rr=16, min_wr=16,
    )
    out = cs.detect_packed(pb, now + 1, 0)
    assert int(out[0]) == COMMITTED
    [st2] = cs.detect(
        [txn([(make_key(5), make_key(6))], [(make_key(7), make_key(8))], now)],
        now + 2, 0,
    )
    assert st2 == CONFLICT, "write through pinned detect_packed invisible"

    # store_to while pinned exports the pinned state (incl. both writes).
    flat = CpuConflictSet()
    cs.store_to(flat)
    assert flat._range_max(LONG, LONG + b"\x00") == now
    assert flat._range_max(make_key(5), make_key(6)) == now + 1

    # load_from with long keys re-pins (no encode crash), and the loaded
    # history still decides.
    cs2 = ShardedJaxConflictSet(
        split, key_words=2, h_cap=1 << 10,
        devices=jax.devices()[:2], bucket_mins=(16, 16, 16),
    )
    cs2.load_from(flat)
    assert cs2._cpu_engines is not None
    [st3] = cs2.detect(
        [txn([(make_key(5), make_key(6))], [(make_key(9), make_key(10))], now)],
        now + 3, 0,
    )
    assert st3 == CONFLICT

    # clear() drops the pin and wipes history.
    cs2.clear(now + 10)
    assert cs2._cpu_engines is None
    [st4] = cs2.detect(
        [txn([(make_key(5), make_key(6))], [(make_key(9), make_key(10))],
             now + 11)],
        now + 12, now + 10,
    )
    assert st4 == COMMITTED
