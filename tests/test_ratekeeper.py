"""Ratekeeper admission control: GRVs throttle when storage lags the log."""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.knobs import g_knobs
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.ratekeeper import Ratekeeper


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def make_rated_cluster(seed, max_tps):
    old = g_knobs.server.ratekeeper_max_tps
    g_knobs.server.ratekeeper_max_tps = max_tps
    c = SimCluster(seed=seed)
    rk = Ratekeeper(c.master_proc, [c.tlog], [c.storage])
    c.proxy.ratekeeper = rk.interface()
    return c, rk, old


def test_grv_rate_limited():
    c, rk, old = make_rated_cluster(61, max_tps=100.0)
    try:
        db = c.database()
        times = []

        async def go():
            for _ in range(30):
                tr = db.create_transaction()
                await tr.get_read_version()
                times.append(c.loop.now())

        c.run_all([(db, go())], timeout_vt=100.0)
        # 30 GRVs at 100 tps with burst 10: must take >= ~0.2s of virtual
        # time (unlimited would be ~30 network RTTs, ~0.02s).
        elapsed = times[-1] - times[0]
        assert elapsed >= 0.15, elapsed
    finally:
        g_knobs.server.ratekeeper_max_tps = old


def test_rate_drops_when_storage_lags():
    c, rk, old = make_rated_cluster(62, max_tps=100000.0)
    try:
        # Freeze storage by cancelling its update loop: the log keeps
        # committing, storage version stalls, lag grows.
        for t in list(c.storage_proc._tasks):
            if "ss_update" in t.name:
                t.cancel()
        db = c.database()

        async def writes():
            for i in range(5):
                tr = db.create_transaction()
                tr.set(b"k%d" % i, b"v")
                await tr.commit()
                await c.loop.delay(0.3)  # let versions advance + rk sample

        c.run_all([(db, writes())], timeout_vt=100.0)
        assert rk.rate.lag_versions > 0
        assert rk.rate.tps < 100000.0  # throttled below max
    finally:
        g_knobs.server.ratekeeper_max_tps = old


def test_queue_bytes_signal_throttles():
    """Storage queue bytes (input - durable) alone must compress the rate
    (ref: TARGET_BYTES_PER_STORAGE_SERVER spring, Ratekeeper.actor.cpp
    :251-340) — version lag stays small, the byte spring does the work."""
    old_t = g_knobs.server.ratekeeper_target_ss_queue_bytes
    old_s = g_knobs.server.ratekeeper_spring_ss_queue_bytes
    g_knobs.server.ratekeeper_target_ss_queue_bytes = 2_000
    g_knobs.server.ratekeeper_spring_ss_queue_bytes = 2_000
    c, rk, old = make_rated_cluster(63, max_tps=100000.0)
    try:
        db = c.database()

        async def writes():
            for i in range(6):
                tr = db.create_transaction()
                tr.set(b"big%02d" % i, b"x" * 400)
                await tr.commit()
            await c.loop.delay(0.1)  # last write applied
            # Freeze the apply loop (so it stops re-marking everything
            # durable) and inject a queue depth; version lag stays 0, so
            # only the byte spring can be the limiter.
            for t in list(c.storage_proc._tasks):
                if "ss_update" in t.name:
                    t.cancel()
            c.storage.input_bytes = c.storage.durable_bytes + 10_000
            await c.loop.delay(0.4)  # two rk samples

        c.run_all([(db, writes())], timeout_vt=100.0)
        assert rk.rate.worst_ss_queue_bytes > 2_000
        assert rk.rate.tps < 100000.0
        assert rk.rate.limiting == "ss_queue"
        # The batch lane is throttled at least as hard.
        assert rk.rate.batch_tps <= rk.rate.tps
    finally:
        g_knobs.server.ratekeeper_max_tps = old
        g_knobs.server.ratekeeper_target_ss_queue_bytes = old_t
        g_knobs.server.ratekeeper_spring_ss_queue_bytes = old_s


def test_batch_priority_lane_throttles_first():
    """At moderate pressure the default lane keeps most of its rate while
    the batch lane compresses (ref: the separate batch limiter with lower
    targets)."""
    old_t = g_knobs.server.ratekeeper_target_lag_versions
    old_s = g_knobs.server.ratekeeper_spring_lag_versions
    c, rk, old = make_rated_cluster(64, max_tps=1000.0)
    try:
        # Construct moderate lag: above the batch target (frac*target) but
        # below the default target.
        from foundationdb_tpu.server.ratekeeper import Signals

        g_knobs.server.ratekeeper_target_lag_versions = 1000
        g_knobs.server.ratekeeper_spring_lag_versions = 1000
        lag = 1400  # batch target 500, spring 500 -> batch heavily cut
        tps, limiting = rk._limit(Signals(lag=lag), 1.0)
        btps, _ = rk._limit(Signals(lag=lag), 0.5)
        assert tps > 0.5 * 1000.0  # default lane mostly open
        assert btps < tps  # batch lane strictly behind
        assert limiting == "ss_lag"
    finally:
        g_knobs.server.ratekeeper_max_tps = old
        g_knobs.server.ratekeeper_target_lag_versions = old_t
        g_knobs.server.ratekeeper_spring_lag_versions = old_s


def test_batch_priority_grv_deferred_under_throttle():
    """End-to-end: with the batch lane throttled hard (as under pressure),
    batch-priority GRVs are deferred while the default lane flows."""
    from foundationdb_tpu.server.ratekeeper import RateInfo

    c, rk, old = make_rated_cluster(65, max_tps=100000.0)
    try:
        # Pin the lanes: default effectively open, batch ~30 tps.
        for t in list(c.master_proc._tasks):
            if "rk_update" in t.name:
                t.cancel()
        rk.rate = RateInfo(tps=100000.0, batch_tps=30.0)
        db = c.database()
        done = {"default": [], "batch": []}

        async def default_client():
            for _ in range(10):
                tr = db.create_transaction()
                await tr.get_read_version()
                done["default"].append(c.loop.now())

        async def batch_client():
            for _ in range(10):
                tr = db.create_transaction()
                tr.options["priority_batch"] = True
                await tr.get_read_version()
                done["batch"].append(c.loop.now())

        c.run_all(
            [(db, default_client()), (db, batch_client())], timeout_vt=200.0
        )
        assert len(done["default"]) == 10 and len(done["batch"]) == 10
        # Default lane unthrottled; the batch lane paced at ~30 tps must
        # take >= ~0.2s of virtual time and finish well after the default.
        assert done["batch"][-1] - done["batch"][0] >= 0.15
        assert done["default"][-1] < done["batch"][-1]
    finally:
        g_knobs.server.ratekeeper_max_tps = old


def test_spring_monotonicity_every_signal():
    """ISSUE 8 satellite: for EACH signal — ss queue, tlog queue, version
    lag, resolver queue, resolve latency, commit latency — a worse input
    yields a non-increasing TPS limit, and `limiting` names the binding
    signal once the spring engages."""
    from foundationdb_tpu.server.ratekeeper import Signals

    c, rk, old = make_rated_cluster(71, max_tps=10000.0)
    try:
        srv = g_knobs.server
        cases = {
            "ss_lag": lambda v: Signals(
                lag=int(v * srv.ratekeeper_target_lag_versions)
            ),
            "ss_queue": lambda v: Signals(
                ss_queue=int(v * srv.ratekeeper_target_ss_queue_bytes)
            ),
            "tlog_queue": lambda v: Signals(
                tlog_queue=int(v * srv.ratekeeper_target_tlog_queue_bytes)
            ),
            "resolver_queue": lambda v: Signals(
                resolver_queue=int(v * srv.ratekeeper_target_resolver_queue)
            ),
            "resolve_latency": lambda v: Signals(
                resolve_p99=v * srv.ratekeeper_target_resolve_p99
            ),
            "commit_latency": lambda v: Signals(
                commit_p99=v * srv.ratekeeper_target_commit_p99
            ),
        }
        for name, mk in cases.items():
            last = None
            for severity in (0.0, 0.5, 1.0, 1.5, 2.0, 4.0, 8.0, 100.0):
                tps, _limiting = rk._limit(mk(severity), 1.0)
                if last is not None:
                    assert tps <= last, (name, severity, tps, last)
                last = tps
            tps, limiting = rk._limit(mk(2.0), 1.0)
            assert limiting == name, (name, limiting)
            assert tps < srv.ratekeeper_max_tps
        # Degraded device backend: worse state => non-increasing, named.
        tps_ok, _ = rk._limit(Signals(), 1.0)
        tps_deg, limiting = rk._limit(Signals(backend_state="degraded"), 1.0)
        assert tps_deg <= tps_ok and limiting == "backend_degraded"
        assert tps_deg <= (
            srv.ratekeeper_max_tps * srv.ratekeeper_degraded_tps_fraction
        )
        tps_prob, limiting = rk._limit(Signals(backend_state="probing"), 1.0)
        assert tps_prob == tps_deg and limiting == "backend_degraded"
        # Disk free springs the other way: LESS free => non-increasing.
        last = None
        for free in (1 << 62, srv.ratekeeper_target_free_bytes,
                     srv.ratekeeper_target_free_bytes // 2,
                     srv.ratekeeper_min_free_bytes, 0):
            tps, _ = rk._limit(Signals(free=free), 1.0)
            if last is not None:
                assert tps <= last, (free, tps, last)
            last = tps
        # Mid-recovery floor: every role probe failing floors admission.
        tps_rec, limiting = rk._limit(Signals(unreachable=True), 1.0)
        assert tps_rec == srv.ratekeeper_min_tps
        assert limiting == "recovering"
    finally:
        g_knobs.server.ratekeeper_max_tps = old


def test_degraded_cap_tracks_measured_cpu_mirror_tps():
    """With ratekeeper_use_measured_cpu_tps (real mode), the degraded cap
    clamps to 80% of the measured CPU-mirror throughput — admission
    contracts proportionally to what the mirror actually sustains."""
    from foundationdb_tpu.server.ratekeeper import Signals

    c, rk, old = make_rated_cluster(72, max_tps=10000.0)
    old_use = g_knobs.server.ratekeeper_use_measured_cpu_tps
    try:
        g_knobs.server.ratekeeper_use_measured_cpu_tps = True
        # Measured mirror slower than the configured fraction: it binds.
        sig = Signals(backend_state="degraded", cpu_mirror_tps=500.0)
        tps, limiting = rk._limit(sig, 1.0)
        assert limiting == "backend_degraded"
        assert tps == pytest.approx(0.8 * 500.0)
        # Measured mirror faster: the configured fraction binds.
        sig = Signals(backend_state="degraded", cpu_mirror_tps=1e9)
        tps, _ = rk._limit(sig, 1.0)
        assert tps == pytest.approx(
            10000.0 * g_knobs.server.ratekeeper_degraded_tps_fraction
        )
        # Sim default: measurement ignored (wall-derived — replay safety).
        g_knobs.server.ratekeeper_use_measured_cpu_tps = False
        sig = Signals(backend_state="degraded", cpu_mirror_tps=500.0)
        tps, _ = rk._limit(sig, 1.0)
        assert tps == pytest.approx(
            10000.0 * g_knobs.server.ratekeeper_degraded_tps_fraction
        )
    finally:
        g_knobs.server.ratekeeper_max_tps = old
        g_knobs.server.ratekeeper_use_measured_cpu_tps = old_use


def test_degraded_cap_contracts_proportionally_for_sharded_resolvers():
    """Shard-granular fault domains (ISSUE 15): when the degraded
    resolver is mesh-sharded, only shards_degraded of shards_total key
    ranges fell back to their mirrors — the cap contracts by the SICK
    FRACTION, not the whole lane, and more sick shards means a lower
    rate (monotone down to the whole-lane clamp at S/S degraded)."""
    from foundationdb_tpu.server.ratekeeper import Signals

    c, rk, old = make_rated_cluster(73, max_tps=10000.0)
    try:
        frac = g_knobs.server.ratekeeper_degraded_tps_fraction
        whole, limiting = rk._limit(Signals(backend_state="degraded"), 1.0)
        assert limiting == "backend_degraded"
        last = None
        for deg in (1, 2, 4, 7, 8):
            sig = Signals(
                backend_state="degraded", shards_total=8, shards_degraded=deg
            )
            tps, limiting = rk._limit(sig, 1.0)
            assert limiting == "backend_degraded"
            expect = 10000.0 * ((8 - deg) + deg * frac) / 8
            assert tps == pytest.approx(expect), (deg, tps)
            if last is not None:
                assert tps < last, (deg, tps, last)
            last = tps
        # One sick chip out of 8 keeps most of the lane...
        one, _ = rk._limit(
            Signals(backend_state="degraded", shards_total=8,
                    shards_degraded=1), 1.0
        )
        assert one > 0.8 * 10000.0 > whole
        # ...and ALL shards degraded equals the whole-lane clamp.
        allm, _ = rk._limit(
            Signals(backend_state="degraded", shards_total=8,
                    shards_degraded=8), 1.0
        )
        assert allm == pytest.approx(whole)
        # Single-device resolvers (0/0) keep the pre-ISSUE-15 clamp.
        single, _ = rk._limit(Signals(backend_state="degraded"), 1.0)
        assert single == pytest.approx(10000.0 * frac)
    finally:
        g_knobs.server.ratekeeper_max_tps = old


def test_binding_shard_fraction_ignores_healthy_sharded_resolvers():
    """The merge regression: a HEALTHY mesh-sharded resolver's 0/N shard
    detail must not neutralize the whole-lane clamp owed to a DIFFERENT
    degraded single-device resolver — only degraded resolvers
    contribute, and a degraded single-device resolver (no shard detail)
    binds as the whole lane."""
    from foundationdb_tpu.server.interfaces import ResolverSignalsReply
    from foundationdb_tpu.server.ratekeeper import Ratekeeper

    def reply(state, tot=0, deg=0):
        return ResolverSignalsReply(
            backend_state=state, shards_total=tot, shards_degraded=deg
        )

    f = Ratekeeper._binding_shard_fraction
    # Healthy sharded + degraded single-device: whole lane (0/0), NOT 0/8.
    assert f([reply("ok", tot=8), reply("degraded")]) == (0, 0)
    # Degraded sharded alone: its fraction.
    assert f([reply("degraded", tot=8, deg=1), reply("ok")]) == (1, 8)
    # Degraded single-device overrides any proportional detail.
    assert f([reply("degraded", tot=8, deg=1), reply("degraded")]) == (0, 0)
    # Worst sick fraction wins among degraded sharded resolvers.
    assert f([reply("degraded", tot=8, deg=1),
              reply("probing", tot=4, deg=2)]) == (2, 4)
    # Nothing degraded: no shard detail reported.
    assert f([reply("ok", tot=8), reply("ok")]) == (0, 0)


def test_resolver_signals_feed_ratekeeper():
    """End-to-end: the resolver's signal_snapshot + the RPC `signals`
    stream expose queue depth / resolve p99 / backend state, and the
    ratekeeper folds them into RateInfo (and the status qos section)."""
    from foundationdb_tpu.server.status import cluster_status

    c, rk, old = make_rated_cluster(73, max_tps=100000.0)
    try:
        # Wire the resolver signals in (make_rated_cluster predates them).
        rk.resolvers = list(c.resolvers)
        db = c.database()

        async def writes():
            for i in range(20):
                tr = db.create_transaction()
                tr.set(b"rs%02d" % i, b"v")
                await tr.commit()
            await c.loop.delay(0.6)  # two rk samples

        c.run_all([(db, writes())], timeout_vt=100.0)
        snap = c.resolver.signal_snapshot()
        assert snap.backend_state == "ok"
        assert snap.queue_depth == 0  # quiesced
        # An idle sim resolves in ZERO virtual seconds — the signal is
        # that the window is populated, not that latency is nonzero.
        assert c.resolver.metrics.histogram("resolve_seconds").count >= 1
        assert c.resolver.resolve_p99_recent() >= 0.0
        assert rk.rate.backend_state == "ok"

        # The RPC probe answers with the same snapshot shape.
        out = {}

        async def probe():
            out["sig"] = await c.resolver.interface().signals.get_reply(
                db.process, None
            )

        c.run_until(db.process.spawn(probe(), "probe"), timeout_vt=50.0)
        assert out["sig"].backend_state == "ok"
        assert out["sig"].resolve_p99 == snap.resolve_p99

        # Status qos carries the new fields.
        doc = cluster_status(c)
        qos = doc["cluster"]["qos"]
        for key in (
            "worst_resolver_queue_depth",
            "resolve_latency_p99_seconds",
            "commit_latency_p99_seconds",
            "conflict_backend_state",
            "worst_grv_queue_depth",
            "conflict_mirror_divergence",
        ):
            assert key in qos, sorted(qos)
        assert qos["conflict_backend_state"] == "ok"
        assert qos["conflict_mirror_divergence"] == 0
    finally:
        g_knobs.server.ratekeeper_max_tps = old


def test_grv_queue_shed_batch_lane_starves_first():
    """Bounded GRV admission queue (ISSUE 8): beyond the depth bound the
    proxy sheds deterministically — batch-priority requests first with
    batch_transaction_throttled, then default-lane ones with
    proxy_memory_limit_exceeded; both retryable."""
    from foundationdb_tpu.flow.error import FdbError
    from foundationdb_tpu.server.interfaces import (
        GRV_FLAG_PRIORITY_BATCH,
        GetReadVersionRequest,
    )
    from foundationdb_tpu.server.ratekeeper import RateInfo

    old_q = g_knobs.server.ratekeeper_grv_queue_max
    g_knobs.server.ratekeeper_grv_queue_max = 8
    c, rk, old = make_rated_cluster(74, max_tps=100000.0)
    try:
        # Pin a tiny rate so the first iteration's budget wait queues the
        # rest of the burst for one oversized drain.
        for t in list(c.master_proc._tasks):
            if "rk_update" in t.name:
                t.cancel()
        rk.rate = RateInfo(tps=2.0, batch_tps=1.0)
        iface = c.proxy.interface()
        proc = c.net.process("grv_burst")
        results = {"ok": 0, "batch_throttled": 0, "default_shed": 0}

        async def one(flags):
            try:
                await iface.get_consistent_read_version.get_reply(
                    proc, GetReadVersionRequest(flags=flags)
                )
                results["ok"] += 1
            except FdbError as e:
                if e.name == "batch_transaction_throttled":
                    results["batch_throttled"] += 1
                elif e.name == "proxy_memory_limit_exceeded":
                    results["default_shed"] += 1
                else:
                    raise

        async def burst():
            from foundationdb_tpu.flow.eventloop import all_of

            tasks = []
            for i in range(15):
                tasks.append(proc.spawn(one(0), f"d{i}"))
                tasks.append(
                    proc.spawn(one(GRV_FLAG_PRIORITY_BATCH), f"b{i}")
                )
            await all_of(tasks)

        c.run_until(proc.spawn(burst(), "burst"), timeout_vt=400.0)
        assert results["batch_throttled"] > 0, results
        # Batch lane starved harder than the default lane.
        assert results["batch_throttled"] >= results["default_shed"], results
        assert results["ok"] + results["batch_throttled"] + results[
            "default_shed"
        ] == 30
        snap = c.proxy.stats.snapshot()
        assert snap["grv_shed_batch"] == results["batch_throttled"]
        assert snap["grv_shed_default"] == results["default_shed"]
        # Both shed errors are client-retryable (exponential backoff +
        # DeterministicRandom jitter in Transaction.on_error).
        for name in ("batch_transaction_throttled",
                     "proxy_memory_limit_exceeded"):
            assert FdbError(name).is_retryable_in_transaction()
    finally:
        g_knobs.server.ratekeeper_max_tps = old
        g_knobs.server.ratekeeper_grv_queue_max = old_q


def test_saturation_stays_inside_mvcc_window():
    """The 'Done' criterion: a write-saturation burst with a lagging
    storage holds the lag inside the MVCC window — clients see no
    transaction_too_old storm — while sustaining most of the unthrottled
    commit throughput."""
    from foundationdb_tpu.flow.error import FdbError

    c, rk, old = make_rated_cluster(66, max_tps=100000.0)
    try:
        db = c.database()
        stats = {"committed": 0, "too_old": 0}

        async def writer(wid):
            for i in range(25):
                tr = db.create_transaction()
                try:
                    # Read-modify-write: the read can hit too_old if the
                    # MVCC window is overrun.
                    await tr.get(b"sat%02d" % wid)
                    tr.set(b"sat%02d" % wid, b"%d" % i)
                    await tr.commit()
                    stats["committed"] += 1
                except FdbError as e:
                    if e.name == "transaction_too_old":
                        stats["too_old"] += 1
                    else:
                        await tr.on_error(e)

        c.run_all([(db, writer(w)) for w in range(4)], timeout_vt=300.0)
        assert stats["committed"] >= 90  # most of 100 commits landed
        assert stats["too_old"] <= 5, stats
    finally:
        g_knobs.server.ratekeeper_max_tps = old


# ---------------------------------------------------------------------------
# CommitChainSampler direct unit tests (ISSUE 10 satellite): the PR-7
# incremental path — open-chain aging and abandoned-open horizon pruning —
# previously exercised only indirectly through the cluster tests above.
# ---------------------------------------------------------------------------


def _commit_ev(loc, did, t):
    return {"Type": "CommitDebug", "Location": loc, "ID": did, "Time": t}


def _chain_fixture():
    """A fresh in-memory global collector + sampler; returns (collector,
    sampler, emit) with the old collector restored by the caller's
    fixture-free try/finally (tests below use _with_collector)."""
    from foundationdb_tpu.flow.trace import TraceCollector

    return TraceCollector()


def _with_collector(fn):
    from foundationdb_tpu.flow.trace import (
        global_collector,
        set_global_collector,
    )

    old = global_collector()
    col = _chain_fixture()
    set_global_collector(col)
    try:
        fn(col)
    finally:
        set_global_collector(old)


def test_chain_sampler_incremental_window_and_err_close():
    from foundationdb_tpu.server.ratekeeper import CommitChainSampler

    def scenario(col):
        s = CommitChainSampler()
        # Two completed chains: durations 1.0 and 3.0.
        col.events += [
            _commit_ev(s.FROM, "a", 10.0), _commit_ev(s.TO, "a", 11.0),
            _commit_ev(s.FROM, "b", 10.0), _commit_ev(s.TO, "b", 13.0),
        ]
        assert s.sample() == 3.0
        # Incremental: only NEW events are scanned; window accumulates.
        col.events += [
            _commit_ev(s.FROM, "c", 20.0), _commit_ev(s.TO, "c", 25.0),
        ]
        assert s.sample() == 5.0
        assert s._cursor == len(col.events)
        # A failed attempt closes its chain via .Error: it neither enters
        # the completed window nor ages as an open chain.
        col.events += [
            _commit_ev(s.FROM, "fail", 30.0),
            _commit_ev(s.ERR, "fail", 30.5),
        ]
        assert s.sample(now=100.0, horizon=1000.0) == 5.0
        assert "fail" not in s._open

    _with_collector(scenario)


def test_chain_sampler_open_chain_ages_signal():
    """A commit whose Before has no After IS the signal during a grey
    failure: its age max-combines into the p99 while it is wedged, and
    the signal releases the moment the chain completes."""
    from foundationdb_tpu.server.ratekeeper import CommitChainSampler

    def scenario(col):
        s = CommitChainSampler()
        col.events += [
            _commit_ev(s.FROM, "x", 10.0), _commit_ev(s.TO, "x", 10.5),
            _commit_ev(s.FROM, "wedged", 11.0),
        ]
        # Completed window alone says 0.5; the open chain is older.
        assert s.sample(now=20.0, horizon=100.0) == 9.0
        # Still wedged: the signal keeps growing with virtual time.
        assert s.sample(now=31.0, horizon=100.0) == 20.0
        # Without `now` there is no aging — pure completed-window p99.
        assert s.sample() == 0.5
        # The wedge resolves: back to the completed window (which now
        # includes the long commit).
        col.events.append(_commit_ev(s.TO, "wedged", 41.0))
        assert s.sample(now=42.0, horizon=100.0) == 30.0

    _with_collector(scenario)


def test_chain_sampler_horizon_prunes_abandoned_opens():
    """An abandoned chain (client killed mid-commit) cannot hold the
    signal up forever: opens older than the horizon are pruned, and the
    spring releases within one horizon of the stall resolving."""
    from foundationdb_tpu.server.ratekeeper import CommitChainSampler

    def scenario(col):
        s = CommitChainSampler()
        col.events += [
            _commit_ev(s.FROM, "x", 10.0), _commit_ev(s.TO, "x", 10.5),
            _commit_ev(s.FROM, "abandoned", 10.0),
        ]
        # Inside the horizon the open ages the signal...
        assert s.sample(now=12.0, horizon=5.0) == 2.0
        # ...past it the open is pruned: the signal RELEASES.
        assert s.sample(now=16.0, horizon=5.0) == 0.5
        assert "abandoned" not in s._open
        # A late After for a pruned chain is ignored (its Before is
        # gone), so it cannot inject a bogus 30s duration.
        col.events.append(_commit_ev(s.TO, "abandoned", 40.0))
        assert s.sample(now=41.0, horizon=5.0) == 0.5

    _with_collector(scenario)


def test_chain_sampler_open_map_bounded_and_collector_reset():
    from foundationdb_tpu.server.ratekeeper import CommitChainSampler
    from foundationdb_tpu.flow.trace import (
        TraceCollector,
        set_global_collector,
    )

    def scenario(col):
        s = CommitChainSampler()
        # >1024 never-resolving opens: the map drops to 512, oldest
        # first, deterministically (insertion order).
        col.events += [
            _commit_ev(s.FROM, "d%04d" % i, float(i)) for i in range(1100)
        ]
        s.sample()
        assert len(s._open) == 512
        assert "d0000" not in s._open and "d1099" in s._open
        # A swapped (or cleared) collector restarts the incremental scan
        # instead of reading a stale cursor past the end.
        col2 = TraceCollector()
        set_global_collector(col2)
        col2.events += [
            _commit_ev(s.FROM, "n", 1.0), _commit_ev(s.TO, "n", 3.0),
        ]
        assert s.sample() == 2.0
        assert len(s._open) == 0

    _with_collector(scenario)


def test_chain_sampler_returns_none_for_file_backed_collector(tmp_path):
    from foundationdb_tpu.flow.trace import (
        TraceCollector,
        global_collector,
        set_global_collector,
    )
    from foundationdb_tpu.server.ratekeeper import CommitChainSampler

    old = global_collector()
    set_global_collector(TraceCollector(path=str(tmp_path / "t.jsonl")))
    try:
        assert CommitChainSampler().sample(now=1.0, horizon=1.0) is None
    finally:
        global_collector().close()
        set_global_collector(old)
