"""Ratekeeper admission control: GRVs throttle when storage lags the log."""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.knobs import g_knobs
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.ratekeeper import Ratekeeper


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def make_rated_cluster(seed, max_tps):
    old = g_knobs.server.ratekeeper_max_tps
    g_knobs.server.ratekeeper_max_tps = max_tps
    c = SimCluster(seed=seed)
    rk = Ratekeeper(c.master_proc, [c.tlog], [c.storage])
    c.proxy.ratekeeper = rk.interface()
    return c, rk, old


def test_grv_rate_limited():
    c, rk, old = make_rated_cluster(61, max_tps=100.0)
    try:
        db = c.database()
        times = []

        async def go():
            for _ in range(30):
                tr = db.create_transaction()
                await tr.get_read_version()
                times.append(c.loop.now())

        c.run_all([(db, go())], timeout_vt=100.0)
        # 30 GRVs at 100 tps with burst 10: must take >= ~0.2s of virtual
        # time (unlimited would be ~30 network RTTs, ~0.02s).
        elapsed = times[-1] - times[0]
        assert elapsed >= 0.15, elapsed
    finally:
        g_knobs.server.ratekeeper_max_tps = old


def test_rate_drops_when_storage_lags():
    c, rk, old = make_rated_cluster(62, max_tps=100000.0)
    try:
        # Freeze storage by cancelling its update loop: the log keeps
        # committing, storage version stalls, lag grows.
        for t in list(c.storage_proc._tasks):
            if "ss_update" in t.name:
                t.cancel()
        db = c.database()

        async def writes():
            for i in range(5):
                tr = db.create_transaction()
                tr.set(b"k%d" % i, b"v")
                await tr.commit()
                await c.loop.delay(0.3)  # let versions advance + rk sample

        c.run_all([(db, writes())], timeout_vt=100.0)
        assert rk.rate.lag_versions > 0
        assert rk.rate.tps < 100000.0  # throttled below max
    finally:
        g_knobs.server.ratekeeper_max_tps = old
