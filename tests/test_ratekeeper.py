"""Ratekeeper admission control: GRVs throttle when storage lags the log."""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.knobs import g_knobs
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.ratekeeper import Ratekeeper


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def make_rated_cluster(seed, max_tps):
    old = g_knobs.server.ratekeeper_max_tps
    g_knobs.server.ratekeeper_max_tps = max_tps
    c = SimCluster(seed=seed)
    rk = Ratekeeper(c.master_proc, [c.tlog], [c.storage])
    c.proxy.ratekeeper = rk.interface()
    return c, rk, old


def test_grv_rate_limited():
    c, rk, old = make_rated_cluster(61, max_tps=100.0)
    try:
        db = c.database()
        times = []

        async def go():
            for _ in range(30):
                tr = db.create_transaction()
                await tr.get_read_version()
                times.append(c.loop.now())

        c.run_all([(db, go())], timeout_vt=100.0)
        # 30 GRVs at 100 tps with burst 10: must take >= ~0.2s of virtual
        # time (unlimited would be ~30 network RTTs, ~0.02s).
        elapsed = times[-1] - times[0]
        assert elapsed >= 0.15, elapsed
    finally:
        g_knobs.server.ratekeeper_max_tps = old


def test_rate_drops_when_storage_lags():
    c, rk, old = make_rated_cluster(62, max_tps=100000.0)
    try:
        # Freeze storage by cancelling its update loop: the log keeps
        # committing, storage version stalls, lag grows.
        for t in list(c.storage_proc._tasks):
            if "ss_update" in t.name:
                t.cancel()
        db = c.database()

        async def writes():
            for i in range(5):
                tr = db.create_transaction()
                tr.set(b"k%d" % i, b"v")
                await tr.commit()
                await c.loop.delay(0.3)  # let versions advance + rk sample

        c.run_all([(db, writes())], timeout_vt=100.0)
        assert rk.rate.lag_versions > 0
        assert rk.rate.tps < 100000.0  # throttled below max
    finally:
        g_knobs.server.ratekeeper_max_tps = old


def test_queue_bytes_signal_throttles():
    """Storage queue bytes (input - durable) alone must compress the rate
    (ref: TARGET_BYTES_PER_STORAGE_SERVER spring, Ratekeeper.actor.cpp
    :251-340) — version lag stays small, the byte spring does the work."""
    old_t = g_knobs.server.ratekeeper_target_ss_queue_bytes
    old_s = g_knobs.server.ratekeeper_spring_ss_queue_bytes
    g_knobs.server.ratekeeper_target_ss_queue_bytes = 2_000
    g_knobs.server.ratekeeper_spring_ss_queue_bytes = 2_000
    c, rk, old = make_rated_cluster(63, max_tps=100000.0)
    try:
        db = c.database()

        async def writes():
            for i in range(6):
                tr = db.create_transaction()
                tr.set(b"big%02d" % i, b"x" * 400)
                await tr.commit()
            await c.loop.delay(0.1)  # last write applied
            # Freeze the apply loop (so it stops re-marking everything
            # durable) and inject a queue depth; version lag stays 0, so
            # only the byte spring can be the limiter.
            for t in list(c.storage_proc._tasks):
                if "ss_update" in t.name:
                    t.cancel()
            c.storage.input_bytes = c.storage.durable_bytes + 10_000
            await c.loop.delay(0.4)  # two rk samples

        c.run_all([(db, writes())], timeout_vt=100.0)
        assert rk.rate.worst_ss_queue_bytes > 2_000
        assert rk.rate.tps < 100000.0
        assert rk.rate.limiting == "ss_queue"
        # The batch lane is throttled at least as hard.
        assert rk.rate.batch_tps <= rk.rate.tps
    finally:
        g_knobs.server.ratekeeper_max_tps = old
        g_knobs.server.ratekeeper_target_ss_queue_bytes = old_t
        g_knobs.server.ratekeeper_spring_ss_queue_bytes = old_s


def test_batch_priority_lane_throttles_first():
    """At moderate pressure the default lane keeps most of its rate while
    the batch lane compresses (ref: the separate batch limiter with lower
    targets)."""
    old_t = g_knobs.server.ratekeeper_target_lag_versions
    old_s = g_knobs.server.ratekeeper_spring_lag_versions
    c, rk, old = make_rated_cluster(64, max_tps=1000.0)
    try:
        # Construct moderate lag: above the batch target (frac*target) but
        # below the default target.
        g_knobs.server.ratekeeper_target_lag_versions = 1000
        g_knobs.server.ratekeeper_spring_lag_versions = 1000
        lag = 1400  # batch target 500, spring 500 -> batch heavily cut
        tps, limiting = rk._limit(lag, 0, 0, 1 << 62, 1.0)
        btps, _ = rk._limit(lag, 0, 0, 1 << 62, 0.5)
        assert tps > 0.5 * 1000.0  # default lane mostly open
        assert btps < tps  # batch lane strictly behind
        assert limiting == "ss_lag"
    finally:
        g_knobs.server.ratekeeper_max_tps = old
        g_knobs.server.ratekeeper_target_lag_versions = old_t
        g_knobs.server.ratekeeper_spring_lag_versions = old_s


def test_batch_priority_grv_deferred_under_throttle():
    """End-to-end: with the batch lane throttled hard (as under pressure),
    batch-priority GRVs are deferred while the default lane flows."""
    from foundationdb_tpu.server.ratekeeper import RateInfo

    c, rk, old = make_rated_cluster(65, max_tps=100000.0)
    try:
        # Pin the lanes: default effectively open, batch ~30 tps.
        for t in list(c.master_proc._tasks):
            if "rk_update" in t.name:
                t.cancel()
        rk.rate = RateInfo(tps=100000.0, batch_tps=30.0)
        db = c.database()
        done = {"default": [], "batch": []}

        async def default_client():
            for _ in range(10):
                tr = db.create_transaction()
                await tr.get_read_version()
                done["default"].append(c.loop.now())

        async def batch_client():
            for _ in range(10):
                tr = db.create_transaction()
                tr.options["priority_batch"] = True
                await tr.get_read_version()
                done["batch"].append(c.loop.now())

        c.run_all(
            [(db, default_client()), (db, batch_client())], timeout_vt=200.0
        )
        assert len(done["default"]) == 10 and len(done["batch"]) == 10
        # Default lane unthrottled; the batch lane paced at ~30 tps must
        # take >= ~0.2s of virtual time and finish well after the default.
        assert done["batch"][-1] - done["batch"][0] >= 0.15
        assert done["default"][-1] < done["batch"][-1]
    finally:
        g_knobs.server.ratekeeper_max_tps = old


def test_saturation_stays_inside_mvcc_window():
    """The 'Done' criterion: a write-saturation burst with a lagging
    storage holds the lag inside the MVCC window — clients see no
    transaction_too_old storm — while sustaining most of the unthrottled
    commit throughput."""
    from foundationdb_tpu.flow.error import FdbError

    c, rk, old = make_rated_cluster(66, max_tps=100000.0)
    try:
        db = c.database()
        stats = {"committed": 0, "too_old": 0}

        async def writer(wid):
            for i in range(25):
                tr = db.create_transaction()
                try:
                    # Read-modify-write: the read can hit too_old if the
                    # MVCC window is overrun.
                    await tr.get(b"sat%02d" % wid)
                    tr.set(b"sat%02d" % wid, b"%d" % i)
                    await tr.commit()
                    stats["committed"] += 1
                except FdbError as e:
                    if e.name == "transaction_too_old":
                        stats["too_old"] += 1
                    else:
                        await tr.on_error(e)

        c.run_all([(db, writer(w)) for w in range(4)], timeout_vt=300.0)
        assert stats["committed"] >= 90  # most of 100 commits landed
        assert stats["too_old"] <= 5, stats
    finally:
        g_knobs.server.ratekeeper_max_tps = old
