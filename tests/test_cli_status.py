"""fdbcli-equivalent command processor + status doc (ref: fdbcli commands,
Status.actor.cpp clusterGetStatus)."""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.status import cluster_status
from foundationdb_tpu.tools.cli import CliProcessor


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def drive(cluster, db, cli, line):
    async def run():
        return await cli.run_command(line)

    return cluster.loop.run_until(db.process.spawn(run()), timeout_vt=60.0)


def test_cli_crud_and_status():
    c = SimCluster(seed=71)
    db = c.database("cli")
    cli = CliProcessor(c, db)

    assert "ERROR" in drive(c, db, cli, "set k v")[0]  # writemode off
    assert drive(c, db, cli, "writemode on") == []
    assert drive(c, db, cli, "set k v") == ["Committed"]
    assert drive(c, db, cli, "get k") == ["`k' is `v'"]
    assert drive(c, db, cli, "set k2 v2") == ["Committed"]
    rows = drive(c, db, cli, "getrange k")
    assert any("k2" in r for r in rows)
    assert drive(c, db, cli, "clear k") == ["Committed"]
    assert drive(c, db, cli, "get k") == ["`k': not found"]
    status = drive(c, db, cli, "status")
    assert any("fully_recovered" in s for s in status)
    assert any("committed" in s for s in status)
    # unknown command
    assert "unknown command" in drive(c, db, cli, "frobnicate")[0]


def test_cli_explicit_transaction():
    c = SimCluster(seed=72)
    db = c.database("cli")
    cli = CliProcessor(c, db)
    drive(c, db, cli, "writemode on")
    assert drive(c, db, cli, "begin") == ["Transaction started"]
    assert drive(c, db, cli, "set a 1") == ["Staged"]
    assert drive(c, db, cli, "get a") == ["`a' is `1'"]  # RYW inside txn
    assert drive(c, db, cli, "commit")[0].startswith("Committed (")
    assert drive(c, db, cli, "get a") == ["`a' is `1'"]

    drive(c, db, cli, "begin")
    drive(c, db, cli, "set b 2")
    assert drive(c, db, cli, "rollback") == ["Transaction rolled back"]
    assert drive(c, db, cli, "get b") == ["`b': not found"]


def test_status_json_shapes():
    c = SimCluster(seed=73)
    db = c.database()

    async def w(tr):
        tr.set(b"x", b"y")

    c.run_all([(db, db.run(w))])

    async def settle():  # storage applies the log asynchronously post-commit
        await c.loop.delay(0.05)

    c.run_until(db.process.spawn(settle()))
    doc = cluster_status(c)
    assert doc["client"]["database_status"]["available"]
    assert doc["cluster"]["workload"]["transactions"]["committed"] >= 1
    assert doc["cluster"]["logs"]["log_version"] > 0
    assert doc["cluster"]["data"]["total_keys_estimate"] >= 1


def test_status_dynamic_cluster():
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=74)
    db = c.database()

    async def w(tr):
        tr.set(b"x", b"y")

    c.run_all([(db, db.run(w))], timeout_vt=300.0)
    doc = cluster_status(c)
    assert doc["client"]["database_status"]["available"]
    assert doc["client"]["coordinators"]["quorum_reachable"]
    assert doc["cluster"]["recovery_state"]["name"] == "fully_recovered"
    assert set(doc["cluster"]["roles"]) >= {
        "proxy",
        "resolver",
        "sequencer",
        "storage",
        "tlog",
    }


def test_cli_configure_exclude_include():
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.tools.cli import CliProcessor

    c = SimCluster(seed=55)
    db = c.database("cli")
    cli = CliProcessor(c, db)

    def run(line):
        task = db.process.spawn(cli.run_command(line))
        return c.loop.run_until(task, timeout_vt=200.0)

    assert run("configure proxies=2") == ["Configuration changed"]
    assert run("exclude ss9") == ["Excluded 1 server(s)"]
    assert run("exclude") == ["Excluded: ss9"]
    assert run("include") == ["Included"]
    assert run("exclude") == ["Excluded: (none)"]
    assert run("configure bogus") == ["ERROR: expected name=value, got `bogus'"]


def test_status_qos_and_logs_sections():
    """qos/data/logs depth (ref Status.actor.cpp:1690): ratekeeper limits,
    queue bytes, shard counts surface in the doc and the cli rendering."""
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.server.ratekeeper import Ratekeeper
    from foundationdb_tpu.server.status import cluster_status
    from foundationdb_tpu.tools.cli import CliProcessor

    c = SimCluster(seed=71)
    rk = Ratekeeper(c.master_proc, [c.tlog], [c.storage])
    c.proxy.ratekeeper = rk.interface()
    db = c.database()

    async def drive():
        for i in range(5):
            tr = db.create_transaction()
            tr.set(b"s%d" % i, b"v")
            await tr.commit()
        await c.loop.delay(0.3)  # rk sample + proxy rate fetch

    c.run_all([(db, drive())], timeout_vt=100.0)
    doc = cluster_status(c)
    cl = doc["cluster"]
    assert "storage_queue_bytes" in cl["data"]
    assert cl["data"]["partitions_count"] >= 1
    assert cl["logs"]["queue_bytes"] >= 0
    assert cl["qos"]["ratekeeper_enabled"]
    assert cl["qos"]["transactions_per_second_limit"] > 0
    assert "performance_limited_by" in cl["qos"]

    cli = CliProcessor(c, db)
    out = c.run_until(
        db.process.spawn(cli._cmd_status([]), "st"), timeout_vt=100.0
    )
    text = "\n".join(out)
    assert "Ratekeeper" in text and "Shards" in text and "Logs" in text
    set_event_loop(None)


def test_quiet_database_waits_for_drain():
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.server.status import quiet_database

    c = SimCluster(seed=72)
    db = c.database()

    async def drive():
        for i in range(10):
            tr = db.create_transaction()
            tr.set(b"q%d" % i, b"v" * 50)
            await tr.commit()
        await quiet_database(db, c, timeout_vt=30.0)
        # Quiet means queue drained and nothing moving.
        assert c.storage.queue_bytes <= 64 << 10
        return True

    assert c.run_all([(db, drive())], timeout_vt=1000.0)[0]
    set_event_loop(None)


def test_cluster_connection_file_roundtrip(tmp_path):
    """Parse/format/atomic-rewrite of `desc:id@addr,...` (ref:
    ClusterConnectionString, MonitorLeader.actor.cpp:53)."""
    from foundationdb_tpu.client.cluster_file import (
        ClusterConnectionString,
        ClusterFileError,
        read_cluster_file,
        write_cluster_file,
    )

    text = "# my cluster\ntestdb:abc123@10.0.0.1:4500,10.0.0.2:4500\n"
    p = tmp_path / "fdb.cluster"
    p.write_text(text)
    cs = read_cluster_file(str(p))
    assert cs.description == "testdb" and cs.cluster_id == "abc123"
    assert cs.coordinators == ["10.0.0.1:4500", "10.0.0.2:4500"]
    cs.coordinators.append("10.0.0.3:4500")
    cs.cluster_id = "def456"
    write_cluster_file(str(p), cs)
    back = read_cluster_file(str(p))
    assert back == cs
    for bad in (
        "no-at-sign",
        "desc@1.2.3.4:1",
        "d:i@",
        "d:i@nohostport",
        "a:b@1.1.1.1:1\nc:d@2.2.2.2:2",
    ):
        import pytest as _pytest

        with _pytest.raises(ClusterFileError):
            ClusterConnectionString.parse(bad)


def test_cli_backup_driver():
    """backup start/status/restore through the CLI (fdbbackup analog)."""
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=73)
    db = c.database()
    cli = CliProcessor(c, db)
    cli.write_mode = True

    async def scenario():
        await cli.run_command("set bk_a 1")
        out = await cli.run_command("backup start bkdir")
        assert out[0].startswith("Backup started"), out
        await cli.run_command("set bk_b 2")
        await c.loop.delay(0.5)  # agent tails
        st = await cli.run_command("backup status bkdir")
        assert "logged through" in st[0]
        await cli.run_command("set bk_c 3")  # post-restore-point write
        await c.loop.delay(0.5)
        out2 = await cli.run_command("backup restore bkdir")
        assert out2[0].startswith("Restored"), out2
        rows = await cli.run_command("getrange bk_ bk~ 10")
        text = "\n".join(rows)
        assert "bk_a" in text and "bk_b" in text and "bk_c" in text
        return True

    assert c.run_until(
        db.process.spawn(scenario(), "sc"), timeout_vt=20000.0
    )


def test_cli_dr_driver():
    """dr start/status through the CLI (fdbdr analog): the destination
    converges to the source."""
    from foundationdb_tpu.server import SimCluster

    src = SimCluster(seed=74)
    # buggify is process-global: False here runs BOTH clusters fault-free
    # deliberately (this is a convergence test, not a chaos test).
    dst = SimCluster(seed=75, loop=src.loop, buggify=False)
    sdb = src.database("cli_src")
    ddb = dst.database("cli_dst")
    cli = CliProcessor(src, sdb, dst_db=ddb)
    cli.write_mode = True

    async def scenario():
        await cli.run_command("set drk_a 1")
        out = await cli.run_command("dr start")
        assert out[0].startswith("DR started"), out
        await cli.run_command("set drk_b 2")
        for _ in range(200):
            st = await cli.run_command("dr status")
            rows = {}

            async def read(tr):
                rows["r"] = await tr.get_range(b"drk", b"drl")

            await ddb.run(read)
            if dict(rows["r"]).get(b"drk_b") == b"2":
                assert "tailing" in st[0]
                return True
            await src.loop.delay(0.05)
        raise AssertionError(f"DR never converged: {rows['r']}")

    assert src.run_until(
        sdb.process.spawn(scenario(), "sc"), timeout_vt=20000.0
    )


def test_cli_backup_describe_and_expire_preserves_pitr():
    """fdbbackup describe + expire: expiry re-snapshots first, so every
    target at or above the new snapshot stays restorable while redundant
    log chunks are deleted (BackupContainer expireData discipline)."""
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=76)
    db = c.database()
    cli = CliProcessor(c, db)
    cli.write_mode = True

    async def scenario():
        await cli.run_command("set ex_a 1")
        out = await cli.run_command("backup start exdir")
        assert out[0].startswith("Backup started"), out
        # Several tail rounds so multiple log chunks exist.
        for i in range(4):
            await cli.run_command(f"set ex_b{i} {i}")
            await c.loop.delay(0.6)
        agent = cli._backups["exdir"]
        assert agent._chunks >= 2, agent._chunks
        d1 = await cli.run_command("backup describe exdir")
        assert "restorable [" in d1[0], d1

        out = await cli.run_command("backup expire exdir")
        assert out[0].startswith("Expired"), out
        d2 = await cli.run_command("backup describe exdir")
        assert "restorable [" in d2[0], d2

        # Post-expire writes + restore: the re-based snapshot + retained
        # chunks still give a correct image.
        await cli.run_command("set ex_c after")
        await c.loop.delay(0.8)
        out2 = await cli.run_command("backup restore exdir")
        assert out2[0].startswith("Restored"), out2
        rows = await cli.run_command("getrange ex_ ex~ 20")
        text = "\n".join(rows)
        assert "ex_a" in text and "ex_b3" in text and "ex_c" in text
        return True

    assert c.run_until(
        db.process.spawn(scenario(), "sc"), timeout_vt=30000.0
    )


def test_cli_dr_switch():
    """dr switch through the CLI: roles reverse, new-primary writes flow
    back to the locked old primary (fdbdr switch analog)."""
    from foundationdb_tpu.server import SimCluster

    src = SimCluster(seed=78)
    dst = SimCluster(seed=79, loop=src.loop, buggify=False)
    sdb, ddb = src.database("sw_src"), dst.database("sw_dst")
    cli = CliProcessor(src, sdb, dst_db=ddb, dst_cluster=dst)
    cli.write_mode = True

    async def scenario():
        await cli.run_command("set pre 1")
        out = await cli.run_command("dr start")
        assert out[0].startswith("DR started"), out
        await src.loop.delay(0.5)
        out = await cli.run_command("dr switch")
        assert out[0].startswith("Switched"), out

        # Writes now go to the NEW primary and flow back to the old one.
        tr = ddb.create_transaction()
        tr.set(b"after_switch", b"yes")
        await tr.commit()
        for _ in range(200):
            got = {}

            async def check(t):
                t.options["lock_aware"] = True
                got["v"] = await t.get(b"after_switch")

            await sdb.run(check)
            if got["v"] == b"yes":
                return True
            await src.loop.delay(0.05)
        return False

    assert src.run_until(
        sdb.process.spawn(scenario(), "sc"), timeout_vt=30000.0
    )


def test_cli_backup_restore_to_timestamp():
    """backup restore --timestamp=T maps T through the TimeKeeper samples
    to a version and PITR-restores there (ref: fdbbackup restore
    --timestamp, timeKeeperVersionFromDatetime).  Samples are written the
    way the CC's timekeeper writes them; an uncovered time errors."""
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.server.system_keys import time_keeper_key

    c = SimCluster(seed=76)
    db = c.database()
    cli = CliProcessor(c, db)
    cli.write_mode = True

    async def scenario():
        loop = c.loop
        await cli.run_command("set ts_a early")
        out = await cli.run_command("backup start tsdir")
        assert out[0].startswith("Backup started"), out
        await loop.delay(0.5)

        # TimeKeeper sample at the mark (what the CC writes each tick).
        async def sample(tr):
            tr.options["access_system_keys"] = True
            v = await tr.get_read_version()
            tr.set(time_keeper_key(int(loop.now())), b"%d" % v)

        await db.run(sample)
        t_mark = loop.now()
        await loop.delay(1.5)
        await cli.run_command("set ts_a late")
        await cli.run_command("set ts_b post-mark")
        await loop.delay(0.5)  # agent tails past the late writes

        out2 = await cli.run_command(
            f"backup restore tsdir --timestamp={t_mark}"
        )
        assert out2[0].startswith("Restored"), out2
        rows = await cli.run_command("getrange ts_ ts~ 10")
        text = "\n".join(rows)
        assert "early" in text and "late" not in text, rows
        assert "ts_b" not in text, rows

        # A pre-sample timestamp is loudly unmappable.
        out3 = await cli.run_command("backup restore tsdir --timestamp=-5")
        assert out3[0].startswith("ERROR"), out3
        return True

    assert c.run_until(
        db.process.spawn(scenario(), "sc"), timeout_vt=20000.0
    )


def test_cli_consistencycheck():
    """consistencycheck: OK on a healthy replicated cluster; reports
    INCONSISTENT (with the diff) when a replica is forced divergent."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=78, n_workers=7, n_storages=2)
    db = c.database()
    cli = CliProcessor(c, db)
    cli.write_mode = True

    async def scenario():
        for i in range(10):
            await cli.run_command(f"set cc_{i:02d} v{i}")
        # Retry through the post-seed settling window (stale location
        # caches answer wrong_shard_server until the map propagates).
        out = ["unset"]
        for _ in range(100):
            out = await cli.run_command("consistencycheck")
            if out[0].startswith("OK:"):
                break
            await c.loop.delay(0.1)
        assert out[0].startswith("OK:"), out
        # Force divergence in one replica's window state.
        victims = [w.roles["storage"] for w in c.workers
                   if "storage" in w.roles]
        assert len(victims) >= 2
        v = victims[1]
        v.store.set(b"cc_03", b"DIVERGED", v.version.get(), 0)
        out2 = await cli.run_command("consistencycheck")
        assert out2[0].startswith("INCONSISTENT"), out2
        return True

    assert c.run_until(
        db.process.spawn(scenario(), "sc"), timeout_vt=20000.0
    )
