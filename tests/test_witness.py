"""Conflict provenance gates (ISSUE 17): per-abort witnesses end-to-end.

The witness rule — (conflicting write version, losing read-range
ordinal) for every CONFLICT verdict, None otherwise; phase-1 conflicts
name the FIRST conflicting read range and the history step function's
range max over it, intra-batch conflicts name the first range
intersecting an earlier committed writer and report `now` — must be
BIT-IDENTICAL across every arm that can decide a batch: the CPU chunked
mirror, the flat CPU engine, the device program (XLA and Pallas kernels,
flat and tiered history), the shard_map sharded step, and the brute
force reimplemented here from scratch.  Faulted streams (breaker open
mid-batch, mirror replay) must report the same provenance as a
fault-free run, and the operator surfaces built on it — the structured
not_committed cause, the client retry hint, `cli contention`, and the
soak contention block — must be deterministic under same-seed replay.

Shape discipline (1-core CI host): key_words=3 + bucket_mins=(32,128,64)
with h_cap in {1<<9, 1<<10} and the test_kernels sharded splits — the
same static shapes the other device suites compile, so XLA's in-process
jit cache makes this module's marginal compile cost near zero.
"""

import json

import pytest

from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.engine_cpu_flat import FlatCpuConflictSet
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.types import (
    COMMITTED,
    CONFLICT,
    TOO_OLD,
    TransactionConflictInfo as T,
)
from foundationdb_tpu.flow import DeterministicRandom, set_event_loop
from foundationdb_tpu.flow.error import FdbError
from foundationdb_tpu.flow.knobs import g_knobs

BUCKETS = (32, 128, 64)


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def k(i: int) -> bytes:
    return b"%08d" % i


def _random_stream(seed, keyspace, batches, txns_per_batch, snap_lag=25):
    rng = DeterministicRandom(seed)
    version = 10
    out = []
    for _ in range(batches):
        txns = []
        for _ in range(rng.random_int(1, txns_per_batch + 1)):
            tr = T(read_snapshot=max(0, version - rng.random_int(0, snap_lag)))
            for _ in range(rng.random_int(0, 4)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
                tr.read_ranges.append((k(a), k(b)))
            for _ in range(rng.random_int(0, 3)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 10))
                tr.write_ranges.append((k(a), k(b)))
            txns.append(tr)
        now = version + rng.random_int(1, 10)
        out.append((txns, now, max(0, version - snap_lag)))
        version = now
    return out


def _brute_force(stream):
    """Witness + verdicts recomputed from scratch — independent of both
    the oracle and the engines (its own overlap test, its own history
    walk) so a shared bug cannot hide."""
    history = []  # (begin, end, version)
    oldest = 0
    out = []

    def overlaps(a, b):
        return a[0] < b[1] and b[0] < a[1]

    for txns, now, new_oldest in stream:
        statuses, witness = [], []
        batch_writes = []
        for tr in txns:
            if tr.read_snapshot < oldest and tr.read_ranges:
                statuses.append(TOO_OLD)
                witness.append(None)
                continue
            wtn = None
            for i, r in enumerate(tr.read_ranges):
                hits = [v for (b, e, v) in history if overlaps(r, (b, e))]
                if any(v > tr.read_snapshot for v in hits):
                    wtn = (max(hits), i)
                    break
            if wtn is None:
                for i, r in enumerate(tr.read_ranges):
                    if any(overlaps(r, w) for w in batch_writes):
                        wtn = (now, i)
                        break
            witness.append(wtn)
            if wtn is None:
                statuses.append(COMMITTED)
                batch_writes.extend(tr.write_ranges)
            else:
                statuses.append(CONFLICT)
        history.extend((b, e, now) for (b, e) in batch_writes)
        if new_oldest > oldest:
            oldest = new_oldest
            history = [h for h in history if h[2] >= oldest]
        out.append((statuses, witness))
    return out


# ---------------------------------------------------------------------------
# 1. the rule itself
# ---------------------------------------------------------------------------


def test_witness_rule_handcrafted():
    """Phase-1 names the FIRST conflicting read range and the range max;
    intra-batch names the first range under an earlier committed writer
    and reports `now`; TOO_OLD and COMMITTED report None."""
    cs = CpuConflictSet()
    assert cs.detect(
        [T(read_snapshot=0, write_ranges=[(k(10), k(20))])], 100, 0
    ) == [COMMITTED]
    assert cs.last_witness == [None]
    s = cs.detect(
        [
            # range 0 misses, range 1 conflicts -> ordinal 1, version 100
            T(read_snapshot=99,
              read_ranges=[(k(30), k(31)), (k(15), k(16)), (k(12), k(13))]),
            T(read_snapshot=100, read_ranges=[(k(15), k(16))]),  # strict >
        ],
        101,
        0,
    )
    assert s == [CONFLICT, COMMITTED]
    assert cs.last_witness == [(100, 1), None]
    # Intra-batch: t0 writes x, t1 reads (y-miss, x-hit) -> (now, 1).
    s = cs.detect(
        [
            T(read_snapshot=101, write_ranges=[(b"x", b"x\x00")]),
            T(read_snapshot=101,
              read_ranges=[(b"y", b"y\x00"), (b"x", b"x\x00")]),
        ],
        110,
        0,
    )
    assert s == [COMMITTED, CONFLICT]
    assert cs.last_witness == [None, (110, 1)]
    # TOO_OLD: no witness (there is no specific conflicting write).
    old = CpuConflictSet(oldest_version=50)
    assert old.detect(
        [T(read_snapshot=10, read_ranges=[(k(1), k(2))])], 60, 50
    ) == [TOO_OLD]
    assert old.last_witness == [None]


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_witness_cpu_engines_match_brute_force(seed):
    """Chunked mirror == flat engine == oracle == from-scratch brute
    force, witnesses AND verdicts, across random streams."""
    stream = _random_stream(seed, 40, batches=30, txns_per_batch=10)
    want = _brute_force(stream)
    for eng in (CpuConflictSet(), FlatCpuConflictSet(), OracleConflictSet()):
        got = []
        for txns, now, nov in stream:
            statuses = eng.detect(txns, now, nov)
            got.append((statuses, list(eng.last_witness)))
        assert got == want, type(eng).__name__


# ---------------------------------------------------------------------------
# 2. device differential: flat/tiered x kernels on/off
# ---------------------------------------------------------------------------


def _run_device(stream, monkeypatch, kernels: bool, tiered: bool):
    from foundationdb_tpu.conflict.engine_jax import JaxConflictSet

    monkeypatch.setenv("FDB_TPU_KERNELS", "1" if kernels else "0")
    if tiered:
        monkeypatch.setenv("FDB_TPU_HISTORY", "tiered")
        monkeypatch.setenv("FDB_TPU_DELTA_CAP", "512")
        monkeypatch.setenv("FDB_TPU_EVICT_EVERY", "3")
    else:
        monkeypatch.delenv("FDB_TPU_HISTORY", raising=False)
    cs = JaxConflictSet(key_words=3, h_cap=1 << 10, bucket_mins=BUCKETS)
    assert cs._use_kernels is kernels and cs.tiered is tiered
    return [
        (cs.detect(txns, now, nov), list(cs.last_witness))
        for txns, now, nov in stream
    ]


@pytest.mark.parametrize("kernels", [False, True], ids=["xla", "kernels"])
@pytest.mark.parametrize("tiered", [False, True], ids=["flat", "tiered"])
@pytest.mark.parametrize("seed", [11, 23, 47])
def test_witness_device_differential(monkeypatch, seed, tiered, kernels):
    """The tentpole gate: the device program's witness (decoded through
    the dispatch ticket) is bit-identical to the CPU reference and the
    brute force, in both the XLA and Pallas arms, flat and tiered."""
    stream = _random_stream(seed, 50, batches=8, txns_per_batch=8)
    got = _run_device(stream, monkeypatch, kernels=kernels, tiered=tiered)
    assert got == _brute_force(stream)


# One seed rides tier-1; the other two are slow-marked — each seed pays
# two uncompiled-cached ShardedJaxConflictSet builds (~35s apiece on the
# 1-core host), which busts the tier-1 budget at three seeds.  The full
# >=3-seed matrix runs under `-m slow` (and the flat/tiered device
# differential above keeps all three seeds in tier-1: JaxConflictSet
# compiles ARE cached across instances).
@pytest.mark.parametrize(
    "seed",
    [5,
     pytest.param(19, marks=pytest.mark.slow),
     pytest.param(31, marks=pytest.mark.slow)],
)
def test_witness_sharded_differential(monkeypatch, seed):
    """The shard_map step: per-shard witnesses against clipped views,
    min-ordinal/max-version combined and translated back to the
    transaction's ORIGINAL read-range ordinals — kernels on == off ==
    a per-shard oracle combined by the same (host-twin) rule."""
    from foundationdb_tpu.parallel.sharded_resolver import (
        ShardedJaxConflictSet,
        _combine_witness,
        _translate_witness,
    )

    stream = _random_stream(seed, 60, batches=8, txns_per_batch=8)
    splits = [k(20), k(40)]

    def run(kernels):
        monkeypatch.setenv("FDB_TPU_KERNELS", "1" if kernels else "0")
        cs = ShardedJaxConflictSet(
            splits, key_words=3, h_cap=1 << 9, bucket_mins=BUCKETS,
        )
        return [
            (cs.detect(txns, now, nov), list(cs.last_witness))
            for txns, now, nov in stream
        ]

    # Reference: clip per shard, witness per shard via the oracle,
    # translate ordinals, combine — the multi-resolver semantic.
    def clip(rng, lo, hi):
        b, e = rng
        cb = max(b, lo)
        ce = e if hi is None else min(e, hi)
        return (cb, ce) if cb < ce else None

    lows = [b""] + splits
    highs = splits + [None]
    engines = [OracleConflictSet() for _ in lows]
    want = []
    for txns, now, nov in stream:
        parts, verdicts = [], []
        for (lo, hi), eng in zip(zip(lows, highs), engines):
            local, rmap = [], []
            for tr in txns:
                rr, rm = [], []
                for i, r in enumerate(tr.read_ranges):
                    c = clip(r, lo, hi)
                    if c is not None:
                        rr.append(c)
                        rm.append(i)
                wr = [c for r in tr.write_ranges
                      if (c := clip(r, lo, hi)) is not None]
                local.append(T(read_snapshot=tr.read_snapshot,
                               read_ranges=rr, write_ranges=wr))
                rmap.append(rm)
            verdicts.append(eng.detect(local, now, nov))
            parts.append(_translate_witness(eng.last_witness, rmap))
        statuses = [min(v) for v in zip(*verdicts)]
        want.append((statuses, _combine_witness(parts, statuses)))

    on = run(True)
    assert on == run(False)
    assert on == want


# ---------------------------------------------------------------------------
# 3. faulted streams: breaker open mid-stream, mirror replay
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [3, 5, 9])
def test_witness_through_faults_matches_fault_free(monkeypatch, seed):
    """Scripted dispatch faults open the breaker mid-stream (including
    the first half-open probe): the batches the mirror absorbs and the
    replayed recovery batches report witnesses BIT-IDENTICAL to a
    fault-free brute-force run, and a same-seed faulted rerun is
    byte-identical — the differential gate extended from verdicts to
    witnesses."""
    from foundationdb_tpu.conflict.api import ConflictSet
    from foundationdb_tpu.conflict.device_faults import DeviceFaultInjector

    monkeypatch.setenv("FDB_TPU_KERNELS", "0")
    stream = _random_stream(seed, 50, batches=14, txns_per_batch=8)

    def run():
        inj = DeviceFaultInjector()
        for at in (4, 5, 6, 7):  # 3 consecutive opens + a faulted probe
            inj.script("dispatch", at=at)
        cs = ConflictSet(backend="jax", key_words=3, h_cap=1 << 10,
                         bucket_mins=BUCKETS, fault_injector=inj)
        out = []
        for txns, now, nov in stream:
            b = cs.new_batch()
            for t in txns:
                b.add_transaction(t)
            statuses = b.detect_conflicts(now, nov)
            out.append((statuses, list(cs.last_witness)))
        return out, cs.device_metrics()

    got, dm = run()
    assert got == _brute_force(stream)
    assert dm["counters"]["device_faults"] >= 3  # the breaker really opened
    got2, dm2 = run()
    assert got2 == got
    assert json.dumps(dm2["breaker"]) == json.dumps(dm["breaker"])


def test_witness_off_surfaces_empty(monkeypatch):
    """FDB_TPU_WITNESS=0: engines still decide identically but the
    surface reports no witnesses — last_witness is [] on the api set."""
    from foundationdb_tpu.conflict.api import ConflictSet

    monkeypatch.setenv("FDB_TPU_WITNESS", "0")
    cs = ConflictSet(backend="cpu")
    b = cs.new_batch()
    b.add_transaction(T(read_snapshot=0, write_ranges=[(k(1), k(2))]))
    b.detect_conflicts(10, 0)
    b2 = cs.new_batch()
    b2.add_transaction(T(read_snapshot=5, read_ranges=[(k(1), k(2))]))
    assert b2.detect_conflicts(20, 0) == [CONFLICT]
    assert cs.last_witness == []


# ---------------------------------------------------------------------------
# 4. wire + proxy + client: the structured cause and the retry hint
# ---------------------------------------------------------------------------


def _lost_conflict(c, db):
    """Run a read-modify-write race: returns (loser FdbError, winner's
    commit version).  The loser read before the winner committed."""
    out = {}

    async def go():
        t1 = db.create_transaction()
        await t1.get(b"wk")
        t2 = db.create_transaction()
        t2.set(b"wk", b"winner")
        out["win_version"] = await t2.commit()
        t1.set(b"wk", b"loser")
        try:
            await t1.commit()
        except FdbError as e:
            out["err"] = e
            out["tr"] = t1

    c.run_until(db.process.spawn(go(), "race"), timeout_vt=500.0)
    return out


def test_structured_not_committed_cause():
    """The proxy decodes the winning resolver's witness into a
    structured cause: the conflicting write version, the exact key
    range, and the batch's resolve version as the safe retry point."""
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=101)
    out = _lost_conflict(c, c.database())
    e = out["err"]
    assert e.name == "not_committed"
    d = e.detail
    assert isinstance(d, dict), d
    assert d["version"] == out["win_version"]
    assert d["retry_version"] >= out["win_version"]
    assert d["range"] == (b"wk", b"wk\x00")


def test_structured_cause_cross_resolver_boundary():
    """A conflict whose read spans resolver boundaries still names the
    conflicting range — decoded against the CLIPPED per-resolver view
    the witness ordinal refers to."""
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=56, n_resolvers=4)
    db1, db2 = c.database(), c.database()
    out = {}

    def make(db, me, key):
        async def go():
            tr = db.create_transaction()
            try:
                await tr.get_range(b"\x10", b"\xf0", limit=5)
                tr.set(key, b"x")
                await tr.commit()
                out[me] = "committed"
            except FdbError as e:
                out[me] = e

        return go()

    c.run_all(
        [(db1, make(db1, 1, b"\x20k")), (db2, make(db2, 2, b"\xe0k"))],
        timeout_vt=500.0,
    )
    err = next(v for v in out.values() if isinstance(v, FdbError))
    d = err.detail
    assert isinstance(d, dict) and d["range"] is not None
    b, e_ = d["range"]
    # The named range is inside the loser's read and covers the winner's
    # write — the clipped per-resolver view decoded back to key bytes.
    assert b"\x10" <= b < e_ <= b"\xf0"
    win_key = b"\x20k" if out[1] == "committed" else b"\xe0k"
    assert b <= win_key < e_, (d, out)


def test_retry_hint_seeds_read_version():
    """on_error with a structured cause seeds the next attempt's read
    version at retry_version (no fresh GRV) and skips the blind backoff;
    FDB_TPU_WITNESS_RETRY=0 keeps the blind path."""
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=102)
    db = c.database()
    out = _lost_conflict(c, db)
    e, tr = out["err"], out["tr"]

    async def retry():
        await tr.on_error(e)
        out["seeded"] = tr._read_version
        out["rv"] = await tr.get_read_version()

    c.run_until(db.process.spawn(retry(), "retry"), timeout_vt=500.0)
    assert out["seeded"] == e.detail["retry_version"]
    assert out["rv"] == e.detail["retry_version"]  # no GRV round-trip
    assert db.witness_hint_retries == 1


def test_retry_hint_disabled_stays_blind(monkeypatch):
    from foundationdb_tpu.server import SimCluster

    monkeypatch.setenv("FDB_TPU_WITNESS_RETRY", "0")
    c = SimCluster(seed=103)
    db = c.database()
    out = _lost_conflict(c, db)
    e, tr = out["err"], out["tr"]

    async def retry():
        await tr.on_error(e)
        out["seeded"] = tr._read_version

    c.run_until(db.process.spawn(retry(), "retry"), timeout_vt=500.0)
    assert out["seeded"] is None
    assert getattr(db, "witness_hint_retries", 0) == 0


def test_witness_off_bare_not_committed(monkeypatch):
    """FDB_TPU_WITNESS=0: the reply carries no witnesses, the proxy
    sends the reference's bare not_committed (detail None), and the
    client falls back to the blind retry — the wire format is
    backward-compatible in both directions."""
    from foundationdb_tpu.server import SimCluster

    monkeypatch.setenv("FDB_TPU_WITNESS", "0")
    c = SimCluster(seed=104)
    db = c.database()
    out = _lost_conflict(c, db)
    e, tr = out["err"], out["tr"]
    assert e.name == "not_committed" and e.detail is None

    async def retry():
        await tr.on_error(e)
        out["seeded"] = tr._read_version

    c.run_until(db.process.spawn(retry(), "retry"), timeout_vt=500.0)
    assert out["seeded"] is None


# ---------------------------------------------------------------------------
# 5. resolver sample decay (the satellite fix) + contention ring
# ---------------------------------------------------------------------------


def test_topk_decays_on_real_batches_only():
    """The decay clock is conflict-bearing batches, never idle time:
    conflict-free traffic and quiescent virtual time leave the top-K
    gauge byte-identical; the decay_batches-th REAL batch halves it."""
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=105)
    r = c.resolver
    gauge = r.metrics.gauge("conflict_witness_topk")
    every = int(g_knobs.server.resolver_witness_decay_batches)
    txn = T(read_snapshot=0, read_ranges=[(b"a", b"b")])
    for _ in range(every - 1):
        r._witness_record([txn], [CONFLICT], [(5, 0)], version=10)
    assert json.loads(gauge.value) == [["61", "62", every - 1]]
    before = gauge.value

    # Conflict-free live traffic + idle virtual time: no decay tick.
    db = c.database()

    async def quiet():
        for i in range(5):
            tr = db.create_transaction()
            tr.set(b"q%d" % i, b"v")
            await tr.commit()
        await c.loop.delay(300.0)

    c.run_until(db.process.spawn(quiet(), "quiet"), timeout_vt=5000.0)
    assert gauge.value == before, "idle/conflict-free traffic decayed top-K"
    assert r._witness_batches == every - 1

    # The next REAL conflict batch crosses the boundary: counts halve
    # (the new abort lands, then 64 // 2).
    r._witness_record([txn], [CONFLICT], [(5, 0)], version=11)
    assert json.loads(gauge.value) == [["61", "62", every // 2]]


def test_contention_ring_and_conflict_witness_block():
    """_witness_record appends one timeline entry per conflict-bearing
    batch — version, batch size, abort count, per-range counts — and
    conflict_witness() surfaces ring + streak + spike counters."""
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=106)
    r = c.resolver
    txns = [
        T(read_snapshot=0, read_ranges=[(b"a", b"b")]),
        T(read_snapshot=0, read_ranges=[(b"c", b"d")]),
        T(read_snapshot=0, write_ranges=[(b"e", b"f")]),
    ]
    r._witness_record(
        txns, [CONFLICT, CONFLICT, COMMITTED], [(5, 0), (7, 0), None],
        version=42,
    )
    cw = r.conflict_witness()
    assert cw["aborts"] == 0  # counter is _complete_resolve's; ring is ours
    (entry,) = cw["contention"]["timeline"]
    assert entry == {
        "version": 42,
        "batch": 3,
        "aborted": 2,
        "ranges": [["61", "62", 1], ["63", "64", 1]],
    }
    assert cw["contention"]["witness_batches"] == 1
    assert cw["contention"]["spikes"] == 0


# ---------------------------------------------------------------------------
# 6. the operator surfaces: cli contention, status qos, soak
# ---------------------------------------------------------------------------


def _fresh_globals():
    from foundationdb_tpu.flow.flight_recorder import (
        FlightRecorder,
        set_global_flight_recorder,
    )
    from foundationdb_tpu.flow.spans import SpanHub, set_global_span_hub
    from foundationdb_tpu.flow.timeseries import (
        TimeSeriesHub,
        set_global_timeseries,
    )

    set_global_flight_recorder(FlightRecorder())
    set_global_span_hub(SpanHub())
    set_global_timeseries(TimeSeriesHub())


def _contention_cli_run(seed):
    """Hot-key contention on a fresh 2-resolver cluster, then `cli
    contention --format=json` — returns the exact text."""
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.tools.cli import CliProcessor

    _fresh_globals()
    c = SimCluster(seed=seed, n_resolvers=2)
    db = c.database()

    async def one(db, i):
        tr = db.create_transaction()
        while True:
            try:
                await tr.get(b"hot")
                tr.set(b"hot", b"%d" % i)
                await tr.commit()
                return
            except FdbError as e:
                await tr.on_error(e)

    c.run_all([(db, one(db, i)) for i in range(12)], timeout_vt=500.0)

    async def show(db):
        cli = CliProcessor(c, db)
        return await cli.run_command("contention --format=json")

    lines = c.run_until(db.process.spawn(show(db), "cli"), timeout_vt=60.0)
    set_event_loop(None)
    return "\n".join(lines)


def test_cli_contention_same_seed_byte_identical():
    """`cli contention --format=json` joins witness timelines, span
    percentiles, and spike captures into one canonical document —
    byte-identical across same-seed runs, divergent across seeds."""
    a = _contention_cli_run(7)
    b = _contention_cli_run(7)
    assert a == b
    doc = json.loads(a)
    (res,) = [r for r in doc["resolvers"].values() if r["aborts"] > 0]
    assert res["witness_batches"] > 0 and res["topk"]
    (rng_key, slot) = next(iter(res["ranges"].items()))
    assert ".." in rng_key and slot["aborts"] > 0 and slot["timeline"]
    # The span join is present for every resolver, exact stage names.
    assert set(doc["spans"]) == set(doc["resolvers"])
    for stages in doc["spans"].values():
        assert "resolve_batch" in stages
    assert _contention_cli_run(8) != a


def test_status_qos_contention_block():
    """status json carries the merged contention block: max streak,
    summed spikes, and the cross-resolver recent timeline."""
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.server.status import cluster_status

    _fresh_globals()
    c = SimCluster(seed=9)
    db = c.database()

    async def one(db, i):
        tr = db.create_transaction()
        while True:
            try:
                await tr.get(b"hot")
                tr.set(b"hot", b"%d" % i)
                await tr.commit()
                return
            except FdbError as e:
                await tr.on_error(e)

    c.run_all([(db, one(db, i)) for i in range(8)], timeout_vt=500.0)
    qos = cluster_status(c)["cluster"]["qos"]
    ct = qos["contention"]
    assert ct["timeline_batches"] > 0
    assert ct["recent"] and all("version" in t for t in ct["recent"])
    assert qos["conflict_witness_aborts"] > 0


def test_soak_contention_block_spike_capture_and_replay():
    """The high-contention Zipf soak arm: the report's contention block
    is populated (witness batches, per-range timeline, decayed top-K,
    hint-guided retries), the flight recorder's contention_spike capture
    fires EXACTLY once (cooldown suppresses the sustained tail), and two
    same-seed runs are byte-identical."""
    from foundationdb_tpu.workloads.soak import contention_config, run_soak

    old = g_knobs.server.resolver_contention_spike_batches
    g_knobs.server.resolver_contention_spike_batches = 3
    try:
        def go():
            return run_soak(contention_config(
                minutes=0.05, peak_tps=100.0, seed=3, witness_retry=True,
            ))

        rep = go()
        ct = rep["contention"]
        assert ct["witness_retry"] is True
        assert ct["hint_retries"] > 0
        (res,) = [r for r in ct["resolvers"].values() if r["aborts"] > 0]
        assert res["witness_batches"] > 0 and res["topk"] and res["timeline"]
        # Exactly one capture: the spike is sustained, the cooldown
        # swallows every re-trigger inside this (short) run.
        assert ct["spike_captures"] == 1
        caps = [c for c in rep["flight_recorder"]["captures"]
                if c["trigger"] == "contention_spike"]
        assert len(caps) == 1
        assert caps[0]["detail"]["streak"] >= 3
        assert res["spikes"] == 1
        assert json.dumps(go(), sort_keys=True) == json.dumps(
            rep, sort_keys=True
        )
    finally:
        g_knobs.server.resolver_contention_spike_batches = old


@pytest.mark.slow
@pytest.mark.soak
def test_contention_ab_guided_beats_blind():
    """THE acceptance arm (slow-marked): witness-guided retry — seed the
    retry read version at the abort's resolve version, skip the blind
    backoff — beats blind retry on goodput under the high-contention
    Zipf load, with fewer conflict aborts per committed txn."""
    from foundationdb_tpu.workloads.soak import run_contention_ab

    ab = run_contention_ab(minutes=0.1, peak_tps=100.0, seed=3)
    g, b = ab["guided"], ab["blind"]
    assert g["hint_retries"] > 0 and b["hint_retries"] == 0
    assert ab["goodput_ratio"] >= 1.0, ab
    assert g["goodput_tps"] >= b["goodput_tps"], ab
    assert g["conflicted"] < b["conflicted"], ab


def test_witness_env_flags_registered():
    """ENV001 satellite: the witness flags are declared in g_env with
    defaults and help text."""
    from foundationdb_tpu.flow.knobs import g_env

    decl = g_env.declared()
    for name in ("FDB_TPU_WITNESS", "FDB_TPU_WITNESS_RETRY"):
        default, help_ = decl[name]
        assert default == "1" and help_ != "", name
