"""RPC + simulated network tests: request/reply, determinism, kills, clogs."""

import pytest

from foundationdb_tpu.flow import EventLoop, FdbError, set_event_loop
from foundationdb_tpu.flow.asyncvar import AsyncVar, NotifiedVersion
from foundationdb_tpu.rpc import RequestStream, SimNetwork
from foundationdb_tpu.rpc.stream import retry_get_reply


@pytest.fixture
def net():
    loop = EventLoop(seed=42)
    set_event_loop(loop)
    yield SimNetwork(loop)
    set_event_loop(None)


def make_echo_server(net, name="server"):
    proc = net.process(name)
    rs = RequestStream(proc, "echo")

    async def server():
        while True:
            req, reply = await rs.pop()
            reply.send(("echo", req))

    proc.spawn(server(), "echo")
    return proc, rs.ref()


def test_request_reply(net):
    _, ref = make_echo_server(net)
    client = net.process("client")
    got = {}

    async def go():
        got["v"] = await ref.get_reply(client, 123)

    client.spawn(go())
    net.loop.run()
    assert got["v"] == ("echo", 123)
    assert net.loop.now() > 0  # latency actually elapsed


def test_determinism_same_seed():
    def run(seed):
        loop = EventLoop(seed=seed)
        set_event_loop(loop)
        net = SimNetwork(loop)
        _, ref = make_echo_server(net)
        client = net.process("client")
        order = []

        async def one(i):
            await ref.get_reply(client, i)
            order.append((i, loop.now()))

        for i in range(10):
            client.spawn(one(i))
        loop.run()
        set_event_loop(None)
        return order

    assert run(7) == run(7)
    assert run(7) != run(8)  # different seed -> different latencies


def test_kill_breaks_promise(net):
    server, ref = make_echo_server(net)

    # A server that never replies, so the request is outstanding at kill time.
    slow = net.process("slow")
    rs = RequestStream(slow, "never")

    async def never():
        while True:
            await rs.pop()  # pop and drop

    slow.spawn(never(), "never")
    client = net.process("client")
    result = {}

    async def go():
        try:
            await rs.ref().get_reply(client, "hi")
            result["r"] = "replied"
        except FdbError as e:
            result["r"] = e.name

    client.spawn(go())

    async def killer():
        await net.loop.delay(0.01)
        slow.kill()

    client.spawn(killer())
    net.loop.run()
    assert result["r"] == "broken_promise"


def test_get_reply_to_already_dead_process(net):
    """A request to a process that is already dead must fail promptly with
    broken_promise (the failed-connect path), not hang."""
    server, ref = make_echo_server(net)
    server.kill()
    client = net.process("client")
    result = {}

    async def go():
        try:
            await ref.get_reply(client, 1)
            result["r"] = "replied"
        except FdbError as e:
            result["r"] = e.name

    client.spawn(go())
    net.loop.run()
    assert result["r"] == "broken_promise"
    assert client._endpoints == {}  # no leaked one-shot reply endpoints


def test_no_endpoint_leak_on_kill(net):
    """Reply endpoints registered before a kill are dropped when broken."""
    slow = net.process("slow")
    rs = RequestStream(slow, "never")

    async def never():
        while True:
            await rs.pop()

    slow.spawn(never(), "never")
    client = net.process("client")

    async def go():
        try:
            await rs.ref().get_reply(client, "x")
        except FdbError:
            pass

    client.spawn(go())

    async def killer():
        await net.loop.delay(0.01)
        slow.kill()

    client.spawn(killer())
    net.loop.run()
    assert client._endpoints == {}
    assert client._pending_on == {}


def test_retry_after_reboot(net):
    """broken_promise retry reaches the rebooted server (same endpoint token)."""
    proc = net.process("server")
    token = 99

    def start_server():
        rs = RequestStream(proc, "echo", token=token)

        async def server():
            while True:
                req, reply = await rs.pop()
                reply.send(req * 2)

        proc.spawn(server(), "echo")
        return rs.ref()

    ref = start_server()
    client = net.process("client")
    result = {}

    async def go():
        result["v"] = await retry_get_reply(ref, client, 21, delay=0.05)

    client.spawn(go())

    async def chaos():
        await net.loop.delay(0.00001)  # kill before the request arrives
        proc.kill()
        await net.loop.delay(0.02)
        proc.reboot()
        start_server()

    net.process("chaos").spawn(chaos())
    net.loop.run()
    assert result["v"] == 42


def test_clog_delays_delivery(net):
    server, ref = make_echo_server(net, "mserver")
    client = net.process("mclient")
    times = {}

    async def go(tag):
        await ref.get_reply(client, tag)
        times[tag] = net.loop.now()

    # First request unclogged for a baseline.
    client.spawn(go("fast"))
    net.loop.run()
    baseline = times["fast"]
    net.clog_pair("mclient", "mserver", 5.0)
    client.spawn(go("slow"))
    net.loop.run()
    assert times["slow"] >= 5.0 > baseline


def test_clog_is_directional_reply_path_stays_clear(net):
    """clog_pair holds ONE direction (ref ISimulator::clogPair): with only
    the server->client leg clogged, requests arrive and are processed —
    the grey failure where work happens but acks stall."""
    proc = net.process("dserver")
    rs = RequestStream(proc, "echo")
    hits = []

    async def server():
        while True:
            req, reply = await rs.pop()
            hits.append((req, net.loop.now()))
            reply.send(("echo", req))

    proc.spawn(server(), "echo")
    ref = rs.ref()
    client = net.process("dclient")
    times = {}

    async def go(tag):
        await ref.get_reply(client, tag)
        times[tag] = net.loop.now()

    # Clog the REPLY direction only.
    net.clog_pair("dserver", "dclient", 5.0)
    client.spawn(go("r1"))
    net.loop.run()
    assert times["r1"] >= 5.0  # the reply ate the clog...
    assert hits and hits[0][0] == "r1"  # ...but the request was delivered
    assert hits[0][1] < 1.0  # promptly, on the unclogged leg


def test_partition_pair_and_unclog_pair(net):
    """partition_pair cuts both directions; unclog_pair releases a single
    pair early without touching other clogs."""
    net.partition_pair("ma", "mb", 30.0)
    net.clog_pair("mc", "md", 30.0)
    assert net._clog_release("ma", "mb") > 0
    assert net._clog_release("mb", "ma") > 0
    net.unclog_pair("ma", "mb")
    assert net._clog_release("ma", "mb") == 0
    assert net._clog_release("mb", "ma") == 0
    # The unrelated one-way clog survived.
    assert net._clog_release("mc", "md") > 0
    assert net._clog_release("md", "mc") == 0


def test_payload_isolation(net):
    """Mutating a sent payload after send must not affect the receiver."""
    proc = net.process("server")
    rs = RequestStream(proc, "take")
    seen = {}

    async def server():
        req, reply = await rs.pop()
        seen["v"] = list(req)
        reply.send(None)

    proc.spawn(server())
    client = net.process("client")

    async def go():
        payload = [1, 2, 3]
        f = rs.ref().get_reply(client, payload)
        payload.append(999)  # after-send mutation
        await f

    client.spawn(go())
    net.loop.run()
    assert seen["v"] == [1, 2, 3]


def test_asyncvar_and_notified_version():
    loop = EventLoop(seed=1)
    set_event_loop(loop)
    av = AsyncVar(1)
    nv = NotifiedVersion(0)
    log = []

    async def watcher():
        while av.get() < 3:
            await av.on_change()
        log.append(("av", av.get()))

    async def waiter():
        await nv.when_at_least(10)
        log.append(("nv", nv.get()))

    loop.spawn(watcher())
    loop.spawn(waiter())

    async def driver():
        await loop.delay(0.01)
        av.set(2)
        av.set(3)
        nv.set(5)
        nv.set(12)

    loop.spawn(driver())
    loop.run()
    assert ("av", 3) in log and ("nv", 12) in log
    set_event_loop(None)


def test_request_stream_close_breaks_parked_requests():
    """RequestStream.close(): requests PARKED in the queue (server busy,
    never popped) must get broken_promise immediately, and later
    deliveries must be refused — the NetNotifiedQueue-destruction analog
    role teardown depends on (ref: fdbrpc.h:192)."""
    from foundationdb_tpu.flow import EventLoop, set_event_loop
    from foundationdb_tpu.flow import testprobe
    from foundationdb_tpu.flow.error import FdbError
    from foundationdb_tpu.rpc import SimNetwork
    from foundationdb_tpu.rpc.stream import RequestStream

    probe_before = testprobe.hit_sites.get("request_stream_closed_parked", 0)
    loop = EventLoop(seed=44)
    set_event_loop(loop)
    net = SimNetwork(loop)
    server = net.process("srv")
    client = net.process("cli")
    stream = RequestStream(server, "busy_service", well_known=True)
    out = {}

    async def run():
        f1 = stream.ref().get_reply(client, "parked-1")
        f2 = stream.ref().get_reply(client, "parked-2")
        await loop.delay(0.1)  # both delivered, nobody pops
        stream.close()
        for name, f in (("one", f1), ("two", f2)):
            try:
                await f
                out[name] = "no error"
            except FdbError as e:
                out[name] = e.name
        # Post-close delivery refused the same way.
        try:
            await stream.ref().get_reply(client, "late")
            out["late"] = "no error"
        except FdbError as e:
            out["late"] = e.name

    loop.run_until(client.spawn(run(), "t"), timeout_vt=100.0)
    assert out == {
        "one": "broken_promise",
        "two": "broken_promise",
        "late": "broken_promise",
    }, out
    assert (
        testprobe.hit_sites.get("request_stream_closed_parked", 0)
        > probe_before
    )
    set_event_loop(None)


# ---------------------------------------------------------------------------
# PRM/TSK burn-down fixes: close() waking parked consumers, observed spawns
# ---------------------------------------------------------------------------


def test_close_wakes_parked_serve_actor(net):
    """A serve actor parked in `await stream.pop()` when its generation
    retires must wake with the close error and exit — before the fix it
    stayed parked forever on a stream nothing could ever push to again
    (the orphaned-wait leak class: the retired role's whole object graph
    pinned by one silent task)."""
    proc = net.process("server")
    rs = RequestStream(proc, "svc")
    state = {}

    async def server():
        try:
            while True:
                req, reply = await rs.pop()
                reply.send(req)
        except FdbError as e:
            state["died"] = e.name
            raise

    t = proc.spawn(server(), "svc_serve")
    net.loop.run()
    assert not t.is_ready()  # parked on pop, nothing delivered yet
    rs.close()
    net.loop.run()
    assert state["died"] == "broken_promise"
    assert t.is_ready() and t.is_error()


def test_close_still_breaks_queued_requests(net):
    # The pre-existing close contract is untouched: queued (undelivered-
    # to-actor) requests break with the close error at their callers.
    proc = net.process("server")
    rs = RequestStream(proc, "svc2")
    client = net.process("client")
    got = {}

    async def call():
        try:
            await rs.ref().get_reply(client, 1)
        except FdbError as e:
            got["err"] = e.name

    client.spawn(call(), "caller")
    net.loop.run()  # delivered into the stream queue; no server popping
    rs.close()
    net.loop.run()
    assert got["err"] == "broken_promise"


def test_spawn_observed_traces_fdb_error_death(net):
    """spawn_observed (the TSK001 remedy): an FdbError killing a dropped
    fire-and-forget task emits SpawnedTaskDied instead of vanishing —
    the EventLoop only surfaces non-FdbError crashes."""
    from foundationdb_tpu.flow.trace import global_collector

    collector = global_collector()
    collector.clear()
    proc = net.process("p")

    async def doomed():
        raise FdbError("transaction_too_old")

    async def clean():
        return 1

    proc.spawn_observed(doomed(), "doomed")
    proc.spawn_observed(clean(), "clean")
    net.loop.run()
    died = collector.find("SpawnedTaskDied")
    assert len(died) == 1
    assert "transaction_too_old" in died[0]["error"]
    assert died[0]["task"].endswith("/doomed")


def test_spawn_observed_is_quiet_on_cancel(net):
    from foundationdb_tpu.flow.trace import global_collector

    collector = global_collector()
    collector.clear()
    proc = net.process("p")

    async def forever(loop):
        while True:
            await loop.delay(1.0)

    t = proc.spawn_observed(forever(net.loop), "forever")
    net.loop.run(max_events=5)
    t.cancel()
    net.loop.run(max_events=5)
    assert collector.find("SpawnedTaskDied") == []
