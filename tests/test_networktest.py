"""RPC throughput characterization of the real TCP fabric + wire codec.

Ref: fdbserver/networktest.actor.cpp (`-r networktestserver` /
`-r networktestclient`) — the reference's tool for measuring raw
FlowTransport request/reply throughput, so serialization changes have a
number.  CI mode keeps the run small and asserts only sanity floors; the
measured rate is printed for the log.
"""

import json
import signal
import subprocess
import sys

import pytest

from conftest import spawn_real_node
from test_tls import make_ca, make_cert


@pytest.fixture(scope="module")
def tls_material(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("nt_tls"))
    ca_key, ca_crt = make_ca(d, "nt-ca")
    key, crt = make_cert(d, "nt-node", ca_key, ca_crt)
    return crt, key, ca_crt


def _run_pair(extra_server=(), extra_client=()):
    server = spawn_real_node("ntserver", *extra_server)
    try:
        ready = server.stdout.readline().strip()
        assert ready.startswith("READY "), ready
        addr = ready.split()[1]
        client = spawn_real_node(
            "ntclient", addr, "--requests", "3000", "--parallel", "16",
            "--size", "128", *extra_client,
        )
        out, _ = client.communicate(timeout=90)
        assert client.returncode == 0, out
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        return json.loads(line)
    finally:
        server.send_signal(signal.SIGTERM)
        try:
            server.wait(timeout=5)
        except subprocess.TimeoutExpired:
            server.kill()


def test_networktest_throughput_plaintext():
    res = _run_pair()
    print(f"\nnetworktest plaintext: {res}", file=sys.stderr)
    assert res["metric"] == "rpc_requests_per_sec"
    # Sanity floor only (CI hosts vary); the real number goes to the log.
    assert res["value"] > 300, res
    assert res["tls"] is False


def test_networktest_throughput_tls(tls_material):
    cert, key, ca = tls_material
    args = ["--tls-cert", cert, "--tls-key", key, "--tls-ca", ca]
    res = _run_pair(extra_server=args, extra_client=args)
    print(f"\nnetworktest mTLS: {res}", file=sys.stderr)
    assert res["value"] > 200, res
    assert res["tls"] is True
