"""Shard-granular fault-domain differential gate (ISSUE 15).

The tentpole contract: scripted faults on shard k — including
mid-compaction (tiered cadence) and mid-probe (the rehydrate choke
point) — across >= 3 seeds x flat/tiered/kernels modes produce verdicts
bit-identical to the fault-free CPU-only multi-resolver oracle, with
ONLY shard k's breaker walking ok -> degraded -> probing -> ok and the
per-shard transition logs byte-identical across same-seed replays.
"""

import json

import numpy as np
import pytest

import jax

from foundationdb_tpu.conflict.device_faults import DeviceFaultInjector
from foundationdb_tpu.parallel.sharded_resolver import (
    ShardedJaxConflictSet,
    uniform_int_split_keys,
)
from test_sharded_resolver import (
    KEY_BYTES,
    N_SHARDS,
    MultiResolverCpuOracle,
    random_txn,
)

SICK = 2  # the faulted shard; every other shard must stay untouched

# Engine modes (the bench VARIANTS' decision-identical axes): flat,
# two-tier history with a 3-batch compaction cadence (so the scripted
# fault window covers a compaction batch), and Pallas kernels in
# interpret mode (the CPU differential arm of ISSUE 14).
MODES = [
    ("flat", {}),
    (
        "tiered",
        {
            "FDB_TPU_HISTORY": "tiered",
            "FDB_TPU_EVICT_EVERY": "3",
            "FDB_TPU_DELTA_CAP": "2048",
        },
    ),
    ("kernels", {"FDB_TPU_KERNELS": "interpret"}),
]


def _make_set(fault_plans=()):
    split = uniform_int_split_keys(N_SHARDS, 2000, KEY_BYTES)
    cs = ShardedJaxConflictSet(
        split,
        key_words=3,
        h_cap=1 << 12,
        devices=jax.devices()[:N_SHARDS],
        bucket_mins=(64, 128, 128),
    )
    inj = DeviceFaultInjector()
    for site, at, persist, shard in fault_plans:
        inj.script(site, at=at, persist=persist, shard=shard)
    cs.install_fault_injector(inj)
    return cs, inj


def _batches(seed, n_batches=14):
    rng = np.random.default_rng(seed)
    now = 100
    out = []
    for _ in range(n_batches):
        txns = [random_txn(rng, now) for _ in range(int(rng.integers(1, 30)))]
        now += int(rng.integers(1, 30))
        out.append((txns, now, max(0, now - 120)))
    return out


# The scripted plan: 3 consecutive dispatch faults starting at shard
# SICK's 3rd device batch (>= breaker threshold, so the circuit opens; in
# tiered mode batch 3 IS a compaction batch at cadence 3 — the fault
# lands mid-compaction), plus a fault on the FIRST rehydrate attempt
# (site grow = the rehydration choke point), so the half-open probe
# itself fails once before recovering.
PLANS = (
    ("dispatch", 3, 3, SICK),
    ("grow", 1, 1, SICK),
)


def _run(seed, plans):
    cs, inj = _make_set(plans)
    verdicts = [
        cs.detect(txns, now, oldest)
        for txns, now, oldest in _batches(seed)
    ]
    return cs, inj, verdicts


@pytest.mark.parametrize("mode,env", MODES, ids=[m for m, _ in MODES])
@pytest.mark.parametrize("seed", [11, 12, 13])
def test_shard_fault_differential_gate(monkeypatch, mode, env, seed):
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    split = uniform_int_split_keys(N_SHARDS, 2000, KEY_BYTES)
    oracle = MultiResolverCpuOracle(split)
    want = [
        oracle.detect(txns, now, oldest)
        for txns, now, oldest in _batches(seed)
    ]

    cs, inj, got = _run(seed, PLANS)
    assert got == want, f"{mode}/seed={seed}: verdicts diverged from oracle"
    assert inj.injected, "the scripted plan never fired"

    # Fault-domain isolation: ONLY shard SICK's breaker walked.
    for s in range(N_SHARDS):
        br = cs._breakers[s]
        if s == SICK:
            continue
        assert br.state == "ok" and br.transitions == [], (
            f"{mode}/seed={seed}: healthy shard {s} breaker moved: "
            f"{br.transitions}"
        )
    sick = cs._breakers[SICK]
    pairs = [(f, t) for _seq, f, t, _r in sick.transitions]
    # Full legal walk incl. the failed probe:
    # ok -> degraded (threshold) -> probing -> degraded (probe_failed,
    # the scripted mid-probe grow fault) -> probing -> ok.
    assert pairs == [
        ("ok", "degraded"),
        ("degraded", "probing"),
        ("probing", "degraded"),
        ("degraded", "probing"),
        ("probing", "ok"),
    ], sick.transitions
    assert sick.transitions[0][3].startswith("threshold:")
    assert sick.transitions[2][3].startswith("probe_failed:")
    assert sick.state == "ok"
    assert cs.metrics.counter("degraded_shard_serves").value > 0

    # Same-seed replay: per-shard transition logs AND the injected fault
    # schedule are byte-identical.
    cs2, inj2, got2 = _run(seed, PLANS)
    assert got2 == got
    assert json.dumps(inj2.injected) == json.dumps(inj.injected)
    for s in range(N_SHARDS):
        assert json.dumps(cs2._breakers[s].transitions) == json.dumps(
            cs._breakers[s].transitions
        ), f"{mode}/seed={seed}: shard {s} transition log not replayable"


def test_metrics_snapshot_shape_is_fault_independent():
    """The PR-4 flat-snapshot discipline, shard-granular: every per-shard
    breaker instrument is pre-created at construction, so WHICH shards
    fault can never change the snapshot's key set."""
    _, _, _ = None, None, None
    cs_clean, _inj, _ = _run(5, ())
    cs_faulty, inj, _ = _run(5, PLANS)
    assert inj.injected
    clean = cs_clean.device_metrics()
    faulty = cs_faulty.device_metrics()
    assert set(clean["counters"]) == set(faulty["counters"])
    assert set(clean["gauges"]) == set(faulty["gauges"])
    for s in range(N_SHARDS):
        assert f"shard{s}_breaker_opens" in clean["counters"]
        assert f"shard{s}_backend_state" in clean["gauges"]


def test_backend_signal_carries_shard_counts():
    """backend_signal() reports (shards_degraded, shards_total) so the
    ratekeeper can contract the lane proportionally — one sick chip out
    of N, not a whole-lane degraded clamp."""
    cs, inj = _make_set()
    inj.begin_outage("dispatch", shard=SICK)
    for txns, now, oldest in _batches(21, n_batches=4):
        cs.detect(txns, now, oldest)
    sig = cs.backend_signal()
    assert sig["shards_total"] == N_SHARDS
    assert sig["shards_degraded"] == 1
    assert sig["backend_state"] == "degraded"
    dm = cs.device_metrics()
    assert dm["shards"]["states"][SICK] == "degraded"
    assert dm["shards"]["degraded"] == 1
    inj.end_outage("dispatch", shard=SICK)


def test_injector_per_shard_sites_are_scoped_and_replayable():
    """Per-shard scripted plans keep their own check counters (shard
    k's 2nd check is independent of shard j's), and the injected log
    names the shard-scoped site key."""
    inj = DeviceFaultInjector()
    inj.script("dispatch", at=2, shard=1)
    # Interleaved checks: shard 0 never faults, shard 1 faults on ITS
    # second check regardless of shard 0's traffic.
    inj.check("dispatch", shard=0)
    inj.check("dispatch", shard=1)
    inj.check("dispatch", shard=0)
    with pytest.raises(Exception):
        inj.check("dispatch", shard=1)
    inj.check("dispatch", shard=0)
    assert [e[1] for e in inj.injected] == ["dispatch#s1"]


def test_mid_probe_fault_reopens_only_sick_shard_tiered(monkeypatch):
    """Tiered mode: a persistent outage spanning several compactions,
    lifted mid-run — recovery rehydrates ONLY the sick shard (its delta
    resets, its base rebuilds from the mirror snapshot) and verdicts
    stay oracle-identical throughout."""
    monkeypatch.setenv("FDB_TPU_HISTORY", "tiered")
    monkeypatch.setenv("FDB_TPU_EVICT_EVERY", "3")
    monkeypatch.setenv("FDB_TPU_DELTA_CAP", "2048")
    split = uniform_int_split_keys(N_SHARDS, 2000, KEY_BYTES)
    oracle = MultiResolverCpuOracle(split)
    cs, inj = _make_set()
    rehydrates_before = cs.metrics.counter(
        f"shard{SICK}_rehydrates"
    ).value
    batches = _batches(31, n_batches=16)
    for i, (txns, now, oldest) in enumerate(batches):
        if i == 2:
            inj.begin_outage("dispatch", shard=SICK)
        if i == 10:
            inj.end_outage("dispatch", shard=SICK)
        got = cs.detect(txns, now, oldest)
        assert got == oracle.detect(txns, now, oldest), f"batch {i}"
    assert cs._breakers[SICK].state == "ok"
    assert (
        cs.metrics.counter(f"shard{SICK}_rehydrates").value
        > rehydrates_before
    )
    for s in range(N_SHARDS):
        if s != SICK:
            assert cs._breakers[s].transitions == []
