"""Differential tests: CPU engine vs brute-force oracle.

Strategy per SURVEY.md §4.8: the oracle is the obviously-correct model; the
production engines must make byte-identical decisions on randomized batch
streams, including adversarial shapes (chains where a conflicted txn
un-conflicts a later one, snapshot==version boundaries, window eviction).
"""

import pytest

from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.types import (
    COMMITTED,
    CONFLICT,
    TOO_OLD,
    TransactionConflictInfo as T,
)
from foundationdb_tpu.flow import DeterministicRandom


def k(i: int) -> bytes:
    return b"%08d" % i


def test_simple_conflict_and_strictness():
    cs = CpuConflictSet()
    # txn A writes [10,20) at version 100
    s = cs.detect([T(read_snapshot=0, write_ranges=[(k(10), k(20))])], 100, 0)
    assert s == [COMMITTED]
    # read at snapshot 99 overlapping -> conflict; at 100 -> NO conflict (strict >)
    s = cs.detect(
        [
            T(read_snapshot=99, read_ranges=[(k(15), k(16))]),
            T(read_snapshot=100, read_ranges=[(k(15), k(16))]),
            T(read_snapshot=99, read_ranges=[(k(20), k(25))]),  # half-open: no overlap
            T(read_snapshot=99, read_ranges=[(k(5), k(10))]),  # ends at begin: no
            T(read_snapshot=99, read_ranges=[(k(5), k(10) + b"\x00")]),  # 1 past: yes
        ],
        101,
        0,
    )
    assert s == [CONFLICT, COMMITTED, COMMITTED, COMMITTED, CONFLICT]


def test_too_old_requires_read_ranges():
    cs = CpuConflictSet(oldest_version=50)
    s = cs.detect(
        [
            T(read_snapshot=10, read_ranges=[(k(1), k(2))]),  # too old
            T(read_snapshot=10, write_ranges=[(k(1), k(2))]),  # no reads: commits
            T(read_snapshot=50, read_ranges=[(k(5), k(6))]),  # at boundary: fine
        ],
        60,
        50,
    )
    assert s == [TOO_OLD, COMMITTED, COMMITTED]


def test_intra_batch_order_and_chain():
    cs = CpuConflictSet()
    # t0 writes X; t1 reads X (conflicts with t0) and writes Y;
    # t2 reads Y -> must COMMIT because t1 conflicted (its write invisible)
    s = cs.detect(
        [
            T(read_snapshot=0, write_ranges=[(b"x", b"x\x00")]),
            T(
                read_snapshot=0,
                read_ranges=[(b"x", b"x\x00")],
                write_ranges=[(b"y", b"y\x00")],
            ),
            T(read_snapshot=0, read_ranges=[(b"y", b"y\x00")]),
        ],
        10,
        0,
    )
    assert s == [COMMITTED, CONFLICT, COMMITTED]


def test_intra_batch_reads_precede_own_writes():
    # A txn whose read range overlaps its OWN write range must not self-conflict
    cs = CpuConflictSet()
    s = cs.detect(
        [T(read_snapshot=0, read_ranges=[(b"a", b"b")], write_ranges=[(b"a", b"b")])],
        10,
        0,
    )
    assert s == [COMMITTED]


def test_later_txn_write_does_not_conflict_earlier_read():
    cs = CpuConflictSet()
    s = cs.detect(
        [
            T(read_snapshot=0, read_ranges=[(b"a", b"b")]),
            T(read_snapshot=0, write_ranges=[(b"a", b"b")]),
        ],
        10,
        0,
    )
    assert s == [COMMITTED, COMMITTED]


def test_window_eviction_too_old():
    cs = CpuConflictSet()
    cs.detect([T(read_snapshot=0, write_ranges=[(k(1), k(2))])], 100, 0)
    cs.detect([], 200, 150)  # advance window past version 100
    s = cs.detect(
        [
            T(read_snapshot=149, read_ranges=[(k(1), k(2))]),  # below window
            T(read_snapshot=150, read_ranges=[(k(1), k(2))]),  # at window: ok, no conflict
        ],
        201,
        150,
    )
    assert s == [TOO_OLD, COMMITTED]


def _random_batch(rng: DeterministicRandom, keyspace: int, version: int, n: int):
    txns = []
    for _ in range(n):
        tr = T(
            read_snapshot=max(0, version - rng.random_int(0, 30)),
            read_ranges=[],
            write_ranges=[],
        )
        for _ in range(rng.random_int(0, 4)):
            a = rng.random_int(0, keyspace)
            b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
            tr.read_ranges.append((k(a), k(b)))
        for _ in range(rng.random_int(0, 3)):
            a = rng.random_int(0, keyspace)
            b = a + 1 + rng.random_int(0, max(1, keyspace // 10))
            tr.write_ranges.append((k(a), k(b)))
        txns.append(tr)
    return txns


@pytest.mark.parametrize("seed,keyspace", [(1, 30), (2, 30), (3, 1000), (4, 8), (5, 200)])
def test_differential_cpu_vs_oracle(seed, keyspace):
    rng = DeterministicRandom(seed)
    cpu = CpuConflictSet()
    orc = OracleConflictSet()
    version = 10
    for batch_i in range(40):
        txns = _random_batch(rng, keyspace, version, rng.random_int(1, 25))
        now = version + rng.random_int(1, 10)
        new_oldest = max(0, version - 25)
        got = cpu.detect(txns, now, new_oldest)
        want = orc.detect(txns, now, new_oldest)
        assert got == want, f"batch {batch_i}: cpu={got} oracle={want}"
        version = now


def test_variable_length_keys_differential():
    rng = DeterministicRandom(77)
    cpu = CpuConflictSet()
    orc = OracleConflictSet()
    alphabet = [b"", b"\x00", b"a", b"ab", b"ab\x00", b"abc", b"b", b"\xff", b"\xff\xff"]
    version = 5
    for _ in range(60):
        txns = []
        for _ in range(rng.random_int(1, 12)):
            tr = T(read_snapshot=max(0, version - rng.random_int(0, 10)))
            for _ in range(rng.random_int(0, 3)):
                a, b = rng.random_choice(alphabet), rng.random_choice(alphabet)
                if a > b:
                    a, b = b, a
                tr.read_ranges.append((a, b))
            for _ in range(rng.random_int(0, 3)):
                a, b = rng.random_choice(alphabet), rng.random_choice(alphabet)
                if a > b:
                    a, b = b, a
                tr.write_ranges.append((a, b))
            txns.append(tr)
        now = version + rng.random_int(1, 5)
        new_oldest = max(0, version - 8)
        assert cpu.detect(txns, now, new_oldest) == orc.detect(txns, now, new_oldest)
        version = now
