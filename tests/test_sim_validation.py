"""flow/sim_validation.py: the simulation-only invariant recorder.

Ref: fdbrpc/sim_validation.{h,cpp} — production code marks promises
("version V was acked durable") that the simulation later checks; a
violation must be a loud failure.  Semantics under test: monotone marks,
checking against the recorded high-water mark, per-loop state isolation
(concurrent simulated clusters in one process must not interfere), and
integration with a live simulated cluster.
"""

import pytest

from foundationdb_tpu.flow.eventloop import EventLoop
from foundationdb_tpu.flow.sim_validation import (
    expect_at_least,
    mark_at_least,
    marked,
)

FLOOR = -(1 << 62)


def test_marked_default_is_floor():
    loop = EventLoop(seed=1)
    assert marked(loop, "never_marked") == FLOOR


def test_mark_is_monotone():
    loop = EventLoop(seed=1)
    mark_at_least(loop, "acked", 100)
    assert marked(loop, "acked") == 100
    # A lower mark must not regress the promise...
    mark_at_least(loop, "acked", 40)
    assert marked(loop, "acked") == 100
    # ...and a higher one advances it.
    mark_at_least(loop, "acked", 250)
    assert marked(loop, "acked") == 250


def test_expect_at_least_passes_at_and_above_mark():
    loop = EventLoop(seed=1)
    mark_at_least(loop, "acked", 100)
    expect_at_least(loop, "acked", 100)  # equality is covering
    expect_at_least(loop, "acked", 101)


def test_expect_below_mark_is_loud():
    loop = EventLoop(seed=1)
    mark_at_least(loop, "durable", 500)
    with pytest.raises(AssertionError, match="promised 500 but observed 499"):
        expect_at_least(loop, "durable", 499)


def test_expect_includes_context_in_failure():
    loop = EventLoop(seed=1)
    mark_at_least(loop, "durable", 7)
    with pytest.raises(AssertionError, match="recovery epoch cut"):
        expect_at_least(loop, "durable", 3, context="recovery epoch cut")


def test_expect_on_unmarked_key_is_vacuous():
    # No promise recorded -> nothing to violate (production code checks
    # unconditionally; only simulation records marks).
    loop = EventLoop(seed=1)
    expect_at_least(loop, "never_marked", -(1 << 61))


def test_keys_are_independent():
    loop = EventLoop(seed=1)
    mark_at_least(loop, "a", 10)
    mark_at_least(loop, "b", 20)
    assert marked(loop, "a") == 10
    assert marked(loop, "b") == 20
    expect_at_least(loop, "a", 10)
    with pytest.raises(AssertionError):
        expect_at_least(loop, "b", 15)


def test_multi_loop_isolation():
    # Two concurrent simulated clusters (two loops) in one test process:
    # marks recorded against one must be invisible to the other.
    a, b = EventLoop(seed=1), EventLoop(seed=2)
    mark_at_least(a, "acked", 1000)
    assert marked(b, "acked") == FLOOR
    expect_at_least(b, "acked", 0)  # no promise on b: vacuous
    with pytest.raises(AssertionError):
        expect_at_least(a, "acked", 999)
    mark_at_least(b, "acked", 5)
    assert marked(a, "acked") == 1000
    assert marked(b, "acked") == 5


def test_state_survives_across_actors_on_one_loop():
    # Marks made inside actors accumulate on the loop exactly like marks
    # made from host code, and checks observe them in virtual-time order.
    from foundationdb_tpu.server.cluster import SimCluster

    cluster = SimCluster(seed=11, buggify=False)
    loop = cluster.loop

    async def committer(db):
        for i in range(5):
            tr = db.create_transaction()
            tr.set(b"k%d" % i, b"v")
            v = await tr.commit()
            mark_at_least(loop, "acked_commit", v)

    async def checker(db):
        await loop.delay(10.0)
        tr = db.create_transaction()
        v = await tr.get_read_version()
        # A read version must cover every acked commit.
        expect_at_least(loop, "acked_commit", v, context="grv behind ack")
        return v

    db = cluster.database()
    cluster.run_until(db.process.spawn(committer(db), "committer"))
    got = cluster.run_until(db.process.spawn(checker(db), "checker"))
    assert marked(loop, "acked_commit") <= got


# ---------------------------------------------------------------------------
# Orphaned-wait teardown check: the dynamic twin of fdblint PRM001/PRM002.
# A Task still parked on a future whose Promise was dropped has zero
# remaining senders — the condition the static pass proves from the ASTs,
# observed here at runtime (behind FDB_TPU_CHECK_ORPHANED_WAITS).
# ---------------------------------------------------------------------------


@pytest.fixture
def orphan_tracking(monkeypatch):
    from foundationdb_tpu.flow.future import track_promise_refs

    monkeypatch.setenv("FDB_TPU_CHECK_ORPHANED_WAITS", "1")
    track_promise_refs(True)
    yield
    track_promise_refs(False)


def test_orphaned_wait_trips_at_teardown(orphan_tracking):
    from foundationdb_tpu.flow.future import Promise
    from foundationdb_tpu.flow.sim_validation import expect_no_orphaned_waits

    loop = EventLoop(seed=1)

    async def waiter(f):
        await f

    p = Promise()
    t = loop.spawn(waiter(p.future), "orphan_waiter")
    loop.run(max_events=10)
    del p  # the only sender is gone: the task can never wake
    with pytest.raises(AssertionError, match="orphan_waiter"):
        expect_no_orphaned_waits(loop, "teardown")
    t.cancel()


def test_live_and_timer_waits_are_clean(orphan_tracking):
    from foundationdb_tpu.flow.future import Promise
    from foundationdb_tpu.flow.sim_validation import expect_no_orphaned_waits

    loop = EventLoop(seed=1)

    async def waiter(f):
        await f

    held = Promise()  # promise alive: a sender still exists
    t1 = loop.spawn(waiter(held.future), "live_waiter")
    t2 = loop.spawn(waiter(loop.delay(50.0)), "timer_waiter")
    loop.run(max_events=4)
    expect_no_orphaned_waits(loop, "mid-run")
    held.send(1)
    loop.run()
    assert t1.is_ready() and t2.is_ready()


def test_check_is_noop_without_flag(monkeypatch):
    from foundationdb_tpu.flow.future import Promise, track_promise_refs
    from foundationdb_tpu.flow.sim_validation import expect_no_orphaned_waits

    monkeypatch.delenv("FDB_TPU_CHECK_ORPHANED_WAITS", raising=False)
    track_promise_refs(True)
    try:
        loop = EventLoop(seed=1)

        async def waiter(f):
            await f

        p = Promise()
        loop.spawn(waiter(p.future), "orphan")
        loop.run(max_events=10)
        del p
        expect_no_orphaned_waits(loop)  # flag off: silent by design
    finally:
        track_promise_refs(False)


def test_flag_without_tracking_is_loud(monkeypatch):
    # The check must refuse to run blind: flag set, bookkeeping off.
    from foundationdb_tpu.flow.sim_validation import expect_no_orphaned_waits

    monkeypatch.setenv("FDB_TPU_CHECK_ORPHANED_WAITS", "1")
    loop = EventLoop(seed=1)
    with pytest.raises(AssertionError, match="track_promise_refs"):
        expect_no_orphaned_waits(loop)


def test_cluster_workload_shutdown_has_no_orphans(orphan_tracking):
    """The tier-1 cross-validation: a real simulated cluster runs a
    commit workload — including the resolver's pipeline park/drain path
    — and at shutdown no task is parked on a dropped promise.  This is
    the dynamic side of the static burn-down's clean bill: the pipeline
    completion promises (_ParkedResolve) and recruit handoffs all keep a
    live sender until resolution."""
    from foundationdb_tpu.flow.sim_validation import expect_no_orphaned_waits
    from foundationdb_tpu.server.cluster import SimCluster

    cluster = SimCluster(seed=23, buggify=False)
    db = cluster.database()

    async def commits(db):
        for i in range(8):
            tr = db.create_transaction()
            tr.set(b"ow%d" % i, b"v")
            await tr.commit()

    cluster.run_until(db.process.spawn(commits(db), "committer"))
    expect_no_orphaned_waits(cluster.loop, "cluster shutdown")


def test_run_until_dry_loop_names_orphans(orphan_tracking):
    from foundationdb_tpu.flow.future import Promise

    loop = EventLoop(seed=1)

    async def waiter(f):
        await f

    p = Promise()
    t = loop.spawn(waiter(p.future), "doomed")
    out = Promise()
    fut = out.future
    loop.run(max_events=10)
    del p
    with pytest.raises(RuntimeError, match="doomed"):
        loop.run_until(fut)
    t.cancel()


def test_dropped_handle_orphan_is_still_detected(orphan_tracking):
    """Review regression: a fire-and-forget spawn (Task handle dropped)
    parked on a dropped promise is only reachable through the
    task<->future callback cycle — the checker must snapshot the weak
    task registry BEFORE collecting, or gc reaps the task and the check
    passes blind on exactly the shape TSK001 polices."""
    from foundationdb_tpu.flow.future import Promise
    from foundationdb_tpu.flow.sim_validation import expect_no_orphaned_waits

    loop = EventLoop(seed=1)

    async def waiter(f):
        await f

    p = Promise()
    loop.spawn(waiter(p.future), "dropped_handle_orphan")  # handle dropped
    loop.run(max_events=10)
    del p
    with pytest.raises(AssertionError, match="dropped_handle_orphan"):
        expect_no_orphaned_waits(loop, "teardown")
