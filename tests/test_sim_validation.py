"""flow/sim_validation.py: the simulation-only invariant recorder.

Ref: fdbrpc/sim_validation.{h,cpp} — production code marks promises
("version V was acked durable") that the simulation later checks; a
violation must be a loud failure.  Semantics under test: monotone marks,
checking against the recorded high-water mark, per-loop state isolation
(concurrent simulated clusters in one process must not interfere), and
integration with a live simulated cluster.
"""

import pytest

from foundationdb_tpu.flow.eventloop import EventLoop
from foundationdb_tpu.flow.sim_validation import (
    expect_at_least,
    mark_at_least,
    marked,
)

FLOOR = -(1 << 62)


def test_marked_default_is_floor():
    loop = EventLoop(seed=1)
    assert marked(loop, "never_marked") == FLOOR


def test_mark_is_monotone():
    loop = EventLoop(seed=1)
    mark_at_least(loop, "acked", 100)
    assert marked(loop, "acked") == 100
    # A lower mark must not regress the promise...
    mark_at_least(loop, "acked", 40)
    assert marked(loop, "acked") == 100
    # ...and a higher one advances it.
    mark_at_least(loop, "acked", 250)
    assert marked(loop, "acked") == 250


def test_expect_at_least_passes_at_and_above_mark():
    loop = EventLoop(seed=1)
    mark_at_least(loop, "acked", 100)
    expect_at_least(loop, "acked", 100)  # equality is covering
    expect_at_least(loop, "acked", 101)


def test_expect_below_mark_is_loud():
    loop = EventLoop(seed=1)
    mark_at_least(loop, "durable", 500)
    with pytest.raises(AssertionError, match="promised 500 but observed 499"):
        expect_at_least(loop, "durable", 499)


def test_expect_includes_context_in_failure():
    loop = EventLoop(seed=1)
    mark_at_least(loop, "durable", 7)
    with pytest.raises(AssertionError, match="recovery epoch cut"):
        expect_at_least(loop, "durable", 3, context="recovery epoch cut")


def test_expect_on_unmarked_key_is_vacuous():
    # No promise recorded -> nothing to violate (production code checks
    # unconditionally; only simulation records marks).
    loop = EventLoop(seed=1)
    expect_at_least(loop, "never_marked", -(1 << 61))


def test_keys_are_independent():
    loop = EventLoop(seed=1)
    mark_at_least(loop, "a", 10)
    mark_at_least(loop, "b", 20)
    assert marked(loop, "a") == 10
    assert marked(loop, "b") == 20
    expect_at_least(loop, "a", 10)
    with pytest.raises(AssertionError):
        expect_at_least(loop, "b", 15)


def test_multi_loop_isolation():
    # Two concurrent simulated clusters (two loops) in one test process:
    # marks recorded against one must be invisible to the other.
    a, b = EventLoop(seed=1), EventLoop(seed=2)
    mark_at_least(a, "acked", 1000)
    assert marked(b, "acked") == FLOOR
    expect_at_least(b, "acked", 0)  # no promise on b: vacuous
    with pytest.raises(AssertionError):
        expect_at_least(a, "acked", 999)
    mark_at_least(b, "acked", 5)
    assert marked(a, "acked") == 1000
    assert marked(b, "acked") == 5


def test_state_survives_across_actors_on_one_loop():
    # Marks made inside actors accumulate on the loop exactly like marks
    # made from host code, and checks observe them in virtual-time order.
    from foundationdb_tpu.server.cluster import SimCluster

    cluster = SimCluster(seed=11, buggify=False)
    loop = cluster.loop

    async def committer(db):
        for i in range(5):
            tr = db.create_transaction()
            tr.set(b"k%d" % i, b"v")
            v = await tr.commit()
            mark_at_least(loop, "acked_commit", v)

    async def checker(db):
        await loop.delay(10.0)
        tr = db.create_transaction()
        v = await tr.get_read_version()
        # A read version must cover every acked commit.
        expect_at_least(loop, "acked_commit", v, context="grv behind ack")
        return v

    db = cluster.database()
    cluster.run_until(db.process.spawn(committer(db), "committer"))
    got = cluster.run_until(db.process.spawn(checker(db), "checker"))
    assert marked(loop, "acked_commit") <= got
