"""ManagementAPI: transactional `\xff/conf` configuration.

Ref: fdbclient/ManagementAPI.actor.cpp (changeConfig :253, excludeServers
:556, includeServers :606) — configuration changes are ordinary
transactions on system keys, and the controller reacts with a new
generation when the topology no longer matches.
"""

import pytest

from foundationdb_tpu.client import management as mgmt
from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server.dynamic_cluster import DynamicCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_configure_and_read_back():
    c = DynamicCluster(seed=120, n_workers=6)
    db = c.database()

    async def go():
        await mgmt.configure(db, proxies=2, storage_team_size=2)
        return await mgmt.get_configuration(db)

    conf = c.run_until(db.process.spawn(go()), timeout_vt=2000.0)
    assert conf["proxies"] == 2
    assert conf["storage_team_size"] == 2


def test_configure_proxies_triggers_regeneration():
    """configure proxies=2 must recruit a new generation with two proxies,
    and the cluster keeps serving (ref: the fdbcli `configure proxies=2`
    flow)."""
    c = DynamicCluster(seed=121, n_workers=6, n_proxies=1)
    db = c.database()

    async def seed_data(tr):
        tr.set(b"before", b"1")

    c.run_all([(db, db.run(seed_data))], timeout_vt=2000.0)
    gen_before = c.acting_controller().generation
    assert sum(
        1 for r in c.acting_controller()._role_addrs if r.startswith("proxy")
    ) == 1

    async def go():
        await mgmt.configure(db, proxies=2)

    c.run_all([(db, go())], timeout_vt=2000.0)

    # Wait for the new generation to serve (a txn through it proves it).
    async def after(tr):
        tr.set(b"after", b"2")
        return await tr.get(b"before")

    async def wait_regen():
        loop = c.loop
        while True:
            cc = c.acting_controller()
            if cc.generation > gen_before and cc.client_info.get().proxies:
                break
            await loop.delay(0.2)
        return await db.run(after)

    before = c.run_until(db.process.spawn(wait_regen()), timeout_vt=5000.0)
    assert before == b"1"
    cc = c.acting_controller()
    n_proxies = sum(
        1 for r in cc._role_addrs if r.startswith("proxy")
    )
    assert n_proxies == 2, cc._role_addrs


def test_exclude_include_records():
    c = DynamicCluster(seed=122, n_workers=6)
    db = c.database()

    async def go():
        await mgmt.exclude_servers(db, ["ss:worker4"])
        first = await mgmt.get_excluded_servers(db)
        await mgmt.include_servers(db)
        second = await mgmt.get_excluded_servers(db)
        return first, second

    first, second = c.run_until(db.process.spawn(go()), timeout_vt=2000.0)
    assert first == ["ss:worker4"]
    assert second == []
