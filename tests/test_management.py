"""ManagementAPI: transactional `\xff/conf` configuration.

Ref: fdbclient/ManagementAPI.actor.cpp (changeConfig :253, excludeServers
:556, includeServers :606) — configuration changes are ordinary
transactions on system keys, and the controller reacts with a new
generation when the topology no longer matches.
"""

import pytest

from foundationdb_tpu.client import management as mgmt
from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server.dynamic_cluster import DynamicCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_configure_and_read_back():
    c = DynamicCluster(seed=120, n_workers=6)
    db = c.database()

    async def go():
        await mgmt.configure(db, proxies=2, storage_team_size=2)
        return await mgmt.get_configuration(db)

    conf = c.run_until(db.process.spawn(go()), timeout_vt=2000.0)
    assert conf["proxies"] == 2
    assert conf["storage_team_size"] == 2


def test_configure_proxies_triggers_regeneration():
    """configure proxies=2 must recruit a new generation with two proxies,
    and the cluster keeps serving (ref: the fdbcli `configure proxies=2`
    flow)."""
    c = DynamicCluster(seed=121, n_workers=6, n_proxies=1)
    db = c.database()

    async def seed_data(tr):
        tr.set(b"before", b"1")

    c.run_all([(db, db.run(seed_data))], timeout_vt=2000.0)
    gen_before = c.acting_controller().generation
    assert sum(
        1 for r in c.acting_controller()._role_addrs if r.startswith("proxy")
    ) == 1

    async def go():
        await mgmt.configure(db, proxies=2)

    c.run_all([(db, go())], timeout_vt=2000.0)

    # Wait for the new generation to serve (a txn through it proves it).
    async def after(tr):
        tr.set(b"after", b"2")
        return await tr.get(b"before")

    async def wait_regen():
        # Convergence is eventual: an unrelated recovery (failover, role
        # failure) may interleave with the config-triggered one, and a new
        # leader re-learns the desired count from \xff/conf.  Wait for the
        # generation actually satisfying the configuration.
        loop = c.loop
        while True:
            cc = c.acting_controller()
            if (
                cc.generation > gen_before
                and len(cc.client_info.get().proxies) == 2
            ):
                break
            await loop.delay(0.2)
        return await db.run(after)

    before = c.run_until(db.process.spawn(wait_regen()), timeout_vt=5000.0)
    assert before == b"1"
    cc = c.acting_controller()
    n_proxies = sum(
        1 for r in cc._role_addrs if r.startswith("proxy")
    )
    assert n_proxies == 2, cc._role_addrs


def test_exclude_include_records():
    c = DynamicCluster(seed=122, n_workers=6)
    db = c.database()

    async def go():
        await mgmt.exclude_servers(db, ["ss:worker4"])
        first = await mgmt.get_excluded_servers(db)
        await mgmt.include_servers(db)
        second = await mgmt.get_excluded_servers(db)
        return first, second

    first, second = c.run_until(db.process.spawn(go()), timeout_vt=2000.0)
    assert first == ["ss:worker4"]
    assert second == []


def test_exclusion_drives_dd_healing():
    """exclude_servers + DD.process_exclusions: shards move off the
    excluded storage and its log tag stops holding the discard floor."""
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=123, n_storages=2)
    db = c.database()

    async def fill(tr):
        for i in range(30):
            tr.set(b"x%03d" % i, b"v%d" % i)

    c.run_all([(db, db.run(fill))])
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        # Replicate onto ss1 (a real fetch), then exclude the original.
        await dd.move(b"", ["ss0", "ss1"])
        await mgmt.exclude_servers(db, ["ss0"])
        # The excluded process shuts down (the realistic operator flow:
        # exclude, wait for data to drain, decommission); its PERSISTED
        # tag floor must not freeze log trimming forever.
        c.storages[0].process.kill()
        return await dd.process_exclusions(
            tlogs=[t.interface() for t in c.tlogs]
        )

    acted = c.run_until(db.process.spawn(place()), timeout_vt=5000.0)
    assert acted == ["ss0"]

    async def verify():
        shard_map = await dd.read_shard_map()
        return shard_map

    shard_map = c.run_until(db.process.spawn(verify()), timeout_vt=1000.0)
    for _b, _e, team, dest in shard_map:
        assert "ss0" not in set(team) | set(dest or []), shard_map

    # Data still readable (served by ss1).
    out = {}

    async def check(tr):
        out["rows"] = await tr.get_range(b"x", b"y")

    c.run_all([(db, db.run(check))])
    assert len(out["rows"]) == 30
    # The excluded tag no longer holds any tlog's floor.
    for t in c.tlogs:
        assert "ss0" not in t.popped_tags
