"""End-to-end slice tests: client -> proxy -> resolver -> tlog -> storage.

Workload designs follow the reference's simulation workloads (SURVEY.md §4):
Cycle (fdbserver/workloads/Cycle.actor.cpp: transactional pointer-chasing
ring whose total invariant survives concurrency), AtomicOps, WriteDuringRead
-style RYW checks, and Sideband-style causal reads.  All runs are seeded and
deterministic.
"""

import pytest

from foundationdb_tpu.client.types import MutationType
from foundationdb_tpu.flow import FdbError, set_event_loop
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_set_get_commit():
    c = SimCluster(seed=1)
    db = c.database()
    out = {}

    async def go(tr):
        tr.set(b"hello", b"world")
        out["pre"] = await tr.get(b"hello")  # RYW sees uncommitted write

    c.run_all([(db, db.run(go))])
    assert out["pre"] == b"world"

    async def check(tr):
        out["post"] = await tr.get(b"hello")
        out["missing"] = await tr.get(b"nope")

    c.run_all([(db, db.run(check))])
    assert out["post"] == b"world"
    assert out["missing"] is None


def test_clear_range_and_get_range():
    c = SimCluster(seed=2)
    db = c.database()
    out = {}

    async def fill(tr):
        for i in range(10):
            tr.set(b"k%02d" % i, b"v%d" % i)

    async def clear(tr):
        tr.clear_range(b"k03", b"k07")
        out["ryw"] = await tr.get_range(b"k", b"l")  # sees the clear pre-commit

    async def check(tr):
        out["post"] = await tr.get_range(b"k", b"l")
        out["limited"] = await tr.get_range(b"k", b"l", limit=2)
        out["rev"] = await tr.get_range(b"k", b"l", limit=2, reverse=True)

    c.run_all([(db, db.run(fill))])
    c.run_all([(db, db.run(clear))])
    c.run_all([(db, db.run(check))])
    keys = [k for k, _ in out["post"]]
    assert keys == [b"k00", b"k01", b"k02", b"k07", b"k08", b"k09"]
    assert out["ryw"] == out["post"]
    assert [k for k, _ in out["limited"]] == [b"k00", b"k01"]
    assert [k for k, _ in out["rev"]] == [b"k09", b"k08"]


def test_conflict_between_transactions():
    """Classic write-skew prevention: two txns read the same key at the same
    snapshot, both write it; exactly one commits (ref: Serializability)."""
    c = SimCluster(seed=3)
    db1, db2 = c.database(), c.database()
    results = []

    def make(db, me):
        async def go():
            tr = db.create_transaction()
            try:
                v = await tr.get(b"counter")
                n = int(v or b"0")
                tr.set(b"counter", b"%d" % (n + 1))
                await tr.commit()
                results.append((me, "committed"))
            except FdbError as e:
                results.append((me, e.name))

        return go()

    # Launch both concurrently: same read snapshot, conflicting writes.
    c.run_all([(db1, make(db1, 1)), (db2, make(db2, 2))])
    statuses = sorted(s for _, s in results)
    assert statuses == ["committed", "not_committed"], results


def test_cycle_workload_invariant():
    """Cycle workload: N nodes in a ring, each txn rotates 3 pointers; the
    ring's total and reachability are invariant (ref: Cycle.actor.cpp)."""
    N = 8
    OPS = 30
    c = SimCluster(seed=4)
    db_init = c.database()

    async def init(tr):
        for i in range(N):
            tr.set(b"cycle/%03d" % i, b"%03d" % ((i + 1) % N))

    c.run_all([(db_init, db_init.run(init))])

    dbs = [c.database() for _ in range(4)]
    done = []

    def worker(db, wid):
        async def go():
            rng = c.loop.rng
            for _ in range(OPS):
                async def op(tr):
                    a = int(rng.random_int(0, N))
                    ka = b"cycle/%03d" % a
                    b = int((await tr.get(ka)).decode())
                    kb = b"cycle/%03d" % b
                    cc = int((await tr.get(kb)).decode())
                    kc = b"cycle/%03d" % cc
                    d = int((await tr.get(kc)).decode())
                    # rotate: a->c, c->b, b->d
                    tr.set(ka, b"%03d" % cc)
                    tr.set(kc, b"%03d" % b)
                    tr.set(kb, b"%03d" % d)

                await db.run(op)
            done.append(wid)

        return go()

    c.run_all(
        [(db, worker(db, i)) for i, db in enumerate(dbs)], timeout_vt=5000.0
    )
    assert len(done) == 4

    out = {}

    async def check(tr):
        out["ring"] = await tr.get_range(b"cycle/", b"cycle0")

    c.run_all([(db_init, db_init.run(check))])
    ring = {k: int(v.decode()) for k, v in out["ring"]}
    assert len(ring) == N
    # Reachability: following pointers from 0 visits every node exactly once.
    seen, cur = set(), 0
    for _ in range(N):
        assert cur not in seen
        seen.add(cur)
        cur = ring[b"cycle/%03d" % cur]
    assert cur == 0 and len(seen) == N


def test_atomic_ops_end_to_end():
    c = SimCluster(seed=5)
    db = c.database()
    out = {}

    async def add(tr):
        tr.atomic_op(MutationType.ADD_VALUE, b"sum", (5).to_bytes(8, "little"))

    for _ in range(3):
        c.run_all([(db, db.run(add))])

    async def check(tr):
        out["sum"] = await tr.get(b"sum")
        # RYW atomic on top of a stored value
        tr.atomic_op(MutationType.ADD_VALUE, b"sum", (1).to_bytes(8, "little"))
        out["ryw"] = await tr.get(b"sum")
        tr.atomic_op(MutationType.BYTE_MAX, b"bm", b"abc")
        out["bm"] = await tr.get(b"bm")

    c.run_all([(db, db.run(check))])
    assert int.from_bytes(out["sum"], "little") == 15
    assert int.from_bytes(out["ryw"], "little") == 16
    assert out["bm"] == b"abc"


def test_versionstamped_key():
    c = SimCluster(seed=6)
    db = c.database()

    async def write(tr):
        # key = prefix + 10 stamp bytes, offset 4 (little-endian suffix)
        key = b"log/" + b"\x00" * 10 + (4).to_bytes(4, "little")
        tr.atomic_op(MutationType.SET_VERSIONSTAMPED_KEY, key, b"payload")

    c.run_all([(db, db.run(write))])
    out = {}

    async def check(tr):
        out["rows"] = await tr.get_range(b"log/", b"log0")

    c.run_all([(db, db.run(check))])
    assert len(out["rows"]) == 1
    k, v = out["rows"][0]
    assert v == b"payload" and len(k) == 14
    stamp_version = int.from_bytes(k[4:12], "big")
    assert stamp_version > 0  # the commit version was substituted


def test_set_then_clear_same_transaction():
    """Mutation order within one commit must hold at storage: set;clear ->
    gone, clear;set -> present (regression: intra-version ordering)."""
    c = SimCluster(seed=13)
    db = c.database()

    async def w1(tr):
        tr.set(b"a", b"x")
        tr.clear(b"a")
        tr.clear(b"b")
        tr.set(b"b", b"y")

    c.run_all([(db, db.run(w1))])
    out = {}

    async def check(tr):
        out["a"] = await tr.get(b"a")
        out["b"] = await tr.get(b"b")

    c.run_all([(db, db.run(check))])
    assert out["a"] is None
    assert out["b"] == b"y"


def test_versionstamp_invalid_offset_rejected():
    c = SimCluster(seed=14)
    db = c.database()
    tr = db.create_transaction()
    with pytest.raises(FdbError) as ei:
        tr.atomic_op(
            MutationType.SET_VERSIONSTAMPED_KEY,
            b"xy" + (100).to_bytes(4, "little"),
            b"v",
        )
    assert ei.value.name == "client_invalid_operation"


def test_limited_range_read_trims_conflict_range():
    """A limit-truncated range read must not conflict with writes beyond the
    returned extent (regression: full-range conflict on limited reads)."""
    c = SimCluster(seed=15)
    db1, db2 = c.database(), c.database()

    async def fill(tr):
        for i in range(6):
            tr.set(b"t%02d" % i, b"v")

    c.run_all([(db1, db1.run(fill))])
    results = []

    async def limited_reader():
        tr = db1.create_transaction()
        try:
            rows = await tr.get_range(b"t", b"u", limit=2)
            assert [k for k, _ in rows] == [b"t00", b"t01"]
            await c.loop.delay(0.05)  # let the far writer commit in between
            tr.set(b"reader_done", b"1")
            await tr.commit()
            results.append("reader_committed")
        except FdbError as e:
            results.append(f"reader_{e.name}")

    async def far_writer():
        tr = db2.create_transaction()
        await tr.get_read_version()
        tr.set(b"t05", b"clobber")  # beyond the reader's returned extent
        await tr.commit()
        results.append("writer_committed")

    c.run_all([(db1, limited_reader()), (db2, far_writer())])
    assert "reader_committed" in results and "writer_committed" in results


def test_causal_consistency_across_clients():
    """Sideband-style: after A commits, B's fresh snapshot must see it."""
    c = SimCluster(seed=7)
    a, b = c.database(), c.database()
    out = {}

    async def writer(tr):
        tr.set(b"flag", b"1")

    c.run_all([(a, a.run(writer))])

    async def reader(tr):
        out["v"] = await tr.get(b"flag")

    c.run_all([(b, b.run(reader))])
    assert out["v"] == b"1"


def test_determinism_same_seed_same_history():
    def run(seed):
        c = SimCluster(seed=seed)
        dbs = [c.database() for _ in range(3)]
        log = []

        def w(db, i):
            async def go():
                for j in range(5):
                    async def op(tr):
                        v = await tr.get(b"x")
                        tr.set(b"x", (v or b"") + b"%d" % i)

                    await db.run(op)
                log.append((i, round(c.loop.now(), 9)))

            return go()

        c.run_all([(db, w(db, i)) for i, db in enumerate(dbs)])
        final = {}

        async def check(tr):
            final["x"] = await tr.get(b"x")

        c.run_all([(dbs[0], dbs[0].run(check))])
        return log, final["x"]

    assert run(11) == run(11)
    assert run(11) != run(12)


def test_differential_cpu_vs_jax_backend(monkeypatch):
    """The same seeded workload must produce identical commit/abort history
    and final state on the CPU and JAX conflict backends (the BASELINE.json
    acceptance property).

    Pinned to pipeline depth 1: cross-BACKEND history identity includes
    reply timing, and the ISSUE-11 async offload defers jax-backend
    replies by design (a CPU backend has nothing to pipeline).  The
    pipelined path's own verdict/state identity across depths is gated
    by tests/test_resolver_pipeline.py."""
    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", "1")

    def run(backend):
        c = SimCluster(seed=99, conflict_backend=backend)
        dbs = [c.database() for _ in range(3)]
        history = []

        def w(db, i):
            async def go():
                rng = c.loop.rng
                for j in range(6):
                    tr = db.create_transaction()
                    try:
                        k = b"d/%d" % int(rng.random_int(0, 5))
                        v = await tr.get(k)
                        tr.set(k, (v or b"") + b"%d" % i)
                        ver = await tr.commit()
                        history.append((i, j, "ok"))
                    except FdbError as e:
                        history.append((i, j, e.name))

            return go()

        c.run_all([(db, w(db, i)) for i, db in enumerate(dbs)], timeout_vt=5000.0)
        out = {}

        async def check(tr):
            out["all"] = await tr.get_range(b"d/", b"d0")

        c.run_all([(dbs[0], dbs[0].run(check))])
        return history, out["all"]

    h_cpu, s_cpu = run("cpu")
    h_jax, s_jax = run("jax")
    assert h_cpu == h_jax
    assert s_cpu == s_jax


def test_limited_range_read_pages_past_local_clears():
    """Regression (ADVICE r1): a limited get_range must keep fetching when
    local clears mask base rows — storage has p1..p5, the txn cleared p1,p2,
    limit=3 must still return [p3, p4, p5], not just [p3]."""
    c = SimCluster(seed=21)
    db = c.database()
    out = {}

    async def fill(tr):
        for i in range(1, 6):
            tr.set(b"p%d" % i, b"v%d" % i)

    async def read(tr):
        tr.clear_range(b"p1", b"p3")  # masks p1, p2
        out["fwd"] = await tr.get_range(b"p", b"q", limit=3)
        out["rev"] = await tr.get_range(b"p", b"q", limit=5, reverse=True)

    c.run_all([(db, db.run(fill))])
    c.run_all([(db, db.run(read))])
    assert [k for k, _ in out["fwd"]] == [b"p3", b"p4", b"p5"]
    assert [k for k, _ in out["rev"]] == [b"p5", b"p4", b"p3"]
