"""Commit-path span tracing + Perfetto timeline export (ISSUE 12).

The headline gates:

1. Same-seed byte identity: `spans_json` AND the `cli trace-export`
   Perfetto artifact of a pipelined resolve run are byte-identical
   across two same-seed runs, and diverge across seeds.
2. Pipeline overlap is VISIBLE: a depth-2 run produces overlapping
   dispatch/apply sibling spans (batch N's mirror apply inside batch
   N+1's device in-flight window on the event-sequence clock) and a
   pipeline_overlap_efficiency gauge > 0; depth 1 stays at 0.
3. The flight recorder embeds the recent span window in captures.
4. Perfetto schema: every ph:B has a matching, properly nested ph:E and
   pids/tids are stable per role (flow/trace_export.validate_perfetto).
5. Phase attribution: the FDB_TPU_ABLATE subtractive harness yields a
   deterministic per-phase FLOP split recorded as child spans of the
   dispatch span.

Shape discipline (1-core CI host): key_words=3 + bucket_mins=(32, 128,
64) + h_cap=1<<10 — the static shapes test_device_faults and
test_resolver_pipeline already compile, so this module's marginal
compile cost in a full run is near zero.
"""

import json

import pytest

from foundationdb_tpu.conflict.api import ConflictSet
from foundationdb_tpu.conflict.types import TransactionConflictInfo as T
from foundationdb_tpu.flow import DeterministicRandom, set_event_loop
from foundationdb_tpu.flow.knobs import g_env
from foundationdb_tpu.flow.spans import (
    NULL_SPAN,
    SpanHub,
    begin_span,
    global_span_hub,
    interval_overlap,
    overlap_efficiency,
    set_global_span_hub,
    span_latency_summary,
    use_span,
)
from foundationdb_tpu.flow.trace_export import (
    perfetto_json,
    perfetto_trace,
    validate_perfetto,
)

pytestmark = pytest.mark.spans

WINDOW = 40


@pytest.fixture(autouse=True)
def _fresh_hub():
    old = global_span_hub()
    set_global_span_hub(SpanHub())
    yield
    set_global_span_hub(old)
    set_event_loop(None)


def k(i: int) -> bytes:
    return b"%08d" % i


def _random_stream(seed, keyspace, batches, txns_per_batch, snap_lag=25):
    rng = DeterministicRandom(seed)
    version = 10
    out = []
    for _ in range(batches):
        txns = []
        for _ in range(rng.random_int(1, txns_per_batch + 1)):
            tr = T(read_snapshot=max(0, version - rng.random_int(0, snap_lag)))
            for _ in range(rng.random_int(0, 3)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
                tr.read_ranges.append((k(a), k(b)))
            for _ in range(rng.random_int(0, 3)):
                a = rng.random_int(0, keyspace // 4)
                b = a + 1 + rng.random_int(0, 4)
                tr.write_ranges.append((k(a), k(b)))
            txns.append(tr)
        version += rng.random_int(1, 10)
        out.append((txns, version, max(0, version - WINDOW)))
    return out


# ---------------------------------------------------------------------------
# unit: span core, overlap math, disabled mode
# ---------------------------------------------------------------------------


def test_span_parenting_stack_and_rings():
    hub = global_span_hub()
    with begin_span("outer", role="R") as outer:
        with begin_span("inner") as inner:
            assert inner.parent_id == outer.span_id
            assert inner.role == "R"  # inherited from the stack parent
        detached = begin_span("held", parent=outer)
    detached.end({"k": 1})
    ring = hub.spans(role="R")
    assert [s.name for s in ring] == ["inner", "outer", "held"]
    assert ring[-1].attrs == {"k": 1}
    # seq pairs are strictly ordered begin<end and unique.
    stamps = sorted(x for s in ring for x in (s.seq, s.end_seq))
    assert stamps == sorted(set(stamps))
    assert all(s.seq < s.end_seq for s in ring)
    # Ring bound holds.
    small = SpanHub(per_role=16)
    set_global_span_hub(small)
    for i in range(50):
        begin_span("x", role="A").end()
    assert len(small.rings["A"]) == 16 and small.begun == 50


def test_spans_disabled_by_env(monkeypatch):
    monkeypatch.setenv("FDB_TPU_SPANS", "0")
    sp = begin_span("x", role="A")
    assert sp is NULL_SPAN
    with sp:
        with use_span(sp):
            sp.annotate("k", 1).end()
    assert global_span_hub().rings == {}


def test_interval_overlap_math():
    # Disjoint: no overlap.
    assert interval_overlap([(0, 2), (2, 4)]) == (4.0, 4.0)
    # Fully double-buffered: half the total is overlapped.
    total, union = interval_overlap([(0, 2), (0, 2)])
    assert (total, union) == (4.0, 2.0)
    # Partial, unsorted input.
    total, union = interval_overlap([(3, 7), (0, 4)])
    assert (total, union) == (8.0, 7.0)
    assert interval_overlap([]) == (0.0, 0.0)


def test_env_flags_registered():
    decl = g_env.declared()
    for name in ("FDB_TPU_SPANS", "FDB_TPU_SPANS_PER_ROLE"):
        _default, help_ = decl[name]
        assert help_ != "", name


# ---------------------------------------------------------------------------
# ConflictSet pipeline: determinism, overlap, schema
# ---------------------------------------------------------------------------


def _device_set(monkeypatch, depth, **kw):
    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", str(depth))
    kw.setdefault("backend", "jax")
    kw.setdefault("key_words", 3)
    kw.setdefault("bucket_mins", (32, 128, 64))
    kw.setdefault("h_cap", 1 << 10)
    return ConflictSet(**kw)


def _drive_pipelined(cs, stream, depth):
    entries = []
    for txns, now, nov in stream:
        entries.append(cs.pipeline_submit(txns, now, nov))
        while cs.pipeline_inflight > depth - 1:
            cs.pipeline_complete_oldest()
    cs.pipeline_drain()
    assert all(e.done for e in entries)
    return entries


def test_pipeline_spans_json_and_perfetto_byte_identical(monkeypatch):
    def run(seed):
        set_global_span_hub(SpanHub())
        cs = _device_set(monkeypatch, 2)
        _drive_pipelined(cs, _random_stream(seed, 60, 10, 8), 2)
        hub = global_span_hub()
        return hub.spans_json(), perfetto_json(hub)

    a_spans, a_trace = run(3)
    b_spans, b_trace = run(3)
    assert a_spans == b_spans
    assert a_trace == b_trace
    c_spans, c_trace = run(5)
    assert c_spans != a_spans and c_trace != a_trace


def test_pipeline_device_spans_overlap_at_depth2(monkeypatch):
    cs = _device_set(monkeypatch, 2)
    _drive_pipelined(cs, _random_stream(3, 60, 10, 8), 2)
    hub = global_span_hub()
    dev = hub.spans(name="device")
    assert len(dev) == 10
    assert overlap_efficiency(dev, axis="seq") > 0.0
    assert overlap_efficiency(dev, axis="wall") > 0.0
    # Depth 1 (the synchronous before-arm): zero overlap by construction.
    set_global_span_hub(SpanHub())
    cs1 = _device_set(monkeypatch, 1)
    for txns, now, nov in _random_stream(3, 60, 10, 8):
        b = cs1.new_batch()
        for t in txns:
            b.add_transaction(t)
        b.detect_conflicts(now, nov)
    dev1 = global_span_hub().spans(name="device")
    assert dev1 and overlap_efficiency(dev1, axis="seq") == 0.0


def test_perfetto_schema_and_stable_pids(monkeypatch):
    cs = _device_set(monkeypatch, 2)
    _drive_pipelined(cs, _random_stream(7, 60, 8, 8), 2)
    doc = perfetto_trace(global_span_hub())
    assert validate_perfetto(doc) == []
    events = doc["traceEvents"]
    assert sum(1 for e in events if e["ph"] == "B") == sum(
        1 for e in events if e["ph"] == "E"
    ) > 0
    # role -> pid mapping is injective and each pid is named once.
    role_pids = {}
    for e in events:
        if e["ph"] == "B":
            role_pids.setdefault(e["cat"], set()).add(e["pid"])
    assert all(len(p) == 1 for p in role_pids.values())
    # A corrupted doc fails the gate.
    bad = json.loads(json.dumps(doc))
    for e in bad["traceEvents"]:
        if e["ph"] == "E":
            bad["traceEvents"].remove(e)
            break
    assert validate_perfetto(bad) != []


def test_lane_assignment_is_parent_aware(monkeypatch):
    """Regression (review): stage children must render on their OWN
    batch's lane.  Batch N+1's encode begins inside batch N's window —
    a purely geometric first-fit nested it under batch N's slice."""
    stream = _random_stream(3, 60, 10, 8)
    loop, r, dproc = _resolver_rig(3, 2, monkeypatch)
    _drive_resolver(loop, r, dproc, stream)
    hub = global_span_hub()
    doc = perfetto_trace(hub)
    assert validate_perfetto(doc) == []
    lane = {e["args"]["span"]: e["tid"] for e in doc["traceEvents"]
            if e["ph"] == "B"}
    by_id = {s.span_id: s for s in hub.spans()}
    checked = 0
    for s in by_id.values():
        p = by_id.get(s.parent_id)
        if p is None:
            continue
        if s.seq < p.end_seq:  # child begins inside its parent's window
            assert lane[s.span_id] == lane[p.span_id], (
                f"{s.name} (batch {s.attrs.get('version')}) on lane "
                f"{lane[s.span_id]}, parent {p.name} on {lane[p.span_id]}"
            )
            checked += 1
    assert checked > 0
    # Concurrent ROOT batch spans stay side by side, never nested.
    role = r.metrics.name
    roots = [s for s in hub.spans(role=role, name="resolve_batch")]
    overlapping = [
        (a, b) for a in roots for b in roots
        if a.span_id < b.span_id and b.seq < a.end_seq
    ]
    assert overlapping, "no concurrent batch spans — rig not pipelining"
    assert all(lane[a.span_id] != lane[b.span_id] for a, b in overlapping)


# ---------------------------------------------------------------------------
# Resolver role: stage tree, overlap gauge, sibling overlap, witnesses
# ---------------------------------------------------------------------------


def _resolver_rig(seed, depth, monkeypatch):
    from foundationdb_tpu.flow.eventloop import EventLoop
    from foundationdb_tpu.rpc.network import SimNetwork
    from foundationdb_tpu.server.resolver import Resolver

    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", str(depth))
    loop = EventLoop(seed)
    set_event_loop(loop)
    net = SimNetwork(loop)
    cs = ConflictSet(
        backend="jax", key_words=3, bucket_mins=(32, 128, 64),
        h_cap=1 << 10,
    )
    r = Resolver(net.process("resolver"), conflict_set=cs)
    return loop, r, net.process("driver")


def _drive_resolver(loop, resolver, dproc, stream, cadence=0.002):
    from foundationdb_tpu.server.interfaces import (
        ResolveTransactionBatchRequest,
    )

    iface = resolver.interface()

    async def drive():
        prev = 0
        futs = []
        for txns, now, _nov in stream:
            futs.append(iface.resolve.get_reply(
                dproc,
                ResolveTransactionBatchRequest(
                    prev_version=prev, version=now,
                    last_received_version=prev, transactions=txns,
                    proxy_id="p0",
                ),
            ))
            prev = now
            await loop.delay(cadence)
        return [(await f).committed for f in futs]

    return loop.run_until(dproc.spawn(drive(), "drive"), timeout_vt=600.0)


def test_resolver_stage_tree_overlap_gauge_and_witnesses(monkeypatch):
    stream = _random_stream(7, 60, 12, 8)
    loop, r, dproc = _resolver_rig(7, 2, monkeypatch)
    _drive_resolver(loop, r, dproc, stream)
    hub = global_span_hub()
    role = r.metrics.name
    names = {s.name for s in hub.spans(role=role)}
    # The full per-batch stage set rides the resolver's track.
    assert {"resolve_batch", "encode", "dispatch", "device", "sync",
            "apply", "reply"} <= names
    # Stage spans are CHILDREN of their batch span (parent links).
    batches = {s.span_id: s for s in hub.spans(role=role,
                                               name="resolve_batch")}
    for name in ("encode", "dispatch", "device", "sync", "apply", "reply"):
        staged = hub.spans(role=role, name=name)
        assert staged and all(s.parent_id in batches for s in staged), name
    # Overlap: the gauge is live and > 0, and batch N's apply span sits
    # INSIDE a different batch's device window on the seq clock — the
    # "overlapping dispatch/apply sibling spans" shape.
    snap = r.metrics.snapshot()
    assert snap["gauges"]["pipeline_overlap_efficiency"] > 0.0
    devs = hub.spans(role=role, name="device")
    applies = hub.spans(role=role, name="apply")
    assert any(
        d.attrs["version"] != a.attrs["version"]
        and d.seq < a.seq < d.end_seq
        for d in devs for a in applies
    ), "no apply span overlapped another batch's device window"
    # Conflict witnesses: the Zipf-ish write keyspace forces aborts.
    assert snap["counters"]["witness_aborts"] > 0
    topk = json.loads(snap["gauges"]["conflict_witness_topk"])
    assert topk and all(len(row) == 3 for row in topk)
    w = r.conflict_witness()
    assert w["aborts"] == snap["counters"]["witness_aborts"]
    assert w["topk"] == topk
    # Depth 1: same stream, gauge stays 0 (no device span ever overlaps).
    set_global_span_hub(SpanHub())
    set_event_loop(None)
    loop1, r1, dproc1 = _resolver_rig(7, 1, monkeypatch)
    _drive_resolver(loop1, r1, dproc1, stream)
    assert r1.metrics.snapshot()["gauges"][
        "pipeline_overlap_efficiency"] == 0.0


def test_overlap_gauge_excludes_faulted_and_replayed_spans(monkeypatch):
    """Regression (review): mirror-replayed device spans all end at
    DRAIN time with near-identical intervals — folding their mutual
    'overlap' into the gauge would report high efficiency exactly when
    the device did no useful work."""
    from foundationdb_tpu.conflict.device_faults import DeviceFaultInjector

    from foundationdb_tpu.flow.eventloop import EventLoop
    from foundationdb_tpu.rpc.network import SimNetwork
    from foundationdb_tpu.server.resolver import Resolver

    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", "3")
    loop = EventLoop(11)
    set_event_loop(loop)
    net = SimNetwork(loop)
    inj = DeviceFaultInjector()
    # Every dispatch from #2 on faults: parked batches drain onto the
    # mirror, so NO device span ever completes a verified sync.
    for at in range(2, 40):
        inj.script("dispatch", at=at, persist=1)
    cs = ConflictSet(
        backend="jax", key_words=3, bucket_mins=(32, 128, 64),
        h_cap=1 << 10, fault_injector=inj,
    )
    r = Resolver(net.process("resolver"), conflict_set=cs)
    _drive_resolver(loop, r, net.process("driver"),
                    _random_stream(11, 60, 10, 8))
    snap = r.metrics.snapshot()
    assert snap["counters"]["degraded_batches"] > 0  # faults really hit
    assert snap["gauges"]["pipeline_overlap_efficiency"] == 0.0


def test_flight_recorder_capture_embeds_span_window(monkeypatch):
    from foundationdb_tpu.flow.flight_recorder import (
        FlightRecorder,
        global_flight_recorder,
        set_global_flight_recorder,
    )

    old_rec = global_flight_recorder()
    set_global_flight_recorder(FlightRecorder())
    try:
        cs = _device_set(monkeypatch, 2)
        _drive_pipelined(cs, _random_stream(3, 60, 6, 8), 2)
        art = global_flight_recorder().capture("unit", now=1.0)
        assert "spans" in art
        all_spans = [s for spans in art["spans"].values() for s in spans]
        assert any(s["name"] == "device" for s in all_spans)
        # Wall fields never enter the artifact (byte-identity contract).
        assert "wall_start" not in json.dumps(art)
    finally:
        set_global_flight_recorder(old_rec)


def test_span_latency_summary_shapes(monkeypatch):
    stream = _random_stream(9, 60, 8, 6)
    loop, r, dproc = _resolver_rig(9, 2, monkeypatch)
    _drive_resolver(loop, r, dproc, stream)
    summary = span_latency_summary(global_span_hub())
    stages = summary[r.metrics.name]
    assert stages["resolve_batch"]["count"] == len(stream)
    for key in ("p50", "p90", "p99", "max"):
        assert stages["resolve_batch"][key] is not None
    # device spans cross awaits at depth 2: nonzero virtual duration.
    assert stages["device"]["max"] > 0.0


# ---------------------------------------------------------------------------
# phase attribution (the FDB_TPU_ABLATE subtractive harness)
# ---------------------------------------------------------------------------


def test_phase_attribution_deterministic_and_recorded(monkeypatch):
    from foundationdb_tpu.conflict.phase_attribution import attribute_phases

    cs = _device_set(monkeypatch, 1)
    stream = _random_stream(3, 60, 3, 8)
    for txns, now, nov in stream:
        b = cs.new_batch()
        for t in txns:
            b.add_transaction(t)
        b.detect_conflicts(now, nov)
    rep1 = attribute_phases(cs._jax, stream[-1][0])
    rep2 = attribute_phases(cs._jax, stream[-1][0], record=False)

    def det(r):
        return json.dumps(
            {k: r[k] for k in ("phases", "full", "residual_flops")},
            sort_keys=True,
        )

    assert det(rep1) == det(rep2)
    assert rep1["full"]["flops"] > 0
    # Shares partition (no double count) and something was attributed.
    assert sum(p["flops"] for p in rep1["phases"]) > 0
    assert sum(p["share"] for p in rep1["phases"]) <= 1.001
    # Child spans landed under the engine's last dispatch span.
    hub = global_span_hub()
    dispatch_id = cs._jax.last_dispatch_span.span_id
    phase_spans = [s for s in hub.spans() if s.name.startswith("phase.")]
    assert {s.name for s in phase_spans} == {
        "phase.search", "phase.fixpoint", "phase.merge", "phase.evict"
    }
    assert all(s.parent_id == dispatch_id for s in phase_spans)


def test_phase_attribution_rejects_tiered(monkeypatch):
    from foundationdb_tpu.conflict.phase_attribution import attribute_phases

    class _Tiered:
        tiered = True

    with pytest.raises(ValueError):
        attribute_phases(_Tiered())


# ---------------------------------------------------------------------------
# acceptance: cli trace-export of a pipelined cluster run
# ---------------------------------------------------------------------------


def _cluster_run(seed, n_commits=6):
    """One SimCluster run at the default pipeline depth (2): commits,
    phase attribution on the live engine, then the CLI export.  Returns
    (export blob, spans_json, status doc, latency lines, metrics-diff
    first line)."""
    from foundationdb_tpu.conflict.phase_attribution import attribute_phases
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.server.status import cluster_status
    from foundationdb_tpu.tools.cli import CliProcessor

    set_global_span_hub(SpanHub())
    c = SimCluster(seed=seed, conflict_backend="jax")
    db = c.database("sp")
    cli = CliProcessor(c, db)

    async def load():
        for i in range(n_commits):
            tr = db.create_transaction()
            tr.set(b"sp/%02d" % i, b"v")
            await tr.commit()
        await c.loop.delay(1.0)  # idle flush drains the pipeline tail

    c.run_until(db.process.spawn(load(), "load"), timeout_vt=5000.0)
    attribute_phases(c.resolver.conflicts._jax)  # device phase children

    def drive(line):
        return c.loop.run_until(
            db.process.spawn(cli.run_command(line)), timeout_vt=60.0
        )

    export = drive("trace-export")
    assert len(export) == 1
    latency = drive("latency")
    diff_first = drive("metrics --diff")[0]
    doc = cluster_status(c)
    out = (export[0], global_span_hub().spans_json(), doc, latency,
           diff_first)
    set_event_loop(None)
    return out


def test_cli_trace_export_acceptance(monkeypatch):
    """The acceptance criterion: `cli trace-export` of a pipelined
    resolve run is valid Chrome trace-event JSON, byte-identical across
    same-seed runs, with the per-batch stage spans and the device
    phase-attribution child spans present."""
    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", "2")
    blob1, spans1, status1, latency1, diff_first = _cluster_run(4242)
    blob2, spans2, _s, _l, _d = _cluster_run(4242)
    assert blob1 == blob2, "same-seed trace-export is not byte-identical"
    assert spans1 == spans2
    doc = json.loads(blob1)
    assert validate_perfetto(doc) == []
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "B"}
    for required in ("resolve_batch", "encode", "dispatch", "device",
                     "sync", "apply", "reply", "commit_batch",
                     "get_version", "resolution", "log_push", "tlog_push",
                     "phase.search", "phase.fixpoint", "phase.merge",
                     "phase.evict"):
        assert required in names, f"span {required!r} missing from export"
    # Different seed diverges.
    blob3, _sp, _st, _la, _di = _cluster_run(4243)
    assert blob3 != blob1
    # Status carries the span inventory + qos witness fields.
    cl = status1["cluster"]
    assert cl["spans"]["begun"] > 0 and cl["spans"]["roles"]
    assert "conflict_witness_aborts" in cl["qos"]
    assert "conflict_witness_topk" in cl["qos"]
    # cli latency defaults to the span layer.
    assert latency1[0].startswith("per-stage span latency")
    assert any("resolve_batch" in ln for ln in latency1)
    # metrics --diff with no prior snapshot says so.
    assert diff_first.startswith("(no prior snapshot")


# ---------------------------------------------------------------------------
# bench: the overlap metric rides the pipeline arms
# ---------------------------------------------------------------------------


def test_bench_pipeline_reports_overlap(monkeypatch):
    import sys

    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
    import numpy as np

    import bench

    rate, overlap = bench.bench_pipeline(
        np.random.default_rng(7), 2, n_batches=4, per_batch=48,
        h_cap=1 << 12, window=4,
    )
    assert rate > 0
    assert set(overlap) == {"wall", "seq", "device_spans"}
    assert overlap["device_spans"] == 4
    assert overlap["seq"] > 0.0
    rate1, overlap1 = bench.bench_pipeline(
        np.random.default_rng(7), 1, n_batches=4, per_batch=48,
        h_cap=1 << 12, window=4,
    )
    assert overlap1["seq"] == 0.0
