"""Sampling CPU profiler + runtime toggle.

Ref: flow/Profiler.actor.cpp:99 (SIGPROF sampler), :175 (runtime enable),
fdbserver/workloads/CpuProfiler.actor.cpp (toggle over RPC).
"""

import time

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.profiler import (
    SamplingProfiler,
    get_profiler,
    profiler_toggle,
)


def _busy_marker_fn(deadline):
    acc = 0
    while time.monotonic() < deadline:
        acc += sum(i * i for i in range(200))
    return acc


def test_sampler_catches_hot_function():
    p = SamplingProfiler(interval=0.002)
    p.start()
    assert p.running
    _busy_marker_fn(time.monotonic() + 0.4)
    p.stop()
    assert not p.running
    rep = p.report()
    assert rep["total_samples"] > 10
    names = {h["function"] for h in rep["hot_functions"]}
    assert any(
        "_busy_marker_fn" in n or "<genexpr>" in n for n in names
    ), names


def test_toggle_stops_sampling():
    p = SamplingProfiler(interval=0.002)
    p.start()
    _busy_marker_fn(time.monotonic() + 0.1)
    p.stop()
    n = p.total_samples
    # Stopped: no further samples accumulate.
    _busy_marker_fn(time.monotonic() + 0.15)
    assert p.total_samples == n
    # Restart works (the runtime toggle's whole point).
    p.start()
    _busy_marker_fn(time.monotonic() + 0.15)
    p.stop()
    assert p.total_samples > n


def test_report_shape_and_clear():
    """Report dict shape (what `profile report` renders) + clear()."""
    p = SamplingProfiler(interval=0.002)
    rep = p.report()
    assert rep == {
        "total_samples": 0,
        "interval": 0.002,
        "running": False,
        "hot_functions": [],
    }
    p.start()
    _busy_marker_fn(time.monotonic() + 0.2)
    p.stop()
    rep = p.report(top=3)
    assert rep["total_samples"] > 0
    assert len(rep["hot_functions"]) <= 3
    for h in rep["hot_functions"]:
        assert set(h) == {"function", "file", "line", "samples", "fraction"}
        assert 0.0 <= h["fraction"] <= 1.0
    # Fractions over ALL hot functions sum to <= 1 of total samples.
    assert sum(h["samples"] for h in rep["hot_functions"]) <= rep[
        "total_samples"
    ]
    p.clear()
    rep2 = p.report()
    assert rep2["total_samples"] == 0 and rep2["hot_functions"] == []


def test_global_toggle_helpers():
    """get_profiler() is a process-wide singleton; profiler_toggle drives
    it (the ProfilerRequest/fdbcli `profile` path)."""
    p = get_profiler()
    assert get_profiler() is p
    state = profiler_toggle(True, interval=0.004)
    try:
        assert state["running"] and state["interval"] == 0.004
        assert p.running
    finally:
        state = profiler_toggle(False)
    assert not state["running"] and not p.running


def test_worker_rpc_toggle_and_cli():
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster
    from foundationdb_tpu.server.worker import ProfilerRequest
    from foundationdb_tpu.tools.cli import CliProcessor

    c = DynamicCluster(seed=830, n_workers=5)
    db = c.database()
    wi = c.workers[0].interface()

    async def toggle(enabled):
        return await wi.init_role.get_reply(
            db.process, ProfilerRequest(enabled=enabled, interval=0.002)
        )

    state = c.run_until(db.process.spawn(toggle(True)), timeout_vt=500.0)
    assert state["running"] is True
    _busy_marker_fn(time.monotonic() + 0.2)
    state = c.run_until(db.process.spawn(toggle(False)), timeout_vt=500.0)
    assert state["running"] is False
    assert get_profiler().total_samples > 0

    cli = CliProcessor(c, db)
    out = c.run_until(
        db.process.spawn(cli.run_command("profile report")), timeout_vt=500.0
    )
    assert out and out[0].startswith("Profiler: stopped")
    set_event_loop(None)
    profiler_toggle(False)
