"""Differential tests: JAX device engine vs CPU engine vs oracle.

The acceptance gate from BASELINE.json: identical decisions between the
device engine and the CPU reference across randomized and adversarial batch
streams, including window eviction, rebase, and hybrid handoff.
"""

import numpy as np
import pytest

from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.engine_jax import JaxConflictSet
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.types import (
    COMMITTED,
    CONFLICT,
    TOO_OLD,
    TransactionConflictInfo as T,
)
from foundationdb_tpu.flow import DeterministicRandom


def k(i: int) -> bytes:
    return b"%08d" % i


@pytest.fixture(scope="module")
def jcs_factory():
    def make(**kw):
        kw.setdefault("key_words", 3)
        kw.setdefault("h_cap", 1 << 10)
        # One shared shape bucket across every test in this module: each
        # distinct (txn_cap, rr_cap, wr_cap, h_cap) is a separate multi-minute
        # XLA compile on this 1-core host.
        kw.setdefault("bucket_mins", (32, 128, 64))
        return JaxConflictSet(**kw)

    return make


def test_basic_decisions(jcs_factory):
    cs = jcs_factory()
    s = cs.detect([T(read_snapshot=0, write_ranges=[(k(10), k(20))])], 100, 0)
    assert s == [COMMITTED]
    s = cs.detect(
        [
            T(read_snapshot=99, read_ranges=[(k(15), k(16))]),
            T(read_snapshot=100, read_ranges=[(k(15), k(16))]),
            T(read_snapshot=99, read_ranges=[(k(20), k(25))]),
            T(read_snapshot=99, read_ranges=[(k(5), k(10))]),
            T(read_snapshot=99, read_ranges=[(k(5), k(10) + b"\x00")]),
        ],
        101,
        0,
    )
    assert s == [CONFLICT, COMMITTED, COMMITTED, COMMITTED, CONFLICT]


def test_intra_batch_chain(jcs_factory):
    cs = jcs_factory()
    s = cs.detect(
        [
            T(read_snapshot=0, write_ranges=[(b"x", b"x\x00")]),
            T(
                read_snapshot=0,
                read_ranges=[(b"x", b"x\x00")],
                write_ranges=[(b"y", b"y\x00")],
            ),
            T(read_snapshot=0, read_ranges=[(b"y", b"y\x00")]),
        ],
        10,
        0,
    )
    assert s == [COMMITTED, CONFLICT, COMMITTED]
    # the conflicted txn's write must NOT have entered history
    s2 = cs.detect([T(read_snapshot=5, read_ranges=[(b"y", b"y\x00")])], 11, 0)
    assert s2 == [COMMITTED]
    # but the committed writes did
    s3 = cs.detect([T(read_snapshot=5, read_ranges=[(b"x", b"x\x00")])], 12, 0)
    assert s3 == [CONFLICT]


def test_deep_chain_exactness(jcs_factory):
    # w0 -> r1w1 -> r2w2 -> ... alternating: sequential semantics says
    # odd txns conflict, even commit.  Exercises multi-round fixpoint.
    cs = jcs_factory()
    n = 12
    txns = [T(read_snapshot=0, write_ranges=[(k(0), k(1))])]
    for i in range(1, n):
        txns.append(
            T(
                read_snapshot=0,
                read_ranges=[(k(i - 1), k(i))],
                write_ranges=[(k(i), k(i + 1))],
            )
        )
    got = cs.detect(txns, 10, 0)
    want = OracleConflictSet().detect(txns, 10, 0)
    assert got == want
    assert cs.last_iters > 1  # genuinely needed multiple rounds


def test_too_old_and_window(jcs_factory):
    cs = jcs_factory(oldest_version=50)
    s = cs.detect(
        [
            T(read_snapshot=10, read_ranges=[(k(1), k(2))]),
            T(read_snapshot=10, write_ranges=[(k(1), k(2))]),
            T(read_snapshot=50, read_ranges=[(k(5), k(6))]),
        ],
        60,
        50,
    )
    assert s == [TOO_OLD, COMMITTED, COMMITTED]
    cs2 = jcs_factory()
    cs2.detect([T(read_snapshot=0, write_ranges=[(k(1), k(2))])], 100, 0)
    cs2.detect([], 200, 150)
    s = cs2.detect(
        [
            T(read_snapshot=149, read_ranges=[(k(1), k(2))]),
            T(read_snapshot=150, read_ranges=[(k(1), k(2))]),
        ],
        201,
        150,
    )
    assert s == [TOO_OLD, COMMITTED]


def _random_stream(seed, keyspace, batches, txns_per_batch, snap_lag=25):
    rng = DeterministicRandom(seed)
    version = 10
    out = []
    for _ in range(batches):
        txns = []
        for _ in range(rng.random_int(1, txns_per_batch + 1)):
            tr = T(read_snapshot=max(0, version - rng.random_int(0, snap_lag)))
            for _ in range(rng.random_int(0, 4)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
                tr.read_ranges.append((k(a), k(b)))
            for _ in range(rng.random_int(0, 3)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 10))
                tr.write_ranges.append((k(a), k(b)))
            txns.append(tr)
        now = version + rng.random_int(1, 10)
        new_oldest = max(0, version - snap_lag)
        out.append((txns, now, new_oldest))
        version = now
    return out


@pytest.mark.parametrize(
    "seed,keyspace", [(11, 30), (12, 8), (13, 500), (14, 60), (15, 3)]
)
def test_differential_jax_vs_cpu_vs_oracle(jcs_factory, seed, keyspace):
    jcs = jcs_factory()
    cpu = CpuConflictSet()
    orc = OracleConflictSet()
    for bi, (txns, now, new_oldest) in enumerate(
        _random_stream(seed, keyspace, batches=25, txns_per_batch=20)
    ):
        gj = jcs.detect(txns, now, new_oldest)
        gc = cpu.detect(txns, now, new_oldest)
        go = orc.detect(txns, now, new_oldest)
        assert gj == gc == go, (
            f"batch {bi}: jax={gj} cpu={gc} oracle={go} "
            f"txns={[(t.read_snapshot, t.read_ranges, t.write_ranges) for t in txns]}"
        )


def test_variable_length_keys(jcs_factory):
    rng = DeterministicRandom(7)
    jcs = jcs_factory()
    cpu = CpuConflictSet()
    alphabet = [b"", b"\x00", b"a", b"ab", b"ab\x00", b"abc", b"b", b"\xff", b"\xff\xff"]
    version = 5
    for _ in range(30):
        txns = []
        for _ in range(rng.random_int(1, 10)):
            tr = T(read_snapshot=max(0, version - rng.random_int(0, 10)))
            for _ in range(rng.random_int(0, 3)):
                a, b = rng.random_choice(alphabet), rng.random_choice(alphabet)
                if a > b:
                    a, b = b, a
                tr.read_ranges.append((a, b))
            for _ in range(rng.random_int(0, 3)):
                a, b = rng.random_choice(alphabet), rng.random_choice(alphabet)
                if a > b:
                    a, b = b, a
                tr.write_ranges.append((a, b))
            txns.append(tr)
        now = version + rng.random_int(1, 5)
        new_oldest = max(0, version - 8)
        assert jcs.detect(txns, now, new_oldest) == cpu.detect(txns, now, new_oldest)
        version = now


def test_history_growth_and_eviction_bound(jcs_factory):
    # many disjoint writes; window advances right behind -> history stays small
    jcs = jcs_factory(h_cap=1 << 9)
    cpu = CpuConflictSet()
    v = 0
    for i in range(40):
        txns = [
            T(read_snapshot=v, write_ranges=[(k(100 * i + j), k(100 * i + j + 2))])
            for j in range(0, 20, 2)
        ]
        assert jcs.detect(txns, v + 5, v) == cpu.detect(txns, v + 5, v)
        v += 5
    assert jcs.boundary_count == cpu.boundary_count


def test_hybrid_handoff():
    from foundationdb_tpu.conflict.api import ConflictSet
    from foundationdb_tpu.flow.knobs import g_knobs

    old_min = g_knobs.server.conflict_device_min_batch
    g_knobs.server.conflict_device_min_batch = 4
    try:
        hyb = ConflictSet(backend="hybrid", key_words=3, bucket_mins=(32, 128, 64))
        orc = OracleConflictSet()
        for bi, (txns, now, new_oldest) in enumerate(
            _random_stream(21, 40, batches=20, txns_per_batch=12)
        ):
            if bi % 3 == 2:  # force a small batch -> CPU path
                txns = txns[:2]
            b = hyb.new_batch()
            for t in txns:
                b.add_transaction(t)
            got = b.detect_conflicts(now, new_oldest)
            want = orc.detect(txns, now, new_oldest)
            assert got == want, f"batch {bi}: hybrid={got} oracle={want}"
    finally:
        g_knobs.server.conflict_device_min_batch = old_min


def test_long_keys_route_to_cpu():
    from foundationdb_tpu.conflict.api import ConflictSet
    from foundationdb_tpu.flow.knobs import g_knobs

    old_min = g_knobs.server.conflict_device_min_batch
    g_knobs.server.conflict_device_min_batch = 1
    try:
        hyb = ConflictSet(backend="hybrid", key_words=3)
        long_key = b"z" * 100  # > 12 bytes: must fall back, not truncate
        b = hyb.new_batch()
        b.add_transaction(T(read_snapshot=0, write_ranges=[(long_key, long_key + b"\x01")]))
        assert b.detect_conflicts(10, 0) == [COMMITTED]
        b2 = hyb.new_batch()
        b2.add_transaction(T(read_snapshot=5, read_ranges=[(long_key, long_key + b"\x01")]))
        assert b2.detect_conflicts(11, 0) == [CONFLICT]
    finally:
        g_knobs.server.conflict_device_min_batch = old_min


def test_fixpoint_divergence_falls_back_to_cpu(jcs_factory, monkeypatch):
    """Adversarial: if the device fixpoint reports non-convergence, the batch
    must be resolved on the CPU engine against pristine state (VERDICT r1
    item 10) — and the engine must keep matching the CPU reference afterward
    (state round-trips through store_to/load_from)."""
    import jax.numpy as jnp

    from foundationdb_tpu.conflict import engine_jax as ej

    jcs = jcs_factory()
    ref = CpuConflictSet()
    # Patch the jit entry the engine actually dispatches through (it used
    # to patch the unused _detect_step alias, which exercised nothing) —
    # mode-aware, so the FDB_TPU_HISTORY=tiered run of this suite
    # exercises the tiered store_to/load_from fallback path too.
    step_name = "_tiered_blob_step" if jcs.tiered else "_blob_step"
    real_step = getattr(ej, step_name)

    def diverged_step(*state_and_blob, **caps):
        # What the core returns when the fixpoint cap is hit: pristine
        # state (every arg but the trailing blob — the final state slot is
        # oldest, doubling as the reverted new_oldest), garbage statuses,
        # undecided > 0.  Works for both entry points: flat state is
        # (hkeys, hvers, hcount, oldest), tiered adds (maxtab, dkeys,
        # dvers, dcount) before oldest.
        state = state_and_blob[:-1]
        return state + (
            jnp.zeros((caps["txn_cap"],), jnp.int32),
            jnp.asarray(1, jnp.int32),
            jnp.asarray(caps["txn_cap"] + 2, jnp.int32),
        )

    for bi, (txns, now, new_oldest) in enumerate(
        _random_stream(31, 40, batches=9, txns_per_batch=12)
    ):
        step = diverged_step if 3 <= bi < 6 else real_step
        monkeypatch.setattr(ej, step_name, step)
        got = jcs.detect(txns, now, new_oldest)
        want = ref.detect(txns, now, new_oldest)
        assert got == want, f"batch {bi}: jax={got} cpu={want}"
    monkeypatch.setattr(ej, step_name, real_step)


def test_hybrid_authority_hysteresis():
    """Alternating big/small batches must not transfer history per batch:
    once device authority is held, small batches run on-device too."""
    from foundationdb_tpu.conflict.api import ConflictSet
    from foundationdb_tpu.flow.knobs import g_knobs

    old_min = g_knobs.server.conflict_device_min_batch
    g_knobs.server.conflict_device_min_batch = 8
    try:
        hyb = ConflictSet(backend="hybrid", key_words=3, bucket_mins=(32, 128, 64))
        orc = OracleConflictSet()
        transfers = {"load": 0, "store": 0}
        real_load, real_store = hyb._jax.load_from, hyb._jax.store_to

        def load(cpu):
            transfers["load"] += 1
            real_load(cpu)

        def store(cpu):
            transfers["store"] += 1
            real_store(cpu)

        hyb._jax.load_from, hyb._jax.store_to = load, store
        for bi, (txns, now, new_oldest) in enumerate(
            _random_stream(41, 40, batches=16, txns_per_batch=12)
        ):
            if bi % 2 == 1:
                txns = txns[:2]  # alternate below the device threshold
            b = hyb.new_batch()
            for t in txns:
                b.add_transaction(t)
            got = b.detect_conflicts(now, new_oldest)
            assert got == orc.detect(txns, now, new_oldest), f"batch {bi}"
        assert transfers["load"] == 1, transfers  # one initial handoff
        assert transfers["store"] == 0, transfers  # never thrashes back
    finally:
        g_knobs.server.conflict_device_min_batch = old_min


def test_multiword_key_ordering_differential():
    """Keys differing in BOTH 4-byte words: the word-significance convention
    must agree between encode, lex compare, search, and the point sort
    (regression: lex_less once treated the least significant word as most
    significant, masked because earlier tests never exercised multiword
    divergence)."""
    from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
    from foundationdb_tpu.conflict.engine_jax import JaxConflictSet
    from foundationdb_tpu.conflict.types import TransactionConflictInfo as T

    k1 = (0x00000001_00000002).to_bytes(8, "big")
    k2 = (0x00000002_00000001).to_bytes(8, "big")
    k3 = (0x00000001_00000003).to_bytes(8, "big")
    cpu, dev = CpuConflictSet(), JaxConflictSet(key_words=3, h_cap=64)
    up = lambda k: k + b"\x00"
    write_k1 = [T(read_snapshot=0, read_ranges=[], write_ranges=[(k1, up(k1))])]
    for eng in (cpu, dev):
        eng.detect(write_k1, now=1, new_oldest_version=0)
    probes = [
        T(read_snapshot=0, read_ranges=[(k1, up(k1))], write_ranges=[]),
        T(read_snapshot=0, read_ranges=[(k2, up(k2))], write_ranges=[]),
        T(read_snapshot=0, read_ranges=[(k3, up(k3))], write_ranges=[]),
        T(read_snapshot=0, read_ranges=[(k1, k2)], write_ranges=[]),
    ]
    got_cpu = cpu.detect(probes, now=2, new_oldest_version=0)
    got_dev = dev.detect(probes, now=2, new_oldest_version=0)
    assert got_cpu == got_dev
    # k1 was written at v1 > snapshot 0 -> conflict; k2/k3 untouched.
    from foundationdb_tpu.conflict.types import COMMITTED, CONFLICT

    assert got_cpu == [CONFLICT, COMMITTED, COMMITTED, CONFLICT]
