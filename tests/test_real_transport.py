"""The sim<->real swap point: the SAME role objects served over real TCP
sockets across OS processes.

Ref: fdbserver.actor.cpp:1468-1473 (Net2 vs Sim2 selection),
FlowTransport.actor.cpp (framed TCP + token dispatch).  Three OS processes
on localhost: one server hosting the write pipeline, two clients running
serializable increment transactions concurrently.
"""

import signal
import subprocess

import pytest

from conftest import spawn_real_node


def test_three_process_localhost_cluster():
    server = spawn_real_node(*["server"])
    try:
        ready = server.stdout.readline().strip()
        assert ready.startswith("READY "), ready
        addr = ready.split()[1]

        # Two concurrent clients, 15 serializable increments each.
        c1 = spawn_real_node(*["client", addr, "--id", "a", "--ops", "15"])
        c2 = spawn_real_node(*["client", addr, "--id", "b", "--ops", "15"])
        out1, _ = c1.communicate(timeout=90)
        out2, _ = c2.communicate(timeout=90)
        assert c1.returncode == 0, out1
        assert c2.returncode == 0, out2

        # A third client verifies the serializable total: 30 increments
        # through conflicting read-modify-write transactions.
        c3 = spawn_real_node(
            "client", addr, "--id", "v", "--ops", "0", "--check-count", "30"
        )
        out3, _ = c3.communicate(timeout=90)
        assert c3.returncode == 0, out3
        assert "DONE 30" in out3, out3
    finally:
        server.send_signal(signal.SIGTERM)
        try:
            server.wait(timeout=5)
        except subprocess.TimeoutExpired:
            server.kill()


@pytest.mark.slow  # tier-1 headroom (ISSUE 4): real-OS-process integration soak
def test_real_server_durable_restart(tmp_path):
    """A real-OS-process server with the native C++ engine: kill it hard,
    restart on the same datadir, and committed data must still be there
    (ref: the storage-engine recovery contract, IKeyValueStore.h:43)."""
    datadir = str(tmp_path / "data")
    server = spawn_real_node(*["server", "--datadir", datadir])
    try:
        ready = server.stdout.readline().strip()
        addr = ready.split()[1]
        c1 = spawn_real_node(*["client", addr, "--id", "d", "--ops", "12"])
        out1, _ = c1.communicate(timeout=90)
        assert c1.returncode == 0, out1
    finally:
        server.kill()
        server.wait()

    server2 = spawn_real_node(*["server", "--datadir", datadir])
    try:
        ready2 = server2.stdout.readline().strip()
        addr2 = ready2.split()[1]
        # The verifier writes nothing; the counter and the idempotence
        # markers written before the kill must have survived.
        c2 = spawn_real_node(*["client", addr2, "--id", "v", "--ops", "0",
                     "--check-count", "12"])
        out2, _ = c2.communicate(timeout=90)
        assert c2.returncode == 0, out2
        assert "DONE 12" in out2, out2
    finally:
        server2.send_signal(signal.SIGTERM)
        try:
            server2.wait(timeout=5)
        except subprocess.TimeoutExpired:
            server2.kill()


@pytest.mark.slow  # tier-1 headroom (ISSUE 4): real-OS-process integration soak
def test_real_server_killed_mid_load(tmp_path):
    """SIGKILL the server WHILE a client is committing, restart on the
    same datadir: the client must ride reconnect + unknown-result fencing
    to completion, and the serializable count must be EXACT — no acked
    increment lost, no retried increment double-applied (ref: the
    reconnect discipline in FlowTransport connectionKeeper + the
    commitDummyTransaction fencing)."""
    datadir = str(tmp_path / "data")
    server = spawn_real_node(*["server", "--datadir", datadir])
    client = None
    server2 = None
    try:
        ready = server.stdout.readline().strip()
        assert ready.startswith("READY "), ready
        addr = ready.split()[1]
        port = addr.rsplit(":", 1)[1]

        client = spawn_real_node(
            *["client", addr, "--id", "k", "--ops", "20", "--progress"]
        )
        # Kill on OBSERVED progress (not wall clock): some ops landed,
        # more are in flight.
        for line in client.stdout:
            if line.startswith("OP 3"):
                break
        server.kill()
        server.wait()
        # Same address: SO_REUSEADDR lets the restart rebind immediately.
        server2 = spawn_real_node(
            *["server", "--datadir", datadir, "--port", port]
        )
        ready2 = server2.stdout.readline().strip()
        assert ready2.startswith("READY "), ready2
        assert ready2.split()[1] == addr, ready2
        out, _ = client.communicate(timeout=120)
        assert client.returncode == 0, out

        c2 = spawn_real_node(
            "client", addr, "--id", "v", "--ops", "0", "--check-count", "20"
        )
        out2, _ = c2.communicate(timeout=90)
        assert c2.returncode == 0, out2
        assert "DONE 20" in out2, out2
    finally:
        for pr in (server, server2, client):
            if pr is not None and pr.poll() is None:
                pr.kill()
                pr.wait()


@pytest.mark.slow  # tier-1 headroom (ISSUE 4): real-OS-process integration soak
def test_kvcheck_verifies_and_detects_corruption(tmp_path):
    """kvcheck (the kvfileintegritycheck role analog): a healthy datadir
    verifies clean; flipping bytes in the engine's durable files makes it
    report corruption with a nonzero exit (ref: fdbserver -r
    kvfileintegritycheck, fdbserver.actor.cpp:637)."""
    import glob
    import json
    import os

    datadir = str(tmp_path / "data")
    server = spawn_real_node(*["server", "--datadir", datadir])
    try:
        ready = server.stdout.readline().strip()
        addr = ready.split()[1]
        c1 = spawn_real_node(*["client", addr, "--id", "kc", "--ops", "10"])
        out1, _ = c1.communicate(timeout=90)
        assert c1.returncode == 0, out1
    finally:
        server.kill()
        server.wait()

    ok = spawn_real_node("kvcheck", "--datadir", datadir)
    rep_raw, _ = ok.communicate(timeout=60)
    assert ok.returncode == 0, rep_raw
    rep = json.loads(rep_raw.strip().splitlines()[-1])
    assert rep["ok"] is True
    assert rep.get("engine_rows", 0) > 0, rep

    # Corrupt the engine's durable files mid-way; kvcheck must fail loudly.
    targets = sorted(glob.glob(os.path.join(datadir, "engine", "*")))
    assert targets, "no engine files written"
    for t in targets:
        n = os.path.getsize(t)
        if n > 40:
            with open(t, "r+b") as f:
                f.seek(n // 2)
                f.write(b"\xde\xad\xbe\xef")
    bad = spawn_real_node("kvcheck", "--datadir", datadir)
    rep2_raw, _ = bad.communicate(timeout=60)
    assert bad.returncode != 0, rep2_raw

    # Read-only contract: mid-file tlog corruption is DETECTED and the
    # file is NOT mutated (a recovery open would truncate it).
    dq = os.path.join(datadir, "tlog.dq")
    size_before = os.path.getsize(dq)
    with open(dq, "r+b") as f:
        f.seek(size_before // 2)
        f.write(b"\xde\xad\xbe\xef")
    chk = spawn_real_node("kvcheck", "--datadir", datadir)
    rep3_raw, _ = chk.communicate(timeout=60)
    assert chk.returncode != 0, rep3_raw
    rep3 = json.loads(rep3_raw.strip().splitlines()[-1])
    assert "tlog_corrupt_at" in rep3, rep3
    assert os.path.getsize(dq) == size_before, (
        "kvcheck mutated the store it was verifying"
    )

    # A typo'd datadir must error, not report a clean empty store.
    typo = spawn_real_node("kvcheck", "--datadir", str(tmp_path / "nope"))
    rep4_raw, _ = typo.communicate(timeout=60)
    assert typo.returncode != 0, rep4_raw
    assert not os.path.exists(str(tmp_path / "nope"))
