"""Flow runtime tests: determinism, futures, cancellation, priorities.

Mirrors the reference's flow-primitive self-tests (fdbrpc/dsltest.actor.cpp):
future/promise semantics, actor cancellation, delay ordering — plus the
bit-reproducibility property sim runs rely on (SURVEY.md §4.8).
"""

import pytest

from foundationdb_tpu.flow import (
    ActorCancelled,
    DeterministicRandom,
    EventLoop,
    FdbError,
    Promise,
    PromiseStream,
    TaskPriority,
    buggify,
    set_buggify_enabled,
)
from foundationdb_tpu.flow.eventloop import first_of, timeout_after, wait_for_all
from foundationdb_tpu.flow.future import error_future, ready_future


def test_promise_future_basics():
    p = Promise()
    assert not p.future.is_ready()
    p.send(42)
    assert p.future.is_ready() and p.future.get() == 42

    e = Promise()
    e.send_error(FdbError("not_committed"))
    assert e.future.is_error()
    with pytest.raises(FdbError):
        e.future.get()


def test_actor_await_and_result():
    loop = EventLoop(seed=7)

    async def child(x):
        await loop.delay(0.5)
        return x * 2

    async def parent():
        a = loop.spawn(child(3))
        b = loop.spawn(child(4))
        return await a + await b

    t = loop.spawn(parent())
    assert loop.run_until(t) == 14
    assert loop.now() == pytest.approx(0.5)


def test_delay_ordering_by_time_then_priority():
    loop = EventLoop(seed=1)
    order = []

    async def waiter(tag, dt, prio):
        await loop.delay(dt, prio)
        order.append(tag)

    loop.spawn(waiter("late", 2.0, TaskPriority.Max))
    loop.spawn(waiter("early_low", 1.0, TaskPriority.Low))
    loop.spawn(waiter("early_high", 1.0, TaskPriority.Max))
    loop.run()
    assert order == ["early_high", "early_low", "late"]


def test_cancellation_propagates():
    loop = EventLoop(seed=1)
    cleaned = []

    async def forever():
        try:
            await loop.delay(1e9)
        except ActorCancelled:
            cleaned.append("cancelled")
            raise

    t = loop.spawn(forever())
    loop.run(max_events=1)
    t.cancel()
    assert cleaned == ["cancelled"]
    assert t.is_error()


def test_promise_stream_fifo_and_end():
    loop = EventLoop(seed=1)
    ps = PromiseStream()
    got = []

    async def consumer():
        while True:
            try:
                got.append(await ps.pop())
            except FdbError as e:
                assert e.name == "end_of_stream"
                return "done"

    t = loop.spawn(consumer())
    for i in range(3):
        ps.send(i)
    ps.send_error(FdbError("end_of_stream"))
    assert loop.run_until(t) == "done"
    assert got == [0, 1, 2]


def test_first_of_and_timeout():
    loop = EventLoop(seed=1)

    async def main():
        idx, val = await first_of(loop.delay(5.0), loop.delay(1.0))
        assert idx == 1
        v = await timeout_after(loop, loop.delay(100.0), 2.0, default="timed_out")
        assert v == "timed_out"
        v2 = await timeout_after(loop, ready_future("fast"), 2.0)
        assert v2 == "fast"
        return "ok"

    assert loop.run_until(loop.spawn(main())) == "ok"


def test_wait_for_all_error_propagates():
    loop = EventLoop(seed=1)

    async def main():
        with pytest.raises(FdbError):
            await wait_for_all([ready_future(1), error_future(FdbError("io_error"))])
        return True

    assert loop.run_until(loop.spawn(main()))


def _sim_trace(seed):
    """A small chaotic actor soup; returns the event interleaving."""
    loop = EventLoop(seed=seed)
    log = []

    async def actor(name):
        for _ in range(5):
            await loop.delay(loop.rng.random01(), priority=loop.rng.random_int(1, 10000))
            log.append((name, round(loop.now(), 9)))
            if loop.rng.coinflip():
                loop.spawn(subactor(name))

    async def subactor(parent):
        await loop.delay(loop.rng.random01() * 0.1)
        log.append((parent + "/sub", round(loop.now(), 9)))

    for i in range(4):
        loop.spawn(actor(f"a{i}"))
    loop.run()
    return log


def test_deterministic_reproducibility():
    assert _sim_trace(12345) == _sim_trace(12345)
    assert _sim_trace(12345) != _sim_trace(54321)


def test_deterministic_random_stability():
    r1 = DeterministicRandom(99)
    r2 = DeterministicRandom(99)
    seq1 = [r1.random_int(0, 1000) for _ in range(100)] + [r1.random01()]
    seq2 = [r2.random_int(0, 1000) for _ in range(100)] + [r2.random01()]
    assert seq1 == seq2
    assert r1.random_unique_id() == r2.random_unique_id()


def test_buggify_gated_and_deterministic():
    set_buggify_enabled(False)
    assert not any(buggify("site_a") for _ in range(100))

    set_buggify_enabled(True, DeterministicRandom(5))
    fires1 = [buggify("site_a") for _ in range(100)]
    set_buggify_enabled(True, DeterministicRandom(5))
    fires2 = [buggify("site_a") for _ in range(100)]
    assert fires1 == fires2
    set_buggify_enabled(False)


def test_buggify_with_prob_and_coverage_report():
    """BUGGIFY_WITH_PROB: caller-chosen fire probability behind the same
    activation gate, with fired-site counts surfacing through
    publish_coverage as MetricsRegistry gauges (chaos-run fault-site
    coverage, ISSUE 3 satellite)."""
    from foundationdb_tpu.flow.buggify import (
        buggify_with_prob,
        coverage,
        fired_counts,
        publish_coverage,
    )
    from foundationdb_tpu.flow.knobs import g_knobs
    from foundationdb_tpu.flow.metrics import MetricsRegistry

    set_buggify_enabled(False)
    assert not buggify_with_prob("p_site", 1.0)  # gated off outside sim

    old_act = g_knobs.flow.buggify_activated_probability
    g_knobs.flow.buggify_activated_probability = 1.0
    try:
        set_buggify_enabled(True, DeterministicRandom(5))
        assert all(buggify_with_prob("always", 1.0) for _ in range(20))
        assert not any(buggify_with_prob("never", 0.0) for _ in range(20))
        cov = coverage()
        assert cov["sites_seen"] == 2 and cov["sites_activated"] == 2
        assert cov["sites_fired"] == 1
        assert cov["fired_counts"] == {"always": 20}
        assert fired_counts["always"] == 20

        reg = MetricsRegistry("BuggifyCoverage")
        publish_coverage(reg)
        snap = reg.snapshot()
        assert snap["gauges"]["buggify_sites_fired"] == 1
        assert snap["gauges"]["fired:always"] == 20

        # p=1 fire replays identically; the plain buggify() rides the
        # same counters.
        set_buggify_enabled(True, DeterministicRandom(5))
        assert buggify("site_b") in (True, False)
        assert coverage()["sites_seen"] == 1  # reset cleared the old run
    finally:
        g_knobs.flow.buggify_activated_probability = old_act
        set_buggify_enabled(False)


def test_unhandled_actor_exception_fails_simulation():
    """A background actor dying with a Python error (a bug, not a simulated
    fault) must surface as SimulationFailure from run_until within one
    event — never a silent hang (VERDICT r2 weak #4)."""
    import pytest

    from foundationdb_tpu.flow.error import SimulationFailure
    from foundationdb_tpu.flow.eventloop import EventLoop

    loop = EventLoop(seed=1)

    async def broken_role():
        await loop.delay(0.1)
        raise AttributeError("no such method")

    loop.spawn(broken_role(), "broken_role")

    async def idle():
        await loop.delay(1000.0)

    t = loop.spawn(idle(), "idle")
    with pytest.raises(SimulationFailure, match="broken_role"):
        loop.run_until(t)


def test_awaited_task_error_raises_original():
    """Directly awaiting the failing task yields the original exception (the
    caller observed it), not a SimulationFailure — and the failure is not
    re-raised on a later run_until."""
    import pytest

    from foundationdb_tpu.flow.eventloop import EventLoop

    loop = EventLoop(seed=1)

    async def fails():
        await loop.delay(0.1)
        raise ValueError("observed")

    t = loop.spawn(fails(), "fails")
    with pytest.raises(ValueError, match="observed"):
        loop.run_until(t)

    async def fine():
        await loop.delay(0.1)
        return 42

    assert loop.run_until(loop.spawn(fine(), "fine")) == 42


def test_fdb_errors_do_not_fail_simulation():
    """FdbError deaths are simulated faults (kills, broken promises), part
    of normal chaos — they must not trip the fail-fast."""
    from foundationdb_tpu.flow.error import FdbError
    from foundationdb_tpu.flow.eventloop import EventLoop

    loop = EventLoop(seed=1)

    async def chaotic():
        await loop.delay(0.1)
        raise FdbError("broken_promise")

    loop.spawn(chaotic(), "chaotic")

    async def idle():
        await loop.delay(10.0)
        return "ok"

    assert loop.run_until(loop.spawn(idle(), "idle")) == "ok"


def test_every_raised_error_name_is_registered():
    """Structural gate: every error NAME the codebase raises must exist
    in the error table — FdbError("unregistered") KeyError-crashes the
    raising actor instead of erroring, a latent-until-the-rare-path bug
    class this sweep found THREE of (fetch_superseded,
    http_bad_response, recovery_superseded)."""
    import os
    import re

    from foundationdb_tpu.flow.error import _ERRORS

    names = set()
    pkg = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ) + "/foundationdb_tpu"
    for root, _d, files in os.walk(pkg):
        for f in files:
            if f.endswith(".py"):
                src = open(os.path.join(root, f)).read()
                names.update(
                    re.findall(r'FdbError\(\s*"([a-z_0-9]+)"', src)
                )
                names.update(
                    re.findall(r'send_error\(\s*"([a-z_0-9]+)"', src)
                )
    missing = sorted(n for n in names if n not in _ERRORS)
    assert not missing, f"raised but not in the error table: {missing}"
    assert len(names) > 20  # the scan actually found the raise sites
