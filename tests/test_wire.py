"""The versioned tagged binary wire codec (rpc/wire.py).

Ref: flow/serialize.h:80-188 — every struct versioned, unknown data
rejected loudly.  The decoder is driven with valid frames, evolved
schemas, and a mutation fuzzer: malformed bytes must raise WireDecodeError
and nothing else (decode constructs data, never executes).
"""

import dataclasses

import pytest

from foundationdb_tpu.client.types import (
    CommitTransactionRef,
    Mutation,
    MutationType,
)
from foundationdb_tpu.rpc.network import Endpoint
from foundationdb_tpu.rpc.stream import RequestStreamRef, _Envelope
from foundationdb_tpu.rpc.wire import (
    WIRE_VERSION,
    WireDecodeError,
    WireEncodeError,
    decode_frame,
    encode_frame,
)
from foundationdb_tpu.server.interfaces import (
    CommitTransactionRequest,
    GetKeyValuesRequest,
    GetStorageMetricsReply,
    StorageInterface,
)


def roundtrip(v):
    out = decode_frame(encode_frame(v))
    assert out == v, (out, v)
    return out


def test_primitives_roundtrip():
    for v in (
        None,
        True,
        False,
        0,
        1,
        -1,
        2**40,
        -(2**40),
        2**100,
        0.0,
        -1.5,
        float("inf"),
        b"",
        b"\x00\xff" * 100,
        "",
        "héllo ☃",
        [],
        [1, [2, [3, b"x"]]],
        (),
        (1, "two", b"three", None),
        {},
        {b"k": [1, 2], "s": {"nested": True}, 7: None},
    ):
        roundtrip(v)


def test_nan_roundtrip():
    import math

    out = decode_frame(encode_frame(float("nan")))
    assert math.isnan(out)


def test_structs_and_enums_roundtrip():
    ep = Endpoint(address="10.0.0.1:4500", token=(1 << 40) | 1234)
    ref = RequestStreamRef(endpoint=ep, name="commit")
    tr = CommitTransactionRef(
        read_snapshot=7,
        read_conflict_ranges=[(b"a", b"b")],
        write_conflict_ranges=[(b"a", b"a\x00")],
        mutations=[Mutation(type=MutationType.SET_VALUE, param1=b"a", param2=b"v")],
    )
    req = CommitTransactionRequest(transaction=tr)
    env = _Envelope(request=req, reply_to=ep)
    out = roundtrip(env)
    m = out.request.transaction.mutations[0]
    assert isinstance(m.type, MutationType) and m.type is MutationType.SET_VALUE
    roundtrip(ref)
    roundtrip(
        StorageInterface(storage_id="ss0", get_value=ref, get_version=ref)
    )
    roundtrip(GetKeyValuesRequest(begin=b"a", end=b"z", version=12))
    roundtrip((False, GetStorageMetricsReply(bytes=10, split_key=None)))
    roundtrip((True, "broken_promise"))


def test_unregistered_class_rejected_at_encode():
    @dataclasses.dataclass
    class NotOnTheWire:
        x: int = 1

    with pytest.raises(WireEncodeError):
        encode_frame(NotOnTheWire())
    with pytest.raises(WireEncodeError):
        encode_frame(object())


def test_wire_version_gate():
    frame = bytearray(encode_frame(42))
    frame[0] = WIRE_VERSION + 1
    with pytest.raises(WireDecodeError):
        decode_frame(bytes(frame))


def test_schema_evolution_fewer_fields_fill_defaults():
    """An old peer omitting newly added trailing fields decodes with the
    dataclass defaults (positional count-prefixed encoding)."""
    full = encode_frame(GetKeyValuesRequest(begin=b"a", end=b"z"))
    # Re-encode by hand with only the first 2 fields: find the varint field
    # count right after the struct tag+id and truncate the value stream.
    # Easier: build from a 2-field struct of identical name is impossible —
    # instead patch the count byte and drop the tail values.
    import foundationdb_tpu.rpc.wire as wire

    cid = wire._class_id("GetKeyValuesRequest")
    flds = wire._structs_by_id[cid][1]
    assert len(flds) >= 3
    out = [bytes((wire.WIRE_VERSION, wire.T_STRUCT))]
    out.append(wire._U16.pack(cid))
    wire._enc_varint(out, 2)
    wire._encode(out, b"a", 1)
    wire._encode(out, b"z", 1)
    got = decode_frame(b"".join(out))
    assert got.begin == b"a" and got.end == b"z"
    assert got.version == dataclasses.fields(GetKeyValuesRequest)[2].default
    assert full  # silence unused


def test_schema_evolution_more_fields_rejected():
    import foundationdb_tpu.rpc.wire as wire

    cid = wire._class_id("GetKeyValuesRequest")
    n = len(wire._structs_by_id[cid][1])
    out = [bytes((wire.WIRE_VERSION, wire.T_STRUCT))]
    out.append(wire._U16.pack(cid))
    wire._enc_varint(out, n + 1)
    for _ in range(n + 1):
        wire._encode(out, None, 1)
    with pytest.raises(WireDecodeError):
        decode_frame(b"".join(out))


def test_pickle_frames_rejected():
    import pickle

    evil = pickle.dumps((123, "payload"), protocol=4)
    with pytest.raises(WireDecodeError):
        decode_frame(evil)


def test_decoder_fuzz_never_escapes_wiredecodeerror():
    """Mutation + truncation + random-soup fuzz: decode either succeeds or
    raises WireDecodeError — no other exception type, no side effects."""
    import numpy as np

    rng = np.random.default_rng(20260730)
    ep = Endpoint(address="h:1", token=99)
    seeds = [
        encode_frame(v)
        for v in (
            _Envelope(
                request=CommitTransactionRequest(
                    transaction=CommitTransactionRef(
                        mutations=[
                            Mutation(MutationType.SET_VALUE, b"k" * 30, b"v" * 100)
                        ]
                    )
                ),
                reply_to=ep,
            ),
            (7, [(b"k", b"v")] * 10),
            {b"a": 1, "b": [Endpoint("x:2", 3)]},
        )
    ]
    checked = 0
    for _ in range(4000):
        base = bytearray(seeds[int(rng.integers(len(seeds)))])
        mode = int(rng.integers(3))
        if mode == 0:  # point mutations
            for _ in range(int(rng.integers(1, 8))):
                base[int(rng.integers(len(base)))] = int(rng.integers(256))
            frame = bytes(base)
        elif mode == 1:  # truncate / extend
            cut = int(rng.integers(len(base) + 1))
            frame = bytes(base[:cut]) + bytes(
                rng.integers(0, 256, int(rng.integers(4)), dtype=np.uint8)
            )
        else:  # pure random soup
            frame = bytes(
                rng.integers(0, 256, int(rng.integers(1, 200)), dtype=np.uint8)
            )
        try:
            decode_frame(frame)
        except WireDecodeError:
            pass
        # anything else propagates and fails the test
        checked += 1
    assert checked == 4000


def test_huge_length_prefixes_bounded():
    """A crafted frame claiming a giant collection must error, not
    allocate: lengths are checked against the remaining frame bytes."""
    import foundationdb_tpu.rpc.wire as wire

    out = [bytes((wire.WIRE_VERSION, wire.T_LIST))]
    wire._enc_varint(out, 1 << 60)
    with pytest.raises(WireDecodeError):
        decode_frame(b"".join(out))
    out = [bytes((wire.WIRE_VERSION, wire.T_BYTES))]
    wire._enc_varint(out, 1 << 60)
    with pytest.raises(WireDecodeError):
        decode_frame(b"".join(out))


def test_depth_bounded():
    deep = None
    for _ in range(200):
        deep = [deep]
    with pytest.raises(WireEncodeError):
        encode_frame(deep)
    frame = bytes((WIRE_VERSION,)) + bytes([7, 1]) * 200  # nested 1-lists
    with pytest.raises(WireDecodeError):
        decode_frame(frame)


def test_resolver_batch_roundtrip():
    """The proxy->resolver hot-path request (embeds conflict-engine types
    from a third module) must be in the wire vocabulary — the first
    cross-process proxy/resolver deployment sends it on every commit."""
    from foundationdb_tpu.conflict.types import TransactionConflictInfo
    from foundationdb_tpu.server.interfaces import (
        ResolveTransactionBatchRequest,
    )

    req = ResolveTransactionBatchRequest(
        prev_version=10,
        version=20,
        transactions=[
            TransactionConflictInfo(
                read_snapshot=5,
                read_ranges=[(b"a", b"b")],
                write_ranges=[(b"c", b"d")],
            )
        ],
        proxy_id="proxy0",
    )
    roundtrip(req)


# --- C accelerator differential (cpp/wirecodec.c) -------------------------


def _c_active():
    import foundationdb_tpu.rpc.wire as wire

    encode_frame(0)  # force registry + C load
    return wire._c_codec() is not None


def _rand_value(rng, depth=0):
    import numpy as np

    kinds = 12 if depth < 4 else 8  # leaves only when deep
    k = int(rng.integers(kinds))
    if k == 0:
        return None
    if k == 1:
        return bool(rng.integers(2))
    if k == 2:
        # includes 64-bit edges and beyond-64-bit (C falls back)
        choice = int(rng.integers(5))
        if choice == 0:
            return int(rng.integers(-(2**62), 2**62))
        if choice == 1:
            return (1 << 63) - 1
        if choice == 2:
            return -(1 << 63)
        if choice == 3:
            return (1 << 80) + int(rng.integers(100))  # fallback path
        return int(rng.integers(-100, 100))
    if k == 3:
        return float(rng.normal())
    if k == 4:
        return bytes(rng.integers(0, 256, int(rng.integers(30)),
                                  dtype=np.uint8))
    if k == 5:
        return "".join(
            chr(int(rng.integers(1, 0x300))) for _ in range(int(rng.integers(8)))
        )
    if k == 6:
        return Mutation(
            MutationType(int(rng.integers(0, 2))),
            bytes(rng.integers(97, 123, 4, dtype=np.uint8)),
            bytes(rng.integers(97, 123, 6, dtype=np.uint8)),
        )
    if k == 7:
        return MutationType(int(rng.integers(0, 2)))
    if k == 8:
        return [_rand_value(rng, depth + 1) for _ in range(int(rng.integers(4)))]
    if k == 9:
        return tuple(
            _rand_value(rng, depth + 1) for _ in range(int(rng.integers(4)))
        )
    if k == 10:
        return {
            int(rng.integers(1000)): _rand_value(rng, depth + 1)
            for _ in range(int(rng.integers(4)))
        }
    return Endpoint(address="h:%d" % int(rng.integers(9)), token=int(rng.integers(99)))


def test_c_codec_differential_fuzz():
    """The C accelerator must be BYTE-identical to the Python reference on
    encode and value-identical on decode, across randomized nested values
    including structs, enums, and beyond-64-bit ints (C fallback path)."""
    import numpy as np

    from foundationdb_tpu.rpc.wire import decode_frame_py, encode_frame_py

    if not _c_active():
        pytest.skip("C codec unavailable")
    rng = np.random.default_rng(20260731)
    for i in range(500):
        v = _rand_value(rng)
        cf = encode_frame(v)  # C (with py fallback for big ints)
        pf = encode_frame_py(v)
        assert cf == pf, f"iter {i}: C/py encodings differ for {v!r}"
        a = decode_frame(pf)  # C decode
        b = decode_frame_py(pf)
        assert a == b, f"iter {i}: C/py decode differ"


def test_c_codec_malformed_agreement():
    """On mutated frames, the C and Python decoders must AGREE: both raise
    WireDecodeError, or both succeed with equal values (the C fallback
    signal never escapes)."""
    import numpy as np

    from foundationdb_tpu.rpc.wire import decode_frame_py

    if not _c_active():
        pytest.skip("C codec unavailable")
    rng = np.random.default_rng(777)
    seed = encode_frame(
        {
            b"k": [Mutation(MutationType.SET_VALUE, b"a", b"b"), 1.5],
            "t": (1, None, True, -(1 << 63)),
        }
    )
    for _ in range(3000):
        base = bytearray(seed)
        for _ in range(int(rng.integers(1, 6))):
            base[int(rng.integers(len(base)))] = int(rng.integers(256))
        frame = bytes(base)
        try:
            a = decode_frame(frame)
            a_err = None
        except WireDecodeError:
            a_err = True
        try:
            b = decode_frame_py(frame)
            b_err = None
        except WireDecodeError:
            b_err = True
        assert (a_err is None) == (b_err is None), (
            f"C/py disagree on malformed frame: {frame.hex()}"
        )
        if a_err is None:
            assert _eq_loose(a, b), f"decoded values differ: {frame.hex()}"


def _eq_loose(a, b):
    # NaN floats compare unequal; treat bitwise-same NaN as equal.
    import math

    if isinstance(a, float) and isinstance(b, float):
        return (math.isnan(a) and math.isnan(b)) or a == b
    if type(a) is not type(b):
        return a == b
    if isinstance(a, (list, tuple)):
        return len(a) == len(b) and all(_eq_loose(x, y) for x, y in zip(a, b))
    if isinstance(a, dict):
        return set(a) == set(b) and all(_eq_loose(a[k], b[k]) for k in a)
    return a == b
