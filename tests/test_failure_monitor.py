"""Cluster-wide failure monitor: CC detector -> delta broadcast -> routing.

Ref: ClusterController.actor.cpp:1257 (failure detection + status
broadcast), FailureMonitorClient.actor.cpp (client-side folding),
LoadBalance consulting IFailureMonitor so a dead replica is avoided
WITHOUT paying a per-request timeout on it first.
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server.dynamic_cluster import DynamicCluster
from foundationdb_tpu.server.failure_monitor import (
    FailureDetector,
    run_failure_monitor_client,
)


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_delta_protocol_and_snapshot_fallback():
    """Version deltas apply incrementally; a consumer older than the
    trimmed history gets a full snapshot."""
    from foundationdb_tpu.flow.eventloop import EventLoop
    from foundationdb_tpu.rpc.network import SimNetwork

    loop = EventLoop(seed=5)
    set_event_loop(loop)
    net = SimNetwork(loop)
    cc = net.process("cc")
    client = net.process("client")
    det = FailureDetector(cc)

    async def run():
        det.set_state("a:0", True)
        det.set_state("b:0", True)
        rep = await det.ref().get_reply(client, 0)
        assert rep.version == 2 and not rep.full
        assert dict(rep.states) == {"a:0": True, "b:0": True}
        det.set_state("a:0", False)
        rep2 = await det.ref().get_reply(client, rep.version)
        assert rep2.version == 3
        assert rep2.states == [("a:0", False)]
        # Overflow the history; an ancient consumer gets a snapshot.
        for i in range(600):
            det.set_state(f"x{i}:0", True)
            det.set_state(f"x{i}:0", False)
        rep3 = await det.ref().get_reply(client, 1)
        assert rep3.full
        assert dict(rep3.states)["a:0"] is False

    loop.run_until(client.spawn(run()), timeout_vt=100.0)


def test_read_routes_around_suspect_replica_without_timeout():
    """The VERDICT 'Done' criterion, grey-failure form: partition a
    storage replica from the CC only (it stays reachable from the client,
    so nothing breaks its promises).  Once the detector's broadcast lands,
    the client's next read routes to the healthy replica purely on monitor
    state — completing far below any request-timeout scale."""
    c = DynamicCluster(seed=81, n_workers=6, n_storages=2)
    db = c.database()

    async def fill(tr):
        for i in range(10):
            tr.set(b"fm%02d" % i, b"v%d" % i)

    c.run_all([(db, db.run(fill))], timeout_vt=600.0)

    storage_workers = [
        w for w in c.workers if "storage" in w.roles and w.process.alive
    ]
    assert len(storage_workers) == 2
    victim = storage_workers[0]
    cc_machine = c.acting_controller().process.machine.machine_id
    out = {}

    async def scenario():
        # Warm the location cache + queue model.
        tr = db.create_transaction()
        assert (await tr.get(b"fm01")) == b"v1"

        # Grey failure: CC can't reach the victim; the client still can.
        # Long enough for several ping timeouts (PING_TIMEOUT=2.0) to
        # elapse INSIDE the clog window — detection timing is seed
        # dependent and must not race the clog's expiry.
        c.net.clog_pair(
            victim.process.machine.machine_id, cc_machine, 8.0
        )

        # Wait until the failure broadcast reaches THIS client.
        addr = victim.process.address
        # Generous bound: detection needs several ping-sweep rounds and
        # the exact count is seed/timing dependent.
        for _ in range(600):
            if db.failure_states.get(addr):
                break
            await c.loop.delay(0.02)
        assert db.failure_states.get(addr), "broadcast never arrived"

        # The monitor-driven pick must avoid the suspect immediately.
        t0 = c.loop.now()
        tr2 = db.create_transaction()
        out["v"] = await tr2.get(b"fm02")
        out["dt"] = c.loop.now() - t0
        out["suspect_marked"] = db.failure_states.get(addr)

    c.run_all([(db, scenario())], timeout_vt=600.0)
    assert out["v"] == b"v2"
    assert out["dt"] < 0.3, f"read ate a timeout: {out['dt']}s"
