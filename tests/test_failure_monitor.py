"""Cluster-wide failure monitor: CC detector -> delta broadcast -> routing.

Ref: ClusterController.actor.cpp:1257 (failure detection + status
broadcast), FailureMonitorClient.actor.cpp (client-side folding),
LoadBalance consulting IFailureMonitor so a dead replica is avoided
WITHOUT paying a per-request timeout on it first.
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server.dynamic_cluster import DynamicCluster
from foundationdb_tpu.server.failure_monitor import (
    FailureDetector,
    run_failure_monitor_client,
)


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_delta_protocol_and_snapshot_fallback():
    """Version deltas apply incrementally; a consumer older than the
    trimmed history gets a full snapshot."""
    from foundationdb_tpu.flow.eventloop import EventLoop
    from foundationdb_tpu.rpc.network import SimNetwork

    loop = EventLoop(seed=5)
    set_event_loop(loop)
    net = SimNetwork(loop)
    cc = net.process("cc")
    client = net.process("client")
    det = FailureDetector(cc)

    async def run():
        det.set_state("a:0", True)
        det.set_state("b:0", True)
        rep = await det.ref().get_reply(client, 0)
        assert rep.version == 2 and not rep.full
        assert dict(rep.states) == {"a:0": True, "b:0": True}
        det.set_state("a:0", False)
        rep2 = await det.ref().get_reply(client, rep.version)
        assert rep2.version == 3
        assert rep2.states == [("a:0", False)]
        # Overflow the history; an ancient consumer gets a snapshot.
        for i in range(600):
            det.set_state(f"x{i}:0", True)
            det.set_state(f"x{i}:0", False)
        rep3 = await det.ref().get_reply(client, 1)
        assert rep3.full
        assert dict(rep3.states)["a:0"] is False

    loop.run_until(client.spawn(run()), timeout_vt=100.0)


def test_long_poll_wakes_on_state_bump():
    """A consumer that is fully caught up parks in _wait_change; a state
    bump mid-wait must wake it IMMEDIATELY with the delta — not after the
    LONG_POLL_TIMEOUT liveness heartbeat."""
    from foundationdb_tpu.flow.eventloop import EventLoop
    from foundationdb_tpu.rpc.network import SimNetwork
    from foundationdb_tpu.server.failure_monitor import LONG_POLL_TIMEOUT

    loop = EventLoop(seed=11)
    set_event_loop(loop)
    net = SimNetwork(loop)
    cc = net.process("cc")
    client = net.process("client")
    det = FailureDetector(cc)
    out = {}

    async def consumer():
        det.set_state("a:0", True)
        rep = await det.ref().get_reply(client, 0)
        assert rep.version == 1
        # Caught up: the next poll parks.  Bump the state mid-wait.
        t0 = loop.now()

        async def bump():
            await loop.delay(0.05)
            det.set_state("b:0", True)

        client.spawn(bump())
        rep2 = await det.ref().get_reply(client, rep.version)
        out["dt"] = loop.now() - t0
        out["states"] = rep2.states
        out["version"] = rep2.version

    loop.run_until(client.spawn(consumer()), timeout_vt=30.0)
    assert out["states"] == [("b:0", True)] and out["version"] == 2
    # Woken by the bump (0.05s + delivery latencies), not the heartbeat.
    assert 0.05 <= out["dt"] < LONG_POLL_TIMEOUT / 2, out["dt"]


def test_heartbeat_answers_empty_when_nothing_changes():
    """The bounded long poll: with NO state change, the parked consumer
    still gets a (delta-free) liveness answer at LONG_POLL_TIMEOUT."""
    from foundationdb_tpu.flow.eventloop import EventLoop
    from foundationdb_tpu.rpc.network import SimNetwork
    from foundationdb_tpu.server.failure_monitor import LONG_POLL_TIMEOUT

    loop = EventLoop(seed=12)
    set_event_loop(loop)
    net = SimNetwork(loop)
    cc = net.process("cc")
    client = net.process("client")
    det = FailureDetector(cc)
    out = {}

    async def consumer():
        t0 = loop.now()
        rep = await det.ref().get_reply(client, 0)
        out["dt"] = loop.now() - t0
        out["rep"] = rep

    loop.run_until(client.spawn(consumer()), timeout_vt=30.0)
    assert out["rep"].version == 0 and out["rep"].states == []
    assert out["dt"] >= LONG_POLL_TIMEOUT, out["dt"]


def test_client_survives_monitor_death_mid_wait():
    """Kill the monitor's host process while a client actor is parked in
    its long poll: the broken promise must NOT kill the client loop — it
    resets to version 0 and re-resolves the next generation's detector
    from ClientDBInfo, then folds the full snapshot."""
    from foundationdb_tpu.flow.asyncvar import AsyncVar
    from foundationdb_tpu.flow.eventloop import EventLoop
    from foundationdb_tpu.rpc.network import SimNetwork

    loop = EventLoop(seed=13)
    set_event_loop(loop)
    net = SimNetwork(loop)
    cc1 = net.process("cc1")
    client_proc = net.process("client")
    det1 = FailureDetector(cc1)
    det1.set_state("a:0", True)

    class _Info:
        def __init__(self, fm):
            self.failure_monitor = fm

    class _Db:
        process = client_proc
        info_var = AsyncVar(_Info(det1.ref()))
        failure_states: dict = {}

    db = _Db()
    client_proc.spawn(run_failure_monitor_client(db), "fm_client")

    async def scenario():
        # Phase 1: the client folds the first generation's state.
        for _ in range(200):
            if db.failure_states.get("a:0"):
                break
            await loop.delay(0.05)
        assert db.failure_states.get("a:0") is True

        # Phase 2: kill the CC while the client is parked in the long
        # poll.  The client must absorb the broken promise and keep
        # polling (not crash), re-reading info_var each round.
        cc1.kill()
        await loop.delay(1.0)

        # Phase 3: a new generation's detector; enough churn that its
        # bounded history is trimmed past version 0, so the client's
        # known-version reset forces a FULL snapshot — which must clear
        # the dead generation's stale entries (a:0) before folding.
        cc2 = net.process("cc2")
        det2 = FailureDetector(cc2)
        det2.set_state("b:0", True)
        for i in range(600):  # > HISTORY_LIMIT: trims past known=0
            det2.set_state(f"x{i}:0", True)
            det2.set_state(f"x{i}:0", False)
        db.info_var.set(_Info(det2.ref()))
        for _ in range(200):
            if db.failure_states.get("b:0"):
                break
            await loop.delay(0.05)

    loop.run_until(client_proc.spawn(scenario()), timeout_vt=600.0)
    assert db.failure_states.get("b:0") is True
    # Stale first-generation state was dropped by the snapshot fold.
    assert db.failure_states.get("a:0") is None


def test_read_routes_around_suspect_replica_without_timeout():
    """The VERDICT 'Done' criterion, grey-failure form: partition a
    storage replica from the CC only (it stays reachable from the client,
    so nothing breaks its promises).  Once the detector's broadcast lands,
    the client's next read routes to the healthy replica purely on monitor
    state — completing far below any request-timeout scale."""
    c = DynamicCluster(seed=81, n_workers=6, n_storages=2)
    db = c.database()

    async def fill(tr):
        for i in range(10):
            tr.set(b"fm%02d" % i, b"v%d" % i)

    c.run_all([(db, db.run(fill))], timeout_vt=600.0)

    storage_workers = [
        w for w in c.workers if "storage" in w.roles and w.process.alive
    ]
    assert len(storage_workers) == 2
    victim = storage_workers[0]
    cc_machine = c.acting_controller().process.machine.machine_id
    out = {}

    async def scenario():
        # Warm the location cache + queue model.
        tr = db.create_transaction()
        assert (await tr.get(b"fm01")) == b"v1"

        # Grey failure: CC can't reach the victim; the client still can.
        # Long enough for several ping timeouts (PING_TIMEOUT=2.0) to
        # elapse INSIDE the clog window — detection timing is seed
        # dependent and must not race the clog's expiry.
        c.net.partition_pair(
            victim.process.machine.machine_id, cc_machine, 8.0
        )

        # Wait until the failure broadcast reaches THIS client.
        addr = victim.process.address
        # Generous bound: detection needs several ping-sweep rounds and
        # the exact count is seed/timing dependent.
        for _ in range(600):
            if db.failure_states.get(addr):
                break
            await c.loop.delay(0.02)
        assert db.failure_states.get(addr), "broadcast never arrived"

        # The monitor-driven pick must avoid the suspect immediately.
        t0 = c.loop.now()
        tr2 = db.create_transaction()
        out["v"] = await tr2.get(b"fm02")
        out["dt"] = c.loop.now() - t0
        out["suspect_marked"] = db.failure_states.get(addr)

    c.run_all([(db, scenario())], timeout_vt=600.0)
    assert out["v"] == b"v2"
    assert out["dt"] < 0.3, f"read ate a timeout: {out['dt']}s"
