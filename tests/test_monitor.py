"""Process watchdog: restart-on-crash over real OS processes.

Ref: fdbmonitor/fdbmonitor.cpp (ini config, fork/exec, per-child logdir,
restart backoff :274-283, config reload).
"""

import os
import signal

import pytest
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ppid(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/stat") as f:
            return int(f.read().split(")")[-1].split()[1])
    except OSError:
        return -1


def _children_of(pid: int):
    return [
        int(p)
        for p in os.listdir("/proc")
        if p.isdigit() and _ppid(int(p)) == pid
    ]


@pytest.mark.slow  # tier-1 headroom (ISSUE 4): watchdog integration soak
def test_monitor_restarts_crashed_server(tmp_path):
    conf = tmp_path / "cluster.conf"
    logdir = tmp_path / "logs"
    conf.write_text(
        "[general]\n"
        "restart_delay = 1\n"
        f"logdir = {logdir}\n\n"
        "[server.1]\n"
        f"command = {sys.executable} -u -m foundationdb_tpu.tools.real_node server\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.setdefault("JAX_PLATFORMS", "cpu")
    from foundationdb_tpu.utils.procutil import die_with_parent

    mon = subprocess.Popen(
        [sys.executable, "-m", "foundationdb_tpu.tools.monitor", str(conf)],
        cwd=REPO,
        env=env,
        # The monitor dies with pytest; its own children carry the same
        # PDEATHSIG (monitor.py), so the whole tree is kill-proof.
        preexec_fn=die_with_parent,
    )
    log = logdir / "server.1.log"

    def ready_addrs():
        if not log.exists():
            return []
        return [
            ln.split()[1]
            for ln in log.read_text().splitlines()
            if ln.startswith("READY ")
        ]

    def wait_ready(count, timeout=45.0):
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            addrs = ready_addrs()
            if len(addrs) >= count:
                return addrs[-1]
            time.sleep(0.1)
        raise TimeoutError(f"server READY #{count} never appeared")

    def run_client(addr, ops, check=-1):
        args = [
            sys.executable, "-m", "foundationdb_tpu.tools.real_node",
            "client", addr, "--id", "m", "--ops", str(ops),
        ]
        if check >= 0:
            args += ["--check-count", str(check)]
        return subprocess.run(
            args, cwd=REPO, env=env, capture_output=True, text=True, timeout=60
        )

    try:
        addr = wait_ready(1)
        r = run_client(addr, 5, check=5)
        assert r.returncode == 0, r.stdout + r.stderr

        # SIGKILL the child; the monitor must respawn it (fresh in-memory
        # server: a new READY line with a new port).
        kids = _children_of(mon.pid)
        assert kids, "monitor has no children"
        os.kill(kids[0], signal.SIGKILL)
        addr2 = wait_ready(2)
        r2 = run_client(addr2, 3, check=3)
        assert r2.returncode == 0, r2.stdout + r2.stderr
    finally:
        # Capture the live children BEFORE stopping: after the monitor
        # exits, orphans would be reparented away from mon.pid and a
        # children-of check would pass vacuously.
        live_kids = _children_of(mon.pid)
        mon.send_signal(signal.SIGTERM)
        try:
            mon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            mon.kill()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and any(
            os.path.exists(f"/proc/{k}") for k in live_kids
        ):
            time.sleep(0.1)
        leaked = [k for k in live_kids if os.path.exists(f"/proc/{k}")]
        assert not leaked, f"monitor leaked children: {leaked}"
