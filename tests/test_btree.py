"""COW B+tree engine: model-differential ops, crash recovery, bounded RAM.

Ref: the IKeyValueStore contract (fdbserver/IKeyValueStore.h:38) and the
ssd engine's role (KeyValueStoreSQLite.actor.cpp); crash strategy follows
SURVEY.md §4 (kill the machine, corrupt unsynced writes per KillMode,
recover, assert the acked prefix survived).
"""

import pytest

from foundationdb_tpu.fileio import KillMode, SimFileSystem
from foundationdb_tpu.fileio.btree import BTreeKeyValueStore
from foundationdb_tpu.flow import EventLoop, set_event_loop
from foundationdb_tpu.rpc import SimNetwork


def make_env(seed, kill_mode=KillMode.FULL_CORRUPTION):
    loop = EventLoop(seed=seed)
    set_event_loop(loop)
    net = SimNetwork(loop)
    fs = SimFileSystem(net, kill_mode=kill_mode)
    return loop, net, fs


def drive(loop, proc, coro, timeout_vt=500.0):
    return loop.run_until(proc.spawn(coro), timeout_vt=timeout_vt)


def _rand_key(rng, space=400):
    return b"k%06d" % int(rng.random_int(0, space))


@pytest.mark.parametrize("seed", range(6))
def test_btree_differential_vs_model(seed):
    """Random set/clear/commit stream; every read mode compared against a
    dict model, including overlay (uncommitted) reads."""
    loop, net, fs = make_env(seed)
    proc = net.process("node")

    async def run():
        kv = await BTreeKeyValueStore.open(
            fs, proc, "t.bt", page_size=1024, cache_pages=8
        )
        model = {}
        rng = loop.rng
        for step in range(300):
            r = rng.random01()
            if r < 0.5:
                k, v = _rand_key(rng), b"v%d" % step * int(rng.random_int(1, 4))
                kv.set(k, v)
                model[k] = v
            elif r < 0.7:
                a = _rand_key(rng)
                b = a + b"\xff" if rng.random01() < 0.5 else _rand_key(rng)
                if a > b:
                    a, b = b, a
                kv.clear_range(a, b)
                for k in [k for k in model if a <= k < b]:
                    del model[k]
            elif r < 0.85:
                await kv.commit()
            else:
                # Reads: point + ranges (limits, reverse).
                k = _rand_key(rng)
                assert kv.read_value(k) == model.get(k)
                a, b = sorted((_rand_key(rng), _rand_key(rng)))
                lim = int(rng.random_int(1, 20))
                want = sorted((k, v) for k, v in model.items() if a <= k < b)
                assert kv.read_range(a, b) == want
                assert kv.read_range(a, b, limit=lim) == want[:lim]
                assert (
                    kv.read_range(a, b, limit=lim, reverse=True)
                    == want[::-1][:lim]
                )
        await kv.commit()
        assert kv.read_range(b"", b"\xff") == sorted(model.items())
        assert kv.count() == len(model)

    drive(loop, proc, run())
    set_event_loop(None)


@pytest.mark.parametrize("seed", range(8))
def test_btree_crash_recovery(seed):
    """Kill mid-stream: recovery must yield exactly the last committed
    generation (never a torn mix, never losing acked commits)."""
    loop, net, fs = make_env(seed)
    proc = net.process("node")
    state = {}

    async def writer():
        kv = await BTreeKeyValueStore.open(fs, proc, "t.bt", page_size=1024)
        model = {}
        committed = {}
        rng = loop.rng
        for round_ in range(int(rng.random_int(2, 6))):
            for _ in range(int(rng.random_int(1, 30))):
                if rng.random01() < 0.8:
                    k, v = _rand_key(rng, 100), b"r%d" % round_
                    kv.set(k, v)
                    model[k] = v
                else:
                    a, b = sorted((_rand_key(rng, 100), _rand_key(rng, 100)))
                    kv.clear_range(a, b)
                    for k in [k for k in model if a <= k < b]:
                        del model[k]
            await kv.commit()
            committed = dict(model)
        # Uncommitted tail that must NOT survive.
        kv.set(b"k999999", b"uncommitted")
        state["committed"] = committed

    drive(loop, proc, writer())
    proc.kill()
    fs.crash_machine("node")
    proc.reboot()

    async def recover():
        kv = await BTreeKeyValueStore.open(fs, proc, "t.bt", page_size=1024)
        state["recovered"] = dict(kv.read_range(b"", b"\xff"))

    drive(loop, proc, recover())
    assert state["recovered"] == state["committed"]
    set_event_loop(None)


def test_btree_exceeds_cache_and_reuses_pages():
    """A dataset far larger than the node cache round-trips correctly (the
    beyond-RAM property), and steady churn does not grow the file without
    bound (free-page reuse)."""
    loop, net, fs = make_env(123)
    proc = net.process("node")

    async def run():
        # cache_pages=4: almost every read goes to "disk".
        kv = await BTreeKeyValueStore.open(
            fs, proc, "big.bt", page_size=1024, cache_pages=4
        )
        n = 3000
        for i in range(0, n, 250):
            for j in range(i, min(n, i + 250)):
                kv.set(b"key%08d" % j, b"val%08d" % j)
            await kv.commit()
        assert len(kv._cache) <= 4
        assert kv.count() == n
        # Spot reads across the whole keyspace.
        for j in range(0, n, 97):
            assert kv.read_value(b"key%08d" % j) == b"val%08d" % j
        assert kv.read_range(b"key00001000", b"key00001005") == [
            (b"key%08d" % j, b"val%08d" % j) for j in range(1000, 1005)
        ]
        # Churn the same keys; the file must stop growing once the free
        # list supplies the pages.
        sizes = []
        for round_ in range(12):
            for j in range(0, 200):
                kv.set(b"key%08d" % j, b"upd%03d" % round_)
            await kv.commit()
            sizes.append(kv.file_pages())
        assert sizes[-1] == sizes[-4], f"file kept growing: {sizes}"

    drive(loop, proc, run(), timeout_vt=5000.0)
    set_event_loop(None)


def test_btree_oversized_keys_and_values():
    """Keys/values larger than a page ride chained pages correctly."""
    loop, net, fs = make_env(7)
    proc = net.process("node")

    async def run():
        kv = await BTreeKeyValueStore.open(
            fs, proc, "big2.bt", page_size=512, cache_pages=4
        )
        big_key = b"K" * 3000
        big_val = b"V" * 9000
        kv.set(big_key, big_val)
        kv.set(b"small", b"x")
        await kv.commit()
        assert kv.read_value(big_key) == big_val
        assert kv.read_value(b"small") == b"x"
        out = kv.read_range(b"", b"\xff")
        assert out == [(big_key, big_val), (b"small", b"x")]

    drive(loop, proc, run())
    set_event_loop(None)


def test_btree_engine_cluster_crash_recovery():
    """A DynamicCluster on the btree engine: a dataset well past the node
    cache commits through the full pipeline, the WHOLE cluster loses power,
    and recovery serves every committed row from the btree files (the
    ssd-engine "Done" criterion: data need not fit the engine's RAM)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=60, n_workers=5, storage_engine="btree")
    db = c.database()
    n = 300

    async def fill(tr):
        for i in range(n):
            tr.set(b"bt%06d" % i, b"val%06d" % i)

    c.run_all([(db, db.run(fill))], timeout_vt=600.0)
    c.crash_and_recover()

    out = {}

    async def check(tr):
        out["rows"] = await tr.get_range(b"bt", b"bu")
        tr.set(b"bt-post", b"works")

    c.run_all([(db, db.run(check))], timeout_vt=900.0)
    assert len(out["rows"]) == n
    assert out["rows"][17] == (b"bt%06d" % 17, b"val%06d" % 17)
    # The serving storage really is on the btree engine with a bounded cache.
    storages = [
        robj
        for wk in c.workers
        for rname, robj in wk.roles.items()
        if rname == "storage"
    ]
    from foundationdb_tpu.fileio.btree import BTreeKeyValueStore

    assert storages and all(
        isinstance(s.kvstore, BTreeKeyValueStore) for s in storages
    )
    assert all(len(s.kvstore._cache) <= s.kvstore._cache_cap for s in storages)
    set_event_loop(None)
