"""The perf-experiment flags must stay decision-identical.

FDB_TPU_SEARCH / FDB_TPU_EVICT_EVERY are read at import, so each flag
combination runs its differential (device engine vs CPU oracle) in a
fresh subprocess.  A regression in either experimental path fails here
before it can corrupt an A/B measurement on hardware.
"""

import os
import subprocess
import sys

import pytest

from conftest import REPO_ROOT

# FDB_TPU_SEARCH is read at import, so each SEARCH mode needs its own
# interpreter; the eviction/history flags are read at ENGINE CONSTRUCTION,
# so one subprocess differential-gates several of those variants back to
# back (one jax import instead of one per combo — tier-1 headroom
# satellite).  h_cap stays 1<<16: exactly _2LEVEL_MIN, so the 2level
# search path is genuinely active when that mode is under test.
DIFF = r"""
import os, sys
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.engine_jax import JaxConflictSet
from foundationdb_tpu.conflict.types import TransactionConflictInfo

CTOR_FLAGS = ("FDB_TPU_EVICT_EVERY", "FDB_TPU_HISTORY", "FDB_TPU_DELTA_CAP")
variants = %(variants)s

def txn(rng, now):
    def rr():
        a = int(rng.integers(0, 3000))
        b = a + 1 + int(rng.integers(0, 25))
        return (a.to_bytes(4, "big"), b.to_bytes(4, "big"))
    return TransactionConflictInfo(
        read_snapshot=now - int(rng.integers(0, 40)),
        read_ranges=[rr() for _ in range(int(rng.integers(0, 3)))],
        write_ranges=[rr() for _ in range(int(rng.integers(0, 3)))],
    )

for flags in variants:
    for k in CTOR_FLAGS:
        os.environ.pop(k, None)
    os.environ.update(flags)
    rng = np.random.default_rng(17)
    cpu, dev = CpuConflictSet(), JaxConflictSet(
        key_words=2, h_cap=1 << 16, bucket_mins=(64, 128, 128)
    )
    now = 100
    for batch in range(10):
        txns = [txn(rng, now) for _ in range(int(rng.integers(5, 40)))]
        now += int(rng.integers(1, 25))
        oldest = max(0, now - 90)
        got = dev.detect(txns, now=now, new_oldest_version=oldest)
        want = cpu.detect(txns, now=now, new_oldest_version=oldest)
        assert got == want, (flags, batch, got, want)
    print("VARIANT_OK", flags)
print("OK")
"""


@pytest.mark.parametrize(
    "search_env,variants",
    [
        # flat search: the evict-batching arm and the two-tier history
        # arm (ISSUE 4: small delta cap + cadence alias so the 10-batch
        # stream crosses several major compactions; this is the env-flag
        # end-to-end proof — the in-process tiered edge suite lives in
        # test_tiered_history.py).
        (
            {},
            [
                {"FDB_TPU_EVICT_EVERY": "3"},
                {"FDB_TPU_HISTORY": "tiered", "FDB_TPU_DELTA_CAP": "1024",
                 "FDB_TPU_EVICT_EVERY": "3"},
            ],
        ),
        # 2level search alone and combined with evict batching.
        (
            {"FDB_TPU_SEARCH": "2level"},
            [{}, {"FDB_TPU_EVICT_EVERY": "3"}],
        ),
    ],
    ids=["evict3+tiered", "2level+both"],
)
def test_experiment_flags_decision_identical(search_env, variants):
    env = dict(os.environ)
    env.update(search_env)
    env["PYTHONPATH"] = REPO_ROOT
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, "-c",
         DIFF % {"repo": REPO_ROOT, "variants": repr(variants)}],
        env=env, capture_output=True, text=True, timeout=600, cwd=REPO_ROOT,
    )
    assert res.returncode == 0 and "\nOK" in "\n" + res.stdout, (
        res.stdout[-800:] + res.stderr[-1500:]
    )
    assert res.stdout.count("VARIANT_OK") == len(variants), res.stdout[-800:]


@pytest.mark.slow  # full-suite acceptance gate for the tiered flag: runs the
# conflict + sharded + device-fault differential suites end-to-end under
# FDB_TPU_HISTORY=tiered (~5 min on this host; tier-1 carries the same
# coverage through test_tiered_history + the in-process suites, since the
# flag is read at engine construction)
def test_full_differential_suites_under_tiered_flag():
    env = dict(os.environ)
    env.update({
        "FDB_TPU_HISTORY": "tiered",
        "FDB_TPU_DELTA_CAP": "512",
        "PYTHONPATH": REPO_ROOT,
        "JAX_PLATFORMS": "cpu",
    })
    res = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         "tests/test_conflict_jax.py", "tests/test_device_faults.py",
         "tests/test_sharded_resolver.py"],
        env=env, capture_output=True, text=True, timeout=1800, cwd=REPO_ROOT,
    )
    assert res.returncode == 0, res.stdout[-3000:] + res.stderr[-2000:]
