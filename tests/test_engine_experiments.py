"""The perf-experiment flags must stay decision-identical.

FDB_TPU_SEARCH / FDB_TPU_EVICT_EVERY are read at import, so each flag
combination runs its differential (device engine vs CPU oracle) in a
fresh subprocess.  A regression in either experimental path fails here
before it can corrupt an A/B measurement on hardware.
"""

import os
import subprocess
import sys

import pytest

from conftest import REPO_ROOT

DIFF = r"""
import os, sys
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.engine_jax import JaxConflictSet
from foundationdb_tpu.conflict.types import TransactionConflictInfo

rng = np.random.default_rng(17)

def txn(now):
    def rr():
        a = int(rng.integers(0, 3000))
        b = a + 1 + int(rng.integers(0, 25))
        return (a.to_bytes(4, "big"), b.to_bytes(4, "big"))
    return TransactionConflictInfo(
        read_snapshot=now - int(rng.integers(0, 40)),
        read_ranges=[rr() for _ in range(int(rng.integers(0, 3)))],
        write_ranges=[rr() for _ in range(int(rng.integers(0, 3)))],
    )

cpu, dev = CpuConflictSet(), JaxConflictSet(
    key_words=2, h_cap=1 << 17, bucket_mins=(64, 128, 128)
)
now = 100
for batch in range(10):
    txns = [txn(now) for _ in range(int(rng.integers(5, 40)))]
    now += int(rng.integers(1, 25))
    oldest = max(0, now - 90)
    got = dev.detect(txns, now=now, new_oldest_version=oldest)
    want = cpu.detect(txns, now=now, new_oldest_version=oldest)
    assert got == want, (batch, got, want)
print("OK")
"""


@pytest.mark.parametrize(
    "flags",
    [
        {"FDB_TPU_SEARCH": "2level"},
        {"FDB_TPU_EVICT_EVERY": "3"},
        {"FDB_TPU_SEARCH": "2level", "FDB_TPU_EVICT_EVERY": "3"},
    ],
    ids=["2level", "evict3", "both"],
)
def test_experiment_flags_decision_identical(flags):
    env = dict(os.environ)
    env.update(flags)
    env["PYTHONPATH"] = REPO_ROOT
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, "-c", DIFF % {"repo": REPO_ROOT}],
        env=env, capture_output=True, text=True, timeout=600, cwd=REPO_ROOT,
    )
    assert res.returncode == 0 and "OK" in res.stdout, (
        res.stdout[-500:] + res.stderr[-1500:]
    )
