"""Two-tier history differential gate (ISSUE 4).

FDB_TPU_HISTORY=tiered must be DECISION-IDENTICAL to the CPU reference
(and therefore to the flat device engine) across random streams, major
compactions, base growth/rebase landing on compaction batches, mid-delta
store_to/load_from round-trips, and device faults firing on the batch
that would have compacted.

The flag is read at JaxConflictSet construction, so these tests run
in-process under monkeypatched env (no subprocess per case); the
full-stream subprocess differential under the flag lives in
test_engine_experiments.py.

Shape discipline (1-core CI host): one tiered shape bucket —
key_words=3, bucket_mins=(32, 128, 64), h_cap=1<<10, d_cap=512 — shared
across the module, so the XLA compile is paid once.  The growth test
starts at h_cap=1<<9 and grows INTO the shared shape.
"""

import pytest

from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.engine_jax import REBASE_THRESHOLD, JaxConflictSet
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.types import TransactionConflictInfo as T
from foundationdb_tpu.flow import DeterministicRandom

D_CAP = 512
BUCKETS = (32, 128, 64)


def k(i: int) -> bytes:
    return b"%08d" % i


@pytest.fixture(autouse=True)
def _tiered_env(monkeypatch):
    monkeypatch.setenv("FDB_TPU_HISTORY", "tiered")
    monkeypatch.setenv("FDB_TPU_DELTA_CAP", str(D_CAP))
    yield


def make(**kw):
    kw.setdefault("key_words", 3)
    kw.setdefault("h_cap", 1 << 10)
    kw.setdefault("bucket_mins", BUCKETS)
    cs = JaxConflictSet(**kw)
    assert cs.tiered and cs.d_cap == D_CAP
    return cs


def _random_stream(seed, keyspace, batches, txns_per_batch, snap_lag=25):
    rng = DeterministicRandom(seed)
    version = 10
    out = []
    for _ in range(batches):
        txns = []
        for _ in range(rng.random_int(1, txns_per_batch + 1)):
            tr = T(read_snapshot=max(0, version - rng.random_int(0, snap_lag)))
            for _ in range(rng.random_int(0, 4)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
                tr.read_ranges.append((k(a), k(b)))
            for _ in range(rng.random_int(0, 3)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 10))
                tr.write_ranges.append((k(a), k(b)))
            txns.append(tr)
        now = version + rng.random_int(1, 10)
        new_oldest = max(0, version - snap_lag)
        out.append((txns, now, new_oldest))
        version = now
    return out


def _majors(cs) -> int:
    return cs.metrics.snapshot()["counters"]["major_compactions"]


@pytest.mark.parametrize("seed,cadence", [(11, 2)], ids=["cadence2"])
def test_tiered_differential_vs_cpu_vs_oracle(monkeypatch, seed, cadence):
    """The headline gate: tiered verdicts == CPU == oracle across a
    random stream, with major compactions exercised through the
    FDB_TPU_EVICT_EVERY cadence alias (the fill-triggered compaction
    edge is pinned by test_delta_exactly_full_triggers_compaction)."""
    monkeypatch.setenv("FDB_TPU_EVICT_EVERY", str(cadence))
    jcs = make()
    cpu, orc = CpuConflictSet(), OracleConflictSet()
    for bi, (txns, now, new_oldest) in enumerate(
        _random_stream(seed, 40, batches=30, txns_per_batch=16)
    ):
        gj = jcs.detect(txns, now, new_oldest)
        gc = cpu.detect(txns, now, new_oldest)
        go = orc.detect(txns, now, new_oldest)
        assert gj == gc == go, f"batch {bi}: jax={gj} cpu={gc} oracle={go}"
        # The delta tier may never exceed its capacity (host bound math).
        assert int(jcs._dcount) <= jcs.d_cap
    assert _majors(jcs) >= 1, "stream never exercised a major compaction"
    # One shape bucket, one retrace: the traced-cond compaction adds no
    # compile buckets per batch (the perf_smoke gate pins this harder).
    snap = jcs.metrics.snapshot()
    assert snap["counters"]["retraces"] == len(jcs._bucket_dispatches) == 1


def test_delta_exactly_full_triggers_compaction():
    """Delta-fill edge: with single-bucket batches of known write count
    the host's bound math must fire the compaction exactly when the NEXT
    batch could overflow — the merge itself never truncates (dcount stays
    <= d_cap at every sync), and the delta resets to its floor row."""
    jcs = make()
    cpu = CpuConflictSet()
    wr_cap = BUCKETS[2]
    add = 2 * wr_cap
    v = 0
    saw_reset = False
    for i in range(20):
        # 16 disjoint NON-adjacent single-key writes (adjacent ones would
        # coalesce): +32 delta rows per batch, window pinned at 0 so
        # nothing evicts and the fill is monotone until the compaction.
        # Read probes over earlier writes keep the verdicts non-trivial
        # (phase-1 hits on both tiers).
        txns = [
            T(read_snapshot=v,
              write_ranges=[(k(10_000 * i + 4 * j), k(10_000 * i + 4 * j + 1))
                            for j in range(16)])
        ] + [
            T(read_snapshot=max(0, v - lag),
              read_ranges=[(k(10_000 * max(0, i - back)),
                            k(10_000 * max(0, i - back) + 70))])
            for lag, back in ((1, 1), (12, 3), (0, 0))
        ]
        pre_bound = jcs._dcount_bound
        expect_major = pre_bound + 2 * add + 2 > jcs.d_cap
        v += 5
        assert jcs.detect(txns, v, 0) == cpu.detect(txns, v, 0), f"batch {i}"
        dcount = int(jcs._dcount)
        assert dcount <= jcs.d_cap, "delta overflowed its capacity"
        if expect_major:
            assert jcs._batches_since_major == 0, (
                f"batch {i}: bound math predicted a compaction that "
                f"did not happen (pre_bound={pre_bound})"
            )
            assert dcount == 1, "delta did not reset after compaction"
            saw_reset = True
    assert saw_reset and _majors(jcs) >= 2
    assert jcs.boundary_count == cpu.boundary_count  # post-compaction exact


def test_major_compaction_same_batch_as_grow():
    """Base growth lands ON a compaction batch (the only batch kind that
    can grow the base in tiered mode): decisions stay identical and the
    engine re-enters steady state at the grown capacity."""
    jcs = make(h_cap=1 << 9)
    cpu = CpuConflictSet()
    v = 0
    for i in range(14):
        txns = [
            T(read_snapshot=v,
              write_ranges=[(k(20_000 * i + 100 * t + 2 * j),
                             k(20_000 * i + 100 * t + 2 * j + 1))
                            for j in range(8)])
            for t in range(8)
        ]
        v += 5
        # Window pinned at 0: every boundary is live, so compactions must
        # eventually exhaust 512 rows of base and grow it.
        assert jcs.detect(txns, v, 0) == cpu.detect(txns, v, 0), f"batch {i}"
    snap = jcs.metrics.snapshot()
    assert snap["counters"]["grows"] >= 1, "base never grew"
    assert _majors(jcs) >= 1
    assert jcs.h_cap > (1 << 9)
    assert jcs.boundary_count == cpu.boundary_count


def test_rebase_keeps_tiers_consistent():
    """A version-offset rebase shifts base versions, delta versions AND
    the carried max-table by the same constant; verdicts must keep
    matching the CPU engine straight through it."""
    jcs = make()
    cpu = CpuConflictSet()
    step = REBASE_THRESHOLD // 3 + 7
    v = 0
    for i in range(6):
        txns = [
            T(read_snapshot=v, write_ranges=[(k(100 * i + 2 * j),
                                              k(100 * i + 2 * j + 1))
                                             for j in range(4)]),
            T(read_snapshot=v, read_ranges=[(k(100 * (i - 1)),
                                             k(100 * i + 10))]),
        ]
        v += step
        oldest = max(0, v - 2 * step)
        assert jcs.detect(txns, v, oldest) == cpu.detect(txns, v, oldest), (
            f"batch {i}"
        )
    assert jcs.metrics.snapshot()["counters"]["rebases"] >= 1, (
        "the stream never crossed REBASE_THRESHOLD"
    )


def test_store_load_roundtrip_mid_delta():
    """store_to exports the MERGED view while the delta is non-empty;
    load_from into a fresh tiered engine must continue bit-identically
    (the PR-3 rehydration path)."""
    stream = _random_stream(29, 40, batches=26, txns_per_batch=12)
    jcs = make()
    cpu = CpuConflictSet()
    for txns, now, new_oldest in stream[:14]:
        assert jcs.detect(txns, now, new_oldest) == cpu.detect(
            txns, now, new_oldest
        )
    assert int(jcs._dcount) > 1, "delta empty — round-trip would be trivial"
    mirror = CpuConflictSet()
    jcs.store_to(mirror)
    jcs2 = make()
    jcs2.load_from(mirror)
    assert int(jcs2._dcount) == 1  # rehydration restarts the delta
    for bi, (txns, now, new_oldest) in enumerate(stream[14:]):
        got = jcs2.detect(txns, now, new_oldest)
        want = cpu.detect(txns, now, new_oldest)
        assert got == want, f"post-roundtrip batch {bi}"


def test_fault_during_major_compaction_batch(monkeypatch):
    """DeviceFaultInjector firing at the dispatch of the batch that WOULD
    have run a major compaction (cadence 4 => batch 4), held down through
    the first half-open probe: the breaker degrades to the CPU mirror
    with identical verdicts, recovers, rehydrates through load_from (the
    delta restarts empty), and the recovered engine compacts and keeps
    deciding identically."""
    from foundationdb_tpu.conflict.api import ConflictSet
    from foundationdb_tpu.conflict.device_faults import DeviceFaultInjector

    monkeypatch.setenv("FDB_TPU_EVICT_EVERY", "4")
    stream = _random_stream(37, 50, batches=18, txns_per_batch=10)

    def run():
        inj = DeviceFaultInjector()
        # Dispatch checks are 1:1 with device-attempted batches: checks
        # 4-6 are batches 4-6 (batch 4 is the cadence-4 compaction batch;
        # the fault raises BEFORE any planning/state mutation) — circuit
        # opens at 3 consecutive; check 7 is the first half-open probe,
        # also faulted -> backoff doubles; the second probe succeeds and
        # rehydrates.
        for at in (4, 5, 6, 7):
            inj.script("dispatch", at=at)
        cs = ConflictSet(backend="jax", key_words=3, h_cap=1 << 10,
                         bucket_mins=BUCKETS, fault_injector=inj)
        assert cs._jax.tiered
        verdicts = []
        for txns, now, nov in stream:
            b = cs.new_batch()
            for t in txns:
                b.add_transaction(t)
            verdicts.append(b.detect_conflicts(now, nov))
        return verdicts, cs.device_metrics()

    verdicts, dm = run()
    cpu = CpuConflictSet()
    want = [cpu.detect(txns, now, nov) for txns, now, nov in stream]
    assert verdicts == want, "faulty tiered run diverged from CPU-only run"
    pairs = [(f, t) for _s, f, t, _r in dm["breaker"]["transitions"]]
    assert pairs == [
        ("ok", "degraded"),
        ("degraded", "probing"),
        ("probing", "degraded"),
        ("degraded", "probing"),
        ("probing", "ok"),
    ], dm["breaker"]["transitions"]
    assert dm["counters"]["rehydrates"] >= 1
    assert dm["backend_state"] == "ok"
    assert dm["counters"]["major_compactions"] >= 1  # post-recovery cadence
    assert dm["tiers"]["mode"] == "tiered" and dm["tiers"]["d_cap"] == D_CAP
    # Replay: byte-identical breaker journey (PR-3 discipline).
    verdicts2, dm2 = run()
    import json as _json

    assert verdicts2 == verdicts
    assert _json.dumps(dm2["breaker"]) == _json.dumps(dm["breaker"])


def test_divergence_on_compaction_batch_keeps_bounds_truthful(monkeypatch):
    """Review regression: a fixpoint-diverged batch landing ON a
    compaction batch must still reset the delta (the cond fires on the
    host's flag alone and compacts the REVERTED pre-batch delta — a pure
    physical rewrite of the same logical function), so the host's
    pipelined bookkeeping (_dcount_bound=1) stays a true upper bound and
    later merges can never silently truncate."""
    from foundationdb_tpu.conflict.engine_jax import PackedBatch

    monkeypatch.setenv("FDB_TPU_EVICT_EVERY", "2")
    jcs = make()
    cpu = CpuConflictSet()
    txns1 = [T(read_snapshot=0,
               write_ranges=[(k(4 * j), k(4 * j + 1)) for j in range(16)])]
    assert jcs.detect(txns1, 5, 0) == cpu.detect(txns1, 5, 0)
    assert int(jcs._dcount) > 1  # delta holds batch 1's rows
    # Batch 2 = the cadence-2 compaction batch: a read-tripled dependency
    # chain whose residual (29 undecided txns x 3 reads = 87 slots)
    # overflows RCAP=64 at this bucket -> undecided > 0 on-device.
    chain = [T(read_snapshot=5, write_ranges=[(k(1000), k(1001))])]
    for i in range(1, 31):
        chain.append(
            T(read_snapshot=5,
              read_ranges=[(k(1000 + i - 1), k(1000 + i))] * 3,
              write_ranges=[(k(1000 + i), k(1000 + i + 1))])
        )
    mt, mr, mw = BUCKETS
    pb = PackedBatch.from_transactions(chain, 3, min_txn=mt, min_rr=mr,
                                       min_wr=mw)
    _statuses, undecided = jcs.dispatch_packed(pb, 10, 0)
    assert int(undecided) > 0, "chain failed to overflow the residual"
    assert int(jcs._dcount) == 1, "compaction did not reset the delta"
    assert jcs._dcount_bound == 1, "host bound drifted from device truth"
    assert int(jcs._hcount) > 2 * 16, "base did not absorb the delta"
    assert _majors(jcs) == 1
    # Finish the diverged batch the way detect_packed would, then keep
    # matching the CPU reference — the logical state never forked.
    out = jcs._fallback_cpu(pb, 10, 0)
    assert list(out[: len(chain)]) == cpu.detect(chain, 10, 0)
    probe = [T(read_snapshot=9, read_ranges=[(k(1000), k(1031))])]
    assert jcs.detect(probe, 12, 0) == cpu.detect(probe, 12, 0)


def test_mixed_bucket_batch_grows_delta_instead_of_truncating(monkeypatch):
    """Review regression: batches of a LARGER bucket than the ones that
    filled the delta must not overflow the merge (which runs before the
    compaction cond, so compaction cannot save it) — the pre-merge guard
    syncs the true count and grows the delta, and no boundary is lost."""
    monkeypatch.setenv("FDB_TPU_DELTA_CAP", "512")
    jcs = JaxConflictSet(key_words=3, h_cap=1 << 11, bucket_mins=(8, 8, 8))
    cpu = CpuConflictSet()
    v = 0
    # Fill with wr_cap=16 batches (16 disjoint writes = 32 delta rows
    # each): their OWN fill trigger fires only past 512-66 = 446 rows, so
    # 12 batches legitimately park ~385 rows in the delta uncompacted.
    for i in range(12):
        txns = [T(read_snapshot=v,
                  write_ranges=[(k(10_000 * i + 4 * j),
                                 k(10_000 * i + 4 * j + 1))
                                for j in range(16)])]
        v += 5
        assert jcs.detect(txns, v, 0) == cpu.detect(txns, v, 0), f"fill {i}"
    assert int(jcs._dcount) > 300  # the delta really is near-full
    assert jcs.d_cap == 512
    # One larger-bucket batch: 2 txns x 20 disjoint writes -> wr_cap 64,
    # add 128.  The small-bucket grow guard (2*add+8=264 <= 512) does NOT
    # fire; without the pre-merge must-fit guard the delta merge would
    # need ~385+130 > 512 rows and silently drop the highest keys.
    big = [T(read_snapshot=v,
             write_ranges=[(k(900_000 + 100 * t + 4 * j),
                            k(900_000 + 100 * t + 4 * j + 1))
                           for j in range(20)])
           for t in range(2)]
    v += 5
    assert jcs.detect(big, v, 0) == cpu.detect(big, v, 0)
    assert jcs.d_cap == 1024, "pre-merge guard did not grow the delta"
    # Nothing was truncated: every written range still conflicts reads.
    probes = [T(read_snapshot=0, read_ranges=[(k(10_000 * i),
                                               k(10_000 * i + 70))])
              for i in range(12)] + [
        T(read_snapshot=0, read_ranges=[(k(900_000), k(900_300))])]
    v += 1
    assert jcs.detect(probes, v, 0) == cpu.detect(probes, v, 0)
    assert jcs.boundary_count == cpu.boundary_count


def test_tiered_metrics_surface():
    """device_metrics() carries the tier telemetry: sizes, occupancy,
    compaction count, and the host-side shape facts."""
    from foundationdb_tpu.conflict.api import ConflictSet

    cs = ConflictSet(backend="jax", key_words=3, h_cap=1 << 10,
                     bucket_mins=BUCKETS)
    cpu = CpuConflictSet()
    for txns, now, nov in _random_stream(41, 40, batches=8, txns_per_batch=10):
        b = cs.new_batch()
        for t in txns:
            b.add_transaction(t)
        assert b.detect_conflicts(now, nov) == cpu.detect(txns, now, nov)
    dm = cs.device_metrics()
    assert dm["tiers"] == {
        "mode": "tiered",
        "d_cap": D_CAP,
        "compact_every": 0,
        "batches_since_major": cs._jax._batches_since_major,
        "delta_bound": cs._jax._dcount_bound,
    }
    assert dm["gauges"]["base_boundaries"] >= 1
    assert dm["gauges"]["delta_boundaries"] >= 1
    assert "delta" in dm["last_occupancy"]
    assert "major_compactions" in dm["counters"]
    assert dm["histograms"]["delta_occupancy_synced"]["count"] >= 1
