"""Replication-policy algebra + model-based read load balancing.

Ref: fdbrpc/ReplicationPolicy.h:33,99,119 (PolicyOne/Across/And),
fdbrpc/Locality.h:117, fdbrpc/LoadBalance.actor.h:159 (loadBalance with
the hedged secondRequest :168), fdbrpc/QueueModel.h.
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.rpc.locality import (
    LocalityData,
    PolicyAcross,
    PolicyAnd,
    PolicyOne,
)
from foundationdb_tpu.rpc.loadbalance import QueueModel
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def L(pid, zone, machine="", dc="dc0"):
    return LocalityData(
        process_id=pid, zone_id=zone, machine_id=machine or zone, dc_id=dc
    )


def test_policy_across_zones():
    pol = PolicyAcross(2, "zoneid")
    cands = {
        "a": L("a", "z1"),
        "b": L("b", "z1"),
        "c": L("c", "z2"),
    }
    sel = pol.select_replicas(cands)
    assert sel is not None
    zones = {cands[k].zone_id for k in sel}
    assert len(zones) == 2
    assert pol.validate([cands[k] for k in sel])
    # Only one zone available: unsatisfiable.
    assert pol.select_replicas({"a": L("a", "z1"), "b": L("b", "z1")}) is None


def test_policy_nested_and():
    # Two DCs, each with two zones (the multi-region shape).
    pol = PolicyAnd(
        [
            PolicyAcross(2, "dcid", PolicyAcross(2, "zoneid")),
        ]
    )
    cands = {
        "a": L("a", "z1", dc="dc0"),
        "b": L("b", "z2", dc="dc0"),
        "c": L("c", "z3", dc="dc1"),
        "d": L("d", "z4", dc="dc1"),
        "e": L("e", "z1", dc="dc0"),
    }
    sel = pol.select_replicas(cands)
    assert sel is not None and len(sel) == 4
    assert pol.validate([cands[k] for k in sel])
    # Remove a DC: unsatisfiable.
    del cands["c"], cands["d"]
    assert pol.select_replicas(cands) is None


def test_queue_model_prefers_fast_and_penalizes_failures():
    m = QueueModel()
    m.update("fast", 0.001, False)
    m.update("slow", 0.1, False)
    assert m.order(["slow", "fast"]) == ["fast", "slow"]
    for _ in range(3):
        m.update("fast", 0.001, True)  # repeated failures
    assert m.order(["slow", "fast"]) == ["slow", "fast"]
    m.update("fast", 0.001, False)  # penalty decays on success
    m.update("fast", 0.001, False)
    m.update("fast", 0.001, False)
    assert m.order(["slow", "fast"]) == ["fast", "slow"]


def test_hedged_read_beats_clogged_replica():
    """With a replicated team, clogging the first replica's machine must
    not stall reads: the hedge fires to the runner-up (ref: the
    secondRequest path)."""
    c = SimCluster(seed=140, n_storages=2)
    db = c.database()

    async def fill(tr):
        for i in range(10):
            tr.set(b"h%02d" % i, b"v%d" % i)

    c.run_all([(db, db.run(fill))])
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.move(b"", ["ss0", "ss1"])  # replicate everywhere

    c.run_until(db.process.spawn(place()), timeout_vt=5000.0)

    # Clog the first-ordered replica's machine from the client.
    first = db.queue_model.order(["ss0", "ss1"])[0]
    proc = {s.storage_id: s.process for s in c.storages}[first]
    out = {}

    async def read():
        c.net.clog_pair(
            db.process.machine.machine_id, proc.machine.machine_id, 30.0
        )
        t0 = c.loop.now()
        tr = db.create_transaction()
        out["val"] = await tr.get(b"h03")
        out["dt"] = c.loop.now() - t0

    c.run_all([(db, read())], timeout_vt=1000.0)
    assert out["val"] == b"v3"
    # Far faster than the 30s clog: the hedge answered.
    assert out["dt"] < 5.0, out["dt"]
