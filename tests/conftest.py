"""Test config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on
8 virtual CPU devices (the driver separately dry-runs the multichip path).

Note: this environment's sitecustomize registers the axon TPU plugin and
calls jax.config.update("jax_platforms", "axon,cpu") at interpreter start,
which overrides the JAX_PLATFORMS env var — so we must override the config
back (env vars alone are ineffective).  XLA_FLAGS must be set before the
CPU client is created (first jax.devices() call), which this file
guarantees by running before any test imports jax-using modules.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache — the same directory bench.py uses.
# The suite compiles many IDENTICAL programs into fresh engine instances
# (sharded sets per test, fault-injection rebuilds, the program-cost-table
# AOT pass re-lowering entry points the conflict suites already compiled)
# and on this 1-core host each duplicate XLA compile costs tens of
# seconds.  The cache dedupes them within a single run (and warms across
# runs); entries are keyed on HLO + compile options, so a hit returns the
# byte-identical executable XLA would have produced.
_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"
)
os.makedirs(_CACHE_DIR, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
try:
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except Exception:
    pass  # knob name varies across jax versions; cache still works

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# --- per-test wall-clock timeout (no pytest-timeout in this image) ---
# A hung simulation (e.g. an actor crash swallowed into an infinite retry
# loop) must fail the test, not block the suite forever.  SIGALRM-based:
# Linux-only, single-threaded tests — both true here.  Generous enough for
# first-time JAX compilation (~20-40s).

import signal  # noqa: E402

import pytest  # noqa: E402

TEST_TIMEOUT_S = 180
# XLA compile time on this 1-core host dominates the first test of each
# jitted-engine module (the shard_map trace over 8 virtual devices most of
# all); give those modules the compiler's budget, keep the tight hang
# watchdog everywhere else.
SLOW_COMPILE_MODULES = ("test_sharded_resolver", "test_conflict_jax")
SLOW_COMPILE_TIMEOUT_S = 600


class TestWallClockTimeout(BaseException):
    """BaseException so broad `except Exception` retry handlers in role code
    cannot swallow the watchdog and re-hang the suite."""


# --- real_node subprocess helper (shared by transport/monitor/TLS tests) ---

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_real_node(*args):
    """Spawn `python -m foundationdb_tpu.tools.real_node <args>` with the
    standard env (repo on path, CPU jax) and kernel-enforced reaping."""
    import subprocess

    from foundationdb_tpu.utils.procutil import die_with_parent

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "foundationdb_tpu.tools.real_node", *args],
        cwd=REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        preexec_fn=die_with_parent,
    )


# --- leaked-subprocess sweep (round-3 orphan incident) ---
# PDEATHSIG on every spawn is the primary defense; this is the audit: at
# session end, any still-alive real_node/monitor process started under THIS
# pytest session (identified by an inherited env marker, so concurrent
# sessions / unrelated monitors are untouched) is killed AND reported as a
# failure so leaks can't go unnoticed.

_SESSION_MARKER = f"FDB_TPU_PYTEST_SESSION={os.getpid()}"
os.environ["FDB_TPU_PYTEST_SESSION"] = str(os.getpid())


def _find_leaked_nodes():
    me = os.getpid()
    leaked = []
    for p in os.listdir("/proc"):
        if not p.isdigit() or int(p) == me:
            continue
        try:
            with open(f"/proc/{p}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\x00", b" ").decode(errors="replace")
            if "foundationdb_tpu.tools.real_node" not in cmd and (
                "foundationdb_tpu.tools.monitor" not in cmd
            ):
                continue
            with open(f"/proc/{p}/environ", "rb") as f:
                environ = f.read().replace(b"\x00", b"\n").decode(
                    errors="replace"
                )
        except OSError:
            continue
        if _SESSION_MARKER in environ.splitlines():
            leaked.append((int(p), cmd.strip()))
    return leaked


def pytest_sessionfinish(session, exitstatus):
    leaked = _find_leaked_nodes()
    for pid, cmd in leaked:
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass
        print(f"\nLEAKED SUBPROCESS killed: pid={pid} {cmd}", file=sys.stderr)
    if leaked and exitstatus == 0:
        session.exitstatus = 1


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    budget = TEST_TIMEOUT_S
    if any(m in str(item.fspath) for m in SLOW_COMPILE_MODULES):
        budget = SLOW_COMPILE_TIMEOUT_S
    if item.get_closest_marker("slow") is not None:
        # slow-marked soaks are excluded from tier-1 and bound their own
        # subprocesses; the watchdog only needs to catch a true hang.
        budget = max(budget, 2100)

    def on_alarm(signum, frame):
        raise TestWallClockTimeout(
            f"test exceeded {budget}s wall-clock (hung simulation?)"
        )

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(budget)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
