"""Test config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on
8 virtual CPU devices (the driver separately dry-runs the multichip path).

Note: this environment's sitecustomize registers the axon TPU plugin and
calls jax.config.update("jax_platforms", "axon,cpu") at interpreter start,
which overrides the JAX_PLATFORMS env var — so we must override the config
back (env vars alone are ineffective).  XLA_FLAGS must be set before the
CPU client is created (first jax.devices() call), which this file
guarantees by running before any test imports jax-using modules.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
