"""fdblint tier-1 gate + rule unit tests.

The analyzer (foundationdb_tpu/tools/fdblint.py) plays the actor
compiler's static-gate role: it must hold the whole package at zero
unsuppressed findings, every suppression must carry a reason, and each
rule must actually fire on the pattern it claims to catch (verified here
on planted violations, including a wall-clock read planted into a copy of
a real sim module).

Runnable alone: pytest -m lint
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

import foundationdb_tpu
from foundationdb_tpu.tools.fdblint import (
    LintConfig,
    RULES,
    lint_package,
    lint_source,
    main,
    parse_pragmas,
)

pytestmark = pytest.mark.lint

PKG_DIR = os.path.dirname(os.path.abspath(foundationdb_tpu.__file__))


def rules_of(findings, suppressed=False):
    return [f.rule for f in findings if f.suppressed == suppressed]


@pytest.fixture(scope="module")
def package_findings():
    # One whole-package scan shared by the gate tests (walking + parsing
    # every module 3x over would triple the gate's cost for nothing).
    return lint_package(PKG_DIR)


# ---------------------------------------------------------------------------
# The tier-1 gate: the package itself is clean
# ---------------------------------------------------------------------------


def test_package_has_zero_unsuppressed_findings(package_findings):
    bad = [f for f in package_findings if not f.suppressed]
    assert not bad, "fdblint violations:\n" + "\n".join(
        f.format() for f in bad
    )


def test_every_suppression_carries_a_reason(package_findings):
    suppressed = [f for f in package_findings if f.suppressed]
    # The package genuinely exercises the pragma mechanism...
    assert suppressed, "expected reasoned pragmas in the real-mode modules"
    # ...and lint_source already converts reasonless pragmas into PRG001
    # findings, so a clean run implies every reason is non-empty.  Belt and
    # braces: check the attached reasons directly.
    for f in suppressed:
        assert f.reason.strip(), f"pragma without reason at {f.format()}"


def test_cli_exits_zero_on_package_and_json_format(capsys):
    assert main([PKG_DIR, "--format=json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["unsuppressed"] == 0
    assert out["total"] >= 1  # the suppressed real-mode findings


def test_module_entrypoint_runs():
    proc = subprocess.run(
        [sys.executable, "-m", "foundationdb_tpu.tools.fdblint", PKG_DIR],
        capture_output=True,
        text=True,
        cwd=os.path.dirname(PKG_DIR),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# Planting a violation into a real sim module must fail the gate
# ---------------------------------------------------------------------------


def test_planted_wall_clock_in_sim_module_fails(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    src = os.path.join(PKG_DIR, "flow", "asyncvar.py")
    dst = pkg / "asyncvar.py"
    shutil.copy(src, dst)
    with open(dst, "a", encoding="utf-8") as f:
        f.write(
            "\n\nimport time\n\n"
            "def _leak_wall_clock():\n"
            "    return time.time()\n"
        )
    findings = lint_package(str(pkg))
    det = [f for f in findings if f.rule == "DET001" and not f.suppressed]
    assert det and "time.time" in det[0].message
    # And the CLI agrees: nonzero exit.
    assert main([str(pkg), "--format=json"]) == 1


# ---------------------------------------------------------------------------
# Per-rule unit tests on small planted sources
# ---------------------------------------------------------------------------


def test_det001_wall_clock_variants():
    src = (
        "import time\n"
        "from time import monotonic as mono\n"
        "import datetime\n"
        "def f():\n"
        "    a = time.time()\n"
        "    b = mono()\n"
        "    c = datetime.datetime.now()\n"
        "    clock = time.perf_counter  # binding, not calling\n"
        "    time.sleep(1)\n"
    )
    found = rules_of(lint_source(src, "server/x.py"))
    # from-import line + 4 reads + the smuggled binding
    assert found.count("DET001") == 6


def test_det002_entropy_variants():
    src = (
        "import random\n"
        "import os, uuid\n"
        "from secrets import token_bytes\n"
        "def f():\n"
        "    os.urandom(8)\n"
        "    uuid.uuid4()\n"
    )
    found = rules_of(lint_source(src, "server/x.py"))
    assert found.count("DET002") == 4


def test_det003_threading_and_asyncio():
    src = "import threading\nimport asyncio\nfrom concurrent.futures import ThreadPoolExecutor\n"
    found = rules_of(lint_source(src, "server/x.py"))
    assert found.count("DET003") == 3


def test_act001_dropped_coroutine():
    src = (
        "async def actor():\n"
        "    return 1\n"
        "class Role:\n"
        "    async def _run(self):\n"
        "        return 2\n"
        "    def start(self, loop):\n"
        "        self._run()\n"          # dropped method coroutine
        "        loop.spawn(self._run())\n"  # fine: handed to spawn
        "def g():\n"
        "    actor()\n"                  # dropped function coroutine
    )
    findings = lint_source(src, "server/x.py")
    act = [f for f in findings if f.rule == "ACT001"]
    assert len(act) == 2
    assert {f.line for f in act} == {7, 10}


def test_act001_no_false_positive_on_unrelated_names():
    # `set`/`sync` on other objects must NOT match same-named async defs
    # elsewhere in the module (the simfile/coordination shape).
    src = (
        "class Store:\n"
        "    async def set(self, v):\n"
        "        return v\n"
        "def f(var):\n"
        "    var.set(1)\n"
        "    {1}.union({2})\n"
    )
    assert rules_of(lint_source(src, "server/x.py")) == []


def test_jax001_only_in_traced_modules_and_functions():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def step(x, n):\n"
        "    print(x)\n"
        "    y = x.item()\n"
        "    z = float(x)\n"
        "    w = np.asarray(x)\n"
        "    return x\n"
        "def host(x):\n"
        "    return float(x)\n"  # host code: fine
    )
    in_traced = rules_of(lint_source(src, "ops/x.py"))
    assert in_traced.count("JAX001") == 4
    # Same source outside the traced modules: JAX001 does not apply.
    assert "JAX001" not in rules_of(lint_source(src, "server/x.py"))


def test_jax001_jit_call_and_shard_map_targets():
    src = (
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "def body(x):\n"
        "    print(x)\n"
        "    return x\n"
        "def make(mesh):\n"
        "    def step(x):\n"
        "        x.item()\n"
        "        return x\n"
        "    mapped = shard_map(body, mesh=mesh)\n"
        "    return jax.jit(step)\n"
    )
    found = rules_of(lint_source(src, "parallel/x.py"))
    assert found.count("JAX001") == 2


def test_trc001_dropped_trace_event():
    src = (
        "from foundationdb_tpu.flow.trace import TraceEvent\n"
        "def f(err):\n"
        "    TraceEvent('Dropped')\n"                      # bare: dropped
        "    TraceEvent('AlsoDropped').detail('K', 1)\n"   # chained: dropped
        "    TraceEvent('Ok').detail('K', 1).log()\n"      # emitted
        "    with TraceEvent('CtxOk') as ev:\n"            # context manager
        "        ev.detail('K', 2)\n"
        "    e = TraceEvent('Held')\n"                     # held: assumed logged later
        "    e.detail('K', 3)\n"
        "    e.log()\n"
    )
    findings = lint_source(src, "server/x.py")
    trc = [f for f in findings if f.rule == "TRC001"]
    assert [f.line for f in trc] == [3, 4]


def test_trc001_respects_aliases_and_pragma():
    src = (
        "from foundationdb_tpu.flow import trace\n"
        "def f():\n"
        "    trace.TraceEvent('X').detail('a', 1)\n"
        "    trace.TraceEvent('Y').detail('a', 1)  # fdblint: ignore[TRC001]: handed to a destructor-emit shim in this test\n"
    )
    findings = lint_source(src, "server/x.py")
    assert rules_of(findings) == ["TRC001"]
    assert [f.line for f in findings if f.rule == "TRC001" and not f.suppressed] == [3]
    # Unrelated builders named differently never match.
    src2 = "def f(ev):\n    ev.detail('a', 1)\n    Event('x')\n"
    assert rules_of(lint_source(src2, "server/x.py")) == []


def test_err001_silent_broad_excepts():
    src = (
        "def f(x):\n"
        "    try:\n"
        "        return g(x)\n"
        "    except Exception:\n"       # silent: flagged
        "        pass\n"
        "def g(x):\n"
        "    try:\n"
        "        return h(x)\n"
        "    except:\n"                 # bare: flagged
        "        return None\n"
        "def h(x):\n"
        "    try:\n"
        "        return x\n"
        "    except BaseException:\n"   # tuple-free broad: flagged
        "        x = 1\n"
    )
    findings = lint_source(src, "server/x.py")
    err = [f for f in findings if f.rule == "ERR001"]
    assert [f.line for f in err] == [4, 9, 14]


def test_err001_handled_broad_excepts_are_clean():
    src = (
        "from foundationdb_tpu.flow.trace import TraceEvent\n"
        "def f(x, rep):\n"
        "    try:\n"
        "        return g(x)\n"
        "    except Exception:\n"
        "        raise\n"                                  # re-raise
        "def g(x):\n"
        "    try:\n"
        "        return x\n"
        "    except Exception as e:\n"
        "        TraceEvent('Oops').detail('e', 1).log()\n"  # traced
        "def h(x, rep):\n"
        "    try:\n"
        "        return x\n"
        "    except Exception:\n"
        "        rep.send_error('broken_promise')\n"        # propagated
        "def k(x):\n"
        "    try:\n"
        "        return x\n"
        "    except Exception as e:\n"
        "        return wrap(e)\n"                          # bound name used
        "def n(x):\n"
        "    try:\n"
        "        return x\n"
        "    except (ValueError, KeyError):\n"              # narrow: not broad
        "        return None\n"
    )
    assert "ERR001" not in rules_of(lint_source(src, "server/x.py"))


def test_err001_pragma_on_except_line_only():
    # The pragma must sit on the `except` line; one buried in the handler
    # body does NOT suppress (the body is not a suppression region).
    good = (
        "def f(x):\n"
        "    try:\n"
        "        return g(x)\n"
        "    except Exception:  # fdblint: ignore[ERR001]: probe — failure is the result\n"
        "        return None\n"
    )
    findings = lint_source(good, "server/x.py")
    assert rules_of(findings) == []
    assert [f.reason for f in findings if f.suppressed] == [
        "probe — failure is the result"
    ]
    bad = (
        "def f(x):\n"
        "    try:\n"
        "        return g(x)\n"
        "    except Exception:\n"
        "        return None  # fdblint: ignore[ERR001]: wrong line\n"
    )
    found = rules_of(lint_source(bad, "server/x.py"))
    assert "ERR001" in found and "PRG002" in found  # stale pragma too


def test_io001_open_and_socket():
    src = (
        "import socket\n"
        "def f(path):\n"
        "    s = socket.socket()\n"
        "    with open(path) as fh:\n"
        "        return fh.read()\n"
    )
    found = rules_of(lint_source(src, "layers/x.py"))
    assert found.count("IO001") == 2  # import + open(); socket.socket rides the import
    # The same file under an allowlisted real backend path is clean.
    assert rules_of(lint_source(src, "rpc/real_network.py")) == []


# ---------------------------------------------------------------------------
# Pragma machinery
# ---------------------------------------------------------------------------


def test_pragma_suppresses_with_reason():
    src = "import time\nt = time.time()  # fdblint: ignore[DET001]: real-mode tool path\n"
    findings = lint_source(src, "server/x.py")
    assert rules_of(findings) == []
    sup = [f for f in findings if f.suppressed]
    assert len(sup) == 1 and sup[0].reason == "real-mode tool path"


def test_pragma_without_reason_is_its_own_finding():
    src = "import time\nt = time.time()  # fdblint: ignore[DET001]\n"
    found = rules_of(lint_source(src, "server/x.py"))
    assert "PRG001" in found and "DET001" not in found


def test_stale_and_unknown_pragmas_flagged():
    src = (
        "x = 1  # fdblint: ignore[DET001]: nothing here\n"
        "y = 2  # fdblint: ignore[ZZZ999]: no such rule\n"
    )
    found = rules_of(lint_source(src, "server/x.py"))
    assert found.count("PRG002") == 2


def test_pragma_multi_rule():
    src = (
        "import time, socket\n"
        "def f():\n"
        "    time.sleep(socket.SO_REUSEADDR)  # fdblint: ignore[DET001,IO001]: contrived both-rules line\n"
    )
    # socket import on line 1 still fires; the combined line is suppressed.
    findings = lint_source(src, "server/x.py")
    assert rules_of(findings) == ["IO001"]
    assert [f.line for f in findings if not f.suppressed] == [1]


def test_parse_pragmas_grammar():
    pragmas = parse_pragmas(
        "a  # fdblint: ignore[DET001, IO001]: why not\n"
        "b  # fdblint: ignore[ACT001]\n"
    )
    assert pragmas[1].rules == {"DET001", "IO001"}
    assert pragmas[1].reason == "why not"
    assert pragmas[2].reason == ""


# ---------------------------------------------------------------------------
# Config allowlist
# ---------------------------------------------------------------------------


def test_config_allowlist_merge_and_validation(tmp_path):
    cfg = tmp_path / "lint.json"
    cfg.write_text(json.dumps({"allow": {"DET001": ["layers/special.py"]}}))
    config = LintConfig.load(str(cfg))
    assert config.allows("DET001", "layers/special.py")
    assert config.allows("DET001", "rpc/real_network.py")  # defaults kept
    src = "import time\nt = time.time()\n"
    assert rules_of(lint_source(src, "layers/special.py", config)) == []
    assert "DET001" in rules_of(lint_source(src, "layers/other.py", config))

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"allow": {"NOPE01": ["x.py"]}}))
    with pytest.raises(ValueError):
        LintConfig.load(str(bad))


def test_single_file_mode_keeps_allowlist_and_traced_globs():
    # Linting one module directly must classify it exactly as a whole-
    # package scan does (regression: relpath used to lose the package
    # prefix, voiding every glob).
    real_net = os.path.join(PKG_DIR, "rpc", "real_network.py")
    assert [f for f in lint_package(real_net) if not f.suppressed] == []
    # And a traced module still gets JAX001 coverage in single-file mode.
    eng = os.path.join(PKG_DIR, "conflict", "engine_jax.py")
    assert [f for f in lint_package(eng) if not f.suppressed] == []
    assert main([real_net]) == 0


def test_det002_not_fooled_by_variable_named_random():
    # A parameter holding a DeterministicRandom is the repo's core idiom
    # (the g_random analog); only the imported module may trip DET002.
    src = (
        "def pick(random, seq):\n"
        "    return seq[random.random_int(0, len(seq))]\n"
        "def clock_like(time):\n"
        "    return time.monotonic()\n"
    )
    assert rules_of(lint_source(src, "server/x.py")) == []


def test_pragma_on_any_line_of_a_multiline_statement():
    # The documented escape hatch must work when the flagged expression's
    # node starts on an earlier physical line than the trailing comment.
    src = (
        "import time\n"
        "deadline = (time.monotonic()\n"
        "            + 5)  # fdblint: ignore[DET001]: real-mode deadline\n"
    )
    findings = lint_source(src, "server/x.py")
    assert rules_of(findings) == []
    assert "PRG002" not in [f.rule for f in findings]
    assert [f.reason for f in findings if f.suppressed] == [
        "real-mode deadline"
    ]


def test_act001_method_matching_is_per_class():
    # A sync method may share its name with an async method of ANOTHER
    # class in the same module without tripping ACT001.
    src = (
        "class A:\n"
        "    async def _run(self):\n"
        "        return 1\n"
        "class B:\n"
        "    def _run(self):\n"
        "        return 2\n"
        "    def go(self):\n"
        "        self._run()\n"       # sync: B has no async _run
        "class C:\n"
        "    async def _run(self):\n"
        "        return 3\n"
        "    def go(self):\n"
        "        self._run()\n"       # dropped: C._run IS async
    )
    findings = lint_source(src, "server/x.py")
    act = [f for f in findings if f.rule == "ACT001"]
    assert [f.line for f in act] == [13]


def test_pragma_examples_in_docstrings_are_inert():
    src = (
        '"""Docs showing the escape hatch:\n'
        "    t = time.monotonic()  # fdblint: ignore[DET001]: real-mode\n"
        '"""\n'
        "x = 1\n"
    )
    assert rules_of(lint_source(src, "server/x.py")) == []


def test_rule_registry_documented():
    for rule in ("DET001", "DET002", "DET003", "ACT001", "JAX001", "IO001",
                 "TRC001", "ERR001"):
        assert rule in RULES and RULES[rule]
