"""fdblint tier-1 gate + rule unit tests.

The analyzer (foundationdb_tpu/tools/fdblint.py) plays the actor
compiler's static-gate role: it must hold the whole package at zero
unsuppressed findings, every suppression must carry a reason, and each
rule must actually fire on the pattern it claims to catch (verified here
on planted violations, including a wall-clock read planted into a copy of
a real sim module).

Runnable alone: pytest -m lint
"""

import json
import os
import shutil
import subprocess
import sys
import time

import pytest

import foundationdb_tpu
from foundationdb_tpu.tools.fdblint import (
    LintConfig,
    Project,
    RULES,
    count_by_rule,
    format_counts,
    lint_package,
    lint_source,
    main,
    parse_pragmas,
)
from foundationdb_tpu.tools.lint import runner as lint_runner

pytestmark = pytest.mark.lint

PKG_DIR = os.path.dirname(os.path.abspath(foundationdb_tpu.__file__))
CASES_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "lint_cases")


def rules_of(findings, suppressed=False):
    return [f.rule for f in findings if f.suppressed == suppressed]


@pytest.fixture(scope="module")
def package_findings():
    # One whole-package scan shared by the gate tests (walking + parsing
    # every module 3x over would triple the gate's cost for nothing).
    # Printed through the unified runner's per-tool formatting so the
    # tier-1 output attributes every count to its tool (bypassing capture
    # on purpose: a rule whose finding count quietly drifts is how
    # regressions hide).
    by_tool = lint_runner.run_source_tools(PKG_DIR, LintConfig())
    print("", file=sys.__stderr__)
    for line in lint_runner.format_tool_counts(by_tool):
        print(line, file=sys.__stderr__)
    return [f for fs in by_tool.values() for f in fs]


# ---------------------------------------------------------------------------
# The tier-1 gate: the package itself is clean
# ---------------------------------------------------------------------------


def test_package_has_zero_unsuppressed_findings(package_findings):
    bad = [f for f in package_findings if not f.suppressed]
    assert not bad, "fdblint violations:\n" + "\n".join(
        f.format() for f in bad
    )


def test_every_suppression_carries_a_reason(package_findings):
    suppressed = [f for f in package_findings if f.suppressed]
    # The package genuinely exercises the pragma mechanism...
    assert suppressed, "expected reasoned pragmas in the real-mode modules"
    # ...and lint_source already converts reasonless pragmas into PRG001
    # findings, so a clean run implies every reason is non-empty.  Belt and
    # braces: check the attached reasons directly.
    for f in suppressed:
        assert f.reason.strip(), f"pragma without reason at {f.format()}"


def test_cli_exits_zero_on_package_and_json_format(capsys):
    assert main([PKG_DIR, "--format=json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["unsuppressed"] == 0
    assert out["total"] >= 1  # the suppressed real-mode findings


def test_module_entrypoint_runs():
    proc = subprocess.run(
        [sys.executable, "-m", "foundationdb_tpu.tools.fdblint", PKG_DIR],
        capture_output=True,
        text=True,
        cwd=os.path.dirname(PKG_DIR),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_script_mode_entrypoint_runs():
    # `python path/to/fdblint.py` (no -m, arbitrary cwd): the shim
    # bootstraps the repo root so wrappers/pre-commit hooks that invoke
    # it by path keep working.
    proc = subprocess.run(
        [sys.executable, os.path.join(PKG_DIR, "tools", "fdblint.py"),
         PKG_DIR],
        capture_output=True,
        text=True,
        cwd="/",
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_wait001_body_pragma_does_not_cover_header_finding():
    # A compound statement's pragma scope is its HEADER only: a stale
    # pragma deep in the loop body must not absorb (and silently
    # consume against) a finding on the `while` test — it suppresses
    # nothing and ages into PRG002.  On the header line it suppresses.
    body_pragma = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d['k'] = 1\n"
        "    async def f(self, loop):\n"
        "        lane = self.d\n"
        "        await loop.delay(1)\n"
        "        while lane['k']:\n"
        "            x = 1  # fdblint: ignore[WAIT001]: unrelated\n"
        "            await loop.delay(1)\n"
    )
    findings = lint_source(body_pragma, "server/x.py")
    wait = [f for f in findings if f.rule == "WAIT001"]
    assert [f.line for f in wait] == [7] and not wait[0].suppressed
    assert any(f.rule == "PRG002" for f in findings)
    header_pragma = body_pragma.replace(
        "        while lane['k']:\n",
        "        while lane['k']:  # fdblint: ignore[WAIT001]: singleton\n",
    ).replace("  # fdblint: ignore[WAIT001]: unrelated", "")
    findings = lint_source(header_pragma, "server/x.py")
    wait = [f for f in findings if f.rule == "WAIT001"]
    assert [f.suppressed for f in wait] == [True]
    assert not any(f.rule == "PRG002" for f in findings)


# ---------------------------------------------------------------------------
# Planting a violation into a real sim module must fail the gate
# ---------------------------------------------------------------------------


def test_planted_wall_clock_in_sim_module_fails(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    src = os.path.join(PKG_DIR, "flow", "asyncvar.py")
    dst = pkg / "asyncvar.py"
    shutil.copy(src, dst)
    with open(dst, "a", encoding="utf-8") as f:
        f.write(
            "\n\nimport time\n\n"
            "def _leak_wall_clock():\n"
            "    return time.time()\n"
        )
    findings = lint_package(str(pkg))
    det = [f for f in findings if f.rule == "DET001" and not f.suppressed]
    assert det and "time.time" in det[0].message
    # And the CLI agrees: nonzero exit.
    assert main([str(pkg), "--format=json"]) == 1


# ---------------------------------------------------------------------------
# Per-rule unit tests on small planted sources
# ---------------------------------------------------------------------------


def test_det001_wall_clock_variants():
    src = (
        "import time\n"
        "from time import monotonic as mono\n"
        "import datetime\n"
        "def f():\n"
        "    a = time.time()\n"
        "    b = mono()\n"
        "    c = datetime.datetime.now()\n"
        "    clock = time.perf_counter  # binding, not calling\n"
        "    time.sleep(1)\n"
    )
    found = rules_of(lint_source(src, "server/x.py"))
    # from-import line + 4 reads + the smuggled binding
    assert found.count("DET001") == 6


def test_det002_entropy_variants():
    src = (
        "import random\n"
        "import os, uuid\n"
        "from secrets import token_bytes\n"
        "def f():\n"
        "    os.urandom(8)\n"
        "    uuid.uuid4()\n"
    )
    found = rules_of(lint_source(src, "server/x.py"))
    assert found.count("DET002") == 4


def test_det003_threading_and_asyncio():
    src = "import threading\nimport asyncio\nfrom concurrent.futures import ThreadPoolExecutor\n"
    found = rules_of(lint_source(src, "server/x.py"))
    assert found.count("DET003") == 3


def test_act001_dropped_coroutine():
    src = (
        "async def actor():\n"
        "    return 1\n"
        "class Role:\n"
        "    async def _run(self):\n"
        "        return 2\n"
        "    def start(self, loop):\n"
        "        self._run()\n"          # dropped method coroutine
        "        loop.spawn(self._run())\n"  # fine: handed to spawn
        "def g():\n"
        "    actor()\n"                  # dropped function coroutine
    )
    findings = lint_source(src, "server/x.py")
    act = [f for f in findings if f.rule == "ACT001"]
    assert len(act) == 2
    assert {f.line for f in act} == {7, 10}


def test_act001_no_false_positive_on_unrelated_names():
    # `set`/`sync` on other objects must NOT match same-named async defs
    # elsewhere in the module (the simfile/coordination shape).
    src = (
        "class Store:\n"
        "    async def set(self, v):\n"
        "        return v\n"
        "def f(var):\n"
        "    var.set(1)\n"
        "    {1}.union({2})\n"
    )
    assert rules_of(lint_source(src, "server/x.py")) == []


def test_jax001_only_in_traced_modules_and_functions():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def step(x, n):\n"
        "    print(x)\n"
        "    y = x.item()\n"
        "    z = float(x)\n"
        "    w = np.asarray(x)\n"
        "    return x\n"
        "def host(x):\n"
        "    return float(x)\n"  # host code: fine
    )
    in_traced = rules_of(lint_source(src, "ops/x.py"))
    assert in_traced.count("JAX001") == 4
    # Same source outside the traced modules: JAX001 does not apply.
    assert "JAX001" not in rules_of(lint_source(src, "server/x.py"))


def test_jax001_jit_call_and_shard_map_targets():
    src = (
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "def body(x):\n"
        "    print(x)\n"
        "    return x\n"
        "def make(mesh):\n"
        "    def step(x):\n"
        "        x.item()\n"
        "        return x\n"
        "    mapped = shard_map(body, mesh=mesh)\n"
        "    return jax.jit(step)\n"
    )
    found = rules_of(lint_source(src, "parallel/x.py"))
    assert found.count("JAX001") == 2


def test_trc001_dropped_trace_event():
    src = (
        "from foundationdb_tpu.flow.trace import TraceEvent\n"
        "def f(err):\n"
        "    TraceEvent('Dropped')\n"                      # bare: dropped
        "    TraceEvent('AlsoDropped').detail('K', 1)\n"   # chained: dropped
        "    TraceEvent('Ok').detail('K', 1).log()\n"      # emitted
        "    with TraceEvent('CtxOk') as ev:\n"            # context manager
        "        ev.detail('K', 2)\n"
        "    e = TraceEvent('Held')\n"                     # held: assumed logged later
        "    e.detail('K', 3)\n"
        "    e.log()\n"
    )
    findings = lint_source(src, "server/x.py")
    trc = [f for f in findings if f.rule == "TRC001"]
    assert [f.line for f in trc] == [3, 4]


def test_trc001_respects_aliases_and_pragma():
    src = (
        "from foundationdb_tpu.flow import trace\n"
        "def f():\n"
        "    trace.TraceEvent('X').detail('a', 1)\n"
        "    trace.TraceEvent('Y').detail('a', 1)  # fdblint: ignore[TRC001]: handed to a destructor-emit shim in this test\n"
    )
    findings = lint_source(src, "server/x.py")
    assert rules_of(findings) == ["TRC001"]
    assert [f.line for f in findings if f.rule == "TRC001" and not f.suppressed] == [3]
    # Unrelated builders named differently never match.
    src2 = "def f(ev):\n    ev.detail('a', 1)\n    Event('x')\n"
    assert rules_of(lint_source(src2, "server/x.py")) == []


def test_err001_silent_broad_excepts():
    src = (
        "def f(x):\n"
        "    try:\n"
        "        return g(x)\n"
        "    except Exception:\n"       # silent: flagged
        "        pass\n"
        "def g(x):\n"
        "    try:\n"
        "        return h(x)\n"
        "    except:\n"                 # bare: flagged
        "        return None\n"
        "def h(x):\n"
        "    try:\n"
        "        return x\n"
        "    except BaseException:\n"   # tuple-free broad: flagged
        "        x = 1\n"
    )
    findings = lint_source(src, "server/x.py")
    err = [f for f in findings if f.rule == "ERR001"]
    assert [f.line for f in err] == [4, 9, 14]


def test_err001_handled_broad_excepts_are_clean():
    src = (
        "from foundationdb_tpu.flow.trace import TraceEvent\n"
        "def f(x, rep):\n"
        "    try:\n"
        "        return g(x)\n"
        "    except Exception:\n"
        "        raise\n"                                  # re-raise
        "def g(x):\n"
        "    try:\n"
        "        return x\n"
        "    except Exception as e:\n"
        "        TraceEvent('Oops').detail('e', 1).log()\n"  # traced
        "def h(x, rep):\n"
        "    try:\n"
        "        return x\n"
        "    except Exception:\n"
        "        rep.send_error('broken_promise')\n"        # propagated
        "def k(x):\n"
        "    try:\n"
        "        return x\n"
        "    except Exception as e:\n"
        "        return wrap(e)\n"                          # bound name used
        "def n(x):\n"
        "    try:\n"
        "        return x\n"
        "    except (ValueError, KeyError):\n"              # narrow: not broad
        "        return None\n"
    )
    assert "ERR001" not in rules_of(lint_source(src, "server/x.py"))


def test_err001_pragma_on_except_line_only():
    # The pragma must sit on the `except` line; one buried in the handler
    # body does NOT suppress (the body is not a suppression region).
    good = (
        "def f(x):\n"
        "    try:\n"
        "        return g(x)\n"
        "    except Exception:  # fdblint: ignore[ERR001]: probe — failure is the result\n"
        "        return None\n"
    )
    findings = lint_source(good, "server/x.py")
    assert rules_of(findings) == []
    assert [f.reason for f in findings if f.suppressed] == [
        "probe — failure is the result"
    ]
    bad = (
        "def f(x):\n"
        "    try:\n"
        "        return g(x)\n"
        "    except Exception:\n"
        "        return None  # fdblint: ignore[ERR001]: wrong line\n"
    )
    found = rules_of(lint_source(bad, "server/x.py"))
    assert "ERR001" in found and "PRG002" in found  # stale pragma too


def test_io001_open_and_socket():
    src = (
        "import socket\n"
        "def f(path):\n"
        "    s = socket.socket()\n"
        "    with open(path) as fh:\n"
        "        return fh.read()\n"
    )
    found = rules_of(lint_source(src, "layers/x.py"))
    assert found.count("IO001") == 2  # import + open(); socket.socket rides the import
    # The same file under an allowlisted real backend path is clean.
    assert rules_of(lint_source(src, "rpc/real_network.py")) == []


# ---------------------------------------------------------------------------
# Pragma machinery
# ---------------------------------------------------------------------------


def test_pragma_suppresses_with_reason():
    src = "import time\nt = time.time()  # fdblint: ignore[DET001]: real-mode tool path\n"
    findings = lint_source(src, "server/x.py")
    assert rules_of(findings) == []
    sup = [f for f in findings if f.suppressed]
    assert len(sup) == 1 and sup[0].reason == "real-mode tool path"


def test_pragma_without_reason_is_its_own_finding():
    src = "import time\nt = time.time()  # fdblint: ignore[DET001]\n"
    found = rules_of(lint_source(src, "server/x.py"))
    assert "PRG001" in found and "DET001" not in found


def test_stale_and_unknown_pragmas_flagged():
    src = (
        "x = 1  # fdblint: ignore[DET001]: nothing here\n"
        "y = 2  # fdblint: ignore[ZZZ999]: no such rule\n"
    )
    found = rules_of(lint_source(src, "server/x.py"))
    assert found.count("PRG002") == 2


def test_pragma_multi_rule():
    src = (
        "import time, socket\n"
        "def f():\n"
        "    time.sleep(socket.SO_REUSEADDR)  # fdblint: ignore[DET001,IO001]: contrived both-rules line\n"
    )
    # socket import on line 1 still fires; the combined line is suppressed.
    findings = lint_source(src, "server/x.py")
    assert rules_of(findings) == ["IO001"]
    assert [f.line for f in findings if not f.suppressed] == [1]


def test_parse_pragmas_grammar():
    pragmas = parse_pragmas(
        "a  # fdblint: ignore[DET001, IO001]: why not\n"
        "b  # fdblint: ignore[ACT001]\n"
    )
    assert pragmas[1].rules == {"DET001", "IO001"}
    assert pragmas[1].reason == "why not"
    assert pragmas[2].reason == ""


# ---------------------------------------------------------------------------
# Config allowlist
# ---------------------------------------------------------------------------


def test_config_allowlist_merge_and_validation(tmp_path):
    cfg = tmp_path / "lint.json"
    cfg.write_text(json.dumps({"allow": {"DET001": ["layers/special.py"]}}))
    config = LintConfig.load(str(cfg))
    assert config.allows("DET001", "layers/special.py")
    assert config.allows("DET001", "rpc/real_network.py")  # defaults kept
    src = "import time\nt = time.time()\n"
    assert rules_of(lint_source(src, "layers/special.py", config)) == []
    assert "DET001" in rules_of(lint_source(src, "layers/other.py", config))

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"allow": {"NOPE01": ["x.py"]}}))
    with pytest.raises(ValueError):
        LintConfig.load(str(bad))


def test_single_file_mode_keeps_allowlist_and_traced_globs():
    # Linting one module directly must classify it exactly as a whole-
    # package scan does (regression: relpath used to lose the package
    # prefix, voiding every glob).
    real_net = os.path.join(PKG_DIR, "rpc", "real_network.py")
    assert [f for f in lint_package(real_net) if not f.suppressed] == []
    # And a traced module still gets JAX001 coverage in single-file mode.
    eng = os.path.join(PKG_DIR, "conflict", "engine_jax.py")
    assert [f for f in lint_package(eng) if not f.suppressed] == []
    assert main([real_net]) == 0


def test_single_file_mode_consumes_cross_module_det101_pragmas():
    # An in-package file is linted with the WHOLE enclosing package loaded
    # (the --changed-only trick), so a pragma that cuts a cross-module
    # DET101 taint edge is consumed exactly as in a package scan.
    # Regression: lint_source saw only the lone module's summary, the edge
    # into rpc/real_network.py never resolved, and the pragmas were
    # reported as stale PRG002 with exit 1 — spuriously failing any
    # editor/pre-commit integration that lints the edited file alone.
    mv = os.path.join(PKG_DIR, "client", "multi_version.py")
    findings = lint_package(mv)
    assert [f for f in findings if not f.suppressed] == []
    assert "PRG002" not in [f.rule for f in findings]
    assert main([mv]) == 0


def test_det002_not_fooled_by_variable_named_random():
    # A parameter holding a DeterministicRandom is the repo's core idiom
    # (the g_random analog); only the imported module may trip DET002.
    src = (
        "def pick(random, seq):\n"
        "    return seq[random.random_int(0, len(seq))]\n"
        "def clock_like(time):\n"
        "    return time.monotonic()\n"
    )
    assert rules_of(lint_source(src, "server/x.py")) == []


def test_pragma_on_any_line_of_a_multiline_statement():
    # The documented escape hatch must work when the flagged expression's
    # node starts on an earlier physical line than the trailing comment.
    src = (
        "import time\n"
        "deadline = (time.monotonic()\n"
        "            + 5)  # fdblint: ignore[DET001]: real-mode deadline\n"
    )
    findings = lint_source(src, "server/x.py")
    assert rules_of(findings) == []
    assert "PRG002" not in [f.rule for f in findings]
    assert [f.reason for f in findings if f.suppressed] == [
        "real-mode deadline"
    ]


def test_act001_method_matching_is_per_class():
    # A sync method may share its name with an async method of ANOTHER
    # class in the same module without tripping ACT001.
    src = (
        "class A:\n"
        "    async def _run(self):\n"
        "        return 1\n"
        "class B:\n"
        "    def _run(self):\n"
        "        return 2\n"
        "    def go(self):\n"
        "        self._run()\n"       # sync: B has no async _run
        "class C:\n"
        "    async def _run(self):\n"
        "        return 3\n"
        "    def go(self):\n"
        "        self._run()\n"       # dropped: C._run IS async
    )
    findings = lint_source(src, "server/x.py")
    act = [f for f in findings if f.rule == "ACT001"]
    assert [f.line for f in act] == [13]


def test_pragma_examples_in_docstrings_are_inert():
    src = (
        '"""Docs showing the escape hatch:\n'
        "    t = time.monotonic()  # fdblint: ignore[DET001]: real-mode\n"
        '"""\n'
        "x = 1\n"
    )
    assert rules_of(lint_source(src, "server/x.py")) == []


def test_rule_registry_documented():
    for rule in ("DET001", "DET002", "DET003", "ACT001", "JAX001", "IO001",
                 "TRC001", "SPN001", "ERR001"):
        assert rule in RULES and RULES[rule]


def test_spn001_leaked_vs_handled_spans():
    """SPN001 (TRC001's span-layer mirror): statement-level begin_span
    chains without .end() are leaks; `with`, explicit end, and stored
    results are the legitimate shapes."""
    src = (
        "from foundationdb_tpu.flow.spans import begin_span\n"
        "def bad():\n"
        "    begin_span('x')\n"
        "    begin_span('y').annotate('k', 1)\n"
        "def good(ctx):\n"
        "    with begin_span('a'):\n"
        "        pass\n"
        "    begin_span('b').end()\n"
        "    sp = begin_span('c')\n"
        "    ctx.span = begin_span('d')\n"
        "    return sp\n"
    )
    findings = lint_source(src, "server/x.py")
    spn = [f for f in findings if f.rule == "SPN001"]
    assert [f.line for f in spn] == [3, 4]
    # Pragma with a reason suppresses; the suppression is counted.
    src2 = (
        "from foundationdb_tpu.flow.spans import begin_span\n"
        "def f():\n"
        "    begin_span('x')  # fdblint: ignore[SPN001]: harness ends every open span at teardown\n"
    )
    assert not [
        f for f in lint_source(src2, "server/x.py") if not f.suppressed
    ]


# ---------------------------------------------------------------------------
# New-rule unit tests (WAIT001/WAIT002, RPY001, DET101, ENV001)
# ---------------------------------------------------------------------------


def test_wait001_capture_reread_and_value_use():
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d['k'] = 1\n"
        "    async def bad(self, loop):\n"
        "        snap = self.d\n"
        "        await loop.delay(1)\n"
        "        return snap['k']\n"          # deref after await: flagged
        "    async def reread(self, loop):\n"
        "        snap = self.d\n"
        "        await loop.delay(1)\n"
        "        snap = self.d\n"             # re-read kills the capture
        "        return snap['k']\n"
        "    async def value_use(self, loop):\n"
        "        snap = self.d\n"
        "        await loop.delay(1)\n"
        "        return f(snap)\n"            # value use: snapshot, clean
    )
    findings = lint_source(src, "server/x.py")
    wait = [f for f in findings if f.rule == "WAIT001"]
    assert [f.line for f in wait] == [7]


def test_wait001_needs_mutation_evidence():
    # Only assigned in __init__: config-immutable, captures never flag.
    src = (
        "class R:\n"
        "    def __init__(self):\n"
        "        self.cfg = {}\n"
        "    async def ok(self, loop):\n"
        "        c = self.cfg\n"
        "        await loop.delay(1)\n"
        "        return c['a']\n"
    )
    assert rules_of(lint_source(src, "server/x.py")) == []


def test_wait001_branch_epoch_is_path_scoped():
    # A deref on an await-FREE branch must not inherit the sibling
    # branch's suspension...
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d['k'] = 1\n"
        "    async def ok(self, loop, cond):\n"
        "        snap = self.d\n"
        "        if cond:\n"
        "            await loop.delay(1)\n"
        "            return None\n"
        "        return snap['k']\n"          # no await on this path
        "    async def bad(self, loop, cond):\n"
        "        snap = self.d\n"
        "        if cond:\n"
        "            await loop.delay(1)\n"
        "        return snap['k']\n"          # await MAY have happened
    )
    findings = lint_source(src, "server/x.py")
    wait = [f for f in findings if f.rule == "WAIT001"]
    # ...while code AFTER the If still counts either branch's await.
    assert [f.line for f in wait] == [14]


def test_wait001_if_branch_reread_clears_and_pairs_with_its_epoch():
    # The re-read lives INSIDE the awaiting branch: every real path is
    # safe (then-path re-reads after its await, else-path never awaits) —
    # merging one branch's env with the other's epoch must not flag it.
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d['k'] = 1\n"
        "    async def ok(self, loop, cond):\n"
        "        snap = self.d\n"
        "        if cond:\n"
        "            await loop.delay(1)\n"
        "            snap = self.d\n"
        "        return snap['k']\n"
    )
    assert "WAIT001" not in rules_of(lint_source(src, "server/x.py"))


def test_wait001_try_handler_sees_pre_reread_state():
    # The body can raise AT the await — before the re-read — so the
    # handler's deref is stale even though the fall-through one is not.
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d['k'] = 1\n"
        "    async def f(self, loop):\n"
        "        snap = self.d\n"
        "        try:\n"
        "            await loop.delay(1)\n"
        "            snap = self.d\n"
        "        except Exception as e:\n"
        "            return (snap['k'], e)\n"   # stale on the raise path
        "        return snap['k']\n"            # fresh: re-read completed
    )
    findings = lint_source(src, "server/x.py")
    wait = [f for f in findings if f.rule == "WAIT001"]
    assert [f.line for f in wait] == [10]


def test_wait001_except_name_shadowing_capture_is_a_rebind():
    # `except E as snap:` binds snap to the FRESH exception — a handler
    # deref of it is not a stale-capture use, same as any other rebind.
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d['k'] = 1\n"
        "    async def f(self, loop, log):\n"
        "        snap = self.d\n"
        "        await loop.delay(1)\n"
        "        try:\n"
        "            log('x')\n"
        "        except Exception as snap:\n"
        "            log(snap.args)\n"
        "        return 0\n"
    )
    findings = lint_source(src, "server/x.py")
    assert [f for f in findings if f.rule == "WAIT001"] == []


def test_wait001_handler_fallthrough_carries_staleness_past_try():
    # The raise-at-await path swallowed by a falling-through handler
    # skips the body's re-read: the post-try deref is stale on that path.
    # A handler that re-reads itself keeps the post-try code clean.
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d['k'] = 1\n"
        "    async def bad(self, loop, log):\n"
        "        snap = self.d\n"
        "        try:\n"
        "            await loop.delay(1)\n"
        "            snap = self.d\n"
        "        except Exception as e:\n"
        "            log(e)\n"
        "        return snap['k']\n"
        "    async def ok(self, loop, log):\n"
        "        snap = self.d\n"
        "        try:\n"
        "            await loop.delay(1)\n"
        "            snap = self.d\n"
        "        except Exception as e:\n"
        "            log(e)\n"
        "            snap = self.d\n"
        "        return snap['k']\n"
    )
    findings = lint_source(src, "server/x.py")
    wait = [f for f in findings if f.rule == "WAIT001"]
    assert [f.line for f in wait] == [11]


def test_wait002_live_iteration_vs_snapshot():
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d.update({})\n"
        "    async def bad(self, loop):\n"
        "        for k in self.d:\n"          # live dict + awaiting body
        "            await loop.delay(1)\n"
        "    async def ok(self, loop):\n"
        "        for k in list(self.d):\n"    # snapshot
        "            await loop.delay(1)\n"
        "    async def no_await(self, loop):\n"
        "        for k in self.d:\n"          # no suspension: clean
        "            f(k)\n"
    )
    findings = lint_source(src, "server/x.py")
    w2 = [f for f in findings if f.rule == "WAIT002"]
    assert [f.line for f in w2] == [5]


def test_wait_rules_async_for_header_and_walrus_capture():
    # `async for` suspends at every __anext__ even with an await-free
    # body, and a walrus capture is the same stale-deref class as the
    # two-line spelling.
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.q.update({})\n"
        "    async def bad_iter(self):\n"
        "        async for req in self.q:\n"
        "            handle(req)\n"
        "    async def bad_walrus(self, loop):\n"
        "        if (snap := self.q):\n"
        "            await loop.delay(1)\n"
        "            return snap['k']\n"
    )
    findings = lint_source(src, "server/x.py")
    assert any(f.rule == "WAIT002" and f.line == 5 for f in findings)
    assert any(f.rule == "WAIT001" and f.line == 10 for f in findings)


def test_rpy001_leak_send_and_handoff():
    src = (
        "class H:\n"
        "    async def leak(self, req, reply):\n"
        "        if req is None:\n"
        "            return\n"                      # leak path
        "        reply.send(req)\n"
        "    async def ok(self, req, reply):\n"
        "        if req is None:\n"
        "            reply.send_error('x')\n"
        "            return\n"
        "        reply.send(req)\n"
        "    async def spawned(self, stream, proc):\n"
        "        while True:\n"
        "            req, reply = await stream.pop()\n"
        "            proc.spawn(self.ok(req, reply), 'h')\n"  # handoff
    )
    findings = lint_source(src, "server/x.py")
    rpy = [f for f in findings if f.rule == "RPY001"]
    assert [f.line for f in rpy] == [2]


def test_rpy001_only_in_server_and_rpc():
    src = (
        "async def leak(req, reply):\n"
        "    return None\n"
    )
    assert "RPY001" in rules_of(lint_source(src, "server/x.py"))
    assert "RPY001" in rules_of(lint_source(src, "rpc/x.py"))
    assert "RPY001" not in rules_of(lint_source(src, "layers/x.py"))


def test_rpy001_swallowed_except_with_in_try_acquisition():
    # The headline serve-loop shape: pop INSIDE the try, awaits between
    # pop and send, handler swallows — the raise-after-acquire path drops
    # the reply.  A bare pop as the try's last statement cannot fail
    # after binding, so recover-and-resend stays clean.
    src = (
        "class H:\n"
        "    async def leaky(self, stream, log):\n"
        "        while True:\n"
        "            try:\n"
        "                req, reply = await stream.pop()\n"
        "                data = await compute(req)\n"
        "                reply.send(data)\n"
        "            except Exception as e:\n"
        "                log(e)\n"                       # reply dropped
        "    async def ok(self, stream, log):\n"
        "        while True:\n"
        "            try:\n"
        "                req, reply = await stream.pop()\n"
        "            except Exception as e:\n"
        "                log(e)\n"                       # nothing acquired
        "                continue\n"
        "            reply.send(req)\n"
    )
    findings = lint_source(src, "server/x.py")
    rpy = [f for f in findings if f.rule == "RPY001"]
    assert [f.line for f in rpy] == [5]


def test_rpy001_while_test_mention_does_not_resolve():
    # A loop test is a bare branch test like If's: `while reply.pending()`
    # inspects the reply without resolving it — the exit path still
    # leaks (an in-body send alone would not either: the zero-iteration
    # path skips it).  A send after the loop covers every path.
    src = (
        "class H:\n"
        "    async def leaky(self, stream, tick):\n"
        "        req, reply = await stream.pop()\n"
        "        while reply.pending():\n"
        "            await tick()\n"
        "        return None\n"
        "    async def ok(self, stream, tick):\n"
        "        req, reply = await stream.pop()\n"
        "        while reply.pending():\n"
        "            await tick()\n"
        "        reply.send(req)\n"
    )
    findings = lint_source(src, "server/x.py")
    rpy = [f for f in findings if f.rule == "RPY001"]
    assert [f.line for f in rpy] == [3]


def test_wait001_tuple_assignment_capture_is_tracked():
    # `snap, other = self.d, 1` is the two-line capture in one statement
    # — element-wise binding must track it.  Starred/mismatched unpacks
    # kill conservatively (no flag).
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d['k'] = 1\n"
        "    async def bad(self, loop):\n"
        "        snap, other = self.d, 1\n"
        "        await loop.delay(1)\n"
        "        return snap['k'], other\n"
        "    async def unpack_ok(self, loop):\n"
        "        a, b = self.d\n"
        "        await loop.delay(1)\n"
        "        return a\n"
    )
    findings = lint_source(src, "server/x.py")
    wait = [f for f in findings if f.rule == "WAIT001"]
    assert [f.line for f in wait] == [7]


def test_wait002_alias_of_shared_state_is_still_live():
    # One local rebinding must not hide the invalidated-iterator class
    # (the exact cluster_controller._watch_roles shape, via an alias).
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d.update({})\n"
        "    async def bad(self, loop):\n"
        "        snap = self.d\n"
        "        for k in snap:\n"
        "            await loop.delay(1)\n"
        "    async def ok(self, loop):\n"
        "        snap = list(self.d)\n"
        "        for k in snap:\n"
        "            await loop.delay(1)\n"
    )
    findings = lint_source(src, "server/x.py")
    w2 = [f for f in findings if f.rule == "WAIT002"]
    assert [f.line for f in w2] == [6]


def test_wait_rules_reach_nested_and_factory_local_classes():
    # A role class built inside a factory, and a class nested in another
    # class, are each their OWN shared-state scope — both were invisible
    # to a top-level-only walk.
    src = (
        "def make():\n"
        "    class R:\n"
        "        def mut(self):\n"
        "            self.d['k'] = 1\n"
        "        async def bad(self, loop):\n"
        "            snap = self.d\n"
        "            await loop.delay(1)\n"
        "            return snap['k']\n"
        "    return R\n"
        "class Outer:\n"
        "    class Inner:\n"
        "        def mut(self):\n"
        "            self.d['k'] = 1\n"
        "        async def bad(self, loop):\n"
        "            snap = self.d\n"
        "            await loop.delay(1)\n"
        "            return snap['k']\n"
    )
    findings = lint_source(src, "server/x.py")
    wait = [f for f in findings if f.rule == "WAIT001"]
    assert [f.line for f in wait] == [8, 17]


def test_wait001_while_test_reevaluates_after_body_await():
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d['k'] = 1\n"
        "    async def bad(self, loop):\n"
        "        snap = self.d\n"
        "        while snap['k']:\n"   # re-evaluated after the await
        "            await loop.delay(1)\n"
    )
    findings = lint_source(src, "server/x.py")
    assert any(f.rule == "WAIT001" and f.line == 6 for f in findings)


def test_rpy001_loop_else_acquisition():
    src = (
        "class H:\n"
        "    async def leak(self, stream, items):\n"
        "        for it in items:\n"
        "            use(it)\n"
        "        else:\n"
        "            req, reply = await stream.pop()\n"
        "            return None\n"                  # reply dropped
        "    async def ok(self, stream, items):\n"
        "        for it in items:\n"
        "            use(it)\n"
        "        else:\n"
        "            req, reply = await stream.pop()\n"
        "        reply.send(req)\n"                  # resolved after loop
    )
    findings = lint_source(src, "server/x.py")
    rpy = [f for f in findings if f.rule == "RPY001"]
    assert [f.line for f in rpy] == [6]


def test_env001_presence_checks_and_mutating_reads():
    src = (
        "import os\n"
        "if 'FDB_TPU_HISTORY' in os.environ:\n"
        "    pass\n"
        "os.environ.setdefault('FDB_TPU_X', '1')\n"
        "os.environ.pop('FDB_TPU_Y', None)\n"
    )
    findings = lint_source(src, "server/x.py")
    env = [f for f in findings if f.rule == "ENV001"]
    assert [f.line for f in env] == [2, 4, 5]


def test_wait001_zero_iteration_loop_does_not_clear_staleness():
    # The loop body may run zero times: its re-read must not clear the
    # pre-loop capture on the loop-skipped path.  `while True:` always
    # enters, so its body re-read genuinely covers every path.
    src = (
        "class R:\n"
        "    def mut(self):\n"
        "        self.d['k'] = 1\n"
        "    async def bad(self, loop, items):\n"
        "        snap = self.d\n"
        "        await loop.delay(1)\n"
        "        for it in items:\n"
        "            snap = self.d\n"
        "        return snap['k']\n"       # stale when items is empty
        "    async def ok(self, loop):\n"
        "        snap = self.d\n"
        "        await loop.delay(1)\n"
        "        while True:\n"
        "            snap = self.d\n"
        "            break\n"
        "        return snap['k']\n"       # always re-read
    )
    findings = lint_source(src, "server/x.py")
    wait = [f for f in findings if f.rule == "WAIT001"]
    assert [f.line for f in wait] == [9]


def test_rpy001_break_then_resolve_after_loop():
    # break carries the reply out of the loop: resolved after it = clean;
    # forgotten after it = the leak.
    src = (
        "class H:\n"
        "    async def ok(self, stream):\n"
        "        while True:\n"
        "            req, reply = await stream.pop()\n"
        "            if req is None:\n"
        "                break\n"
        "            reply.send(req)\n"
        "        reply.send_error('shutdown')\n"
        "    async def leak(self, stream):\n"
        "        while True:\n"
        "            req, reply = await stream.pop()\n"
        "            if req is None:\n"
        "                break\n"
        "            reply.send(req)\n"
    )
    findings = lint_source(src, "server/x.py")
    rpy = [f for f in findings if f.rule == "RPY001"]
    assert [f.line for f in rpy] == [11]


def test_changed_only_survives_missing_git(monkeypatch, tmp_path, capsys):
    # No git binary at all (raises OSError) must mean full scan, not a
    # traceback and not a silently-green gate.
    from foundationdb_tpu.tools.lint import cli as cli_mod

    def no_git(*a, **k):
        raise FileNotFoundError("git not installed")

    monkeypatch.setattr(cli_mod.subprocess, "run", no_git)
    pkg = tmp_path / "pkg" / "server"
    pkg.mkdir(parents=True)
    (pkg / "cfg.py").write_text(
        "import os\nA = os.environ.get('FDB_TPU_X')\n"
    )
    rc = main([str(tmp_path / "pkg"), "--format=json", "--no-cache",
               "--changed-only"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["path"] for f in out["findings"]} == {"server/cfg.py"}


def test_rpy001_while_one_is_infinite_too():
    # `while 1:` serves forever exactly like `while True:` — the
    # unreachable fall-through must not read as a leaked reply.
    src = (
        "class H:\n"
        "    async def serve(self, stream):\n"
        "        while 1:\n"
        "            req, reply = await stream.pop()\n"
        "            reply.send(req)\n"
    )
    assert "RPY001" not in rules_of(lint_source(src, "server/x.py"))


def test_det101_intramodule_chain_and_pragma_cut():
    src = (
        "import time\n"
        "def low():\n"
        "    return time.time()\n"        # DET001 flags the direct site
        "def mid():\n"
        "    return low()\n"              # DET101: clean-looking carrier
        "def top():\n"
        "    return mid()\n"              # DET101: two frames above
    )
    findings = lint_source(src, "server/x.py")
    det101 = [f for f in findings if f.rule == "DET101"]
    assert [f.line for f in det101] == [5, 7]
    assert "DET001" in rules_of(findings)
    # Sanctioning the SOURCE clears the whole cascade (and the pragma is
    # consumed, not stale).
    src_ok = src.replace(
        "    return time.time()\n",
        "    return time.time()  # fdblint: ignore[DET001]: real-mode stamp\n",
    )
    clean = lint_source(src_ok, "server/x.py")
    assert rules_of(clean) == []


def test_det101_source_sanction_spans_multiline_statement():
    # The pragma sits on the statement's LAST line (the only place it can
    # on a multiline call): it must clear the DET001 finding AND the
    # upstream DET101 cascade with the same scope.
    src = (
        "import time\n"
        "def low():\n"
        "    return (\n"
        "        time.time()\n"
        "    )  # fdblint: ignore[DET001]: real-mode stamp\n"
        "def top():\n"
        "    return low()\n"
    )
    findings = lint_source(src, "server/x.py")
    assert rules_of(findings) == []
    assert "DET001" in rules_of(findings, suppressed=True)


def test_det101_pragma_on_clean_edge_goes_stale():
    # An edge-cutting pragma is only CONSUMED when the callee is actually
    # tainted: once the helper is fixed, the leftover pragma must age
    # into PRG002 instead of silently sanctioning forever.
    src = (
        "def helper(x):\n"
        "    return x + 1\n"
        "def top():\n"
        "    return helper(2)  # fdblint: ignore[DET101]: was tainted once\n"
    )
    findings = lint_source(src, "server/x.py")
    assert "PRG002" in rules_of(findings)
    assert "DET101" not in rules_of(findings)


def test_env001_variants_and_registry_exemption():
    src = (
        "import os\n"
        "def f():\n"
        "    a = os.environ.get('FDB_TPU_MODE')\n"
        "    b = os.getenv('FDB_TPU_X', '1')\n"
        "    c = os.environ['FDB_TPU_Y']\n"
        "    d = os.environ.get('HOME')\n"
        "    return a, b, c, d\n"
    )
    found = rules_of(lint_source(src, "server/x.py"))
    assert found.count("ENV001") == 3
    # The registry module itself is exempt.
    assert "ENV001" not in rules_of(lint_source(src, "flow/knobs.py"))


def test_env_flags_registry_reads_environ_at_call_time(monkeypatch):
    from foundationdb_tpu.flow.knobs import g_env

    monkeypatch.delenv("FDB_TPU_SEARCH_STRIDE", raising=False)
    assert g_env.get_int("FDB_TPU_SEARCH_STRIDE") == 512  # declared default
    monkeypatch.setenv("FDB_TPU_SEARCH_STRIDE", "64")
    assert g_env.get_int("FDB_TPU_SEARCH_STRIDE") == 64
    with pytest.raises(KeyError):
        g_env.get("FDB_TPU_NOT_DECLARED")
    # Declarations carry docs for status/README enumeration.
    assert all(h for _d, h in g_env.declared().values())


# ---------------------------------------------------------------------------
# Golden-file corpus: every case dir is a mini scan root; EXPECT markers
# pin the exact unsuppressed findings, asserted through the real CLI's
# --format=json output.
# ---------------------------------------------------------------------------


def _expected_markers(case_dir):
    expected = set()
    for dirpath, _dirs, files in os.walk(case_dir):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, case_dir).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f, 1):
                    if "# EXPECT:" in line:
                        for rule in line.split("# EXPECT:")[1].split(","):
                            expected.add((rel, i, rule.strip()))
    return expected


@pytest.mark.parametrize(
    "case", ["wait_rules", "rpy_cases", "det101_pkg", "env_cases",
             "spn_cases", "prm_cases", "race_cases", "hot_cases"]
)
def test_golden_corpus(case, capsys):
    case_dir = os.path.join(CASES_DIR, case)
    expected = _expected_markers(case_dir)
    assert expected, f"corpus case {case} has no EXPECT markers"
    rc = main([case_dir, "--format=json", "--no-cache"])
    out = json.loads(capsys.readouterr().out)
    got = {
        (f["path"], f["line"], f["rule"])
        for f in out["findings"]
        if not f["suppressed"]
    }
    assert got == expected, (
        f"{case}: findings != EXPECT markers\n"
        f"  unexpected: {sorted(got - expected)}\n"
        f"  missing:    {sorted(expected - got)}"
    )
    assert rc == 1  # every corpus case plants at least one violation


def test_det101_interprocedural_acceptance(capsys):
    """The acceptance criterion verbatim: a sim-reachable function calling
    a clean-looking helper that calls time.time() two levels down is
    flagged; the same source reachable only from real-mode backends is
    not flagged anywhere."""
    case_dir = os.path.join(CASES_DIR, "det101_pkg")
    main([case_dir, "--format=json", "--no-cache"])
    out = json.loads(capsys.readouterr().out)
    det = [f for f in out["findings"] if f["rule"] == "DET101"]
    # The sim role's call site is flagged with the full chain spelled out.
    sim = [f for f in det if f["path"] == "server/sim_role.py"]
    assert len(sim) == 1 and "time.time" in sim[0]["message"]
    assert "prep -> shape -> clock_stamp" in sim[0]["message"]
    # Method-resolution taint: the inherited helper taints Child.run.
    roles = [f for f in det if f["path"] == "server/roles.py"]
    assert {f["line"] for f in roles} == {9, 15}
    # Real-mode modules carry taint but are never flagged; wall_only is
    # reachable ONLY from real-mode code and appears nowhere.
    assert not [f for f in out["findings"] if f["path"].startswith("tools/")]
    assert not any("wall_only" in f["message"] for f in out["findings"])


def test_det101_pragma_on_bottom_edge_clears_cascade(tmp_path, capsys):
    """Compositional pragmas: sanctioning the ONE offending edge (the
    shape -> clock_stamp call) un-taints every frame above it."""
    src_dir = os.path.join(CASES_DIR, "det101_pkg")
    dst = tmp_path / "pkg"
    shutil.copytree(src_dir, dst)
    helpers = dst / "flow" / "helpers.py"
    text = helpers.read_text().replace(
        "    return clock_stamp(x)  # EXPECT: DET101",
        "    return clock_stamp(x)  # fdblint: ignore[DET101]: wall stamp is part of the exported record format, not control flow",
    )
    helpers.write_text(text)
    rc = main([str(dst), "--format=json", "--no-cache"])
    out = json.loads(capsys.readouterr().out)
    det = [f for f in out["findings"] if f["rule"] == "DET101"]
    assert det == [], det
    # The bottom-edge pragma cut a genuinely tainted edge: consumed.  The
    # UPSTREAM sanctioning pragma in sim_role.py now cuts a clean edge —
    # redundant, so it ages into PRG002 instead of lingering forever.
    prg = [f for f in out["findings"] if f["rule"] == "PRG002"]
    assert [(f["path"], f["line"]) for f in prg] == [("server/sim_role.py", 17)]


# ---------------------------------------------------------------------------
# Project cache: correctness under edits + the tier-1 warm-time budget
# ---------------------------------------------------------------------------


def test_cache_reuses_unchanged_files_and_sees_cross_file_edits(tmp_path):
    src_dir = os.path.join(CASES_DIR, "det101_pkg")
    work = tmp_path / "pkg"
    shutil.copytree(src_dir, work)
    cache = str(tmp_path / "lint.pkl")

    p1 = Project(str(work), cache_path=cache, use_cache=True)
    first = p1.lint()
    assert p1.stats["parsed"] == p1.stats["files"] > 0
    n_det = len([f for f in first if f.rule == "DET101" and not f.suppressed])
    assert n_det == 5

    # Warm: same findings, zero parses.
    p2 = Project(str(work), cache_path=cache, use_cache=True)
    second = p2.lint()
    assert p2.stats["parsed"] == 0
    assert p2.stats["cache_hits"] == p2.stats["files"]
    assert [f.format() for f in second] == [f.format() for f in first]

    # Cross-file correctness: fix the SOURCE file only — every cached
    # upstream file's DET101 findings must disappear (the interprocedural
    # pass runs on cached summaries, it is not per-file-cached).
    clockbox = work / "tools" / "clockbox.py"
    clockbox.write_text(
        "def clock_stamp(x):\n    return (x, 0.0)\n"
        "def wall_only():\n    return 0.0\n"
    )
    p3 = Project(str(work), cache_path=cache, use_cache=True)
    third = p3.lint()
    assert p3.stats["parsed"] == 1  # only the edited file re-analyzed
    assert not [f for f in third if f.rule == "DET101"]


def test_touched_but_unchanged_file_stays_cached(tmp_path):
    src_dir = os.path.join(CASES_DIR, "env_cases")
    work = tmp_path / "pkg"
    shutil.copytree(src_dir, work)
    cache = str(tmp_path / "lint.pkl")
    Project(str(work), cache_path=cache, use_cache=True).lint()
    # Touch without changing content: content-hash fallback must hit.
    target = work / "server" / "config.py"
    os.utime(target, ns=(1, 1))
    p = Project(str(work), cache_path=cache, use_cache=True)
    p.lint()
    assert p.stats["parsed"] == 0


def test_full_repo_warm_lint_under_5s(tmp_path):
    """The acceptance budget: full-repo lint <= 5s with a warm cache."""
    cache = str(tmp_path / "repo.pkl")
    Project(PKG_DIR, cache_path=cache, use_cache=True).lint()  # warm it
    t0 = time.perf_counter()
    p = Project(PKG_DIR, cache_path=cache, use_cache=True)
    findings = p.lint()
    wall = time.perf_counter() - t0
    assert p.stats["parsed"] == 0, "cache miss on an unchanged repo"
    assert not [f for f in findings if not f.suppressed]
    assert wall <= 5.0, f"warm full-repo lint took {wall:.2f}s (budget 5s)"
    print(f"\n[fdblint] warm full-repo lint: {wall:.2f}s "
          f"({p.stats['files']} files cached)", file=sys.__stderr__)


def test_per_rule_counts_surface(package_findings):
    counts = count_by_rule(package_findings)
    # The suppressed real-mode findings keep these families visible.
    assert counts["DET001"]["suppressed"] >= 1
    assert counts["WAIT001"]["suppressed"] >= 1
    text = format_counts(package_findings)
    assert "DET001=" in text and "WAIT001=" in text
    # The RACE family + ENV002 surface in the counts line EVEN AT ZERO:
    # a burned-down family that silently vanished from the output is how
    # it quietly regrows.
    for rule in ("RACE001", "RACE002", "RACE003", "RACE004", "ENV002",
                 "HOT001", "HOT002", "HOT003", "HOT004"):
        assert f"{rule}=" in text, text
    assert "RACE003=" in format_counts([])  # zero findings still shows it
    assert "HOT001=" in format_counts([])  # the HOT family too (ISSUE 20)


# ---------------------------------------------------------------------------
# CLI: SARIF output + --changed-only git mode
# ---------------------------------------------------------------------------


def test_sarif_output_shape(capsys):
    case_dir = os.path.join(CASES_DIR, "env_cases")
    rc = main([case_dir, "--format=sarif", "--no-cache", "--show-suppressed"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["version"] == "2.1.0"
    run = out["runs"][0]
    assert run["tool"]["driver"]["name"] == "fdblint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"ENV001", "DET101", "WAIT001", "RPY001"} <= rule_ids
    results = run["results"]
    flagged = [r for r in results if r["level"] == "error"]
    assert {r["ruleId"] for r in flagged} == {"ENV001"}
    loc = flagged[0]["locations"][0]["physicalLocation"]
    # URIs are CWD-relative (the repo root in CI), NOT scan-root-relative:
    # GitHub code scanning resolves them against the repository root, so a
    # 'server/config.py' uri from a subdirectory scan would never attach.
    expect = os.path.relpath(
        os.path.join(case_dir, "server", "config.py"), os.getcwd()
    ).replace(os.sep, "/")
    assert loc["artifactLocation"]["uri"] == expect
    assert loc["region"]["startLine"] >= 1
    # The pragma-suppressed read rides along as a justified suppression.
    sup = [r for r in results if r.get("suppressions")]
    assert sup and sup[0]["suppressions"][0]["justification"]


def test_changed_only_filters_to_git_diff(tmp_path, capsys):
    git = shutil.which("git")
    if git is None:
        pytest.skip("git unavailable")
    repo = tmp_path / "repo"
    pkg = repo / "pkg" / "server"
    pkg.mkdir(parents=True)

    def run_git(*args):
        return subprocess.run(
            [git, "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=repo, capture_output=True, text=True, check=True,
        )

    clean = pkg / "committed.py"
    clean.write_text("import os\nA = os.environ.get('FDB_TPU_OLD')\n")
    run_git("init", "-q")
    run_git("add", "-A")
    run_git("commit", "-qm", "seed")
    # A NEW (untracked) violating file: the only thing reported.
    dirty = pkg / "fresh.py"
    dirty.write_text("import os\nB = os.environ.get('FDB_TPU_NEW')\n")

    root = str(repo / "pkg")
    rc = main([root, "--format=json", "--no-cache", "--changed-only"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    paths = {f["path"] for f in out["findings"]}
    assert paths == {"server/fresh.py"}
    # Without the flag, the committed violation reports too.
    main([root, "--format=json", "--no-cache"])
    out_full = json.loads(capsys.readouterr().out)
    assert {f["path"] for f in out_full["findings"]} == {
        "server/fresh.py", "server/committed.py"
    }


def test_changed_only_does_not_adopt_same_named_deeper_files(tmp_path, capsys):
    git = shutil.which("git")
    if git is None:
        pytest.skip("git unavailable")
    repo = tmp_path / "repo"
    pkg = repo / "pkg"
    (pkg / "server").mkdir(parents=True)
    # UNCHANGED deeper file with a violation; its path is a suffix of the
    # changed clean top-level file's path — it must NOT be reported.
    (pkg / "server" / "config.py").write_text(
        "import os\nA = os.environ.get('FDB_TPU_DEEP')\n"
    )
    subprocess.run([git, "init", "-q"], cwd=repo, check=True)
    subprocess.run([git, "add", "-A"], cwd=repo, check=True)
    subprocess.run(
        [git, "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-qm", "seed"], cwd=repo, check=True,
    )
    (pkg / "config.py").write_text("X = 1\n")  # changed, clean
    rc = main([str(pkg), "--format=json", "--no-cache", "--changed-only"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["findings"] == []


def test_changed_only_outside_git_falls_back_to_full_scan(tmp_path, capsys):
    # A scan root that is NOT a git checkout (exported tarball, bare
    # worktree in CI) must fall back to the full scan — silently dropping
    # every finding would turn the gate permanently green.
    pkg = tmp_path / "pkg" / "server"
    pkg.mkdir(parents=True)
    (pkg / "cfg.py").write_text(
        "import os\nA = os.environ.get('FDB_TPU_X')\n"
    )
    rc = main([str(tmp_path / "pkg"), "--format=json", "--no-cache",
               "--changed-only"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["path"] for f in out["findings"]} == {"server/cfg.py"}


def test_new_rules_registered_and_documented():
    for rule in ("WAIT001", "WAIT002", "DET101", "RPY001", "ENV001",
                 "RACE001", "RACE002", "RACE003", "RACE004", "ENV002",
                 "HOT001", "HOT002", "HOT003", "HOT004"):
        assert rule in RULES and RULES[rule]


# ---------------------------------------------------------------------------
# Unified runner (python -m foundationdb_tpu.tools.lint): one warm cache,
# merged SARIF, per-tool counts, pragma inventory (ISSUE 20 satellites)
# ---------------------------------------------------------------------------


def test_unified_runner_module_entrypoint():
    proc = subprocess.run(
        [sys.executable, "-m", "foundationdb_tpu.tools.lint", PKG_DIR],
        capture_output=True,
        text=True,
        cwd=os.path.dirname(PKG_DIR),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # Per-tool count lines, HOT family visible even at zero.
    assert "[fdblint]" in proc.stderr and "[perfcheck]" in proc.stderr
    assert "HOT001=" in proc.stderr


def test_unified_runner_merged_sarif(capsys):
    rc = lint_runner.main(
        [PKG_DIR, "--format=sarif", "--show-suppressed"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    # ONE document, one run per tool — what CI uploads as one artifact.
    assert out["version"] == "2.1.0"
    names = [r["tool"]["driver"]["name"] for r in out["runs"]]
    assert names == ["fdblint", "perfcheck"]
    perf = out["runs"][1]
    rule_ids = {r["id"] for r in perf["tool"]["driver"]["rules"]}
    assert {"HOT001", "HOT002", "HOT003", "HOT004"} <= rule_ids
    # The repo's reasoned HOT pragmas ride along as justified suppressions.
    sup = [r for r in perf["results"] if r.get("suppressions")]
    assert sup and all(
        s["suppressions"][0]["justification"] for s in sup)


def test_unified_runner_json_per_tool_counts(capsys):
    rc = lint_runner.main([PKG_DIR, "--format=json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert set(out["tools"]) == {"fdblint", "perfcheck"}
    assert out["unsuppressed"] == 0
    # PR 19's staging-ring pragmas are perfcheck suppressions.
    assert out["tools"]["perfcheck"]["counts"]["HOT003"]["suppressed"] >= 1


def test_unified_runner_flags_planted_hot_violation(tmp_path, capsys):
    # The runner is a real gate: a planted HOT003 exits 1 and attributes
    # the finding to perfcheck, while fdblint stays clean.
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import numpy as np\n\n\n"
        "def hot_path(bound='batch'):\n"
        "    def deco(fn):\n"
        "        return fn\n"
        "    return deco\n\n\n"
        "@hot_path(bound='batch')\n"
        "def build(n):\n"
        "    return np.zeros(n, np.uint8)\n"
    )
    rc = lint_runner.main([str(pkg), "--format=json", "--no-cache"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["tools"]["fdblint"]["unsuppressed"] == 0
    perf = out["tools"]["perfcheck"]
    assert [f["rule"] for f in perf["findings"]] == ["HOT003"]


def test_pragma_inventory_canonical_and_reasoned(capsys):
    rc = lint_runner.main([PKG_DIR, "--pragma-inventory"])
    inv = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert inv, "the package genuinely uses pragmas"
    # Canonical: sorted by (file, line, tool), stable field set.
    key = lambda e: (e["file"], e["line"], e["tool"])
    assert inv == sorted(inv, key=key)
    assert all(set(e) == {"file", "line", "tool", "rules", "reason"}
               for e in inv)
    # All three namespaces appear, and the stale-pragma sweep holds:
    # every suppression in the repo carries a reason.
    assert {e["tool"] for e in inv} == {"fdblint", "jaxcheck", "perfcheck"}
    assert all(e["reason"].strip() for e in inv)
    # Determinism: a second run byte-identical.
    lint_runner.main([PKG_DIR, "--pragma-inventory"])
    again = json.loads(capsys.readouterr().out)
    assert again == inv
