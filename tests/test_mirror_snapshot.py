"""Batch-update snapshot mirror (ISSUE 9): differential + robustness gate.

The chunked CpuConflictSet replaced the flat array as the production CPU
mirror; the old engine survives as engine_cpu_flat.FlatCpuConflictSet and
is the ORACLE here: every verdict AND every exported (keys, vers) state
must be bit-identical across randomized interleavings of detect /
apply_batch / evict / clear / snapshot / rehydrate, across seeds.

Robustness half: probe rehydration is a snapshot handoff whose host work
is proportional to changes since the last device sync (asserted via the
rehydrate_keys_* op-count telemetry), a fault mid-rehydration leaves the
mirror untouched with a legal, byte-identically-replayable breaker log,
and the consistency checker catches a deliberately planted mirror/device
divergence within one check period and opens the breaker.

Shape discipline (1-core CI host): device engines use key_words=3 +
bucket_mins=(32, 128, 64) with h_cap in {1<<9, 1<<10, 1<<12} — the
static shapes test_conflict_jax/test_device_faults already compile.
"""

import json

import pytest

from foundationdb_tpu.conflict.api import ConflictSet
from foundationdb_tpu.conflict.device_faults import DeviceFaultInjector
from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet, MirrorSnapshot
from foundationdb_tpu.conflict.engine_cpu_flat import FlatCpuConflictSet
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.types import (
    COMMITTED,
    TransactionConflictInfo as T,
)
from foundationdb_tpu.flow import DeterministicRandom, set_event_loop
from foundationdb_tpu.flow.buggify import set_buggify_enabled
from foundationdb_tpu.flow.knobs import g_env


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_buggify_enabled(False)
    set_event_loop(None)


def k(i: int) -> bytes:
    return b"%08d" % i


def _random_batch(rng, keyspace, version, n_max, wide=False):
    txns = []
    span = max(1, keyspace // (4 if wide else 8))
    for _ in range(rng.random_int(1, n_max + 1)):
        tr = T(read_snapshot=max(0, version - rng.random_int(0, 30)))
        for _ in range(rng.random_int(0, 4)):
            a = rng.random_int(0, keyspace)
            tr.read_ranges.append((k(a), k(a + 1 + rng.random_int(0, span))))
        for _ in range(rng.random_int(0, 3)):
            a = rng.random_int(0, keyspace)
            tr.write_ranges.append((k(a), k(a + 1 + rng.random_int(0, span))))
        txns.append(tr)
    return txns


def _state(eng):
    return (list(eng.keys), list(eng.vers), eng.oldest_version)


# ---------------------------------------------------------------------------
# Differential gate: chunked vs flat oracle vs brute force
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed,chunk", [(1, 4), (2, 7), (3, 64), (4, 3),
                                        (5, 256)])
def test_differential_fuzz_interleavings(seed, chunk):
    """Randomized detect/apply_batch/evict/clear/snapshot interleavings:
    verdicts match the brute-force oracle, and verdicts AND exported
    state are bit-identical to the flat engine after EVERY step."""
    rng = DeterministicRandom(seed)
    new = CpuConflictSet(chunk=chunk)
    flat = FlatCpuConflictSet()
    orc = OracleConflictSet()
    version = 10
    snaps = []  # (snapshot, frozen flat state) immutability probes
    for step in range(70):
        keyspace = (8, 40, 300, 2000)[rng.random_int(0, 4)]
        txns = _random_batch(rng, keyspace, version, 16)
        now = version + rng.random_int(1, 10)
        nov = max(0, version - rng.random_int(0, 45))
        op = rng.random_int(0, 10)
        if op == 0:
            # clear at a random version (ref clearConflictSet)
            new.clear(now)
            flat.clear(now)
            orc.clear(now)
        elif op <= 2:
            # adopt externally-decided statuses (the device-mirror path):
            # decide on a THROWAWAY flat copy so the adoption is exact.
            dec = FlatCpuConflictSet()
            dec.keys, dec.vers = list(flat.keys), list(flat.vers)
            dec.oldest_version = flat.oldest_version
            statuses = dec.detect(txns, now, nov)
            new.apply_batch(txns, statuses, now, nov)
            flat.apply_batch(txns, statuses, now, nov)
            orc.detect(txns, now, nov)  # oracle re-decides identically
        else:
            got = new.detect(txns, now, nov)
            want = flat.detect(txns, now, nov)
            worc = orc.detect(txns, now, nov)
            assert got == want == worc, f"step {step}"
        assert _state(new) == _state(flat), f"step {step}: exported state"
        assert new.boundary_count == len(flat.keys)
        if rng.random01() < 0.3:
            s = new.snapshot()
            snaps.append((s, s.to_flat()))
        version = now
    # Every snapshot still reads exactly what it captured.
    assert snaps
    for s, frozen in snaps:
        assert s.to_flat() == frozen


def hk(i: int, region: int) -> bytes:
    """Hostile key shapes for the columnar fast paths (ISSUE 19).
    Region 0: plain short keys.  Region 1: every key shares one 8-byte
    prefix, so the uint64-prefix searchsorted collides on ALL of them
    and must refine over full encoded rows.  Region 2: 30-byte keys past
    the 16-byte digitization width (key_words=4) — chunks lose their ek
    column and every columnar path must route to the reference loops,
    then recover once the long keys evict away."""
    if region == 0:
        return b"%06d" % i
    if region == 1:
        return b"TIEPREFX" + b"%06d" % i
    return b"L" * 24 + b"%06d" % i


def _hostile_batch(rng, keyspace, version, n_max):
    txns = []
    span = max(1, keyspace // 8)
    for _ in range(rng.random_int(1, n_max + 1)):
        tr = T(read_snapshot=max(0, version - rng.random_int(0, 30)))
        for _ in range(rng.random_int(0, 4)):
            a = rng.random_int(0, keyspace)
            r = rng.random_int(0, 3)
            tr.read_ranges.append(
                (hk(a, r), hk(a + 1 + rng.random_int(0, span), r))
            )
        for _ in range(rng.random_int(0, 3)):
            a = rng.random_int(0, keyspace)
            r = rng.random_int(0, 3)
            if r == 2 and rng.random01() < 0.3:
                # Cross-region span: begins among the short keys, ends
                # among the long ones (b"%06d" < b"L"*24 bytewise).
                tr.write_ranges.append((hk(a, 0), hk(a, 2)))
            else:
                tr.write_ranges.append(
                    (hk(a, r), hk(a + 1 + rng.random_int(0, span), r))
                )
        txns.append(tr)
    return txns


@pytest.mark.parametrize("seed,chunk", [(11, 3), (12, 7), (13, 32),
                                        (14, 128), (15, 256)])
def test_differential_fuzz_hostile_keys(seed, chunk):
    """ISSUE 19: the columnar engine's hard key shapes — encoded-prefix
    ties (equal first 8 bytes force full-row tie refinement inside the
    vectorized bisects) and long keys past the digitization width (the
    ek fallback) — stay bit-identical to the flat engine and the
    brute-force oracle in verdicts, WITNESSES, and exported state."""
    rng = DeterministicRandom(seed)
    new = CpuConflictSet(chunk=chunk)
    flat = FlatCpuConflictSet()
    orc = OracleConflictSet()
    version = 10
    for step in range(50):
        keyspace = (8, 60, 900)[rng.random_int(0, 3)]
        txns = _hostile_batch(rng, keyspace, version, 10)
        now = version + rng.random_int(1, 8)
        nov = max(0, version - rng.random_int(0, 45))
        got = new.detect(txns, now, nov)
        want = flat.detect(txns, now, nov)
        worc = orc.detect(txns, now, nov)
        assert got == want == worc, f"step {step}"
        assert new.last_witness == flat.last_witness, f"step {step}"
        assert _state(new) == _state(flat), f"step {step}: exported state"
        version = now


def test_apply_batch_matches_detect_merge():
    """apply_batch(statuses from detect) leaves the same state detect
    itself would have — on both engines, compared directly."""
    rng = DeterministicRandom(99)
    a = CpuConflictSet(chunk=5)
    b = CpuConflictSet(chunk=5)
    flat = FlatCpuConflictSet()
    version = 10
    for _ in range(30):
        txns = _random_batch(rng, 60, version, 10)
        now = version + rng.random_int(1, 8)
        nov = max(0, version - 30)
        statuses = flat.detect(txns, now, nov)
        got = a.detect(txns, now, nov)
        assert got == statuses
        b.apply_batch(txns, statuses, now, nov)
        assert _state(a) == _state(b) == _state(flat)
        version = now


def test_snapshot_is_o1_and_immutable():
    cs = CpuConflictSet(chunk=4)
    cs.detect(
        [T(read_snapshot=0, write_ranges=[(k(2 * i), k(2 * i + 1))])
         for i in range(20)],
        10, 0,
    )
    s1 = cs.snapshot()
    # O(1): the snapshot aliases the live immutable chunk tuple.
    assert s1.chunks is cs._chunks
    assert s1.boundary_count == cs.boundary_count
    frozen = s1.to_flat()
    cs.detect([T(read_snapshot=10, write_ranges=[(k(3), k(30))])], 20, 0)
    s2 = cs.snapshot()
    assert s2.stamp > s1.stamp
    assert s1.to_flat() == frozen, "snapshot observed a later mutation"
    # A no-op batch (nothing committed, nothing evicted) keeps chunk
    # identity — snapshots are equal by stamp.
    s3 = cs.snapshot()
    cs.detect([], 21, 0)
    assert cs.snapshot().stamp == s3.stamp
    assert cs.snapshot().chunks is s3.chunks


def test_boundary_count_o1_and_evict_skips_rebuild():
    """ISSUE 9 satellite: O(1) boundary_count, and a window advance with
    nothing below the window does ZERO chunk rebuilds (the flat engine
    pays a full O(H) keep pass on every advance)."""
    cs = CpuConflictSet(chunk=4)
    flat = FlatCpuConflictSet()
    txns = [
        T(read_snapshot=0, write_ranges=[(k(2 * i), k(2 * i + 1))])
        for i in range(30)
    ]
    assert cs.detect(txns, 100, 0) == flat.detect(txns, 100, 0)
    assert cs.boundary_count == len(flat.keys) == cs._count
    chunks_before = cs._chunks
    rebuilt_before = cs.chunks_rebuilt
    skips_before = cs.evict_skips
    # Window advances to 50: every boundary is at version 100 — nothing
    # drops, no chunk is rebuilt, chunk identity is preserved.
    assert cs.detect([], 101, 50) == flat.detect([], 101, 50)
    assert cs.evict_skips == skips_before + 1
    assert cs.chunks_rebuilt == rebuilt_before
    assert cs._chunks is chunks_before
    assert _state(cs) == _state(flat)
    # Window passes 100: now boundaries drop, and only then do rebuilds
    # happen; state stays identical to the flat oracle.
    assert cs.detect([], 200, 150) == flat.detect([], 200, 150)
    assert cs.chunks_rebuilt > rebuilt_before
    assert _state(cs) == _state(flat)
    assert cs.boundary_count == len(flat.keys) == 1


def test_localized_batch_preserves_chunk_identity():
    """A batch touching one narrow key range rewrites only the chunks
    that cover it — the rest keep identity (the copy-on-write fact the
    device encode cache and snapshot diffing ride on)."""
    cs = CpuConflictSet(chunk=8)
    cs.detect(
        [T(read_snapshot=0, write_ranges=[(k(2 * i), k(2 * i + 1))])
         for i in range(100)],
        10, 0,
    )
    before = cs._chunks
    cs.detect([T(read_snapshot=10, write_ranges=[(k(100), k(101))])], 20, 0)
    after = cs._chunks
    shared = set(id(c) for c in before) & set(id(c) for c in after)
    assert len(shared) >= len(before) - 3, (
        "a localized write rewrote far-away chunks"
    )


def test_flat_adoption_via_properties_and_value_at():
    """The store_to/load_from flat contract: assigning .keys then .vers
    (engine_jax.store_to, the sharded rig) rebuilds the chunk structure;
    reads see flat lists; _value_at answers like the flat engine."""
    src = FlatCpuConflictSet()
    src.detect(
        [T(read_snapshot=0, write_ranges=[(k(i * 3), k(i * 3 + 2))])
         for i in range(40)],
        50, 0,
    )
    dst = CpuConflictSet(chunk=4)
    dst.keys = list(src.keys)
    dst.vers = list(src.vers)
    dst.oldest_version = src.oldest_version
    assert _state(dst) == _state(src)
    assert dst.boundary_count == len(src.keys)
    for probe in (b"", k(1), k(5), k(59), k(10_000)):
        assert dst._value_at(probe) == src._value_at(probe)
    assert dst._range_max(k(0), k(200)) == src._range_max(k(0), k(200))


def test_eviction_coalesces_shrunken_chunks():
    """Review regression: heavy eviction must not fragment the chunk
    sequence toward per-boundary chunks — survivors of a contiguous run
    of rewritten chunks re-chunk together (Jiffy node-merge), keeping
    per-chunk costs amortized over a long-running window."""
    cs = CpuConflictSet(chunk=4)
    flat = FlatCpuConflictSet()
    cold = [
        T(read_snapshot=0, write_ranges=[(k(10 * i), k(10 * i + 1))])
        for i in range(100)
    ]
    hot = [
        T(read_snapshot=100, write_ranges=[(k(250 + 500 * j), k(251 + 500 * j))])
        for j in range(4)
    ]
    for eng in (cs, flat):
        eng.detect(cold, 100, 0)
        eng.detect(hot, 200, 0)
    # Window passes 100: almost everything drops, survivors are sparse
    # hot islands scattered across one long rewritten run.
    assert cs.detect([], 300, 150) == flat.detect([], 300, 150)
    assert _state(cs) == _state(flat)
    n = cs.boundary_count
    assert n < 20  # eviction really was heavy
    # Coalesced: chunk count tracks ceil(n / chunk_size), not the number
    # of source chunks the survivors came from.
    assert cs.chunk_count <= (n + 3) // 4 + 2, (cs.chunk_count, n)


def test_flat_adoption_builds_chunks_once():
    """Review regression: a paired `keys = …; vers = …` adoption (the
    store_to contract) builds the chunk sequence ONCE — the keys half is
    staged, not rebuilt twice — so the fresh-hint backlog sees one chunk
    per final chunk, and a keys-only assignment is still visible to the
    next read (the staged flush)."""
    src = FlatCpuConflictSet()
    src.detect(
        [T(read_snapshot=0, write_ranges=[(k(3 * i), k(3 * i + 2))])
         for i in range(40)],
        50, 0,
    )
    dst = CpuConflictSet(chunk=8)
    dst.take_fresh_chunks()  # drain construction-time entries
    dst.keys = list(src.keys)
    dst.vers = list(src.vers)
    dst.oldest_version = src.oldest_version
    fresh, complete = dst.take_fresh_chunks()
    assert complete and len(fresh) == dst.chunk_count
    assert _state(dst) == _state(src)
    # Keys-only assignment: visible on next read, paired with old vers
    # (padded) — the flat engine's transiently-torn state.
    dst2 = CpuConflictSet(chunk=8)
    dst2.keys = [b"", b"a", b"b"]
    assert dst2.keys == [b"", b"a", b"b"]
    assert len(dst2.vers) == 3


def test_stamp_bumps_on_no_drop_window_advance():
    """Review regression: 'equal stamps mean identical state' — a window
    advance that drops nothing still changes state (oldest_version), so
    the stamp must move even though no chunk was rebuilt."""
    cs = CpuConflictSet(chunk=4)
    cs.detect([T(read_snapshot=0, write_ranges=[(k(0), k(5))])], 100, 0)
    s1 = cs.snapshot()
    cs.apply_batch([], [], 101, 50)  # nothing drops: all vers == 100
    s2 = cs.snapshot()
    assert s2.chunks is s1.chunks  # no rebuild…
    assert s2.stamp > s1.stamp  # …but the state (window) DID change
    assert s2.oldest_version == 50 and s1.oldest_version == 0


def test_take_fresh_chunks_hint():
    """The device's incremental-sync hint: take_fresh_chunks() returns
    exactly the chunks created since the last take (a superset of the
    live changed set — dead chunks allowed), resets on read, and
    degrades to complete=False past _FRESH_CAP so the consumer falls
    back to a full walk instead of trusting a truncated hint."""
    cs = CpuConflictSet(chunk=4)
    fresh, complete = cs.take_fresh_chunks()
    assert fresh == [] and complete
    cs.detect(
        [T(read_snapshot=0, write_ranges=[(k(2 * i), k(2 * i + 1))])
         for i in range(10)],
        10, 0,
    )
    fresh, complete = cs.take_fresh_chunks()
    assert complete
    assert {id(c) for c in cs._chunks} <= {id(c) for c in fresh}
    # A localized batch creates only a few chunks; untouched live chunks
    # must NOT reappear in the hint.
    cs.detect([T(read_snapshot=10, write_ranges=[(k(0), k(1))])], 20, 0)
    fresh2, complete = cs.take_fresh_chunks()
    assert complete and 1 <= len(fresh2) < len(cs._chunks)
    # Overflow: past the cap the hint reports incomplete ONCE, then
    # tracking resumes.
    cs._FRESH_CAP = 2
    cs.detect(
        [T(read_snapshot=20, write_ranges=[(k(2 * i), k(2 * i + 1))])
         for i in range(10)],
        30, 0,
    )
    fresh3, complete = cs.take_fresh_chunks()
    assert not complete and fresh3 == []
    cs.detect([T(read_snapshot=30, write_ranges=[(k(0), k(1))])], 40, 0)
    fresh4, complete = cs.take_fresh_chunks()
    assert complete and fresh4


def test_env_flags_registered():
    """ENV001 cleanliness: every FDB_TPU_MIRROR_* knob is declared in
    g_env (flow/knobs.py) with a default."""
    decl = g_env.declared()
    for flag in ("FDB_TPU_MIRROR_ENGINE", "FDB_TPU_MIRROR_CHUNK",
                 "FDB_TPU_MIRROR_CHECK_SECONDS", "FDB_TPU_MIRROR_COALESCE",
                 "FDB_TPU_ENCODE_STAGING"):
        assert flag in decl, flag
    assert g_env.get_int("FDB_TPU_MIRROR_CHUNK") >= 4
    assert float(g_env.get("FDB_TPU_MIRROR_CHECK_SECONDS")) >= 0


# ---------------------------------------------------------------------------
# Coalesced mirror apply (ISSUE 19)
# ---------------------------------------------------------------------------


def test_coalesced_apply_exact_at_every_barrier():
    """FDB_TPU_MIRROR_COALESCE semantics: queued folds are INVISIBLE.
    Every kind of mirror read (detect, snapshot, keys/vers export,
    value_at, oldest_version) is a flush barrier, so a coalescing
    engine is bit-identical to a per-batch engine at every observation
    point — while pending_batches proves folding actually happened."""
    rng = DeterministicRandom(77)
    co = CpuConflictSet(chunk=6)
    co.coalesce_window = 3
    plain = CpuConflictSet(chunk=6)
    flat = FlatCpuConflictSet()
    version = 10
    queued_seen = 0
    for step in range(60):
        txns = _random_batch(rng, 80, version, 8)
        now = version + rng.random_int(1, 8)
        nov = max(0, version - 35)
        statuses = flat.detect(txns, now, nov)
        plain.apply_batch(txns, statuses, now, nov)
        co.apply_batch(txns, statuses, now, nov)
        queued_seen = max(queued_seen, co.pending_batches)
        # oldest_version is passive-exact: reading it does NOT settle.
        assert co.oldest_version == plain.oldest_version
        barrier = rng.random_int(0, 5)
        if barrier == 0:
            assert co.snapshot().to_flat() == plain.snapshot().to_flat()
        elif barrier == 1:
            assert _state(co) == _state(plain) == _state(flat), f"step {step}"
        elif barrier == 2:
            probe = k(rng.random_int(0, 80))
            assert co._value_at(probe) == flat._value_at(probe)
            if probe in flat.keys:
                assert co.boundary_locate(probe) == flat.keys.index(probe)
        elif barrier == 3:
            d = _random_batch(rng, 80, now, 4)
            assert co.detect(d, now + 1, nov) == flat.detect(d, now + 1, nov)
            plain.detect(d, now + 1, nov)  # keep the engines in lockstep
            now += 1
        # barrier == 4: no read at all — folds survive to the next batch.
        version = now
    assert queued_seen >= 2, "coalescing never actually queued a batch"
    assert _state(co) == _state(plain) == _state(flat)


@pytest.mark.parametrize("seed", [5, 21])
def test_fault_mid_coalesce_replay_byte_identical(seed):
    """Scripted dispatch faults drain the pipeline while the mirror
    holds queued coalesced folds: verdicts and exported mirror state
    must match the coalesce-off run exactly, and two same-seed
    coalesce-on runs must produce byte-identical breaker transition
    logs (the ISSUE-19 replay gate)."""
    import os

    def stream():
        rng = DeterministicRandom(seed)
        version = 10
        out = []
        for _ in range(14):
            txns = _random_batch(rng, 60, version, 8)
            version += rng.random_int(1, 10)
            out.append((txns, version, max(0, version - 40)))
        return out

    def run(coalesce):
        env = {"FDB_TPU_MIRROR_COALESCE": coalesce,
               "FDB_TPU_PIPELINE_DEPTH": "2"}
        old = {kk: os.environ.get(kk) for kk in env}
        os.environ.update(env)
        try:
            inj = DeviceFaultInjector()
            for at in (2, 3, 4, 6):
                inj.script("dispatch", at=at)
            cs = _device_set(fault_injector=inj)
            verdicts = _drive(cs, stream())
            log = json.dumps(cs.device_metrics()["breaker"]["transitions"])
            return verdicts, _state(cs._cpu), log
        finally:
            for kk, vv in old.items():
                if vv is None:
                    os.environ.pop(kk, None)
                else:
                    os.environ[kk] = vv

    v_off, s_off, _log = run("0")
    v_on, s_on, log_on = run("auto")
    v_on2, s_on2, log_on2 = run("auto")
    assert v_on == v_off, "coalescing changed a verdict"
    assert s_on == s_off, "coalescing changed exported mirror state"
    assert (v_on2, s_on2) == (v_on, s_on)
    assert log_on == log_on2, "same-seed replay must be byte-identical"


# ---------------------------------------------------------------------------
# Device integration: snapshot rehydration + fault mid-probe
# ---------------------------------------------------------------------------


def _device_set(**kw):
    kw.setdefault("backend", "jax")
    kw.setdefault("key_words", 3)
    kw.setdefault("bucket_mins", (32, 128, 64))
    kw.setdefault("h_cap", 1 << 10)
    return ConflictSet(**kw)


def _drive(cs, stream):
    out = []
    for txns, now, nov in stream:
        b = cs.new_batch()
        for t in txns:
            b.add_transaction(t)
        out.append(b.detect_conflicts(now, nov))
    return out


def _disjoint_writes_batch(base, n=8, per=4):
    """n txns with `per` disjoint non-adjacent single-key writes each."""
    return [
        T(
            read_snapshot=0,
            write_ranges=[
                (k(base + 100 * t + 2 * j), k(base + 100 * t + 2 * j + 1))
                for j in range(per)
            ],
        )
        for t in range(n)
    ]


def test_rehydration_work_proportional_to_changes():
    """Acceptance: half-open-probe rehydration does host work
    proportional to changes since the last device sync — asserted via
    the rehydrate_keys_encoded / rehydrate_keys_total op counters, with
    the healthy path keeping the chunk encode cache warm
    (note_synced)."""
    inj = DeviceFaultInjector()
    cs = _device_set(h_cap=1 << 12, fault_injector=inj)
    v = 0
    # Build a sizable device-synced history (window pinned: no eviction).
    for i in range(12):
        v += 5
        b = cs.new_batch()
        for t in _disjoint_writes_batch(10_000 * i, n=8, per=8):
            b.add_transaction(t)
        b.detect_conflicts(v, 0)
    m = cs._jax.metrics
    total_before = m.counter("rehydrate_keys_total").value
    enc_before = m.counter("rehydrate_keys_encoded").value
    boundaries = cs._cpu.boundary_count
    assert boundaries > 700  # the history is genuinely large
    # Device outage: the mirror alone absorbs THREE small batches.
    inj.begin_outage("dispatch")
    for i in range(3):
        v += 5
        b = cs.new_batch()
        b.add_transaction(
            T(read_snapshot=v - 1, write_ranges=[(k(i * 2), k(i * 2 + 1))])
        )
        b.detect_conflicts(v, 0)
    assert cs.backend_signal()["backend_state"] == "degraded"
    inj.end_outage("dispatch")
    # Walk the breaker to a successful probe (device-eligible batches
    # advance the backoff clock).
    for i in range(12):
        v += 5
        b = cs.new_batch()
        b.add_transaction(
            T(read_snapshot=v - 1,
              write_ranges=[(k(900 + 2 * i), k(900 + 2 * i + 1))])
        )
        b.detect_conflicts(v, 0)
        if cs.backend_signal()["backend_state"] == "ok":
            break
    assert cs.backend_signal()["backend_state"] == "ok"
    total = m.counter("rehydrate_keys_total").value - total_before
    encoded = m.counter("rehydrate_keys_encoded").value - enc_before
    assert total >= boundaries, "the probe rehydrated the full history"
    # The op-count evidence, columnar form (ISSUE 19): the mirror's ek
    # column IS the device encoding (same key_words), so rehydration
    # re-encodes NOTHING — not merely "proportional to changes" but
    # exactly zero, even for chunks created during the outage.
    assert encoded == 0, (total, encoded)
    # Verdict sanity: the whole run matches a flat-engine replay… the
    # differential suites cover this broadly; here just one probe read.
    b = cs.new_batch()
    b.add_transaction(T(read_snapshot=0, read_ranges=[(k(0), k(1))]))
    assert b.detect_conflicts(v + 5, 0) != [COMMITTED]  # conflicts: written above


def test_fault_mid_rehydration_leaves_mirror_untouched():
    """Acceptance: a fault injected mid-snapshot-rehydration (the probe's
    load_from needs a grow, which faults) leaves the mirror bit-identical
    (immutable snapshot handoff), re-opens the breaker with a legal
    transition log, and a same-seed replay is byte-identical."""

    def run():
        inj = DeviceFaultInjector()
        cs = _device_set(h_cap=1 << 9)
        cs.install_fault_injector(inj)
        v = 0
        # Hold BOTH dispatch and grow down and fill the mirror well past
        # the device's h_cap: every half-open probe in this window runs
        # load_from against a mirror that no longer fits, so the probe
        # faults INSIDE the snapshot rehydration (at the grow choke
        # point) — the mid-rehydration fault under test.
        inj.begin_outage("dispatch")
        inj.begin_outage("grow")
        for i in range(10):
            v += 5
            b = cs.new_batch()
            for t in _disjoint_writes_batch(10_000 * i, n=8, per=8):
                b.add_transaction(t)
            b.detect_conflicts(v, 0)
        assert cs.backend_signal()["backend_state"] == "degraded"
        inj.end_outage("dispatch")  # only the grow site stays down

        def grow_faults():
            return sum(1 for _s, site, _k in inj.injected if site == "grow")

        base_grow = grow_faults()
        pre_probe = None
        probed = False
        # The dispatch outage doubled the backoff several times; give the
        # clock room to walk to the next probe.
        for i in range(40):
            v += 5
            snap_before = cs._cpu.snapshot()
            frozen = snap_before.to_flat()
            b = cs.new_batch()
            txn = T(read_snapshot=v - 1,
                    write_ranges=[(k(999_000 + 2 * i), k(999_000 + 2 * i + 1))])
            b.add_transaction(txn)
            b.detect_conflicts(v, 0)
            if grow_faults() > base_grow:
                probed = True
                pre_probe = (snap_before, frozen)
                break
        assert probed, "no probe attempted a grow — capacity math drifted"
        # The mirror absorbed THIS batch (served host-side after the
        # faulted probe) but the rehydration itself touched nothing: the
        # pre-batch snapshot still reads exactly its captured state.
        snap_obj, frozen = pre_probe
        assert isinstance(snap_obj, MirrorSnapshot)
        s_now = cs._cpu.snapshot()
        assert s_now.stamp > snap_obj.stamp  # the batch landed in the mirror…
        # …but the snapshot handed to the faulted probe still reads
        # exactly what it captured — the rehydration touched nothing.
        assert snap_obj.to_flat() == frozen
        dm = cs.device_metrics()
        # Legal walk: opened by the dispatch outage, probe faulted on
        # grow -> back to degraded.
        pairs = [(f, t) for _s, f, t, _r in dm["breaker"]["transitions"]]
        assert pairs[:3] == [
            ("ok", "degraded"),
            ("degraded", "probing"),
            ("probing", "degraded"),
        ], dm["breaker"]["transitions"]
        assert any(
            r.startswith("probe_failed:DeviceOOM:grow")
            for _s, _f, t, r in dm["breaker"]["transitions"]
            if t == "degraded"
        )
        # Recovery after the grow outage lifts: state converges again.
        inj.end_outage("grow")
        for i in range(40):
            v += 5
            b = cs.new_batch()
            b.add_transaction(
                T(read_snapshot=v - 1,
                  write_ranges=[(k(888_000 + 2 * i), k(888_000 + 2 * i + 1))])
            )
            b.detect_conflicts(v, 0)
            if cs.backend_signal()["backend_state"] == "ok":
                break
        assert cs.backend_signal()["backend_state"] == "ok"
        assert cs._jax.boundary_count == cs._cpu.boundary_count
        return json.dumps(dm["breaker"]), [list(e) for e in inj.injected]

    log1, inj1 = run()
    log2, inj2 = run()
    assert log1 == log2, "same-seed replay must be byte-identical"
    assert inj1 == inj2 and inj1


# ---------------------------------------------------------------------------
# Consistency checker
# ---------------------------------------------------------------------------


def test_mirror_check_unit_detects_planted_divergence():
    """Plant a divergence directly in device state: mirror_check reports
    it, counts it, opens the breaker with reason mirror_divergence, and
    marks the device stale; replays are byte-identical."""

    def run():
        cs = _device_set()
        v = 0
        for i in range(4):
            v += 5
            b = cs.new_batch()
            b.add_transaction(
                T(read_snapshot=v - 1,
                  write_ranges=[(k(10 * i), k(10 * i + 3))])
            )
            b.detect_conflicts(v, 0)
        rep = cs.mirror_check()
        assert rep["status"] == "ok" and rep["mismatch_keys"] == 0
        # Plant: bump a live device history version (a silent device-side
        # corruption the fixpoint check can never see).
        cs._jax._hvers = cs._jax._hvers.at[1].set(cs._jax._hvers[1] + 7)
        rep = cs.mirror_check()
        assert rep["status"] == "diverged" and rep["mismatch_keys"] >= 1
        dm = cs.device_metrics()
        assert dm["backend_state"] == "degraded"
        assert dm["counters"]["mirror_divergence"] == 1
        assert cs._device_stale  # recovery must rehydrate from snapshot
        assert [
            (f, t) for _s, f, t, _r in dm["breaker"]["transitions"]
        ] == [("ok", "degraded")]
        assert dm["breaker"]["transitions"][0][3].startswith(
            "mirror_divergence:"
        )
        # While degraded the checker skips (nothing to confirm) — O(1).
        assert cs.mirror_check()["status"] == "skipped"
        # Recovery: backoff elapses, the probe rehydrates from the
        # authoritative mirror, and the next check is clean again.
        for i in range(10):
            v += 5
            b = cs.new_batch()
            b.add_transaction(
                T(read_snapshot=v - 1,
                  write_ranges=[(k(500 + 2 * i), k(500 + 2 * i + 1))])
            )
            b.detect_conflicts(v, 0)
            if cs.backend_signal()["backend_state"] == "ok":
                break
        assert cs.backend_signal()["backend_state"] == "ok"
        assert cs.mirror_check()["status"] == "ok"
        return json.dumps(cs.device_metrics()["breaker"])

    assert run() == run(), "same-seed replay must be byte-identical"


def test_mirror_check_skips_for_host_only_and_flat_mirror_works(monkeypatch):
    assert ConflictSet(backend="cpu").mirror_check() is None
    # FDB_TPU_MIRROR_ENGINE=flat: the legacy flat mirror still supports
    # the whole robustness surface (legacy O(H) rehydrate, flat-view
    # consistency check) and decides identically.
    monkeypatch.setenv("FDB_TPU_MIRROR_ENGINE", "flat")
    cs = _device_set()
    assert isinstance(cs._cpu, FlatCpuConflictSet)
    v = 0
    for i in range(3):
        v += 5
        b = cs.new_batch()
        b.add_transaction(
            T(read_snapshot=v - 1, write_ranges=[(k(2 * i), k(2 * i + 1))])
        )
        b.detect_conflicts(v, 0)
    rep = cs.mirror_check()
    assert rep["status"] == "ok" and rep["stamp"] is None


@pytest.mark.parametrize("seed", [3, 9, 17])
def test_faulted_runs_identical_across_mirror_engines(seed):
    """ConflictSet-level differential: the SAME seeded faulty stream run
    with the chunked mirror and with the flat mirror produces identical
    verdicts and identical exported mirror state (the A/B arm's
    decision-identity guarantee), through breaker opens and probe
    recoveries."""

    def stream():
        rng = DeterministicRandom(seed)
        version = 10
        out = []
        for _ in range(14):
            txns = _random_batch(rng, 60, version, 8)
            version += rng.random_int(1, 10)
            out.append((txns, version, max(0, version - 40)))
        return out

    def run(engine):
        import os

        old = os.environ.get("FDB_TPU_MIRROR_ENGINE")
        os.environ["FDB_TPU_MIRROR_ENGINE"] = engine
        try:
            inj = DeviceFaultInjector()
            for at in (2, 3, 4, 6):
                inj.script("dispatch", at=at)
            cs = _device_set(fault_injector=inj)
            verdicts = _drive(cs, stream())
            return verdicts, _state(cs._cpu)
        finally:
            if old is None:
                os.environ.pop("FDB_TPU_MIRROR_ENGINE", None)
            else:
                os.environ["FDB_TPU_MIRROR_ENGINE"] = old

    v_chunked, s_chunked = run("")
    v_flat, s_flat = run("flat")
    assert v_chunked == v_flat
    assert s_chunked == s_flat


# ---------------------------------------------------------------------------
# Cluster integration: the periodic actor, status and the CLI
# ---------------------------------------------------------------------------


def _plant_and_catch(seed):
    """SimCluster run: commit traffic, plant a device-side divergence,
    and wait for the PERIODIC checker to catch it.  Returns (virtual
    seconds until caught, breaker json, qos doc, cli outputs)."""
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.tools.cli import CliProcessor

    period = float(g_env.get("FDB_TPU_MIRROR_CHECK_SECONDS"))
    c = SimCluster(seed=seed, conflict_backend="jax")
    db = c.database()
    cs = c.resolver.conflicts

    async def scenario():
        for i in range(5):
            tr = db.create_transaction()
            tr.set(b"mc/%02d" % i, b"v")
            await tr.commit()

    c.run_until(db.process.spawn(scenario(), "scenario"), timeout_vt=5000.0)
    # Plant: corrupt the device's floor-row version.
    cs._jax._hvers = cs._jax._hvers.at[0].set(12345)
    t0 = c.loop.now()

    async def wait_caught():
        while cs._breaker.state == "ok":
            await c.loop.delay(0.25)
        return c.loop.now() - t0

    caught_after = c.run_until(
        db.process.spawn(wait_caught(), "wait"), timeout_vt=5000.0
    )
    assert caught_after <= period + 1.0, (
        f"divergence caught after {caught_after}s > one {period}s period"
    )
    dm = cs.device_metrics()
    assert dm["counters"]["mirror_divergence"] == 1
    assert any(
        r.startswith("mirror_divergence:")
        for _s, _f, _t, r in dm["breaker"]["transitions"]
    )
    cli = CliProcessor(c, db)

    async def run_cli():
        return (
            await cli.run_command("mirror-check"),
            await cli.run_command("mirror-check --format=json"),
            await cli.run_command("status --format=json"),
        )

    text, js, status = c.run_until(
        db.process.spawn(run_cli(), "cli"), timeout_vt=600.0
    )
    return (
        json.dumps(dm["breaker"]),
        text,
        json.loads("\n".join(js)),
        json.loads("\n".join(status)),
    )


def test_cluster_checker_catches_divergence_within_one_period():
    """Acceptance: the consistency checker detects a deliberately planted
    mirror/device divergence within one check period, opens the breaker,
    and the whole journey is replayable byte-identically; the operator
    surface (cli mirror-check text+json, status --format=json tpu
    section) reports it."""
    log1, text, js, status = _plant_and_catch(4242)
    log2, _t2, _j2, _s2 = _plant_and_catch(4242)
    assert log1 == log2, "same-seed replay must be byte-identical"
    # CLI: after the divergence the device is degraded+stale, so the
    # on-demand check reports the skip (the PERIODIC check caught the
    # divergence; its report is in the tpu section's mirror block).
    assert any("skipped" in ln for ln in text)
    assert js and all("status" in rep for rep in js.values())
    tpu = status["cluster"]["resolver"]["tpu"]["resolver"]
    assert tpu["backend_state"] in ("degraded", "probing", "ok")
    assert tpu["counters"]["mirror_divergence"] == 1
    assert tpu["mirror"]["last_check"]["status"] in ("diverged", "skipped",
                                                     "ok")
    assert tpu["mirror"]["engine"] == "CpuConflictSet"


def test_cli_mirror_check_healthy_cluster():
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.tools.cli import CliProcessor

    c = SimCluster(seed=7, conflict_backend="jax")
    db = c.database()
    cli = CliProcessor(c, db)

    async def scenario():
        tr = db.create_transaction()
        tr.set(b"ok/1", b"v")
        await tr.commit()
        return (
            await cli.run_command("mirror-check"),
            await cli.run_command("mirror-check --format=json"),
        )

    text, js = c.run_until(
        db.process.spawn(scenario(), "cli"), timeout_vt=5000.0
    )
    assert len(text) == 1 and ("OK" in text[0] or "skipped" in text[0])
    doc = json.loads("\n".join(js))
    assert set(doc) == {"resolver"}
    assert doc["resolver"]["status"] in ("ok", "skipped")
