"""Cross-interpreter determinism: same seed => byte-identical history.

Ref: the reference's bit-reproducibility contract (DeterministicRandom.h:
every random decision rides g_random; simulation runs replay exactly from
the seed).  The subtle failure mode this guards: iterating a SET of
id-hashed objects (e.g. pending reply promises broken on process death)
gives allocation/PYTHONHASHSEED-dependent order — invisible within one
interpreter, diverging across runs.  So the check runs the same kill-heavy
simulation in SEPARATE interpreters with DIFFERENT hash seeds and demands
identical output.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCRIPT = r"""
import sys
sys.path.insert(0, %r)
import jax
jax.config.update("jax_platforms", "cpu")
from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

c = DynamicCluster(seed=77, n_workers=5, storage_engine="btree")
db = c.database()

async def fill(tr):
    for i in range(120):
        tr.set(b"d%%05d" %% i, b"v%%05d" %% i)

c.run_all([(db, db.run(fill))], timeout_vt=600.0)
c.crash_and_recover()
out = {}

async def check(tr):
    out["rows"] = await tr.get_range(b"d", b"e")

c.run_all([(db, db.run(check))], timeout_vt=900.0)
print("rows:", len(out["rows"]))
print("gen:", c.acting_controller().generation, "vt:", round(c.loop.now(), 9))
print("tasks:", c.loop.tasks_run, "rng:", round(c.loop.rng.random01(), 12))
""" % (REPO,)


def _run(hashseed: str) -> str:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = hashseed
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, "-c", SCRIPT],
        capture_output=True,
        text=True,
        timeout=180,
        env=env,
        cwd=REPO,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    return p.stdout


def test_kill_recovery_identical_across_hash_seeds():
    a = _run("1")
    b = _run("2")
    assert "rows: 120" in a
    assert a == b, f"nondeterminism across interpreters:\nA:\n{a}\nB:\n{b}"
