"""Cross-interpreter determinism: same seed => byte-identical history.

Ref: the reference's bit-reproducibility contract (DeterministicRandom.h:
every random decision rides g_random; simulation runs replay exactly from
the seed).  The subtle failure mode this guards: iterating a SET of
id-hashed objects (e.g. pending reply promises broken on process death)
gives allocation/PYTHONHASHSEED-dependent order — invisible within one
interpreter, diverging across runs.  So the check runs the same kill-heavy
simulation in SEPARATE interpreters with DIFFERENT hash seeds and demands
identical output.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCRIPT = r"""
import sys
sys.path.insert(0, %r)
import jax
jax.config.update("jax_platforms", "cpu")
from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

c = DynamicCluster(seed=77, n_workers=5, storage_engine="btree")
db = c.database()

async def fill(tr):
    for i in range(120):
        tr.set(b"d%%05d" %% i, b"v%%05d" %% i)

c.run_all([(db, db.run(fill))], timeout_vt=600.0)
c.crash_and_recover()
out = {}

async def check(tr):
    out["rows"] = await tr.get_range(b"d", b"e")

c.run_all([(db, db.run(check))], timeout_vt=900.0)
print("rows:", len(out["rows"]))
print("gen:", c.acting_controller().generation, "vt:", round(c.loop.now(), 9))
print("tasks:", c.loop.tasks_run, "rng:", round(c.loop.rng.random01(), 12))
""" % (REPO,)


def _run(hashseed: str) -> str:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = hashseed
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, "-c", SCRIPT],
        capture_output=True,
        text=True,
        timeout=180,
        env=env,
        cwd=REPO,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    return p.stdout


def test_kill_recovery_identical_across_hash_seeds():
    a = _run("1")
    b = _run("2")
    assert "rows: 120" in a
    assert a == b, f"nondeterminism across interpreters:\nA:\n{a}\nB:\n{b}"


# ---------------------------------------------------------------------------
# Telemetry byte-identity across interpreter hash seeds (racecheck PR): the
# spans log, the per-role metrics snapshots and a short soak report are the
# artifacts the replay gates diff — if any of them ever iterates an id-hashed
# container, the divergence shows up here first.
# ---------------------------------------------------------------------------

TELEMETRY_SCRIPT = r"""
import json
import sys
sys.path.insert(0, %r)
import jax
jax.config.update("jax_platforms", "cpu")
from foundationdb_tpu.flow.eventloop import all_of
from foundationdb_tpu.flow.spans import SpanHub, set_global_span_hub, global_span_hub
from foundationdb_tpu.server import SimCluster

set_global_span_hub(SpanHub())
c = SimCluster(seed=211, n_proxies=2)
db = c.database()

async def actor(aid):
    for r in range(3):
        async def op(tr, aid=aid, r=r):
            cur = await tr.get(b"shared")
            tr.set(b"shared", (cur or b"") + b"%%d" %% aid)
            tr.set(b"t%%02d/%%02d" %% (aid, r), b"v")
        await db.run(op)

async def drive():
    await all_of([db.process.spawn(actor(i), "wl_%%d" %% i) for i in range(4)])

c.run_all([(db, drive())], timeout_vt=3000.0)
now = c.loop.now()
print("spans:", global_span_hub().spans_json())
print("resolver:", c.resolver.metrics.snapshot_json(now=now))
print("proxy:", c.proxy.metrics.snapshot_json(now=now))

from foundationdb_tpu.flow import set_event_loop
set_event_loop(None)
from foundationdb_tpu.workloads.soak import SoakConfig, SoakPhase, run_soak

cfg = SoakConfig(
    seed=5, cluster="sim", backend="cpu", mode="open", keys=32,
    phases=[SoakPhase("warm", 0.8, 30.0)], faults=[], drain_timeout=5.0,
)
print("soak:", json.dumps(run_soak(cfg), sort_keys=True))
""" % (REPO,)


def _run_telemetry(hashseed: str) -> str:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = hashseed
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, "-c", TELEMETRY_SCRIPT],
        capture_output=True,
        text=True,
        timeout=180,
        env=env,
        cwd=REPO,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    return p.stdout


def test_telemetry_byte_identical_across_hash_seeds():
    a = _run_telemetry("1")
    b = _run_telemetry("2")
    assert "spans:" in a and "soak:" in a
    assert a == b, f"telemetry nondeterminism across interpreters:\nA:\n{a[:2000]}\nB:\n{b[:2000]}"
