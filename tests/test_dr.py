"""DR to a second cluster: continuous prefix-consistent replication.

Ref: fdbclient/DatabaseBackupAgent.actor.cpp — the destination cluster is
at every moment a consistent (older) snapshot of the source; the agent
tails the source's mutation stream, applying one source version per
destination transaction.
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.eventloop import EventLoop
from foundationdb_tpu.layers.dr import DRAgent
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def two_clusters(seed):
    """Source + destination on ONE deterministic loop (the extraDB pattern,
    ref: SimulatedCluster.actor.cpp:164)."""
    loop = EventLoop(seed=seed)
    a = SimCluster(seed=seed, loop=loop)
    b = SimCluster(seed=seed + 1, loop=loop)
    return loop, a, b


def read_all(cluster, db):
    out = {}

    async def txn(tr):
        out["rows"] = await tr.get_range(b"", b"\xff", limit=1 << 20)

    cluster.run_all([(db, db.run(txn))])
    return out["rows"]


def test_dr_snapshot_then_tail():
    loop, a, b = two_clusters(170)
    src, dst = a.database(), b.database()

    async def fill(tr):
        for i in range(30):
            tr.set(b"k%03d" % i, b"v%d" % i)

    a.run_all([(src, src.run(fill))])

    agent = DRAgent(src, dst, [t.interface() for t in a.tlogs])

    async def drive():
        await agent.start()
        # Writes AFTER the snapshot must flow through the tail.
        return True

    a.run_until(src.process.spawn(drive()), timeout_vt=5000.0)

    async def more(tr):
        tr.set(b"k%03d" % 99, b"late")
        tr.clear(b"k000")
        from foundationdb_tpu.client.types import MutationType

        tr.atomic_op(MutationType.ADD_VALUE, b"counter", (5).to_bytes(8, "little"))

    a.run_all([(src, src.run(more))])

    async def tail():
        for _ in range(200):
            await agent.tail_once()
            await loop.delay(0.01)

    a.run_until(src.process.spawn(tail()), timeout_vt=5000.0)

    rows_a = dict(read_all(a, src))
    rows_b = dict(read_all(b, dst))
    assert rows_b == rows_a
    assert rows_b[b"counter"] == (5).to_bytes(8, "little")
    assert b"k000" not in rows_b


def test_dr_destination_is_always_a_consistent_prefix():
    """Cycle workload churns the source while the agent tails; EVERY
    observation of the destination must be a valid ring (never a torn mix
    of source versions)."""
    loop, a, b = two_clusters(171)
    src, dst = a.database(), b.database()
    N = 6

    async def init(tr):
        for i in range(N):
            tr.set(b"c%02d" % i, b"%02d" % ((i + 1) % N))

    a.run_all([(src, src.run(init))])
    agent = DRAgent(src, dst, [t.interface() for t in a.tlogs])
    a.run_until(src.process.spawn(agent.start()), timeout_vt=5000.0)

    stop = []
    bad = []

    async def churn():
        rng = loop.rng
        for _ in range(60):

            async def op(tr):
                x = int(rng.random_int(0, N))
                kx = b"c%02d" % x
                y = int((await tr.get(kx)).decode())
                ky = b"c%02d" % y
                z = int((await tr.get(ky)).decode())
                kz = b"c%02d" % z
                w = int((await tr.get(kz)).decode())
                tr.set(kx, b"%02d" % z)
                tr.set(kz, b"%02d" % y)
                tr.set(ky, b"%02d" % w)

            await src.run(op)
        stop.append(True)

    async def tailer():
        while not stop:
            await agent.tail_once()
            await loop.delay(0.005)
        # Drain the remainder.
        for _ in range(50):
            await agent.tail_once()

    async def observer():
        while not stop:
            rows = {}

            async def rd(tr):
                rows.update(dict(await tr.get_range(b"c", b"d")))

            await dst.run(rd)
            if len(rows) == N:
                seen, cur = set(), 0
                ok = True
                for _ in range(N):
                    if cur in seen:
                        ok = False
                        break
                    seen.add(cur)
                    cur = int(rows[b"c%02d" % cur].decode())
                if not ok or cur != 0:
                    bad.append(dict(rows))
            await loop.delay(0.01)

    a.run_all(
        [(src, churn()), (src, tailer()), (dst, observer())],
        timeout_vt=8000.0,
    )
    assert not bad, f"destination showed a torn state: {bad[:2]}"
    # Fully drained: byte-identical.
    assert dict(read_all(a, src)) == dict(read_all(b, dst))


def test_dr_follows_sharded_source():
    """DD-sharded source: user mutations carry per-storage tags, which the
    agent must discover from the serverList — a default-tags-only peek
    would silently replicate nothing."""
    loop, a, b = two_clusters(172)
    a2 = SimCluster(seed=300, loop=loop, n_storages=2)
    src, dst = a2.database(), b.database()

    async def fill(tr):
        for i in range(40):
            tr.set(b"s%03d" % i, b"v%d" % i)

    a2.run_all([(src, src.run(fill))])
    dd = a2.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"s020")
        await dd.move(b"s020", ["ss1"])

    a2.run_until(src.process.spawn(place()), timeout_vt=5000.0)

    agent = DRAgent(src, dst, [t.interface() for t in a2.tlogs])
    a2.run_until(src.process.spawn(agent.start()), timeout_vt=5000.0)

    async def more(tr):
        tr.set(b"s005", b"updated")
        tr.set(b"s030", b"updated2")  # lands on the moved shard

    a2.run_all([(src, src.run(more))])

    async def tail():
        # NO manual tag refresh: the agent must discover the per-storage
        # tags from the serverList mutations IN the stream it tails.
        for _ in range(100):
            await agent.tail_once()
            await loop.delay(0.01)

    a2.run_until(src.process.spawn(tail()), timeout_vt=5000.0)
    rows_b = dict(read_all(b, dst))
    assert rows_b.get(b"s005") == b"updated"
    assert rows_b.get(b"s030") == b"updated2"
    assert sum(1 for k in rows_b if k.startswith(b"s0")) == 40


def test_dr_atomic_switchover():
    """fdbdr switch (DatabaseBackupAgent::atomicSwitchover): the roles
    reverse with no recopy — the old primary is left locked as the
    replica of the new one, new-primary writes flow back, nothing is
    lost, and plain writes to the old primary fail database_locked."""
    from foundationdb_tpu.flow.error import FdbError

    loop, a, b = two_clusters(175)
    src, dst = a.database(), b.database()

    async def fill(tr):
        for i in range(25):
            tr.set(b"sw%03d" % i, b"v%d" % i)

    a.run_all([(src, src.run(fill))])

    agent = DRAgent(src, dst, [t.interface() for t in a.tlogs])
    out = {}

    async def drive():
        await agent.start()
        # Tail a bit, then some fresh source writes that must drain
        # during the switch.
        tr = src.create_transaction()
        for i in range(25, 35):
            tr.set(b"sw%03d" % i, b"late%d" % i)
        await tr.commit()

        rev = await agent.switchover([t.interface() for t in b.tlogs])
        out["rev"] = rev

        # Old primary is locked: a plain write fails.
        tr2 = src.create_transaction()
        tr2.set(b"stray", b"x")
        try:
            await tr2.commit()
            out["stray"] = "accepted"
        except FdbError as e:
            out["stray"] = e.name

        # New-primary writes replicate BACK to the old primary.
        tr3 = dst.create_transaction()
        for i in range(3):
            tr3.set(b"post%02d" % i, b"p%d" % i)
        await tr3.commit()
        for _ in range(200):
            n = await out["rev"].tail_once()
            done = {}

            async def check(tr):
                tr.options["lock_aware"] = True
                done["v"] = await tr.get(b"post02")

            await src.run(check)
            if done["v"] == b"p2":
                break
            await loop.delay(0.05)
        out["replicated"] = done["v"]
        return True

    a.run_until(src.process.spawn(drive()), timeout_vt=30000.0)
    assert out["stray"] == "database_locked"
    assert out["replicated"] == b"p2"

    # Full-content equality through lock-aware reads: everything the old
    # primary ever committed + the new primary's writes, on BOTH sides.
    rows_new = dict(read_all(b, dst))
    got = {}

    async def scan_old(tr):
        tr.options["lock_aware"] = True
        got["rows"] = dict(await tr.get_range(b"", b"\xff", limit=1 << 20))

    a.run_all([(src, src.run(scan_old))])
    rows_old = got["rows"]
    user_new = {k: v for k, v in rows_new.items() if not k.startswith(b"\xff")}
    user_old = {k: v for k, v in rows_old.items() if not k.startswith(b"\xff")}
    assert user_new == user_old
    assert user_new[b"sw034"] == b"late34" and user_new[b"post00"] == b"p0"


def test_dr_switchover_unwinds_on_locked_destination():
    """A destination already locked by someone else aborts the switch;
    the unwind must leave the SOURCE unlocked and replication resumable."""
    from foundationdb_tpu.client.management import lock_database
    from foundationdb_tpu.flow.error import FdbError

    loop, a, b = two_clusters(176)
    src, dst = a.database(), b.database()

    async def fill(tr):
        tr.set(b"uw", b"1")

    a.run_all([(src, src.run(fill))])
    agent = DRAgent(src, dst, [t.interface() for t in a.tlogs])
    out = {}

    async def drive():
        await agent.start()
        await lock_database(dst, uid=b"someone-else")
        try:
            await agent.switchover([t.interface() for t in b.tlogs])
            out["switch"] = "succeeded"
        except FdbError as e:
            out["switch"] = e.name
        # Source must be WRITABLE again (unwound), and tailing resumable.
        tr = src.create_transaction()
        tr.set(b"post_unwind", b"yes")
        await tr.commit()
        for _ in range(100):
            await agent.tail_once()
            got = {}

            async def check(t):
                t.options["lock_aware"] = True
                got["v"] = await t.get(b"post_unwind")

            await dst.run(check)
            if got["v"] == b"yes":
                return True
            await loop.delay(0.05)
        return False

    assert a.run_until(src.process.spawn(drive()), timeout_vt=30000.0)
    assert out["switch"] == "database_locked"
    assert agent.stopped is False


def test_dr_abort_leaves_usable_consistent_destination():
    """fdbdr abort mid-stream (ref: workloads/BackupToDBAbort.actor.cpp):
    the destination must be left a CONSISTENT prefix of the source (a
    valid cycle ring, never a torn mix of versions), immediately usable
    for ordinary writes, and the source logs must stop retaining for the
    dead DR tag (its pop floor unregistered)."""
    loop, a, b = two_clusters(175)
    src, dst = a.database(), b.database()
    N = 6

    async def init(tr):
        for i in range(N):
            tr.set(b"c%02d" % i, b"%02d" % ((i + 1) % N))

    a.run_all([(src, src.run(init))])
    agent = DRAgent(src, dst, [t.interface() for t in a.tlogs])
    a.run_until(src.process.spawn(agent.start()), timeout_vt=5000.0)

    async def churn_and_abort():
        rng = loop.rng
        for n in range(40):
            # Keep the ring valid: rotate three pointers atomically.
            async def rotate(tr):
                vals = {}
                for i in range(N):
                    vals[i] = int((await tr.get(b"c%02d" % i)).decode())
                # swap successors of two nodes (stays a single ring only
                # for adjacent picks; use the 3-node rotation instead)
                x = int(rng.random_int(0, N))
                y = vals[x]
                z = vals[y]
                w = vals[z]
                tr.set(b"c%02d" % x, b"%02d" % z)
                tr.set(b"c%02d" % z, b"%02d" % y)
                tr.set(b"c%02d" % y, b"%02d" % w)

            await src.run(rotate)
            if n % 5 == 0:
                await agent.tail_once()
        await agent.abort()

    a.run_until(src.process.spawn(churn_and_abort()), timeout_vt=8000.0)

    # Destination: a valid ring (consistent prefix, not torn).
    rows = dict(read_all(b, dst))
    ring = {k: v for k, v in rows.items() if k.startswith(b"c")}
    assert len(ring) == N
    seen, cur = set(), 0
    for _ in range(N):
        assert cur not in seen, f"torn destination ring: {ring}"
        seen.add(cur)
        cur = int(ring[b"c%02d" % cur].decode())
    assert cur == 0

    # Source logs no longer hold a floor for the DR tag.
    from foundationdb_tpu.layers.dr import DR_TAG

    for t in a.tlogs:
        assert DR_TAG not in t.popped_tags

    # Destination is usable for ordinary writes after the abort.
    async def write(tr):
        tr.set(b"after_abort", b"yes")

    b.run_all([(dst, dst.run(write))])
    assert dict(read_all(b, dst))[b"after_abort"] == b"yes"
