"""Dynamic twins of the racecheck static pass (tools/lint/races.py).

Three gates ride here:

- the FDB_TPU_STATE_SANITIZER shared-state sanitizer must catch a PLANTED
  lost update within one run, and stay quiet on a clean full SimCluster
  commit workload (pipelined commits included) with real production dicts
  audited;
- the FDB_TPU_SCHED_FUZZ scheduler-perturbation mode must replay
  byte-identically for the same (seed, fuzz) and keep the differential
  commit gates green across >=3 fuzz seeds (each a different LEGAL
  interleaving);
- the structural fixes the static pass forced (resolver_balancer's
  validated repartition commit, the transaction GRV first-resolution-wins
  re-check, DiskQueue's header-dirty ordering) are regression-pinned.
"""

import pytest

from foundationdb_tpu.fileio import DiskQueue, SimFileSystem
from foundationdb_tpu.flow import EventLoop, set_event_loop
from foundationdb_tpu.flow.eventloop import all_of
from foundationdb_tpu.flow.state_sanitizer import (
    audited_dict,
    expect_clean_shared_state,
)
from foundationdb_tpu.rpc import SimNetwork
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server import system_keys as sk


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


# ---------------------------------------------------------------------------
# State sanitizer
# ---------------------------------------------------------------------------


def test_sanitizer_catches_planted_lost_update(monkeypatch):
    """Two actors read-modify-write the same key across an await: the
    classic lost update.  The sanitizer must report the stale-read→write
    pair in the same run (and the data really is wrong: 1, not 2)."""
    monkeypatch.setenv("FDB_TPU_STATE_SANITIZER", "1")
    loop = EventLoop(seed=7)
    set_event_loop(loop)
    shared = audited_dict(loop, "planted.counter", {"n": 0})

    async def bump():
        val = shared["n"]  # read ...
        await loop.delay(0.01)  # ... suspension: the other bump runs ...
        shared["n"] = val + 1  # ... write from the stale read

    done = all_of([loop.spawn(bump(), "bump_a"), loop.spawn(bump(), "bump_b")])
    loop.run_until(done, timeout_vt=10.0)
    assert shared["n"] == 1  # one increment was lost
    san = loop._state_sanitizer
    assert len(san.violations) == 1, san.violations
    assert "planted.counter['n']" in san.violations[0]
    assert "lost update" in san.violations[0]
    with pytest.raises(AssertionError, match="stale-read→write"):
        expect_clean_shared_state(loop, "planted")


def test_sanitizer_ignores_recheck_discipline(monkeypatch):
    """Read → await → RE-READ → write is the sanctioned shape (the
    re-check refreshes the reader's knowledge): no violation."""
    monkeypatch.setenv("FDB_TPU_STATE_SANITIZER", "1")
    loop = EventLoop(seed=8)
    set_event_loop(loop)
    shared = audited_dict(loop, "clean.counter", {"n": 0})

    async def bump():
        _ = shared["n"]
        await loop.delay(0.01)
        shared["n"] = shared["n"] + 1  # re-read in the write step

    done = all_of([loop.spawn(bump(), "bump_a"), loop.spawn(bump(), "bump_b")])
    loop.run_until(done, timeout_vt=10.0)
    assert shared["n"] == 2
    expect_clean_shared_state(loop, "recheck")  # must not raise


def test_sanitizer_blindness_check(monkeypatch):
    """Flag set but nothing audited: the shutdown check must refuse to
    silently pass (mirrors expect_no_orphaned_waits' tracking guard)."""
    monkeypatch.setenv("FDB_TPU_STATE_SANITIZER", "1")
    loop = EventLoop(seed=9)
    with pytest.raises(AssertionError, match="blind"):
        expect_clean_shared_state(loop)


def test_sanitizer_off_is_plain_dict(monkeypatch):
    monkeypatch.delenv("FDB_TPU_STATE_SANITIZER", raising=False)
    loop = EventLoop(seed=10)
    d = audited_dict(loop, "anything", {"k": 1})
    assert type(d) is dict
    assert getattr(loop, "_state_sanitizer", None) is None
    expect_clean_shared_state(loop)  # no-op with the flag off


def _commit_workload(c: SimCluster, rounds: int = 3, actors: int = 4):
    """Concurrent committing actors (conflicting + disjoint keys): drives
    the proxy's pipelined commit path (park/drain at depth 2) plus GRV
    batching and the CC's registration/ping registry."""
    db = c.database()
    out = {}

    async def actor(aid):
        for r in range(rounds):
            async def op(tr, aid=aid, r=r):
                cur = await tr.get(b"shared")
                tr.set(b"shared", (cur or b"") + b"%d" % aid)
                tr.set(b"a%02d/%02d" % (aid, r), b"v")

            await db.run(op)

    async def check(tr):
        out["shared"] = await tr.get(b"shared")
        out["rows"] = await tr.get_range(b"a", b"b")

    async def drive():
        await all_of(
            [db.process.spawn(actor(i), f"wl_{i}") for i in range(actors)]
        )

    c.run_all([(db, drive())], timeout_vt=3000.0)
    c.run_all([(db, db.run(check))], timeout_vt=1000.0)
    assert len(out["shared"]) == rounds * actors  # every commit landed
    assert len(out["rows"]) == rounds * actors
    return out


def test_sanitizer_quiet_on_full_commit_workload(monkeypatch):
    """Cross-validation: production audited dicts (the CC worker registry,
    the proxy server-list map) stay clean on a full SimCluster commit
    workload — the structural disciplines racecheck enforced really do
    hold at runtime."""
    monkeypatch.setenv("FDB_TPU_STATE_SANITIZER", "1")
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=41, n_workers=5)
    _commit_workload(c)
    san = getattr(c.loop, "_state_sanitizer", None)
    assert san is not None and "cluster_controller.workers" in san.names
    assert "proxy.server_list" in san.names
    expect_clean_shared_state(c.loop, "commit workload shutdown")


# ---------------------------------------------------------------------------
# Scheduler-perturbation replay gates (FDB_TPU_SCHED_FUZZ)
# ---------------------------------------------------------------------------


def _fingerprint(c: SimCluster, out) -> tuple:
    return (
        out["shared"],
        tuple(out["rows"]),
        c.loop.tasks_run,
        round(c.loop.now(), 9),
        round(c.loop.rng.random01(), 12),
    )


def _fuzzed_run(seed: int, fuzz: int) -> tuple:
    c = SimCluster(seed=seed, n_proxies=2)
    out = _commit_workload(c)
    fp = _fingerprint(c, out)
    set_event_loop(None)
    return fp


@pytest.mark.parametrize("fuzz", [1, 2, 3])
def test_commit_gate_green_and_replayable_under_sched_fuzz(monkeypatch, fuzz):
    """Each fuzz value is a different legal interleaving: the commit
    workload's invariants must hold (asserted inside _commit_workload),
    and the same (seed, fuzz) must replay to an identical fingerprint."""
    monkeypatch.setenv("FDB_TPU_SCHED_FUZZ", str(fuzz))
    a = _fuzzed_run(113, fuzz)
    b = _fuzzed_run(113, fuzz)
    assert a == b, f"same (seed, fuzz={fuzz}) must replay byte-identically"


def test_sched_fuzz_perturbs_the_interleaving(monkeypatch):
    """Different fuzz values must actually explore different schedules
    (else the gate is a no-op): the run fingerprints cannot all agree."""
    fps = []
    for fuzz in ("", "1", "2", "3"):
        if fuzz:
            monkeypatch.setenv("FDB_TPU_SCHED_FUZZ", fuzz)
        else:
            monkeypatch.delenv("FDB_TPU_SCHED_FUZZ", raising=False)
        fps.append(_fuzzed_run(113, int(fuzz or 0)))
    assert len({fp[2:] for fp in fps}) > 1, fps


# ---------------------------------------------------------------------------
# Regression pins for the structural fixes racecheck forced
# ---------------------------------------------------------------------------


def test_balancer_drops_stale_plan_instead_of_stomping(monkeypatch):
    """RACE001/WAIT001 fix pin: a competing repartition landing while
    run_once is suspended must abort this round — the durable partition
    and the in-memory view are never rebuilt from the stale snapshot."""
    c = SimCluster(seed=102, n_resolvers=2)
    assert c.split_keys == [b"\x80"]
    db = c.database()

    async def load():
        for i in range(60):
            async def op(tr, i=i):
                k = b"hot/%03d" % (i % 20)
                await tr.get(k)
                tr.set(k, b"x%d" % i)

            await db.run(op)

    c.run_all([(db, load())], timeout_vt=4000.0)
    bal = c.resolver_balancer(min_ops=20, ratio=1.5)

    competing = [b"\x40"]
    orig_run = bal.db.run

    async def hijack(txn):
        # A competing mover commits a different partition just before the
        # balancer's own commit (i.e. during its await window).
        async def other(tr):
            tr.options["access_system_keys"] = True
            tr.set(
                sk.RESOLVER_SPLIT_KEY, sk.encode_resolver_split(competing)
            )

        await orig_run(other)
        bal.db.run = orig_run  # only the balancer's commit is hijacked
        return await orig_run(txn)

    bal.db.run = hijack
    moved = c.run_until(db.process.spawn(bal.run_once()), timeout_vt=1000.0)
    assert moved is None
    assert bal.moves == 0
    # The stale plan was dropped, not stomped over the competing one.
    assert bal.split_keys == [b"\x80"]

    async def read_durable(tr):
        tr.options["access_system_keys"] = True
        return await tr.get(sk.RESOLVER_SPLIT_KEY)

    durable = c.run_until(
        db.process.spawn(orig_run(read_durable)), timeout_vt=1000.0
    )
    assert sk.decode_resolver_split(durable) == competing


def test_grv_concurrent_requests_one_snapshot(monkeypatch):
    """RACE001 fix pin: two get_read_version calls racing on one
    transaction must resolve to ONE snapshot version (first resolution
    wins) — never split the transaction's reads across two versions."""
    c = SimCluster(seed=43)
    db = c.database()
    out = {}

    async def go(tr):
        t1 = db.process.spawn(tr.get_read_version(), "grv1")
        t2 = db.process.spawn(tr.get_read_version(), "grv2")
        a, b = await all_of([t1, t2])
        out["versions"] = (a, b, tr._read_version)

    c.run_all([(db, db.run(go))], timeout_vt=1000.0)
    a, b, cached = out["versions"]
    assert a == b == cached


def test_diskqueue_pop_during_header_write_not_lost():
    """RACE001 fix pin: a pop() landing while the header write is in
    flight must re-dirty the header so the NEXT commit persists the newer
    popped_seq (the old ordering cleared the flag after the await and
    silently dropped the pop's progress)."""
    loop = EventLoop(seed=11)
    set_event_loop(loop)
    net = SimNetwork(loop)
    fs = SimFileSystem(net)
    proc = net.process("node")
    state = {}

    async def scenario():
        q, rec = await DiskQueue.open(fs, proc, "hdr.dq")
        for s in range(1, 4):
            q.push(s, b"p%d" % s)
        await q.commit()
        q.pop(1)

        # Interleave a pop exactly when the header write is issued.
        real_write = q._file.write

        async def write_hook(offset, data):
            if offset == 0 and "late_pop" not in state:
                state["late_pop"] = True
                q.pop(2)  # lands while the header write is in flight
            await real_write(offset, data)

        q._file.write = write_hook
        await q.commit()  # persists popped=1; pop(2) arrives mid-write
        q._file.write = real_write
        assert q._header_dirty  # the late pop re-dirtied the header
        await q.commit()  # must persist popped=2

        q2, rec2 = await DiskQueue.open(fs, proc, "hdr.dq")
        state["popped"] = q2.popped_seq
        state["recovered"] = [s for s, _p in rec2]

    loop.run_until(proc.spawn(scenario()), timeout_vt=100.0)
    assert state["popped"] == 2
    assert state["recovered"] == [3]
