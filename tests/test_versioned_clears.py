"""VersionedClears: the versioned clear-range index behind VersionedStore.

Ref: fdbclient/VersionedMap.h:43 — the PTree is versioned-ordered so MVCC
reads never scan history; the round-4 review flagged the flat clear list's
O(#clears) point reads (storage.py _latest_clear_over) as its stand-in's
collapse mode under clear-heavy load.
"""

import time

import pytest

from foundationdb_tpu.server.storage import VersionedClears, VersionedStore


class FlatOracle:
    """The round-4 flat list, kept as the differential oracle."""

    def __init__(self):
        self.clears = []

    def add(self, b, e, v, s):
        if b < e:
            self.clears.append((v, s, b, e))

    def latest_over(self, key, version):
        best = (-1, -1)
        for v, s, b, e in self.clears:
            if v <= version and b <= key < e and (v, s) > best:
                best = (v, s)
        return best

    def trim(self, through):
        self.clears = [c for c in self.clears if c[0] > through]


def k(i):
    return b"%05d" % i


def test_differential_vs_flat_oracle():
    import random

    rng = random.Random(77)
    vc, oracle = VersionedClears(), FlatOracle()
    version = 0
    for step in range(400):
        version += rng.randint(1, 3)
        op = rng.random()
        if op < 0.55:
            a = rng.randint(0, 500)
            b = a + rng.randint(1, 60)
            seq = rng.randint(0, 5)
            vc.add(k(a), k(b), version, seq)
            oracle.add(k(a), k(b), version, seq)
        elif op < 0.7 and step > 50:
            cut = version - rng.randint(5, 50)
            vc.trim(cut)
            oracle.trim(cut)
        # Probe a batch of random (key, version) points each step.
        for _ in range(10):
            key = k(rng.randint(0, 520))
            at = version - rng.randint(0, 40)
            assert vc.latest_over(key, at) == oracle.latest_over(key, at), (
                f"step {step}: diverged at {key!r}@{at}"
            )


def test_iteration_is_coverage_equivalent():
    """update_storage flushes clears by iterating fragments; the fragments
    must cover exactly what the inserted clears covered, stamps intact."""
    vc = VersionedClears()
    vc.add(k(10), k(40), 5, 0)
    vc.add(k(30), k(60), 7, 1)
    frags = list(vc)
    # Rebuild coverage from fragments and compare against direct queries.
    oracle = FlatOracle()
    for v, s, b, e in frags:
        oracle.add(b, e, v, s)
    for i in range(0, 70):
        for at in (4, 5, 6, 7, 8):
            assert oracle.latest_over(k(i), at) == vc.latest_over(k(i), at)


def test_trim_bounds_structure_to_live_window():
    """Segments and stamps must not accumulate beyond the live window: a
    long clear-heavy history trimmed as it goes keeps the index small."""
    vc = VersionedClears()
    for v in range(1, 2001):
        a = (v * 37) % 900
        vc.add(k(a), k(a + 20), v, 0)
        if v % 50 == 0:
            vc.trim(v - 30)  # keep a 30-version window
    vc.trim(2000 - 30)
    assert len(vc) <= 60, len(vc)  # ~30 live clears (+fragment slack)
    assert len(vc.bounds) <= 130, len(vc.bounds)


def test_point_read_cost_scales_sublinearly():
    """The adversarial case the review named: thousands of live clears in
    the window.  Per-query time at 256 vs 8192 live clears must grow far
    slower than the 32x a linear scan shows (binary searches: ~log factor;
    assert <8x with generous scheduler slack)."""

    def build(n):
        vc = VersionedClears()
        for v in range(1, n + 1):
            a = (v * 101) % (4 * n)
            vc.add(k(a), k(a + 3), v, 0)
        return vc

    def probe(vc, n, reps):
        t0 = time.perf_counter()
        acc = 0
        for i in range(reps):
            acc += vc.latest_over(k((i * 17) % (4 * n)), n)[0]
        return time.perf_counter() - t0

    small, big = build(256), build(8192)
    probe(small, 256, 1000)  # warm
    t_small = min(probe(small, 256, 4000) for _ in range(3))
    t_big = min(probe(big, 8192, 4000) for _ in range(3))
    assert t_big < 8 * t_small, (t_small, t_big)


def test_versioned_store_clear_semantics_unchanged():
    """The store-level contract through the new index: (version, seq)
    ordering of sets vs clears within one commit."""
    st = VersionedStore()
    st.set(b"a", b"1", 10, 0)
    st.clear_range(b"a", b"b", 10, 1)  # clear AFTER set in the same commit
    assert st.get(b"a", 10) is None
    st.clear_range(b"c", b"d", 20, 0)
    st.set(b"c", b"2", 20, 1)  # set AFTER clear in the same commit
    assert st.get(b"c", 20) == b"2"
    assert st.get(b"c", 19) is None
    # Reads below the clear version still see the old value.
    st.set(b"e", b"3", 5, 0)
    st.clear_range(b"e", b"f", 30, 0)
    assert st.get(b"e", 29) == b"3"
    assert st.get(b"e", 30) is None
    # Trim keeps only the live window (clears at 20 and 30 survive).
    st.trim(10)
    assert st.get(b"e", 31) is None
    assert len(st.clears) == 2
    st.trim(20)
    assert len(st.clears) == 1
