"""Pallas kernel differential gate (ISSUE 14).

FDB_TPU_KERNELS=1 (interpret-mode Pallas on this CPU host) must be
DECISION- and STATE-identical to the XLA fallback and to the CPU
reference across random streams in every engine mode — flat, tiered
(steady-state delta merges + in-cond major compactions), and the
sharded shard_map entry — including a scripted DeviceFaultInjector
fault landing ON a kernelized batch (breaker degrades to the mirror,
replays bit-identically, same-seed transition logs byte-identical).

Unit layer: the two kernels against brute-force oracles — the fused
merge-evict-compact against a numpy merge + removeBefore walk, the
streaming phase-1 search against ops.rangequery.searchsorted_words.

Shape discipline (1-core CI host): one small bucket per mode so each
interpret-mode compile is paid once.

Run alone: pytest -m kernels
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.engine_jax import JaxConflictSet
from foundationdb_tpu.conflict.types import TransactionConflictInfo as T
from foundationdb_tpu.flow import DeterministicRandom

pytestmark = pytest.mark.kernels

FLOOR = -(2**30)
INF = 0xFFFFFFFF
BUCKETS = (32, 128, 64)


def k(i: int) -> bytes:
    return b"%08d" % i


def _random_stream(seed, keyspace, batches, txns_per_batch, snap_lag=25):
    rng = DeterministicRandom(seed)
    version = 10
    out = []
    for _ in range(batches):
        txns = []
        for _ in range(rng.random_int(1, txns_per_batch + 1)):
            tr = T(read_snapshot=max(0, version - rng.random_int(0, snap_lag)))
            for _ in range(rng.random_int(0, 4)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
                tr.read_ranges.append((k(a), k(b)))
            for _ in range(rng.random_int(0, 3)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 10))
                tr.write_ranges.append((k(a), k(b)))
            txns.append(tr)
        now = version + rng.random_int(1, 10)
        new_oldest = max(0, version - snap_lag)
        out.append((txns, now, new_oldest))
        version = now
    return out


# ---------------------------------------------------------------------------
# 1. kernel unit oracles
# ---------------------------------------------------------------------------


def _merge_case(width, NA, NB, liveA, liveB, seed, window):
    from foundationdb_tpu.conflict.kernels import fused_merge_evict

    r = np.random.default_rng(seed)
    keepA = np.zeros(NA, bool)
    keepA[r.choice(NA, size=liveA, replace=False)] = True
    keepB = np.zeros(NB, bool)
    keepB[r.choice(NB, size=liveB, replace=False)] = True
    mc = liveA + liveB
    assert mc <= width
    a_slots = np.sort(r.choice(mc, size=liveA, replace=False))
    b_slots = np.setdiff1d(np.arange(mc), a_slots)
    posA = np.full(NA, 123456789, np.int32)
    posA[np.where(keepA)[0]] = a_slots
    posB = np.full(NB, 987654321, np.int32)
    posB[np.where(keepB)[0]] = b_slots
    versA = r.integers(-100, 100, NA).astype(np.int32)
    versB = r.integers(-100, 100, NB).astype(np.int32)
    kA = r.integers(0, 2**32, (3, NA), dtype=np.uint32)
    kB = r.integers(0, 2**32, (3, NB), dtype=np.uint32)

    ok, ov, oc = fused_merge_evict(
        jnp.asarray(kA), jnp.asarray(versA), jnp.asarray(keepA),
        jnp.asarray(posA),
        jnp.asarray(kB), jnp.asarray(versB), jnp.asarray(keepB),
        jnp.asarray(posB),
        jnp.asarray(mc, jnp.int32), jnp.asarray(window, jnp.int32),
        width=width, kw1=3, interpret=True,
    )
    ok, ov, oc = np.asarray(ok), np.asarray(ov), int(oc)

    # Oracle: materialize the merge, then the removeBefore walk.
    mk = np.zeros((3, mc), np.uint32)
    mv = np.zeros(mc, np.int32)
    mk[:, a_slots] = kA[:, keepA]
    mv[a_slots] = versA[keepA]
    mk[:, b_slots] = kB[:, keepB]
    mv[b_slots] = versB[keepB]
    prev = np.concatenate([[FLOOR], mv[:-1]])
    ev = (np.arange(mc) > 0) & (mv < window) & (prev < window)
    keep = ~ev
    want_k, want_v = mk[:, keep], mv[keep]
    n = want_v.shape[0]
    assert oc == n, (oc, n)
    assert (ov[:n] == want_v).all()
    assert (ok[:, :n] == want_k).all()


def test_fused_merge_evict_vs_oracle():
    for seed, (w, na, nb, la, lb) in enumerate([
        (512, 512, 64, 300, 40),
        (256, 256, 16, 100, 10),
        (1024, 1024, 128, 777, 100),
        (256, 256, 16, 0, 0),       # empty
        (256, 256, 16, 1, 16),      # singleton A, full B
    ]):
        _merge_case(w, na, nb, la, lb, seed + 1, window=0)


def test_fused_merge_evict_floor_window_keeps_everything():
    # window = FLOOR disables eviction (the noevict / amortized-skip arm).
    _merge_case(512, 512, 64, 300, 40, seed=9, window=FLOOR)


def test_phase1_search_vs_searchsorted_words():
    from foundationdb_tpu.conflict.kernels import phase1_search
    from foundationdb_tpu.ops.rangequery import searchsorted_words

    for seed, (N, live, R) in enumerate(
        [(1024, 700, 64), (512, 1, 16), (2048, 2048, 256)]
    ):
        r = np.random.default_rng(seed + 1)
        hk = np.full((3, N), INF, np.uint32)
        vals = np.sort(r.choice(2**20, size=live, replace=False)).astype(
            np.uint32)
        hk[0, :live] = vals >> 10
        hk[1, :live] = vals & 1023
        hk[2, :live] = 7

        def enc(q):
            out = np.zeros((3, R), np.uint32)
            out[0], out[1], out[2] = q >> 10, q & 1023, 7
            return out

        rb = enc(r.choice(2**20, size=R).astype(np.uint32))
        re_ = enc(r.choice(2**20, size=R).astype(np.uint32))
        rb[:, -2:] = INF  # padding-row queries rank too
        re_[:, -1:] = INF
        i0, j1 = phase1_search(jnp.asarray(hk), jnp.asarray(rb),
                               jnp.asarray(re_), interpret=True)
        want_i0 = searchsorted_words(jnp.asarray(hk), jnp.asarray(rb),
                                     "right") - 1
        want_j1 = searchsorted_words(jnp.asarray(hk), jnp.asarray(re_),
                                     "left") - 1
        assert (np.asarray(i0) == np.asarray(want_i0)).all(), (N, live, R)
        assert (np.asarray(j1) == np.asarray(want_j1)).all(), (N, live, R)


# ---------------------------------------------------------------------------
# 2. engine differentials: kernels vs XLA fallback vs CPU, state included
# ---------------------------------------------------------------------------


def _run_engine(stream, monkeypatch, kernels: bool, tiered: bool):
    if kernels:
        monkeypatch.setenv("FDB_TPU_KERNELS", "1")
    else:
        monkeypatch.setenv("FDB_TPU_KERNELS", "0")
    if tiered:
        monkeypatch.setenv("FDB_TPU_HISTORY", "tiered")
        monkeypatch.setenv("FDB_TPU_DELTA_CAP", "512")
        monkeypatch.setenv("FDB_TPU_EVICT_EVERY", "3")
    else:
        monkeypatch.delenv("FDB_TPU_HISTORY", raising=False)
    cs = JaxConflictSet(key_words=3, h_cap=1 << 10, bucket_mins=BUCKETS)
    assert cs._use_kernels is kernels
    assert cs.tiered is tiered
    verdicts = [cs.detect(txns, now, nov) for txns, now, nov in stream]
    exported = CpuConflictSet()
    cs.store_to(exported)
    if tiered:
        assert cs.metrics.snapshot()["counters"]["major_compactions"] >= 2
    return verdicts, (exported.keys, exported.vers,
                      exported.oldest_version)


@pytest.mark.parametrize("seed", [11, 23, 47])
@pytest.mark.parametrize("tiered", [False, True],
                         ids=["flat", "tiered"])
def test_kernel_vs_fallback_differential(monkeypatch, seed, tiered):
    """The acceptance gate: verdicts AND exported state bit-identical,
    kernels vs XLA, across >= 3 seeds x flat/tiered — and both match
    the CPU reference."""
    stream = _random_stream(seed, 50, batches=12, txns_per_batch=10)
    kv, kstate = _run_engine(stream, monkeypatch, kernels=True,
                             tiered=tiered)
    xv, xstate = _run_engine(stream, monkeypatch, kernels=False,
                             tiered=tiered)
    assert kv == xv
    assert kstate == xstate
    cpu = CpuConflictSet()
    want = [cpu.detect(txns, now, nov) for txns, now, nov in stream]
    assert kv == want


@pytest.mark.parametrize("seed", [5, 19, 31])
def test_kernel_sharded_differential(monkeypatch, seed):
    """Kernels inside the shard_map entry: per-shard detect_core runs the
    fused kernels on each device's slice; verdicts match the XLA-sharded
    run bit-for-bit AND the multi-resolver CPU oracle (the sharded
    semantic is per-shard clipping + min-combine — the reference's
    multi-resolver behavior, test_sharded_resolver's oracle)."""
    from test_sharded_resolver import MultiResolverCpuOracle

    from foundationdb_tpu.parallel.sharded_resolver import (
        ShardedJaxConflictSet,
    )

    stream = _random_stream(seed, 60, batches=8, txns_per_batch=8)
    splits = [k(20), k(40)]

    def run(kernels):
        monkeypatch.setenv("FDB_TPU_KERNELS", "1" if kernels else "0")
        cs = ShardedJaxConflictSet(
            splits, key_words=3, h_cap=1 << 9, bucket_mins=BUCKETS,
        )
        assert cs._use_kernels is kernels
        return [cs.detect(txns, now, nov) for txns, now, nov in stream]

    kv = run(True)
    assert kv == run(False)
    oracle = MultiResolverCpuOracle(splits)
    assert kv == [oracle.detect(txns, now, nov) for txns, now, nov in stream]


# ---------------------------------------------------------------------------
# 3. device fault ON a kernelized batch (breaker + mirror replay)
# ---------------------------------------------------------------------------


def test_scripted_fault_on_kernelized_batch(monkeypatch):
    """DeviceFaultInjector firing on kernelized batches (incl. the first
    half-open probe): breaker degrades, the mirror replays those batches
    bit-identically, recovery rehydrates, and a same-seed rerun produces
    a byte-identical transition log."""
    from foundationdb_tpu.conflict.api import ConflictSet
    from foundationdb_tpu.conflict.device_faults import DeviceFaultInjector

    monkeypatch.setenv("FDB_TPU_KERNELS", "1")
    monkeypatch.setenv("FDB_TPU_HISTORY", "tiered")
    monkeypatch.setenv("FDB_TPU_DELTA_CAP", "512")
    monkeypatch.setenv("FDB_TPU_EVICT_EVERY", "4")
    stream = _random_stream(41, 50, batches=18, txns_per_batch=10)

    def run():
        inj = DeviceFaultInjector()
        for at in (4, 5, 6, 7):  # batch 4 = the compaction batch
            inj.script("dispatch", at=at)
        cs = ConflictSet(backend="jax", key_words=3, h_cap=1 << 10,
                         bucket_mins=BUCKETS, fault_injector=inj)
        assert cs._jax._use_kernels and cs._jax.tiered
        verdicts = []
        for txns, now, nov in stream:
            b = cs.new_batch()
            for t in txns:
                b.add_transaction(t)
            verdicts.append(b.detect_conflicts(now, nov))
        return verdicts, cs.device_metrics()

    verdicts, dm = run()
    cpu = CpuConflictSet()
    want = [cpu.detect(txns, now, nov) for txns, now, nov in stream]
    assert verdicts == want, "faulty kernelized run diverged from CPU"
    pairs = [(f, t) for _s, f, t, _r in dm["breaker"]["transitions"]]
    assert pairs == [
        ("ok", "degraded"),
        ("degraded", "probing"),
        ("probing", "degraded"),
        ("degraded", "probing"),
        ("probing", "ok"),
    ], dm["breaker"]["transitions"]
    assert dm["counters"]["rehydrates"] >= 1
    assert dm["backend_state"] == "ok"
    verdicts2, dm2 = run()
    assert verdicts2 == verdicts
    assert json.dumps(dm2["breaker"]) == json.dumps(dm["breaker"])


# ---------------------------------------------------------------------------
# 4. FDB_TPU_KERNELS / FDB_TPU_H_CAP flag plumbing
# ---------------------------------------------------------------------------


def test_kernels_flag_validated_at_construction(monkeypatch):
    from foundationdb_tpu.parallel.sharded_resolver import (
        ShardedJaxConflictSet,
    )

    monkeypatch.setenv("FDB_TPU_KERNELS", "banana")
    with pytest.raises(ValueError, match="FDB_TPU_KERNELS"):
        JaxConflictSet(key_words=3, h_cap=1 << 8)
    # The sharded set validates through the SAME resolve helper — a
    # typo'd flag raises rather than silently selecting the fallback.
    with pytest.raises(ValueError, match="FDB_TPU_KERNELS"):
        ShardedJaxConflictSet([k(20)], key_words=3, h_cap=1 << 8,
                              bucket_mins=BUCKETS)


def test_kernels_flag_auto_is_backend_gated():
    from foundationdb_tpu.conflict.kernels import (
        kernel_interpret,
        kernels_requested,
    )

    assert kernels_requested("", "tpu") and not kernels_requested("", "cpu")
    assert kernels_requested("auto", "tpu")
    assert kernels_requested("1", "cpu") and kernels_requested("1", "tpu")
    assert not kernels_requested("0", "tpu")
    assert kernel_interpret("1", "cpu") and not kernel_interpret("1", "tpu")
    assert kernel_interpret("interpret", "tpu")


def test_h_cap_knob_must_fit_grow_guard(monkeypatch):
    """Satellite (PERF_NOTES lever 2): the default h_cap drop rides the
    FDB_TPU_H_CAP knob, and the engine's must-fit guard makes any drop
    safe — a live boundary set outrunning the knob's cap triggers a
    sync+grow, never truncation, with verdicts identical to the CPU
    reference throughout.  Exercised under kernels so the grown shape
    recompiles the kernelized program too."""
    from foundationdb_tpu.conflict.api import ConflictSet
    from foundationdb_tpu.flow.knobs import g_env

    assert "FDB_TPU_H_CAP" in g_env.declared()
    monkeypatch.setenv("FDB_TPU_H_CAP", "256")
    monkeypatch.setenv("FDB_TPU_KERNELS", "1")
    cs = ConflictSet(backend="jax", key_words=3, bucket_mins=BUCKETS)
    assert cs._jax.h_cap == 256
    cpu = CpuConflictSet()
    v = 0
    # Dense distinct writes: ~64 boundaries/batch, overrunning 256 rows.
    for i in range(8):
        txns = [T(read_snapshot=v,
                  write_ranges=[(k(1000 * i + 3 * j), k(1000 * i + 3 * j + 1))
                                for j in range(32)]),
                T(read_snapshot=v,
                  read_ranges=[(k(1000 * i), k(1000 * i + 120))])]
        v += 5
        b = cs.new_batch()
        for t in txns:
            b.add_transaction(t)
        assert b.detect_conflicts(v, 0) == cpu.detect(txns, v, 0), i
    assert cs._jax.h_cap > 256, "must-fit guard never grew"
    assert cs._jax.metrics.snapshot()["counters"]["grows"] >= 1
    assert cs._jax.boundary_count == cpu.boundary_count


def test_h_cap_knob_rounds_to_kernel_tile(monkeypatch):
    """An arbitrary knob value is rounded UP to a 256-row multiple so
    the kernels' power-of-two tile never degrades toward a per-row
    sequential grid (api.env_h_cap)."""
    from foundationdb_tpu.conflict.api import ConflictSet, env_h_cap
    from foundationdb_tpu.conflict.kernels import _tile

    monkeypatch.setenv("FDB_TPU_H_CAP", "1000001")
    assert env_h_cap() == 1000192  # next multiple of 256
    assert _tile(env_h_cap()) == 256
    cs = ConflictSet(backend="jax", key_words=3, bucket_mins=BUCKETS)
    assert cs._jax.h_cap == 1000192
    monkeypatch.setenv("FDB_TPU_H_CAP", "0")
    assert env_h_cap() == 0
