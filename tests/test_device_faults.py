"""Device-fault injection & degraded-mode differential gate (ISSUE 3).

The headline invariant: a same-seed simulation with device faults
injected produces conflict verdicts IDENTICAL to the fault-free CPU-only
run — the CPU SkipList mirror stays authoritative through every fault,
open circuit, half-open probe, and rehydration — and the breaker's
transition log is byte-identical across replays of the same seed.

Shape discipline (1-core CI host): every JaxConflictSet here uses
key_words=3 + bucket_mins=(32, 128, 64) with h_cap in {1<<9, 1<<10},
the same static shapes test_conflict_jax compiles — XLA's in-process jit
cache makes the marginal compile cost of this module near zero in a full
run.  The cluster tests use SimCluster defaults, sharing test_e2e's
shapes.
"""

import json

import pytest

from foundationdb_tpu.conflict.api import ConflictSet
from foundationdb_tpu.conflict.device_faults import (
    CompileFailed,
    DeviceCircuitBreaker,
    DeviceFault,
    DeviceFaultInjector,
    DeviceOOM,
    DeviceUnavailable,
)
from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.types import TransactionConflictInfo as T
from foundationdb_tpu.flow import DeterministicRandom, set_event_loop
from foundationdb_tpu.flow.buggify import set_buggify_enabled
from foundationdb_tpu.flow.knobs import g_knobs


@pytest.fixture(autouse=True)
def _clean_buggify_and_loop():
    yield
    set_buggify_enabled(False)
    set_event_loop(None)


def k(i: int) -> bytes:
    return b"%08d" % i


def _random_stream(seed, keyspace, batches, txns_per_batch, snap_lag=25):
    """(txns, now, new_oldest) batches from a seeded rng (standalone twin
    of test_conflict_jax's stream: regenerable for a second engine)."""
    rng = DeterministicRandom(seed)
    version = 10
    out = []
    for _ in range(batches):
        txns = []
        for _ in range(rng.random_int(1, txns_per_batch + 1)):
            tr = T(read_snapshot=max(0, version - rng.random_int(0, snap_lag)))
            for _ in range(rng.random_int(0, 4)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
                tr.read_ranges.append((k(a), k(b)))
            for _ in range(rng.random_int(0, 3)):
                a = rng.random_int(0, keyspace)
                b = a + 1 + rng.random_int(0, max(1, keyspace // 8))
                tr.write_ranges.append((k(a), k(b)))
            txns.append(tr)
        version += rng.random_int(1, 10)
        out.append((txns, version, max(0, version - 40)))
    return out


def _device_set(**kw):
    kw.setdefault("backend", "jax")
    kw.setdefault("key_words", 3)
    kw.setdefault("bucket_mins", (32, 128, 64))
    kw.setdefault("h_cap", 1 << 10)
    return ConflictSet(**kw)


def _drive(cs, stream):
    out = []
    for txns, now, nov in stream:
        b = cs.new_batch()
        for t in txns:
            b.add_transaction(t)
        out.append(b.detect_conflicts(now, nov))
    return out


def _drive_cpu(stream):
    cpu = CpuConflictSet()
    return [cpu.detect(txns, now, nov) for txns, now, nov in stream]


# ---------------------------------------------------------------------------
# Injector + breaker units
# ---------------------------------------------------------------------------


def test_injector_scripted_plan_and_log():
    inj = DeviceFaultInjector()
    inj.script("dispatch", at=2)
    inj.script("grow", at=1, persist=2)
    inj.check("dispatch")  # 1: clean
    with pytest.raises(DeviceOOM):
        inj.check("grow")  # 1: scripted, persists
    with pytest.raises(DeviceUnavailable):
        inj.check("dispatch")  # 2: scripted transient
    with pytest.raises(DeviceOOM):
        inj.check("grow")  # 2: persistence tail
    inj.check("grow")  # 3: clean again
    inj.check("dispatch")  # 3: clean
    assert [e[1:] for e in inj.injected] == [
        ["grow", "persistent"],
        ["dispatch", "transient"],
        ["grow", "persistent"],
    ]
    # Outages hold a site down until released; compile faults have their
    # own type.
    inj.begin_outage("compile")
    with pytest.raises(CompileFailed):
        inj.check("compile")
    inj.end_outage("compile")
    inj.check("compile")


def test_injector_overlapping_scripted_windows_extend():
    """A scripted entry whose check number falls inside an active
    persistence window is consumed there and EXTENDS the window
    (max-merge) — overlapping plans never silently vanish."""
    inj = DeviceFaultInjector()
    inj.script("dispatch", at=1, persist=2)  # covers checks 1-2
    inj.script("dispatch", at=2, persist=4)  # lands inside the window
    for n in (1, 2, 3, 4, 5):  # extended through check 5
        with pytest.raises(DeviceUnavailable):
            inj.check("dispatch")
    inj.check("dispatch")  # 6: clean
    assert len(inj.injected) == 5


def test_injector_random_mode_replays_from_seed():
    def run(seed):
        set_buggify_enabled(True, DeterministicRandom(seed))
        inj = DeviceFaultInjector(
            rng=DeterministicRandom(seed + 1), fire_probability=0.5
        )
        for i in range(60):
            site = ("dispatch", "grow", "compile", "rebase")[i % 4]
            try:
                inj.check(site)
            except DeviceFault:
                pass
        return inj.injected

    a, b = run(7), run(7)
    assert a == b and a, "same seed must replay the same fault schedule"
    assert run(7) != run(8), "schedule must actually depend on the seed"


def test_breaker_state_machine_unit():
    br = DeviceCircuitBreaker(threshold=3, backoff_batches=2)
    fault = DeviceUnavailable("x", site="dispatch")
    # Two faults: still closed (transient blips).
    for _ in range(2):
        assert br.allows_device()
        br.on_failure(fault)
    assert br.state == "ok"
    assert br.allows_device()
    br.on_success()
    assert br.consecutive_failures == 0
    # Three consecutive: opens.
    for _ in range(3):
        assert br.allows_device()
        br.on_failure(fault)
    assert br.state == "degraded"
    # Backoff: one blocked batch, then a probe that fails -> backoff
    # doubles; 3 blocked batches, then a probe that succeeds -> ok.
    assert not br.allows_device()
    assert br.allows_device() and br.state == "probing"
    br.on_failure(fault)
    assert br.state == "degraded" and br.backoff == 4
    for _ in range(3):
        assert not br.allows_device()
    assert br.allows_device() and br.state == "probing"
    br.on_success()
    assert br.state == "ok" and br.backoff == 2
    assert [(f, t) for _s, f, t, _r in br.transitions] == [
        ("ok", "degraded"),
        ("degraded", "probing"),
        ("probing", "degraded"),
        ("degraded", "probing"),
        ("probing", "ok"),
    ]


# ---------------------------------------------------------------------------
# The differential gate
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [3, 5, 9])
def test_same_seed_faulty_run_matches_cpu_only_run(seed):
    """>= 3 seeds: buggify-driven random device faults; verdicts must be
    identical to the fault-free CPU-only run, and a same-seed replay must
    produce a byte-identical breaker transition log + fault schedule."""
    old_act = g_knobs.flow.buggify_activated_probability
    g_knobs.flow.buggify_activated_probability = 1.0  # every site armed
    try:
        def faulty_run():
            set_buggify_enabled(True, DeterministicRandom(seed))
            inj = DeviceFaultInjector(
                rng=DeterministicRandom(seed * 7 + 1), fire_probability=0.3
            )
            cs = _device_set(fault_injector=inj)
            verdicts = _drive(cs, _random_stream(seed, 60, 14, 8))
            dm = cs.device_metrics()
            return verdicts, dm, inj.injected

        v1, dm1, log1 = faulty_run()
        v2, dm2, log2 = faulty_run()
        want = _drive_cpu(_random_stream(seed, 60, 14, 8))
        assert v1 == want, "faulty run diverged from the CPU-only run"
        assert v1 == v2
        assert log1 == log2 and log1, "fault schedule must replay (and fire)"
        assert json.dumps(dm1["breaker"]) == json.dumps(dm2["breaker"])
        assert dm1["counters"]["device_faults"] == len(log1)
    finally:
        g_knobs.flow.buggify_activated_probability = old_act


def test_faults_mid_grow_and_recovery():
    """A device OOM raised inside _grow (history at capacity) degrades to
    the CPU with identical verdicts; once the outage lifts, the probe
    rehydrates — growing the device history from the CPU state — and the
    device resumes."""
    inj = DeviceFaultInjector()
    cs = _device_set(h_cap=1 << 9, fault_injector=inj)
    cpu = CpuConflictSet()
    v = 0
    outage = False
    for i in range(10):
        # 8 txns x 8 disjoint NON-adjacent single-key writes (adjacent
        # ones would coalesce into one segment): +128 boundaries per
        # batch with the window pinned at 0, so capacity 512 exhausts at
        # batch ~3 and growth is forced while the outage holds.
        txns = [
            T(
                read_snapshot=v,
                write_ranges=[
                    (
                        k(10_000 * i + 100 * t + 2 * j),
                        k(10_000 * i + 100 * t + 2 * j + 1),
                    )
                    for j in range(8)
                ],
            )
            for t in range(8)
        ]
        if i == 2 and not outage:
            inj.begin_outage("grow")
            outage = True
        if i == 6:
            inj.end_outage("grow")
        v += 5
        b = cs.new_batch()
        for t in txns:
            b.add_transaction(t)
        assert b.detect_conflicts(v, 0) == cpu.detect(txns, v, 0), f"batch {i}"
    assert any(site == "grow" for _s, site, _k in inj.injected), (
        "the outage never hit _grow — capacity math drifted"
    )
    dm = cs.device_metrics()
    assert dm["backend_state"] == "ok", dm["breaker"]
    assert dm["counters"]["faults_grow"] >= 1
    assert dm["counters"]["rehydrates"] >= 1
    # The device really did grow past its initial capacity after recovery.
    assert dm["h_cap"] > (1 << 9)
    assert cs._jax.boundary_count == cpu.boundary_count


def test_fault_during_half_open_probe():
    """Scripted: 3 consecutive dispatch faults open the circuit; the
    first half-open probe is faulted too (degraded again, backoff
    doubles); the second probe succeeds and rehydrates.  The transition
    sequence is exact and verdicts never diverge."""
    stream = _random_stream(17, 50, 16, 6)

    def run():
        inj = DeviceFaultInjector()
        # Site-check numbering: check #1 is batch 1's dispatch (batch 1
        # also checks "compile" once — separate counter).  Faults at
        # dispatch checks 2,3,4 are consecutive failures (batches 2,3,4)
        # -> circuit opens; check 5 is the first probe -> faulted.
        for at in (2, 3, 4, 5):
            inj.script("dispatch", at=at)
        cs = _device_set(fault_injector=inj)
        verdicts = _drive(cs, stream)
        return verdicts, cs.device_metrics()

    verdicts, dm = run()
    assert verdicts == _drive_cpu(stream)
    assert [(f, t) for _s, f, t, _r in dm["breaker"]["transitions"]] == [
        ("ok", "degraded"),
        ("degraded", "probing"),
        ("probing", "degraded"),
        ("degraded", "probing"),
        ("probing", "ok"),
    ], dm["breaker"]["transitions"]
    assert dm["backend_state"] == "ok"
    assert dm["counters"]["breaker_opens"] == 1
    assert dm["counters"]["breaker_probes"] == 2
    assert dm["counters"]["breaker_closes"] == 1
    # Replay: the transition log is byte-identical.
    verdicts2, dm2 = run()
    assert verdicts2 == verdicts
    assert json.dumps(dm2["breaker"]) == json.dumps(dm["breaker"])


def test_hybrid_faults_keep_cpu_agreement():
    """Hybrid routing (size threshold + authority hysteresis) under
    faults, including a DeviceOOM raised inside the probe's load_from
    rehydration: verdicts stay identical to a pure-CPU run."""
    old_min = g_knobs.server.conflict_device_min_batch
    g_knobs.server.conflict_device_min_batch = 4
    try:
        stream = _random_stream(23, 60, 18, 8)
        inj = DeviceFaultInjector()
        for at in (2, 3, 4):  # open the circuit on-device
            inj.script("dispatch", at=at)
        inj.script("grow", at=1, persist=1)  # first rehydrate-grow attempt
        cs = _device_set(backend="hybrid", fault_injector=inj)
        assert _drive(cs, stream) == _drive_cpu(stream)
        assert cs.device_metrics()["counters"]["device_faults"] >= 3
    finally:
        g_knobs.server.conflict_device_min_batch = old_min


# ---------------------------------------------------------------------------
# Cluster integration: resolver absorption, status/CLI surface, chaos
# ---------------------------------------------------------------------------


def test_cluster_resolver_absorbs_device_outage_and_status_surfaces():
    """A persistent dispatch outage under live commit traffic: no error
    ever reaches the proxy (every commit gets a verdict), the breaker
    walks ok -> degraded -> ... -> ok, and the whole journey is visible
    in resolver metrics, `ConflictSet.device_metrics()`, the status
    doc's tpu section, and `status --format=json`."""
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.server.status import cluster_status
    from foundationdb_tpu.tools.cli import CliProcessor

    c = SimCluster(seed=1234, conflict_backend="jax")
    db = c.database()
    cs = c.resolver.conflicts
    inj = DeviceFaultInjector()
    cs.install_fault_injector(inj)
    committed = []

    async def commits(n, tag):
        for i in range(n):
            tr = db.create_transaction()
            tr.set(b"df/%s%02d" % (tag, i), b"v")
            committed.append(await tr.commit())

    async def scenario():
        await commits(3, b"a")  # healthy
        inj.begin_outage("dispatch")
        await commits(4, b"b")  # degraded: CPU absorbs, nothing escapes
        inj.end_outage("dispatch")
        # Let the idle/commit batches walk the breaker through its
        # backoff to a successful probe.
        await commits(4, b"c")
        for _ in range(200):
            if cs._breaker.state == "ok":
                break
            await c.loop.delay(0.1)

    c.run_until(db.process.spawn(scenario(), "scenario"), timeout_vt=5000.0)
    assert len(committed) == 11 and all(v is not None for v in committed)
    dm = cs.device_metrics()
    assert dm["backend_state"] == "ok", dm["breaker"]
    pairs = [(f, t) for _s, f, t, _r in dm["breaker"]["transitions"]]
    assert ("ok", "degraded") in pairs and ("probing", "ok") in pairs
    assert dm["counters"]["device_faults"] >= 3
    # Resolver-side: the degraded batches were counted and tagged.
    snap = c.resolver.metrics.snapshot()
    assert snap["counters"]["degraded_batches"] >= 3
    assert snap["histograms"]["degraded_batch_size"]["count"] >= 1
    # Status doc: the tpu sub-section carries backend_state + transitions.
    doc = cluster_status(c)
    tpu = doc["cluster"]["resolver"]["tpu"]["resolver"]
    assert tpu["backend_state"] == "ok"
    assert tpu["breaker"]["transitions"] == dm["breaker"]["transitions"]
    # And the operator surface agrees: status --format=json parses.
    cli = CliProcessor(c, db)

    async def run_cli():
        return await cli.run_command("status --format=json")

    lines = c.run_until(db.process.spawn(run_cli(), "cli"), timeout_vt=600.0)
    cli_doc = json.loads("\n".join(lines))
    assert (
        cli_doc["cluster"]["resolver"]["tpu"]["resolver"]["backend_state"]
        == "ok"
    )


def test_resolver_host_retry_for_raw_conflict_set():
    """A RAW conflict set (store_to but no breaker) that surfaces a
    DeviceFault mid-resolve: the resolver retries the batch on a host
    engine built from the set's pre-batch state IN the same resolve call
    (no error to the proxy), then the CPU engine takes over for the rest
    of the role's life."""
    from foundationdb_tpu.conflict.api import ConflictBatch
    from foundationdb_tpu.server import SimCluster

    class FaultyRawSet:
        def __init__(self):
            self._cpu = CpuConflictSet()
            self.detects = 0

        def new_batch(self):
            return ConflictBatch(self)

        def _detect(self, txns, now, nov):
            self.detects += 1
            if self.detects >= 3:
                raise DeviceUnavailable("raw set lost its device",
                                        site="dispatch")
            return self._cpu.detect(txns, now, nov)

        def store_to(self, cpu):
            cpu.keys = list(self._cpu.keys)
            cpu.vers = list(self._cpu.vers)
            cpu.oldest_version = self._cpu.oldest_version

    raw = FaultyRawSet()
    c = SimCluster(seed=77, conflict_set=raw)
    db = c.database()
    committed = []

    async def commits():
        for i in range(8):
            tr = db.create_transaction()
            tr.set(b"raw/%02d" % i, b"v")
            committed.append(await tr.commit())

    c.run_until(db.process.spawn(commits(), "commits"), timeout_vt=5000.0)
    assert len(committed) == 8 and all(v is not None for v in committed)
    r = c.resolver
    assert r._cpu_takeover is not None, "host takeover never happened"
    snap = r.metrics.snapshot()
    assert snap["counters"]["degraded_batches"] >= 1
    # The raw set was abandoned at the fault — every later batch was
    # decided by the takeover engine against the exported state.
    assert raw.detects == 3


def test_device_chaos_workload_composes_with_clogging():
    """DeviceChaosWorkload + RandomClogging under a Cycle invariant load:
    serializability holds through combined device faults and network
    chaos, the workload's own degraded-mode checks pass (run_workloads
    asserts them), and the sim-end buggify coverage report names the
    device fault sites."""
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.workloads import (
        CycleWorkload,
        DeviceChaosWorkload,
        RandomCloggingWorkload,
        SerializabilityWorkload,
        run_workloads,
    )

    old_act = g_knobs.flow.buggify_activated_probability
    g_knobs.flow.buggify_activated_probability = 1.0  # arm every site
    try:
        c = SimCluster(seed=424242, conflict_backend="jax", n_proxies=2)
        chaos = DeviceChaosWorkload(duration=3.0, fire_probability=0.5)
        run_workloads(
            c,
            [
                CycleWorkload(nodes=6, ops=12, actors=2),
                SerializabilityWorkload(registers=4, actors=2, ops=5),
                chaos,
                RandomCloggingWorkload(duration=2.0),
            ],
            timeout_vt=20000.0,
        )
        assert chaos.installed, "no device engine found to inject into"
        fired = [inj.injected for _cs, inj in chaos.installed]
        assert any(fired), "chaos run never injected a device fault"
        # Sim-end coverage (satellite): the registry gauges name the
        # device fault sites the seed exercised.
        cov = c.buggify_coverage.snapshot()
        assert cov["gauges"]["buggify_sites_fired"] >= 1
        assert any(
            g.startswith("fired:device_fault_") for g in cov["gauges"]
        ), sorted(cov["gauges"])
    finally:
        g_knobs.flow.buggify_activated_probability = old_act


def test_backend_signal_cheap_probe():
    """ISSUE 8 satellite: ConflictSet.backend_signal() is the O(1)
    ratekeeper probe — breaker state + measured CPU-mirror throughput —
    with no history-row walks and no histogram snapshotting.  Degraded
    batches feed the measurement; healthy ones don't."""
    sig = _device_set().backend_signal()
    assert sig == {
        "backend_state": "ok",
        "cpu_mirror_tps": 0.0,
        "cpu_fallback_txns": 0,
        "mirror_divergence": 0,
    }
    # CPU-only sets answer trivially-ok too (uniform resolver plumbing).
    assert ConflictSet(backend="cpu").backend_signal()["backend_state"] == "ok"

    inj = DeviceFaultInjector()
    for at in (1, 2, 3, 4):
        inj.script("dispatch", at=at)
    cs = _device_set(fault_injector=inj)
    for i in range(4):
        b = cs.new_batch()
        b.add_transaction(
            T(read_snapshot=9 + i, write_ranges=[(k(i), k(i + 1))])
        )
        b.detect_conflicts(10 + i, 0)
    sig = cs.backend_signal()
    assert sig["backend_state"] == "degraded"  # 3 consecutive faults opened
    assert sig["cpu_fallback_txns"] == 4  # every faulted batch measured
    assert sig["cpu_mirror_tps"] > 0.0  # wall-measured mirror throughput
    # The deterministic counter surface carries the txn count too.
    assert cs._jax.metrics.counter("cpu_fallback_txns").value == 4


def test_long_key_pin_lifts_after_window(monkeypatch):
    """ISSUE 8 regression: a long-key write pins history to the CPU
    mirror, but only until the write ages out of the MVCC window AND its
    boundary leaves the mirror — NOT for the resolver's lifetime (a
    DynamicCluster's system-keyspace metadata writes would otherwise
    disable the device path forever)."""
    from foundationdb_tpu.conflict.types import COMMITTED

    cs = _device_set()
    max_key = min(
        g_knobs.server.conflict_max_device_key_bytes, 3 * 4
    )
    long_key = b"L" * (max_key + 4)

    def short_batch(now, nov):
        b = cs.new_batch()
        b.add_transaction(
            T(read_snapshot=now - 1, write_ranges=[(k(now), k(now + 1))])
        )
        return b.detect_conflicts(now, nov)

    assert short_batch(10, 0) == [COMMITTED]
    before = cs._jax.metrics.counter("batches").value
    assert before >= 1  # device served the short batch

    # Long-key write at version 20: pins the device path.
    b = cs.new_batch()
    b.add_transaction(
        T(read_snapshot=19, write_ranges=[(long_key, long_key + b"\x00")])
    )
    b.detect_conflicts(20, 0)
    assert cs._history_long_keys and cs._long_key_version == 20
    assert short_batch(25, 0) == [COMMITTED]  # still CPU-served
    assert cs._jax.metrics.counter("batches").value == before

    # Window passes the long-key write: eviction drops the boundary (its
    # predecessor is also below-window), the pin lifts, the device
    # rehydrates and serves again.
    assert short_batch(60, 30) == [COMMITTED]  # evicts; scan next batch
    assert short_batch(61, 31) == [COMMITTED]
    assert not cs._history_long_keys
    assert cs._jax.metrics.counter("batches").value > before
    assert all(len(key) <= max_key for key in cs._cpu.keys)


def test_long_key_pin_persists_while_boundary_survives():
    """The sound half of the un-pin: a long-key boundary that outlives
    the window (as the right edge of a hot predecessor range) keeps the
    pin until it is really gone — load_from must never see it."""
    from foundationdb_tpu.conflict.types import COMMITTED

    cs = _device_set()
    max_key = min(g_knobs.server.conflict_max_device_key_bytes, 3 * 4)
    long_key = b"L" * (max_key + 4)
    # A range whose END is the long key: the long boundary marks the
    # right edge, and rewriting the range start keeps it load-bearing.
    b = cs.new_batch()
    b.add_transaction(
        T(read_snapshot=9, write_ranges=[(b"A", long_key)])
    )
    b.detect_conflicts(10, 0)
    assert cs._history_long_keys

    def hot_rewrite(now, nov):
        bb = cs.new_batch()
        bb.add_transaction(
            T(read_snapshot=now - 1, write_ranges=[(b"A", b"B")])
        )
        return bb.detect_conflicts(now, nov)

    # Window passes version 10, but the hot predecessor keeps the long
    # boundary alive (removeBefore keeps a below-window boundary whose
    # predecessor is in-window) — the pin must hold.
    for now, nov in ((40, 20), (70, 50), (100, 80)):
        assert hot_rewrite(now, nov) == [COMMITTED]
    if any(len(key) > max_key for key in cs._cpu.keys):
        assert cs._history_long_keys  # boundary alive => pinned


def test_degraded_flag_consumed_once():
    inj = DeviceFaultInjector()
    inj.script("dispatch", at=1)
    cs = _device_set(fault_injector=inj)
    txns = [T(read_snapshot=0, write_ranges=[(k(1), k(2))])]
    b = cs.new_batch()
    b.add_transaction(txns[0])
    b.detect_conflicts(5, 0)
    assert cs.consume_degraded() is True
    assert cs.consume_degraded() is False  # reading resets
    from foundationdb_tpu.conflict.types import CONFLICT

    b2 = cs.new_batch()
    b2.add_transaction(T(read_snapshot=4, read_ranges=[(k(1), k(2))]))
    # CONFLICT: the faulted batch's write really landed (CPU authority).
    assert b2.detect_conflicts(6, 0) == [CONFLICT]
    assert cs.consume_degraded() is False  # healthy batch
