"""KVStoreTest: randomized engine differential with crash/recover.

Ref: fdbserver/workloads/KVStoreTest.actor.cpp — drive an IKeyValueStore
with a random op mix and verify against a model; here each engine
(memory WAL+snapshot, COW btree) runs the same seeded op stream against
a dict model, with periodic commits, machine crashes, and recovery — the
recovered store must equal the model AS OF THE LAST COMMIT exactly
(shadow paging / WAL replay must neither lose committed ops nor
resurrect uncommitted ones).
"""

import zlib

import pytest

from foundationdb_tpu.fileio import SimFileSystem
from foundationdb_tpu.fileio.kvstore import open_engine
from foundationdb_tpu.flow import EventLoop, set_event_loop
from foundationdb_tpu.rpc import SimNetwork


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def _key(rng, space):
    return b"k%05d" % int(rng.random_int(0, space))


@pytest.mark.parametrize("engine", ["memory", "btree", "memory+compress", "btree+compress"])
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_engine_random_differential_with_crashes(engine, seed):
    # Stable per-engine seed offset (hash() varies with PYTHONHASHSEED,
    # which would break cross-run reproducibility).
    loop = EventLoop(seed=seed * 100 + (zlib.crc32(engine.encode()) % 7))
    set_event_loop(loop)
    net = SimNetwork(loop)
    fs = SimFileSystem(net)
    proc = net.process("kvhost", machine_id="kvhost")
    driver = net.process("driver", machine_id="driver")
    rng = loop.rng
    space = 200
    state = {"done": False}

    async def run():
        model = {}  # mirrors the store INCLUDING uncommitted ops
        committed = {}  # model as of the last successful commit
        kv = await open_engine(engine, fs, proc, "store")
        for round_no in range(6):
            for _ in range(120):
                op = int(rng.random_int(0, 10))
                if op < 6:
                    k = _key(rng, space)
                    v = b"v%d" % int(rng.random_int(0, 1 << 20))
                    kv.set(k, v)
                    model[k] = v
                elif op < 8:
                    a = _key(rng, space)
                    b = a + b"\x00" * 2 + b"9"
                    a, b = min(a, b), max(a, b)
                    kv.clear_range(a, b)
                    for kk in [x for x in model if a <= x < b]:
                        del model[kk]
                else:
                    k = _key(rng, space)
                    assert kv.read_value(k) == model.get(k)
            await kv.commit()
            committed.clear()
            committed.update(model)
            # Read-back differential on a few random ranges.
            for _ in range(5):
                a, b = sorted([_key(rng, space), _key(rng, space)])
                got = kv.read_range(a, b, limit=1 << 20)
                want = sorted(
                    (k, v) for k, v in committed.items() if a <= k < b
                )
                assert got == want
            if round_no % 2 == 1:
                # Crash: uncommitted ops after this point must vanish,
                # committed state must survive byte-exact.
                for _ in range(20):
                    k = _key(rng, space)
                    kv.set(k, b"UNCOMMITTED")
                    model[k] = b"UNCOMMITTED"
                proc.kill()
                fs.crash_machine("kvhost")
                proc.reboot()
                kv = await open_engine(engine, fs, proc, "store")
                model.clear()
                model.update(committed)
                got = kv.read_range(b"", b"\xff", limit=1 << 20)
                assert got == sorted(committed.items()), (
                    f"recovered state diverged after crash "
                    f"(round {round_no})"
                )
        state["done"] = True

    loop.run_until(driver.spawn(run(), "kvtest"), timeout_vt=50000.0)
    assert state["done"]
