"""WAIT001/WAIT002 golden corpus: state held across await.

An EXPECT comment marker pins an UNSUPPRESSED finding on its line; every
other line must stay clean (the negative half of the corpus).  The test
drives the real CLI over this mini scan root with --format=json and
compares exactly."""


class MutableRole:
    def __init__(self):
        self.table = {}
        self.peers = []
        self.frozen = {"a": 1}  # only ever assigned here: config-immutable

    def bump(self, k):
        self.table[k] = self.table.get(k, 0) + 1  # mutation evidence

    def join(self, p):
        self.peers.append(p)  # mutation evidence

    async def stale_capture(self, loop):
        snap = self.table
        await loop.delay(1)
        return snap["k"]  # EXPECT: WAIT001

    async def reread_after_await(self, loop):
        snap = self.table
        await loop.delay(1)
        snap = self.table  # re-read kills the capture
        return snap["k"]  # clean: bound after the await

    async def immutable_snapshot(self, loop):
        cfg = self.frozen  # no mutation evidence anywhere: snapshot
        await loop.delay(1)
        return cfg["a"]  # clean

    async def value_use_is_snapshot(self, loop):
        n = self.table
        await loop.delay(1)
        return report(n)  # clean: value use, not a deref

    async def live_view_any_use(self, loop):
        view = self.table.items()
        await loop.delay(1)
        return report(view)  # EXPECT: WAIT001

    async def iterator_across_await(self, loop):
        it = iter(self.peers)
        await loop.delay(1)
        return next(it)  # EXPECT: WAIT001

    async def genexp_across_await(self, loop):
        gen = (p for p in self.peers)
        await loop.delay(1)
        return list(gen)  # EXPECT: WAIT001

    async def iterate_live_dict(self, loop):
        for k, v in self.table.items():  # EXPECT: WAIT002
            await loop.delay(v)
            self.bump(k)

    async def iterate_snapshot(self, loop):
        for k, v in list(self.table.items()):  # clean: deliberate snapshot
            await loop.delay(v)
            self.bump(k)

    async def iterate_sorted_snapshot(self, loop):
        for p in sorted(self.peers):  # clean: sorted() copies
            await loop.delay(1)
        for p in self.peers:  # clean: no await in this body
            report(p)

    async def nested_async_def(self, loop):
        async def inner():
            snap = self.peers
            await loop.delay(1)
            return snap[0]  # EXPECT: WAIT001

        return inner()

    async def lambda_capture_is_deferred(self, loop):
        cb = lambda: self.table["k"]  # noqa: E731 - deliberate closure
        await loop.delay(1)
        return cb()  # clean: the closure re-reads at call time

    async def comprehension_is_immediate(self, loop):
        await loop.delay(1)
        return [p for p in self.peers]  # clean: iterates NOW, post-await


class PipelinedResolver:
    """ISSUE 11: the overlap state machine's capture discipline.  The
    real pipeline parks an actor across the dispatch await while other
    handlers mutate the in-flight deque and the mirror — a live view (or
    element capture) of either, deref'd after the await, is exactly the
    state-across-wait class; snapshot-then-apply stays clean."""

    def __init__(self):
        self.pipe = []
        self.mirror = {}

    def submit(self, b):
        self.pipe.append(b)  # mutation evidence

    def apply(self, k):
        self.mirror[k] = self.mirror.get(k, 0) + 1  # mutation evidence

    async def snapshot_then_apply(self, loop):
        parked = list(self.pipe)  # deliberate snapshot before suspending
        await loop.delay(1)
        return parked[0]  # clean: the snapshot is ours alone

    async def live_head_across_dispatch(self, loop):
        head = self.pipe[0]
        await loop.delay(1)  # the dispatch await: other handlers ran
        return head.statuses  # EXPECT: WAIT001

    async def reread_head_after_dispatch(self, loop):
        head = self.pipe[0]
        await loop.delay(1)
        head = self.pipe[0]  # re-read after the suspension
        return head.statuses  # clean: bound after the await

    async def drain_live_pipe(self, loop):
        for b in self.pipe:  # EXPECT: WAIT002
            await loop.delay(1)
            self.apply(b)


class EncodeStager:
    """ISSUE 19: the zero-copy batch-encode staging ring.  Encode packs
    the batch blob into a REUSABLE per-length staging buffer; the
    dispatch await parks the actor while the next batch's encode may
    rotate onto the same storage.  Holding one buffer view across that
    await and deref'ing it after is exactly the staging-reuse hazard the
    ring's depth rule (ring length > pipeline depth) exists to prevent —
    the device owns the bytes once dispatch returns, the host must not
    re-read them."""

    def __init__(self):
        self.staging = {}

    def rotate(self, n):
        self.staging[n] = bytearray(n)  # mutation evidence: ring rotates

    async def hold_staging_across_dispatch(self, loop):
        buf = self.staging[4096]
        await loop.delay(1)  # dispatch await: the ring may rotate here
        return buf[0]  # EXPECT: WAIT001

    async def snapshot_blob_before_dispatch(self, loop):
        blob = list(self.staging[4096])  # copy-out before suspending
        await loop.delay(1)
        return blob[0]  # clean: the copy is ours alone

    async def reacquire_after_dispatch(self, loop):
        buf = self.staging[4096]
        buf[0] = 1
        await loop.delay(1)
        buf = self.staging[4096]  # next slot re-acquired post-await
        return buf[0]  # clean: bound after the await


def report(x):
    return x
