"""The registry module itself: FDB_TPU_* reads are legal here."""

import os


def get(name, default=""):
    return os.environ.get(name if name.startswith("FDB_TPU_") else name,
                          default)


def get_mode():
    return os.environ.get("FDB_TPU_MODE", "")  # clean: the registry
