"""ENV001 golden corpus: FDB_TPU_* environment reads outside the
registry module."""

import os


def read_flags():
    a = os.environ.get("FDB_TPU_MODE")  # EXPECT: ENV001
    b = os.getenv("FDB_TPU_LEVEL", "0")  # EXPECT: ENV001
    c = os.environ["FDB_TPU_FORCE"]  # EXPECT: ENV001
    d = os.environ.get("OTHER_PREFIX_FLAG")  # clean: not our namespace
    e = os.environ.get("FDB_TPU_LEGACY")  # fdblint: ignore[ENV001]: migration shim read during the deprecation window
    return a, b, c, d, e
