"""Mini env-flag registry for the ENV002 corpus: one flag with a real
call-time read elsewhere in the scan root, one dead declaration."""


class _Env:
    def declare(self, name, default, help=""):
        pass


g_env = _Env()
g_env.declare("FDB_TPU_CASE_LIVE", "", help="read by server/reader.py")
g_env.declare("FDB_TPU_CASE_DEAD", "", help="never read anywhere")  # EXPECT: ENV002
