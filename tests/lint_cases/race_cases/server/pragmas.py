"""Pragma corpus for the RACE family: one reasoned suppression (appears
as suppressed, not unsuppressed) plus a stale pragma that suppresses
nothing and ages into PRG002."""


class Deliberate:
    def __init__(self):
        self.cursor = 0

    async def advance(self, loop):
        cached = self.cursor
        await loop.delay(0.1)
        self.cursor = cached + 1  # fdblint: ignore[RACE001]: single caller by protocol — the drive loop never overlaps advance calls

    async def clean(self, loop):
        await loop.delay(0.1)
        self.cursor = 7  # fdblint: ignore[RACE001]: stale — nothing here spans an await  # EXPECT: PRG002
