"""RACE001 interprocedural corpus: the RMW's read or write side goes
through a resolvable helper (call-graph may-await summaries)."""


class Spiller:
    def __init__(self):
        self.mem_bytes = 0

    def _load(self):
        return self.mem_bytes

    def _store(self, v):
        self.mem_bytes = v

    async def spill(self, loop):
        v = self._load()
        await loop.delay(0.1)
        self.mem_bytes = v - 100  # EXPECT: RACE001

    async def drain(self, loop):
        v = self.mem_bytes
        await loop.delay(0.1)
        self._store(v)  # EXPECT: RACE001

    async def sync_negative(self):
        v = self._load()
        self._store(v - 100)
