"""RACE003 corpus: two attrs co-written atomically everywhere else get
split across an await in one function (torn invariant)."""


class Ledger:
    def __init__(self):
        self.entries = []
        self.total = 0

    async def credit(self, loop, amount):
        self.entries = self.entries + [amount]
        self.total = self.total + amount

    async def debit(self, loop, amount):
        self.entries = self.entries + [-amount]
        self.total = self.total - amount

    async def torn(self, loop, amount):
        self.entries = self.entries + [amount]
        await loop.delay(0.1)
        self.total = self.total + amount  # EXPECT: RACE003
