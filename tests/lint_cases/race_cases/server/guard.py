"""RACE002 corpus: check-then-act across an await."""


class Registry:
    def __init__(self):
        self.leader = None
        self.version = 0

    async def elect(self, loop, who):
        if self.leader is None:
            await loop.delay(0.1)
            self.leader = who  # EXPECT: RACE002

    async def elect_recheck_negative(self, loop, who):
        if self.leader is None:
            await loop.delay(0.1)
            if self.leader is None:
                self.leader = who

    async def no_guard_negative(self, loop, who):
        await loop.delay(0.1)
        self.leader = who
