"""The live flag's call-time read site (any literal mention counts)."""

from ..flow.knobs import g_env


def backend_choice():
    return g_env.get("FDB_TPU_CASE_LIVE")
