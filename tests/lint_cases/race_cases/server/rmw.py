"""RACE001 corpus: read-modify-write of shared state spanning an await."""


async def fetch(x):
    return x


class Counter:
    def __init__(self):
        self.n = 0
        self.log = []

    async def lost_update(self, loop):
        cached = self.n
        await loop.delay(0.1)
        self.n = cached + 1  # EXPECT: RACE001

    async def direct_span(self, loop):
        self.n = await fetch(self.n)  # EXPECT: RACE001

    async def recheck_negative(self, loop):
        cached = self.n
        await loop.delay(0.1)
        self.n = self.n + 1  # re-read in the write step: sanctioned
        self.log.append(cached)

    async def atomic_negative(self, loop):
        await loop.delay(0.1)
        self.n += 1  # one step: no window

    async def finally_write(self, loop):
        cached = self.n
        try:
            await loop.delay(0.1)
        finally:
            self.n = cached + 1  # EXPECT: RACE001
