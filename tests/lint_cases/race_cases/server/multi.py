"""RACE004 corpus: an attr written by two actor functions with an
await-separated write; plus the lock-free single-writer negative."""


class Shared:
    def __init__(self):
        self.table = ()
        self.owned = ()

    async def rebuild(self, loop):
        size = len(self.table)
        await loop.delay(0.1)
        self.table = tuple(range(size))  # EXPECT: RACE004

    async def install(self, loop, t):
        self.table = t

    async def single_writer_negative(self, loop):
        n = len(self.owned)
        await loop.delay(0.1)
        self.owned = (n,)


class Observer:
    def __init__(self, shared):
        self.shared = shared

    def peek(self):
        return self.shared.owned
