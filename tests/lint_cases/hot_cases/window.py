"""HOT001 corpus: implicit device->host syncs on in-flight dispatch
state, inside and outside the depth-2 dispatch->sync window."""

import numpy as np


class Engine:
    def dispatch_txns(self, txns, now, new_oldest_version):
        return txns

    def sync_ticket(self, ticket):
        # Sanctioned sync point: blocking readbacks are this function's
        # whole job, so the int() below must NOT flag.
        return int(ticket.iters)


def _peek_status(ticket):
    # Depth 2: reached from drive() through the CallGraph — the finding
    # must name the drive -> _peek_status chain.
    return np.asarray(ticket.statuses)  # EXPECT: HOT001


def drive(engine, txns):
    ticket = engine.dispatch_txns(txns, 0, 0)
    n = int(ticket.hcount)  # EXPECT: HOT001
    flags = _peek_status(ticket)
    return engine.sync_ticket(ticket), n, flags


def tally(counts):
    # Untainted int()/len(): no dispatch state involved, must not flag.
    return int(counts.sum()) + len(counts)
