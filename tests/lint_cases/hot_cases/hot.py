"""HOT002/HOT003/HOT004 corpus: declared-bound loops, unstaged
allocations, and per-row scalarization in @hot_path functions."""

import numpy as np


def hot_path(bound="batch"):
    # Local stub: the static pass matches the DECORATOR NAME (it never
    # imports the runtime module), exactly like production code that
    # guards the import.
    def deco(fn):
        return fn
    return deco


@hot_path(bound="batch")
def apply_rows(span):
    total = 0
    for k in span.keys:  # EXPECT: HOT002
        total += 1
    return total


@hot_path(bound="chunks")
def apply_chunks(span):
    touched = 0
    for c in span.chunks:  # chunk iteration is the declared bound: clean
        touched += 1
    return touched


@hot_path(bound="const")
def probe(span):
    for c in span.chunks:  # EXPECT: HOT002
        pass
    for _ in (1, 2, 3):  # literal iteration is O(1): clean
        pass


def undecorated(span):
    # No declared bound: HOT002 does not police undecorated functions.
    for k in span.keys:
        pass


@hot_path(bound="batch")
def build(n):
    return np.zeros(n, np.uint8)  # EXPECT: HOT003


@hot_path(bound="batch")
def scalarize(vals, rows):
    out = vals.tolist()  # EXPECT: HOT004
    acc = 0
    for i in range(len(rows)):  # EXPECT: HOT004
        acc += rows[i]
    return out, acc
