"""Pragma corpus for the HOT family: one reasoned suppression (appears
suppressed, not unsuppressed), one reasonless pragma (PRG001), and one
stale pragma that suppresses nothing (PRG002)."""

import numpy as np


def hot_path(bound="batch"):
    def deco(fn):
        return fn
    return deco


@hot_path(bound="batch")
def staged(n):
    return np.empty(n, np.uint32)  # perfcheck: ignore[HOT003]: retained output buffer returned to the caller; the staging ring cannot serve it


@hot_path(bound="batch")
def reasonless(vals):
    return vals.tolist()  # perfcheck: ignore[HOT004]  # EXPECT: PRG001


@hot_path(bound="batch")
def stale(n):
    return n + 1  # perfcheck: ignore[HOT001]: stale — nothing here syncs device state  # EXPECT: PRG002
