"""Cross-file negative: the consumer side of a producer/consumer promise
pair.  `Handshake.ready` is awaited here and sent ONLY from
server/producer.py — no finding while the producer keeps its send; the
cache-correctness test edits the producer out and the PRM001 finding
must appear HERE, from warm cache, with only the producer re-parsed
(the recovery re-recruit handoff shape: a consumer parked on a promise
another role's file fulfills).
"""

from foundationdb_tpu.flow.future import Promise


class Handshake:
    def __init__(self):
        self.ready = Promise()

    async def wait_ready(self):
        await self.ready.future
