"""PRM002 corpus: promises abandoned on some path without
send/send_error/close — RPY001's broken-promise analysis generalized to
all promises, including the interprocedural handoff shape.
"""

from foundationdb_tpu.flow.future import Promise


def early_return_drop(cond):
    p = Promise()  # EXPECT: PRM002
    if cond:
        return None  # the promise is dropped here
    p.send(1)
    return p.future


def swallowed_except_drop(risky):
    p = Promise()  # EXPECT: PRM002
    try:
        p.send(risky())
    except ValueError:
        return None  # the raise-inside-send path abandons p
    return p.future


def finally_send_is_clean(risky):
    p = Promise()
    try:
        risky()
    finally:
        p.send_error(ValueError("done"))
    return p.future


class Holder:
    def __init__(self):
        self.kept = None

    def stored_for_later_is_clean(self):
        p = Promise()
        self.kept = p  # ownership transferred to the object
        return p.future


def handoff_to_leaky_spawn(loop, req):
    # The promise's ONLY use is handing it into a spawned handler that
    # can itself drop it (return-without-send on the None path).
    p = Promise()
    loop.spawn(leaky_handler(req, p), "handler")  # EXPECT: PRM002
    return None


async def leaky_handler(req, done):
    if req is None:
        return  # drops `done`
    done.send(req)


def handoff_to_clean_spawn(loop, req):
    # Same shape, but the callee resolves on every path — no finding.
    p = Promise()
    loop.spawn(clean_handler(req, p), "handler")
    return None


async def clean_handler(req, done):
    if req is None:
        done.send_error(ValueError("empty"))
        return
    done.send(req)
