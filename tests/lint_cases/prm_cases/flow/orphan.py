"""PRM001 corpus: orphaned waits — futures nothing can ever send to.

Positives park forever; negatives have a sender, an escape (someone we
cannot see may send), or a handoff into a callee that sends.
"""

from foundationdb_tpu.flow.future import Promise


class Forgotten:
    """Creates a promise, awaits it — and NOTHING in the corpus ever
    sends to `.gate` or lets it escape: the static hang."""

    def __init__(self):
        self.gate = Promise()

    async def parked_forever(self):
        await self.gate.future  # EXPECT: PRM001


async def local_orphan():
    p = Promise()
    await p.future  # EXPECT: PRM001


async def escaped_is_unknown(registry):
    # Stored into a container: an unseen holder may send — no finding.
    p = Promise()
    registry.append(p)
    await p.future


async def handoff_to_sender(loop):
    # Handed into a spawned actor that sends on every path — no finding
    # (the call-graph resolves the callee's param to a sender).
    p = Promise()
    loop.spawn(fulfiller(p), "fulfiller")
    await p.future


async def fulfiller(prom):
    prom.send(1)


class StoredForLater:
    """The resolver _ParkedResolve shape (pipeline park/drain): the
    promise is created lazily, the future handed out, and a DIFFERENT
    method sends at completion — no finding on either side."""

    def __init__(self):
        self.parked_done = Promise()

    def future(self):
        return self.parked_done.future

    def mark_finished(self):
        if not self.parked_done.is_set():
            self.parked_done.send(None)

    async def drain_wait(self):
        await self.parked_done.future
