"""PRM004 corpus: consumer loops over streams whose producers can all
terminate without closing them (the pipeline idle-flush/drain shape).
"""

from foundationdb_tpu.flow.future import PromiseStream


class LeakyPipe:
    def __init__(self):
        self.leaky_q = PromiseStream()

    async def consume(self):
        while True:
            item = await self.leaky_q.pop()  # EXPECT: PRM004
            del item

    async def produce(self, items):
        # Terminates after the loop without ever closing the stream: once
        # it finishes, the consumer parks forever.
        for it in items:
            self.leaky_q.send(it)


class ClosingPipe:
    def __init__(self):
        self.closed_q = PromiseStream()

    async def consume(self):
        while True:
            item = await self.closed_q.pop()
            del item

    async def produce(self, items):
        for it in items:
            self.closed_q.send(it)
        # close-in-producer: the consumer observes end-of-stream.
        self.closed_q.send_error(ValueError("end_of_stream"))


class ForeverPipe:
    def __init__(self):
        self.forever_q = PromiseStream()

    async def consume(self):
        while True:
            item = await self.forever_q.pop()
            del item

    async def produce(self, source):
        # The producer itself never terminates (unbroken while True):
        # the consumer can always expect more — no finding.
        while True:
            self.forever_q.send(source())


async def local_stream_loop(items):
    ps = PromiseStream()
    for it in items:
        ps.send(it)
    while True:
        item = await ps.pop()  # EXPECT: PRM004
        del item
