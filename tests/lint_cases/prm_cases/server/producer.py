"""Cross-file negative: the producer side — the only sender of
flow/consumer.py's `Handshake.ready`.  Removing `kick` (the
cache-correctness test does) must surface PRM001 on the consumer side.
"""


def kick(handshake):
    handshake.ready.send(1)
