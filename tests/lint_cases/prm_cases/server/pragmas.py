"""Pragma corpus for the PRM/TSK family: one reasoned suppression per
rule (these appear as suppressed, not unsuppressed, findings) plus a
stale pragma per rule that suppresses nothing and ages into PRG002.
"""

from foundationdb_tpu.flow.future import Promise, PromiseStream


class DeliberatePark:
    def __init__(self):
        self.never_sent = Promise()

    async def parked(self):
        await self.never_sent.future  # fdblint: ignore[PRM001]: corpus — harness fulfills via debug hook


def deliberate_drop(cond):
    p = Promise()  # fdblint: ignore[PRM002]: corpus — probe promise, abandonment is the measured outcome
    if cond:
        return None
    p.send(1)
    return p.future


class DeliberateCycle:
    def __init__(self):
        self.px = Promise()
        self.py = Promise()

    async def first(self):
        await self.py.future  # fdblint: ignore[PRM003]: corpus — lockstep pair driven externally in the harness
        self.px.send(1)

    async def second(self):
        await self.px.future  # fdblint: ignore[PRM003]: corpus — lockstep pair driven externally in the harness
        self.py.send(1)


class DeliberateDrain:
    def __init__(self):
        self.drain_q = PromiseStream()

    async def consume(self):
        while True:
            item = await self.drain_q.pop()  # fdblint: ignore[PRM004]: corpus — consumer cancelled with its role at teardown
            del item

    async def produce(self, items):
        for it in items:
            self.drain_q.send(it)


async def flaky(loop):
    await loop.delay(1)


def fire_and_forget(loop):
    loop.spawn(flaky(loop), "flaky")  # fdblint: ignore[TSK001]: corpus — best-effort prefetch, errors are acceptable


# Stale pragmas: nothing on these lines fires, so each ages into PRG002.
A = 1  # fdblint: ignore[PRM001]: stale  # EXPECT: PRG002
B = 2  # fdblint: ignore[PRM002]: stale  # EXPECT: PRG002
C = 3  # fdblint: ignore[PRM003]: stale  # EXPECT: PRG002
D = 4  # fdblint: ignore[PRM004]: stale  # EXPECT: PRG002
E = 5  # fdblint: ignore[TSK001]: stale  # EXPECT: PRG002
