"""TSK001 corpus: statement-level spawns whose Task is dropped while the
coroutine can raise with neither a handler nor a TraceEvent.
"""

from foundationdb_tpu.flow.trace import TraceEvent


async def fragile(loop):
    await loop.delay(1)  # any await can deliver an FdbError


async def guarded(loop):
    try:
        await loop.delay(1)
    except ValueError:
        return None


async def traced(loop):
    await loop.delay(1)
    TraceEvent("TracedDone").log()


def start_unobserved(loop):
    loop.spawn(fragile(loop), "fragile")  # EXPECT: TSK001


def start_with_handler(loop):
    loop.spawn(guarded(loop), "guarded")


def start_with_trace(loop):
    loop.spawn(traced(loop), "traced")


def start_held(loop):
    # The Task is held: the caller observes the error — no finding.
    t = loop.spawn(fragile(loop), "held")
    return t


def start_observed(process, loop):
    # spawn_observed attaches a death observer by construction.
    process.spawn_observed(fragile(loop), "observed")
