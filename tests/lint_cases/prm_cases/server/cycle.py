"""PRM003 corpus: wait-cycles in the actor wait-graph.

`Deadlocked.first` awaits a future only `Deadlocked.second` sends, and
conversely — an SCC with no external sender.  `Breakable` has the same
internal cycle plus an external sender, so it is live.
"""

from foundationdb_tpu.flow.future import Promise


class Deadlocked:
    def __init__(self):
        self.cx = Promise()
        self.cy = Promise()

    async def first(self):
        await self.cy.future  # EXPECT: PRM003
        self.cx.send(1)

    async def second(self):
        await self.cx.future  # EXPECT: PRM003
        self.cy.send(1)


class Breakable:
    def __init__(self):
        self.lx = Promise()
        self.ly = Promise()

    async def first(self):
        await self.ly.future
        self.lx.send(1)

    async def second(self):
        await self.lx.future
        self.ly.send(1)

    def external_kick(self):
        # An external sender outside the cycle: the recruit/handoff
        # "recovery kicks the parked generation" shape — no finding.
        self.ly.send(0)
