"""SPN001 golden corpus: leaked open spans vs the legitimate shapes.

A `begin_span()` result that is neither context-managed, `.end()`ed,
nor stored never closes — it silently vanishes without ever reaching a
ring (TRC001's span-layer mirror).
"""

from foundationdb_tpu.flow.spans import begin_span
from foundationdb_tpu.flow import spans as spanmod
from foundationdb_tpu.flow.spans import begin_span as start_span


def leaked_bare():
    begin_span("resolve_batch")  # EXPECT: SPN001


def leaked_builder_chain():
    # Detailed but never ended: still a leak.
    begin_span("resolve_batch").annotate("version", 7)  # EXPECT: SPN001


def leaked_module_qualified():
    spanmod.begin_span("dispatch", role="Resolver")  # EXPECT: SPN001


def leaked_aliased():
    start_span("encode")  # EXPECT: SPN001


def leaked_with_pragma():
    begin_span("probe")  # fdblint: ignore[SPN001]: handed to a test harness that ends every open span at teardown


def ok_context_managed():
    with begin_span("encode"):
        pass


def ok_explicit_end():
    begin_span("reply").end()


def ok_end_after_annotate():
    begin_span("reply").annotate("n", 1).end()


def ok_stored_for_later(ctx):
    # Stored: the deferred-end shape (a parked pipeline batch holds its
    # span across awaits and ends it at completion).
    ctx.span = begin_span("device")
    sp = begin_span("sync")
    return sp


def ok_not_a_span(event):
    # Same statement shape, different callee: not ours to police.
    event.begin_edit("x")
