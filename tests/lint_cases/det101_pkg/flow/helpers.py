"""Sim-surface shared helpers: clean-looking, but the bottom frame calls
into the real-mode clockbox.  Every frame of the chain is flagged at its
call site — fixing (or pragma-ing) the one offending edge clears the
cascade on the next run."""

from tools.clockbox import clock_stamp


def shape(x):
    return clock_stamp(x)  # EXPECT: DET101


def prep(x):
    return shape(x)  # EXPECT: DET101


def pure(x):
    return x + 1  # untainted helper: callers stay clean
