"""Real-mode module (tools/ is DET001/DET101-allowlisted): wall reads are
legal HERE, but they still taint any sim-surface caller chain."""

import time


def clock_stamp(x):
    return (x, time.time())  # legal here; the hidden source two frames down


def wall_only():
    # Reachable ONLY from real-mode code (real_prog.main): no finding
    # anywhere — the acceptance criterion's negative half.
    return time.time()
