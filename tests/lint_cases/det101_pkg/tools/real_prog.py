"""Real-mode program: calls the shared helper AND the wall-only helper.
Allowlisted module — no DET101 findings despite reaching wall clocks."""

from flow.helpers import prep
from tools.clockbox import wall_only


def main():
    prep(2)
    return wall_only()
