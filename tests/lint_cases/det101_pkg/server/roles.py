"""Taint through method resolution: the child class never mentions the
helper chain, but its inherited method reaches it."""

from flow.helpers import prep


class Base:
    def helper(self):
        return prep(1)  # EXPECT: DET101


class Child(Base):
    async def run(self, loop):
        await loop.delay(1)
        return self.helper()  # EXPECT: DET101

    def clean(self):
        return 7
