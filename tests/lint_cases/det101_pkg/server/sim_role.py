"""Sim-executed role: the clean-looking helper hides time.time() two
frames down — DET101's acceptance case."""

from flow.helpers import prep, pure


async def run(loop):
    await loop.delay(1)
    return prep(3)  # EXPECT: DET101


def untainted(loop):
    return pure(4)  # clean: the helper never reaches a clock


def sanctioned():
    return prep(5)  # fdblint: ignore[DET101]: test fixture — deliberate wall stamp on a real-mode-only diagnostics path
