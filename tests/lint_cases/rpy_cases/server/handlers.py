"""RPY001 golden corpus: reply-promise path analysis.

Positive cases leak a received reply on at least one path; negative cases
send/error/hand it off on every path (or abandon it by RAISING, which is
the visible teardown path).  EXPECT markers sit on the ACQUISITION line
(param -> the def line, pop-unpack -> that statement)."""


class Handlers:
    async def early_return_leak(self, req, reply):  # EXPECT: RPY001
        if req is None:
            return  # leak: falls out without touching the reply
        reply.send(req)

    async def swallowed_except_leak(self, req, reply):  # EXPECT: RPY001
        try:
            reply.send(compute(req))
        except ValueError:
            return None  # leak: compute may raise before the send

    async def all_paths_send(self, req, reply):
        if req is None:
            reply.send_error("operation_failed")
            return
        try:
            reply.send(compute(req))
        except ValueError:
            reply.send_error("broken_promise")

    async def raise_is_visible(self, req, reply):
        if req is None:
            raise RuntimeError("bad request")  # teardown breaks the reply
        reply.send(req)

    async def handed_to_spawned_actor(self, stream, process):
        while True:
            req, reply = await stream.pop()
            process.spawn(self.early_return_leak(req, reply), "handler")

    async def serve_loop_sends(self, stream):
        while True:
            req, reply = await stream.pop()
            reply.send(req)

    async def serve_loop_drops_on_continue(self, stream):
        while True:
            req, reply = await stream.pop()  # EXPECT: RPY001
            if req is None:
                continue  # leak: next pop rebinds, this reply is dropped
            reply.send(req)

    async def finally_always_answers(self, stream):
        while True:
            req, reply = await stream.pop()
            try:
                check(req)
            finally:
                reply.send(None)

    async def stored_for_later(self, stream, pending):
        while True:
            req, reply = await stream.pop()
            pending.append((req, reply))  # handoff: batcher answers later

    def deferred_closure_handoff(self, req, reply, loop):
        loop.call_later(0.1, lambda: reply.send(req))  # closure owns it


def compute(req):
    return req


def check(req):
    if req is None:
        raise ValueError("nope")
