"""jaxcheck golden corpus: scratch device entry points that each JXP rule
must fire on (positives) and stay silent on (negatives), plus the pragma
cases.  Loaded by tests/test_jaxcheck.py via importlib so pragma parsing
and finding spans run against this REAL file, exactly as they do for the
package's registered entries.  `make_registry()` returns a private
registry — the corpus never pollutes DEVICE_ENTRY_POINTS.
"""

from functools import partial

import jax
import jax.numpy as jnp

from foundationdb_tpu.conflict.engine_jax import register_entry_point

H = 512  # the corpus "history" width (small: traces must stay cheap)
SC = (("H", H),)


def _sds(shape, dtype=jnp.int32):
    return jax.ShapeDtypeStruct(shape, dtype)


# -- JXP001: H-sized work placement -----------------------------------------


def _sort_outside(x):
    return jnp.sort(x)


def _ep_jxp001_pos():
    """Positive: H-sized sort in the steady-state path of a
    compaction-gated entry."""
    return _sort_outside, None, (_sds((H,)),), {}


def _sort_inside_cond(x, flag):
    return jax.lax.cond(flag != 0, lambda v: jnp.sort(v), lambda v: v, x)


def _ep_jxp001_neg():
    """Must-not-flag: the H-sized sort lives inside the compaction cond."""
    return _sort_inside_cond, None, (_sds((H,)), _sds(())), {}


def _double_width(x):
    return jnp.sort(jnp.concatenate([x, x]))


def _ep_jxp001_bound_pos():
    """Positive: a work primitive above the entry's declared width bound
    (the per-shard-code-touching-global-data class)."""
    return _double_width, None, (_sds((H,)),), {}


# -- JXP002: host transfers/callbacks ---------------------------------------


def _callback(x):
    return jax.pure_callback(
        lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x
    )


def _ep_jxp002_pos():
    return _callback, None, (_sds((H,)),), {}


# -- JXP003: donation discipline --------------------------------------------


def _step(state, delta):
    return state + delta


_donating = partial(jax.jit, donate_argnames=("state",))(_step)
_nondonating = jax.jit(_step)
_overdonating = partial(jax.jit, donate_argnames=("state", "delta"))(_step)


def _ep_jxp003_pos():
    """Positive: carried state not donated — the HBM-doubling class (the
    lint_cases-style pin for the grow/rebase burn-down)."""
    return _step, _nondonating, (_sds((H,)), _sds((H,))), {}


def _ep_jxp003_neg():
    return _step, _donating, (_sds((H,)), _sds((H,))), {}


def _ep_jxp003_pinned_pos():
    """Positive: pinned (reused read-only) state donated."""
    return _step, _overdonating, (_sds((H,)), _sds((H,))), {}


def _ep_jxp003_pragma():  # jaxcheck: ignore[JXP003]: corpus: deliberate non-donated carry, reasoned
    return _step, _nondonating, (_sds((H,)), _sds((H,))), {}


def _ep_noreason_pragma():  # jaxcheck: ignore[JXP003]
    return _step, _nondonating, (_sds((H,)), _sds((H,))), {}


def _ep_stale_pragma():  # jaxcheck: ignore[JXP001]: corpus: suppresses nothing and must age into PRG002
    return _step, _donating, (_sds((H,)), _sds((H,))), {}


# -- JXP004: x64 widenings ---------------------------------------------------


def _widen(mask):
    # The pre-burn-down engine idiom: dtype-less index math that silently
    # stays 32-bit by default but doubles under x64.
    return jnp.cumsum(mask) * (jnp.arange(mask.shape[0]) + 1)


def _ep_jxp004_pos():
    return _widen, None, (_sds((H,), jnp.bool_),), {}


def _widen_fixed(mask):
    return jnp.cumsum(mask, dtype=jnp.int32) * (
        jnp.arange(mask.shape[0], dtype=jnp.int32) + 1
    )


def _ep_jxp004_neg():
    return _widen_fixed, None, (_sds((H,), jnp.bool_),), {}


# -- JXP005: shape-bucket table ---------------------------------------------


def _ep_jxp005_pos():
    return _widen_fixed, None, (_sds((100,), jnp.bool_),), {}


def _ep_jxp005_drift_pos():
    """Positive: a bucket-aligned declaration the traced signature no
    longer contains (registry drifted from the real program)."""
    return _widen_fixed, None, (_sds((H,), jnp.bool_),), {}


def make_registry():
    reg = {}

    def add(name, builder, **meta):
        meta.setdefault("size_classes", SC)
        meta.setdefault("h_threshold", H)
        register_entry_point(name, builder, registry=reg, **meta)

    add("jxp001_pos", _ep_jxp001_pos, arg_names=("x",),
        compaction_gated=True)
    add("jxp001_neg", _ep_jxp001_neg, arg_names=("x", "flag"),
        compaction_gated=True)
    add("jxp001_bound_pos", _ep_jxp001_bound_pos, arg_names=("x",),
        work_bound=H)
    add("jxp002_pos", _ep_jxp002_pos, arg_names=("x",))
    add("jxp003_pos", _ep_jxp003_pos, arg_names=("state", "delta"),
        carried=("state",))
    add("jxp003_neg", _ep_jxp003_neg, arg_names=("state", "delta"),
        carried=("state",), pinned=("delta",))
    add("jxp003_pinned_pos", _ep_jxp003_pinned_pos,
        arg_names=("state", "delta"), carried=("state",),
        pinned=("delta",))
    add("jxp003_pragma", _ep_jxp003_pragma, arg_names=("state", "delta"),
        carried=("state",))
    add("noreason_pragma", _ep_noreason_pragma,
        arg_names=("state", "delta"), carried=("state",))
    add("stale_pragma", _ep_stale_pragma, arg_names=("state", "delta"),
        carried=("state",))
    add("jxp004_pos", _ep_jxp004_pos, arg_names=("mask",))
    add("jxp004_neg", _ep_jxp004_neg, arg_names=("mask",))
    add("jxp005_pos", _ep_jxp005_pos, arg_names=("mask",),
        bucket_dims={"h_cap": (100, 64)})
    add("jxp005_drift_pos", _ep_jxp005_drift_pos, arg_names=("mask",),
        bucket_dims={"h_cap": (1024, 64)})
    return reg
