"""Multi-proxy commit pipeline: several proxies on one sequencer chain.

Ref: MasterProxyServer.actor.cpp commitBatch with multiple proxies on the
master's prevVersion chain, Resolver.actor.cpp per-proxy ordering + reply
cache + state-transaction retention (:104-190), NativeAPI commit_unknown_
result resolution via a self-conflicting dummy transaction (:2430-2449).
"""

import pytest

from foundationdb_tpu.flow import FdbError, set_event_loop
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.dynamic_cluster import DynamicCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def run_cycle(c, n_clients=4, ops=25, n=8, timeout_vt=5000.0):
    """Cycle workload (ref Cycle.actor.cpp) against cluster `c`; returns the
    final ring read back."""
    db_init = c.database()

    async def init(tr):
        for i in range(n):
            tr.set(b"cycle/%03d" % i, b"%03d" % ((i + 1) % n))

    c.run_all([(db_init, db_init.run(init))], timeout_vt=timeout_vt)

    dbs = [c.database() for _ in range(n_clients)]
    done = []

    def worker(db, wid):
        async def go():
            rng = c.loop.rng
            for _ in range(ops):

                async def op(tr):
                    a = int(rng.random_int(0, n))
                    ka = b"cycle/%03d" % a
                    b = int((await tr.get(ka)).decode())
                    kb = b"cycle/%03d" % b
                    cc = int((await tr.get(kb)).decode())
                    kc = b"cycle/%03d" % cc
                    d = int((await tr.get(kc)).decode())
                    tr.set(ka, b"%03d" % cc)
                    tr.set(kc, b"%03d" % b)
                    tr.set(kb, b"%03d" % d)

                await db.run(op)
            done.append(wid)

        return go()

    c.run_all(
        [(db, worker(db, i)) for i, db in enumerate(dbs)],
        timeout_vt=timeout_vt,
    )
    assert len(done) == n_clients

    out = {}

    async def check(tr):
        out["ring"] = await tr.get_range(b"cycle/", b"cycle0")

    c.run_all([(db_init, db_init.run(check))], timeout_vt=timeout_vt)
    return {k: int(v.decode()) for k, v in out["ring"]}


def assert_ring_ok(ring, n=8):
    assert len(ring) == n
    seen, cur = set(), 0
    for _ in range(n):
        assert cur not in seen
        seen.add(cur)
        cur = ring[b"cycle/%03d" % cur]
    assert cur == 0 and len(seen) == n


def test_cycle_two_proxies():
    """Serializable isolation holds when commits interleave through two
    proxies sharing the sequencer's version chain."""
    c = SimCluster(seed=71, n_proxies=2)
    ring = run_cycle(c)
    assert_ring_ok(ring)
    # Both proxies actually carried commits (round-robin clients).
    batches = [p.stats["batches"] for p in c.proxies]
    assert all(b > 0 for b in batches), batches


def test_cycle_two_proxies_two_resolvers():
    c = SimCluster(seed=72, n_proxies=2, n_resolvers=2)
    ring = run_cycle(c)
    assert_ring_ok(ring)


def test_causal_consistency_across_proxies():
    """A read-version request through proxy B must reflect a commit acked
    through proxy A (the sequencer committed-watermark floor; ref: GRV
    confirming other proxies' committed versions)."""
    c = SimCluster(seed=73, n_proxies=2)
    writer, reader = c.database(), c.database()
    # Skew the round-robin so writer and reader prefer different proxies.
    reader._proxy_rr = {"grv": 1, "commit": 1}
    failures = []

    async def go():
        for i in range(20):

            async def w(tr):
                tr.set(b"causal", b"%d" % i)

            await writer.run(w)

            async def r(tr):
                v = await tr.get(b"causal")
                if v is None or int(v.decode()) < i:
                    failures.append((i, v))

            await reader.run(r)

    c.run_all([(writer, go())], timeout_vt=5000.0)
    assert not failures, failures


def test_metadata_propagates_across_proxies():
    """A shard move committed through one proxy must update EVERY proxy's
    routing/tag map (resolver state-transaction retention): writes tagged by
    the other proxy reach the destination storage too."""
    c = SimCluster(seed=74, n_proxies=2, n_storages=2)
    db = c.database()

    async def fill(tr):
        for i in range(40):
            tr.set(b"m%03d" % i, b"v%d" % i)

    c.run_all([(db, db.run(fill))])
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"m020")
        await dd.move(b"m020", ["ss1"])

    c.run_until(db.process.spawn(place()), timeout_vt=5000.0)

    # Write through BOTH proxies after the move; every write to m02x-m03x
    # must land on ss1 (the new owner), regardless of which proxy tags it.
    dbs = [c.database() for _ in range(2)]
    dbs[1]._proxy_rr = {"grv": 1, "commit": 1}

    def writer(db, base):
        async def go():
            for i in range(base, base + 10):

                async def w(tr):
                    tr.set(b"m%03d" % (20 + i % 20), b"w%d" % i)

                await db.run(w)

        return go()

    c.run_all([(d, writer(d, i * 10)) for i, d in enumerate(dbs)], timeout_vt=5000.0)

    # Both proxies' maps agree on the moved range.
    for p in c.proxies:
        route, _tags = p.key_servers[b"m025"]
        assert route == ("ss1",), (p.proxy_id, route)

    # And the data is readable (routed to ss1).
    out = {}

    async def check(tr):
        out["rows"] = await tr.get_range(b"m020", b"m040")

    c.run_all([(db, db.run(check))])
    assert len(out["rows"]) == 20


@pytest.mark.parametrize(
    "seed,ops,kill_after", [(75, 30, 8), (76, 40, 15)]
)
def test_dynamic_two_proxies_survives_proxy_kill(seed, ops, kill_after):
    """Kill one of two proxies mid-workload (after `kill_after` completed
    ops, so commits are in flight): generation recovery replaces both;
    in-flight commits resolve as commit_unknown_result and the client's
    dummy-transaction fence keeps the retry loop serializable."""
    c = DynamicCluster(seed=seed, n_workers=6, n_proxies=2)
    db = c.database()
    done = []

    async def workload():
        for i in range(ops):

            async def op(tr, i=i):
                v = await tr.get(b"count")
                n = int(v.decode()) if v else 0
                tr.set(b"count", b"%d" % (n + 1))
                # Idempotent marker keyed by the CLIENT's op id: retries of
                # an unknown-result commit rewrite the same key (the
                # reference's documented idempotence discipline for
                # commit_unknown_result retry loops, NativeAPI:2446-2448).
                tr.set(b"audit/%03d" % i, b"x")

            await db.run(op)
            done.append(i)

    async def chaos():
        while len(done) < kill_after:
            await c.loop.delay(0.01)
        c.kill_role_process("proxy1")

    c.run_all([(db, workload()), (db, chaos())], timeout_vt=8000.0)

    # Every op's idempotent marker exists exactly once; the counter saw at
    # least one increment per op (a commit_unknown_result whose original
    # DID commit legitimately double-increments on retry — serializability,
    # not exactly-once, is the commit contract; ref NativeAPI:2446-2448).
    out = {}

    async def check(tr):
        v = await tr.get(b"count")
        rows = await tr.get_range(b"audit/", b"audit0")
        out["count"] = int(v.decode())
        out["audit"] = len(rows)

    c.run_all([(db, db.run(check))], timeout_vt=5000.0)
    assert out["audit"] == ops
    assert out["count"] >= ops
