"""Crash-durability tests for the simulated file stack.

Strategy follows the reference (SURVEY.md §4): commit through the public
API, kill the machine (which resolves unsynced writes per the NonDurable
corruption model), reboot, recover, and assert the prefix-durability
contract.  Seeds are swept so drop/torn/corrupt paths all fire.
"""

import pytest

from foundationdb_tpu.fileio import DiskQueue, KeyValueStoreMemory, KillMode, SimFileSystem
from foundationdb_tpu.flow import EventLoop, set_event_loop
from foundationdb_tpu.rpc import SimNetwork


def make_env(seed, kill_mode=KillMode.FULL_CORRUPTION):
    loop = EventLoop(seed=seed)
    set_event_loop(loop)
    net = SimNetwork(loop)
    fs = SimFileSystem(net, kill_mode=kill_mode)
    return loop, net, fs


def drive(loop, proc, coro):
    return loop.run_until(proc.spawn(coro), timeout_vt=100.0)


@pytest.mark.parametrize("seed", range(8))
def test_diskqueue_prefix_durability(seed):
    loop, net, fs = make_env(seed)
    proc = net.process("node")
    state = {}

    async def writer():
        q, rec = await DiskQueue.open(fs, proc, "queue.dq")
        assert rec == []
        committed = []
        seq = 0
        for round_ in range(5):
            for _ in range(loop.rng.random_int(1, 4)):
                seq += 1
                q.push(seq, b"payload-%d" % seq * loop.rng.random_int(1, 9))
            await q.commit()
            committed.append(seq)
        # Push some records that are never committed.
        for _ in range(loop.rng.random_int(0, 3)):
            seq += 1
            q.push(seq, b"uncommitted-%d" % seq)
        state["committed_through"] = committed[-1]
        state["pushed_through"] = seq

    drive(loop, proc, writer())
    proc.kill()
    fs.crash_machine("node")
    proc.reboot()

    async def recover():
        _q, rec = await DiskQueue.open(fs, proc, "queue.dq")
        state["recovered"] = rec

    drive(loop, proc, recover())
    rec = state["recovered"]
    seqs = [s for s, _ in rec]
    # Prefix: contiguous from 1, contains at least everything committed.
    assert seqs == list(range(1, len(seqs) + 1))
    assert len(seqs) >= state["committed_through"]
    assert len(seqs) <= state["pushed_through"]
    # Committed payloads intact (never corrupted).
    for s, payload in rec:
        if s <= state["committed_through"]:
            assert payload.startswith(b"payload-")
    set_event_loop(None)


@pytest.mark.parametrize("seed", range(8))
def test_kvstore_memory_recovers_committed_state(seed):
    loop, net, fs = make_env(seed)
    proc = net.process("node")
    state = {}

    async def writer():
        kv = await KeyValueStoreMemory.open(fs, proc, "store.dq")
        committed = {}
        for round_ in range(6):
            for _ in range(loop.rng.random_int(1, 5)):
                k = b"k%d" % loop.rng.random_int(0, 20)
                if loop.rng.random01() < 0.25:
                    e = b"k%d" % loop.rng.random_int(0, 30)
                    b, e = min(k, e), max(k, e)
                    kv.clear_range(b, e)
                    for kk in [x for x in committed if b <= x < e]:
                        del committed[kk]
                else:
                    v = b"v%d-%d" % (round_, loop.rng.random_int(0, 1000))
                    kv.set(k, v)
                    committed[k] = v
            await kv.commit()
        # Uncommitted tail: must NOT survive.
        kv.set(b"uncommitted", b"x")
        state["committed"] = dict(committed)

    drive(loop, proc, writer())
    proc.kill()
    fs.crash_machine("node")
    proc.reboot()

    async def recover():
        kv = await KeyValueStoreMemory.open(fs, proc, "store.dq")
        state["recovered"] = dict(kv.read_range(b"", b"\xff"))

    drive(loop, proc, recover())
    assert state["recovered"] == state["committed"]
    set_event_loop(None)


def test_kvstore_snapshot_compaction():
    loop, net, fs = make_env(3)
    proc = net.process("node")
    state = {}

    async def writer():
        kv = await KeyValueStoreMemory.open(fs, proc, "store.dq")
        kv.SNAPSHOT_EVERY_BYTES = 256  # force frequent snapshots
        for i in range(30):
            kv.set(b"key%02d" % (i % 7), b"val%d" % i)
            await kv.commit()
        state["popped"] = kv._q.popped_seq
        state["final"] = dict(kv.read_range(b"", b"\xff"))

    drive(loop, proc, writer())
    assert state["popped"] > 0  # snapshots actually popped the log

    async def recover():
        kv = await KeyValueStoreMemory.open(fs, proc, "store.dq")
        state["recovered"] = dict(kv.read_range(b"", b"\xff"))

    drive(loop, proc, recover())
    assert state["recovered"] == state["final"]
    set_event_loop(None)


def test_sync_makes_writes_survive_full_corruption():
    """Synced data survives any kill mode; unsynced may not."""
    loop, net, fs = make_env(5)
    proc = net.process("node")

    async def writer():
        f = fs.open(proc, "raw.bin")
        await f.write(0, b"A" * 100)
        await f.sync()
        await f.write(100, b"B" * 100)  # unsynced

    drive(loop, proc, writer())
    proc.kill()
    fs.crash_machine("node")
    proc.reboot()

    async def reader():
        f = fs.open(proc, "raw.bin")
        return await f.read(0, 200)

    data = drive(loop, proc, reader())
    assert data[:100] == b"A" * 100
    set_event_loop(None)


@pytest.mark.parametrize("seed", range(4))
def test_diskqueue_concurrent_commits_serialize(seed):
    """Regression (code-review r2): two actors committing the same DiskQueue
    concurrently must not clobber each other's frames — after a crash, every
    acked record from BOTH actors must be recovered."""
    loop, net, fs = make_env(seed)
    proc = net.process("node")
    state = {"acked": set()}

    async def run():
        q, rec = await DiskQueue.open(fs, proc, "cq.dq")
        assert rec == []

        async def committer(base):
            for i in range(6):
                seq = base + i
                q.push(seq, b"actor%d-%d" % (base, seq) * 3)
                await q.commit()
                state["acked"].add(seq)

        from foundationdb_tpu.flow.eventloop import all_of

        await all_of(
            [
                proc.spawn(committer(100)),
                proc.spawn(committer(200)),
                proc.spawn(committer(300)),
            ]
        )

    drive(loop, proc, run())
    proc.kill()
    fs.crash_machine(proc.machine.machine_id)
    proc.reboot()

    async def recover():
        q, rec = await DiskQueue.open(fs, proc, "cq.dq")
        got = {seq for seq, _ in rec}
        missing = state["acked"] - got
        assert not missing, f"acked records lost: {sorted(missing)}"

    drive(loop, proc, recover())
    set_event_loop(None)
