"""Tuple/subspace/directory layers.

Ref: bindings/python/fdb tuple.py (ordering + round-trip properties, the
binding tester's core checks), subspace_impl.py, directory_impl.py (node
tree + HighContentionAllocator under concurrency).
"""

import uuid

import pytest

from foundationdb_tpu.flow import FdbError, set_event_loop
from foundationdb_tpu.layers import (
    DirectoryLayer,
    Subspace,
    Versionstamp,
    pack,
    unpack,
)
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


SAMPLES = [
    (),
    (None,),
    (b"",),
    (b"foo", b"b\x00ar"),
    ("unicode ☃", ""),
    (0, 1, -1, 255, 256, -255, -256, 2**63 - 1, -(2**63) + 1),
    (1.5, -1.5, 0.0, 3.141592653589793),
    (True, False),
    (uuid.UUID(int=0x1234567890ABCDEF1234567890ABCDEF),),
    ((b"nested", (1, None), ()), 2),
    (Versionstamp(b"\x01" * 10, 7),),
]


def test_tuple_roundtrip():
    for t in SAMPLES:
        assert unpack(pack(t)) == t, t


def test_tuple_ordering_matches_bytes():
    """pack preserves element-wise order (the layer's defining property)."""
    vals = [
        (0,), (1,), (255,), (256,), (-1,), (-256,),
        (b"a",), (b"a\x00",), (b"b",),
        ("a",), ("b",),
        (1.0,), (-2.5,), (2.5,),
        (False,), (True,),
        ((1,),), ((1, 2),), ((2,),),
    ]
    import itertools

    for a, b in itertools.combinations(vals, 2):
        if type(a[0]) is not type(b[0]):
            continue
        expect = (a < b)
        assert (pack(a) < pack(b)) == expect, (a, b)


def test_subspace():
    s = Subspace(("app", 1))
    key = s.pack((b"k", 2))
    assert s.contains(key)
    assert s.unpack(key) == (b"k", 2)
    nested = s[b"sub"]
    assert nested.raw_prefix.startswith(s.raw_prefix)
    b, e = s.range()
    assert b < nested.pack((1,)) < e


def test_directory_create_open_list_move_remove():
    c = SimCluster(seed=110)
    db = c.database()
    d = DirectoryLayer()
    out = {}

    async def go(tr):
        app = await d.create_or_open(tr, ("app",))
        users = await d.create_or_open(tr, ("app", "users"))
        tr.set(users.pack((b"alice",)), b"1")
        out["app"] = app
        out["users"] = users

    c.run_all([(db, db.run(go))])
    assert out["users"].raw_prefix != out["app"].raw_prefix

    async def check(tr):
        again = await d.open(tr, ("app", "users"))
        out["again"] = again
        out["alice"] = await tr.get(again.pack((b"alice",)))
        out["ls_root"] = await d.list(tr, ())
        out["ls_app"] = await d.list(tr, ("app",))
        with pytest.raises(FdbError, match="directory_already_exists"):
            await d.create(tr, ("app", "users"))
        with pytest.raises(FdbError, match="directory_does_not_exist"):
            await d.open(tr, ("app", "nope"))

    c.run_all([(db, db.run(check))])
    assert out["again"].raw_prefix == out["users"].raw_prefix
    assert out["alice"] == b"1"
    assert out["ls_root"] == ["app"]
    assert out["ls_app"] == ["users"]

    async def mv(tr):
        moved = await d.move(tr, ("app", "users"), ("app", "members"))
        out["moved"] = moved

    c.run_all([(db, db.run(mv))])
    assert out["moved"].raw_prefix == out["users"].raw_prefix

    async def after_mv(tr):
        out["ls_after"] = await d.list(tr, ("app",))
        m = await d.open(tr, ("app", "members"))
        out["alice2"] = await tr.get(m.pack((b"alice",)))

    c.run_all([(db, db.run(after_mv))])
    assert out["ls_after"] == ["members"]
    assert out["alice2"] == b"1"

    async def rm(tr):
        out["removed"] = await d.remove(tr, ("app",))

    c.run_all([(db, db.run(rm))])

    async def gone(tr):
        out["exists"] = await d.exists(tr, ("app",))
        out["data"] = await tr.get(out["users"].pack((b"alice",)))

    c.run_all([(db, db.run(gone))])
    assert out["removed"] is True
    assert out["exists"] is False
    assert out["data"] is None


def test_hca_concurrent_allocations_unique():
    """Many clients allocating directories concurrently must get unique
    prefixes (the HighContentionAllocator's whole point)."""
    c = SimCluster(seed=111)
    d = DirectoryLayer()
    dbs = [c.database() for _ in range(6)]
    results = []

    def worker(db, wid):
        async def go():
            for i in range(4):

                async def op(tr, i=i):
                    sub = await d.create_or_open(
                        tr, ("w%d" % wid, "d%d" % i)
                    )
                    return sub.raw_prefix

                results.append(await db.run(op))

        return go()

    c.run_all(
        [(db, worker(db, i)) for i, db in enumerate(dbs)], timeout_vt=5000.0
    )
    assert len(results) == 24
    assert len(set(results)) == 24  # all prefixes distinct
    # No prefix is a prefix of another (directories must not nest by
    # accident).
    for a in results:
        for b in results:
            if a is not b:
                assert not b.startswith(a) or a == b
