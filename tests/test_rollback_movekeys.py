"""Rollback + RandomMoveKeys chaos workloads.

Ref: fdbserver/workloads/Rollback.actor.cpp (partial-durability partition
forcing version rollback through recovery) and RandomMoveKeys.actor.cpp
(shard moves racing live load).
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.workloads import (
    ConsistencyChecker,
    CycleWorkload,
    RandomMoveKeysWorkload,
    RollbackWorkload,
    run_workloads,
)


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


@pytest.mark.parametrize("seed", [8101, 8102, 8103])
def test_rollback_partition_recovers(seed):
    """Clog proxy<->tlogs mid-commit; the recovery must roll back
    non-quorum-durable versions and lose no acked commit (cycle ring
    invariant + consistency check)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=seed, n_workers=7, n_tlogs=2, n_storages=2)
    wl = RollbackWorkload(rounds=1, clog_duration=2.0, delay_between=1.0)
    run_workloads(
        c,
        [
            CycleWorkload(nodes=6, ops=12, actors=2),
            wl,
            ConsistencyChecker(require_comparisons=True),
        ],
        timeout_vt=40000.0,
    )
    assert wl.triggered >= 1


@pytest.mark.parametrize("seed", [8201, 8202])
def test_random_move_keys_under_load(seed):
    c = SimCluster(seed=seed, n_storages=3, n_proxies=2)
    wl = RandomMoveKeysWorkload(moves=4)
    run_workloads(
        c,
        [CycleWorkload(nodes=8, ops=20, actors=2), wl],
        timeout_vt=40000.0,
    )
    assert wl.performed >= 1
