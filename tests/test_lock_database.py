"""Database lock (lockDatabase/unlockDatabase + proxy enforcement).

Ref: fdbclient/ManagementAPI.actor.cpp:1241-1334, databaseLockedKey in
SystemData.cpp, commitBatch/GRV lock checks, and the lock surviving
recovery through the txnStateStore.
"""

import pytest

from foundationdb_tpu.client import management as mgmt
from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.error import FdbError
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_lock_blocks_commits_and_grvs_until_unlock():
    c = SimCluster(seed=840, n_proxies=2)
    db = c.database("lk")
    out = {}

    async def flow():
        tr = db.create_transaction()
        tr.set(b"pre", b"1")
        await tr.commit()

        uid = await mgmt.lock_database(db)
        out["uid"] = uid

        # Lock state reaches the OTHER proxies through the resolvers'
        # state-transaction exchange (one batch of lag, as in the
        # reference's txnStateStore propagation); enforcement is asserted
        # after every proxy has applied it.
        for _ in range(200):
            if all(p.locked_uid == uid for p in c.proxies):
                break
            await c.loop.delay(0.05)
        assert all(p.locked_uid == uid for p in c.proxies)

        # Non-lock-aware commit: database_locked (no silent retry here —
        # an explicit transaction surfaces the raw error).
        tr2 = db.create_transaction()
        tr2.set(b"blocked", b"x")
        try:
            await tr2.commit()
            out["commit"] = "accepted"
        except FdbError as e:
            out["commit"] = e.name

        # Non-lock-aware GRV: database_locked too.
        tr3 = db.create_transaction()
        try:
            await tr3.get_read_version()
            out["grv"] = "accepted"
        except FdbError as e:
            out["grv"] = e.name

        # Lock-aware work proceeds.
        tr4 = db.create_transaction()
        tr4.options["lock_aware"] = True
        assert await tr4.get(b"pre") == b"1"
        tr4.set(b"aware", b"ok")
        await tr4.commit()

        # Wrong-uid lock attempt surfaces database_locked.
        try:
            await mgmt.lock_database(db, uid=b"someone-else")
            out["relock"] = "accepted"
        except FdbError as e:
            out["relock"] = e.name

        await mgmt.unlock_database(db, uid)

        # Unlock propagates to the OTHER proxies via the resolver's
        # state-transaction exchange; database_locked is client-retryable
        # exactly so this window is transparent under db.run.
        async def post(tr):
            tr.set(b"post", b"2")

        await db.run(post)

        async def read(tr):
            out["post"] = await tr.get(b"post")

        await db.run(read)
        return True

    assert c.run_until(db.process.spawn(flow()), timeout_vt=5000.0)
    assert out["commit"] == "database_locked"
    assert out["grv"] == "database_locked"
    assert out["relock"] == "database_locked"
    assert out["post"] == b"2"


def test_lock_survives_recovery():
    """A generation change must not drop the lock: the CC re-injects it
    from storage with the routing map (the txnStateStore analog)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=841, n_workers=6)
    db = c.database()
    out = {}

    async def setup(tr):
        tr.set(b"pre", b"1")

    c.run_all([(db, db.run(setup))], timeout_vt=1000.0)

    async def lock():
        out["uid"] = await mgmt.lock_database(db)

    c.run_until(db.process.spawn(lock()), timeout_vt=1000.0)

    # Force a new generation.
    c.kill_role_process("proxy0")

    async def after():
        # Wait for the new generation to serve lock-aware work, then
        # verify the lock still blocks plain commits.
        tr = db.create_transaction()
        tr.options["lock_aware"] = True
        for _ in range(200):
            try:
                await tr.get_read_version()
                break
            except FdbError:
                tr.reset()
                await c.loop.delay(0.2)
        tr.set(b"aware2", b"ok")
        await tr.commit()

        tr2 = db.create_transaction()
        tr2.set(b"blocked2", b"x")
        try:
            await tr2.commit()
            out["commit"] = "accepted"
        except FdbError as e:
            out["commit"] = e.name
        await mgmt.unlock_database(db, out["uid"])

        async def post(tr):
            tr.set(b"post", b"2")

        await db.run(post)
        return True

    assert c.run_until(db.process.spawn(after()), timeout_vt=10000.0)
    assert out["commit"] == "database_locked"


def test_cli_lock_unlock():
    from foundationdb_tpu.tools.cli import CliProcessor

    c = SimCluster(seed=842, n_proxies=1)
    db = c.database("lk2")
    cli = CliProcessor(c, db)

    def run(line):
        return c.run_until(
            db.process.spawn(cli.run_command(line)), timeout_vt=3000.0
        )

    out = run("lock")
    assert out[0].startswith("Database locked")
    out = run("get pre")  # plain reads need a GRV -> database_locked
    assert "database_locked" in out[0]
    out = run("unlock")
    assert out[0] == "Database unlocked"
    run("writemode on")
    out = run("set back v")
    assert "ERROR" not in (out[0] if out else ""), out
