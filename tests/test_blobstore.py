"""BlobStore endpoint + HTTP client + blobstore:// backup containers.

Ref: fdbrpc/BlobStore.h:34 (BlobStoreEndpoint with rate knobs),
fdbrpc/HTTP.actor.cpp (hand-rolled HTTP/1.1), BackupContainer.actor.cpp
(the blobstore container flavor).  Real sockets on localhost, like the
real-transport suite.
"""

import time

import pytest

from foundationdb_tpu.fileio.blobstore import (
    BlobStoreEndpoint,
    BlobStoreServer,
    TokenBucket,
    build_response,
    parse_request,
)
from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.error import FdbError


@pytest.fixture
def server():
    s = BlobStoreServer()
    yield s
    s.close()


def test_http_codec_roundtrip():
    raw = (
        b"PUT /b/o HTTP/1.1\r\nHost: x\r\nContent-Length: 5\r\n\r\nhello"
        b"GET /b/o HTTP/1.1\r\nContent-Length: 0\r\n\r\n"
    )
    m, p, h, body, used = parse_request(raw)
    assert (m, p, body) == ("PUT", "/b/o", b"hello") and h["host"] == "x"
    m2, p2, _h2, body2, _ = parse_request(raw[used:])
    assert (m2, p2, body2) == ("GET", "/b/o", b"")
    assert parse_request(raw[:10]) is None  # incomplete
    resp = build_response(404, b"nope")
    assert resp.startswith(b"HTTP/1.1 404") and resp.endswith(b"nope")


def test_endpoint_crud_and_listing(server):
    ep = BlobStoreEndpoint.from_url(server.url)
    big = bytes(range(256)) * 4096  # 1 MiB
    ep.put_object("pages/p1", b"alpha")
    ep.put_object("pages/p2", big)
    ep.put_object("manifest", b"{}")
    assert ep.get_object("pages/p1") == b"alpha"
    assert ep.get_object("pages/p2") == big
    assert ep.list_objects("pages/") == ["pages/p1", "pages/p2"]
    assert ep.list_objects() == ["manifest", "pages/p1", "pages/p2"]
    assert ep.object_exists("manifest")
    ep.delete_object("pages/p1")
    assert not ep.object_exists("pages/p1")
    with pytest.raises(FdbError, match="file_not_found"):
        ep.get_object("pages/p1")
    ep.close()


def test_endpoint_url_knobs():
    ep = BlobStoreEndpoint.from_url(
        "blobstore://10.0.0.1:9000/bkt?requests_per_second=55"
        "&read_bytes_per_second=1000000&retries=7"
    )
    assert (ep.host, ep.port, ep.bucket) == ("10.0.0.1", 9000, "bkt")
    assert ep.req_bucket.rate == 55.0
    assert ep.read_bucket.rate == 1000000.0
    assert ep.retries == 7


def test_token_bucket_paces_requests():
    tb = TokenBucket(rate=200.0, burst=1.0)
    t0 = time.monotonic()
    for _ in range(21):
        tb.acquire()
    dt = time.monotonic() - t0
    # 20 refills at 200/s = 100ms minimum (generous upper bound for a
    # loaded host).
    assert dt >= 0.08, dt


def test_token_bucket_injectable_clock_is_deterministic():
    """The clock/sleep hooks exist so pacing can be tested against fake
    time (no wall-clock dependence; the DET001 pragma rationale)."""
    fake = [0.0]
    slept = []

    def sleep(s):
        slept.append(s)
        fake[0] += s

    tb = TokenBucket(rate=10.0, burst=1.0, clock=lambda: fake[0], sleep=sleep)
    for _ in range(3):
        tb.acquire(1.0)
    # First acquire spends the burst; the next two each wait exactly 0.1
    # fake-seconds at 10 tokens/s, in 0.05 sleep slices (modulo float
    # rounding in the refill arithmetic).
    assert len(slept) == 4
    assert all(abs(s - 0.05) < 1e-9 for s in slept)
    assert abs(sum(slept) - 0.2) < 1e-9


def test_retry_backoff_sleep_is_injectable():
    """_backoff_sleep is the wall binding for retry pacing; stubbing it
    runs the whole bounded retry chain instantly."""
    ep = BlobStoreEndpoint("127.0.0.1", 1, "b", retries=3)  # nothing listens
    backoffs = []
    ep._backoff_sleep = backoffs.append
    with pytest.raises(FdbError, match="connection_failed"):
        ep.put_object("x", b"1")
    # One backoff per failed attempt (retries + 1 attempts), doubling and
    # capped at 2s.
    assert backoffs == [0.1, 0.2, 0.4, 0.8]
    ep.close()


def test_endpoint_reconnects_after_connection_loss(server):
    """Keep-alive breakage mid-session: the retry loop must transparently
    reconnect (ref: BlobStoreEndpoint::doRequest's reconnect-on-error)."""
    ep = BlobStoreEndpoint.from_url(server.url)
    ep.put_object("x", b"1")
    server.kick_connections()
    ep.put_object("y", b"2")  # must survive the dead keep-alive socket
    assert ep.get_object("y") == b"2"
    assert ep.get_object("x") == b"1"
    ep.close()


def test_snapshot_backup_to_blobstore_and_restore(server):
    """End-to-end: dump a SimCluster keyspace into the blob store through
    the agent's container factory, wipe, restore, verify — the reference's
    backup-to-S3 path shape."""
    from foundationdb_tpu.layers.backup import FileBackupAgent
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=820, n_proxies=1)
    db = c.database("bk")

    async def fill(tr):
        for i in range(120):
            tr.set(b"bs%03d" % i, b"val%d" % i)

    c.run_all([(db, db.run(fill))], timeout_vt=2000.0)

    agent = FileBackupAgent(db, c.fs)
    container = agent.container(server.url + "/snap1")

    async def run_backup():
        await agent.submit_backup(container, begin=b"bs", end=b"bt")
        await agent.executor(c.database()).run(until_empty=True)
        return await container.read_manifest()

    manifest = c.run_until(db.process.spawn(run_backup()), timeout_vt=5000.0)
    assert manifest is not None and manifest["pages"] >= 1
    # Pages physically live in the object store.
    assert any(
        n.startswith("snap1/range-") for (_b, n) in server.objects
    ), sorted(server.objects)

    async def wipe(tr):
        tr.clear_range(b"bs", b"bt")

    c.run_all([(db, db.run(wipe))], timeout_vt=2000.0)

    async def run_restore():
        await agent.restore(container)
        return True

    assert c.run_until(db.process.spawn(run_restore()), timeout_vt=5000.0)
    out = {}

    async def check(tr):
        out["rows"] = await tr.get_range(b"bs", b"bt")

    c.run_all([(db, db.run(check))], timeout_vt=2000.0)
    assert len(out["rows"]) == 120
    assert out["rows"][5] == (b"bs005", b"val5")
    set_event_loop(None)


def test_http_codec_rejects_garbage_loudly():
    """Codec hardening: garbage status lines / content-lengths surface as
    http_bad_response (never a raw ValueError escaping the error model),
    negative lengths are rejected, and the client retries a desynced
    keep-alive stream on a FRESH connection instead of crashing."""
    import socket as _socket
    import threading

    import pytest

    from foundationdb_tpu.fileio.blobstore import (
        BlobStoreEndpoint,
        FdbError,
        build_response,
        parse_request,
        read_response,
    )

    # read_response: malformed frames -> http_bad_response.
    def respond_with(raw: bytes):
        a, b = _socket.socketpair()
        try:
            a.sendall(raw)
            a.shutdown(_socket.SHUT_WR)
            return read_response(b)
        finally:
            a.close()
            b.close()

    for raw in (
        b"HTTP/1.1 xyz OK\r\nContent-Length: 0\r\n\r\n",
        b"HTTP/1.1 200 OK\r\nContent-Length: nope\r\n\r\n",
        b"HTTP/1.1 200 OK\r\nContent-Length: -5\r\n\r\n",
        b"GARBAGE\r\n\r\n",
    ):
        with pytest.raises(FdbError) as ei:
            respond_with(raw)
        assert ei.value.name == "http_bad_response", raw

    # parse_request: malformed input raises ValueError (the server
    # answers 400), never returns a bogus tuple.
    with pytest.raises(ValueError):
        parse_request(b"BROKEN\r\n\r\n")
    with pytest.raises(ValueError):
        parse_request(b"GET / HTTP/1.1\r\nContent-Length: -1\r\n\r\n")

    # End-to-end: a server that answers ONE garbage response must not
    # kill the client — it drops the connection and retries fresh.
    hits = []
    srv = _socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]

    def serve():
        while len(hits) < 2:
            conn, _ = srv.accept()
            data = conn.recv(65536)
            hits.append(data[:16])
            if len(hits) == 1:
                conn.sendall(b"HTTP/1.1 banana\r\n\r\n")  # desynced garbage
            else:
                conn.sendall(build_response(200, b"payload"))
            conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    ep = BlobStoreEndpoint("127.0.0.1", port, "b")
    assert ep.get_object("k") == b"payload"
    assert len(hits) == 2  # first attempt consumed the garbage, then retried
    srv.close()
