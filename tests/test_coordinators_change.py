"""Coordinator quorum change (changeQuorum) + process classes (setclass).

Ref: fdbclient/ManagementAPI.actor.cpp:684 (changeQuorum's safety checks +
the movable coordinated state), fdbserver/Coordination.actor.cpp
(ForwardRequest), ClusterController.actor.cpp:622-659 (ProcessClass
fitness in recruitment).
"""

import pickle

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.client import management as mgmt
from foundationdb_tpu.server.coordination import CoordinatedState
from foundationdb_tpu.server.dynamic_cluster import DynamicCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def _write(c, db, kv):
    async def txn(tr):
        for k, v in kv.items():
            tr.set(k, v)

    c.run_all([(db, db.run(txn))], timeout_vt=3000.0)


def _read(c, db, begin, end):
    out = {}

    async def txn(tr):
        out["rows"] = dict(await tr.get_range(begin, end))

    c.run_all([(db, db.run(txn))], timeout_vt=3000.0)
    return out["rows"]


def _wait_vt(c, db, cond, timeout_vt=600.0):
    done = {}

    async def poll():
        while not cond():
            await c.loop.delay(0.25)
        done["ok"] = True

    c.run_until(db.process.spawn(poll()), timeout_vt=timeout_vt)
    return done.get("ok", False)


def test_change_coordinators_during_load():
    """Swap the quorum onto three worker machines mid-load: the acting CC
    performs the movable-state handoff, every election client retargets via
    forwarding, and killing the ENTIRE old quorum afterward does not stop
    the database."""
    c = DynamicCluster(seed=601, n_workers=7)
    db = c.database()
    _write(c, db, {b"q%02d" % i: b"v%d" % i for i in range(20)})

    new_set = [p.address for p in c._worker_procs[:3]]
    c.run_all([(db, mgmt.change_coordinators(db, new_set))], timeout_vt=500.0)

    def swapped():
        try:
            cc = c.acting_controller()
        except RuntimeError:
            return False
        return cc.coordinators.addresses == new_set

    assert _wait_vt(c, db, swapped, timeout_vt=1200.0)

    # A FRESH client bootstrapping from a STALE cluster file works while
    # the retired coordinators still forward (a stale file with the whole
    # old quorum dead is unrecoverable in the reference too).
    db2 = c.database("late_client")
    _write(c, db2, {b"late": b"client"})

    # The old quorum is now disposable: kill all three original
    # coordinators permanently.
    for p in c._coord_procs:
        p.kill()

    _write(c, db, {b"after_swap": b"yes"})
    rows = _read(c, db, b"q", b"r")
    assert len(rows) == 20
    assert _read(c, db, b"after", b"aftes")[b"after_swap"] == b"yes"
    # The pre-swap client AND the late client both keep working with the
    # old quorum gone: their connection-file views were retargeted.
    _write(c, db2, {b"late2": b"still works"})


def test_reelection_on_new_quorum_after_swap():
    """After the swap, kill the acting controller: the standby must win an
    election held on the NEW coordinators (it learned them via candidacy
    forwarding) and recover the database."""
    c = DynamicCluster(seed=602, n_workers=7, n_controllers=2)
    db = c.database()
    _write(c, db, {b"r%02d" % i: b"v%d" % i for i in range(10)})

    new_set = [p.address for p in c._worker_procs[:3]]
    c.run_all([(db, mgmt.change_coordinators(db, new_set))], timeout_vt=500.0)

    def swapped():
        try:
            return c.acting_controller().coordinators.addresses == new_set
        except RuntimeError:
            return False

    assert _wait_vt(c, db, swapped, timeout_vt=1200.0)

    # Decommission discipline (as in the reference): wait until EVERY
    # controller's connection-file view has been rewritten by forwarding
    # before destroying the old quorum — then give worker/client monitors
    # a few poll rounds for the same.
    def all_ccs_retargeted():
        return all(
            cc.coordinators.addresses == new_set for cc in c.controllers
        )

    assert _wait_vt(c, db, all_ccs_retargeted, timeout_vt=1200.0)

    async def settle():
        await c.loop.delay(5.0)

    c.run_until(db.process.spawn(settle()), timeout_vt=100.0)

    old_cc = c.acting_controller()
    gen_before = old_cc.generation
    for p in c._coord_procs:
        p.kill()  # old quorum gone: only the new one can elect
    old_cc.process.kill()

    def new_leader():
        try:
            cc = c.acting_controller()
        except RuntimeError:
            return False
        return cc is not old_cc and cc.coordinators.addresses == new_set

    assert _wait_vt(c, db, new_leader, timeout_vt=2000.0)
    _write(c, db, {b"after_failover": b"yes"})
    assert c.acting_controller().generation > gen_before


def test_stale_cstate_writer_fenced_after_move():
    """A CoordinatedState session that read BEFORE the move must get
    coordinated_state_conflict writing after it — the fence that makes the
    handoff safe (ref: MovableCoordinatedState)."""
    from foundationdb_tpu.flow.error import FdbError

    c = DynamicCluster(seed=603, n_workers=6)
    db = c.database()
    _write(c, db, {b"x": b"1"})

    # Stale session pinned to the ORIGINAL quorum (same membership-derived
    # register key the real controllers use), read done pre-move.
    from foundationdb_tpu.server.coordination import quorum_state_key

    stale = CoordinatedState(
        db.process,
        list(c.coord_set.interfaces),
        key=quorum_state_key(list(c.coord_set.addresses)),
    )
    raw = {}

    async def pre_read():
        raw["v"] = await stale.read()

    c.run_until(db.process.spawn(pre_read()), timeout_vt=500.0)

    new_set = [p.address for p in c._worker_procs[:3]]
    c.run_all([(db, mgmt.change_coordinators(db, new_set))], timeout_vt=500.0)

    def swapped():
        try:
            return c.acting_controller().coordinators.addresses == new_set
        except RuntimeError:
            return False

    assert _wait_vt(c, db, swapped, timeout_vt=1200.0)

    async def stale_write():
        try:
            await stale.set(pickle.dumps({"evil": True}))
        except FdbError as e:
            return e.name
        return "accepted"

    out = c.run_until(db.process.spawn(stale_write()), timeout_vt=500.0)
    assert out == "coordinated_state_conflict"


def test_crash_recover_after_quorum_move():
    """Whole-cluster power loss after the move: worker-hosted coordinators
    resume from disk at boot, rebooted processes start from their ORIGINAL
    cluster files and must re-find the cluster through the retired
    coordinators' durable forwards."""
    c = DynamicCluster(seed=604, n_workers=6)
    db = c.database()
    _write(c, db, {b"c%02d" % i: b"v%d" % i for i in range(10)})

    new_set = [p.address for p in c._worker_procs[:3]]
    c.run_all([(db, mgmt.change_coordinators(db, new_set))], timeout_vt=500.0)

    def swapped():
        try:
            return c.acting_controller().coordinators.addresses == new_set
        except RuntimeError:
            return False

    assert _wait_vt(c, db, swapped, timeout_vt=1200.0)

    c.crash_and_recover()
    db2 = c.database("post_crash")
    assert len(_read(c, db2, b"c", b"d")) == 10
    _write(c, db2, {b"post_crash": b"yes"})
    # The recovered controller follows the durable forward to the new set.
    assert c.acting_controller().coordinators.addresses == new_set


def test_unsatisfiable_coordinator_request_is_rejected():
    """A request naming an unregistered address must be DROPPED (conf key
    cleared), not retried forever; the quorum stays unchanged and live."""
    c = DynamicCluster(seed=606, n_workers=5)
    db = c.database()
    _write(c, db, {b"pre": b"1"})
    before = list(c.acting_controller().coordinators.addresses)

    c.run_all(
        [(db, mgmt.change_coordinators(db, ["worker0:0", "nosuch:0", "worker1:0"]))],
        timeout_vt=500.0,
    )

    done = {}

    async def poll():
        while True:
            out = {}

            async def probe(tr):
                tr.options["access_system_keys"] = True
                out["v"] = await tr.get(mgmt.conf_key("coordinators"))

            await db.run(probe)
            if out["v"] is None:
                done["ok"] = True
                return
            await c.loop.delay(0.25)

    c.run_until(db.process.spawn(poll()), timeout_vt=600.0)
    assert done.get("ok")
    assert c.acting_controller().coordinators.addresses == before
    _write(c, db, {b"post_reject": b"yes"})


def test_overlapping_quorum_change_keeps_elections_alive():
    """Replace ONE member (the common operation): the two STAYING members
    must keep serving real elections — forwarding them would out-vote
    every candidate with the forward pseudo-nominee and wedge the cluster
    permanently (round-5 review finding)."""
    c = DynamicCluster(seed=607, n_workers=6, n_controllers=2)
    db = c.database()
    _write(c, db, {b"ov%02d" % i: b"v%d" % i for i in range(5)})

    keep = [p.address for p in c._coord_procs[:2]]
    new_set = keep + [c._worker_procs[0].address]
    c.run_all([(db, mgmt.change_coordinators(db, new_set))], timeout_vt=500.0)

    def swapped():
        try:
            return c.acting_controller().coordinators.addresses == new_set
        except RuntimeError:
            return False

    assert _wait_vt(c, db, swapped, timeout_vt=1200.0)
    # Staying members must NOT be forwarding.
    for coord in c.coordinators[:2]:
        assert coord.forward is None, coord.process.address

    # Force a fresh election on the overlapping set: the standby must win.
    old_cc = c.acting_controller()
    old_cc.process.kill()

    def new_leader():
        try:
            return c.acting_controller() is not old_cc
        except RuntimeError:
            return False

    assert _wait_vt(c, db, new_leader, timeout_vt=2000.0)
    _write(c, db, {b"after_overlap": b"yes"})


def test_reused_retired_address_serves_again():
    """A member retired in an earlier change (durable forward on disk) is
    named in a LATER quorum: rejoining must clear its forward, or two
    quorums point at each other and nobody can ever be elected (round-5
    review finding).  New members must be registered workers, so the
    chain is A -> B(w0,w1,w2) -> C(w1,w2,w3) [retires w0] ->
    D(w0,w2,w3) [reuses w0]."""
    c = DynamicCluster(seed=608, n_workers=7, n_controllers=2)
    db = c.database()
    _write(c, db, {b"ru": b"1"})

    w = [p.address for p in c._worker_procs]

    def on(addrs):
        def cond():
            try:
                return c.acting_controller().coordinators.addresses == addrs
            except RuntimeError:
                return False

        return cond

    for step, new_set in enumerate(
        ([w[0], w[1], w[2]], [w[1], w[2], w[3]], [w[0], w[2], w[3]])
    ):
        c.run_all(
            [(db, mgmt.change_coordinators(db, new_set))], timeout_vt=500.0
        )
        assert _wait_vt(c, db, on(new_set), timeout_vt=2000.0), step

    # The reused member (w0) is live again, not forwarding.
    w0_worker = next(
        x for x in c.workers if x.process.address == w[0]
    )
    assert w0_worker.roles["coordinator"].forward is None
    _write(c, db, {b"after_reuse": b"yes"})

    # Elections still work on the final set.
    old_cc = c.acting_controller()
    old_cc.process.kill()

    def new_leader():
        try:
            return c.acting_controller() is not old_cc
        except RuntimeError:
            return False

    assert _wait_vt(c, db, new_leader, timeout_vt=2000.0)
    _write(c, db, {b"after_reuse2": b"yes"})


def test_setclass_prefers_stateless_workers():
    """Workers marked `stateless` must win proxy recruitment at the next
    generation over storage-class ones (ProcessClass fitness)."""
    c = DynamicCluster(seed=605, n_workers=6, n_proxies=1)
    db = c.database()
    _write(c, db, {b"s": b"1"})

    preferred = [p.address for p in c._worker_procs[3:]]
    for a in preferred:
        c.run_all(
            [(db, mgmt.set_process_class(db, a, "stateless"))],
            timeout_vt=300.0,
        )
    for a in [p.address for p in c._worker_procs[:3]]:
        c.run_all(
            [(db, mgmt.set_process_class(db, a, "storage"))],
            timeout_vt=300.0,
        )

    # Wait for the running generation's monitor to pick the classes up,
    # then force a regeneration with two proxies.
    def classes_seen():
        try:
            return len(c.acting_controller().process_classes) >= 6
        except RuntimeError:
            return False

    assert _wait_vt(c, db, classes_seen, timeout_vt=600.0)
    c.run_all([(db, mgmt.configure(db, proxies=2))], timeout_vt=500.0)

    def regenerated():
        try:
            cc = c.acting_controller()
        except RuntimeError:
            return False
        proxies = [a for r, a in cc._role_addrs.items() if r.startswith("proxy")]
        return len(proxies) == 2 and all(a in preferred for a in proxies)

    assert _wait_vt(c, db, regenerated, timeout_vt=2000.0)
    _write(c, db, {b"after_setclass": b"yes"})
