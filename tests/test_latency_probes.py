"""Latency-probe chains + status depth.

Ref: g_traceBatch CommitDebug/TransactionDebug stage events
(NativeAPI.actor.cpp:2376, Resolver.actor.cpp:84), ContinuousSample
percentiles in the status qos, the active latency_probe section, and a
StatusWorkload-style schema gate (Status.actor.cpp:1690,
workloads/Status.actor.cpp).
"""

import json

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.knobs import g_knobs
from foundationdb_tpu.flow.trace import global_collector
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _sampled():
    saved = g_knobs.client.latency_sample_rate
    g_knobs.client.latency_sample_rate = 1.0
    global_collector().clear()
    yield
    g_knobs.client.latency_sample_rate = saved
    set_event_loop(None)


def _run_commits(c, db, n=5):
    async def txn(tr):
        for i in range(3):
            tr.set(b"lp%02d_%d" % (n, i), b"v")

    for _ in range(n):
        c.run_all([(db, db.run(txn))], timeout_vt=1000.0)


def _chain_for(events, debug_id):
    return [e["Location"] for e in events if e.get("ID") == debug_id]


def test_commit_debug_chain_spans_every_stage():
    c = SimCluster(seed=810, n_proxies=1, n_tlogs=1)
    db = c.database("probe")
    _run_commits(c, db)
    ev = global_collector().find("CommitDebug")
    # Find a batch-leader id (it carries the proxy/resolver/log stages).
    leaders = {
        e["ID"]
        for e in ev
        if e["Location"] == "MasterProxyServer.commitBatch.Before"
    }
    assert leaders, "no sampled batch reached the proxy"
    full_chains = 0
    for did in leaders:
        chain = _chain_for(ev, did)
        required = [
            "NativeAPI.commit.Before",
            "MasterProxyServer.commitBatch.Before",
            "MasterProxyServer.commitBatch.GotCommitVersion",
            "Resolver.resolveBatch.Before",
            "Resolver.resolveBatch.After",
            "MasterProxyServer.commitBatch.AfterResolution",
            "TLog.tLogCommit.BeforeWaitForVersion",
            "TLog.tLogCommit.AfterTLogCommit",
            "MasterProxyServer.commitBatch.AfterLogPush",
            "MasterProxyServer.commitBatch.AfterReply",
            "NativeAPI.commit.After",
        ]
        if all(loc in chain for loc in required):
            # Stage order must match the pipeline order.
            idx = [chain.index(loc) for loc in required]
            assert idx == sorted(idx), chain
            full_chains += 1
    assert full_chains >= 1


def test_grv_debug_chain():
    c = SimCluster(seed=811, n_proxies=1)
    db = c.database("probe")

    async def one():
        tr = db.create_transaction()
        await tr.get_read_version()

    c.run_until(db.process.spawn(one()), timeout_vt=1000.0)
    ev = global_collector().find("TransactionDebug")
    ids = {e["ID"] for e in ev}
    assert any(
        [
            "NativeAPI.getConsistentReadVersion.Before",
            "MasterProxyServer.serveGrv.GotRequest",
            "MasterProxyServer.serveGrv.Replied",
            "NativeAPI.getConsistentReadVersion.After",
        ]
        == _chain_for(ev, did)
        for did in ids
    ), ev


def test_status_latency_sections_and_probe():
    from foundationdb_tpu.tools.cli import CliProcessor

    c = SimCluster(seed=812, n_proxies=1)
    db = c.database("probe")
    _run_commits(c, db)
    cli = CliProcessor(c, db)
    out = c.run_until(
        db.process.spawn(cli.run_command("status json")), timeout_vt=2000.0
    )
    doc = json.loads("\n".join(out))
    lat = doc["cluster"]["latency"]
    for section in ("commit_seconds", "grv_seconds"):
        s = lat[section]
        assert s["count"] > 0
        assert 0 <= s["min"] <= s["median"] <= s["p99"] <= s["max"]
    probe = doc["cluster"]["latency_probe"]
    for field in ("transaction_start_seconds", "read_seconds", "commit_seconds"):
        assert isinstance(probe[field], float) and probe[field] >= 0


def test_status_schema_gate():
    """StatusWorkload analog: the required schema tree must be present
    (workloads/Status.actor.cpp checking against the schema doc)."""
    from foundationdb_tpu.server.status import cluster_status

    c = SimCluster(seed=813, n_proxies=1)
    db = c.database("probe")
    _run_commits(c, db, n=2)
    doc = cluster_status(c)
    schema = {
        "client": {"database_status": {"available": bool}, "coordinators": {}},
        "cluster": {
            "recovery_state": {"name": str, "generation": int},
            "roles": {},
            "data": {"storage_version": int, "storage_queue_bytes": int},
            "logs": {"log_version": int, "queue_bytes": int},
            "workload": {"committed_version": int},
            "qos": {"ratekeeper_enabled": bool},
            "latency": {
                "commit_seconds": {"count": int, "median": float},
                "grv_seconds": {"count": int, "median": float},
            },
            "processes": {},
            "machines": {},
        },
    }

    def check(node, spec, path="$"):
        for key, sub in spec.items():
            assert key in node, f"status schema: missing {path}.{key}"
            if isinstance(sub, dict):
                check(node[key], sub, f"{path}.{key}")
            else:
                assert isinstance(node[key], sub), (
                    f"status schema: {path}.{key} is {type(node[key])}, "
                    f"wanted {sub}"
                )

    check(doc, schema)
    # Processes carry role assignments and machine ids; every role address
    # appears (Status.actor.cpp's processes map).
    procs = doc["cluster"]["processes"]
    assert procs, "no processes in status"
    role_addrs = {
        a for addrs in doc["cluster"]["roles"].values() for a in addrs
    }
    assert role_addrs <= set(procs), "role address missing from processes"
    for p in procs.values():
        assert {"machine_id", "alive", "roles", "live_actors"} <= set(p)
    assert doc["cluster"]["machines"], "no machines in status"
