"""jaxcheck tier-1 gate + JXP rule unit tests (ISSUE 7).

The jaxpr analog of tests/test_lint.py: the registered device entry
points (flat/tiered blob steps, the sharded shard_map step, the
grow/rebase/compaction bodies) must hold zero unsuppressed JXP findings,
every suppression must carry a reason, the committed structural
fingerprints under tests/jax_fingerprints/ must match the current CPU
traces, and each rule must actually fire on the golden corpus in
tests/lint_cases/jxp_cases/ (positives) while staying silent on the
must-not-flag twins.

Runnable alone: pytest -m jaxcheck
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

from foundationdb_tpu.tools.lint import jaxfingerprint as jfp
from foundationdb_tpu.tools.lint import jaxir
from foundationdb_tpu.tools.lint.cli import format_counts

pytestmark = pytest.mark.jaxcheck

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "lint_cases", "jxp_cases",
    "entries.py",
)


def _load_corpus():
    spec = importlib.util.spec_from_file_location("jxp_cases_entries", CORPUS)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _by_entry(findings):
    """{entry_name: [finding, ...]} using the [name] message prefix."""
    out = {}
    for f in findings:
        if f.message.startswith("["):
            name = f.message[1:].split("]", 1)[0]
        else:
            name = f"<{f.rule}>"  # pragma-police findings carry no entry
        out.setdefault(name, []).append(f)
    return out


@pytest.fixture(scope="module")
def gate():
    """One shared whole-registry scan + baseline diff (tracing every
    entry 3x over would triple the gate's cost for nothing)."""
    findings = jaxir.run_jaxcheck()
    problems = jfp.check_baselines()
    # Per-rule counts into the tier-1 log, matching the lint gate.
    print(f"\n[jaxcheck] {format_counts(findings)}", file=sys.__stderr__)
    return findings, problems


@pytest.fixture(scope="module")
def corpus_findings():
    mod = _load_corpus()
    return jaxir.run_jaxcheck(registry=mod.make_registry())


# ---------------------------------------------------------------------------
# The tier-1 gate: the registered entry points are clean + fingerprinted
# ---------------------------------------------------------------------------


def test_registry_covers_every_device_entry_point():
    reg = jaxir.default_registry()
    assert {
        "flat_step", "tiered_step", "sharded_step",
        "grow_body", "rebase_body", "compact_body",
    } <= set(reg), sorted(reg)


def test_entry_points_have_zero_unsuppressed_findings(gate):
    findings, _ = gate
    bad = [f for f in findings if not f.suppressed]
    assert not bad, "jaxcheck violations:\n" + "\n".join(
        f.format() for f in bad
    )


def test_every_suppression_carries_a_reason(gate):
    findings, _ = gate
    suppressed = [f for f in findings if f.suppressed]
    # The registry genuinely exercises the pragma mechanism (grow_body's
    # deliberate non-donated reallocation)...
    assert suppressed, "expected the reasoned grow_body JXP003 pragma"
    for f in suppressed:
        assert f.reason.strip(), f"pragma without reason at {f.format()}"


def test_fingerprint_baselines_match_current_traces(gate):
    _, problems = gate
    assert not problems, "fingerprint divergence:\n" + "\n".join(problems)


def test_committed_fingerprints_exist_for_all_modes():
    d = jfp.baseline_dir()
    for name in ("flat_step", "tiered_step", "sharded_step"):
        path = os.path.join(d, f"{name}.json")
        assert os.path.exists(path), path
        fp = json.load(open(path))
        assert fp["entry"] == name and fp["eqns"], name


def test_warm_scan_under_10s(gate):
    # The module fixture warmed every per-entry trace cache; the gate's
    # steady-state cost is re-walking cached jaxprs + the baseline diff.
    t0 = time.time()
    jaxir.run_jaxcheck()
    jfp.check_baselines()
    assert time.time() - t0 <= 10.0


def test_cli_module_entrypoint_runs():
    proc = subprocess.run(
        [sys.executable, "-m", "foundationdb_tpu.tools.lint.jaxir",
         "--format=json"],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["unsuppressed"] == 0
    assert out["total"] >= 1  # the suppressed grow_body finding


# ---------------------------------------------------------------------------
# Burn-down pins: the donation/widening fixes stay fixed
# ---------------------------------------------------------------------------


def test_rebase_body_donates_carried_state():
    don = jaxir.default_registry()["rebase_body"].donation()
    assert don == {"vers": True, "d": False}


def test_blob_steps_donate_all_carried_state():
    reg = jaxir.default_registry()
    for name in ("flat_step", "tiered_step"):
        entry = reg[name]
        don = entry.donation()
        for nm in entry.carried:
            assert don[nm], (name, nm)
        assert not don["blob"], name  # the batch transfer is per-batch input


def test_sharded_pinned_bounds_not_donated():
    entry = jaxir.default_registry()["sharded_step"]
    don = entry.donation()
    assert all(don[n] for n in ("hkeys", "hvers", "hcount", "oldest"))
    assert not don["lo"] and not don["hi"]


def test_grow_nondonation_is_reason_pragmad(gate):
    findings, _ = gate
    grow = [f for f in findings
            if f.rule == "JXP003" and "[grow_body]" in f.message]
    assert grow, "grow_body's deliberate non-donation must stay visible"
    for f in grow:
        assert f.suppressed and f.reason.strip()


def test_sharded_step_work_is_per_shard_bounded():
    # The ROADMAP-item-2 down-payment: inside the shard_map body every
    # work primitive operates on ONE shard's slice (the flat engine's
    # legitimate per-shard merge/evict sorts), never on globally-sized
    # (S * h_cap) operands.
    entry = jaxir.default_registry()["sharded_step"]
    work = [e for e in jaxir.walk_jaxpr(entry.jaxpr())
            if e.prim in jaxir.WORK_PRIMS]
    assert any(
        e.prim == "sort" and e.max_dim >= entry.h_threshold for e in work
    ), "per-shard merge/evict sorts vanished — detector is blind"
    assert all(e.max_dim <= entry.work_bound for e in work)


def test_engine_steps_are_x64_widening_clean(gate):
    # The JXP004 burn-down (bare arange/cumsum/sum in the H-sized
    # merge/evict/compact pipeline) stays fixed.
    findings, _ = gate
    assert not [f for f in findings if f.rule == "JXP004"]


# ---------------------------------------------------------------------------
# Golden corpus: every rule fires on its positive, never on its negative
# ---------------------------------------------------------------------------


def test_corpus_positives_fire_and_negatives_stay_silent(corpus_findings):
    by = _by_entry([f for f in corpus_findings if not f.suppressed])
    expect = {
        "jxp001_pos": "JXP001",
        "jxp001_bound_pos": "JXP001",
        "jxp002_pos": "JXP002",
        "jxp003_pos": "JXP003",
        "jxp003_pinned_pos": "JXP003",
        "jxp004_pos": "JXP004",
        "jxp005_pos": "JXP005",
        "jxp005_drift_pos": "JXP005",
    }
    for entry, rule in expect.items():
        rules = [f.rule for f in by.get(entry, ())]
        assert rule in rules, (entry, rules, by)
    for entry in ("jxp001_neg", "jxp003_neg", "jxp004_neg"):
        assert entry not in by, by.get(entry)


def test_corpus_pinned_donation_names_the_arg(corpus_findings):
    by = _by_entry(corpus_findings)
    msgs = [f.message for f in by["jxp003_pinned_pos"]]
    assert any("'delta'" in m and "pinned" in m for m in msgs), msgs


def test_corpus_pragma_suppresses_with_reason(corpus_findings):
    by = _by_entry(corpus_findings)
    f = by["jxp003_pragma"][0]
    assert f.rule == "JXP003" and f.suppressed
    assert "reasoned" in f.reason


def test_corpus_pragma_without_reason_is_prg001(corpus_findings):
    prg1 = [f for f in corpus_findings if f.rule == "PRG001"]
    assert prg1, "the reasonless corpus pragma must yield PRG001"
    # ...while still suppressing its JXP003 finding (scope is separate
    # from the reason requirement, matching flowcheck).
    by = _by_entry(corpus_findings)
    assert by["noreason_pragma"][0].suppressed


def test_corpus_stale_pragma_is_prg002(corpus_findings):
    prg2 = [f for f in corpus_findings if f.rule == "PRG002"]
    assert any("JXP001" in f.message for f in prg2), prg2


def test_fdblint_does_not_police_jaxcheck_pragmas():
    # The two pragma namespaces must not cross-police: flowcheck parsing
    # this corpus file sees NO pragmas at all (they are jaxcheck-marked).
    from foundationdb_tpu.tools.lint.base import parse_pragmas

    src = open(CORPUS).read()
    assert parse_pragmas(src) == {}
    assert len(parse_pragmas(src, tool="jaxcheck")) == 3


# ---------------------------------------------------------------------------
# Fingerprint workflow
# ---------------------------------------------------------------------------


def _mini_registries():
    """Two registries sharing the entry name 'mini' whose programs differ
    by one primitive (the deliberately-perturbed-program case)."""
    import jax
    import jax.numpy as jnp

    from foundationdb_tpu.conflict.engine_jax import register_entry_point

    def _ep_mini():
        return (lambda x: jnp.sort(x)), None, (
            jax.ShapeDtypeStruct((256,), jnp.int32),), {}

    def _ep_mini_perturbed():
        return (lambda x: jnp.sort(jnp.cumsum(x, dtype=jnp.int32))), None, (
            jax.ShapeDtypeStruct((256,), jnp.int32),), {}

    a, b = {}, {}
    meta = dict(arg_names=("x",), size_classes=(("H", 256),),
                h_threshold=256)
    register_entry_point("mini", _ep_mini, registry=a, **meta)
    register_entry_point("mini", _ep_mini_perturbed, registry=b, **meta)
    return a, b


def test_update_baselines_rewrites_deterministically(tmp_path):
    reg, _ = _mini_registries()
    d1, d2 = tmp_path / "a", tmp_path / "b"
    (p1,) = jfp.write_baselines(reg, str(d1))
    (p2,) = jfp.write_baselines(reg, str(d2))
    assert open(p1, "rb").read() == open(p2, "rb").read()
    assert jfp.check_baselines(reg, str(d1)) == []


def test_perturbed_program_fails_baseline_diff(tmp_path):
    reg, perturbed = _mini_registries()
    jfp.write_baselines(reg, str(tmp_path))
    problems = jfp.check_baselines(perturbed, str(tmp_path))
    assert problems, "a changed program must fail the committed diff"
    text = "\n".join(problems)
    assert "mini" in text and "eqns" in text
    # Readable: names the drifted key with both values.
    assert any("baseline" in line and "current" in line
               for line in problems), problems


def test_missing_baseline_is_an_error_not_a_skip(tmp_path):
    reg, _ = _mini_registries()
    problems = jfp.check_baselines(reg, str(tmp_path))
    assert problems and "MISSING" in problems[0]


def test_stale_baseline_is_flagged(tmp_path):
    reg, _ = _mini_registries()
    jfp.write_baselines(reg, str(tmp_path))
    (tmp_path / "ghost.json").write_text("{}")
    problems = jfp.check_baselines(reg, str(tmp_path))
    assert any("STALE" in p and "ghost" in p for p in problems), problems


def test_baseline_dir_env_override(monkeypatch, tmp_path):
    # FDB_TPU_JAXCHECK_DIR goes through the g_env registry (ENV001-clean).
    monkeypatch.setenv("FDB_TPU_JAXCHECK_DIR", str(tmp_path))
    assert jfp.baseline_dir() == str(tmp_path)
