"""RangeMap (KeyRangeMap analog) unit tests + randomized differential vs a
brute-force dict-of-keys model."""

import numpy as np

from foundationdb_tpu.utils import RangeMap


def test_basic_set_get():
    m = RangeMap("s0")
    assert m[b""] == "s0" and m[b"zzz"] == "s0"
    m.set_range(b"b", b"d", "s1")
    assert m[b"a"] == "s0"
    assert m[b"b"] == "s1"
    assert m[b"c\xff"] == "s1"
    assert m[b"d"] == "s0"
    assert list(m.items()) == [
        (b"", b"b", "s0"),
        (b"b", b"d", "s1"),
        (b"d", None, "s0"),
    ]


def test_coalescing():
    m = RangeMap("a")
    m.set_range(b"b", b"c", "b")
    m.set_range(b"c", b"d", "b")
    assert list(m.items()) == [(b"", b"b", "a"), (b"b", b"d", "b"), (b"d", None, "a")]
    m.set_range(b"b", b"d", "a")
    assert list(m.items()) == [(b"", None, "a")]


def test_set_to_infinity():
    m = RangeMap("x")
    m.set_range(b"m", None, "y")
    assert m[b"z"] == "y" and m[b"a"] == "x"
    assert list(m.items()) == [(b"", b"m", "x"), (b"m", None, "y")]


def test_intersecting_clips():
    m = RangeMap("a")
    m.set_range(b"c", b"f", "b")
    got = list(m.intersecting(b"d", b"z"))
    assert got == [(b"d", b"f", "b"), (b"f", b"z", "a")]
    got = list(m.intersecting(b"c", b"d"))
    assert got == [(b"c", b"d", "b")]


def test_randomized_vs_bruteforce():
    rng = np.random.default_rng(5)
    m = RangeMap(0)
    keys = [b"%03d" % i for i in range(100)]
    brute = {k: 0 for k in keys}
    for step in range(300):
        a, b = sorted(rng.integers(0, 100, 2))
        v = int(rng.integers(0, 5))
        if a == b:
            b = a + 1
        m.set_range(b"%03d" % a, b"%03d" % b, v)
        for i in range(a, b):
            brute[b"%03d" % i] = v
        for k in keys:
            assert m[k] == brute[k], (step, k)
        # invariants: begins sorted+unique, neighbours coalesced
        assert m.begins == sorted(set(m.begins))
        assert all(
            m.values[i] != m.values[i - 1] for i in range(1, len(m.values))
        )
