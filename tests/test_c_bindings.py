"""Native C client bindings: libfdb_tpu_c over the versioned wire protocol.

Ref: bindings/c/foundationdb/fdb_c.h:190 (the ABI surface) and
bindings/bindingtester (cross-binding differential testing).  The C
client is a from-scratch C++ implementation of the tagged wire codec +
FlowTransport framing (no embedded interpreter); these tests build it,
run it against a real-mode OS-process server, and differential-check its
results against the Python client on the same cluster.
"""

import ctypes
import os
import signal
import subprocess

import pytest

from conftest import REPO_ROOT, spawn_real_node

LIB = os.path.join(REPO_ROOT, "libfdb_tpu_c.so")


def _build_lib():
    """Regenerate the schema header and (re)build when sources changed."""
    schema = os.path.join(REPO_ROOT, "cpp", "wire_schema.h")
    src = os.path.join(REPO_ROOT, "cpp", "fdb_c_client.cpp")
    hdr = os.path.join(REPO_ROOT, "cpp", "fdb_tpu_c.h")
    gen = os.path.join(REPO_ROOT, "tools", "gen_wire_schema.py")
    import sys

    out = subprocess.run(
        [sys.executable, gen], capture_output=True, text=True, cwd=REPO_ROOT,
        check=True,
    )
    new_schema = out.stdout
    if not os.path.exists(schema) or open(schema).read() != new_schema:
        with open(schema, "w") as f:
            f.write(new_schema)
    deps = max(os.path.getmtime(p) for p in (schema, src, hdr))
    if not os.path.exists(LIB) or os.path.getmtime(LIB) < deps:
        subprocess.run(
            ["g++", "-std=c++17", "-O2", "-fPIC", "-shared", src, "-o", LIB],
            cwd=REPO_ROOT, check=True, capture_output=True, text=True,
        )
    return LIB


class CClient:
    """Thin ctypes veneer over the C ABI (what a C caller would write)."""

    def __init__(self, lib_path: str, address: str):
        L = ctypes.CDLL(lib_path)
        L.fdb_create_database.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
        L.fdb_database_create_transaction.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]
        for fn in ("fdb_transaction_commit", "fdb_transaction_get_read_version"):
            getattr(L, fn).argtypes = [ctypes.c_void_p]
            getattr(L, fn).restype = ctypes.c_void_p
        L.fdb_transaction_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        L.fdb_transaction_clear.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        L.fdb_transaction_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        L.fdb_transaction_get.restype = ctypes.c_void_p
        L.fdb_transaction_get_range.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        L.fdb_transaction_get_range.restype = ctypes.c_void_p
        L.fdb_future_get_error.argtypes = [ctypes.c_void_p]
        L.fdb_future_get_value.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
            ctypes.POINTER(ctypes.c_int)]
        L.fdb_future_get_version.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]

        class KV(ctypes.Structure):
            _fields_ = [("key", ctypes.POINTER(ctypes.c_ubyte)),
                        ("key_len", ctypes.c_int),
                        ("value", ctypes.POINTER(ctypes.c_ubyte)),
                        ("value_len", ctypes.c_int)]

        self.KV = KV
        L.fdb_future_get_keyvalue_array.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(KV)),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        L.fdb_future_destroy.argtypes = [ctypes.c_void_p]
        L.fdb_transaction_destroy.argtypes = [ctypes.c_void_p]
        L.fdb_transaction_reset.argtypes = [ctypes.c_void_p]
        L.fdb_database_destroy.argtypes = [ctypes.c_void_p]
        L.fdb_get_error.argtypes = [ctypes.c_int]
        L.fdb_get_error.restype = ctypes.c_char_p
        self.L = L
        db = ctypes.c_void_p()
        rc = L.fdb_create_database(address.encode(), ctypes.byref(db))
        assert rc == 0, f"fdb_create_database: {rc}"
        self.db = db

    def txn(self):
        tr = ctypes.c_void_p()
        rc = self.L.fdb_database_create_transaction(self.db, ctypes.byref(tr))
        assert rc == 0
        return tr

    def set(self, tr, k: bytes, v: bytes):
        self.L.fdb_transaction_set(tr, k, len(k), v, len(v))

    def clear(self, tr, k: bytes):
        self.L.fdb_transaction_clear(tr, k, len(k))

    def get(self, tr, k: bytes):
        f = self.L.fdb_transaction_get(tr, k, len(k))
        try:
            err = self.L.fdb_future_get_error(f)
            if err:
                return ("error", self.L.fdb_get_error(err).decode())
            present = ctypes.c_int()
            val = ctypes.POINTER(ctypes.c_ubyte)()
            n = ctypes.c_int()
            rc = self.L.fdb_future_get_value(
                f, ctypes.byref(present), ctypes.byref(val), ctypes.byref(n))
            assert rc == 0
            if not present.value:
                return None
            return bytes(bytearray(val[i] for i in range(n.value)))
        finally:
            self.L.fdb_future_destroy(f)

    def get_range(self, tr, b: bytes, e: bytes, limit=1000):
        f = self.L.fdb_transaction_get_range(tr, b, len(b), e, len(e), limit)
        try:
            err = self.L.fdb_future_get_error(f)
            assert err == 0, self.L.fdb_get_error(err)
            arr = ctypes.POINTER(self.KV)()
            count = ctypes.c_int()
            more = ctypes.c_int()
            rc = self.L.fdb_future_get_keyvalue_array(
                f, ctypes.byref(arr), ctypes.byref(count), ctypes.byref(more))
            assert rc == 0
            out = []
            for i in range(count.value):
                kv = arr[i]
                out.append((
                    bytes(bytearray(kv.key[j] for j in range(kv.key_len))),
                    bytes(bytearray(kv.value[j] for j in range(kv.value_len))),
                ))
            return out
        finally:
            self.L.fdb_future_destroy(f)

    def commit(self, tr):
        f = self.L.fdb_transaction_commit(tr)
        try:
            err = self.L.fdb_future_get_error(f)
            if err:
                return ("error", self.L.fdb_get_error(err).decode())
            v = ctypes.c_int64()
            rc = self.L.fdb_future_get_version(f, ctypes.byref(v))
            assert rc == 0
            return v.value
        finally:
            self.L.fdb_future_destroy(f)


@pytest.fixture(scope="module")
def server():
    proc = spawn_real_node("server")
    ready = proc.stdout.readline().strip()
    assert ready.startswith("READY "), ready
    yield ready.split()[1]
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_c_client_set_get_commit(server):
    c = CClient(_build_lib(), server)
    tr = c.txn()
    c.set(tr, b"ckey", b"cvalue")
    c.set(tr, b"ckey2", b"x" * 5000)
    v = c.commit(tr)
    assert isinstance(v, int) and v > 0, v
    c.L.fdb_transaction_destroy(tr)

    tr2 = c.txn()
    assert c.get(tr2, b"ckey") == b"cvalue"
    assert c.get(tr2, b"ckey2") == b"x" * 5000
    assert c.get(tr2, b"missing") is None
    # Read-your-writes inside a txn — get AND get_range must agree.
    c.set(tr2, b"ckey", b"updated")
    assert c.get(tr2, b"ckey") == b"updated"
    c.clear(tr2, b"ckey2")
    assert c.get(tr2, b"ckey2") is None
    rows = dict(c.get_range(tr2, b"ckey", b"ckez"))
    assert rows.get(b"ckey") == b"updated" and b"ckey2" not in rows, rows
    v2 = c.commit(tr2)
    assert v2 > v
    c.L.fdb_transaction_destroy(tr2)
    c.L.fdb_database_destroy(c.db)


def test_c_client_conflict_detected(server):
    """Two C transactions in read-modify-write conflict: exactly one
    commits, the other gets not_committed — serializability through the
    native client."""
    c = CClient(_build_lib(), server)
    t1, t2 = c.txn(), c.txn()
    base = c.get(t1, b"counter") or b"0"
    base2 = c.get(t2, b"counter") or b"0"
    c.set(t1, b"counter", b"%d" % (int(base) + 1))
    c.set(t2, b"counter", b"%d" % (int(base2) + 1))
    r1 = c.commit(t1)
    r2 = c.commit(t2)
    outcomes = sorted(
        ("ok" if isinstance(r, int) else r[1]) for r in (r1, r2)
    )
    assert outcomes == ["not_committed", "ok"], outcomes
    for t in (t1, t2):
        c.L.fdb_transaction_destroy(t)
    c.L.fdb_database_destroy(c.db)


def test_c_client_atomic_add_and_on_error(server):
    """Server-side atomic ADDs through the native client, with the
    fdb_transaction_on_error retry loop shape a C caller writes."""
    c = CClient(_build_lib(), server)
    c.L.fdb_transaction_atomic_op.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    c.L.fdb_transaction_on_error.argtypes = [ctypes.c_void_p, ctypes.c_int]
    MT_ADD = 2
    one = (1).to_bytes(8, "little")

    def add_once():
        tr = c.txn()
        try:
            while True:
                c.L.fdb_transaction_atomic_op(
                    tr, b"c_atomic", len(b"c_atomic"), one, len(one), MT_ADD
                )
                r = c.commit(tr)
                if isinstance(r, int):
                    return
                from foundationdb_tpu.flow.error import error_code

                rc = c.L.fdb_transaction_on_error(tr, error_code(r[1]))
                assert rc == 0, f"non-retryable: {r[1]}"
        finally:
            c.L.fdb_transaction_destroy(tr)

    for _ in range(10):
        add_once()
    tr = c.txn()
    val = c.get(tr, b"c_atomic")
    assert int.from_bytes(val, "little") == 10, val
    c.L.fdb_transaction_destroy(tr)
    c.L.fdb_database_destroy(c.db)


def test_bindingtester_differential_vs_python_client(server):
    """Mini bindingtester: the same randomized op sequence through the C
    client and the Python client against one cluster; final range scans
    observed by BOTH clients must agree byte-for-byte."""
    import numpy.random as npr

    c = CClient(_build_lib(), server)
    rng = npr.default_rng(99)
    model = {}
    tr = c.txn()
    for i in range(120):
        op = rng.integers(0, 10)
        k = b"bt/%03d" % int(rng.integers(0, 40))
        if op < 6:
            v = b"v%d" % int(rng.integers(0, 1 << 20))
            c.set(tr, k, v)
            model[k] = v
        elif op < 8:
            c.clear(tr, k)
            model.pop(k, None)
        else:
            got = c.get(tr, k)
            assert got == model.get(k), (k, got, model.get(k))
        if rng.integers(0, 8) == 0:
            assert isinstance(c.commit(tr), int)
            c.L.fdb_transaction_destroy(tr)
            tr = c.txn()
    assert isinstance(c.commit(tr), int)
    c.L.fdb_transaction_destroy(tr)

    # C-side scan agrees with the model...
    tr2 = c.txn()
    c_rows = c.get_range(tr2, b"bt/", b"bt0")
    assert c_rows == sorted(model.items()), "C scan diverged from model"
    c.L.fdb_transaction_destroy(tr2)
    c.L.fdb_database_destroy(c.db)

    # ...and the PYTHON client sees the identical state over the same wire.
    code = r"""
import sys
sys.path.insert(0, %r)
from foundationdb_tpu.flow.eventloop import EventLoop, set_event_loop
from foundationdb_tpu.rpc.network import Endpoint
from foundationdb_tpu.rpc.real_network import RealNetwork
from foundationdb_tpu.rpc.stream import RequestStreamRef, well_known_token
from foundationdb_tpu.client.transaction import Database

loop = EventLoop(seed=7)
set_event_loop(loop)
net = RealNetwork(loop)
proc = net.process("pyclient")
boot = RequestStreamRef(Endpoint(%r, well_known_token("bootstrap")), "bootstrap")

async def main():
    ifaces = await boot.get_reply(proc, None)
    db = Database(proc, ifaces["proxy"], ifaces["storage"], proxies=ifaces["proxies"])
    tr = db.create_transaction()
    rows = await tr.get_range(b"bt/", b"bt0", limit=10000)
    for k, v in rows:
        print(k.hex(), v.hex())

task = proc.spawn(main(), "main")
net.run_realtime(until=task, timeout_s=30.0)
"""
    import sys

    out = subprocess.run(
        [sys.executable, "-c", code % (REPO_ROOT, server)],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    py_rows = [
        (bytes.fromhex(a), bytes.fromhex(b))
        for a, b in (ln.split() for ln in out.stdout.strip().splitlines() if ln)
    ]
    assert py_rows == sorted(model.items()), "python scan diverged"
