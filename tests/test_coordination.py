"""Coordination layer: quorum register, leader election, failover."""

import pytest

from foundationdb_tpu.flow import EventLoop, FdbError, set_event_loop
from foundationdb_tpu.flow.asyncvar import AsyncVar
from foundationdb_tpu.rpc import SimNetwork
from foundationdb_tpu.server.coordination import (
    CoordinatedState,
    Coordinator,
    LeaderInfo,
    monitor_leader,
    try_become_leader,
)


def make_coords(net, n=3):
    coords = [Coordinator(net.process(f"coord{i}")) for i in range(n)]
    return coords, [c.interface() for c in coords]


@pytest.fixture
def env():
    loop = EventLoop(seed=1234)
    set_event_loop(loop)
    net = SimNetwork(loop)
    yield loop, net
    set_event_loop(None)


def test_coordinated_state_read_write(env):
    loop, net = env
    _, ifaces = make_coords(net, 3)
    p = net.process("client")
    out = {}

    async def go():
        cs = CoordinatedState(p, ifaces)
        out["initial"] = await cs.read()
        await cs.set(b"generation-1")
        cs2 = CoordinatedState(p, ifaces)
        out["after"] = await cs2.read()

    loop.run_until(p.spawn(go()), timeout_vt=60.0)
    assert out["initial"] is None
    assert out["after"] == b"generation-1"


def test_coordinated_state_conflict(env):
    loop, net = env
    _, ifaces = make_coords(net, 3)
    p1, p2 = net.process("m1"), net.process("m2")
    out = {}

    async def race():
        a = CoordinatedState(p1, ifaces)
        b = CoordinatedState(p2, ifaces)
        await a.read()
        await b.read()  # b's read promises a higher generation
        try:
            await a.set(b"from-a")
            out["a"] = "ok"
        except FdbError as e:
            out["a"] = e.name
        await b.set(b"from-b")
        out["b"] = "ok"
        c = CoordinatedState(p1, ifaces)
        out["final"] = await c.read()

    loop.run_until(p1.spawn(race()), timeout_vt=60.0)
    assert out["a"] == "coordinated_state_conflict"
    assert out["b"] == "ok"
    assert out["final"] == b"from-b"


def test_coordinated_state_tolerates_minority_failure(env):
    loop, net = env
    coords, ifaces = make_coords(net, 5)
    coords[0].process.kill()
    coords[1].process.kill()
    p = net.process("client")
    out = {}

    async def go():
        cs = CoordinatedState(p, ifaces)
        await cs.read()
        await cs.set(b"v")
        cs2 = CoordinatedState(p, ifaces)
        out["v"] = await cs2.read()

    loop.run_until(p.spawn(go()), timeout_vt=60.0)
    assert out["v"] == b"v"


def test_leader_election_and_failover(env):
    loop, net = env
    _, ifaces = make_coords(net, 3)

    cand_procs = [net.process(f"cand{i}") for i in range(3)]
    flags = [AsyncVar(False) for _ in range(3)]
    infos = [
        LeaderInfo(priority=0, change_id=100 + i, address=p.address)
        for i, p in enumerate(cand_procs)
    ]
    for p, info, flag in zip(cand_procs, infos, flags):
        p.spawn(try_become_leader(p, ifaces, info, flag), "candidacy")

    watcher = net.process("watcher")
    leader_var = AsyncVar(None)
    watcher.spawn(monitor_leader(watcher, ifaces, leader_var), "monitor")

    async def until(pred, timeout=30.0):
        t0 = loop.now()
        while not pred():
            assert loop.now() - t0 < timeout, "condition never held"
            await loop.delay(0.1)

    async def scenario():
        # Exactly one leader emerges, and it is the lowest change_id.
        await until(lambda: sum(f.get() for f in flags) == 1)
        assert flags[0].get()  # change_id 100 wins
        await until(lambda: leader_var.get() is not None)
        assert leader_var.get().change_id == 100

        # Kill the leader: another candidate takes over, monitor follows.
        # (The dead process's own flag is moot — its actors are cancelled.)
        cand_procs[0].kill()
        await until(lambda: flags[1].get(), timeout=60.0)
        await until(
            lambda: leader_var.get() and leader_var.get().change_id == 101,
            timeout=60.0,
        )

    driver = net.process("driver")
    loop.run_until(driver.spawn(scenario()), timeout_vt=200.0)
    set_event_loop(None)


def test_election_determinism():
    def run(seed):
        loop = EventLoop(seed=seed)
        set_event_loop(loop)
        net = SimNetwork(loop)
        _, ifaces = make_coords(net, 3)
        p = net.process("cand")
        flag = AsyncVar(False)
        info = LeaderInfo(priority=0, change_id=7, address=p.address)
        p.spawn(try_become_leader(p, ifaces, info, flag), "c")

        async def wait_leader():
            while not flag.get():
                await loop.delay(0.05)
            return round(loop.now(), 9)

        t = loop.run_until(p.spawn(wait_leader()), timeout_vt=60.0)
        set_event_loop(None)
        return t

    assert run(5) == run(5)
