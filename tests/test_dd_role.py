"""Self-driving DataDistribution: the live control loop heals, splits,
rebalances, and drains WITHOUT any test intervention.

Ref: fdbserver/DataDistribution.actor.cpp:1237 (teamTracker),
DataDistributionTracker.actor.cpp (split cadence),
DataDistributionQueue.actor.cpp (priority move queue) — the acceptance
shape the round-4 review asked for: kill a storage permanently and watch
the cluster restore full team width on its own; write a hot shard and
watch it split + rebalance on its own.
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.knobs import g_knobs
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.interfaces import GetKeyValuesRequest


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


@pytest.fixture
def fast_dd():
    """Sim-scaled DD cadences/thresholds; restored after each test."""
    saved = {
        k: getattr(g_knobs.server, k)
        for k in (
            "dd_tracker_interval",
            "dd_shard_max_bytes",
            "dd_shard_min_bytes",
            "dd_failure_detections",
        )
    }
    g_knobs.server.dd_tracker_interval = 0.5
    yield g_knobs.server
    for k, v in saved.items():
        setattr(g_knobs.server, k, v)


def wait_until(c, db, cond_coro_fn, timeout_vt=300.0, interval=0.25):
    """Advance virtual time until an async condition holds (the 'no test
    intervention' driver: the test only *observes*)."""
    result = {}

    async def poll():
        while True:
            ok = await cond_coro_fn()
            if ok:
                result["ok"] = True
                return
            await c.loop.delay(interval)

    c.run_until(db.process.spawn(poll()), timeout_vt=timeout_vt)
    return result.get("ok", False)


def fill(c, db, n=50, prefix=b"k"):
    async def txn(tr):
        for i in range(n):
            tr.set(prefix + b"%03d" % i, b"v%d" % i)

    c.run_all([(db, db.run(txn))])


def place_teams(c, db, dd):
    """Initial placement: two user shards on overlapping width-2 teams over
    ss0..ss2; ss3 stays a spare."""

    async def go():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"k025")
        await dd.split(b"\xff")
        await dd.move(b"", ["ss0", "ss1"])
        await dd.move(b"k025", ["ss1", "ss2"])

    c.run_until(db.process.spawn(go()), timeout_vt=500.0)


def shard_map_rows(c, db, dd):
    return c.run_until(
        db.process.spawn(dd.read_shard_map()), timeout_vt=200.0
    )


def shard_teams(c, db, dd):
    return {
        b: (set(team), set(dest))
        for b, _e, team, dest in shard_map_rows(c, db, dd)
    }


def test_storage_kill_heals_without_intervention(fast_dd):
    """Kill a replica of two width-2 shards permanently; the DD role alone
    must declare it failed, pick the spare, and restore both shards to
    width 2 — the test never calls heal()."""
    c = SimCluster(seed=172, n_storages=4, n_tlogs=2)
    db = c.database()
    fill(c, db)
    dd = c.data_distributor()
    place_teams(c, db, dd)
    role = c.dd_role(dd)

    c.storages[1].process.kill()  # replica of BOTH user shards

    async def healed():
        user = [
            (b, set(team), set(dest))
            for b, _e, team, dest in await dd.read_shard_map()
            if b < b"\xff"
        ]
        return user and all(
            not dest and "ss1" not in team and len(team) == 2
            for _b, team, dest in user
        )

    assert wait_until(c, db, healed, timeout_vt=600.0)
    # At least one relocation was an explicit heal; the other may have been
    # the count-rebalancer racing ahead of failure detection (both valid).
    assert role.moves_done >= 2 and role.heals_done >= 1

    # Every replica the heal recruited actually serves its shard's data.
    version = c.proxy.committed.get()
    by_id = {s.storage_id: s for s in c.storages}
    for b, e, team, _dest in shard_map_rows(c, db, dd):
        if b >= b"\xff":
            continue
        lo, hi = max(b, b"k"), min(e or b"\xff", b"l")
        if lo >= hi:
            continue
        contents = []
        for sid in team:
            out = {}

            async def direct(sid=sid, lo=lo, hi=hi, out=out):
                rep = await by_id[sid].interface().get_key_values.get_reply(
                    db.process,
                    GetKeyValuesRequest(begin=lo, end=hi, version=version),
                )
                out["rows"] = rep.data

            c.run_until(db.process.spawn(direct()), timeout_vt=200.0)
            contents.append(out["rows"])
        assert contents and all(r == contents[0] for r in contents)

    # And the client still reads everything through normal routing.
    rows = {}

    async def read(tr):
        rows["all"] = await tr.get_range(b"k", b"l")

    c.run_all([(db, db.run(read))], timeout_vt=500.0)
    assert len(rows["all"]) == 50
    role.stop()


def test_hot_shard_splits_and_rebalances(fast_dd):
    """One team owns everything; a write-hot shard crosses the byte
    threshold: the tracker must split it and the queue must move a half
    onto the idle storage — on its own."""
    fast_dd.dd_shard_max_bytes = 3000
    fast_dd.dd_shard_min_bytes = 0  # merge off: tiny shards are the point
    c = SimCluster(seed=173, n_storages=2)
    db = c.database()
    dd = c.data_distributor()

    async def go():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"\xff")

    c.run_until(db.process.spawn(go()), timeout_vt=500.0)
    role = c.dd_role(dd)

    # Hot writes: enough sampled bytes to trip the 3000-byte threshold.
    for j in range(4):
        async def txn(tr, j=j):
            for i in range(60):
                tr.set(b"h%d%03d" % (j, i), b"x" * 40)

        c.run_all([(db, db.run(txn))], timeout_vt=500.0)

    async def rebalanced():
        per = {}
        for b, _e, team, dest in await dd.read_shard_map():
            if b >= b"\xff" or dest:
                continue
            for sid in team:
                per[sid] = per.get(sid, 0) + 1
        return role.splits_done >= 1 and per.get("ss1", 0) >= 1

    assert wait_until(c, db, rebalanced, timeout_vt=900.0)

    rows = {}

    async def read(tr):
        rows["all"] = await tr.get_range(b"h", b"i")

    c.run_all([(db, db.run(read))], timeout_vt=500.0)
    assert len(rows["all"]) == 240
    role.stop()


def test_dynamic_cluster_dd_drops_dead_storage(fast_dd):
    """Full control plane: the CC recruits the DD singleton each generation
    and seeds `\xff/keyServers` from the owned meta.  A storage machine that
    never returns is (a) recovered around after the grace (existing
    behavior) and (b) scrubbed from the authoritative shard map by DD alone
    — no operator, no test intervention (ref: teamTracker,
    DataDistribution.actor.cpp:1237)."""
    from foundationdb_tpu.server.data_distribution import DataDistributor
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=271, n_workers=7, n_tlogs=2, n_storages=2)
    db = c.database()

    async def w1(tr):
        tr.set(b"boot", b"1")
        for i in range(10):
            tr.set(b"d%02d" % i, b"x%d" % i)

    c.run_all([(db, db.run(w1))], timeout_vt=300.0)

    dead_proc = c.kill_role_process("storage0")
    dead_sid = f"ss:{dead_proc.machine.machine_id}"

    # Commits resume after the degraded recovery (existing guarantee).
    async def w2(tr):
        tr.set(b"after", b"loss")

    c.run_all([(db, db.run(w2))], timeout_vt=2000.0)

    # DD (recruited by the CC, reading the seeded map) must scrub the dead
    # id from every team on its own.
    reader = DataDistributor(db)

    async def scrubbed():
        rows = await reader.read_shard_map()
        return rows and all(
            dead_sid not in set(team) | set(dest)
            for _b, _e, team, dest in rows
        )

    assert wait_until(c, db, scrubbed, timeout_vt=900.0)

    out = {}

    async def readback(tr):
        out["rows"] = await tr.get_range(b"d", b"e")

    c.run_all([(db, db.run(readback))], timeout_vt=500.0)
    assert len(out["rows"]) == 10


def test_exclusion_drains_server(fast_dd):
    """Writing an exclusion (the operator action) is all it takes: the DD
    role observes `\xff/conf/excluded/...` and relocates every shard off
    the excluded server."""
    from foundationdb_tpu.client.management import exclude_servers

    c = SimCluster(seed=174, n_storages=4, n_tlogs=2)
    db = c.database()
    fill(c, db)
    dd = c.data_distributor()
    place_teams(c, db, dd)
    role = c.dd_role(dd)

    c.run_all([(db, exclude_servers(db, ["ss1"]))], timeout_vt=200.0)

    async def drained():
        for _b, _e, team, dest in await dd.read_shard_map():
            if "ss1" in set(team) | set(dest):
                return False
        return True

    assert wait_until(c, db, drained, timeout_vt=600.0)
    # ss1 is still alive — drain must not have used it as a spare either.
    teams = shard_teams(c, db, dd)
    assert all("ss1" not in t | d for t, d in teams.values())
    role.stop()


def test_dd_probe_corpus(fast_dd):
    """Coverage gate for the DD probe set: the existing scenarios assert
    OUTCOMES (healed teams, split shards); this gate asserts the probed
    rare PATHS actually fire — dd_storage_declared_failed, heal/rebalance
    enqueues, auto split — so a silently-dead path is loud (the TEST()
    discipline; these probes were write-only before)."""
    from foundationdb_tpu.flow import testprobe

    before = {
        n: testprobe.hit_sites.get(n, 0)
        for n in (
            "dd_storage_declared_failed",
            "dd_heal_enqueued",
            "dd_auto_split_fired",
        )
    }
    fast_dd.dd_shard_max_bytes = 3000
    fast_dd.dd_shard_min_bytes = 0
    c = SimCluster(seed=177, n_storages=3)
    db = c.database()
    dd = c.data_distributor()

    async def go():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0", "ss1"])
        await dd.split(b"\xff")

    c.run_until(db.process.spawn(go()), timeout_vt=500.0)
    c.dd_role(dd)

    # Hot writes trip the split threshold.
    for j in range(4):
        async def txn(tr, j=j):
            for i in range(60):
                tr.set(b"p%d%03d" % (j, i), b"x" * 40)

        c.run_all([(db, db.run(txn))], timeout_vt=500.0)

    # Kill a team member permanently: failure declaration + heal enqueue.
    c.storage_procs[1].kill()

    def fired():
        return all(
            testprobe.hit_sites.get(n, 0) > b for n, b in before.items()
        )

    async def wait():
        for _ in range(2000):
            if fired():
                return True
            await c.loop.delay(0.25)
        return False

    assert c.run_until(db.process.spawn(wait()), timeout_vt=2000.0), {
        n: testprobe.hit_sites.get(n, 0) - b for n, b in before.items()
    }
