"""Storage sharding end-to-end: DD seeding/split/move, client location
cache with wrong_shard_server re-routing, invariants under concurrent moves.

Ref: fdbserver/MoveKeys.actor.cpp (startMoveKeys/finishMoveKeys),
fdbclient/NativeAPI.actor.cpp:1027 (getKeyLocation + invalidation),
fdbserver/workloads/RandomMoveKeys.actor.cpp (moves under load).
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server import system_keys as sk


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def settle(c, db, t=0.1):
    """Storages apply the log asynchronously after a commit; drive a little
    virtual time before asserting their internal maps."""

    async def idle():
        await c.loop.delay(t)

    c.run_until(db.process.spawn(idle()))


def fill(c, db, n=60, prefix=b"k"):
    async def txn(tr):
        for i in range(n):
            tr.set(prefix + b"%03d" % i, b"v%d" % i)

    c.run_all([(db, db.run(txn))])


def read_all(c, db, prefix=b"k"):
    out = {}

    async def txn(tr):
        out["rows"] = await tr.get_range(prefix, prefix + b"\xff")

    c.run_all([(db, db.run(txn))])
    return out["rows"]


def test_seed_spread_and_cross_shard_reads():
    c = SimCluster(seed=31, n_storages=3)
    db = c.database()
    fill(c, db)
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.spread_evenly(split_points=[b"k020", b"k040"])

    c.run_until(db.process.spawn(place()), timeout_vt=500.0)
    settle(c, db)

    # Each storage owns part of the user keyspace.
    owners = [s for s in c.storages if any(
        v for _b, _e, v in s.owned.intersecting(b"k", b"l"))]
    assert len(owners) == 3
    # ss0 keeps the system keyspace.
    assert c.storages[0].owned[b"\xff/keyServers/"]

    # Cross-shard range read returns everything, in order.
    rows = read_all(c, db)
    assert [k for k, _ in rows] == [b"k%03d" % i for i in range(60)]
    assert rows[0][1] == b"v0" and rows[-1][1] == b"v59"

    # Reverse cross-shard read too.
    out = {}

    async def rev(tr):
        out["rows"] = await tr.get_range(b"k", b"k\xff", reverse=True, limit=25)

    c.run_all([(db, db.run(rev))])
    assert [k for k, _ in out["rows"]] == [b"k%03d" % i for i in range(59, 34, -1)]

    # Point reads route to the right shards (fresh client = cold cache).
    db2 = c.database()
    vals = {}

    async def points(tr):
        vals[b"k005"] = await tr.get(b"k005")
        vals[b"k025"] = await tr.get(b"k025")
        vals[b"k045"] = await tr.get(b"k045")

    c.run_all([(db2, db2.run(points))])
    assert vals == {b"k005": b"v5", b"k025": b"v25", b"k045": b"v45"}


def test_stale_location_cache_rerouted_after_move():
    c = SimCluster(seed=32, n_storages=2)
    db = c.database()
    fill(c, db, n=20)
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"k010")
        await dd.split(b"\xff")

    c.run_until(db.process.spawn(place()), timeout_vt=500.0)

    # Warm this client's cache on the pre-move layout.
    assert dict(read_all(c, db))[b"k015"] == b"v15"

    async def do_move():
        await dd.move(b"k010", ["ss1"])

    c.run_until(db.process.spawn(do_move()), timeout_vt=500.0)
    settle(c, db)
    assert any(v for _b, _e, v in c.storages[1].owned.intersecting(b"k010", b"l"))
    assert not any(
        v for _b, _e, v in c.storages[0].owned.intersecting(b"k010", b"k\xff")
    )

    # The stale cache points at ss0; wrong_shard_server must re-route
    # transparently, and writes must still land.
    vals = {}

    async def rw(tr):
        vals["get"] = await tr.get(b"k015")
        tr.set(b"k015", b"v15b")

    c.run_all([(db, db.run(rw))])
    assert vals["get"] == b"v15"

    async def verify(tr):
        vals["after"] = await tr.get(b"k015")

    c.run_all([(db, db.run(verify))])
    assert vals["after"] == b"v15b"


def test_cycle_invariant_under_concurrent_moves():
    """The Cycle workload keeps its ring invariant while DD bounces a shard
    between storages (ref: RandomMoveKeys + Cycle compound workloads)."""
    N = 8
    OPS = 20
    c = SimCluster(seed=33, n_storages=2)
    db_init = c.database()

    async def init(tr):
        for i in range(N):
            tr.set(b"cycle/%03d" % i, b"%03d" % ((i + 1) % N))

    c.run_all([(db_init, db_init.run(init))])

    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"cycle/004")
        await dd.split(b"\xff")

    c.run_until(db_init.process.spawn(place()), timeout_vt=500.0)

    dbs = [c.database() for _ in range(3)]
    done = []

    def worker(db, wid):
        async def go():
            rng = c.loop.rng
            for _ in range(OPS):
                async def op(tr):
                    a = int(rng.random_int(0, N))
                    ka = b"cycle/%03d" % a
                    b = int((await tr.get(ka)).decode())
                    kb = b"cycle/%03d" % b
                    cc = int((await tr.get(kb)).decode())
                    kc = b"cycle/%03d" % cc
                    d = int((await tr.get(kc)).decode())
                    tr.set(ka, b"%03d" % cc)
                    tr.set(kc, b"%03d" % b)
                    tr.set(kb, b"%03d" % d)

                await db.run(op)
            done.append(wid)

        return go()

    async def mover():
        # Bounce the [cycle/004, ...) shard back and forth during the load.
        for dest in (["ss1"], ["ss0"], ["ss1"]):
            await dd.move(b"cycle/004", dest)
            await c.loop.delay(0.2)

    tasks = [db.process.spawn(worker(db, i)) for i, db in enumerate(dbs)]
    tasks.append(db_init.process.spawn(mover()))
    from foundationdb_tpu.flow.eventloop import all_of

    c.run_until(all_of(tasks), timeout_vt=5000.0)
    assert len(done) == 3

    out = {}

    async def check(tr):
        out["ring"] = await tr.get_range(b"cycle/", b"cycle0")

    settle(c, db_init)
    c.run_all([(db_init, db_init.run(check))])
    ring = {k: int(v.decode()) for k, v in out["ring"]}
    assert len(ring) == N
    seen, cur = set(), 0
    for _ in range(N):
        assert cur not in seen
        seen.add(cur)
        cur = ring[b"cycle/%03d" % cur]
    assert cur == 0 and len(seen) == N
    # Final placement: the bounced shard lives on ss1.
    assert any(
        v for _b, _e, v in c.storages[1].owned.intersecting(b"cycle/004", b"d")
    )


def test_shard_map_is_authoritative_in_db():
    """The shard map is data: readable back from the system keyspace and
    consistent with what storages enforce (ref: keyServers as ordinary
    keys, SystemData.cpp)."""
    c = SimCluster(seed=34, n_storages=2)
    db = c.database()
    fill(c, db, n=10)
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"k005")
        await dd.split(b"\xff")
        await dd.move(b"k005", ["ss1"])
        return await dd.read_shard_map()

    shard_map = c.run_until(db.process.spawn(place()), timeout_vt=500.0)
    by_begin = {b: (e, team, dest) for b, e, team, dest in shard_map}
    assert by_begin[b"k005"][1] == ["ss1"] and not by_begin[b"k005"][2]
    assert by_begin[b""][1] == ["ss0"]
    # Determinism: the same scenario replays identically from the seed.
    assert c.loop.rng.random_int(0, 1 << 30) is not None


def test_auto_split_on_byte_samples():
    """DD splits oversized shards at the byte-sample median (ref:
    DataDistributionTracker split on shard size; StorageMetrics byte
    sample)."""
    c = SimCluster(seed=160, n_storages=2)
    db = c.database()

    # Skewed bulk: many large values under one prefix, a few elsewhere.
    async def fill(tr, base):
        for i in range(base, base + 40):
            tr.set(b"big/%04d" % i, b"x" * 300)

    for base in range(0, 160, 40):
        c.run_all([(db, db.run(lambda tr, b=base: fill(tr, b)))])

    async def small(tr):
        for i in range(5):
            tr.set(b"tiny/%02d" % i, b"y")

    c.run_all([(db, db.run(small))])
    settle(c, db)

    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        return await dd.auto_split(max_shard_bytes=20000)

    split_keys = c.run_until(db.process.spawn(place()), timeout_vt=5000.0)
    assert split_keys, "no split happened"
    assert all(k.startswith(b"big/") for k in split_keys), split_keys

    async def verify():
        return await dd.read_shard_map()

    shard_map = c.run_until(db.process.spawn(verify()), timeout_vt=1000.0)
    assert len(shard_map) >= 2
    # Data integrity across the split boundary.
    out = {}

    async def check(tr):
        rows = await tr.get_range(b"big/", b"big0", limit=1 << 20)
        out["n"] = len(rows)

    c.run_all([(db, db.run(check))])
    assert out["n"] == 160


def test_byte_sample_follows_moves_and_clears():
    """Metrics stay truthful across the paths the sample must track: shard
    fetch populates the destination's sample, disown clears the source's,
    and clear_range drops entries."""
    c = SimCluster(seed=161, n_storages=2)
    db = c.database()

    async def fill(tr):
        for i in range(50):
            tr.set(b"mv/%03d" % i, b"z" * 200)

    c.run_all([(db, db.run(fill))])
    settle(c, db)
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"mv/")
        await dd.move(b"mv/", ["ss1"])

    c.run_until(db.process.spawn(place()), timeout_vt=5000.0)
    settle(c, db, 0.3)
    s0, s1 = c.storages
    # Destination learned the bytes through the fetch; source dropped them.
    assert s1.byte_sample.bytes_in(b"mv/", b"mv0") > 5000
    assert s0.byte_sample.bytes_in(b"mv/", b"mv0") == 0

    async def wipe(tr):
        tr.clear_range(b"mv/", b"mv0")

    c.run_all([(db, db.run(wipe))])
    settle(c, db, 0.3)
    assert s1.byte_sample.bytes_in(b"mv/", b"mv0") == 0


def test_auto_merge_coalesces_small_adjacent_shards():
    """Adjacent small shards on the same team merge back into one record
    (ref: DataDistributionTracker's merge path); big shards and
    cross-system-boundary pairs do not."""
    c = SimCluster(seed=41, n_storages=2)
    db = c.database()
    fill(c, db, n=30)
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"k010")
        await dd.split(b"k020")
        await dd.split(b"\xff")

    c.run_until(db.process.spawn(place()), timeout_vt=500.0)

    async def merge_round():
        before = [
            (b, e) for b, e, _t, _d in await dd.read_shard_map() if b < b"\xff"
        ]
        assert len(before) == 3, before
        absorbed = await dd.auto_merge(min_shard_bytes=1 << 20)
        after = [
            (b, e, t) for b, e, t, _d in await dd.read_shard_map() if b < b"\xff"
        ]
        return absorbed, after

    absorbed, after = c.run_until(
        db.process.spawn(merge_round()), timeout_vt=500.0
    )
    # All three user shards coalesced into one settled record.
    assert absorbed == [b"k010", b"k020"], absorbed
    assert len(after) == 1 and after[0][0] == b"" and after[0][1] == b"\xff"

    # Reads still route correctly through the merged map.
    db.invalidate_location(b"")
    assert dict(read_all(c, db))[b"k015"] == b"v15"

    # A shard ABOVE the byte threshold does not merge.  (Values must be
    # big enough to register in the probabilistic byte sample.)
    async def split_again():
        async def big(tr):
            for i in range(10):
                tr.set(b"k%03d" % i, b"x" * 5000)
            for i in range(10, 20):
                tr.set(b"k%03d" % i, b"x" * 5000)

        await db.run(big)
        await c.loop.delay(0.2)  # applied + sampled
        await dd.split(b"k010")
        return await dd.auto_merge(min_shard_bytes=1)  # everything too big

    absorbed2 = c.run_until(db.process.spawn(split_again()), timeout_vt=500.0)
    assert absorbed2 == []


def test_superseded_fetch_stops_write_through():
    """A fetch superseded MID-PAGE by a re-issued move must stop writing
    through to the destination's base engine: the old snapshot's stale
    rows racing the new fetch's clear+sets in one commit buffer could win
    last-writer-wins and surface after a crash (the round-5 review race).
    Drives it deterministically: tiny fetch pages, re-commit the move
    record while the first fetch is between pages, assert the probe fired
    and the final served data is byte-exact."""
    from foundationdb_tpu.flow import testprobe
    from foundationdb_tpu.flow.knobs import g_knobs

    probe_before = testprobe.hit_sites.get("fetch_superseded", 0)
    old_page = g_knobs.server.fetch_shard_page_rows
    g_knobs.server.fetch_shard_page_rows = 1  # 40 pages: the fetch
    # spans many RPC roundtrips, so the superseding record lands mid-flight
    try:
        c = SimCluster(seed=39, n_storages=2)
        db = c.database()
        fill(c, db, n=40, prefix=b"m")
        dd = c.data_distributor()

        async def place():
            await dd.register_storages(dd.storages)
            await dd.seed(["ss0"])

        c.run_until(db.process.spawn(place()), timeout_vt=500.0)
        settle(c, db)

        async def move(tr):
            tr.options["access_system_keys"] = True
            tr.set(
                sk.key_servers_key(b"m000"),
                sk.encode_key_servers(["ss0"], ["ss1"], b"m040"),
            )

        async def move_narrow(tr):
            tr.options["access_system_keys"] = True
            tr.set(
                sk.key_servers_key(b"m000"),
                sk.encode_key_servers(["ss0"], ["ss1"], b"m020"),
            )
            tr.set(
                sk.key_servers_key(b"m020"),
                sk.encode_key_servers(["ss0"], [], b"m040"),
            )

        # First move record: ss1 starts FETCHING in tiny pages.
        c.run_all([(db, db.run(move))])
        # An OVERLAPPING move with a different extent supersedes the
        # in-flight AddingShard (an identical record would be deduped as
        # a DD retry); the OLD fetch must stop writing through.
        c.run_all([(db, db.run(move_narrow))])
        # Restore the full-range move and let it complete.
        c.run_all([(db, db.run(move))])
        settle(c, db, 1.0)  # final fetch completes

        # Settle the move; ss1 serves the shard byte-exact.
        async def finish(tr):
            tr.options["access_system_keys"] = True
            tr.set(
                sk.key_servers_key(b"m000"),
                sk.encode_key_servers(["ss1"], [], b"m040"),
            )

        c.run_all([(db, db.run(finish))])
        settle(c, db, 0.5)
        rows = read_all(c, db, prefix=b"m")
        assert rows == [(b"m%03d" % i, b"v%d" % i) for i in range(40)]
        assert (
            testprobe.hit_sites.get("fetch_superseded", 0) > probe_before
        ), "the superseded-fetch path never fired — race untested"
    finally:
        g_knobs.server.fetch_shard_page_rows = old_page
