"""real_node graceful shutdown (ISSUE 8 satellite): SIGTERM closes the
transport cleanly and exits 0, so multi-process soak teardown can't leak
orphans or flake CI on kill -9 corpses."""

import os
import signal
import time

from conftest import spawn_real_node


def _read_ready(proc, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("READY "):
            return line.split()[1]
    raise AssertionError("server never printed READY")


def test_server_sigterm_clean_exit():
    proc = spawn_real_node("server", "--port", "0")
    try:
        _read_ready(proc)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=20)
        assert proc.returncode == 0, (proc.returncode, out)
        assert "SHUTDOWN" in out, out
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_ntserver_sigterm_clean_exit():
    proc = spawn_real_node("ntserver", "--port", "0")
    try:
        _read_ready(proc)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=20)
        assert proc.returncode == 0, (proc.returncode, out)
        assert "SHUTDOWN" in out, out
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_server_serves_then_shuts_down_cleanly():
    """End-to-end: a client completes real transactions, THEN the server
    is terminated — the shutdown path must not corrupt an active server's
    exit (transport close after live connections)."""
    server = spawn_real_node("server", "--port", "0")
    client = None
    try:
        addr = _read_ready(server)
        client = spawn_real_node(
            "client", addr, "--id", "c1", "--ops", "5", "--check-count", "5"
        )
        cout, _ = client.communicate(timeout=60)
        assert client.returncode == 0, cout
        assert "DONE 5" in cout, cout
        server.send_signal(signal.SIGTERM)
        out, _ = server.communicate(timeout=20)
        assert server.returncode == 0, (server.returncode, out)
        assert "SHUTDOWN" in out, out
    finally:
        for p in (client, server):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()


def test_second_sigterm_escalates():
    """The procutil ladder: install_graceful_term's second TERM SIGKILLs
    the process group (exit 143) — a wedged shutdown can't hang forever.
    Driven via a child whose stop_fn deliberately wedges."""
    import subprocess
    import sys

    from conftest import REPO_ROOT

    code = (
        "import signal, time, sys;"
        "sys.path.insert(0, %r);"
        "from foundationdb_tpu.utils.procutil import install_graceful_term;"
        "install_graceful_term(lambda: None);"  # stop that stops nothing
        "print('ARMED', flush=True);"
        "time.sleep(60)"
    ) % REPO_ROOT
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        start_new_session=True,  # its own group: killpg(0) can't hit us
    )
    try:
        assert proc.stdout.readline().startswith("ARMED")
        proc.send_signal(signal.SIGTERM)  # graceful: wedges (sleep goes on)
        time.sleep(0.2)
        assert proc.poll() is None  # still alive: stop_fn did nothing
        proc.send_signal(signal.SIGTERM)  # escalation: killpg + exit
        proc.wait(timeout=10)
        assert proc.returncode in (143, -signal.SIGKILL), proc.returncode
    finally:
        if proc.poll() is None:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait()
