"""IndexedSet (order-statistic treap with metric sums).

Ref: flow/IndexedSet.h — per-node subtree totals giving O(log n)
insert/erase/sumRange/index; StorageMetrics' byte sample rides it
(StorageMetrics.actor.h:404).
"""

import random
import time

import pytest

from foundationdb_tpu.flow.rng import DeterministicRandom
from foundationdb_tpu.utils.indexed_set import IndexedSet


def k(i):
    return b"%06d" % i


def test_differential_vs_dict_model():
    rng = DeterministicRandom(7)
    py = random.Random(7)
    s = IndexedSet(rng)
    model = {}
    for step in range(3000):
        op = py.random()
        key = k(py.randrange(0, 400))
        if op < 0.5:
            w = py.randrange(1, 1000)
            s.set(key, w)
            model[key] = w
        elif op < 0.7:
            s.erase(key)
            model.pop(key, None)
        elif op < 0.8:
            a = k(py.randrange(0, 400))
            b = k(py.randrange(0, 400))
            if a > b:
                a, b = b, a
            s.erase_range(a, b)
            for mk in [x for x in model if a <= x < b]:
                del model[mk]
        else:
            a = k(py.randrange(0, 400))
            b = k(py.randrange(0, 400))
            if a > b:
                a, b = b, a
            want = sum(w for mk, w in model.items() if a <= mk < b)
            assert s.sum_range(a, b) == want, step
            want_n = sum(1 for mk in model if a <= mk < b)
            assert s.count_range(a, b) == want_n, step
        if step % 500 == 0:
            assert len(s) == len(model)
            assert s.keys_in(b"", None) == sorted(model)
    assert s.sum_range(b"", None) == sum(model.values())


def test_key_at_metric():
    rng = DeterministicRandom(9)
    s = IndexedSet(rng)
    for i in range(10):
        s.set(k(i), 10)  # total 100
    # Accumulating from the start: weight exceeds 35 at the 4th key
    # (inclusive prefix of k(3) is 40 > 35).
    assert s.key_at_metric(b"", None, 35) == k(3)
    assert s.key_at_metric(b"", None, 0) == k(0)
    assert s.key_at_metric(b"", None, 99) == k(9)
    assert s.key_at_metric(b"", None, 100) is None
    # Range-restricted: start accumulating at k(5).
    assert s.key_at_metric(k(5), None, 15) == k(6)
    assert s.key_at_metric(k(5), k(8), 25) == k(7)
    assert s.key_at_metric(k(5), k(8), 30) is None


@pytest.mark.slow  # tier-1 headroom (ISSUE 4): scaling sweep
def test_operations_scale_logarithmically():
    """The review-visible property: point ops on 64k keys must not scan.
    Compare per-op time at 4k vs 64k keys (16x data, ~1.33x log factor;
    assert < 6x with scheduler slack — a linear structure shows ~16x)."""

    def build(n, seed):
        rng = DeterministicRandom(seed)
        s = IndexedSet(rng)
        for i in range(n):
            s.set(k(i * 7 % n), 10 + i % 90)
        return s

    def probe(s, n, reps):
        t0 = time.perf_counter()
        for i in range(reps):
            s.set(k((i * 13) % n), 55)
            s.sum_range(k(n // 4), k(3 * n // 4))
        return time.perf_counter() - t0

    small, big = build(1 << 12, 1), build(1 << 16, 2)
    probe(small, 1 << 12, 500)  # warm
    t_small = min(probe(small, 1 << 12, 2000) for _ in range(3))
    t_big = min(probe(big, 1 << 16, 2000) for _ in range(3))
    assert t_big < 6 * t_small, (t_small, t_big)


def test_byte_sample_behavior_unchanged():
    """ByteSample semantics through the new backing structure."""
    from foundationdb_tpu.server.storage import ByteSample

    rng = DeterministicRandom(11)
    bs = ByteSample(rng)
    for i in range(50):
        bs.update(k(i), 200)  # always admitted (>= UNIT)
    assert bs.bytes_in(b"", None) == 50 * 200
    assert bs.bytes_in(k(10), k(20)) == 10 * 200
    sp = bs.split_point(b"", None)
    assert sp is not None and k(20) <= sp <= k(30)
    bs.remove_range(k(0), k(25))
    assert bs.bytes_in(b"", None) == 25 * 200
    # Re-update overwrites, erase-by-downsample removes.
    bs.update(k(30), 1000)
    assert bs.bytes_in(k(30), k(31)) == 1000
    assert bs.split_point(k(40), k(41)) is None  # single key: no split
