"""Tag-partitioned log + storage replication >= 2.

Ref: TagPartitionedLogSystem.actor.cpp:63 (per-tag push to a policy-chosen
tlog subset), tLogPeekMessages :946 (per-tag peek; failover across the
tag's replicas), DDTeamCollection (teams of storageTeamSize), and the
ConsistencyCheck workload (checkDataConsistency :562 — every replica of
every shard agrees).
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.interfaces import GetKeyValuesRequest


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def settle(c, db, t=0.2):
    async def idle():
        await c.loop.delay(t)

    c.run_until(db.process.spawn(idle()))


def place(c, db, dd, replication, split_points):
    async def go():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.spread_evenly(
            split_points=split_points, replication=replication
        )

    c.run_until(db.process.spawn(go()), timeout_vt=500.0)
    settle(c, db)


def fill(c, db, n=50):
    async def txn(tr):
        for i in range(n):
            tr.set(b"k%03d" % i, b"v%d" % i)

    c.run_all([(db, db.run(txn))])


def replica_contents(c, db, storage, begin, end, version):
    """Direct full-range read from one storage (no client routing)."""
    out = {}

    async def go():
        rep = await storage.interface().get_key_values.get_reply(
            db.process,
            GetKeyValuesRequest(begin=begin, end=end, version=version),
        )
        out["rows"] = rep.data

    c.run_until(db.process.spawn(go()), timeout_vt=200.0)
    return out["rows"]


def check_replicas_consistent(c, db):
    """ConsistencyCheck analog: every live replica of every user shard
    returns identical contents at one version."""
    version = c.proxy.committed.get()
    by_id = {s.storage_id: s for s in c.storages}
    shard_map = list(c.proxy.key_servers.items())
    checked = 0
    for b, e, v in shard_map:
        if v is None or b >= b"\xff":
            continue
        team = [by_id[sid] for sid in v[0] if sid in by_id]
        live = [s for s in team if s.process.alive]
        if len(live) < 2:
            continue
        e2 = e if e is not None else b"\xff"
        contents = [
            replica_contents(c, db, s, b, min(e2, b"\xff"), version)
            for s in live
        ]
        for other in contents[1:]:
            assert other == contents[0], (b, e)
        checked += 1
    return checked


def test_replicated_teams_agree_under_load():
    c = SimCluster(seed=41, n_storages=3, n_tlogs=2)
    db = c.database()
    fill(c, db)
    dd = c.data_distributor()
    place(c, db, dd, replication=2, split_points=[b"k020", b"k040"])

    # Every storage holds SOME shard, each shard has 2 replicas.
    owners = [s for s in c.storages if any(
        val for _b, _e, val in s.owned.intersecting(b"k", b"l"))]
    assert len(owners) == 3

    # More writes after placement (tagged per team now).
    async def more(tr):
        for i in range(50):
            tr.set(b"k%03d" % i, b"w%d" % i)

    c.run_all([(db, db.run(more))])
    settle(c, db)
    assert check_replicas_consistent(c, db) >= 3

    # Cross-shard client read sees the new values.
    out = {}

    async def read(tr):
        out["rows"] = await tr.get_range(b"k", b"k\xff")

    c.run_all([(db, db.run(read))])
    assert len(out["rows"]) == 50 and out["rows"][7][1] == b"w7"


def test_storage_kill_no_data_loss_and_heal():
    """Kill one storage mid-workload: reads fail over to the surviving
    replica; DD re-replicates onto a spare from the survivor (ref:
    teamTracker + MoveKeys healing)."""
    c = SimCluster(seed=42, n_storages=4, n_tlogs=2)
    db = c.database()
    fill(c, db)
    dd = c.data_distributor()
    # Teams of 2 over ss0..ss2; ss3 is the spare.
    async def go():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"k025")
        await dd.split(b"\xff")
        await dd.move(b"", ["ss0", "ss1"])
        await dd.move(b"k025", ["ss1", "ss2"])

    c.run_until(db.process.spawn(go()), timeout_vt=500.0)
    settle(c, db)

    victim = c.storages[1]  # replica of BOTH shards
    victim.process.kill()

    # All data still readable through the client (rotates to survivors).
    out = {}

    async def read(tr):
        out["rows"] = await tr.get_range(b"k", b"k\xff")

    c.run_all([(db, db.run(read))], timeout_vt=500.0)
    assert len(out["rows"]) == 50

    # Heal: survivors source the re-replication to the spare.
    async def heal():
        await dd.heal("ss1", "ss3")

    c.run_until(db.process.spawn(heal()), timeout_vt=1000.0)
    settle(c, db)
    m = {b: (team, dest) for b, _e, team, dest in c.run_until(
        db.process.spawn(dd.read_shard_map()), timeout_vt=200.0)}
    assert set(m[b""][0]) == {"ss0", "ss3"}
    assert set(m[b"k025"][0]) == {"ss2", "ss3"}
    # The spare actually serves the data now.
    version = c.proxy.committed.get()
    rows = replica_contents(c, db, c.storages[3], b"k", b"k\xff", version)
    assert len(rows) == 50
    assert check_replicas_consistent(c, db) >= 2


def test_tlog_kill_peek_failover():
    """With log replication 2, each tag lives on both logs: after one tlog
    dies, storages keep serving applied data and their peek cursors rotate
    to the surviving replica (ref: peek-merge cursor failover :568-581).
    NOTE the known-committed bound: storages only APPLY versions proven
    durable on every replica (or proxy-acked), so with a log down and no
    recovery (static cluster) the un-acked tail stays unapplied — the
    dynamic-cluster tests cover the recovery that drains it."""
    c = SimCluster(seed=43, n_storages=2, n_tlogs=2)
    db = c.database()
    fill(c, db, n=30)
    settle(c, db, t=0.3)  # storages confirm + apply through the fill
    version = c.proxy.committed.get()
    c.tlogs[1].process.kill()
    settle(c, db, t=0.3)  # peek cursors rotate to the survivor
    rows = replica_contents(c, db, c.storages[0], b"k", b"k\xff", version)
    assert len(rows) == 30

    out = {}

    async def read(tr):
        out["v"] = await tr.get(b"k007")

    c.run_all([(db, db.run(read))])
    assert out["v"] == b"v7"


def test_cycle_invariant_with_replication_and_kill():
    """Cycle workload over replicated shards; one replica dies mid-run;
    the ring invariant holds and survivors agree (zero data loss)."""
    N = 8
    c = SimCluster(seed=44, n_storages=3, n_tlogs=2)
    db_init = c.database()

    async def init(tr):
        for i in range(N):
            tr.set(b"cycle/%03d" % i, b"%03d" % ((i + 1) % N))

    c.run_all([(db_init, db_init.run(init))])
    dd = c.data_distributor()

    async def go():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"cycle/004")
        await dd.split(b"\xff")
        await dd.move(b"", ["ss0", "ss1"])
        await dd.move(b"cycle/004", ["ss1", "ss2"])

    c.run_until(db_init.process.spawn(go()), timeout_vt=500.0)
    settle(c, db_init)

    dbs = [c.database() for _ in range(3)]
    done = []

    def worker(db, wid):
        async def run():
            rng = c.loop.rng
            for _ in range(15):
                async def op(tr):
                    a = int(rng.random_int(0, N))
                    ka = b"cycle/%03d" % a
                    b = int((await tr.get(ka)).decode())
                    kb = b"cycle/%03d" % b
                    cc = int((await tr.get(kb)).decode())
                    kc = b"cycle/%03d" % cc
                    d = int((await tr.get(kc)).decode())
                    tr.set(ka, b"%03d" % cc)
                    tr.set(kc, b"%03d" % b)
                    tr.set(kb, b"%03d" % d)

                await db.run(op)
            done.append(wid)

        return run()

    async def killer():
        await c.loop.delay(0.15)
        c.storages[1].process.kill()  # a replica of both shards

    tasks = [db.process.spawn(worker(db, i)) for i, db in enumerate(dbs)]
    tasks.append(db_init.process.spawn(killer()))
    from foundationdb_tpu.flow.eventloop import all_of

    c.run_until(all_of(tasks), timeout_vt=5000.0)
    assert len(done) == 3
    settle(c, db_init)

    out = {}

    async def check(tr):
        out["ring"] = await tr.get_range(b"cycle/", b"cycle0")

    c.run_all([(db_init, db_init.run(check))])
    ring = {k: int(v.decode()) for k, v in out["ring"]}
    assert len(ring) == N
    seen, cur = set(), 0
    for _ in range(N):
        assert cur not in seen
        seen.add(cur)
        cur = ring[b"cycle/%03d" % cur]
    assert cur == 0 and len(seen) == N
