"""MergePeekCursor coverage semantics.

Ref: fdbserver/LogSystemPeekCursor.actor.cpp — MergedPeekCursor must
never emit a gapped stream: a member that cannot serve a range is fine
only while ANOTHER member covers it; when nobody does, the merge must
fail loudly (the single-log peek_below_begin discipline), because every
consumer downstream (backup chunks, DR apply, log routers) assumes the
stream is complete through the returned horizon.
"""

import pytest

from foundationdb_tpu.client.types import Mutation, MutationType
from foundationdb_tpu.flow import EventLoop, set_event_loop
from foundationdb_tpu.flow.error import FdbError
from foundationdb_tpu.rpc import SimNetwork
from foundationdb_tpu.rpc.peek_cursor import MergePeekCursor
from foundationdb_tpu.server.interfaces import (
    TAG_ALL,
    TLogCommitRequest,
    TLogPopRequest,
)
from foundationdb_tpu.server.tlog import TLog


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def _env(seed):
    loop = EventLoop(seed=seed)
    set_event_loop(loop)
    return loop, SimNetwork(loop)


def _mut(i):
    return Mutation(MutationType.SET_VALUE, b"k%04d" % i, b"v%d" % i)


async def _commit(iface, proc, version, prev):
    await iface.commit.get_reply(
        proc,
        TLogCommitRequest(
            version=version,
            prev_version=prev,
            tagged={TAG_ALL: [(0, _mut(version))]},
            epoch=0,
        ),
    )


async def _pop(iface, proc, tag, version):
    await iface.pop.get_reply(
        proc, TLogPopRequest(tag=tag, version=version)
    )


def test_fresh_replacement_log_served_by_survivor():
    """A merge over [survivor, fresh-replacement] delivers the FULL
    stream: the replacement (begin_version = recovery point) serves only
    its own range, the survivor covers below it — no wedge, no gap."""
    loop, net = _env(11)
    proc = net.process("c")
    done = {}

    async def run():
        survivor = TLog(net.process("t0"))
        fresh = TLog(
            net.process("t1"), epoch_begin_version=10, begin_version=10
        )
        prev = 0
        for v in range(1, 21):
            await _commit(survivor.interface(), proc, v, prev)
            if v > 10:
                await _commit(
                    fresh.interface(), proc, v, prev if prev > 10 else 10
                )
            prev = v
        cur = MergePeekCursor(
            proc,
            [survivor.interface(), fresh.interface()],
            tags=None,
            begin=0,
        )
        got = []
        while True:
            entries, horizon = await cur.next_batch()
            got.extend(v for v, _b in entries)
            if horizon >= 20:
                break
        assert got == list(range(1, 21)), got
        done["ok"] = True

    loop.run_until(proc.spawn(run(), "t"), timeout_vt=200.0)
    assert done.get("ok")


def test_uncovered_range_raises_not_skips():
    """EVERY member's floor above the merge begin: the cursor must raise
    peek_below_begin (nobody holds the range), never silently advance."""
    from foundationdb_tpu.flow import testprobe

    probe_before = testprobe.hit_sites.get("merge_cursor_uncovered", 0)
    loop, net = _env(12)
    proc = net.process("c")
    done = {}

    async def run():
        logs = [TLog(net.process(f"t{i}")) for i in range(2)]
        prev = 0
        for v in range(1, 11):
            for lg in logs:
                await _commit(lg.interface(), proc, v, prev)
            prev = v
        # Both replicas popped to 6: versions 1..6 retained nowhere.
        for lg in logs:
            await _pop(lg.interface(), proc, "consumer", 6)
        cur = MergePeekCursor(
            proc, [lg.interface() for lg in logs], tags=None, begin=0
        )
        with pytest.raises(FdbError) as ei:
            await cur.next_batch()
        assert ei.value.name == "peek_below_begin"
        done["ok"] = True

    loop.run_until(proc.spawn(run(), "t"), timeout_vt=200.0)
    assert done.get("ok")
    assert (
        testprobe.hit_sites.get("merge_cursor_uncovered", 0) > probe_before
    )


def test_mid_stream_floor_jump_raises():
    """The hole check must keep working AFTER the first batch: a cursor
    that tailed to horizon H, then found every replica's floor above H,
    must raise — covered_from tracks the CURRENT contiguous segment, not
    a min-ever that first-batch coverage would pin low forever."""
    loop, net = _env(13)
    proc = net.process("c")
    done = {}

    async def run():
        logs = [TLog(net.process(f"t{i}")) for i in range(2)]
        prev = 0
        for v in range(1, 11):
            for lg in logs:
                await _commit(lg.interface(), proc, v, prev)
            prev = v
        cur = MergePeekCursor(
            proc, [lg.interface() for lg in logs], tags=None, begin=0
        )
        got = []
        while cur.begin < 10:
            entries, _h = await cur.next_batch()
            got.extend(v for v, _b in entries)
        assert got == list(range(1, 11))
        # More commits land; every replica pops past the cursor's resume
        # point (a lagging consumer that lost the retention race).
        for v in range(11, 21):
            for lg in logs:
                await _commit(lg.interface(), proc, v, prev)
            prev = v
        for lg in logs:
            await _pop(lg.interface(), proc, "consumer", 16)
        with pytest.raises(FdbError) as ei:
            await cur.next_batch()
        assert ei.value.name == "peek_below_begin"
        done["ok"] = True

    loop.run_until(proc.spawn(run(), "t"), timeout_vt=200.0)
    assert done.get("ok")


def test_tag_slot_hole_not_masked_by_unrelated_log():
    """Tag-aware coverage: tag ss:c lives on ring logs [0, 1] of 3.  Both
    its replicas floored above begin must raise peek_below_begin even
    though the UNRELATED log 2 still covers begin — one log's coverage
    for other tags must not mask a hole in this tag's whole slot."""
    loop, net = _env(14)
    proc = net.process("c")
    done = {}

    async def run():
        logs = [TLog(net.process(f"t{i}")) for i in range(3)]
        prev = 0
        for v in range(1, 11):
            # ss:c rides its slot [0, 1]; log 2 carries only broadcast.
            for i, lg in enumerate(logs):
                tagged = {TAG_ALL: [(0, _mut(v))]}
                if i in (0, 1):
                    tagged["ss:c"] = [(1, _mut(v))]
                await lg.interface().commit.get_reply(
                    proc,
                    TLogCommitRequest(
                        version=v, prev_version=prev, tagged=tagged, epoch=0
                    ),
                )
            prev = v
        # Both slot members popped past 6; log 2 untouched.
        for lg in logs[:2]:
            await _pop(lg.interface(), proc, "consumer", 6)
        cur = MergePeekCursor(
            proc,
            [lg.interface() for lg in logs],
            tags=["ss:c"],
            begin=0,
        )
        with pytest.raises(FdbError) as ei:
            await cur.next_batch()
        assert ei.value.name == "peek_below_begin"
        done["ok"] = True

    loop.run_until(proc.spawn(run(), "t"), timeout_vt=200.0)
    assert done.get("ok")
