"""WriteDuringRead workload + the client semantics it exists to check.

Ref: fdbserver/workloads/WriteDuringRead.actor.cpp (byte-exact memory model
vs RYW transaction under concurrent intra-transaction ops) and
ReadYourWrites.actor.cpp's used_during_commit contract.
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.error import FdbError
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.workloads import (
    RandomReadWriteWorkload,
    WriteDuringReadWorkload,
    run_workloads,
)


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


@pytest.mark.parametrize("seed", [7001, 7002, 7003])
def test_write_during_read_memory_model(seed):
    c = SimCluster(seed=seed, n_proxies=2, n_storages=2)
    wl = WriteDuringReadWorkload(nodes=30, txns=10)
    run_workloads(c, [wl], timeout_vt=30000.0)
    assert wl.committed_txns > 0
    assert not wl.mismatches


def test_random_read_write_workload():
    c = SimCluster(seed=7010, n_proxies=2)
    wl = RandomReadWriteWorkload(nodes=100, actors=3, txns_per_actor=6)
    run_workloads(c, [wl], timeout_vt=30000.0)
    assert wl.committed == 18


def test_read_does_not_see_write_issued_during_flight():
    """A set() issued while a get() is awaiting storage must NOT leak into
    the get's result (issue-time RYW snapshot; the reference computes the
    expected value synchronously at op issue — WriteDuringRead.actor.cpp
    getAndCompare)."""
    c = SimCluster(seed=7020)
    db = c.database("t")

    async def scenario():
        async def fill(tr):
            tr.set(b"k", b"old")

        await db.run(fill)

        tr = db.create_transaction()
        got = {}

        async def reader():
            got["v"] = await tr.get(b"k")

        task = db.process.spawn(reader(), "inflight_get")
        # Let the read reach storage, then write while it is in flight.
        await c.loop.delay(0.0001)
        tr.set(b"k", b"new")
        await task
        # Issue-time snapshot: the in-flight read must see the OLD value.
        assert got["v"] == b"old", got
        # A read issued after the write sees it (RYW still works).
        assert await tr.get(b"k") == b"new"

    c.run_until(db.process.spawn(scenario(), "scenario"), timeout_vt=1000.0)


def test_used_during_commit():
    c = SimCluster(seed=7021)
    db = c.database("t")

    async def scenario():
        tr = db.create_transaction()
        tr.set(b"a", b"1")
        commit_task = db.process.spawn(tr.commit(), "commit")
        # Yield so the commit coroutine actually starts (and is in flight).
        await c.loop.delay(0.0001)
        # Ops racing the in-flight commit fail cleanly.
        with pytest.raises(FdbError, match="used_during_commit"):
            await tr.get(b"a")
        with pytest.raises(FdbError, match="used_during_commit"):
            tr.set(b"b", b"2")
        with pytest.raises(FdbError, match="used_during_commit"):
            tr.clear(b"a")
        await commit_task
        # Still unusable after commit completes, until reset.
        with pytest.raises(FdbError, match="used_during_commit"):
            await tr.get(b"a")
        tr.reset()
        assert await tr.get(b"a") == b"1"

    c.run_until(db.process.spawn(scenario(), "scenario"), timeout_vt=1000.0)


@pytest.mark.parametrize("seed", [7101, 7102, 7103, 7104])
def test_fuzz_api_workload(seed):
    from foundationdb_tpu.workloads import FuzzApiWorkload

    c = SimCluster(seed=seed, n_proxies=2)
    wl = FuzzApiWorkload(nodes=20, txns=15)
    run_workloads(c, [wl], timeout_vt=30000.0)
    assert not wl.failures
    assert len(wl.errors_exercised) >= 3, wl.errors_exercised


def test_writemap_reads_scale_with_key_ops_not_log_size():
    """The WriteMap upgrade's point (ref: fdbclient/WriteMap.h): a read
    inside a transaction holding a LARGE mutation log must not scan it.
    Compare per-get time with 500 vs 8000 pending mutations (16x log;
    assert < 6x — the old full-log replay showed ~16x)."""
    import time

    from foundationdb_tpu.flow import set_event_loop
    from foundationdb_tpu.server import SimCluster

    def timed_reads(seed, n_muts):
        c = SimCluster(seed=seed)
        db = c.database()
        out = {}

        async def go():
            tr = db.create_transaction()
            for i in range(n_muts):
                tr.set(b"wm%06d" % i, b"v")
            # Warm + time overlay-hit reads (no storage round trip varies:
            # all keys routed the same way).
            for i in range(50):
                await tr.get(b"wm%06d" % (i % n_muts))
            t0 = time.perf_counter()
            for i in range(300):
                await tr.get(b"wm%06d" % ((i * 13) % n_muts))
            out["dt"] = time.perf_counter() - t0

        c.run_until(db.process.spawn(go()), timeout_vt=100000.0)
        set_event_loop(None)
        return out["dt"]

    t_small = min(timed_reads(910, 500) for _ in range(2))
    t_big = min(timed_reads(911, 8000) for _ in range(2))
    assert t_big < 6 * t_small, (t_small, t_big)
