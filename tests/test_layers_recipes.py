"""Container + pubsub layer recipes (ref: layers/containers, layers/pubsub).

The queue's versionstamped push is the canonical contention-free append:
pushes from concurrent writers NEVER conflict, pops carry ordinary
conflict semantics.  PubSub is a pull-model feed/inbox layer with
per-feed watermarks.
"""

import pytest

from foundationdb_tpu.client import transactional
from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.eventloop import all_of
from foundationdb_tpu.layers.pubsub import PubSub
from foundationdb_tpu.layers.queue import Queue, Vector
from foundationdb_tpu.layers.subspace import Subspace
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_queue_versionstamped_push_is_contention_free():
    """N concurrent pushers, zero conflicts (versionstamped keys), pops
    return every value exactly once in commit order."""
    c = SimCluster(seed=700, n_proxies=2)
    db = c.database()
    q = Queue(Subspace(("q",)))
    state = {"retries": 0, "popped": []}

    async def pusher(aid):
        for i in range(6):
            async def txn(tr, aid=aid, i=i):
                q.push(tr, b"%d:%d" % (aid, i))

            await db.run(txn)

    async def drive():
        await all_of(
            [db.process.spawn(pusher(a), f"push{a}") for a in range(4)]
        )
        while True:
            async def pop_txn(tr):
                return await q.pop(tr)

            v = await db.run(pop_txn)
            if v is None:
                break
            state["popped"].append(v)

    c.run_until(db.process.spawn(drive(), "qd"), timeout_vt=20000.0)
    assert len(state["popped"]) == 24
    assert len(set(state["popped"])) == 24
    # Per-pusher FIFO holds (global order is commit order).
    for a in range(4):
        mine = [v for v in state["popped"] if v.startswith(b"%d:" % a)]
        assert mine == [b"%d:%d" % (a, i) for i in range(6)]


def test_vector_recipe():
    c = SimCluster(seed=701)
    db = c.database()
    vec = Vector(Subspace(("vec",)))
    out = {}

    async def drive():
        async def fill(tr):
            for i in range(5):
                vec.set(tr, i, b"v%d" % i)

        await db.run(fill)

        async def ops(tr):
            assert await vec.size(tr) == 5
            await vec.swap(tr, 0, 4)
            out["popped"] = await vec.pop(tr)
            out["head"] = await vec.get(tr, 0)
            out["size_after"] = await vec.size(tr)

        await db.run(ops)

    c.run_until(db.process.spawn(drive(), "vd"), timeout_vt=10000.0)
    assert out["popped"] == b"v0"  # swapped to the tail, then popped
    assert out["head"] == b"v4"
    assert out["size_after"] == 4


def test_pubsub_feeds_inboxes_watermarks():
    c = SimCluster(seed=702, n_proxies=2)
    db = c.database()
    ps = PubSub(db)
    out = {}

    async def drive():
        await ps.create_feed("news")
        await ps.create_feed("sports")
        await ps.create_inbox("alice")
        await ps.subscribe("alice", "news")
        await ps.subscribe("alice", "sports")
        await ps.post("news", b"n1")
        await ps.post("sports", b"s1")
        await ps.post("news", b"n2")
        out["feeds"] = await ps.list_feeds()
        out["feed_msgs"] = await ps.get_feed_messages("news")
        out["batch1"] = await ps.get_inbox_messages("alice")
        await ps.post("news", b"n3")
        out["batch2"] = await ps.get_inbox_messages("alice")
        out["batch3"] = await ps.get_inbox_messages("alice")
        with pytest.raises(ValueError):
            await ps.subscribe("alice", "nonexistent")

    c.run_until(db.process.spawn(drive(), "psd"), timeout_vt=20000.0)
    assert out["feeds"] == ["news", "sports"]
    assert out["feed_msgs"] == [b"n1", b"n2"]
    assert sorted(out["batch1"]) == [
        ("news", b"n1"), ("news", b"n2"), ("sports", b"s1")
    ]
    assert out["batch2"] == [("news", b"n3")]  # watermark advanced
    assert out["batch3"] == []


def test_transactional_decorator_composes():
    """@transactional: database arg -> retry loop; transaction arg ->
    joins the caller's transaction (one atomic commit)."""
    c = SimCluster(seed=703)
    db = c.database()
    out = {}

    @transactional
    async def put(tr, k, v):
        tr.set(k, v)

    @transactional
    async def put_both(tr, a, b):
        await put(tr, a, b"A")  # composes into the SAME txn
        await put(tr, b, b"B")

    async def drive():
        await put(db, b"x", b"1")  # db form: own retry loop
        await put_both(db, b"y", b"z")

        async def read(tr):
            out["x"] = await tr.get(b"x")
            out["y"] = await tr.get(b"y")
            out["z"] = await tr.get(b"z")

        await db.run(read)

    c.run_until(db.process.spawn(drive(), "td"), timeout_vt=10000.0)
    assert (out["x"], out["y"], out["z"]) == (b"1", b"A", b"B")
