"""Elastic-resharding gates (ISSUE 18).

Four families:

1.  Differential gate — mid-stream ``reshard`` (a boundary move, then
    2x shard-count scaling 4→6→8) fuzzed across ≥3 seeds × the three
    engine modes (flat / tiered / kernels-interpret), compared against a
    multi-resolver CPU oracle resharded in LOCKSTEP.  Verdicts AND abort
    witnesses must stay bit-identical across every move.

2.  Reshard racing a scripted device fault — the move DEFERS (mirrors
    stay exact, verdicts keep matching an un-resharded oracle), a retry
    completes, and the whole schedule replays byte-identically.

3.  ShardBalancer determinism — same-seed runs dump byte-identical
    decision and move logs, and sustained pressure scales the mesh.

4.  (slow) Hot-key rebalance soak A/B — the balancer restores hot-range
    device goodput to ≥2× the pinned arm's floor while holding the
    commit-p99 SLO, with byte-identical same-seed transition logs.

The oracle's reshard is deliberately INDEPENDENT math from the engine's
chunk handoff: each engine's flat boundary rows are globally
concatenated and re-clipped per new range through the ``keys``/``vers``
flat views, so a handoff bug cannot cancel out of the comparison.
"""

import json

import numpy as np
import pytest

from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.types import CONFLICT, TransactionConflictInfo
from foundationdb_tpu.parallel.sharded_resolver import (
    ShardedJaxConflictSet,
    uniform_int_split_keys,
)

pytestmark = pytest.mark.reshard

N_KEYS = 2000
KEY_BYTES = 8

MODES = [
    ("flat", {}),
    (
        "tiered",
        {
            "FDB_TPU_HISTORY": "tiered",
            "FDB_TPU_EVICT_EVERY": "3",
            "FDB_TPU_DELTA_CAP": "2048",
        },
    ),
    ("kernels", {"FDB_TPU_KERNELS": "interpret"}),
]


def make_key(i: int) -> bytes:
    return int(i).to_bytes(KEY_BYTES, "big")


def random_txn(rng, now, *, max_ranges=3, snap_back=50):
    def rrange():
        a = rng.integers(0, N_KEYS)
        b = a + rng.integers(1, 20)
        return (make_key(a), make_key(b))

    return TransactionConflictInfo(
        read_snapshot=now - int(rng.integers(0, snap_back)),
        read_ranges=[rrange() for _ in range(rng.integers(0, max_ranges + 1))],
        write_ranges=[rrange() for _ in range(rng.integers(0, max_ranges + 1))],
    )


class ReshardingCpuOracle:
    """Multi-resolver CPU oracle (tests/test_sharded_resolver.py) grown
    two ways for ISSUE 18: per-txn abort WITNESSES under the proxy's
    combine rule (min losing read ordinal over conflicting resolvers,
    version = max among that ordinal's holders), and lockstep
    ``reshard`` via global flatten → re-clip of the engines' flat
    boundary rows."""

    def __init__(self, split_keys, oldest_version=0):
        self.split_keys = [bytes(k) for k in split_keys]
        self.engines = [
            CpuConflictSet(oldest_version)
            for _ in range(len(self.split_keys) + 1)
        ]
        self.last_witness: list = []

    @property
    def bounds(self):
        ks = self.split_keys
        return list(zip([b""] + ks, ks + [None]))

    @staticmethod
    def _clip(rng, lo, hi):
        b, e = rng
        cb = max(b, lo)
        ce = e if hi is None else min(e, hi)
        return (cb, ce) if cb < ce else None

    def detect(self, txns, now, new_oldest):
        verdicts, parts = [], []
        for (lo, hi), eng in zip(self.bounds, self.engines):
            local, rmaps = [], []
            for tr in txns:
                rr, rmap = [], []
                for i, r in enumerate(tr.read_ranges):
                    c = self._clip(r, lo, hi)
                    if c is not None:
                        rr.append(c)
                        rmap.append(i)
                wr = [
                    c
                    for r in tr.write_ranges
                    if (c := self._clip(r, lo, hi)) is not None
                ]
                local.append(
                    TransactionConflictInfo(
                        read_snapshot=tr.read_snapshot,
                        read_ranges=rr,
                        write_ranges=wr,
                    )
                )
                rmaps.append(rmap)
            verdicts.append(eng.detect(local, now, new_oldest))
            # Translate clipped-read witness ordinals back to the txn's
            # original read_ranges before combining across resolvers.
            parts.append(
                [
                    None if w is None else (w[0], rmaps[t][w[1]])
                    for t, w in enumerate(eng.last_witness)
                ]
            )
        statuses = [min(v) for v in zip(*verdicts)]
        wit: list = []
        for t, st in enumerate(statuses):
            cands = [p[t] for p in parts if p[t] is not None]
            if st != CONFLICT or not cands:
                wit.append(None)
                continue
            rng = min(c[1] for c in cands)
            wit.append((max(c[0] for c in cands if c[1] == rng), rng))
        self.last_witness = wit
        return statuses

    # -- lockstep reshard: flatten + re-clip (NOT the engine's handoff) --
    def _flat_rows(self):
        from bisect import bisect_left

        rows: list = []
        for (lo, hi), eng in zip(self.bounds, self.engines):
            ks, vs = list(eng.keys), list(eng.vers)
            if lo == b"":
                i0 = 0
            elif len(ks) > 1 and ks[1] == lo:
                i0 = 1  # a real boundary sits exactly at lo
            else:
                # The b"" floor row's value covers [lo, first real key):
                # anchor it at the shard's low bound.
                rows.append((lo, vs[0]))
                i0 = 1
            i1 = len(ks) if hi is None else bisect_left(ks, hi)
            rows.extend(zip(ks[i0:i1], vs[i0:i1]))
        return rows

    def reshard(self, new_split_keys):
        from bisect import bisect_left, bisect_right

        new = [bytes(k) for k in new_split_keys]
        rows = self._flat_rows()
        keys = [r[0] for r in rows]
        oldest = max(e.oldest_version for e in self.engines)
        engines = []
        for lo, hi in zip([b""] + new, new + [None]):
            i0 = bisect_right(keys, lo)
            i1 = len(rows) if hi is None else bisect_left(keys, hi)
            ks = [b""] + [r[0] for r in rows[i0:i1]]
            vs = [rows[i0 - 1][1]] + [r[1] for r in rows[i0:i1]]
            eng = CpuConflictSet(oldest)
            eng.keys = ks
            eng.vers = vs
            engines.append(eng)
        self.split_keys = new
        self.engines = engines


def _mk_sharded(split, max_shards=8):
    import jax

    return ShardedJaxConflictSet(
        split,
        key_words=3,
        h_cap=1 << 12,
        devices=jax.devices(),
        bucket_mins=(64, 128, 128),
        max_shards=max_shards,
    )


# ---------------------------------------------------------------------------
# 1. Differential gate: mid-stream reshard, ≥3 seeds × three engine modes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode,env", MODES, ids=[m[0] for m in MODES])
@pytest.mark.parametrize("seed", [7, 11, 23])
def test_reshard_differential(seed, mode, env, monkeypatch):
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    monkeypatch.setenv("FDB_TPU_WITNESS", "1")
    split = uniform_int_split_keys(4, N_KEYS, KEY_BYTES)
    cs = _mk_sharded(split)
    oracle = ReshardingCpuOracle(split)
    rng = np.random.default_rng(seed)
    now = 100
    # batch index -> new partition (boundary move, then 4→6→8 scaling)
    moved = [make_key(500), make_key(1100), make_key(1500)]
    schedule = {
        3: moved,
        6: uniform_int_split_keys(6, N_KEYS, KEY_BYTES),
        9: uniform_int_split_keys(8, N_KEYS, KEY_BYTES),
    }
    for b in range(12):
        txns = [random_txn(rng, now) for _ in range(int(rng.integers(1, 40)))]
        now += int(rng.integers(1, 30))
        new_oldest = max(0, now - 120)
        got = cs.detect(txns, now, new_oldest)
        want = oracle.detect(txns, now, new_oldest)
        assert got == want, f"{mode} seed {seed} batch {b}: verdicts diverged"
        assert cs.last_witness == oracle.last_witness, (
            f"{mode} seed {seed} batch {b}: witnesses diverged"
        )
        new = schedule.get(b)
        if new is not None:
            entry = cs.reshard(new, reason=f"test_b{b}")
            assert entry["action"] == "live", entry
            oracle.reshard(new)
            assert cs.n_shards == len(new) + 1
    assert cs.n_shards == 8
    assert [e["action"] for e in cs.move_log] == ["live"] * 3


# ---------------------------------------------------------------------------
# 2. Reshard racing a scripted device fault: defer, retry, byte-identical
# ---------------------------------------------------------------------------


def test_reshard_fault_defers_and_replays(monkeypatch):
    from foundationdb_tpu.conflict.device_faults import DeviceFaultInjector

    monkeypatch.setenv("FDB_TPU_WITNESS", "1")
    moved = [make_key(500), make_key(1100), make_key(1500)]

    def run_once():
        split = uniform_int_split_keys(4, N_KEYS, KEY_BYTES)
        cs = _mk_sharded(split)
        inj = DeviceFaultInjector()
        # Shard 1's bounds change under `moved`; its FIRST reshard
        # choke-point check faults (the device dies mid-handoff).
        inj.script("reshard", at=1, shard=1)
        cs.install_fault_injector(inj)
        oracle = ReshardingCpuOracle(split)
        rng = np.random.default_rng(5)
        now = 100
        verdicts = []
        for b in range(8):
            txns = [
                random_txn(rng, now) for _ in range(int(rng.integers(1, 30)))
            ]
            now += int(rng.integers(1, 30))
            new_oldest = max(0, now - 120)
            got = cs.detect(txns, now, new_oldest)
            assert got == oracle.detect(txns, now, new_oldest), f"batch {b}"
            assert cs.last_witness == oracle.last_witness, f"batch {b}"
            verdicts.append(got)
            if b == 3:
                entry = cs.reshard(moved, reason="race")
                # The fault fires BEFORE any mutation: the whole move
                # defers and the oracle is NOT resharded — continued
                # verdict identity proves the mirrors weren't torn.
                assert entry["action"] == "deferred"
                assert entry["fault_shard"] == 1
                assert [bytes(k) for k in cs.split_keys] == [
                    bytes(k) for k in split
                ]
            if b == 5:
                entry = cs.reshard(moved, reason="retry")
                # The scripted fault is consumed; the retry completes —
                # degraded-on-mirror if the deferral opened the breaker.
                assert entry["action"] in ("live", "degraded_on_mirror")
                oracle.reshard(moved)
        assert int(cs.metrics.counter("reshard_deferred").value) == 1
        return json.dumps(
            {
                "move_log": cs.move_log,
                "injected": inj.injected,
                "verdicts": verdicts,
            },
            sort_keys=True,
            default=str,
        )

    assert run_once() == run_once(), "fault-race schedule not replayable"


# ---------------------------------------------------------------------------
# 3. ShardBalancer: same-seed byte-identical logs; pressure scales the mesh
# ---------------------------------------------------------------------------


def test_balancer_deterministic_and_scales():
    import random

    from foundationdb_tpu.server.resolver_balancer import ShardBalancer

    def run(seed):
        split = uniform_int_split_keys(2, N_KEYS, KEY_BYTES)
        cs = _mk_sharded(split)
        bal = ShardBalancer(
            cs,
            ratio=1.5,
            hysteresis=2,
            cooldown=2,
            min_boundaries=16,
            scale_up_pressure=0.8,
        )
        rng = random.Random(seed)
        now = 100
        for b in range(30):
            txns = []
            for _ in range(24):
                # Zipf-ish skew: most writes land in the first 10% of keys
                lo = rng.randrange(0, 200 if rng.random() < 0.8 else N_KEYS)
                w = [(make_key(lo), make_key(lo + rng.randrange(1, 8)))]
                txns.append(
                    TransactionConflictInfo(
                        read_snapshot=max(0, now - 5),
                        read_ranges=list(w),
                        write_ranges=list(w),
                    )
                )
            now += 1
            cs.detect(txns, now, max(0, now - 50))
            bal.evaluate(pressure=0.9 if 10 <= b < 20 else 0.2)
        return bal, cs

    b1, cs1 = run(7)
    b2, cs2 = run(7)
    assert b1.decisions_json() == b2.decisions_json()
    assert json.dumps(cs1.move_log, sort_keys=True) == json.dumps(
        cs2.move_log, sort_keys=True
    )
    actions = [d["action"] for d in b1.decisions]
    assert "scale" in actions, actions  # sustained pressure doubled the mesh
    assert cs1.n_shards > 2
    assert "cooldown" in actions  # the per-move cooldown gate engaged


# ---------------------------------------------------------------------------
# 4. Hot-key rebalance soak A/B (slow): goodput recovery + SLO + identity
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.soak
def test_hot_key_rebalance_ab():
    from foundationdb_tpu.workloads.soak import (
        hot_key_rebalance_config,
        run_hot_key_rebalance_ab,
        run_soak,
        transition_logs_json,
    )

    ab = run_hot_key_rebalance_ab(minutes=0.35, peak_tps=60.0, seed=3)
    assert ab["recovery_ratio"] >= 2.0, ab
    assert ab["slo_ok"], ab
    assert ab["balancer_moves"] >= 1, ab
    # Same-seed byte identity of the balanced arm's transition logs
    # (balancer decisions + move log + breaker/fault timelines).
    r1 = run_soak(hot_key_rebalance_config(minutes=0.35, peak_tps=60.0, seed=3))
    r2 = run_soak(hot_key_rebalance_config(minutes=0.35, peak_tps=60.0, seed=3))
    assert transition_logs_json(r1) == transition_logs_json(r2)
    sect = r1["resharding"]
    assert sect["balancer"]["moves"] >= 1
    assert any(v["reshards"] >= 1 for v in sect["resolvers"].values())
