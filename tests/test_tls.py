"""Mutual TLS on the real transport (ref: FDBLibTLS + TLSConnection).

Certs are minted at test time with the openssl CLI: one CA signs the
server and client certs; an IMPOSTOR CA signs a cert that must be
rejected (the verify-peers model: trust is the CA chain, not hostnames).
"""

import signal
import subprocess

import pytest

from conftest import spawn_real_node


def _sh(*args):
    subprocess.run(args, check=True, capture_output=True)


def make_ca(dirpath, name):
    ca_key = f"{dirpath}/{name}.key"
    ca_crt = f"{dirpath}/{name}.crt"
    _sh("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", ca_key, "-out", ca_crt, "-days", "1",
        "-subj", f"/CN={name}")
    return ca_key, ca_crt


def make_cert(dirpath, name, ca_key, ca_crt):
    key = f"{dirpath}/{name}.key"
    csr = f"{dirpath}/{name}.csr"
    crt = f"{dirpath}/{name}.crt"
    _sh("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", key, "-out", csr, "-subj", f"/CN={name}")
    _sh("openssl", "x509", "-req", "-in", csr, "-CA", ca_crt,
        "-CAkey", ca_key, "-CAcreateserial", "-out", crt, "-days", "1")
    return key, crt


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tls"))
    ca_key, ca_crt = make_ca(d, "cluster-ca")
    s_key, s_crt = make_cert(d, "server", ca_key, ca_crt)
    c_key, c_crt = make_cert(d, "client", ca_key, ca_crt)
    bad_ca_key, bad_ca_crt = make_ca(d, "impostor-ca")
    i_key, i_crt = make_cert(d, "intruder", bad_ca_key, bad_ca_crt)
    return {
        "ca": ca_crt,
        "server": (s_crt, s_key),
        "client": (c_crt, c_key),
        "bad_ca": bad_ca_crt,
        "intruder": (i_crt, i_key),
    }


def test_tls_cluster_roundtrip(certs):
    """Server and client with CA-chained certs: transactions flow over the
    encrypted channel end to end."""
    s_crt, s_key = certs["server"]
    c_crt, c_key = certs["client"]
    server = spawn_real_node(*[
        "server", "--tls-cert", s_crt, "--tls-key", s_key,
        "--tls-ca", certs["ca"],
    ])
    try:
        ready = server.stdout.readline().strip()
        assert ready.startswith("READY "), ready
        addr = ready.split()[1]
        cl = spawn_real_node(*[
            "client", addr, "--id", "t", "--ops", "8", "--check-count", "8",
            "--tls-cert", c_crt, "--tls-key", c_key, "--tls-ca", certs["ca"],
        ])
        out, _ = cl.communicate(timeout=90)
        assert cl.returncode == 0, out
        assert "DONE 8" in out
    finally:
        server.send_signal(signal.SIGTERM)
        try:
            server.wait(timeout=5)
        except subprocess.TimeoutExpired:
            server.kill()


def test_tls_rejects_untrusted_peer(certs):
    """A client whose cert chains to a DIFFERENT CA is rejected at the
    handshake; it makes no progress against the cluster."""
    s_crt, s_key = certs["server"]
    i_crt, i_key = certs["intruder"]
    server = spawn_real_node(*[
        "server", "--tls-cert", s_crt, "--tls-key", s_key,
        "--tls-ca", certs["ca"],
    ])
    try:
        ready = server.stdout.readline().strip()
        assert ready.startswith("READY "), ready
        addr = ready.split()[1]
        intruder = spawn_real_node(*[
            "client", addr, "--id", "x", "--ops", "1",
            "--tls-cert", i_crt, "--tls-key", i_key,
            # The intruder even TRUSTS the real CA; its own identity is
            # what fails verification server-side.
            "--tls-ca", certs["ca"],
        ])
        try:
            out, _ = intruder.communicate(timeout=15)
            # If it exited, it must NOT have completed its op.
            assert "DONE" not in out, out
        except subprocess.TimeoutExpired:
            intruder.kill()  # wedged at the rejected handshake: also a pass
        # The cluster still serves trusted clients afterwards.
        c_crt, c_key = certs["client"]
        good = spawn_real_node(*[
            "client", addr, "--id", "g", "--ops", "2",
            "--tls-cert", c_crt, "--tls-key", c_key,
            "--tls-ca", certs["ca"],
        ])
        out2, _ = good.communicate(timeout=90)
        assert good.returncode == 0, out2
    finally:
        server.send_signal(signal.SIGTERM)
        try:
            server.wait(timeout=5)
        except subprocess.TimeoutExpired:
            server.kill()
