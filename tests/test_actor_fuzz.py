"""ActorFuzz: generated random actor/future-combinator graphs.

Ref: flow/ActorFuzz.actor.cpp (generated actor programs stress the actor
compiler's state machines) — here the generator builds random trees of
the flow primitives (delay, spawn, all_of, first_of, promises, errors,
cancellation) and checks the runtime invariants the combinators promise:
completion, same-seed determinism, error propagation, and that
cancellation mid-graph never wedges the loop or leaks ready callbacks.
"""

import pytest

from foundationdb_tpu.flow import EventLoop, set_event_loop
from foundationdb_tpu.flow.error import ActorCancelled, FdbError
from foundationdb_tpu.flow.eventloop import all_of, first_of
from foundationdb_tpu.flow.future import Promise


@pytest.fixture(autouse=True)
def _clean():
    yield
    set_event_loop(None)


def build_actor(loop, rng, depth, trace, label="r"):
    """A random actor coroutine; records (label, event) pairs in trace."""

    async def leaf_delay():
        await loop.delay(rng.random01() * 0.01)
        trace.append((label, "delay"))
        return 1

    async def leaf_value():
        trace.append((label, "value"))
        return 2

    async def leaf_error():
        await loop.delay(rng.random01() * 0.005)
        trace.append((label, "raise"))
        raise FdbError("operation_failed")

    async def leaf_promise():
        p = Promise()

        def fire():
            if not p.is_set():
                p.send(3)

        loop._schedule(7000, fire, at=loop.now() + rng.random01() * 0.01)
        v = await p.future
        trace.append((label, "promise"))
        return v

    if depth <= 0:
        r = rng.random01()
        if r < 0.4:
            return leaf_delay()
        if r < 0.7:
            return leaf_value()
        if r < 0.85:
            return leaf_promise()
        return leaf_error()

    r = rng.random01()
    n = int(rng.random_int(2, 4))

    # Children are built LAZILY, inside the combinator bodies: an eagerly
    # built tree drops pre-built grandchild coroutines when a subtree task
    # is cancelled before it starts (the "coroutine was never awaited"
    # class whose blanket pytest ignore was removed; see pytest.ini).  Built-
    # immediately-spawned coroutines are always owned by a Task, which
    # closes them if never driven.
    def build_child(i):
        return build_actor(loop, rng, depth - 1, trace, f"{label}.{i}")

    if r < 0.35:

        async def combin_all():
            try:
                vals = await all_of(
                    [loop.spawn(build_child(i), f"{label}.{i}") for i in range(n)]
                )
                trace.append((label, f"all{len(vals)}"))
                return sum(v or 0 for v in vals)
            except ActorCancelled:
                raise  # cancellation must PROPAGATE, never be swallowed
            except FdbError:
                trace.append((label, "all_err"))
                return -1

        return combin_all()
    if r < 0.65:

        async def combin_first():
            tasks = [
                loop.spawn(build_child(i), f"{label}.{i}") for i in range(n)
            ]
            try:
                idx, val = await first_of(*tasks)
                trace.append((label, f"first{idx}"))
            except ActorCancelled:
                for t in tasks:
                    if not t.is_ready():
                        t.cancel()
                raise  # cancellation must PROPAGATE, never be swallowed
            except FdbError:
                trace.append((label, "first_err"))
                idx, val = -1, -1
            # The losers must still be drainable (no wedge): cancel them.
            for t in tasks:
                if not t.is_ready():
                    t.cancel()
            return val

        return combin_first()

    async def combin_seq():
        total = 0
        # Built one at a time, just before its spawn: children after a
        # mid-sequence cancellation are simply never constructed.
        for i in range(n):
            try:
                total += (await loop.spawn(build_child(i), f"{label}.{i}")) or 0
            except ActorCancelled:
                raise  # cancellation must PROPAGATE, never be swallowed
            except FdbError:
                trace.append((label, f"seq_err{i}"))
        trace.append((label, "seq"))
        return total

    return combin_seq()


def run_graph(seed, depth=3, cancel_after=None):
    loop = EventLoop(seed=seed)
    set_event_loop(loop)
    trace = []
    root = loop.spawn(build_actor(loop, loop.rng, depth, trace), "root")
    if cancel_after is not None:
        loop._schedule(7000, root.cancel, at=cancel_after)
    # Drain the loop completely (root may be cancelled; losers cancelled).
    while loop.run_one():
        if len(trace) > 100000:
            raise AssertionError("runaway actor graph")
    result = (
        "cancelled"
        if root.is_error() and isinstance(root.error(), ActorCancelled)
        else ("error" if root.is_error() else root.get())
    )
    # Loop fully drained: no parked ready-but-unrun events.
    assert not loop._heap or all(c[3][0] is None for c in loop._heap)
    set_event_loop(None)
    return result, tuple(trace), loop.tasks_run


@pytest.mark.parametrize("seed", range(40))
def test_fuzzed_graphs_complete_and_replay_identically(seed):
    r1 = run_graph(seed)
    r2 = run_graph(seed)
    assert r1 == r2, f"seed {seed} diverged across replays"


@pytest.mark.parametrize("seed", range(40, 70))
def test_fuzzed_graphs_survive_random_cancellation(seed):
    """Cancel the root mid-flight at a random virtual time: the loop must
    drain (no wedge, no runaway), and a replay with the same seed and the
    same cancel point is identical."""
    loop_probe = EventLoop(seed=seed)
    cancel_at = loop_probe.rng.random01() * 0.01
    r1 = run_graph(seed, cancel_after=cancel_at)
    r2 = run_graph(seed, cancel_after=cancel_at)
    assert r1 == r2
