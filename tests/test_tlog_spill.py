"""TLog spill: bounded memory under consumer backlog.

Ref: TLogServer.actor.cpp:539 updatePersistentData — old unpopped tag data
moves from the in-memory window (and the DiskQueue) into a per-tag durable
btree; a lagging consumer bounds the log's RAM, not its correctness.
"""

import pickle

import pytest

from foundationdb_tpu.client.types import Mutation, MutationType
from foundationdb_tpu.fileio import SimFileSystem
from foundationdb_tpu.flow import EventLoop, set_event_loop
from foundationdb_tpu.rpc import SimNetwork
from foundationdb_tpu.server.interfaces import (
    TLogCommitRequest,
    TLogPeekRequest,
    TLogPopRequest,
)
from foundationdb_tpu.server.tlog import TLog


def make_env(seed):
    loop = EventLoop(seed=seed)
    set_event_loop(loop)
    net = SimNetwork(loop)
    fs = SimFileSystem(net)
    return loop, net, fs


def _mut(i):
    return Mutation(MutationType.SET_VALUE, b"k%06d" % i, b"v" * 100)


async def _push(log_iface, proc, version, prev, tagged):
    return await log_iface.commit.get_reply(
        proc,
        TLogCommitRequest(
            version=version, prev_version=prev, tagged=tagged, epoch=0
        ),
    )


@pytest.mark.parametrize("seed", [1, 2])
def test_spill_bounds_memory_and_serves_backlog(seed):
    """300 commits against a 20KB spill threshold with a consumer that
    never pops: memory stays bounded near the threshold while EVERY version
    remains peekable (old ones from the spill store), in order, intact."""
    loop, net, fs = make_env(seed)
    proc = net.process("tlog")
    client = net.process("client")
    state = {}

    async def run():
        log = await TLog.fresh(proc, fs, "t.dq")
        log.spill_threshold_bytes = 20_000
        log.spill_keep_versions = 8
        iface = log.interface()
        n = 300
        for v in range(1, n + 1):
            tagged = {"ss0": [(0, _mut(v))]}
            await _push(iface, client, v, v - 1, tagged)
        # Let the spill task drain.
        for _ in range(200):
            if log._mem_bytes <= log.spill_threshold_bytes and not log._spilling:
                break
            await loop.delay(0.01)
        state["mem_bytes"] = log._mem_bytes
        state["mem_versions"] = len(log.versions)
        state["spilled_through"] = log.spilled_through
        assert log.spilled_through > 0, "spill never engaged"
        assert log._mem_bytes <= log.spill_threshold_bytes, (
            f"memory unbounded: {log._mem_bytes}"
        )
        assert len(log.versions) < n // 2

        # The lagging consumer now reads EVERYTHING from version 0.
        got = []
        begin = 0
        while True:
            rep = await iface.peek.get_reply(
                client,
                TLogPeekRequest(begin_version=begin, tags=["ss0"]),
            )
            for v, muts in rep.entries:
                got.append((v, muts))
            if rep.end_version <= begin and not rep.entries:
                break
            begin = max(rep.end_version, begin)
            if begin >= n and not rep.has_more:
                break
        assert [v for v, _m in got] == list(range(1, n + 1))
        assert all(
            m[0].param1 == b"k%06d" % v for v, m in got
        ), "spilled mutation payloads corrupted"
        state["ok"] = True

    loop.run_until(proc.spawn(run()), timeout_vt=5000.0)
    assert state.get("ok")
    set_event_loop(None)


def test_spill_survives_crash_recovery():
    """Spill, then SIGKILL the machine: recovery must serve the full
    history — spilled prefix from the btree, suffix from the queue."""
    loop, net, fs = make_env(11)
    proc = net.process("tlog")
    client = net.process("client")
    state = {}

    async def writer():
        log = await TLog.fresh(proc, fs, "t.dq")
        log.spill_threshold_bytes = 10_000
        log.spill_keep_versions = 4
        iface = log.interface()
        for v in range(1, 121):
            await _push(iface, client, v, v - 1, {"ss0": [(0, _mut(v))]})
        for _ in range(200):
            if not log._spilling and log._mem_bytes <= log.spill_threshold_bytes:
                break
            await loop.delay(0.01)
        assert log.spilled_through > 0
        state["spilled_through"] = log.spilled_through

    loop.run_until(proc.spawn(writer()), timeout_vt=5000.0)
    proc.kill()
    fs.crash_machine("tlog")
    proc.reboot()

    async def recover():
        log = await TLog.recover(proc, fs, "t.dq")
        assert log.spilled_through == state["spilled_through"]
        assert log.durable.get() == 120
        iface = log.interface()
        got = []
        begin = 0
        while begin < 120:
            rep = await iface.peek.get_reply(
                client, TLogPeekRequest(begin_version=begin, tags=["ss0"])
            )
            got.extend(v for v, _m in rep.entries)
            begin = max(rep.end_version, begin + (0 if rep.entries else 1))
        assert got == list(range(1, 121))
        state["ok"] = True

    loop.run_until(proc.spawn(recover()), timeout_vt=5000.0)
    assert state.get("ok")
    set_event_loop(None)


def test_pop_clears_spilled_data():
    """Consumer pops release spilled ranges: after popping everything, the
    spill store's tag range is empty (storage reclaimed, ref tLogPop)."""
    loop, net, fs = make_env(21)
    proc = net.process("tlog")
    client = net.process("client")
    state = {}

    async def run():
        log = await TLog.fresh(proc, fs, "t.dq")
        log.spill_threshold_bytes = 10_000
        log.spill_keep_versions = 4
        iface = log.interface()
        for v in range(1, 101):
            await _push(iface, client, v, v - 1, {"ss0": [(0, _mut(v))]})
        for _ in range(200):
            if not log._spilling:
                break
            await loop.delay(0.01)
        assert log.spilled_through > 0
        await iface.pop.get_reply(
            client, TLogPopRequest(version=100, tag="ss0")
        )
        for _ in range(100):
            await loop.delay(0.01)
        left = log.spill_store.read_range(b"t/", b"t0", limit=10)
        assert left == [], f"spilled rows survived the pop: {left[:3]}"
        state["ok"] = True

    loop.run_until(proc.spawn(run()), timeout_vt=5000.0)
    assert state.get("ok")
    set_event_loop(None)


def test_truncate_above_purges_spill():
    """Epoch-end truncation must purge spilled versions above the cut —
    otherwise _peek_spilled resurrects rolled-back mutations into the new
    generation (regression test for exactly that bug)."""
    loop, net, fs = make_env(31)
    proc = net.process("tlog")
    client = net.process("client")
    state = {}

    async def run():
        log = await TLog.fresh(proc, fs, "t.dq")
        log.spill_threshold_bytes = 10_000
        log.spill_keep_versions = 4
        iface = log.interface()
        for v in range(1, 101):
            await _push(iface, client, v, v - 1, {"ss0": [(0, _mut(v))]})
        for _ in range(200):
            if not log._spilling:
                break
            await loop.delay(0.01)
        assert log.spilled_through > 60, log.spilled_through
        cut = 60
        await log.truncate_above(cut)
        assert log.spilled_through == cut
        # Nothing above the cut may surface from any peek path.
        got = []
        begin = 0
        while begin < cut:
            rep = await iface.peek.get_reply(
                client, TLogPeekRequest(begin_version=begin, tags=["ss0"])
            )
            got.extend(v for v, _m in rep.entries)
            begin = max(rep.end_version, begin + (0 if rep.entries else 1))
        assert got == list(range(1, cut + 1))
        rows = log.spill_store.read_range(b"t/", b"t0")
        assert all(int.from_bytes(k[-8:], "big") <= cut for k, _ in rows)
        state["ok"] = True

    loop.run_until(proc.spawn(run()), timeout_vt=5000.0)
    assert state.get("ok")
    set_event_loop(None)


def test_unregistered_tag_spill_gc_survives_restart():
    """A dead consumer's tag keeps receiving commits until DD heals
    keyServers; its unregistration must keep spill GC collecting those
    rows ACROSS a tlog restart.  The __pop__ unregister queue record is
    trimmed once the floor passes it, so durability rides a spill-store
    marker — forgetting it would silently regrow the spill forever."""
    from foundationdb_tpu.flow import testprobe

    probe_before = testprobe.hit_sites.get("dead_tag_spill_gc", 0)
    loop, net, fs = make_env(31)
    proc = net.process("tlog")
    client = net.process("client")
    state = {}

    async def phase1():
        log = await TLog.fresh(proc, fs, "t.dq")
        log.spill_threshold_bytes = 10_000
        log.spill_keep_versions = 4
        iface = log.interface()
        # Register the live consumer, then declare dead1 dead.
        await iface.pop.get_reply(
            client, TLogPopRequest(version=0, tag="ss0")
        )
        await iface.pop.get_reply(
            client, TLogPopRequest(tag="dead1", unregister=True)
        )
        # Both tags keep receiving rows (DD has not healed keyServers
        # yet); enough volume to spill (and commit the marker).
        for v in range(1, 101):
            await _push(
                iface, client, v, v - 1,
                {"ss0": [(0, _mut(v))], "dead1": [(1, _mut(v))]},
            )
        for _ in range(200):
            if not log._spilling:
                break
            await loop.delay(0.01)
        assert log.spilled_through > 0
        assert "dead1" in log._dead_tags

    loop.run_until(proc.spawn(phase1()), timeout_vt=5000.0)
    proc.kill()
    fs.crash_machine("tlog")
    proc.reboot()

    async def phase2():
        log = await TLog.recover(proc, fs, "t.dq")
        assert "dead1" in log._dead_tags, "dead tag forgotten on restart"
        iface = log.interface()
        prev = log.durable.get()
        for v in range(prev + 1, prev + 81):
            await _push(
                iface, client, v, v - 1,
                {"ss0": [(0, _mut(v))], "dead1": [(1, _mut(v))]},
            )
        for _ in range(200):
            if not log._spilling:
                break
            await loop.delay(0.01)
        # The live consumer advances; GC must release dead1's rows below
        # the floor even though nobody ever pops dead1.
        floor = prev + 80
        await iface.pop.get_reply(
            client, TLogPopRequest(version=floor, tag="ss0")
        )
        for _ in range(100):
            await loop.delay(0.01)
        left = log.spill_store.read_range(
            b"t/dead1/", b"t/dead10", limit=10
        )
        assert left == [], (
            f"dead tag's spilled rows survived GC: {left[:3]}"
        )
        assert (
            testprobe.hit_sites.get("dead_tag_spill_gc", 0) > probe_before
        )
        state["ok"] = True

    loop.run_until(proc.spawn(phase2()), timeout_vt=5000.0)
    assert state.get("ok")
    set_event_loop(None)
