"""Restarting test family: whole-cluster power loss mid-workload.

Ref: the tests/restarting specs (CycleTestRestart-1.txt pattern): run a
workload, SIGKILL every process in the simulation, restart each from its
disk files, RESUME the workload against the recovered cluster, and check
invariants — including one restart landing mid-shard-move (the MoveKeys
restart protocol must re-drive the fetch).
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server.dynamic_cluster import DynamicCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


async def _cycle_round(db, cluster, nodes, ops, prefix=b"cycle/"):
    """One batch of cycle rotations (the CycleWorkload op, inlined so the
    same ring can be resumed across restarts)."""
    rng = cluster.loop.rng
    for _ in range(ops):

        async def op(tr):
            a = int(rng.random_int(0, nodes))
            ka = prefix + b"%04d" % a
            b = int((await tr.get(ka)).decode())
            kb = prefix + b"%04d" % b
            c2 = int((await tr.get(kb)).decode())
            kc = prefix + b"%04d" % c2
            d = int((await tr.get(kc)).decode())
            tr.set(ka, b"%04d" % c2)
            tr.set(kc, b"%04d" % b)
            tr.set(kb, b"%04d" % d)

        await db.run(op)


async def _check_ring(db, nodes, prefix=b"cycle/"):
    out = {}

    async def read(tr):
        out["ring"] = await tr.get_range(prefix, prefix + b"\xff")

    await db.run(read)
    ring = {k: int(v.decode()) for k, v in out["ring"]}
    assert len(ring) == nodes, f"ring lost nodes: {sorted(ring)}"
    seen, cur = set(), 0
    for _ in range(nodes):
        assert cur not in seen, "ring split into multiple cycles"
        seen.add(cur)
        cur = ring[prefix + b"%04d" % cur]
    assert cur == 0 and len(seen) == nodes


@pytest.mark.parametrize("seed", [9301, 9302, 9303])
def test_cycle_restart(seed):
    """CycleTestRestart: load -> power loss -> restart from disk -> resume
    -> ring invariant (per seed; generation strictly increases)."""
    nodes = 6
    c = DynamicCluster(seed=seed, n_workers=5)
    db = c.database()

    async def init(tr):
        for i in range(nodes):
            tr.set(b"cycle/%04d" % i, b"%04d" % ((i + 1) % nodes))

    c.run_all([(db, db.run(init))], timeout_vt=600.0)
    c.run_all([(db, _cycle_round(db, c, nodes, 8))], timeout_vt=2000.0)
    gen_before = c.acting_controller().generation

    c.crash_and_recover()

    # Resume the SAME workload against the recovered cluster.
    c.run_all([(db, _cycle_round(db, c, nodes, 8))], timeout_vt=2000.0)
    c.run_all([(db, _check_ring(db, nodes))], timeout_vt=600.0)
    assert c.acting_controller().generation > gen_before


def test_restart_mid_shard_move():
    """Power loss while a shard move is fetching: the in-flight
    AddingShard is not durable, DD restarts the move from the keyServers
    record, and the move settles with the data intact (ref: MoveKeys
    restart via the 'missing' shard state, MoveKeys.actor.cpp)."""
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.server.cluster import SimCluster as SC

    c = SC(seed=9310, n_storages=2, durable=False)
    db = c.database()

    async def fill(tr):
        for i in range(40):
            tr.set(b"mv%04d" % i, b"val%04d" % i)

    c.run_until(db.process.spawn(db.run(fill), "fill"), timeout_vt=600.0)
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"mv0020")
        await dd.split(b"\xff")

    c.run_until(db.process.spawn(place(), "place"), timeout_vt=600.0)

    # Start the move but DO NOT drive it to completion: write the
    # startMove record only, then kill the destination storage process
    # mid-fetch (its AddingShard state is RAM-only).
    from foundationdb_tpu.server import system_keys as sk

    async def start_move(tr):
        tr.options["access_system_keys"] = True
        b, e = b"mv0020", b"\xff"
        tr.set(sk.key_servers_key(b), sk.encode_key_servers(["ss0"], ["ss1"], e))

    c.run_until(db.process.spawn(db.run(start_move), "sm"), timeout_vt=600.0)

    async def brief():
        await c.loop.delay(0.02)  # let the fetch begin

    c.run_until(db.process.spawn(brief(), "b"), timeout_vt=600.0)
    dst_proc = c.storages[1].process
    dst_proc.kill()
    dst_proc.reboot()
    # Restart the destination storage role (non-durable sim: fresh object,
    # same id; a durable deployment would StorageServer.recover).
    from foundationdb_tpu.server.storage import StorageServer

    # A fresh joiner starts at the log's CURRENT durable version (its data
    # comes from the source storage via fetch, not from log history; old
    # history below the pop floors is gone by design).
    new_dst = StorageServer(
        dst_proc,
        [t.interface() for t in c.tlogs],
        storage_id="ss1",
        owned_all=False,
        epoch_begin_version=c.tlogs[0].durable.get(),
    )
    c.storages[1] = new_dst
    dd.storages["ss1"] = new_dst.interface()

    # DD drives the move to done: it must observe "missing" on the fresh
    # destination and restart the fetch.
    async def finish():
        await dd.move(b"mv0020", ["ss1"])

    c.run_until(db.process.spawn(finish(), "fin"), timeout_vt=2000.0)

    out = {}

    async def check(tr):
        out["rows"] = await tr.get_range(b"mv0020", b"mv\xff")

    c.run_until(db.process.spawn(db.run(check), "chk"), timeout_vt=600.0)
    assert len(out["rows"]) == 20
    assert out["rows"][0] == (b"mv0020", b"val0020")


def test_restart_after_fetch_ready_before_fold_loses_nothing():
    """Crash the DESTINATION right after its fetch reached READY + settled
    but BEFORE the fetched snapshot folded through the version window.
    The fetch WRITE-THROUGH (rows into the durable base engine, fsynced
    with the READY claim in one commit) must let the recovered
    destination serve the shard: the settle durably DROPS the source's
    copy, so without the write-through the data would exist nowhere —
    silent loss (round-5 review finding).  Exercised at the component
    level: two durable storages, a manual keyServers move, a destination
    machine crash, StorageServer.recover."""
    from foundationdb_tpu.fileio import SimFileSystem
    from foundationdb_tpu.flow.eventloop import EventLoop
    from foundationdb_tpu.flow import set_event_loop as sel
    from foundationdb_tpu.rpc.network import SimNetwork
    from foundationdb_tpu.server.interfaces import (
        GetKeyValuesRequest,
        GetShardStateRequest,
    )
    from foundationdb_tpu.server.storage import StorageServer
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.server import system_keys as sk

    c = SimCluster(seed=9320, durable=True)  # single durable storage src
    db = c.database()

    async def fill(tr):
        for i in range(25):
            tr.set(b"wt%03d" % i, b"d%d" % i)

    c.run_until(db.process.spawn(db.run(fill), "fill"), timeout_vt=600.0)

    # A SECOND durable storage on its own machine joins as the move dest.
    proc2 = c.net.process("storage2", machine_id="m_storage2")
    dst_holder = {}

    async def boot_dst():
        dst_holder["ss"] = await StorageServer.recover(
            proc2,
            [t.interface() for t in c.tlogs],
            c.fs,
            "storage2.dq",
            storage_id="ss2",
            owned_all=False,
        )

    c.run_until(proc2.spawn(boot_dst(), "boot2"), timeout_vt=600.0)
    dst = dst_holder["ss"]

    # Manual MoveKeys: serverList entries + startMove + settle.
    src_id = c.storage.storage_id

    async def start_move(tr):
        tr.options["access_system_keys"] = True
        tr.set(sk.server_list_key(src_id),
               sk.encode_server_entry(c.storage.interface()))
        tr.set(sk.server_list_key("ss2"),
               sk.encode_server_entry(dst.interface()))
        tr.set(sk.key_servers_key(b"wt"),
               sk.encode_key_servers([src_id], ["ss2"], b"wu"))

    c.run_until(db.process.spawn(db.run(start_move), "sm"), timeout_vt=600.0)

    async def wait_fetched():
        for _ in range(400):
            state = await dst.interface().get_shard_state.get_reply(
                db.process, GetShardStateRequest(begin=b"wt", end=b"wu")
            )
            if state == "fetched":
                return True
            await c.loop.delay(0.05)
        return False

    assert c.run_until(db.process.spawn(wait_fetched(), "wf"),
                       timeout_vt=2000.0)

    async def settle(tr):
        tr.options["access_system_keys"] = True
        tr.set(sk.key_servers_key(b"wt"),
               sk.encode_key_servers(["ss2"], [], b"wu"))

    c.run_until(db.process.spawn(db.run(settle), "st"), timeout_vt=600.0)

    async def wait_flipped():
        for _ in range(400):
            state = await dst.interface().get_shard_state.get_reply(
                db.process, GetShardStateRequest(begin=b"wt", end=b"wu")
            )
            if state == "readable":
                return True
            await c.loop.delay(0.05)
        return False

    assert c.run_until(db.process.spawn(wait_flipped(), "wfl"),
                       timeout_vt=2000.0)

    # CRASH the destination machine NOW — far below the 5M-version fold
    # window, so only the write-through can have made the rows durable.
    proc2.kill()
    c.fs.crash_machine("m_storage2")
    proc2.reboot()

    async def recover_and_read():
        ss2 = await StorageServer.recover(
            proc2,
            [t.interface() for t in c.tlogs],
            c.fs,
            "storage2.dq",
            storage_id="ss2",
            owned_all=False,
        )
        # The recovered destination must CLAIM the shard (READY from the
        # fetch-time durable meta; the settle record replays from the log
        # tail and flips it readable as the update loop catches up).
        state = None
        for _ in range(200):
            state = await ss2.interface().get_shard_state.get_reply(
                db.process, GetShardStateRequest(begin=b"wt", end=b"wu")
            )
            if state == "readable":
                break
            assert state in ("fetched", "adding", "readable"), state
            await c.loop.delay(0.05)
        assert state == "readable", state
        # ...and serve every fetched row at a fresh version.
        for _ in range(200):
            v = ss2.version.get()
            try:
                rep = await ss2.interface().get_key_values.get_reply(
                    db.process,
                    GetKeyValuesRequest(begin=b"wt", end=b"wu", version=v),
                )
                if len(rep.data) == 25:
                    return rep.data
            except Exception:
                pass
            await c.loop.delay(0.05)
        return None

    rows = c.run_until(db.process.spawn(recover_and_read(), "rr"),
                       timeout_vt=5000.0)
    assert rows is not None and rows[7] == (b"wt007", b"d7")
