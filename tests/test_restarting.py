"""Restarting test family: whole-cluster power loss mid-workload.

Ref: the tests/restarting specs (CycleTestRestart-1.txt pattern): run a
workload, SIGKILL every process in the simulation, restart each from its
disk files, RESUME the workload against the recovered cluster, and check
invariants — including one restart landing mid-shard-move (the MoveKeys
restart protocol must re-drive the fetch).
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server.dynamic_cluster import DynamicCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


async def _cycle_round(db, cluster, nodes, ops, prefix=b"cycle/"):
    """One batch of cycle rotations (the CycleWorkload op, inlined so the
    same ring can be resumed across restarts)."""
    rng = cluster.loop.rng
    for _ in range(ops):

        async def op(tr):
            a = int(rng.random_int(0, nodes))
            ka = prefix + b"%04d" % a
            b = int((await tr.get(ka)).decode())
            kb = prefix + b"%04d" % b
            c2 = int((await tr.get(kb)).decode())
            kc = prefix + b"%04d" % c2
            d = int((await tr.get(kc)).decode())
            tr.set(ka, b"%04d" % c2)
            tr.set(kc, b"%04d" % b)
            tr.set(kb, b"%04d" % d)

        await db.run(op)


async def _check_ring(db, nodes, prefix=b"cycle/"):
    out = {}

    async def read(tr):
        out["ring"] = await tr.get_range(prefix, prefix + b"\xff")

    await db.run(read)
    ring = {k: int(v.decode()) for k, v in out["ring"]}
    assert len(ring) == nodes, f"ring lost nodes: {sorted(ring)}"
    seen, cur = set(), 0
    for _ in range(nodes):
        assert cur not in seen, "ring split into multiple cycles"
        seen.add(cur)
        cur = ring[prefix + b"%04d" % cur]
    assert cur == 0 and len(seen) == nodes


@pytest.mark.parametrize("seed", [9301, 9302, 9303])
def test_cycle_restart(seed):
    """CycleTestRestart: load -> power loss -> restart from disk -> resume
    -> ring invariant (per seed; generation strictly increases)."""
    nodes = 6
    c = DynamicCluster(seed=seed, n_workers=5)
    db = c.database()

    async def init(tr):
        for i in range(nodes):
            tr.set(b"cycle/%04d" % i, b"%04d" % ((i + 1) % nodes))

    c.run_all([(db, db.run(init))], timeout_vt=600.0)
    c.run_all([(db, _cycle_round(db, c, nodes, 8))], timeout_vt=2000.0)
    gen_before = c.acting_controller().generation

    c.crash_and_recover()

    # Resume the SAME workload against the recovered cluster.
    c.run_all([(db, _cycle_round(db, c, nodes, 8))], timeout_vt=2000.0)
    c.run_all([(db, _check_ring(db, nodes))], timeout_vt=600.0)
    assert c.acting_controller().generation > gen_before


def test_restart_mid_shard_move():
    """Power loss while a shard move is fetching: the in-flight
    AddingShard is not durable, DD restarts the move from the keyServers
    record, and the move settles with the data intact (ref: MoveKeys
    restart via the 'missing' shard state, MoveKeys.actor.cpp)."""
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.server.cluster import SimCluster as SC

    c = SC(seed=9310, n_storages=2, durable=False)
    db = c.database()

    async def fill(tr):
        for i in range(40):
            tr.set(b"mv%04d" % i, b"val%04d" % i)

    c.run_until(db.process.spawn(db.run(fill), "fill"), timeout_vt=600.0)
    dd = c.data_distributor()

    async def place():
        await dd.register_storages(dd.storages)
        await dd.seed(["ss0"])
        await dd.split(b"mv0020")
        await dd.split(b"\xff")

    c.run_until(db.process.spawn(place(), "place"), timeout_vt=600.0)

    # Start the move but DO NOT drive it to completion: write the
    # startMove record only, then kill the destination storage process
    # mid-fetch (its AddingShard state is RAM-only).
    from foundationdb_tpu.server import system_keys as sk

    async def start_move(tr):
        tr.options["access_system_keys"] = True
        b, e = b"mv0020", b"\xff"
        tr.set(sk.key_servers_key(b), sk.encode_key_servers(["ss0"], ["ss1"], e))

    c.run_until(db.process.spawn(db.run(start_move), "sm"), timeout_vt=600.0)

    async def brief():
        await c.loop.delay(0.02)  # let the fetch begin

    c.run_until(db.process.spawn(brief(), "b"), timeout_vt=600.0)
    dst_proc = c.storages[1].process
    dst_proc.kill()
    dst_proc.reboot()
    # Restart the destination storage role (non-durable sim: fresh object,
    # same id; a durable deployment would StorageServer.recover).
    from foundationdb_tpu.server.storage import StorageServer

    # A fresh joiner starts at the log's CURRENT durable version (its data
    # comes from the source storage via fetch, not from log history; old
    # history below the pop floors is gone by design).
    new_dst = StorageServer(
        dst_proc,
        [t.interface() for t in c.tlogs],
        storage_id="ss1",
        owned_all=False,
        epoch_begin_version=c.tlogs[0].durable.get(),
    )
    c.storages[1] = new_dst
    dd.storages["ss1"] = new_dst.interface()

    # DD drives the move to done: it must observe "missing" on the fresh
    # destination and restart the fetch.
    async def finish():
        await dd.move(b"mv0020", ["ss1"])

    c.run_until(db.process.spawn(finish(), "fin"), timeout_vt=2000.0)

    out = {}

    async def check(tr):
        out["rows"] = await tr.get_range(b"mv0020", b"mv\xff")

    c.run_until(db.process.spawn(db.run(check), "chk"), timeout_vt=600.0)
    assert len(out["rows"]) == 20
    assert out["rows"][0] == (b"mv0020", b"val0020")
