"""PRM/TSK promise-lifecycle lint family: rule units, interprocedural
cache correctness, CLI modes, and the tier-1 per-rule count surface.

The golden corpus (tests/lint_cases/prm_cases) runs through the shared
test_golden_corpus runner in test_lint.py; this module covers what the
corpus cannot: warm-cache cross-file correctness (editing only a
producer file must clear/raise a consumer-side PRM001), --changed-only
and single-file modes over the new interprocedural facts, SARIF shape,
and the conservative three-valued behaviors on planted sources.

Runnable alone: pytest -m lint tests/test_promises_lint.py
"""

import json
import os
import shutil
import sys

import pytest

import foundationdb_tpu
from foundationdb_tpu.tools.fdblint import (
    RULES,
    Project,
    count_by_rule,
    lint_package,
    lint_source,
    main,
)

pytestmark = pytest.mark.lint

PKG_DIR = os.path.dirname(os.path.abspath(foundationdb_tpu.__file__))
CASES_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "lint_cases"
)
PRM_RULES = ("PRM001", "PRM002", "PRM003", "PRM004", "TSK001")


def rules_of(findings, suppressed=False):
    return [f.rule for f in findings if f.suppressed == suppressed]


def test_prm_rules_registered_and_documented():
    for rule in PRM_RULES:
        assert rule in RULES and RULES[rule]


# ---------------------------------------------------------------------------
# PRM001 — orphaned waits
# ---------------------------------------------------------------------------


def test_prm001_attr_and_local_orphans():
    src = (
        "from foundationdb_tpu.flow.future import Promise\n"
        "class G:\n"
        "    def __init__(self):\n"
        "        self.gate = Promise()\n"
        "    async def w(self):\n"
        "        await self.gate.future\n"
        "async def lo():\n"
        "    p = Promise()\n"
        "    await p.future\n"
    )
    findings = lint_source(src, "server/x.py")
    prm = [f for f in findings if f.rule == "PRM001"]
    assert [f.line for f in prm] == [6, 9]


def test_prm001_sender_anywhere_clears():
    src = (
        "from foundationdb_tpu.flow.future import Promise\n"
        "class G:\n"
        "    def __init__(self):\n"
        "        self.gate = Promise()\n"
        "    async def w(self):\n"
        "        await self.gate.future\n"
        "def kick(g):\n"
        "    g.gate.send(1)\n"
    )
    assert "PRM001" not in rules_of(lint_source(src, "server/x.py"))


def test_prm001_escape_is_three_valued_unknown():
    # Aliasing or storing the entity voids tracking: someone unseen may
    # send — conservative no-finding, never a guess.
    src = (
        "from foundationdb_tpu.flow.future import Promise\n"
        "class G:\n"
        "    def __init__(self):\n"
        "        self.gate = Promise()\n"
        "    def share(self, reg):\n"
        "        reg.append(self.gate)\n"
        "    async def w(self):\n"
        "        await self.gate.future\n"
    )
    assert "PRM001" not in rules_of(lint_source(src, "server/x.py"))


def test_prm001_handoff_resolved_through_call_graph():
    # The local promise is handed into a callee; whether PRM001 fires is
    # decided by whether code reachable through that param can send.
    sender = (
        "from foundationdb_tpu.flow.future import Promise\n"
        "def fulfill(prom):\n"
        "    prom.send(1)\n"
        "async def w(loop):\n"
        "    p = Promise()\n"
        "    fulfill(p)\n"
        "    await p.future\n"
    )
    assert "PRM001" not in rules_of(lint_source(sender, "server/x.py"))
    nonsender = sender.replace("    prom.send(1)\n", "    return prom.future\n")
    found = rules_of(lint_source(nonsender, "server/x.py"))
    assert "PRM001" in found


def test_prm001_transitive_param_forwarding():
    # fulfill() forwards to a helper two frames down that sends: the
    # fixpoint must carry "may send" back through the chain.
    src = (
        "from foundationdb_tpu.flow.future import Promise\n"
        "def deep(x):\n"
        "    x.send(1)\n"
        "def mid(prom):\n"
        "    deep(prom)\n"
        "async def w():\n"
        "    p = Promise()\n"
        "    mid(p)\n"
        "    await p.future\n"
    )
    assert "PRM001" not in rules_of(lint_source(src, "server/x.py"))


def test_prm001_pragma_suppresses_with_reason():
    src = (
        "from foundationdb_tpu.flow.future import Promise\n"
        "class G:\n"
        "    def __init__(self):\n"
        "        self.gate = Promise()\n"
        "    async def w(self):\n"
        "        await self.gate.future  # fdblint: ignore[PRM001]: debug hook sends in tests\n"
    )
    findings = lint_source(src, "server/x.py")
    assert not [f for f in findings if not f.suppressed]
    assert [f.reason for f in findings if f.suppressed] == [
        "debug hook sends in tests"
    ]


# ---------------------------------------------------------------------------
# PRM002 — dropped promises
# ---------------------------------------------------------------------------


def test_prm002_paths_and_negatives():
    src = (
        "from foundationdb_tpu.flow.future import Promise\n"
        "def drop(cond):\n"
        "    p = Promise()\n"
        "    if cond:\n"
        "        return None\n"
        "    p.send(1)\n"
        "def fin(risky):\n"
        "    p = Promise()\n"
        "    try:\n"
        "        risky()\n"
        "    finally:\n"
        "        p.send_error(ValueError('x'))\n"
        "    return p.future\n"
        "class H:\n"
        "    def keep(self):\n"
        "        p = Promise()\n"
        "        self.kept = p\n"
        "        return p.future\n"
    )
    findings = lint_source(src, "server/x.py")
    prm = [f for f in findings if f.rule == "PRM002"]
    assert [f.line for f in prm] == [3]


def test_prm002_handoff_to_leaky_callee():
    src = (
        "from foundationdb_tpu.flow.future import Promise\n"
        "async def leaky(req, done):\n"
        "    if req is None:\n"
        "        return\n"
        "    done.send(req)\n"
        "def hand(loop, req):\n"
        "    p = Promise()\n"
        "    loop.spawn(leaky(req, p), 'h')\n"
        "    return None\n"
    )
    findings = lint_source(src, "server/x.py")
    prm = [f for f in findings if f.rule == "PRM002"]
    assert [f.line for f in prm] == [8]
    assert "leaky" in prm[0].message and "'done'" in prm[0].message
    fixed = src.replace("        return\n", "        done.send_error('e')\n        return\n")
    assert "PRM002" not in rules_of(lint_source(fixed, "server/x.py"))


def test_prm002_shared_ownership_not_flagged():
    # The caller keeps using the promise after the handoff: ownership is
    # shared, the handoff alone must not be called a drop.
    src = (
        "from foundationdb_tpu.flow.future import Promise\n"
        "async def leaky(req, done):\n"
        "    if req is None:\n"
        "        return\n"
        "    done.send(req)\n"
        "def hand(loop, req):\n"
        "    p = Promise()\n"
        "    loop.spawn(leaky(req, p), 'h')\n"
        "    return p.future\n"
    )
    assert "PRM002" not in rules_of(lint_source(src, "server/x.py"))


# ---------------------------------------------------------------------------
# PRM003 — wait-cycles
# ---------------------------------------------------------------------------


def test_prm003_cycle_and_external_sender():
    src = (
        "from foundationdb_tpu.flow.future import Promise\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self.x = Promise()\n"
        "        self.y = Promise()\n"
        "    async def a(self):\n"
        "        await self.y.future\n"
        "        self.x.send(1)\n"
        "    async def b(self):\n"
        "        await self.x.future\n"
        "        self.y.send(1)\n"
    )
    findings = lint_source(src, "server/x.py")
    prm = [f for f in findings if f.rule == "PRM003"]
    assert [f.line for f in prm] == [7, 10]
    live = src + "    def kick(self):\n        self.y.send(0)\n"
    assert "PRM003" not in rules_of(lint_source(live, "server/x.py"))


def test_prm003_unresolvable_receiver_is_conservative():
    # The waiter reaches the peer through a parameter — statically
    # unattributable, so no edge and no finding (three-valued unknown).
    src = (
        "from foundationdb_tpu.flow.future import Promise\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self.ap = Promise()\n"
        "    async def run(self, peer):\n"
        "        await peer.bp.future\n"
        "        self.ap.send(1)\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self.bp = Promise()\n"
        "    async def run(self, peer):\n"
        "        await peer.ap.future\n"
        "        self.bp.send(1)\n"
    )
    assert "PRM003" not in rules_of(lint_source(src, "server/x.py"))


# ---------------------------------------------------------------------------
# PRM004 — producerless stream loops
# ---------------------------------------------------------------------------


def test_prm004_terminating_vs_infinite_vs_closing_producers():
    base = (
        "from foundationdb_tpu.flow.future import PromiseStream\n"
        "class Pipe:\n"
        "    def __init__(self):\n"
        "        self.q = PromiseStream()\n"
        "    async def consume(self):\n"
        "        while True:\n"
        "            item = await self.q.pop()\n"
        "    async def produce(self, items):\n"
        "        for it in items:\n"
        "            self.q.send(it)\n"
    )
    findings = lint_source(base, "server/x.py")
    assert [f.line for f in findings if f.rule == "PRM004"] == [7]
    closing = base + "    def drain(self):\n        self.q.send_error(ValueError('eos'))\n"
    assert "PRM004" not in rules_of(lint_source(closing, "server/x.py"))
    forever = base.replace(
        "        for it in items:\n            self.q.send(it)\n",
        "        while True:\n            self.q.send(items())\n",
    )
    assert "PRM004" not in rules_of(lint_source(forever, "server/x.py"))


# ---------------------------------------------------------------------------
# TSK001 — unobserved spawned tasks
# ---------------------------------------------------------------------------


def test_tsk001_dropped_vs_held_vs_guarded():
    src = (
        "async def fragile(loop):\n"
        "    await loop.delay(1)\n"
        "async def guarded(loop):\n"
        "    try:\n"
        "        await loop.delay(1)\n"
        "    except ValueError:\n"
        "        return None\n"
        "def go(loop):\n"
        "    loop.spawn(fragile(loop), 'f')\n"
        "    loop.spawn(guarded(loop), 'g')\n"
        "    t = loop.spawn(fragile(loop), 'h')\n"
        "    loop.spawn_observed(fragile(loop), 'o')\n"
        "    return t\n"
    )
    findings = lint_source(src, "server/x.py")
    tsk = [f for f in findings if f.rule == "TSK001"]
    assert [f.line for f in tsk] == [9]


def test_tsk001_nonraising_coroutine_is_clean():
    src = (
        "async def pure():\n"
        "    return 1\n"
        "def go(loop):\n"
        "    loop.spawn(pure(), 'p')\n"
    )
    assert "TSK001" not in rules_of(lint_source(src, "server/x.py"))


# ---------------------------------------------------------------------------
# Interprocedural cache correctness: the producer-edit scenario
# ---------------------------------------------------------------------------


def test_editing_producer_clears_and_raises_consumer_prm001(tmp_path):
    """PR 5's DET101 cache-correctness discipline for the PRM facts: the
    consumer-side PRM001 must appear/disappear when ONLY the producer
    file changes, with the consumer's record served from warm cache."""
    src_dir = os.path.join(CASES_DIR, "prm_cases")
    work = tmp_path / "pkg"
    shutil.copytree(src_dir, work)
    cache = str(tmp_path / "lint.pkl")

    p1 = Project(str(work), cache_path=cache, use_cache=True)
    first = p1.lint()
    assert p1.stats["parsed"] == p1.stats["files"] > 0
    assert not [
        f for f in first
        if f.rule == "PRM001" and f.path == "flow/consumer.py"
    ]

    # Remove the only sender: the cached consumer must now flag.
    producer = work / "server" / "producer.py"
    producer.write_text("def kick(handshake):\n    return None\n")
    p2 = Project(str(work), cache_path=cache, use_cache=True)
    second = p2.lint()
    assert p2.stats["parsed"] == 1  # only the producer re-analyzed
    consumer_hits = [
        f for f in second
        if f.rule == "PRM001" and f.path == "flow/consumer.py"
        and not f.suppressed
    ]
    assert len(consumer_hits) == 1

    # Restore the send: the finding clears again, still from cache.
    producer.write_text(
        "def kick(handshake):\n    handshake.ready.send(1)\n"
    )
    p3 = Project(str(work), cache_path=cache, use_cache=True)
    third = p3.lint()
    assert p3.stats["parsed"] == 1
    assert not [
        f for f in third
        if f.rule == "PRM001" and f.path == "flow/consumer.py"
    ]


def test_changed_only_reports_consumer_side_finding(tmp_path, capsys):
    """--changed-only with only the producer edited: the whole project is
    still loaded, so the consumer-side PRM001 exists — and the filter
    keeps only the changed file's findings, exactly like DET101."""
    git = shutil.which("git")
    if git is None:
        pytest.skip("git unavailable")
    import subprocess

    repo = tmp_path / "repo"
    shutil.copytree(os.path.join(CASES_DIR, "prm_cases"), repo / "pkg")

    def run_git(*args):
        subprocess.run(
            [git, "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=repo, capture_output=True, text=True, check=True,
        )

    run_git("init", "-q")
    run_git("add", "-A")
    run_git("commit", "-qm", "seed")
    (repo / "pkg" / "server" / "producer.py").write_text(
        "def kick(handshake):\n    return None\n"
    )
    rc = main([str(repo / "pkg"), "--format=json", "--no-cache",
               "--changed-only"])
    out = json.loads(capsys.readouterr().out)
    # DET101 semantics carried over: the filter keeps only the CHANGED
    # file's findings (the clean producer), so the gate passes here —
    # but the whole project was loaded, and the full scan must show the
    # consumer-side PRM001 the edit introduced.
    assert rc == 0 and out["findings"] == []
    rc_full = main([str(repo / "pkg"), "--format=json", "--no-cache"])
    full = json.loads(capsys.readouterr().out)
    assert rc_full == 1
    assert any(
        f["rule"] == "PRM001" and f["path"] == "flow/consumer.py"
        for f in full["findings"]
    )


def test_single_file_mode_sees_cross_file_senders():
    """Linting one real module alone must load the enclosing package so
    cross-file senders keep clearing PRM001 (the editor/pre-commit
    integration path)."""
    res = os.path.join(PKG_DIR, "server", "resolver.py")
    findings = lint_package(res)
    assert not [f for f in findings if not f.suppressed], [
        f.format() for f in findings if not f.suppressed
    ]
    assert main([res]) == 0


# ---------------------------------------------------------------------------
# Gate surfaces: SARIF, per-rule counts, package cleanliness
# ---------------------------------------------------------------------------


def test_sarif_declares_prm_rules(capsys):
    case_dir = os.path.join(CASES_DIR, "prm_cases")
    rc = main([case_dir, "--format=sarif", "--no-cache", "--show-suppressed"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    run = out["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert set(PRM_RULES) <= rule_ids
    flagged = {r["ruleId"] for r in run["results"] if r["level"] == "error"}
    assert set(PRM_RULES) <= flagged
    # Reasoned suppressions ride along as justified SARIF suppressions.
    sup = [r for r in run["results"] if r.get("suppressions")]
    assert sup and all(
        s["suppressions"][0]["justification"] for s in sup
    )


def test_package_clean_and_prm_counts_printed():
    """The tier-1 surface: the whole package holds zero unsuppressed
    PRM/TSK findings, and the per-rule counts (zero or not) are printed
    to the tier-1 log so drift is visible."""
    findings = lint_package(PKG_DIR)
    counts = count_by_rule(findings)
    cells = []
    for rule in PRM_RULES:
        c = counts.get(rule, {"flagged": 0, "suppressed": 0})
        assert c["flagged"] == 0, (
            f"{rule}: {[f.format() for f in findings if f.rule == rule]}"
        )
        cells.append(f"{rule}={c['flagged']}+{c['suppressed']}s")
    print(
        "\n[fdblint] promise-lifecycle (flagged+suppressed): "
        + " ".join(cells),
        file=sys.__stderr__,
    )


def test_pipeline_and_recovery_paths_lint_clean_single_file():
    """The acceptance-named paths, linted individually through the real
    single-file CLI mode: the pipeline park/drain completion promises
    (server/resolver.py) and the recovery re-recruit handoffs
    (server/cluster_controller.py) are tested NEGATIVES — promise-clean
    under the full interprocedural fact set."""
    for mod in ("resolver.py", "cluster_controller.py",
                "failure_monitor.py"):
        path = os.path.join(PKG_DIR, "server", mod)
        bad = [f for f in lint_package(path) if not f.suppressed]
        assert not bad, [f.format() for f in bad]


# ---------------------------------------------------------------------------
# Review regressions
# ---------------------------------------------------------------------------


def test_prm004_nested_break_does_not_make_producer_terminating():
    # A break belonging to a NESTED loop does not exit the producer's
    # `while True:` — the producer never terminates, so the consumer
    # loop must not flag (review regression: ast.walk found the inner
    # break and classified the while-True as breakable).
    src = (
        "from foundationdb_tpu.flow.future import PromiseStream\n"
        "class Q:\n"
        "    def __init__(self):\n"
        "        self.s = PromiseStream()\n"
        "    async def consumer(self):\n"
        "        while True:\n"
        "            item = await self.s.pop()\n"
        "    async def producer(self):\n"
        "        while True:\n"
        "            for item in self.batch():\n"
        "                if item is None:\n"
        "                    break\n"
        "                self.s.send(item)\n"
    )
    assert "PRM004" not in rules_of(lint_source(src, "server/x.py"))
    # ...while a break that DOES exit the while-True keeps it a
    # terminating producer, and the consumer flags.
    own_break = src.replace(
        "            for item in self.batch():\n"
        "                if item is None:\n"
        "                    break\n"
        "                self.s.send(item)\n",
        "            item = self.batch()\n"
        "            if item is None:\n"
        "                break\n"
        "            self.s.send(item)\n",
    )
    assert "PRM004" in rules_of(lint_source(own_break, "server/x.py"))


def test_standalone_file_mode_skips_project_global_attr_rules(tmp_path):
    """A real .py OUTSIDE any package, linted alone (lint_package's
    standalone fallback): sibling files were not loaded, so the
    attr-entity rules must not claim "no code in the project sends" —
    while the function-LOCAL entity rules (unreachable from other
    files) still run."""
    mod = tmp_path / "standalone.py"
    mod.write_text(
        "from foundationdb_tpu.flow.future import Promise\n"
        "class G:\n"
        "    def __init__(self):\n"
        "        self.gate = Promise()\n"
        "    async def w(self):\n"
        "        await self.gate.future\n"  # a sibling file may send
        "async def lo():\n"
        "    p = Promise()\n"
        "    await p.future\n"              # provably local: still flags
    )
    findings = [f for f in lint_package(str(mod)) if f.rule == "PRM001"]
    assert [f.line for f in findings] == [9]


def test_prm004_local_stream_infinite_producer_is_clean():
    # Review regression: the LOCAL-stream branch must apply the same
    # infinite-producer exemption as the attr branch — a closure
    # producer sending inside an unbroken `while True:` never
    # terminates, so the consumer loop is legitimate.
    src = (
        "from foundationdb_tpu.flow.future import PromiseStream\n"
        "async def pump(loop):\n"
        "    ps = PromiseStream()\n"
        "    async def producer():\n"
        "        while True:\n"
        "            ps.send(1)\n"
        "    loop.spawn(producer(), 'prod')\n"
        "    while True:\n"
        "        item = await ps.pop()\n"
    )
    assert "PRM004" not in rules_of(lint_source(src, "server/x.py"))
