"""The BASELINE.json acceptance matrix: every named config exists as a
runnable workload, and the CPU<->JAX conflict backends produce IDENTICAL
histories on the adversarial ones.

BASELINE.json configs:
1. skipListTest microbench            -> bench.py (driver-run)
2. WriteDuringRead, high contention   -> differential gate here
3. RandomReadWrite, low contention    -> differential gate here
4. Multi-resolver (4) + Cycle         -> differential gate here
5. 64k-batch Zipf replay              -> bench.py device phase (driver-run)

Identity of histories is the real acceptance bar (ref: the north star's
"identical tooManyConflicts decisions vs CPU SkipList on the simulated
WriteDuringRead workload"): the simulation is deterministic per seed, so
swapping ONLY the conflict backend must reproduce the exact per-txn
outcome sequence, final database state, and mismatch-free memory model.
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.workloads import (
    CycleWorkload,
    RandomReadWriteWorkload,
    WriteDuringReadWorkload,
    run_workloads,
)


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def _final_state(cluster, prefix: bytes):
    db = cluster.database("final_reader")

    async def read(tr):
        return await tr.get_range(prefix, prefix + b"\xff")

    async def run():
        out = None
        tr = db.create_transaction()
        out = await read(tr)
        return out

    return cluster.run_until(db.process.spawn(run(), "final"), timeout_vt=5000.0)


def _run_wdr(backend: str, seed: int, conflict_set=None):
    c = SimCluster(
        seed=seed, conflict_backend=backend, n_proxies=2,
        conflict_set=conflict_set,
    )
    # contention_actors: write-conflict-only contenders make the history
    # carry REAL abort decisions (the high-contention config the north
    # star names) while the memory model stays byte-exact.
    wl = WriteDuringReadWorkload(nodes=25, txns=10, contention_actors=3)
    run_workloads(c, [wl], timeout_vt=30000.0)
    state = _final_state(c, wl.prefix)
    set_event_loop(None)
    return wl, state


def test_write_during_read_differential_cpu_vs_jax(monkeypatch):
    """Config 2: the high-contention RYW workload, identical histories.

    Pinned to pipeline depth 1: cross-BACKEND history identity includes
    reply timing, and the ISSUE-11 async offload defers jax-backend
    replies by design.  Verdict/state identity of the pipelined path
    itself is gated across depths by tests/test_resolver_pipeline.py."""
    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", "1")
    cpu_wl, cpu_state = _run_wdr("cpu", seed=9001)
    jax_wl, jax_state = _run_wdr("jax", seed=9001)
    assert not cpu_wl.mismatches and not jax_wl.mismatches
    assert cpu_wl.history == jax_wl.history
    assert cpu_wl.committed_txns == jax_wl.committed_txns > 0
    # The contention must actually produce conflict decisions to compare.
    assert cpu_wl.conflicts == jax_wl.conflicts > 0, cpu_wl.history
    assert cpu_state == jax_state


def _run_rrw(backend: str, seed: int):
    c = SimCluster(seed=seed, conflict_backend=backend, n_proxies=2)
    wl = RandomReadWriteWorkload(nodes=120, actors=3, txns_per_actor=6)
    run_workloads(c, [wl], timeout_vt=30000.0)
    state = _final_state(c, wl.prefix)
    set_event_loop(None)
    return wl, state


def test_random_read_write_differential_cpu_vs_jax(monkeypatch):
    """Config 3: uniform keys, low contention, identical histories.
    (Depth 1 for cross-backend timing comparability — see config 2.)"""
    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", "1")
    cpu_wl, cpu_state = _run_rrw("cpu", seed=9002)
    jax_wl, jax_state = _run_rrw("jax", seed=9002)
    assert cpu_wl.committed == jax_wl.committed == 18
    assert cpu_state == jax_state


def _run_cycle_multi_resolver(backend: str, seed: int):
    c = SimCluster(
        seed=seed, conflict_backend=backend, n_resolvers=4, n_proxies=2
    )
    # ops trimmed 25 -> 12 for tier-1 runtime headroom (ISSUE 4 satellite):
    # the gate still drives 4-resolver sharded contention with identical-
    # history assertion; the larger soak belongs to the slow sweeps.
    wl = CycleWorkload(nodes=8, ops=12, actors=3)
    run_workloads(c, [wl], timeout_vt=30000.0)
    state = _final_state(c, wl.prefix)
    set_event_loop(None)
    return state


def test_cycle_multi_resolver_differential_cpu_vs_jax(monkeypatch):
    """Config 4: resolvers=4 with KeyRangeMap sharding, Cycle invariant.
    (Depth 1 for cross-backend timing comparability — see config 2.)"""
    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", "1")
    cpu_state = _run_cycle_multi_resolver("cpu", seed=9003)
    jax_state = _run_cycle_multi_resolver("jax", seed=9003)
    assert cpu_state == jax_state


def _run_wdr_sharded(seed: int):
    import jax

    from foundationdb_tpu.parallel.sharded_resolver import (
        ShardedJaxConflictSet,
    )
    from foundationdb_tpu.workloads.write_during_read import (
        WriteDuringReadWorkload as _WDR,
    )

    # Split at the MIDDLE of the workload's actual key format
    # (prefix + b"%06d") so both shards carry real traffic and the
    # cross-shard min-combine path is genuinely exercised.
    probe = _WDR(nodes=25)
    split_key = probe.prefix + b"000012"
    cs = ShardedJaxConflictSet(
        [split_key],
        key_words=4,
        h_cap=1 << 12,
        devices=jax.devices()[:2],
        bucket_mins=(64, 128, 128),
    )
    wl, state = _run_wdr("cpu", seed, conflict_set=cs)
    # conflict_set overrides the backend arg in the resolver; assert BOTH
    # shards actually accumulated history (the split did its job).
    assert cs.boundary_count > 0
    import numpy as np

    per_shard = np.asarray(cs._hcount) if cs._cpu_engines is None else [
        len(e.keys) for e in cs._cpu_engines
    ]
    assert all(int(n) > 1 for n in per_shard), (
        f"a shard stayed empty — split key wrong: {per_shard}"
    )
    return wl, state


def test_write_during_read_differential_cpu_vs_sharded():
    """The MESH-SHARDED device resolver must reproduce the single CPU
    set's exact per-txn history on the high-contention config: min-combine
    over per-shard clipped verdicts ≡ global detection (a conflict in any
    shard is a global conflict; window floors advance identically), so
    swapping in the multichip backend must not change a single outcome."""
    cpu_wl, cpu_state = _run_wdr("cpu", seed=9003)
    sh_wl, sh_state = _run_wdr_sharded(seed=9003)
    assert not cpu_wl.mismatches and not sh_wl.mismatches
    assert cpu_wl.history == sh_wl.history
    assert cpu_wl.committed_txns == sh_wl.committed_txns > 0
    assert cpu_wl.conflicts == sh_wl.conflicts > 0
    assert cpu_state == sh_state
