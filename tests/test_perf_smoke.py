"""CPU-backend perf smoke gates (ISSUE 4 satellite): recompile storms and
H-proportional per-batch work must fail tier-1, not show up on hardware.

Two pins:

1. Compile counts: retraces == distinct static shape buckets for both the
   flat and the tiered engine, across batches that include major
   compactions — the traced-lax.cond compaction must add NO new compile
   buckets per batch.

2. Structural (jaxpr) bound on steady-state work: in the tiered step,
   every H-sized sort/cumsum/concatenate/scatter lives INSIDE the major-
   compaction cond branch; non-compaction batches touch the base only
   through read-only gathers (binary search + carried max-table lookups).
   This is the CPU-assertable form of "per-batch work bounded by delta
   size, not h_cap" — it needs no hardware timer and cannot flake.

Run alone: pytest -m perf_smoke
"""

import math
from functools import partial

import pytest

import jax
import jax.numpy as jnp

from foundationdb_tpu.conflict.engine_cpu import CpuConflictSet
from foundationdb_tpu.conflict.engine_jax import (
    JaxConflictSet,
    detect_core,
    detect_core_tiered,
)
from foundationdb_tpu.conflict.keys import ENCODE_OPS
from foundationdb_tpu.conflict.types import TransactionConflictInfo as T
from foundationdb_tpu.tools.lint.jaxir import WORK_PRIMS, walk_jaxpr

pytestmark = pytest.mark.perf_smoke


def k(i: int) -> bytes:
    return b"%08d" % i


# ---------------------------------------------------------------------------
# 1. compile-count pins
# ---------------------------------------------------------------------------


def _drive(cs, batches=10, writes_per_batch=6):
    cpu = CpuConflictSet()
    v = 0
    for i in range(batches):
        txns = [
            T(read_snapshot=v,
              write_ranges=[(k(1000 * i + 4 * j), k(1000 * i + 4 * j + 1))
                            for j in range(writes_per_batch)]),
            T(read_snapshot=max(0, v - 3),
              read_ranges=[(k(1000 * max(0, i - 1)), k(1000 * i + 30))]),
        ]
        v += 5
        assert cs.detect(txns, v, max(0, v - 20)) == cpu.detect(
            txns, v, max(0, v - 20)
        ), f"batch {i}"


def test_flat_retraces_equal_distinct_buckets():
    cs = JaxConflictSet(key_words=3, h_cap=1 << 8, bucket_mins=(8, 8, 16))
    _drive(cs)
    snap = cs.metrics.snapshot()
    assert snap["counters"]["batches"] == 10
    assert snap["counters"]["retraces"] == len(cs._bucket_dispatches) == 1, (
        "recompile storm: one static bucket must compile exactly once"
    )


def test_tiered_compaction_adds_no_compile_buckets(monkeypatch):
    """Cadence-2 compactions: 10 batches alternate minor/major through the
    SAME compiled program (the cond is traced, not re-jitted)."""
    monkeypatch.setenv("FDB_TPU_HISTORY", "tiered")
    monkeypatch.setenv("FDB_TPU_DELTA_CAP", "128")
    monkeypatch.setenv("FDB_TPU_EVICT_EVERY", "2")
    cs = JaxConflictSet(key_words=3, h_cap=1 << 8, bucket_mins=(8, 8, 16))
    assert cs.tiered and cs.compact_every == 2
    _drive(cs)
    snap = cs.metrics.snapshot()
    assert snap["counters"]["major_compactions"] >= 4
    assert snap["counters"]["retraces"] == len(cs._bucket_dispatches) == 1, (
        "tiered path added compile buckets per batch"
    )


# ---------------------------------------------------------------------------
# 2. structural jaxpr gate: steady-state work bounded by delta size
# ---------------------------------------------------------------------------

KW1 = 4
H_CAP = 4096
D_CAP = 256
TXN, RR, WR = 32, 128, 64

# The shared jaxpr visitor + work-primitive set live in tools/lint/jaxir.py
# (jaxcheck) — ONE walker serves this gate and the JXP rule family, so the
# perf_smoke invariant and jaxcheck can never drift apart.  Note
# EqnEntry.max_dim spans operands AND results (a concat BUILDING an
# H-sized array from small pieces is H-sized work).


def _tiered_jaxpr(kernels=False):
    lmax = max(1, math.ceil(math.log2(H_CAP)))
    u32 = jnp.uint32
    i32 = jnp.int32
    args = (
        jnp.zeros((KW1, H_CAP), u32),        # hkeys
        jnp.zeros((H_CAP,), i32),            # hvers
        jnp.asarray(1, i32),                 # hcount
        jnp.zeros((lmax + 1, H_CAP), i32),   # maxtab
        jnp.zeros((KW1, D_CAP), u32),        # dkeys
        jnp.zeros((D_CAP,), i32),            # dvers
        jnp.asarray(1, i32),                 # dcount
        jnp.asarray(0, i32),                 # oldest
        jnp.zeros((KW1, RR), u32),           # r_begin
        jnp.zeros((KW1, RR), u32),           # r_end
        jnp.zeros((RR,), i32),               # r_txn
        jnp.zeros((RR,), i32),               # r_snap
        jnp.zeros((KW1, WR), u32),           # w_begin
        jnp.zeros((KW1, WR), u32),           # w_end
        jnp.zeros((WR,), i32),               # w_txn
        jnp.zeros((TXN,), i32),              # t_snap
        jnp.zeros((TXN,), bool),             # t_has_reads
        jnp.zeros((TXN,), bool),             # t_valid
        jnp.asarray(1, i32),                 # now_rel
        jnp.asarray(0, i32),                 # new_oldest_rel
        jnp.asarray(0, i32),                 # do_major
    )
    fn = partial(detect_core_tiered, txn_cap=TXN, rr_cap=RR, wr_cap=WR,
                 h_cap=H_CAP, d_cap=D_CAP, kernels=kernels,
                 kernel_interpret=kernels)
    return jax.make_jaxpr(fn)(*args)


def _flat_jaxpr(kernels=False):
    u32 = jnp.uint32
    i32 = jnp.int32
    args = (
        jnp.zeros((KW1, H_CAP), u32),
        jnp.zeros((H_CAP,), i32),
        jnp.asarray(1, i32),
        jnp.asarray(0, i32),
        jnp.zeros((KW1, RR), u32),
        jnp.zeros((KW1, RR), u32),
        jnp.zeros((RR,), i32),
        jnp.zeros((RR,), i32),
        jnp.zeros((KW1, WR), u32),
        jnp.zeros((KW1, WR), u32),
        jnp.zeros((WR,), i32),
        jnp.zeros((TXN,), i32),
        jnp.zeros((TXN,), bool),
        jnp.zeros((TXN,), bool),
        jnp.asarray(1, i32),
        jnp.asarray(0, i32),
    )
    fn = partial(detect_core, txn_cap=TXN, rr_cap=RR, wr_cap=WR, h_cap=H_CAP,
                 kernels=kernels, kernel_interpret=kernels)
    return jax.make_jaxpr(fn)(*args)


def test_flat_step_has_h_sized_sorts():
    """Detector sanity: the flat step's merge+evict ARE H-sized sorts (the
    very ones the tier split amortizes) and the shared visitor sees them."""
    entries = walk_jaxpr(_flat_jaxpr())
    h_sorts = [e for e in entries if e.prim == "sort" and e.max_dim >= H_CAP]
    assert len(h_sorts) >= 2, entries


def test_tiered_steady_state_has_no_h_sized_work_outside_cond():
    """The gate: every H-sized work primitive lives inside the compaction
    cond; the steady-state (non-compaction) batch is bounded by delta/
    point-domain sizes.  The compaction branch must still contain the
    H-sized sorts (it exists and does the real merge)."""
    entries = walk_jaxpr(_tiered_jaxpr())
    outside = [
        e for e in entries
        if not e.in_cond and e.prim in WORK_PRIMS and e.max_dim >= H_CAP
    ]
    assert not outside, (
        f"H-sized work escaped the compaction cond: {outside}"
    )
    inside_sorts = [
        e for e in entries
        if e.in_cond and e.prim == "sort" and e.max_dim >= H_CAP
    ]
    assert len(inside_sorts) >= 2, (
        "the compaction branch lost its H-sized merge/evict sorts"
    )
    # And the biggest sort outside the cond is delta/point-domain sized.
    out_sorts = [
        e.max_dim for e in entries if not e.in_cond and e.prim == "sort"
    ]
    assert out_sorts and max(out_sorts) < H_CAP


def test_kernel_mode_has_no_h_sized_sort_anywhere():
    """ISSUE 14 acceptance gate: kernelized merge+evict runs as ONE pass.

    With FDB_TPU_KERNELS on, the fused Pallas kernel replaces BOTH
    sort-by-target passes — so the flat step has NO H-sized sort at all,
    and the tiered step's compaction cond (which held the two full-H
    sorts, pinned above) holds ZERO.  Remaining sorts are batch-domain
    (point sort, new-boundary sort, kernel query sort) — all < H."""
    flat = walk_jaxpr(_flat_jaxpr(kernels=True))
    flat_h_sorts = [e for e in flat
                    if e.prim == "sort" and e.max_dim >= H_CAP]
    assert not flat_h_sorts, flat_h_sorts
    tiered = walk_jaxpr(_tiered_jaxpr(kernels=True))
    cond_h_sorts = [
        e for e in tiered
        if e.in_cond and e.prim == "sort" and e.max_dim >= H_CAP
    ]
    assert not cond_h_sorts, (
        f"kernel mode left an H-sized sort in the compaction cond: "
        f"{cond_h_sorts}"
    )
    any_h_sorts = [e for e in tiered
                   if e.prim == "sort" and e.max_dim >= H_CAP]
    assert not any_h_sorts, any_h_sorts
    # The pallas kernels are actually IN the program (one fused-merge
    # call per compaction site + the tier-combined searches).
    assert sum(e.prim == "pallas_call" for e in tiered) >= 3
    assert sum(e.prim == "pallas_call" for e in flat) >= 2


def test_kernel_mode_tiered_steady_state_stays_delta_bounded():
    """Same contract as the sort-path gate: with kernels on, NO H-sized
    work primitive outside the compaction cond — including INSIDE kernel
    bodies (walk_jaxpr descends pallas_call sub-jaxprs, and pl.when's
    lowered cond deliberately does not count as the compaction cond)."""
    entries = walk_jaxpr(_tiered_jaxpr(kernels=True))
    outside = [
        e for e in entries
        if not e.in_cond and e.prim in WORK_PRIMS and e.max_dim >= H_CAP
    ]
    assert not outside, (
        f"H-sized work escaped the compaction cond under kernels: {outside}"
    )
    # In-kernel work primitives are tile-bounded (the whole point of the
    # VMEM-resident design): far below one tier's width.
    in_kernel_work = [
        e.max_dim for e in entries
        if e.in_kernel and e.prim in WORK_PRIMS
    ]
    assert in_kernel_work and max(in_kernel_work) <= 1024


def test_sharded_step_per_shard_work_bounded_at_production_shape():
    """ISSUE 15 structural pin: in the mesh-sharded step — traced at a
    PRODUCTION per-shard width with the kernels flag on — every work
    primitive stays bounded by ONE shard's slice.  A primitive sized
    S*h_cap would mean something is touching globally-sized data inside
    the shard_map body (the per-shard fault domain would then not bound
    per-shard work)."""
    from foundationdb_tpu.parallel.sharded_resolver import (
        AXIS,
        _make_sharded_step,
    )
    from jax.sharding import Mesh
    import numpy as np

    S = 2
    SHARD_H = 1 << 19  # ~ BASE_H_CAP / 8: the production per-shard slice
    mesh = Mesh(np.array(jax.devices()[:S]), (AXIS,))
    step = _make_sharded_step(
        mesh, TXN, RR, WR, SHARD_H, kernels=True, kernel_interpret=True
    )
    sds = jax.ShapeDtypeStruct
    u32, i32 = jnp.uint32, jnp.int32
    args = (
        sds((S, KW1), u32),            # lo
        sds((S, KW1), u32),            # hi
        sds((S,), jnp.bool_),          # active
        sds((S, KW1, SHARD_H), u32),   # hkeys
        sds((S, SHARD_H), i32),        # hvers
        sds((S,), i32),                # hcount
        sds((S,), i32),                # oldest
        sds((KW1, RR), u32),           # r_begin
        sds((KW1, RR), u32),           # r_end
        sds((RR,), i32),               # r_txn
        sds((RR,), i32),               # r_snap
        sds((KW1, WR), u32),           # w_begin
        sds((KW1, WR), u32),           # w_end
        sds((WR,), i32),               # w_txn
        sds((TXN,), i32),              # t_snap
        sds((TXN,), jnp.bool_),        # t_valid
        sds((), i32),                  # now_rel
        sds((), i32),                  # new_oldest_rel
    )
    entries = walk_jaxpr(jax.make_jaxpr(step)(*args))
    bound = SHARD_H + 4 * WR  # the flat engine's legitimate full-width
    # merge at ONE shard's h_cap (the jaxcheck work_bound contract)
    too_wide = [
        e for e in entries
        if e.prim in WORK_PRIMS and e.max_dim > bound
    ]
    assert not too_wide, (
        f"work primitives exceeded the per-shard slice bound {bound}: "
        f"{too_wide}"
    )
    # With kernels on there is no H-sized sort at all (the ISSUE-14
    # one-pass contract holds inside the shard body too) and the fused
    # kernels are actually in the program.
    h_sorts = [
        e for e in entries if e.prim == "sort" and e.max_dim >= SHARD_H
    ]
    assert not h_sorts, h_sorts
    assert sum(e.prim == "pallas_call" for e in entries) >= 2


# ---------------------------------------------------------------------------
# 3. device program cost accounting (ISSUE 10)
# ---------------------------------------------------------------------------


def test_carried_buffer_bytes_match_capacity_shape_math():
    """CPU-assertable pin: each entry point's REPORTED carried-buffer
    byte accounting equals independent h_cap/d_cap arithmetic — a silent
    footprint regression (a widened dtype, an extra carried buffer) must
    fail here, no TPU needed.  arg_nbytes is pure shape math (no trace,
    no compile)."""
    from foundationdb_tpu.conflict.engine_jax import (
        DEVICE_ENTRY_POINTS,
        EP_D,
        EP_H,
        EP_KW1,
    )

    kw1 = EP_KW1  # already the key-words+1 (length-word) form
    lmax = max(1, math.ceil(math.log2(EP_H)))
    expected = {
        # hkeys (kw1, H) u32 + hvers (H,) i32 + hcount + oldest scalars
        "flat_step": 4 * kw1 * EP_H + 4 * EP_H + 4 + 4,
        # + maxtab (lmax+1, H) i32 + delta tier (dkeys/dvers/dcount)
        "tiered_step": (4 * kw1 * EP_H + 4 * EP_H + 4
                        + 4 * (lmax + 1) * EP_H
                        + 4 * kw1 * EP_D + 4 * EP_D + 4 + 4),
        "compact_body": 0,  # inner body: donation/carry owned by the cond
        "rebase_body": 4 * EP_H,
        "grow_body": 4 * kw1 * EP_H,
    }
    for name, want in expected.items():
        ep = DEVICE_ENTRY_POINTS[name]
        got = sum(ep.carried_bytes().values())
        assert got == want, (name, got, want)
        # And every carried name is accounted individually.
        assert set(ep.carried_bytes()) == set(ep.carried), name


def test_program_cost_table_covers_every_entry_point():
    """Acceptance gate (ISSUE 10): device_metrics()["programs"] has a
    cost block for every DEVICE_ENTRY_POINTS entry — carried bytes,
    memory_analysis allocation, FLOPs per batch — once the table is
    computed (lazily; FDB_TPU_PROGRAM_COSTS makes it eager).  Compiles
    each program once at its canonical trace shapes (cached for the
    process)."""
    from foundationdb_tpu.conflict.api import ConflictSet
    from foundationdb_tpu.conflict.engine_jax import (
        DEVICE_ENTRY_POINTS,
        program_cost_table,
    )

    table = program_cost_table()
    for name, ep in DEVICE_ENTRY_POINTS.items():
        blk = table[name]
        assert "error" not in blk, (name, blk)
        assert blk["carried_bytes_total"] == sum(
            ep.carried_bytes().values()
        )
        assert blk["memory"]["argument"] > 0, name
        # The step programs do real arithmetic; pure data movement
        # (grow) may legitimately report no flops.
        if name in ("flat_step", "tiered_step", "compact_body"):
            assert blk["flops_per_batch"] and blk["flops_per_batch"] > 0
            assert blk["memory"]["temp"] > 0, name
        # pallas_call-bearing entries (ISSUE 14) are never silently
        # missing: the explicit kernel marker plus either a real
        # cost-analysis block or the shape-math byte accounting.
        if name.endswith("_kernels"):
            assert blk.get("kernel") is True, (name, blk)
            assert (blk.get("flops_per_batch")
                    or blk["argument_bytes_total"] > 0), (name, blk)
        else:
            assert "kernel" not in blk, name
    # Deterministic blocks only: compile wall lives in the separate
    # include_wall view (the record_wall discipline).
    assert all("compile_wall_seconds" not in b for b in table.values())
    wall = program_cost_table(include_wall=True)
    assert wall["_compile_wall"]["count"] >= len(DEVICE_ENTRY_POINTS)
    assert all(
        "compile_wall_seconds" in wall[n] for n in DEVICE_ENTRY_POINTS
    )
    # The cached table now surfaces through the ConflictSet API.
    cs = ConflictSet(backend="jax")
    dm = cs.device_metrics()
    assert set(DEVICE_ENTRY_POINTS) <= set(dm["programs"])
    for blk in dm["programs"].values():
        assert "compile_wall_seconds" not in blk


# ---------------------------------------------------------------------------
# 4. host-budget counters: the PR-19 wins pinned as numbers (ISSUE 20)
# ---------------------------------------------------------------------------


def _big_batch(base, ranges=40):
    """One txn per side-heavy batch: `ranges` ranges per side puts every
    encode_keys call (begin+end concatenated = 2*ranges keys) on the
    n>=64 bulk path."""
    t = T(read_snapshot=0)
    for j in range(ranges):
        t.read_ranges.append((k(base + 4 * j), k(base + 4 * j + 1)))
        t.write_ranges.append((k(base + 4 * j + 2), k(base + 4 * j + 3)))
    return [t]


@pytest.mark.parametrize("mode", ["flat", "tiered", "kernels"])
def test_bulk_encode_does_zero_per_key_python(monkeypatch, mode):
    """The zero-copy batch-encode win as an op count: at n>=64 keys per
    encode call, the per-key ljust path runs ZERO times — across the
    flat, tiered, and kernels-interpret engines (the counter twin of
    perfcheck's HOT004)."""
    if mode == "tiered":
        monkeypatch.setenv("FDB_TPU_HISTORY", "tiered")
        monkeypatch.setenv("FDB_TPU_DELTA_CAP", "512")
        monkeypatch.setenv("FDB_TPU_EVICT_EVERY", "3")
    if mode == "kernels":
        monkeypatch.setenv("FDB_TPU_KERNELS", "1")
    cs = JaxConflictSet(key_words=3, h_cap=1 << 10,
                        bucket_mins=(32, 128, 64))
    assert cs.tiered is (mode == "tiered")
    perkey0 = ENCODE_OPS["perkey"]
    bulk0 = ENCODE_OPS["bulk_batches"]
    v = 0
    for i in range(4):
        v += 5
        cs.detect(_big_batch(10_000 * i), v, max(0, v - 40))
    assert ENCODE_OPS["perkey"] == perkey0, (
        "a side-heavy batch took the per-key ljust path"
    )
    # Both sides of every batch rode the vectorized bulk encode.
    assert ENCODE_OPS["bulk_batches"] >= bulk0 + 8


def test_pipelined_batch_host_sync_and_alloc_budget(monkeypatch):
    """FDB_TPU_TRANSFER_GUARD's counter half: a healthy pipelined batch
    enters at most 3 sanctioned sync scopes (ticket readback + witness
    readback + occasional planning), and with the staging ring on
    (default 'auto') steady-state encode allocates NOTHING — the blob
    ring hands out the same buffers forever."""
    from foundationdb_tpu.conflict.api import ConflictSet

    monkeypatch.setenv("FDB_TPU_PIPELINE_DEPTH", "2")
    cs = ConflictSet(backend="jax", key_words=3, h_cap=1 << 10,
                     bucket_mins=(32, 128, 64))
    m = cs._jax.metrics

    def drive(i0, n):
        v = 5 * i0
        for i in range(i0, i0 + n):
            v += 5
            e = cs.pipeline_submit(_big_batch(10_000 * i), v, 0)
            while cs.pipeline_inflight > 1:
                cs.pipeline_complete_oldest()
            assert e is not None
        cs.pipeline_drain()

    drive(0, 2)  # warmup: compiles + populates the staging ring
    syncs0 = m.counter("host_syncs").value
    allocs0 = m.counter("host_allocs").value
    batches = 8
    drive(2, batches)
    syncs = m.counter("host_syncs").value - syncs0
    allocs = m.counter("host_allocs").value - allocs0
    assert syncs <= 3 * batches, (
        f"{syncs} sanctioned syncs over {batches} healthy batches "
        f"(budget 3/batch)"
    )
    assert allocs == 0, (
        f"steady-state encode allocated {allocs} buffers past the "
        f"staging ring"
    )
    assert m.counter("host_syncs").value > 0  # the scopes really count


def test_host_and_device_max_tables_agree():
    """The tiered engine's CARRIED base max-table is seeded host-side
    (numpy) and queried by range_max against the device-built layout;
    both come from ONE shared builder — pin the parity anyway so a layout
    change can never silently skew only the host twin."""
    import numpy as np

    from foundationdb_tpu.ops.rangequery import (
        build_max_table,
        build_max_table_np,
    )

    rng = np.random.default_rng(3)
    for n in (1, 2, 3, 7, 64, 1000, 4096):
        v = rng.integers(-(2 ** 30), 2 ** 30, size=(n,)).astype(np.int32)
        host = build_max_table_np(v)
        dev = np.asarray(build_max_table(jnp.asarray(v)))
        assert host.shape == dev.shape, n
        assert (host == dev).all(), n
