"""Client-side GRV batching (readVersionBatcher, NativeAPI.actor.cpp:2698).

One in-flight proxy GRV request serves every concurrent caller that
arrived behind it; the proxy-side `grv_requests` counter proves the
coalescing happened on the wire, not just in client bookkeeping.
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.eventloop import all_of
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def _grv_requests(cluster) -> int:
    return sum(
        p.stats.counter("grv_requests").value for p in cluster.proxies
    )


def test_concurrent_grvs_coalesce_on_the_wire():
    c = SimCluster(seed=710, n_proxies=1)
    db = c.database("grv")
    versions = []

    async def one():
        tr = db.create_transaction()
        versions.append(await tr.get_read_version())

    async def burst():
        await all_of([db.process.spawn(one(), f"g{i}") for i in range(24)])

    before = _grv_requests(c)
    c.run_until(db.process.spawn(burst()), timeout_vt=1000.0)
    sent = _grv_requests(c) - before
    assert len(versions) == 24 and all(v is not None for v in versions)
    # First caller's request flies alone; everyone behind it shares the
    # next one (or two, depending on arrival interleaving).
    assert sent <= 3, sent


def test_batched_versions_are_current():
    """A batched read version must still observe every commit acknowledged
    before the GRV was requested (external consistency through the
    batcher)."""
    c = SimCluster(seed=711, n_proxies=1)
    db = c.database("grv2")

    async def flow():
        tr = db.create_transaction()
        tr.set(b"gb", b"1")
        committed = await tr.commit()
        # Two concurrent readers batched into one GRV:
        trs = [db.create_transaction() for _ in range(2)]
        vs = []
        for t in trs:
            vs.append(await t.get_read_version())
        assert all(v >= committed for v in vs), (vs, committed)
        for t in trs:
            assert await t.get(b"gb") == b"1"
        return True

    assert c.run_until(db.process.spawn(flow()), timeout_vt=1000.0)


def test_grv_error_propagates_to_all_waiters():
    """If the shared request fails, every queued caller sees the error and
    can retry independently — nobody hangs."""
    from foundationdb_tpu.flow.error import FdbError

    c = SimCluster(seed=712, n_proxies=1)
    db = c.database("grv3")
    results = []

    async def one(i):
        tr = db.create_transaction()
        try:
            results.append(await tr.get_read_version())
        except FdbError as e:
            results.append(e.name)

    async def burst_with_kill():
        tasks = [db.process.spawn(one(i), f"k{i}") for i in range(6)]
        c.proxy.process.kill()
        await all_of(tasks)

    c.run_until(db.process.spawn(burst_with_kill()), timeout_vt=1000.0)
    assert len(results) == 6
    # Proxy died mid-burst: waiters either got a version (request won the
    # race) or the broken_promise error — never a hang.
    assert all(isinstance(r, int) or r == "broken_promise" for r in results)
