"""Chaos-soak harness gates (ISSUE 8): deterministic replay, visible
throttling through fault windows, bounded-queue shedding, the BENCH-style
CLI artifact, and the slow full-matrix soak (process kill + one-directional
clog + device outage at sim-minutes of sustained load)."""

import json

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow.knobs import g_env, g_knobs
from foundationdb_tpu.flow.rng import DeterministicRandom
from foundationdb_tpu.workloads.soak import (
    FaultEvent,
    SoakConfig,
    SoakPhase,
    default_config,
    run_soak,
    transition_logs_json,
    zipf_cdf,
    zipf_pick,
)


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def _short_cfg(seed, backend="cpu", faults=(), **kw):
    return SoakConfig(
        seed=seed,
        cluster="sim",
        backend=backend,
        mode="open",
        keys=64,
        phases=[SoakPhase("warm", 1.0, 40.0), SoakPhase("peak", 2.0, 80.0)],
        faults=list(faults),
        drain_timeout=5.0,
        **kw,
    )


def _limiting_within(admission_log, t0, t1):
    """Non-"none" limiting entries the admission log shows in [t0, t1]."""
    return [e for e in admission_log if t0 <= e[0] <= t1 and e[1] != "none"]


def test_zipf_skew_properties():
    cdf = zipf_cdf(100, 0.9)
    assert len(cdf) == 100
    assert all(b >= a for a, b in zip(cdf, cdf[1:]))
    assert cdf[-1] == pytest.approx(1.0)
    # Skew: the hottest 10 ranks carry far more than uniform mass.
    assert cdf[9] > 0.35
    # Uniform at theta=0.
    flat = zipf_cdf(100, 0.0)
    assert flat[9] == pytest.approx(0.1)
    # Deterministic picks from a seeded stream, all in range.
    rng = DeterministicRandom(5)
    picks = [zipf_pick(rng, cdf) for _ in range(200)]
    assert all(0 <= p < 100 for p in picks)
    assert picks == [zipf_pick(DeterministicRandom(5), cdf)
                     for _ in range(1)] + picks[1:]


def test_soak_clog_throttles_and_releases():
    """A one-directional tlog->storage clog mid-peak: the ratekeeper
    visibly throttles during the window (limiting != none) and releases
    after; goodput and the SLO hold through it."""
    rep = run_soak(
        _short_cfg(7, faults=[FaultEvent(at=1.5, kind="clog", duration=0.6)])
    )
    assert rep["slo"]["ok"], rep["slo"]
    assert rep["totals"]["committed"] > 0
    # Goodput is committed txns, not attempts.
    assert rep["totals"]["attempts"] >= rep["totals"]["committed"]
    (t0, kind, detail, t1), = rep["faults"]
    assert kind == "clog" and "->" in detail
    log = rep["ratekeeper"]["admission_log"]
    assert _limiting_within(log, t0, t1 + 1.0), (log, t0, t1)
    # Released: the log's last entry is back to "none".
    assert log[-1][1] == "none", log
    # Per-phase goodput floors held.
    for ph in rep["phases"]:
        assert ph["slo_ok"], ph
        assert ph["goodput_tps"] >= ph["goodput_floor_tps"]


def test_soak_same_seed_byte_identical():
    """The replay gate: same seed => the transition logs (admission,
    ratekeeper, breakers, fault timeline) — and in fact the whole report
    — are byte-identical; a different seed diverges."""
    faults = [FaultEvent(at=1.5, kind="clog", duration=0.6)]
    a = run_soak(_short_cfg(7, faults=faults))
    b = run_soak(_short_cfg(7, faults=faults))
    c = run_soak(_short_cfg(8, faults=faults))
    assert transition_logs_json(a) == transition_logs_json(b)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    assert transition_logs_json(a) != transition_logs_json(c)


def test_soak_coalesce_on_byte_identical_and_exact():
    """ISSUE 19 at the soak level: with coalesced mirror folds on
    ('auto' ties the fold window to the pipeline depth) the same seed
    still produces a byte-identical report, and the workload-visible
    outcome (committed/conflicted/too_old tallies) matches the
    coalesce-off run exactly — coalescing is a cost model, never a
    behavior change."""
    import os

    faults = [FaultEvent(at=1.5, kind="clog", duration=0.6)]
    env = {"FDB_TPU_MIRROR_COALESCE": "auto", "FDB_TPU_PIPELINE_DEPTH": "2"}
    old = {kk: os.environ.get(kk) for kk in env}
    os.environ.update(env)
    try:
        a = run_soak(_short_cfg(7, faults=faults))
        b = run_soak(_short_cfg(7, faults=faults))
    finally:
        for kk, vv in old.items():
            if vv is None:
                os.environ.pop(kk, None)
            else:
                os.environ[kk] = vv
    off = run_soak(_short_cfg(7, faults=faults))
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    picks = ("committed", "conflicted", "too_old")
    assert {f: a["totals"][f] for f in picks} == \
        {f: off["totals"][f] for f in picks}


def test_soak_device_outage_degrades_throttles_recovers():
    """Mid-soak device outage via DeviceFaultInjector: the PR-3 breaker
    walks ok -> degraded -> probing -> ok, the ratekeeper contracts to
    the degraded cap while the circuit is open (limiting ==
    backend_degraded), and admission releases after recovery."""
    rep = run_soak(
        SoakConfig(
            seed=9,
            cluster="sim",
            backend="jax",
            mode="open",
            keys=64,
            phases=[SoakPhase("peak", 3.0, 60.0)],
            faults=[FaultEvent(at=1.0, kind="device_outage", duration=1.0)],
            drain_timeout=5.0,
            degraded_tps_fraction=0.1,
        )
    )
    assert rep["slo"]["ok"], rep["slo"]
    (t0, kind, _detail, t1), = rep["faults"]
    assert kind == "device_outage"
    log = rep["ratekeeper"]["admission_log"]
    window = _limiting_within(log, t0, t1 + 0.5)
    assert any(e[1] == "backend_degraded" for e in window), log
    assert log[-1][1] == "none", log
    # Breaker transition log: a legal walk that ends recovered.
    (transitions,) = rep["breakers"].values()
    legal = {("ok", "degraded"), ("degraded", "probing"),
             ("probing", "ok"), ("probing", "degraded")}
    prev = "ok"
    for _seq, frm, to, _reason in transitions:
        assert frm == prev and (frm, to) in legal, transitions
        prev = to
    assert prev == "ok", transitions
    # Verdicts kept flowing on the CPU mirror: goodput never went to zero.
    assert rep["totals"]["committed"] > 0
    assert rep["totals"]["failed"] == 0 and rep["totals"]["exhausted"] == 0
    # Flight recorder (ISSUE 10): the scripted breaker-open mid-soak
    # yields a capture whose window contains the triggering transition,
    # the surrounding time-series deltas, and the recent trace events;
    # the fault window itself is captured automatically.
    fr = rep["flight_recorder"]
    triggers = [c["trigger"] for c in fr["captures"]]
    assert "breaker_open" in triggers, triggers
    assert "fault_window:device_outage" in triggers, triggers
    cap = next(c for c in fr["captures"] if c["trigger"] == "breaker_open")
    assert cap["transitions"][-1][1:3] == ["ok", "degraded"]
    assert t0 <= cap["time"] <= t1 + 0.5, (cap["time"], t0, t1)
    series = cap["timeseries"]
    assert any(n.startswith("JaxConflict") for n in series), series.keys()
    assert "Ratekeeper" in series and "Resolver.resolver" in series
    dev = next(v for k, v in series.items() if k.startswith("JaxConflict"))
    assert sum(s["counters"].get("batches", 0) for s in dev) > 0
    assert any(
        e["Type"] == "DeviceBackendStateChange" for e in cap["recent_events"]
    ), [e["Type"] for e in cap["recent_events"]][-10:]
    assert fr["status"]["captures"] == len(fr["captures"])


def test_soak_shard_kill_survivors_hold_floor():
    """Shard-kill fault (ISSUE 15): a chip loss scoped to one shard of
    the mesh-sharded resolver.  Only that shard's breaker opens (and
    serves degraded off its mirror), the surviving shards hold every
    phase's goodput floor, admission contracts PROPORTIONALLY (one sick
    shard out of N — not the whole-lane degraded clamp), and recovery
    rehydrates only the sick shard."""
    from foundationdb_tpu.workloads.soak import shard_outage_config

    cfg = shard_outage_config(
        minutes=0.15, peak_tps=60.0, seed=17, shard=1, n_shards=4
    )
    cfg.keys = 64
    cfg.drain_timeout = 5.0
    cfg.max_tps = 60.0
    cfg.degraded_tps_fraction = 0.0  # whole-lane clamp would zero the
    # rate; the proportional cap must keep ~3/4 of it instead
    rep = run_soak(cfg)
    # Every phase — INCLUDING shard_outage — held its goodput floor.
    assert rep["slo"]["ok"], rep["slo"]
    (t0, kind, detail, t1), = rep["faults"]
    assert kind == "shard_kill" and detail.endswith(":shard1"), rep["faults"]
    # Only shard 1's breaker walked, and it ended recovered.
    (rname, sh) = detail.split(":")
    for key, transitions in rep["breakers"].items():
        if key == f"{rname}.shard1":
            assert transitions and transitions[0][1:3] == ["ok", "degraded"]
            assert transitions[-1][2] == "ok", transitions
        else:
            assert transitions == [], (key, transitions)
    shards = rep["shards"][rname]
    assert shards["total"] == 4
    assert shards["states"] == ["ok"] * 4  # all recovered by soak end
    assert shards["degraded_shard_serves"] > 0
    # Proportional admission (the ratekeeper's shard-granular cap): while
    # shard 1 was down, the binding backend_degraded rate stayed near
    # 3/4 of max_tps — NOT the zeroed whole-lane degraded clamp.
    window = _limiting_within(rep["ratekeeper"]["admission_log"], t0, t1 + 0.5)
    deg = [e for e in window if e[1] == "backend_degraded"]
    assert deg, rep["ratekeeper"]["admission_log"]
    assert all(e[2] >= 0.5 * cfg.max_tps for e in deg), deg
    assert rep["ratekeeper"]["admission_log"][-1][1] == "none"
    # The shard-breaker open is a flight-recorder trigger naming the
    # sick shard's domain.
    fr = rep["flight_recorder"]
    triggers = [c["trigger"] for c in fr["captures"]]
    assert "breaker_open" in triggers and "fault_window:shard_kill" in triggers
    cap = next(c for c in fr["captures"] if c["trigger"] == "breaker_open")
    assert cap["detail"]["domain"] == "shard1", cap["detail"]


def test_soak_shard_kill_same_seed_byte_identical():
    """The shard-outage soak is replayable: same seed => byte-identical
    full reports (per-shard transition logs included)."""
    from foundationdb_tpu.workloads.soak import shard_outage_config

    def go():
        cfg = shard_outage_config(
            minutes=0.1, peak_tps=40.0, seed=23, shard=2, n_shards=4
        )
        cfg.keys = 32
        cfg.drain_timeout = 5.0
        return run_soak(cfg)

    a, b = go(), go()
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    assert transition_logs_json(a) == transition_logs_json(b)


def test_soak_overload_sheds_and_clients_recover():
    """Open-loop overload far beyond a tiny TPS cap with a small GRV
    queue bound: the proxy sheds (counted, deterministic), shed clients
    retry with backoff, and the run still makes forward progress."""
    rep = run_soak(
        SoakConfig(
            seed=13,
            cluster="sim",
            backend="cpu",
            mode="open",
            keys=32,
            phases=[SoakPhase("flood", 2.0, 400.0, rmw_fraction=1.0,
                              read_fraction=0.0)],
            drain_timeout=20.0,
            max_in_flight=256,
            clients=64,  # distinct GRV batchers: real queue pressure
            max_tps=25.0,
            grv_queue_max=16,
            goodput_floor_frac=0.01,
            slo_commit_p99=30.0,
        )
    )
    shed = rep["throttle_shed"]
    # Both surfaces saw it: the proxy shed deterministically AND clients
    # observed (retryable) throttle errors.  Client counts can exceed
    # proxy counts — one shed GRV reply fans out to every coalesced
    # waiter in the client-side batcher.
    assert shed["grv_shed_default"] + shed["grv_shed_batch"] > 0, shed
    assert shed["client_throttled"] > 0, shed
    assert rep["totals"]["committed"] > 0


def test_cli_soak_emits_bench_style_artifact(capsys):
    """`cli soak --format=json` (satellite): a BENCH-style artifact with
    the headline goodput metric, per-phase evidence, throttle/shed
    counts, and the fault timeline."""
    from foundationdb_tpu.tools.cli import soak_main

    rc = soak_main(
        [
            "--format=json",
            "--minutes=0.05",
            "--tps=40",
            "--seed=3",
            "--keys=32",
            "--backend=cpu",
            "--no-faults",
        ]
    )
    out = capsys.readouterr().out
    artifact = json.loads(out)
    assert rc == 0
    assert artifact["metric"] == "soak_goodput_txn_per_sec"
    assert artifact["unit"] == "txn/s"
    assert artifact["value"] > 0
    for key in ("phases", "throttle_shed", "fault_timeline",
                "ratekeeper_transitions", "breaker_transitions", "slo",
                "committed", "attempts", "sim_seconds", "seed"):
        assert key in artifact, sorted(artifact)
    assert artifact["slo"]["ok"] is True


def test_soak_env_flags_registered():
    """ENV001 satellite: every FDB_TPU_SOAK_* flag is declared in g_env
    with a default and help string."""
    decl = g_env.declared()
    for name in ("FDB_TPU_SOAK_MINUTES", "FDB_TPU_SOAK_SEED",
                 "FDB_TPU_SOAK_TPS", "FDB_TPU_SOAK_KEYS",
                 "FDB_TPU_SOAK_THETA", "FDB_TPU_SOAK_BACKEND"):
        default, help_ = decl[name]
        assert default != "" and help_ != "", name


@pytest.mark.slow
@pytest.mark.soak
def test_soak_full_matrix_slow():
    """THE acceptance soak (slow-marked, under the 2100s watchdog): N sim
    minutes (FDB_TPU_SOAK_MINUTES) of ramped open-loop Zipf load on a
    DynamicCluster with the full scripted fault matrix — process kill
    with the machine held down, one-directional clog, device outage —
    holding the latency SLO and per-phase goodput floors, with the
    ratekeeper visibly throttling in EVERY fault window and releasing
    after recovery, and two same-seed runs producing byte-identical
    ratekeeper + breaker transition logs."""
    minutes = float(g_env.get("FDB_TPU_SOAK_MINUTES"))
    cfg_kw = dict(
        minutes=minutes,
        peak_tps=float(g_env.get("FDB_TPU_SOAK_TPS")),
        seed=g_env.get_int("FDB_TPU_SOAK_SEED"),
        cluster="dynamic",
        backend=g_env.get("FDB_TPU_SOAK_BACKEND"),
        keys=g_env.get_int("FDB_TPU_SOAK_KEYS"),
        zipf_theta=float(g_env.get("FDB_TPU_SOAK_THETA")),
        faults=True,
    )
    cfg = default_config(**cfg_kw)
    cfg.slo_commit_p99 = 5.0
    cfg.goodput_floor_frac = 0.25
    rep = run_soak(cfg)

    assert rep["slo"]["ok"], rep["slo"]
    for ph in rep["phases"]:
        assert ph["slo_ok"], ph
    # All three fault kinds fired and recorded recovery times.
    kinds = [f[1] for f in rep["faults"]]
    assert set(kinds) == {"kill", "clog", "device_outage"}, kinds
    # The ratekeeper visibly throttled in EVERY fault window (a kill's
    # window extends through recovery, already in its timeline t_end).
    log = rep["ratekeeper"]["admission_log"]
    for t0, kind, _detail, t1 in rep["faults"]:
        assert _limiting_within(log, t0 - 0.1, t1 + 2.0), (kind, t0, t1, log)
    # ... and released after the last fault.
    assert log[-1][1] == "none", log
    # Goodput under overload, not raw attempts: the floor already gated
    # per phase above; the soak as a whole must also have absorbed load.
    assert rep["totals"]["committed"] > 0.25 * rep["totals"]["arrivals"]

    # Same-seed replay: byte-identical ratekeeper + breaker logs.
    rep2 = run_soak(default_config(**cfg_kw))
    assert transition_logs_json(rep) == transition_logs_json(rep2)
