"""Probe-coverage gate: the coveragetool analog.

Ref: flow/UnitTest.h's TEST() macro + the coveragetool CI step: named
probes sit at rare-but-important code paths; a corpus run must actually
REACH them, or the "coverage" the chaos suite claims is fiction.  This
gate runs a compact chaos corpus and asserts the required probe set
fired (buggify sites have their own equivalent gate in test_workloads).
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.flow import testprobe


@pytest.fixture(autouse=True)
def _probes():
    testprobe.reset()
    yield
    set_event_loop(None)


def test_chaos_corpus_reaches_probed_paths():
    from foundationdb_tpu.workloads import (
        AttritionWorkload,
        CycleWorkload,
        RandomCloggingWorkload,
        run_workloads,
    )
    from foundationdb_tpu.workloads.config import SimulationConfig

    # A few seeds of cycle-under-chaos on random topologies: enough for
    # the failover/fence paths to fire.  Margin matters: the event
    # schedule is RNG-stream sensitive, so one seed's probe flipping off
    # after an unrelated code change must not kill the gate (observed
    # round 5: the latency-sampling RNG draw shifted every later seed).
    for seed in (3001, 3002, 3003, 3012, 3013):
        cfg = SimulationConfig.random(seed)
        c = cfg.build(seed)
        run_workloads(
            c,
            [
                CycleWorkload(nodes=5, ops=12, actors=2),
                RandomCloggingWorkload(duration=2.0),
                AttritionWorkload(kills=1),
            ],
            timeout_vt=20000.0,
        )
        set_event_loop(None)
    # The round-5 invariant trio under attrition: unknown-result commits
    # are likely across these seeds, exercising the fence path.
    from foundationdb_tpu.workloads import (
        AtomicOpsWorkload,
        SerializabilityWorkload,
        VersionStampWorkload,
    )

    for seed in (3004, 3005):
        cfg = SimulationConfig.random(seed)
        c = cfg.build(seed)
        run_workloads(
            c,
            [
                AtomicOpsWorkload(groups=2, actors=2, ops=5),
                VersionStampWorkload(actors=2, ops=4),
                SerializabilityWorkload(registers=5, actors=2, ops=5),
                RandomCloggingWorkload(duration=1.5),
                AttritionWorkload(kills=1),
            ],
            timeout_vt=30000.0,
        )
        set_event_loop(None)
    hit = set(testprobe.hit_sites)
    # Paths a chaos corpus MUST reach (kills + clogs + recoveries).
    required = {"storage_peek_failover"}
    missing = required - hit
    assert not missing, f"chaos corpus never reached: {missing}; hit={hit}"


def test_spill_and_btree_probes_fire():
    """The spill/btree corpus (dedicated suites) reaches its probes;
    drives the smallest cases directly so the probes count here."""
    from foundationdb_tpu.fileio import SimFileSystem
    from foundationdb_tpu.flow import EventLoop, set_event_loop as sel
    from foundationdb_tpu.rpc import SimNetwork
    from foundationdb_tpu.fileio.btree import BTreeKeyValueStore

    loop = EventLoop(seed=1)
    sel(loop)
    net = SimNetwork(loop)
    fs = SimFileSystem(net)
    proc = net.process("n")

    async def run():
        kv = await BTreeKeyValueStore.open(fs, proc, "c.bt", page_size=512)
        kv.set(b"big", b"x" * 4000)  # oversized node -> chained pages
        await kv.commit()

    loop.run_until(proc.spawn(run()), timeout_vt=100.0)
    assert "btree_chained_node" in testprobe.hit_sites

    from foundationdb_tpu.client.types import Mutation, MutationType
    from foundationdb_tpu.server.interfaces import (
        TLogCommitRequest,
        TLogPeekRequest,
    )
    from foundationdb_tpu.server.tlog import TLog

    proc2 = net.process("t")

    async def spill():
        log = await TLog.fresh(proc2, fs, "c.dq")
        log.spill_threshold_bytes = 5_000
        log.spill_keep_versions = 2
        iface = log.interface()
        for v in range(1, 60):
            await iface.commit.get_reply(
                proc,
                TLogCommitRequest(
                    version=v,
                    prev_version=v - 1,
                    tagged={"s": [(0, Mutation(
                        MutationType.SET_VALUE, b"k%d" % v, b"v" * 200
                    ))]},
                    epoch=0,
                ),
            )
        for _ in range(200):
            if not log._spilling:
                break
            await loop.delay(0.01)
        await iface.peek.get_reply(
            proc, TLogPeekRequest(begin_version=0, tags=["s"])
        )

    loop.run_until(proc2.spawn(spill()), timeout_vt=1000.0)
    assert "tlog_spilled" in testprobe.hit_sites
    assert "tlog_peek_spilled" in testprobe.hit_sites


def test_remaining_probes_fire_deterministically():
    """Every shipped probe has a gate: epoch orphan truncation, GRV batch
    deferral, and the commit-unknown fence are driven directly."""
    from foundationdb_tpu.client.types import Mutation, MutationType
    from foundationdb_tpu.fileio import SimFileSystem
    from foundationdb_tpu.flow import EventLoop, set_event_loop as sel
    from foundationdb_tpu.rpc import SimNetwork
    from foundationdb_tpu.server.interfaces import TLogCommitRequest
    from foundationdb_tpu.server.tlog import TLog

    # -- epoch_orphans_truncated: truncate a log holding entries above cut.
    loop = EventLoop(seed=2)
    sel(loop)
    net = SimNetwork(loop)
    fs = SimFileSystem(net)
    proc = net.process("t2")

    async def orphan():
        log = await TLog.fresh(proc, fs, "o.dq")
        iface = log.interface()
        for v in range(1, 6):
            await iface.commit.get_reply(
                proc,
                TLogCommitRequest(
                    version=v,
                    prev_version=v - 1,
                    tagged={"s": [(0, Mutation(
                        MutationType.SET_VALUE, b"k", b"v"
                    ))]},
                    epoch=0,
                ),
            )
        log.locked = True
        await log.truncate_above(2)

    loop.run_until(proc.spawn(orphan()), timeout_vt=100.0)
    assert "epoch_orphans_truncated" in testprobe.hit_sites
    sel(None)

    # -- grv_batch_deferred: a hard-throttled batch lane defers replies.
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.server.ratekeeper import RateInfo, Ratekeeper

    c = SimCluster(seed=3)
    rk = Ratekeeper(c.master_proc, [c.tlog], [c.storage])
    c.proxy.ratekeeper = rk.interface()
    for t in list(c.master_proc._tasks):
        if "rk_update" in t.name:
            t.cancel()
    rk.rate = RateInfo(tps=100000.0, batch_tps=5.0)
    db = c.database()

    async def batch_grvs():
        for _ in range(6):
            tr = db.create_transaction()
            tr.options["priority_batch"] = True
            await tr.get_read_version()

    c.run_all([(db, batch_grvs())], timeout_vt=300.0)
    assert "grv_batch_deferred" in testprobe.hit_sites
    sel(None)

    # -- commit_unknown_fence: a commit whose proxy dies mid-flight.
    c2 = SimCluster(seed=4)
    db2 = c2.database()
    from foundationdb_tpu.flow.error import FdbError

    async def unknown():
        tr = db2.create_transaction()
        await tr.get_read_version()
        tr.set(b"uf", b"1")
        task = db2.process.spawn(tr.commit(), "commit")
        await c2.loop.delay(0.0001)  # commit in flight
        c2.proxy_proc.kill()  # reply can never arrive -> broken_promise
        try:
            await task
        except FdbError as e:
            assert e.name == "commit_unknown_result"

    c2.run_until(db2.process.spawn(unknown(), "u"), timeout_vt=300.0)
    assert "commit_unknown_fence" in testprobe.hit_sites
