"""Workload framework + randomized-topology chaos sweep.

Ref: the simulation test strategy (SURVEY.md §4): seed-randomized
SimulationConfig (SimulatedCluster.actor.cpp:673), stacked workloads
(CompoundWorkload tester.actor.cpp:239), ConsistencyCheck after chaos
(tester.actor.cpp:819), BUGGIFY firing under simulation (flow/flow.h:60-67).
"""

import pytest

from foundationdb_tpu.flow import set_event_loop
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.workloads import (
    AttritionWorkload,
    ConsistencyChecker,
    CycleWorkload,
    RandomCloggingWorkload,
    SimulationConfig,
    check_consistency,
    run_workloads,
)


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_cycle_workload_on_simcluster():
    c = SimCluster(seed=90, n_proxies=2, n_storages=2)
    run_workloads(c, [CycleWorkload(nodes=6, ops=20, actors=3)])


def test_consistency_checker_detects_divergence():
    """The checker must actually catch a diverged replica (sabotage one
    storage's data behind the log's back)."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster

    c = DynamicCluster(seed=91, n_workers=6, n_storages=2)
    db = c.database()

    async def fill(tr):
        for i in range(10):
            tr.set(b"d%02d" % i, b"v%d" % i)

    c.run_all([(db, db.run(fill))], timeout_vt=1000.0)

    # Healthy replicas agree.
    out = c.run_until(
        db.process.spawn(check_consistency(db)), timeout_vt=1000.0
    )
    assert out >= 1  # at least one multi-replica shard compared

    # Sabotage: flip a key inside one storage's window, bypassing the log.
    storages = [
        robj
        for wk in c.workers
        for rname, robj in wk.roles.items()
        if rname == "storage"
    ]
    assert storages
    # Placed at the storage's CURRENT version so any fresh read version
    # already covers it.
    storages[0].store.set(b"d05", b"EVIL", storages[0].version.get(), 1)

    with pytest.raises(AssertionError, match="divergence"):
        c.run_until(
            db.process.spawn(check_consistency(db)), timeout_vt=1000.0
        )


@pytest.mark.parametrize("seed", range(1000, 1010))
def test_randomized_chaos_sweep(seed):
    """Ten seeds, each a random topology running Cycle under swizzled
    clogging and machine attrition, ending in a consistency check."""
    cfg = SimulationConfig.random(seed)
    c = cfg.build(seed)
    checker = ConsistencyChecker(
        require_comparisons=cfg.n_storages >= 2
    )
    run_workloads(
        c,
        [
            CycleWorkload(nodes=6, ops=15, actors=2),
            RandomCloggingWorkload(duration=2.5),
            AttritionWorkload(kills=1, delay_between=1.0),
            checker,
        ],
        timeout_vt=20000.0,
        quiet=True,  # gate the consistency check on quiescence
    )


def test_buggify_fires_across_seeds():
    """BUGGIFY sites must actually activate somewhere in a seed sweep
    (p=0.25 per site per seed; 8 seeds make a silent regression to zero
    call sites effectively impossible)."""
    import foundationdb_tpu.flow.buggify as bug_mod
    import importlib

    bug = importlib.import_module("foundationdb_tpu.flow.buggify")
    fired = set()
    for seed in range(30, 38):
        c = SimCluster(seed=seed, n_proxies=2)
        run_workloads(c, [CycleWorkload(nodes=4, ops=8, actors=2)])
        fired |= set(bug.fired_sites)
        set_event_loop(None)
    assert len(fired) >= 3, fired


def test_atomic_ops_and_serializability_workloads():
    from foundationdb_tpu.workloads import (
        AtomicLedgerWorkload,
        WriteSkewWorkload,
    )

    c = SimCluster(seed=95, n_proxies=2)
    run_workloads(
        c,
        [
            AtomicLedgerWorkload(actors=3, ops=10),
            WriteSkewWorkload(rounds=8),
            CycleWorkload(nodes=5, ops=10, actors=2),
        ],
    )


@pytest.mark.parametrize("seed", range(2000, 2006))
def test_invariant_sweep_under_chaos(seed):
    """Six seeds of the full invariant stack (atomic accounting, write-skew
    probes, cycle) under clogging + attrition on random topologies."""
    cfg = SimulationConfig.random(seed)
    c = cfg.build(seed)
    from foundationdb_tpu.workloads import (
        AtomicLedgerWorkload,
        WriteSkewWorkload,
    )

    run_workloads(
        c,
        [
            AtomicLedgerWorkload(actors=2, ops=8),
            WriteSkewWorkload(rounds=5),
            CycleWorkload(nodes=5, ops=10, actors=2),
            RandomCloggingWorkload(duration=2.0),
            AttritionWorkload(kills=1),
            ConsistencyChecker(require_comparisons=cfg.n_storages >= 2),
        ],
        timeout_vt=20000.0,
    )


@pytest.mark.parametrize("seed", [9501, 9502])
def test_sideband_external_consistency(seed):
    """Commit acknowledged before a side-channel message must be visible
    to any transaction started after the message (Sideband.actor.cpp)."""
    from foundationdb_tpu.workloads import SidebandWorkload

    c = SimCluster(seed=seed, n_proxies=2)
    wl = SidebandWorkload(messages=15)
    run_workloads(c, [wl], timeout_vt=20000.0)
    assert wl.checked == 15 and wl.violations == 0


def test_watches_chain():
    """Watch chains fire on real changes, never spuriously
    (Watches.actor.cpp)."""
    from foundationdb_tpu.workloads import WatchesWorkload

    c = SimCluster(seed=9510)
    wl = WatchesWorkload(chain=3, rounds=4)
    run_workloads(c, [wl], timeout_vt=30000.0)
    assert wl.fired > 0 and wl.spurious == 0


def test_selector_correctness_sweep():
    """Exhaustive KeySelector resolution vs the model
    (SelectorCorrectness.actor.cpp)."""
    from foundationdb_tpu.workloads import SelectorCorrectnessWorkload

    c = SimCluster(seed=9520)
    wl = SelectorCorrectnessWorkload(nodes=8, max_offset=4)
    run_workloads(c, [wl], timeout_vt=30000.0)
    assert wl.checked >= 8 * 2 * 9 and not wl.failures


def test_increment_workload():
    """Concurrent RMW counters sum exactly (Increment.actor.cpp)."""
    from foundationdb_tpu.workloads import IncrementWorkload

    c = SimCluster(seed=9530, n_proxies=2)
    run_workloads(c, [IncrementWorkload(counters=3, actors=3, ops=8)])


@pytest.mark.parametrize("seed", [8801, 8807])
def test_kitchen_sink_composition(seed):
    """The grand CompoundWorkload: a dozen invariant workloads composed
    SIMULTANEOUSLY with clogging + attrition on a dynamic cluster, ending
    in quiescence + the consistency gate (ref: multi-workload test specs,
    tester.actor.cpp CompoundWorkload) — cross-workload interference
    (shared proxies, ratekeeper budgets, watch maps, metrics keyspace) is
    the target."""
    from foundationdb_tpu.server.dynamic_cluster import DynamicCluster
    from foundationdb_tpu.workloads import (
        AtomicOpsWorkload,
        BulkLoadWorkload,
        CommitBugWorkload,
        IncrementWorkload,
        InventoryWorkload,
        LowLatencyWorkload,
        QueuePushWorkload,
        StatusWorkload,
        ThroughputWorkload,
        VersionStampWorkload,
    )

    c = DynamicCluster(seed=seed, n_workers=8, n_proxies=2, n_storages=2,
                       n_tlogs=2)
    run_workloads(
        c,
        [
            CycleWorkload(nodes=5, ops=8, actors=2),
            AtomicOpsWorkload(groups=2, actors=2, ops=5),
            IncrementWorkload(counters=3, actors=2, ops=6),
            InventoryWorkload(products=4, actors=2, moves=6),
            QueuePushWorkload(actors=3, pushes=4),
            CommitBugWorkload(iterations=8),
            VersionStampWorkload(actors=2, ops=4),
            BulkLoadWorkload(rows=80, batch=20),
            StatusWorkload(duration=5.0),
            # Generous bounds HERE: this composition includes attrition,
            # and ops spanning a kill/recovery window legitimately stall
            # (~0.5-1s vt); the tight defaults belong to the
            # clogging-only LowLatency test (seed-swept finding).
            LowLatencyWorkload(ops=20, p95_bound=2.0, slow_bound=5.0,
                               slow_fraction=0.3),
            ThroughputWorkload(actors=2, txns_per_actor=8),
            RandomCloggingWorkload(duration=4.0),
            AttritionWorkload(kills=1),
            ConsistencyChecker(),
        ],
        timeout_vt=120000.0,
        quiet=True,
    )
