"""Watches + key selectors (ref: Watches + KeySelector workloads)."""

import pytest

from foundationdb_tpu.client.types import KeySelector
from foundationdb_tpu.flow import FdbError, set_event_loop
from foundationdb_tpu.server import SimCluster


@pytest.fixture(autouse=True)
def _clean_loop():
    yield
    set_event_loop(None)


def test_watch_fires_on_change():
    c = SimCluster(seed=31)
    db_w, db_m = c.database(), c.database()
    events = []

    async def watcher():
        tr = db_w.create_transaction()
        fut = await tr.watch(b"signal")
        await tr.commit()  # read-only commit arms the watch
        events.append(("armed", c.loop.now()))
        fired_version = await fut
        events.append(("fired", fired_version > 0))

    async def mutator():
        await c.loop.delay(0.05)

        async def op(tr):
            tr.set(b"signal", b"go")

        await db_m.run(op)
        events.append(("mutated",))

    c.run_all([(db_w, watcher()), (db_m, mutator())], timeout_vt=100.0)
    assert ("fired", True) in events


def test_watch_no_false_fire_on_same_value():
    """Setting the same value must NOT fire the watch (value-compare, not
    write-compare — ref watchValue semantics)."""
    c = SimCluster(seed=32)
    db_w, db_m = c.database(), c.database()
    state = {"fired": False}

    async def setup(tr):
        tr.set(b"k", b"same")

    c.run_all([(db_w, db_w.run(setup))])

    async def watcher():
        tr = db_w.create_transaction()
        fut = await tr.watch(b"k")
        await tr.commit()

        async def on_fire():
            await fut
            state["fired"] = True

        db_w.process.spawn(on_fire())

    async def rewrite_same(tr):
        tr.set(b"k", b"same")

    c.run_all([(db_w, watcher())])
    c.run_all([(db_m, db_m.run(rewrite_same))])
    # Drain some virtual time; the watch must still be parked.
    idle = c.net.process("idle")

    async def wait_a_bit():
        await c.loop.delay(1.0)

    c.run_until(idle.spawn(wait_a_bit()), timeout_vt=50.0)
    assert not state["fired"]

    async def rewrite_diff(tr):
        tr.set(b"k", b"different")

    c.run_all([(db_m, db_m.run(rewrite_diff))])
    c.run_until(idle.spawn(wait_a_bit()), timeout_vt=50.0)
    assert state["fired"]


def test_watch_fires_immediately_if_already_changed():
    c = SimCluster(seed=33)
    db = c.database()

    async def setup(tr):
        tr.set(b"k", b"v1")

    c.run_all([(db, db.run(setup))])
    fired = {}

    async def race():
        tr = db.create_transaction()
        fut = await tr.watch(b"k")  # sees v1
        # Another client changes the value before the watch is armed.
        db2 = c.database()

        async def change(tr2):
            tr2.set(b"k", b"v2")

        await db2.run(change)
        await tr.commit()
        fired["version"] = await fut

    c.run_all([(db, race())], timeout_vt=100.0)
    assert fired["version"] > 0


def test_key_selectors():
    c = SimCluster(seed=34)
    db = c.database()

    async def fill(tr):
        for k in (b"a", b"c", b"e", b"g"):
            tr.set(k, b"x")

    c.run_all([(db, db.run(fill))])
    out = {}

    async def resolve(tr):
        out["fge_c"] = await tr.get_key(KeySelector.first_greater_or_equal(b"c"))
        out["fge_d"] = await tr.get_key(KeySelector.first_greater_or_equal(b"d"))
        out["fgt_c"] = await tr.get_key(KeySelector.first_greater_than(b"c"))
        out["llt_c"] = await tr.get_key(KeySelector.last_less_than(b"c"))
        out["lle_c"] = await tr.get_key(KeySelector.last_less_or_equal(b"c"))
        out["lle_d"] = await tr.get_key(KeySelector.last_less_or_equal(b"d"))
        out["fge_z"] = await tr.get_key(KeySelector.first_greater_or_equal(b"z"))
        out["llt_a"] = await tr.get_key(KeySelector.last_less_than(b"a"))
        out["fge_c_off2"] = await tr.get_key(KeySelector(b"c", False, 2))
        out["llt_g_off-1"] = await tr.get_key(KeySelector(b"g", False, -1))

    c.run_all([(db, db.run(resolve))])
    assert out["fge_c"] == b"c"
    assert out["fge_d"] == b"e"
    assert out["fgt_c"] == b"e"
    assert out["llt_c"] == b"a"
    assert out["lle_c"] == b"c"
    assert out["lle_d"] == b"c"
    assert out["fge_z"] == b"\xff"  # past the end
    assert out["llt_a"] == b""  # before the front
    assert out["fge_c_off2"] == b"e"
    assert out["llt_g_off-1"] == b"c"
